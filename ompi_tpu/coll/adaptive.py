"""coll/adapt — event-driven segment-pipelined tree collectives.

Reference: ompi/mca/coll/adapt (coll_adapt_ibcast.c / coll_adapt_ireduce.c,
~4k LoC) — bcast/reduce run as SEGMENTED binomial trees where every
segment moves the moment it is available, driven by request-completion
callbacks rather than round barriers: an inner node starts forwarding
segment 0 to its subtree while segment 1 is still in flight from its
parent, so tree depth and message length pipeline instead of
multiplying. The reference ships it disabled by default (enabled via
``--mca coll adapt``); same here (``coll_adapt_enable``).

Redesign notes vs the reference:
- the event engine is the framework's own request-completion callbacks
  (core/request.py ``add_completion_callback`` — fired from the
  progress thread), not libevent;
- contexts/inbuf free-lists collapse to per-segment views of one
  contiguous staging buffer;
- reduce restricts itself to commutative ops (children's segments
  combine in ARRIVAL order — the reference's ireduce has the same
  constraint and falls back otherwise) and delegates non-commutative /
  heterogeneous cases to the basic linear algorithm.
"""

from __future__ import annotations

import threading
from typing import Any, List, Optional

import numpy as np

from ompi_tpu.coll.base import CollModule, coll_framework
from ompi_tpu.coll.basic import (
    BasicColl,
    _ccid,
    _np_reduce_typed,
    _typed_view,
)
from ompi_tpu.comm.communicator import parse_buffer
from ompi_tpu.core import op as _op
from ompi_tpu.core.convertor import pack as cv_pack, unpack as cv_unpack
from ompi_tpu.core.datatype import BYTE
from ompi_tpu.core.errors import MPIError
from ompi_tpu.mca.component import Component
from ompi_tpu.mca.var import register_var, get_var
from ompi_tpu.runtime.progress import progress_until

register_var("coll_adapt", "enable", False,
             help="Event-driven segment-pipelined tree bcast/reduce "
                  "(reference: ompi/mca/coll/adapt, disabled by default "
                  "there too)", level=5)
register_var("coll_adapt", "segsize", 1 << 16,
             help="Pipeline segment size in bytes (reference: "
                  "coll_adapt_ibcast_segment_size)", level=6)

_TAG_BASE = -1000  # per-segment tags: _TAG_BASE - seg_index (coll plane)
_MAX_SEGS = 2048   # tag budget; larger messages grow the segment size


def _tree(rank: int, n: int, root: int):
    """Binomial tree in root-rotated coordinates: returns (parent,
    children) as comm ranks. parent(v) clears v's lowest set bit;
    children(v) set one bit below it (reference: the in-order binomial
    of coll_base_topo)."""
    v = (rank - root) % n
    if v == 0:
        parent = None
        low = 1
        while low < n:
            low <<= 1
    else:
        low = v & -v
        parent = (v & (v - 1))
    children = []
    k = 1
    while k < low:
        c = v | k
        if c < n and c != v:
            children.append(c)
        k <<= 1
    to_rank = lambda u: (u + root) % n
    return (None if parent is None else to_rank(parent)), \
        [to_rank(c) for c in children]


def _segments(nbytes: int, item: int = 1) -> List[tuple]:
    """(offset, length) pipeline segments: the configured size rounded
    to the ``item`` granule (element-typed reduces must not split an
    element), doubled while the tag budget would overflow."""
    seg = max(int(get_var("coll_adapt", "segsize")) // item, 1) * item
    while nbytes > seg * _MAX_SEGS:
        seg *= 2
    return [(off, min(seg, nbytes - off))
            for off in range(0, nbytes, seg)] or [(0, 0)]


class AdaptColl(CollModule):
    """Segment-pipelined binomial bcast/reduce."""

    def __init__(self):
        self._flat = BasicColl()

    # ---------------------------------------------------------------- bcast
    def bcast(self, comm, buf, root: int) -> None:
        obj, count, dt = parse_buffer(buf)
        nbytes = count * dt.size
        n, r = comm.size, comm.rank
        if nbytes == 0 or n == 1:
            return
        parent, children = _tree(r, n, root)
        cid = _ccid(comm)
        if r == root:
            packed = np.ascontiguousarray(cv_pack(obj, count, dt)
                                          ).view(np.uint8).reshape(-1)
        else:
            packed = np.empty(nbytes, np.uint8)
        segs = _segments(nbytes)
        fwd: List[Any] = []
        fwd_err: List[MPIError] = []
        fwd_lock = threading.Lock()

        def forward(i: int) -> None:
            off, ln = segs[i]
            view = packed[off: off + ln]
            for c in children:
                try:
                    q = comm.pml.isend(view, ln, BYTE,
                                       comm.group.world_rank(c),
                                       _TAG_BASE - i, cid)
                except MPIError as e:
                    # callback context: record, don't throw into the
                    # progress thread (the waiter re-raises)
                    with fwd_lock:
                        fwd_err.append(e)
                    return
                with fwd_lock:
                    fwd.append(q)

        if r == root:
            # the root has every segment: the whole pipeline is enqueued
            # at once, per child in segment order
            for i in range(len(segs)):
                forward(i)
            rreqs: List[Any] = []
        else:
            rreqs = []
            pw = comm.group.world_rank(parent)
            for i, (off, ln) in enumerate(segs):
                req = comm.pml.irecv(packed[off: off + ln], ln, BYTE,
                                     pw, _TAG_BASE - i, cid)
                if children:
                    # EVENT-DRIVEN forward: the progress thread fires
                    # this the moment segment i lands — no waiting for
                    # later segments (the adapt property)
                    req.add_completion_callback(
                        lambda _q, i=i: forward(i))
                rreqs.append(req)
        for q in rreqs:
            q.Wait()
        # a recv's Wait can return BEFORE its completion callback posted
        # the forwards (the event flips first) — drain by EXPECTED post
        # count, not by the current snapshot, or the node exits with
        # segment sends unposted and a later same-tag send can overtake
        expected = len(children) * len(segs)

        def fwd_done() -> bool:
            with fwd_lock:
                if fwd_err:
                    return True
                return len(fwd) == expected and \
                    all(q.is_complete for q in fwd)

        progress_until(fwd_done)
        if fwd_err:
            raise fwd_err[0]
        if r != root:
            cv_unpack(packed, obj, count, dt)

    # --------------------------------------------------------------- reduce
    def reduce(self, comm, sendbuf, recvbuf, op: _op.Op,
               root: int) -> None:
        obj_s, count, dt = parse_buffer(
            recvbuf if sendbuf is None else sendbuf)
        nbytes = count * dt.size
        n, r = comm.size, comm.rank
        if nbytes == 0 or n == 1:
            if r == root and sendbuf is not None:
                obj_r, rcount, rdt = parse_buffer(recvbuf)
                cv_unpack(np.ascontiguousarray(
                    cv_pack(obj_s, count, dt)).view(np.uint8
                                                    ).reshape(-1),
                          obj_r, rcount, rdt)
            return
        if not op.commutative:
            # arrival-order combining needs commutativity (reference:
            # adapt ireduce has the same constraint)
            return self._flat.reduce(comm, sendbuf, recvbuf, op, root)
        acc = np.ascontiguousarray(cv_pack(obj_s, count, dt)
                                   ).view(np.uint8).reshape(-1).copy()
        try:
            _typed_view(acc[: dt.size], dt)
        except MPIError:
            return self._flat.reduce(comm, sendbuf, recvbuf, op, root)
        parent, children = _tree(r, n, root)
        cid = _ccid(comm)
        # element-granular segments: the typed combine must not split
        # an element across a segment boundary
        item = _typed_view(acc[: dt.size], dt).dtype.itemsize
        segs = _segments(nbytes, item)
        lock = threading.Lock()
        remaining = [len(children)] * len(segs)
        up: List[Any] = []
        up_err: List[MPIError] = []
        done = threading.Event()
        n_pending = [len(segs)]
        pw = None if parent is None else comm.group.world_rank(parent)

        def seg_ready(i: int) -> None:
            """All children contributed segment i: push it upward (or,
            at the root, count it complete)."""
            off, ln = segs[i]
            if pw is not None:
                try:
                    q = comm.pml.isend(acc[off: off + ln], ln, BYTE, pw,
                                       _TAG_BASE - i, cid)
                except MPIError as e:
                    # callback context: record and unblock the waiter
                    # (which re-raises) instead of throwing into the
                    # progress thread
                    with lock:
                        up_err.append(e)
                        done.set()
                    return
            with lock:
                if pw is not None:
                    up.append(q)
                n_pending[0] -= 1
                if n_pending[0] == 0:
                    done.set()

        if not children:
            for i in range(len(segs)):
                seg_ready(i)
        else:
            # ONE contiguous staging buffer per child (views per
            # segment) — per-(child, segment) allocations would peak at
            # n_children x message_size of scattered buffers
            for c in children:
                cw = comm.group.world_rank(c)
                stage = np.empty(nbytes, np.uint8)
                for i, (off, ln) in enumerate(segs):
                    tmp = stage[off: off + ln]
                    req = comm.pml.irecv(tmp, ln, BYTE, cw,
                                         _TAG_BASE - i, cid)

                    def landed(_q, i=i, tmp=tmp, off=off, ln=ln):
                        # combine in ARRIVAL order under the lock
                        # (commutative ops only — checked above)
                        with lock:
                            a = _typed_view(acc[off: off + ln], dt)
                            b = _typed_view(tmp, dt)
                            a[...] = _np_reduce_typed(op, a, b)
                            remaining[i] -= 1
                            fire = remaining[i] == 0
                        if fire:
                            seg_ready(i)

                    req.add_completion_callback(landed)
        progress_until(done.is_set)
        if up_err:
            raise up_err[0]

        # `up` is complete-by-construction when done fires (sends append
        # under the lock before the last n_pending decrement), but their
        # DELIVERY may still be in flight — drain them
        def up_done() -> bool:
            with lock:
                return all(q.is_complete for q in up)

        progress_until(up_done)
        if r == root:
            obj_r, rcount, rdt = parse_buffer(recvbuf)
            cv_unpack(acc, obj_r, rcount, rdt)


class AdaptCollComponent(Component):
    NAME = "adapt"
    PRIORITY = 48  # above tuned(30)/han(45), below coll/sm(50): on one
    # node the segment collectives win; adapt targets deep trees

    def query(self, comm=None, **ctx: Any) -> Optional[AdaptColl]:
        from ompi_tpu.comm.communicator import ProcComm

        if not get_var("coll_adapt", "enable"):
            return None
        if not isinstance(comm, ProcComm) or comm.size < 2:
            return None
        return AdaptColl()


coll_framework.register(AdaptCollComponent())
