"""Round-based collective schedules: the engine under both the tuned
blocking algorithms and the nonblocking (MPI_I*) collectives.

Reference: ompi/mca/coll/libnbc (12,429 LoC) expresses every nonblocking
collective as a DAG of send/recv/op/copy steps grouped into rounds
(NBC_Sched_send/recv/op, nbc_internal.h:156-161) progressed by
opal_progress. Redesign: an algorithm here is a Python *generator* that
yields ``Round`` objects (the communication steps) and performs local
compute between yields — the round barrier the reference encodes as
schedule delimiters falls out of generator suspension. One algorithm
definition serves both paths:

- blocking:   ``run_blocking`` drains the generator, waiting each round;
- nonblocking: ``NbcRequest`` issues each round and advances from request
  completion callbacks, so the schedule progresses from the progress
  engine/thread exactly like libnbc rounds do.

Traffic isolation: nonblocking schedules run in a dedicated CID plane
(NBC_CID_BIT) with a per-communicator sequence number as the tag, so
overlapping schedules on one communicator never cross-match (libnbc's
per-comm tag counter, nbc_internal.h SCHED tag logic).

Datapath discipline (the PR 9 btl contract, extended up to this layer):

- **sends are borrowed views** over the caller's packed/accumulator
  buffers — a payload is copied only when the source is genuinely
  non-contiguous, and that copy is counted;
- **recvs are pooled or land direct**: a ``(nbytes, src)`` recv draws a
  size-classed block from ``runtime/mpool.class_pool`` (recycled on
  clean completion or ``Round.free``; DISCARDED — never recycled — when
  the schedule fails, so a racing drain can't alias the next owner); a
  ``(nbytes, src, dest)`` recv unpacks straight into the caller's view
  (the final out/accumulator slice) with no staging at all;
- **windowing**: a ``Round(ordered=False)`` promises the generator
  neither reads the round's results nor touches its buffers until it
  RESUMES from the next ordered yield (or the schedule completes), so
  up to ``coll_round_window`` such rounds stay in flight instead of a
  full barrier per round — in both ``run_blocking`` and ``NbcRequest``.
  Unordered rounds to the SAME peer must be order-insensitive (the
  built-in user is alltoall pairwise: every round targets a distinct
  peer). An ordered round is a barrier on RESUME — its own sends/recvs
  are issued before the window drains (recvs pre-post), so they must
  not depend on in-flight unordered results; only when the generator
  resumes has every earlier round completed.
- **measured, not estimated**: ``coll_round_bytes_copied`` /
  ``bytes_moved`` / ``pool_hits`` / ``windowed`` pvars, with the legacy
  engine (fresh ``np.empty`` per recv, staged recv->dest copies) kept
  behind ``coll_round_copy_mode=1`` as the A/B baseline.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import (Callable, Dict, Generator, List, Optional, Sequence,
                    Tuple)

import numpy as np

from ompi_tpu.core.datatype import BYTE
from ompi_tpu.core.errors import MPIError, ERR_REQUEST
from ompi_tpu.core.request import Request
from ompi_tpu.mca.var import register_var, register_pvar
from ompi_tpu.runtime import forensics as _forensics
from ompi_tpu.runtime import mpool
from ompi_tpu.runtime import trace as _trace

# Distinct CID plane per traffic class: COLL_CID_BIT = 1<<30 (coll/basic),
# PART_CID_BIT = 1<<29 (pml/partitioned) — NBC takes 1<<28 so overlapping
# nonblocking schedules, partitioned transfers, and blocking collectives on
# the same communicator can never cross-match.
NBC_CID_BIT = 1 << 28

_window_var = register_var(
    "coll_round", "window", 4,
    help="Max unordered rounds kept in flight per schedule (1 = "
         "lockstep, the pre-PR-10 barrier-per-round behavior). Only "
         "rounds yielded with ordered=False window; an ordered round "
         "is a full barrier.", level=6)
_copy_mode_var = register_var(
    "coll_round", "copy_mode", 0,
    help="1 = legacy round engine (fresh np.empty per recv, staged "
         "recv->dest copies, algorithm-side concat/ascontiguousarray "
         "staging) kept verbatim for the bench A/B — the copies feed "
         "coll_round_bytes_copied either way, so copies-per-byte-moved "
         "is measured, not estimated", level=8)

# measured datapath counters (read via the coll_round_* pvars):
# copied = staging bytes the round engine/algorithms duplicated;
# moved  = payload bytes carried by round sends+recvs;
# pool_hits = recv blocks served from a size-class free list;
# windowed  = rounds issued without waiting (ordered=False, in-window).
# Bumps go through _bump: the app thread (run_blocking) and the
# progress thread (NbcRequest callbacks) both count, and an unlocked
# dict read-modify-write loses increments under that interleaving (the
# progress._call_count lesson) — the lock is per ROUND, not per byte,
# so the hot path pays one uncontended acquire per bump site.
_ctr = {"copied": 0, "moved": 0, "pool_hits": 0, "windowed": 0}
_ctr_lock = threading.Lock()


def _bump(key: str, n: int = 1) -> None:
    with _ctr_lock:
        _ctr[key] += n

register_pvar("coll_round", "bytes_copied", lambda: _ctr["copied"],
              help="Staging bytes copied by the collective round engine "
                   "and its algorithms (legacy A/B baseline included)")
register_pvar("coll_round", "bytes_moved", lambda: _ctr["moved"],
              help="Payload bytes carried by round sends+recvs — the "
                   "denominator of copies-per-byte-moved")
register_pvar("coll_round", "pool_hits", lambda: _ctr["pool_hits"],
              help="Round recv blocks served from the mpool size-class "
                   "free lists (steady-state recycling proof)")
register_pvar("coll_round", "windowed", lambda: _ctr["windowed"],
              help="Rounds issued without a barrier (ordered=False "
                   "inside the coll_round_window)")


# coll/persist imports this module, so the replay-counter handle binds
# lazily — a one-time memo, not a per-Start sys.modules lookup (Start
# latency is the pvar the persistent A/B measures)
_persist_mod = None


def _persist():
    global _persist_mod
    if _persist_mod is None:
        from ompi_tpu.coll import persist

        _persist_mod = persist
    return _persist_mod


def copy_mode() -> bool:
    """True when the legacy (copying) round engine is armed — the
    algorithms branch to their verbatim pre-PR-10 staging on it."""
    return bool(_copy_mode_var._value)


# ------------------------------------------------------- stall forensics
# Live-schedule registry for the forensics provider: populated only
# while the plane is armed (one live-Var load per schedule otherwise).
# NbcRequests ride a WeakSet (they die with their requests); blocking
# schedules check in/out explicitly around the drive loop.
import weakref as _weakref  # noqa: E402

_fx_lock = threading.Lock()
_live_nbc: "_weakref.WeakSet" = _weakref.WeakSet()
_live_blocking: Dict[int, dict] = {}


def _fx_debug_state() -> dict:
    """Forensics provider: every in-flight schedule's round batches and
    window occupancy (what the schedule is waiting FOR), plus the
    datapath counters. NbcRequest fields are read under each request's
    own lock — the same lock its batch retirement holds."""
    now = time.monotonic()
    with _fx_lock:
        nbc = [r for r in _live_nbc]
        blocking = [dict(v) for v in _live_blocking.values()]
    reqs = []
    nbc_live = 0
    for r in nbc:
        if r._complete.is_set():
            continue
        nbc_live += 1
        if len(reqs) >= _forensics.CAP:
            continue
        with r._lock:
            waiting = ("round-self" if r._wait_self
                       else "ordered-barrier" if r._wait_batch is not None
                       else "window-full" if r._park_bufs is not None
                       else "schedule-done" if r._gen_done
                       else "advancing")
            reqs.append({"tag": r._tag, "cid": r._cid,
                         "inflight_batches": r._inflight,
                         "waiting": waiting,
                         "child_error": r._child_error,
                         "age_s": round(
                             now - getattr(r, "_fx_born", now), 3)})
    for b in blocking:
        b["age_s"] = round(now - b.pop("born"), 3)
    with _ctr_lock:
        counters = dict(_ctr)
    return {"window": int(_window_var._value),
            "nbc_inflight": reqs,
            "nbc_inflight_omitted": max(0, nbc_live - len(reqs)),
            "blocking": _forensics.clip(blocking),
            "blocking_omitted": max(0, len(blocking) - _forensics.CAP),
            "counters": counters}


_forensics.register_provider("coll.sched", _fx_debug_state)


def note_copied(nbytes: int) -> None:
    """Charge a staging copy to the round-engine copy budget."""
    _bump("copied", int(nbytes))


class Round:
    """One communication round: isend all ``sends``, irecv all ``recvs``,
    then hand the received payloads back to the generator in order.

    ``sends``  — (contiguous uint8 view, dst comm-rank): the engine
    borrows the view; the caller must not mutate it until the round (or,
    for unordered rounds, the schedule's next barrier) completes.
    ``recvs``  — (nbytes, src) for a pooled staging block, or
    (nbytes, src, dest_view) to land the payload directly in ``dest_view``
    (a writable contiguous uint8 view of exactly ``nbytes``).
    ``ordered`` — False marks the round independent: the engine may
    window it. Contract precision: an unordered round's results and
    buffers are guaranteed only when the generator RESUMES from the
    next ordered yield (or the schedule completes) — both engines issue
    an ordered round's sends/recvs BEFORE draining the window (the
    recvs pre-post), so the ordered round's own payloads must not
    depend on any in-flight unordered result.
    ``wait``   — (only meaningful with ``ordered=False``) the generator
    resumes as soon as THIS round's own sends/recvs complete, WITHOUT
    draining other in-flight unordered rounds: its own results are
    guaranteed at resume, everything else keeps flying. This is the
    cross-phase pipelining seam (coll/persist.py's chunked allreduce
    issues chunk k+1's reduce-scatter rounds while chunk k's allgather
    rounds are still in flight) — a full ``ordered`` barrier between
    the phases would serialize exactly the overlap the chunking buys.
    ``free``   — previously-received pooled views the generator is done
    with: recycled immediately instead of at schedule end (the
    segmented-ring steady-state path).
    ``qos``    — QoS class for this round's sends (ompi_tpu/qos.py;
    None = the pml's own classification). A schedule phase that tags
    its rounds BULK lets the shaped tcp btl interleave another phase's
    frames ahead of it instead of serializing them FIFO.
    ``plane``  — tag sub-plane (0-3): rounds on different planes match
    on distinct tags. REQUIRED whenever two phases of one schedule
    carry different QoS classes to the same peer: the shaped btl
    reorders across classes, and same-(cid, src, tag) frames arriving
    out of send order would bind to the wrong posted receives.
    ``chunk``  — pipeline-chunk ordinal (or None): purely descriptive
    trace stamp so coll/persist's chunked replays keep their stage
    structure visible in the merged timeline (the ``coll.round`` span
    tools/mpicrit.py groups wire edges under)."""

    __slots__ = ("sends", "recvs", "ordered", "wait", "free", "qos",
                 "plane", "chunk")

    def __init__(self,
                 sends: Sequence[Tuple[np.ndarray, int]] = (),
                 recvs: Sequence[Tuple] = (),
                 ordered: bool = True,
                 wait: bool = False,
                 free: Sequence[np.ndarray] = (),
                 qos: Optional[int] = None,
                 plane: int = 0,
                 chunk: Optional[int] = None):
        self.sends = list(sends)
        self.recvs = list(recvs)
        self.ordered = ordered
        self.wait = wait
        self.free = free
        self.qos = qos
        self.plane = plane
        self.chunk = chunk


Schedule = Generator[Round, List[np.ndarray], None]


class _RoundState:
    """Pool-block ownership for one schedule — the explicit contract:
    blocks recycle on clean completion (or early, via ``Round.free``);
    a failing/abandoned schedule DISCARDS them, never recycles (the
    PR 9 dying-conn lesson: an in-flight drain may still land in a
    block, and a recycled block would alias its next owner)."""

    __slots__ = ("_held", "rounds")

    def __init__(self):
        # id(view) -> (pool, block, view): the view keeps id() stable
        self._held: Dict[int, tuple] = {}
        # rounds issued so far — the trace-only ordinal stamped on
        # coll.round spans (per schedule, not per communicator)
        self.rounds = 0

    def alloc(self, nbytes: int) -> np.ndarray:
        pool = mpool.class_pool(nbytes)
        if pool is None:  # zero-byte tokens / jumbo past the class cap
            return np.empty(nbytes, dtype=np.uint8)
        block, hit = pool.acquire_pair()
        if hit:
            _bump("pool_hits")
        view = np.frombuffer(block, np.uint8, nbytes)
        self._held[id(view)] = (pool, block, view)  # owns: _held
        return view

    def free(self, views) -> None:
        for v in views:
            ent = self._held.pop(id(v), None)  # mpiracer: disable=cross-thread-race — a _RoundState belongs to ONE schedule; the single-driver _gen_running token (NbcRequest) serializes every resume that can reach free()
            if ent is not None:
                ent[0].release(ent[1])

    def release_all(self) -> None:
        held, self._held = self._held, {}
        for pool, block, _ in held.values():
            pool.release(block)

    def discard_all(self) -> None:
        held, self._held = self._held, {}
        for pool, block, _ in held.values():
            pool.discard(block)


def _issue(comm, rnd: Round, tag: int, cid: int, state: _RoundState):
    """Post the round's receives then sends. Returns
    (requests, recv_bufs, postcopies): ``postcopies`` is the legacy
    engine's deferred recv->dest staging — (dest, staging, nbytes)
    triples applied (and counted) after the round completes, exactly
    where the pre-PR-10 algorithms did ``out[...] = bufs[i]``."""
    reqs = []
    bufs: List[np.ndarray] = []
    post: List[tuple] = []
    legacy = _copy_mode_var._value
    moved = 0
    tr = _trace.enabled()
    if tr:
        t0 = _trace.now()
    if rnd.plane:
        # tag sub-plane: far above the per-comm NBC sequence counters,
        # symmetric across ranks (both sides build the same rounds)
        tag = tag | (rnd.plane << 56)
    for rec in rnd.recvs:
        nbytes, src = rec[0], rec[1]
        dest = rec[2] if len(rec) > 2 else None
        moved += nbytes
        if legacy:
            # the legacy engine, verbatim: a fresh allocation per recv,
            # then a staged copy into the caller's destination
            buf = np.empty(nbytes, dtype=np.uint8)
            if dest is not None:
                post.append((dest, buf, nbytes))
                bufs.append(dest)
            else:
                bufs.append(buf)
        elif dest is not None:
            buf = dest  # zero staging: the payload lands in place
            bufs.append(dest)
        else:
            buf = state.alloc(nbytes)
            bufs.append(buf)
        reqs.append(comm.pml.irecv(buf, nbytes, BYTE,
                                   comm.group.world_rank(src), tag, cid))
    for data, dst in rnd.sends:
        if not data.flags.c_contiguous:
            # the one allowed send-side staging copy: a genuinely
            # non-contiguous source can't be borrowed as a flat view
            data = np.ascontiguousarray(data)  # mpilint: disable=hot-copy — non-contiguous fallback, counted
            _bump("copied", data.nbytes)
        moved += data.nbytes
        reqs.append(comm.pml.isend(data, data.nbytes, BYTE,
                                   comm.group.world_rank(dst), tag, cid,
                                   qos=rnd.qos))
    _bump("moved", moved)
    if tr:
        # stage structure into the trace: (cid, tag, round, chunk,
        # plane) lets tools/mpicrit.py group the wire edges a round
        # produced under the schedule stage that issued them
        state.rounds += 1
        _trace.record_span("coll.round", t0, _trace.now(), cat="coll",
                           cid=cid, tag=tag, round=state.rounds,
                           chunk=rnd.chunk, plane=rnd.plane,
                           sends=len(rnd.sends), recvs=len(rnd.recvs))
    return reqs, bufs, post


def _apply_post(post) -> None:
    """Legacy staged recv->dest copies, charged to the copy budget."""
    for dest, staging, nbytes in post:
        dest[:nbytes] = staging[:nbytes]
        _bump("copied", nbytes)


def run_blocking(comm, gen: Schedule, tag: int, cid: int) -> None:
    """Drive a schedule to completion. Ordered rounds are barriers
    (every outstanding round drains first, then the round itself);
    unordered rounds stay in flight up to ``coll_round_window``. A
    failing request must not abandon outstanding requests mid-schedule
    (the Waitsome lesson): unwaited sends would cross-match the NEXT
    schedule on this communicator — wait them all, then surface the
    first error. Pool blocks recycle only on clean completion;
    any failure path discards them."""
    state = _RoundState()
    inflight: deque = deque()  # (reqs, postcopies) of unordered rounds
    first_error: Optional[MPIError] = None
    fx_key = None
    if _forensics._enable_var._value:  # forensics check-in
        fx_key = id(state)
        with _fx_lock:
            _live_blocking[fx_key] = {"tag": tag, "cid": cid,
                                      "round": 0,
                                      "born": time.monotonic()}

    def retire(reqs, post) -> None:
        nonlocal first_error
        for r in reqs:
            try:
                r.Wait()
            except MPIError as e:
                if first_error is None:
                    first_error = e
        if first_error is None:
            _apply_post(post)

    bufs: Optional[List[np.ndarray]] = None
    first = True
    try:
        while True:
            try:
                rnd = next(gen) if first else gen.send(bufs)
            except StopIteration:
                break
            first = False
            if fx_key is not None:
                with _fx_lock:
                    ent = _live_blocking.get(fx_key)
                    if ent is not None:
                        ent["round"] += 1
            if rnd.free:
                state.free(rnd.free)
            reqs, bufs, post = _issue(comm, rnd, tag, cid, state)
            window = _window_var._value
            if rnd.ordered or window <= 1:
                while inflight:
                    retire(*inflight.popleft())
                retire(reqs, post)
            elif rnd.wait:
                # self-wait: this round's own results gate the resume,
                # earlier unordered rounds keep flying (the cross-phase
                # pipelining contract)
                if inflight:
                    _bump("windowed")
                retire(reqs, post)
            else:
                _bump("windowed")
                inflight.append((reqs, post))
                while len(inflight) >= max(1, window):
                    retire(*inflight.popleft())
            if first_error is not None:
                raise first_error
        while inflight:
            retire(*inflight.popleft())
        if first_error is not None:
            raise first_error
    except BaseException:
        while inflight:
            retire(*inflight.popleft())
        state.discard_all()
        raise
    finally:
        if fx_key is not None:  # forensics check-out, every exit path
            with _fx_lock:
                _live_blocking.pop(fx_key, None)
    state.release_all()


def alloc_nbc_tag(comm) -> int:
    """Per-comm schedule sequence number; ranks agree because MPI requires
    collectives to be called in the same order on every member."""
    seq = getattr(comm, "_nbc_seq", 0)
    comm._nbc_seq = seq + 1
    return seq


class NbcRequest(Request):
    """A nonblocking collective in flight: advances its schedule from
    request completion callbacks (libnbc's NBC_Progress analog), keeping
    up to ``coll_round_window`` unordered rounds in flight.

    Concurrency contract: exactly one thread drives the generator at a
    time (``_gen_running``); every other mutation — child errors, batch
    retirement, park/resume decisions, the pool-block release on the
    completion path — happens under ``self._lock``. ``_child_error`` in
    particular is written ONLY under the lock (the pre-PR-10 engine
    wrote it unlocked from the progress thread while ``_advance`` read
    it mid-loop, so a losing error could be dropped)."""

    def __init__(self, comm, gen: Schedule):
        super().__init__()
        self._comm = comm
        self._gen = gen
        self._tag = alloc_nbc_tag(comm)
        self._cid = comm.cid | NBC_CID_BIT
        self._lock = threading.Lock()
        self._child_error = 0
        self._state = _RoundState()
        self._inflight = 0          # issued-but-unretired batches
        self._wait_batch = None     # ordered batch the generator awaits
        self._wait_self = False     # Round.wait: resume on the batch's
        #                             OWN retirement, not the window's
        self._park_bufs = None      # bufs pending a free window slot
        self._gen_done = False
        self._finishing = False
        self._gen_running = True
        if _forensics._enable_var._value:  # forensics registry
            self._fx_born = time.monotonic()
            with _fx_lock:
                _live_nbc.add(self)
        self._advance(None, first=True)

    # ------------------------------------------------------------ engine
    def _advance(self, bufs: Optional[List[np.ndarray]],
                 first: bool = False) -> None:
        # invariant: the caller claimed _gen_running under the lock
        while True:
            with self._lock:
                err = self._child_error
            if err:
                self._gen_stopped()
                return
            try:
                rnd = next(self._gen) if first else self._gen.send(bufs)
            except StopIteration:
                self._gen_stopped(done=True)
                return
            except MPIError as e:
                self._gen_stopped(done=True, code=e.code)
                return
            except Exception:
                # Rounds >= 2 run inside completion callbacks on the
                # progress thread; an escaped exception would kill it and
                # leave Wait() spinning forever. Fail the request instead.
                from ompi_tpu.core.errors import ERR_INTERN
                from ompi_tpu.utils.output import get_logger

                get_logger("coll.nbc").warning(
                    "schedule raised", exc_info=True)
                self._gen_stopped(done=True, code=ERR_INTERN)
                return
            first = False
            if rnd.free:
                with self._lock:
                    self._state.free(rnd.free)
            reqs, next_bufs, post = _issue(self._comm, rnd, self._tag,
                                           self._cid, self._state)
            window = max(1, _window_var._value)
            ordered = rnd.ordered or window <= 1
            wait_self = not ordered and rnd.wait
            if not reqs:
                if ordered:
                    # a request-less ordered round is still a barrier
                    # (run_blocking drains the window for it too):
                    # resume only once every in-flight batch retires
                    with self._lock:
                        if self._inflight > 0:
                            self._wait_batch = {"n": 0, "post": (),
                                                "bufs": next_bufs}
                            self._gen_running = False
                            return
                bufs = next_bufs
                continue
            # Hold one extra token so synchronous completions loop here
            # instead of recursing through the callback.
            batch = {"n": len(reqs) + 1, "post": post, "bufs": next_bufs}
            with self._lock:
                self._inflight += 1
            for r in reqs:
                r.add_completion_callback(
                    lambda r, b=batch: self._child_done(r, b))
            overlapped = False
            with self._lock:
                batch["n"] -= 1
                done_now = batch["n"] == 0
                if done_now:
                    if not self._child_error:
                        _apply_post(batch["post"])
                    batch["post"] = ()
                    self._inflight -= 1
                    barrier_ok = self._inflight == 0
                else:
                    barrier_ok = False
                if ordered:
                    if not (done_now and barrier_ok):
                        # resume when THIS batch and the whole window
                        # have drained (ordered == barrier)
                        self._wait_batch = batch
                        self._gen_running = False
                        return
                elif wait_self:
                    # Round.wait: this batch's own retirement gates the
                    # resume; other in-flight batches keep flying (they
                    # are the overlap the schedule asked for)
                    overlapped = self._inflight > (0 if done_now else 1)
                    if not done_now:
                        self._wait_batch = batch
                        self._wait_self = True
                        self._gen_running = False
                        if overlapped:
                            # _ctr_lock is a leaf lock: safe under _lock
                            _bump("windowed")
                        return
                elif not done_now and self._inflight >= window:
                    self._park_bufs = next_bufs
                    self._gen_running = False
                    return
            if (not ordered and not wait_self and not done_now) or \
                    (wait_self and overlapped):
                _bump("windowed")
            bufs = next_bufs

    def _child_done(self, r, batch) -> None:
        fire = None
        finish = None
        with self._lock:
            if r._error and not self._child_error:
                self._child_error = r._error
            batch["n"] -= 1
            if batch["n"] != 0:
                return
            # batch retired: apply its legacy staging copies while the
            # lock orders them before any generator resume
            if not self._child_error:
                _apply_post(batch["post"])
            batch["post"] = ()
            self._inflight -= 1
            if self._gen_running or self._finishing:
                pass  # the driving thread observes the new state itself
            elif self._child_error:
                if self._inflight == 0:
                    self._finishing = True
                    finish = self._child_error
            elif self._wait_batch is not None:
                # ordered waits resume when the whole window drains; a
                # Round.wait batch resumes on its OWN retirement (the
                # just-retired batch is `batch`), leaving other rounds
                # in flight
                if self._inflight == 0 or \
                        (self._wait_self and batch is self._wait_batch):
                    fire = self._wait_batch["bufs"]
                    self._wait_batch = None
                    self._wait_self = False
                    self._gen_running = True
            elif self._park_bufs is not None and \
                    self._inflight < max(1, _window_var._value):
                fire = self._park_bufs
                self._park_bufs = None
                self._gen_running = True
                _bump("windowed")
            elif self._gen_done and self._inflight == 0:
                self._finishing = True
                finish = 0
        if finish is not None:
            self._finish_schedule(finish)
        elif fire is not None:
            self._advance(fire)

    def _gen_stopped(self, done: bool = False, code: int = 0) -> None:
        """The driving thread is leaving the advance loop: either the
        generator finished/raised (``done``) or a child error stops the
        schedule. Completion fires once every in-flight batch retires."""
        finish = None
        with self._lock:
            if code and not self._child_error:
                self._child_error = code
            if done:
                self._gen_done = True
            self._gen_running = False
            if self._inflight == 0 and not self._finishing:
                self._finishing = True
                finish = self._child_error
        if finish is not None:
            self._finish_schedule(finish)

    def _finish_schedule(self, err: int) -> None:
        """Terminal transition (exactly once): settle pool-block
        ownership — recycle on success, DISCARD on failure — then
        complete the request."""
        if err:
            self._state.discard_all()
            try:
                self._gen.close()
            except Exception:
                pass
        else:
            self._state.release_all()
        self._set_complete(err)


class PersistentCollRequest(Request):
    """Persistent collective (MPI_Allreduce_init & co, MPI-4).

    Reference: ompi/mca/coll/coll.h:545-620 declares the *_init third of the
    triple surface; libnbc builds the schedule at init and replays it per
    Start. Here ``issue`` is a thunk capturing the buffers/op/root that
    launches the activation: when the persistent-plan compiler
    (coll/persist.py) froze the lowering at init, it replays the frozen
    schedule; otherwise (``coll_persist_enable=0`` or an ineligible
    shape) it rebuilds and launches a fresh NbcRequest per Start — the
    pre-PR-11 re-issue path, kept verbatim as the A/B baseline. Tag
    consistency across ranks holds because MPI requires persistent
    starts (like every collective) to be identically ordered on all
    members, so the per-comm NBC sequence counter stays aligned."""

    def __init__(self, issue: Callable[[], Request],
                 name: str = "persistent collective"):
        super().__init__()
        self.persistent = True
        self._issue = issue
        self._name = name
        # Active state is distinct from completion: the request stays
        # *active* from Start until Wait/Test collects it, even though the
        # inner schedule may have completed microseconds after Start (MPI
        # 3.0 §3.9: a started persistent request must be completed by a
        # completion call before it can be restarted).
        self._active = False
        self._complete.set()  # inactive == complete (MPI semantics)

    def Start(self) -> "PersistentCollRequest":
        if self._active:
            raise MPIError(
                ERR_REQUEST,
                f"Start on still-active {self._name}: the previous "
                "activation must be completed by Wait/Test before a "
                "restart (MPI 3.0 §3.9)")
        self._active = True
        self._complete.clear()
        self._error = 0
        t0 = time.perf_counter()
        try:
            inner = self._issue()
        except BaseException:
            # a failed issue (revoked comm, bad schedule) must not wedge
            # the request: roll back to inactive so the error is
            # retryable and Wait doesn't spin forever
            self._active = False
            self._complete.set()
            raise
        # the A/B denominator: Start-call latency (issue decisions +
        # first-round launch) accumulated for BOTH the frozen-replay and
        # re-issue paths, so the replay win is measured from pvars
        p = _persist()
        p._starts[0] += 1
        p._replay_us[0] += (time.perf_counter() - t0) * 1e6

        def done(r):
            self.status = r.status
            self._set_complete(r._error)

        inner.add_completion_callback(done)
        return self

    def Free(self) -> None:
        """MPI_Request_free on an inactive persistent collective: retire
        the frozen plan so its held pool blocks return to their free
        lists (an active plan's are discarded — in-flight drains may
        still land in its views). The comm's Free covers requests the
        caller never frees."""
        box = getattr(self, "_persist_box", None)
        if box is not None and box[0] is not None:
            box[0].retire()
            box[0] = None

    def _finish(self, status) -> None:
        self._active = False
        super()._finish(status)


class JaxRequest(Request):
    """Mesh-path nonblocking collective: the jitted executable has been
    dispatched (jax dispatch is asynchronous); the request completes when
    the result buffers are ready. ``result`` holds the output array(s)."""

    def __init__(self, result):
        super().__init__()
        self.result = result
        self._set_dispatch_complete()

    def Start(self):
        raise MPIError(ERR_REQUEST, "not a persistent request")

    def _set_dispatch_complete(self):
        # Completion flag tracks device readiness lazily: Test polls
        # is_ready, Wait blocks on the buffer.
        pass

    @property
    def is_complete(self) -> bool:
        try:
            import jax

            leaves = jax.tree_util.tree_leaves(self.result)
            return all(
                x.is_ready() if hasattr(x, "is_ready") else True
                for x in leaves
            )
        except Exception:
            return True

    def Test(self, status=None) -> bool:
        if self.is_complete:
            if not self._complete.is_set():
                self._set_complete(0)
            self._finish(status)
            return True
        return False

    def Wait(self, status=None, timeout=None):
        import jax
        import time

        if timeout is None:
            jax.block_until_ready(self.result)
        else:
            deadline = time.monotonic() + timeout
            while not self.is_complete:
                if time.monotonic() > deadline:
                    from ompi_tpu.core.errors import ERR_PENDING

                    raise MPIError(ERR_PENDING, "Wait timed out")
                time.sleep(0.001)
        if not self._complete.is_set():
            self._set_complete(0)
        self._finish(status)


class MeshPersistentRequest(JaxRequest):
    """Persistent mesh collective (Allreduce_init & co on XlaComm).

    The TPU-native reading of MPI-4 persistence: the setup that init
    amortizes is trace+compile — XlaComm's init methods run one warm-up
    dispatch so every Start is a cached-executable dispatch only, and
    (PR 11) pre-freeze the resolved fast-table executable into
    ``dispatch`` so Start skips even the fast-dict lookup. jax operands
    are immutable, so "re-reads the buffer at Start" becomes an optional
    fresh operand argument (same shape/dtype/sharding triggers no
    retrace); omitted, the init-time operand is re-run. ``result`` holds
    the latest Start's output once Wait/Test observes completion.

    ``donate`` (armed by ``coll_persist_donate``) is a second
    executable compiled at init with the operand buffer DONATED to XLA:
    a ``Start(x)`` with a fresh operand consumes ``x`` (its buffer is
    reused for the output — the MPI-4 reading: the started buffer
    belongs to the operation until completion). The init-time operand is
    kept un-donated so operand-less restarts stay valid."""

    def __init__(self, comm, dispatch, x, frozen: bool = False,
                 donate=None):
        Request.__init__(self)
        self.persistent = True
        self._comm = comm
        self._dispatch = dispatch
        self._x = x
        self._frozen = frozen
        self._donate = donate
        self._active = False
        self.result = None
        self._complete.set()  # inactive == complete

    def Start(self, x=None):
        if self._active:
            raise MPIError(
                ERR_REQUEST,
                f"Start on still-active persistent mesh collective on "
                f"{self._comm.name}: complete it with Wait/Test first")
        self._comm._check_usable()  # revoked comms must not dispatch
        t0 = time.perf_counter()
        # dispatch before committing any state: a failed dispatch (bad
        # shape/sharding) must leave the request inactive with the
        # previous operand and result intact, not report stale data as
        # this Start's success
        if x is not None and self._donate is not None \
                and x is not self._x:
            # donated path: x is consumed; the init-time operand stays
            # bound (and un-donated) for operand-less restarts — which
            # is why passing the init operand itself routes to the
            # un-donated dispatch below instead of deleting it
            result = self._donate(x)
        else:
            result = self._dispatch(self._x if x is None else x)
            if x is not None:
                self._x = x
        p = _persist()
        p._starts[0] += 1
        p._replay_us[0] += (time.perf_counter() - t0) * 1e6
        self._active = True
        self._complete.clear()
        self._error = 0
        self.result = result
        return self

    def _finish(self, status) -> None:
        self._active = False
        super()._finish(status)
