"""Round-based collective schedules: the engine under both the tuned
blocking algorithms and the nonblocking (MPI_I*) collectives.

Reference: ompi/mca/coll/libnbc (12,429 LoC) expresses every nonblocking
collective as a DAG of send/recv/op/copy steps grouped into rounds
(NBC_Sched_send/recv/op, nbc_internal.h:156-161) progressed by
opal_progress. Redesign: an algorithm here is a Python *generator* that
yields ``Round`` objects (the communication steps) and performs local
compute between yields — the round barrier the reference encodes as
schedule delimiters falls out of generator suspension. One algorithm
definition serves both paths:

- blocking:   ``run_blocking`` drains the generator, waiting each round;
- nonblocking: ``NbcRequest`` issues each round and advances from request
  completion callbacks, so the schedule progresses from the progress
  engine/thread exactly like libnbc rounds do.

Traffic isolation: nonblocking schedules run in a dedicated CID plane
(NBC_CID_BIT) with a per-communicator sequence number as the tag, so
overlapping schedules on one communicator never cross-match (libnbc's
per-comm tag counter, nbc_internal.h SCHED tag logic).
"""

from __future__ import annotations

import threading
from typing import Callable, Generator, List, Optional, Sequence, Tuple

import numpy as np

from ompi_tpu.core.datatype import BYTE
from ompi_tpu.core.errors import MPIError, ERR_REQUEST
from ompi_tpu.core.request import Request

# Distinct CID plane per traffic class: COLL_CID_BIT = 1<<30 (coll/basic),
# PART_CID_BIT = 1<<29 (pml/partitioned) — NBC takes 1<<28 so overlapping
# nonblocking schedules, partitioned transfers, and blocking collectives on
# the same communicator can never cross-match.
NBC_CID_BIT = 1 << 28


class Round:
    """One communication round: isend all ``sends``, irecv all ``recvs``,
    then hand the received payloads back to the generator in order."""

    __slots__ = ("sends", "recvs")

    def __init__(self,
                 sends: Sequence[Tuple[np.ndarray, int]] = (),
                 recvs: Sequence[Tuple[int, int]] = ()):
        self.sends = list(sends)   # (contiguous uint8 data, dst comm-rank)
        self.recvs = list(recvs)   # (nbytes, src comm-rank)


Schedule = Generator[Round, List[np.ndarray], None]


def _issue(comm, rnd: Round, tag: int, cid: int):
    """Post the round's receives then sends; returns (requests, recv_bufs)."""
    reqs = []
    bufs = []
    for nbytes, src in rnd.recvs:
        buf = np.empty(nbytes, dtype=np.uint8)
        bufs.append(buf)
        reqs.append(comm.pml.irecv(buf, nbytes, BYTE,
                                   comm.group.world_rank(src), tag, cid))
    for data, dst in rnd.sends:
        reqs.append(comm.pml.isend(data, data.nbytes, BYTE,
                                   comm.group.world_rank(dst), tag, cid))
    return reqs, bufs


def run_blocking(comm, gen: Schedule, tag: int, cid: int) -> None:
    """Drive a schedule to completion, waiting out each round. A failing
    request must not abandon the round's remaining requests mid-loop
    (the Waitsome lesson): outstanding sends left unwaited would
    cross-match the NEXT schedule on this communicator — wait them all,
    then surface the first error."""
    bufs: Optional[List[np.ndarray]] = None
    while True:
        try:
            rnd = next(gen) if bufs is None else gen.send(bufs)
        except StopIteration:
            return
        reqs, bufs = _issue(comm, rnd, tag, cid)
        first_error: Optional[MPIError] = None
        for r in reqs:
            try:
                r.Wait()
            except MPIError as e:
                if first_error is None:
                    first_error = e
        if first_error is not None:
            raise first_error


def alloc_nbc_tag(comm) -> int:
    """Per-comm schedule sequence number; ranks agree because MPI requires
    collectives to be called in the same order on every member."""
    seq = getattr(comm, "_nbc_seq", 0)
    comm._nbc_seq = seq + 1
    return seq


class NbcRequest(Request):
    """A nonblocking collective in flight: advances its schedule one round
    at a time from completion callbacks (libnbc's NBC_Progress analog)."""

    def __init__(self, comm, gen: Schedule):
        super().__init__()
        self._comm = comm
        self._gen = gen
        self._tag = alloc_nbc_tag(comm)
        self._cid = comm.cid | NBC_CID_BIT
        self._lock = threading.Lock()
        self._child_error = 0
        self._advance(None, first=True)

    def _advance(self, bufs: Optional[List[np.ndarray]],
                 first: bool = False) -> None:
        while True:
            if self._child_error:
                self._gen.close()
                self._set_complete(self._child_error)
                return
            try:
                rnd = next(self._gen) if first else self._gen.send(bufs)
            except StopIteration:
                self._set_complete(0)
                return
            except MPIError as e:
                self._set_complete(e.code)
                return
            except Exception:
                # Rounds >= 2 run inside completion callbacks on the
                # progress thread; an escaped exception would kill it and
                # leave Wait() spinning forever. Fail the request instead.
                from ompi_tpu.core.errors import ERR_INTERN
                from ompi_tpu.utils.output import get_logger

                get_logger("coll.nbc").warning(
                    "schedule raised", exc_info=True)
                self._set_complete(ERR_INTERN)
                return
            first = False
            reqs, bufs = _issue(self._comm, rnd, self._tag, self._cid)
            if not reqs:
                continue
            # Hold one extra token so synchronous completions loop here
            # instead of recursing through the callback.
            state = {"n": len(reqs) + 1}
            next_bufs = bufs

            def child_done(r, state=state, next_bufs=next_bufs):
                if r._error and not self._child_error:
                    self._child_error = r._error
                with self._lock:
                    state["n"] -= 1
                    fire = state["n"] == 0
                if fire:
                    self._advance(next_bufs)

            for r in reqs:
                r.add_completion_callback(child_done)
            with self._lock:
                state["n"] -= 1
                synchronous = state["n"] == 0
            if not synchronous:
                return  # the last callback will re-enter _advance


class PersistentCollRequest(Request):
    """Persistent collective (MPI_Allreduce_init & co, MPI-4).

    Reference: ompi/mca/coll/coll.h:545-620 declares the *_init third of the
    triple surface; libnbc builds the schedule at init and replays it per
    Start. Here ``issue`` is a thunk capturing the buffers/op/root that
    builds and launches a fresh NbcRequest per Start — the generator *is*
    the schedule, so replay == regenerate. Tag consistency across ranks
    holds because MPI requires persistent starts (like every collective) to
    be identically ordered on all members, so the per-comm NBC sequence
    counter stays aligned."""

    def __init__(self, issue: Callable[[], Request]):
        super().__init__()
        self.persistent = True
        self._issue = issue
        # Active state is distinct from completion: the request stays
        # *active* from Start until Wait/Test collects it, even though the
        # inner schedule may have completed microseconds after Start (MPI
        # 3.0 §3.9: a started persistent request must be completed by a
        # completion call before it can be restarted).
        self._active = False
        self._complete.set()  # inactive == complete (MPI semantics)

    def Start(self) -> "PersistentCollRequest":
        if self._active:
            raise MPIError(ERR_REQUEST,
                           "persistent collective already active")
        self._active = True
        self._complete.clear()
        self._error = 0
        try:
            inner = self._issue()
        except BaseException:
            # a failed issue (revoked comm, bad schedule) must not wedge
            # the request: roll back to inactive so the error is
            # retryable and Wait doesn't spin forever
            self._active = False
            self._complete.set()
            raise

        def done(r):
            self.status = r.status
            self._set_complete(r._error)

        inner.add_completion_callback(done)
        return self

    def _finish(self, status) -> None:
        self._active = False
        super()._finish(status)


class JaxRequest(Request):
    """Mesh-path nonblocking collective: the jitted executable has been
    dispatched (jax dispatch is asynchronous); the request completes when
    the result buffers are ready. ``result`` holds the output array(s)."""

    def __init__(self, result):
        super().__init__()
        self.result = result
        self._set_dispatch_complete()

    def Start(self):
        raise MPIError(ERR_REQUEST, "not a persistent request")

    def _set_dispatch_complete(self):
        # Completion flag tracks device readiness lazily: Test polls
        # is_ready, Wait blocks on the buffer.
        pass

    @property
    def is_complete(self) -> bool:
        try:
            import jax

            leaves = jax.tree_util.tree_leaves(self.result)
            return all(
                x.is_ready() if hasattr(x, "is_ready") else True
                for x in leaves
            )
        except Exception:
            return True

    def Test(self, status=None) -> bool:
        if self.is_complete:
            if not self._complete.is_set():
                self._set_complete(0)
            self._finish(status)
            return True
        return False

    def Wait(self, status=None, timeout=None):
        import jax
        import time

        if timeout is None:
            jax.block_until_ready(self.result)
        else:
            deadline = time.monotonic() + timeout
            while not self.is_complete:
                if time.monotonic() > deadline:
                    from ompi_tpu.core.errors import ERR_PENDING

                    raise MPIError(ERR_PENDING, "Wait timed out")
                time.sleep(0.001)
        if not self._complete.is_set():
            self._set_complete(0)
        self._finish(status)


class MeshPersistentRequest(JaxRequest):
    """Persistent mesh collective (Allreduce_init & co on XlaComm).

    The TPU-native reading of MPI-4 persistence: the setup that init
    amortizes is trace+compile — XlaComm's init methods run one warm-up
    dispatch so every Start is a cached-executable dispatch only. jax
    operands are immutable, so "re-reads the buffer at Start" becomes an
    optional fresh operand argument (same shape/dtype/sharding triggers no
    retrace); omitted, the init-time operand is re-run. ``result`` holds
    the latest Start's output once Wait/Test observes completion."""

    def __init__(self, comm, dispatch, x):
        Request.__init__(self)
        self.persistent = True
        self._comm = comm
        self._dispatch = dispatch
        self._x = x
        self._active = False
        self.result = None
        self._complete.set()  # inactive == complete

    def Start(self, x=None):
        if self._active:
            raise MPIError(ERR_REQUEST,
                           "persistent collective already active")
        self._comm._check_usable()  # revoked comms must not dispatch
        # dispatch before committing any state: a failed dispatch (bad
        # shape/sharding) must leave the request inactive with the
        # previous operand and result intact, not report stale data as
        # this Start's success
        result = self._dispatch(self._x if x is None else x)
        if x is not None:
            self._x = x
        self._active = True
        self._complete.clear()
        self._error = 0
        self.result = result
        return self

    def _finish(self, status) -> None:
        self._active = False
        super()._finish(status)
