"""coll/han — hierarchical two-level collectives.

Reference: ompi/mca/coll/han (10,517 LoC) — splits each collective into
an intra-node phase over the fast local transport and an inter-node
phase between per-node leaders, with sub-communicators built lazily on
first use (coll_han_subcomms.c).

TPU-native mapping: "node" = the set of peers reached over self/sm (the
ICI/fast domain analog on the host path); the leader ("up") phase rides
tcp (the DCN analog). Mesh-mode comms don't take this component: within
a slice XLA already owns the hierarchical ICI schedule, and the
multi-slice DCN split belongs to the launcher topology (future work,
like the reference's han+accelerator stacking).

Decision rule (reference: coll_han component query): at least two
nodes AND at least one node with two or more ranks — otherwise the
two-level split degenerates and the flat algorithms win.
"""

from __future__ import annotations

from typing import Any, List, Optional

import numpy as np

from ompi_tpu.coll.base import CollModule, coll_framework
from ompi_tpu.comm.communicator import UNDEFINED
from ompi_tpu.core import op as _op
from ompi_tpu.mca.component import Component
from ompi_tpu.mca.var import register_var, get_var
from ompi_tpu.runtime import spc

import threading
import weakref

# guard: while han builds its own sub-communicators, their coll
# selection must not pick han again (under fake topologies the
# round-robin map could otherwise recurse a level per Split)
_building = threading.local()

# per-process cache of universe-rank -> node identity (world-static)
_node_sid_cache: dict = {}

# per-comm HanColl registry: when BOTH han and coll/hier select on one
# communicator they must share ONE module instance — and therefore ONE
# lazily-built (low, up) sub-communicator pair — instead of each Split
# its own copy (weak VALUES: the comm's coll table holds the module via
# its bound slot fns, so the entry dies with the comm)
_shared_modules: "weakref.WeakValueDictionary" = weakref.WeakValueDictionary()


def shared_han(comm, node_of: "List[int]") -> "HanColl":
    """The ONE HanColl (and its lazily-built leader sub-communicators)
    for this comm — han's component query and coll/hier's composer both
    resolve through here. Node ids are normalized to first-seen order
    BEFORE the identity check: han's modex map carries first-seen-RANK
    ids ([0,0,2,2]) while hier's DomainMap is 0..k-1 ([0,0,1,1]) for
    the same layout, and comparing the raw forms would silently defeat
    the sharing on every real contiguous topology."""
    first: dict = {}
    norm = [first.setdefault(n, len(first)) for n in node_of]
    key = comm.cid
    m = _shared_modules.get(key)
    if m is None or m._node_of != norm:
        m = HanColl(norm)
        _shared_modules[key] = m
    return m

register_var("coll_han", "fake_nodes", 0,
             help="Pretend the comm spans N nodes (round-robin by rank) — "
                  "the single-host test hook for the hierarchy "
                  "(reference analog: han's topology override vars)",
             level=7)


class HanColl(CollModule):
    """Two-level allreduce/bcast/reduce/barrier over lazily-built
    (low, up) sub-communicators."""

    def __init__(self, node_of: List[int]):
        # full node map, identical on every member (from the modex or
        # the fake-topology var) — per-rank heuristics would make the
        # selection inconsistent across members and deadlock the first
        # collective
        self._node_of = node_of
        self._low = None
        self._up = None       # leaders comm (None on non-leaders)
        self._built = False
        # precomputed topology maps (the node map is immutable)
        leaders = sorted(min(r for r, n in enumerate(node_of) if n == node)
                         for node in set(node_of))
        self._up_rank_of_node = {node_of[ld]: i
                                 for i, ld in enumerate(leaders)}
        self._leader_of_node = {node_of[ld]: ld for ld in leaders}
        members: dict = {}
        for r, n in enumerate(node_of):
            members.setdefault(n, []).append(r)
        self._low_rank = {r: members[n].index(r)
                          for r, n in enumerate(node_of)}

    # ------------------------------------------------------------ subcomms
    def _subcomms(self, comm):
        """Build (low, up) on first use (reference:
        coll_han_subcomms.c lazy creation inside the first collective —
        legal because the first collective is the same on every
        member)."""
        if not self._built:
            _building.active = True
            try:
                with spc.suppressed():
                    node = self._node_of[comm.rank]
                    low = comm.Split(node, comm.rank)
                    is_leader = low.Get_rank() == 0
                    up = comm.Split(0 if is_leader else UNDEFINED,
                                    comm.rank)
            finally:
                _building.active = False
            self._low, self._up = low, up
            self._built = True
        return self._low, self._up

    def _up_root(self, comm, root_node: int) -> int:
        """The up-comm rank of root_node's leader (leaders ordered by
        comm rank; each node's leader is its lowest comm rank)."""
        return self._up_rank_of_node[root_node]

    # ---------------------------------------------------------- collectives
    @staticmethod
    def _flat():
        """Flat fallback for re-entrant calls: the Splits inside
        _subcomms run parent-comm collectives (Allgather + the CID
        agreement's Allreduce) that dispatch back into han's own slots —
        without this delegation the first collective deadlocks on
        itself."""
        from ompi_tpu.coll.basic import flat_module

        return flat_module()

    def allreduce(self, comm, sendbuf, recvbuf, op: _op.Op = _op.SUM) -> None:
        """low reduce -> leaders allreduce -> low bcast (the han
        'simple' allreduce schedule). Non-commutative ops take the flat
        path: the hierarchical split regroups contributions out of rank
        order (reference: han checks ompi_op_is_commute and falls
        back)."""
        if getattr(_building, "active", False) or not op.commutative:
            return self._flat().allreduce(comm, sendbuf, recvbuf, op)
        from ompi_tpu.comm.communicator import parse_buffer

        low, up = self._subcomms(comm)
        with spc.suppressed():
            low.Reduce(sendbuf, recvbuf, op=op, root=0)
            if up is not None:
                robj, rcount, rdt = parse_buffer(recvbuf)
                tmp = np.array(np.asarray(robj), copy=True)
                up.Allreduce([tmp, rcount, rdt], recvbuf, op=op)
            low.Bcast(recvbuf, root=0)

    # coll-plane tag for the leader->root hand-off in rooted reduce
    _TAG_REDUCE_HANDOFF = -70

    def reduce(self, comm, sendbuf, recvbuf, op: _op.Op = _op.SUM,
               root: int = 0) -> None:
        """Rooted two-level reduce honoring the MPI contract (recvbuf
        significant ONLY at root — reference: han's reduce schedule with
        a leader->root hand-off when the root isn't its node's
        leader)."""
        if getattr(_building, "active", False) or not op.commutative \
                or sendbuf is None:
            # flat path for non-commutative ops and MPI_IN_PLACE (the
            # staging below needs a real send descriptor)
            return self._flat().reduce(comm, sendbuf, recvbuf, op, root)
        from ompi_tpu.coll.basic import COLL_CID_BIT
        from ompi_tpu.comm.communicator import parse_buffer

        low, up = self._subcomms(comm)
        sobj, scount, sdt = parse_buffer(sendbuf)
        if not sdt.is_contiguous:
            # the packed staging buffer below is not a valid unpacked
            # buffer for derived datatypes (extent > size) — flat path
            return self._flat().reduce(comm, sendbuf, recvbuf, op, root)
        tmp = np.zeros(scount * sdt.size, np.uint8)
        tview = [tmp, scount, sdt]
        with spc.suppressed():
            low.Reduce(sendbuf, tview, op=op, root=0)
            root_up = self._up_rank_of_node[self._node_of[root]]
            if up is not None:
                tmp2 = np.zeros_like(tmp)
                up.Reduce(tview, [tmp2, scount, sdt], op=op, root=root_up)
                tmp = tmp2
        # hand the result from the root-node leader to the root
        leader_is_root = (self._low_rank[root] == 0)
        cid = comm.cid | COLL_CID_BIT
        if comm.rank == root:
            robj, rcount, rdt = parse_buffer(recvbuf)
            if leader_is_root and up is not None:
                np.asarray(robj).reshape(-1).view(np.uint8)[
                    : scount * sdt.size] = tmp
            else:
                leader = self._leader_of_node[self._node_of[root]]
                comm.pml.irecv(robj, rcount, rdt,
                               comm._world_rank(leader),
                               self._TAG_REDUCE_HANDOFF, cid).Wait()
        if (not leader_is_root
                and comm.rank == self._leader_of_node[self._node_of[root]]):
            comm.pml.isend(tmp, scount, sdt, comm._world_rank(root),
                           self._TAG_REDUCE_HANDOFF, cid).Wait()

    def bcast(self, comm, buf, root: int = 0) -> None:
        if getattr(_building, "active", False):
            return self._flat().bcast(comm, buf, root)
        low, up = self._subcomms(comm)  # completes self._node_of
        root_node = self._node_of[root]
        my_node = self._node_of[comm.rank]
        with spc.suppressed():
            if my_node == root_node:
                # distribute within the root's node first so its leader
                # holds the data for the up phase
                low.Bcast(buf, root=self._low_rank_of(comm, root))
            if up is not None:
                up.Bcast(buf, root=self._up_root(comm, root_node))
            if my_node != root_node:
                low.Bcast(buf, root=0)

    def _low_rank_of(self, comm, root: int) -> int:
        return self._low_rank[root]

    def barrier(self, comm) -> None:
        if getattr(_building, "active", False):
            return self._flat().barrier(comm)
        low, up = self._subcomms(comm)
        with spc.suppressed():
            low.Barrier()
            if up is not None:
                up.Barrier()
            low.Barrier()


class HanCollComponent(Component):
    NAME = "han"
    PRIORITY = 45  # above tuned/basic; below xla/self

    def query(self, comm=None, **ctx: Any) -> Optional[HanColl]:
        from ompi_tpu.comm.communicator import ProcComm

        if getattr(_building, "active", False):
            return None  # never stack han inside its own subcomms
        if not isinstance(comm, ProcComm) or comm.size < 3:
            return None
        fake = int(get_var("coll_han", "fake_nodes"))
        if fake > 1:
            if fake >= comm.size:
                return None  # no node would hold 2+ ranks
            return shared_han(comm, [r % fake for r in range(comm.size)])
        node_of = self._modex_node_map(comm)
        if node_of is None:
            return None
        n_nodes = len(set(node_of))
        biggest = max(node_of.count(n) for n in set(node_of))
        if n_nodes >= 2 and biggest >= 2:
            return shared_han(comm, node_of)
        return None

    @staticmethod
    def _modex_node_map(comm) -> Optional[List[int]]:
        """Node id per comm rank from the modex locality cards — the
        SAME key/value store on every member, so the selection decision
        (and the map) is consistent everywhere. Per-rank endpoint
        heuristics are not: lazily-wired cross-job endpoints differ
        between members (found the hard way — a mixed han/flat selection
        deadlocks the first collective)."""
        from ompi_tpu.runtime import wireup

        ctx = wireup._ctx
        if ctx is None:
            return None
        modex = ctx["modex"]
        raw = []
        for r in range(comm.size):
            w = comm._world_rank(r)
            sid = _node_sid_cache.get(w)
            if sid is None:
                try:
                    # post-fence, a missing card never appears: don't wait
                    sid = str(modex.get(w, "btl.sm.node", timeout=0.0))
                    _node_sid_cache[w] = sid  # only cache real cards:
                    # a transient miss must not freeze a wrong identity
                    # for the life of the process
                except Exception:
                    sid = f"solo-{w}"  # no sm: its own node (uncached)
            raw.append(sid)
        first: dict = {}
        return [first.setdefault(sid, r) for r, sid in enumerate(raw)]


coll_framework.register(HanCollComponent())
