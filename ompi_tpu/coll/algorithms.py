"""Host collective algorithm library, expressed as round schedules.

Reference: ompi/mca/coll/base — allreduce {recursive doubling
coll_base_allreduce.c:134, ring :345, segmented ring :622}, binomial
bcast/reduce (coll_base_bcast.c, coll_base_reduce.c), bruck allgather
(coll_base_allgather.c), pairwise alltoall (coll_base_alltoall.c),
dissemination barrier. Every function is a generator yielding
``sched.Round`` objects (see coll/sched.py); the same definition backs the
blocking tuned path and the nonblocking MPI_I* path.

All algorithms are datatype-agnostic: payloads travel as convertor-packed
bytes; reductions view packed streams with the datatype's element dtype
(homogeneous or value/index pair typemaps, as in coll/basic).

Datapath discipline (the PR 9 borrowed-view contract, one layer up):
sends are contiguous VIEWS over the caller's packed/accumulator buffers;
receives land either in a pooled staging block (reduction operands) or
directly in their final location — a slice of the caller's receive
buffer or the ring accumulator — via the ``(nbytes, src, dest)`` recv
form. A staging copy happens only where the data genuinely cannot be
borrowed (non-contiguous layouts, the bruck rotation, padded ring
tails) and every such copy is charged to ``coll_round_bytes_copied``.
The pre-PR-10 staging (fresh recv buffers, recv->out copies, the bruck
concatenate, the ring segment scratch + gather) is kept VERBATIM behind
``coll_round_copy_mode=1`` as the measured A/B baseline.

Reduction-bearing schedules (recursive doubling, ring, binomial reduce)
require a commutative op — the decision layer (coll/tuned.py) routes
non-commutative ops to the rank-ordered linear algorithms, matching the
reference's decision rules.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from ompi_tpu.coll.basic import _np_reduce_typed, _typed_view
from ompi_tpu.coll import sched as _sched
from ompi_tpu.coll.sched import Round
from ompi_tpu.comm.communicator import parse_buffer
from ompi_tpu.core import op as _op
from ompi_tpu.core.convertor import (
    _as_byte_view as _as_bytes,
    pack as cv_pack,
    unpack as cv_unpack,
)
from ompi_tpu.core.datatype import Datatype


def _packed(buf):
    """Packed wire bytes of ``buf`` — the convertor's contiguous fast
    path is a borrowed view; only a genuinely non-contiguous pack output
    pays a counted staging copy."""
    obj, count, dt = parse_buffer(buf)
    data = cv_pack(obj, count, dt)
    if not data.flags.c_contiguous:
        _sched.note_copied(data.nbytes)
        data = np.ascontiguousarray(data)  # mpilint: disable=hot-copy — non-contiguous pack output, counted
    return data, count, dt


def _bytes(a: np.ndarray) -> np.ndarray:
    """Flat uint8 VIEW of ``a``; a non-contiguous source is the one
    counted fallback copy the borrowed-view contract allows."""
    if a.flags.c_contiguous:
        return a.view(np.uint8)
    _sched.note_copied(a.nbytes)
    return np.ascontiguousarray(a).view(np.uint8)  # mpilint: disable=hot-copy — non-contiguous fallback, counted


def _unpack_into(data: np.ndarray, buf) -> None:
    obj, count, dt = parse_buffer(buf)
    cv_unpack(_bytes(data), obj, count, dt)


def _direct_view(buf) -> Optional[np.ndarray]:
    """Flat uint8 view over the receive buffer so rounds can land
    payloads in their FINAL location (no staging, no final unpack), or
    None when staging is required: non-contiguous datatype or layout —
    or the legacy engine, which always stages (that difference is
    exactly what the copy_mode A/B measures)."""
    if _sched.copy_mode():
        return None
    obj, count, dt = parse_buffer(buf)
    if dt.is_contiguous and isinstance(obj, np.ndarray) \
            and obj.flags.c_contiguous and obj.flags.writeable:
        return _as_bytes(obj)[:count * dt.size]
    return None


def _unpack_staging(data: np.ndarray, buf) -> None:
    """Final unpack from a STAGING array into the user's receive buffer
    — a counted copy (the direct-landing path skips it entirely)."""
    obj, count, dt = parse_buffer(buf)
    cv_unpack(data, obj, count, dt)
    _sched.note_copied(data.nbytes)


# ----------------------------------------------------------------- barrier
def barrier_dissemination(comm):
    """ceil(log2 n) zero-byte rounds (coll/base dissemination)."""
    n, r = comm.size, comm.rank
    token = np.zeros(0, dtype=np.uint8)
    d = 1
    while d < n:
        yield Round(sends=[(token, (r + d) % n)], recvs=[(0, (r - d) % n)])
        d <<= 1


# ------------------------------------------------------------------- bcast
def bcast_binomial(comm, buf, root: int):
    """Binomial tree (coll_base_bcast.c binomial). Non-root ranks with a
    contiguous buffer receive STRAIGHT into it and forward borrowed
    views of it — zero staging on the whole tree."""
    n, r = comm.size, comm.rank
    obj, count, dt = parse_buffer(buf)
    nbytes = count * dt.size
    vrank = (r - root) % n
    dest: Optional[np.ndarray] = None
    data: Optional[np.ndarray] = None
    if vrank == 0:
        data = _packed(buf)[0]
    else:
        mask = 1
        while not (vrank & mask):
            mask <<= 1
        src = (vrank - mask + root) % n
        dest = _direct_view(buf)
        if dest is not None:
            yield Round(recvs=[(nbytes, src, dest)])
            data = dest
        else:
            bufs = yield Round(recvs=[(nbytes, src)])
            data = bufs[0]
        # children live below the bit that connected us to our parent
        mask >>= 1
    if vrank == 0:
        mask = 1
        while mask < n:
            mask <<= 1
        mask >>= 1
    sends = []
    while mask > 0:
        if vrank + mask < n and not (vrank & mask):
            sends.append((data, (vrank + mask + root) % n))
        mask >>= 1
    if sends:
        yield Round(sends=sends)
    if vrank != 0 and dest is None:
        _unpack_staging(data, buf)


# ------------------------------------------------------------------ reduce
def reduce_linear(comm, sendbuf, recvbuf, op: _op.Op, root: int):
    """Rank-ordered linear fan-in — correct for non-commutative ops
    (coll/basic linear reduce). Contributions arrive in pooled blocks
    (they are reduction operands, not final data)."""
    n, r = comm.size, comm.rank
    packed, _, dt = _packed(recvbuf if sendbuf is None else sendbuf)
    if r != root:
        yield Round(sends=[(packed, root)])
        return
    others = [i for i in range(n) if i != root]
    bufs = yield Round(recvs=[(packed.nbytes, i) for i in others])
    parts: List[np.ndarray] = [None] * n  # type: ignore[list-item]
    parts[root] = packed
    for i, b in zip(others, bufs):
        parts[i] = b
    acc = _typed_view(parts[0].copy(), dt)
    for i in range(1, n):
        acc = _np_reduce_typed(op, acc, _typed_view(parts[i], dt))
    _unpack_into(acc, recvbuf)


def reduce_binomial(comm, sendbuf, recvbuf, op: _op.Op, root: int):
    """Binomial fan-in for commutative ops (coll_base_reduce.c binomial):
    log2 n depth instead of the linear O(n) fan-in at the root."""
    n, r = comm.size, comm.rank
    packed, _, dt = _packed(recvbuf if sendbuf is None else sendbuf)
    nb = packed.nbytes
    vrank = (r - root) % n
    children = []
    mask = 1
    while mask < n:
        if vrank & mask:
            break
        if vrank + mask < n:
            children.append((vrank + mask + root) % n)
        mask <<= 1
    acc = _typed_view(packed.copy(), dt)
    if children:
        bufs = yield Round(recvs=[(nb, c) for c in children])
        for b in bufs:
            acc = _np_reduce_typed(op, acc, _typed_view(b, dt))
    if vrank != 0:
        parent = (vrank - mask + root) % n
        yield Round(sends=[(_bytes(acc), parent)])
        return
    _unpack_into(acc, recvbuf)  # vrank 0 == root


# --------------------------------------------------------------- allreduce
def allreduce_recursive_doubling(comm, sendbuf, recvbuf, op: _op.Op):
    """Recursive doubling with the non-power-of-two fold-in pre/post phase
    (coll_base_allreduce.c:134)."""
    n, r = comm.size, comm.rank
    packed, _, dt = _packed(recvbuf if sendbuf is None else sendbuf)
    nb = packed.nbytes
    acc = _typed_view(packed.copy(), dt)
    if n == 1:
        _unpack_into(acc, recvbuf)
        return
    pow2 = 1 << (n.bit_length() - 1)
    if pow2 > n:
        pow2 >>= 1
    rem = n - pow2
    # pre: the first 2*rem ranks fold pairwise so pow2 ranks remain
    if r < 2 * rem:
        if r % 2 == 0:
            yield Round(sends=[(_bytes(acc), r + 1)])
            newrank = -1
        else:
            bufs = yield Round(recvs=[(nb, r - 1)])
            acc = _np_reduce_typed(op, acc, _typed_view(bufs[0], dt))
            newrank = r // 2
    else:
        newrank = r - rem
    if newrank >= 0:
        mask = 1
        while mask < pow2:
            pn = newrank ^ mask
            partner = pn * 2 + 1 if pn < rem else pn + rem
            bufs = yield Round(sends=[(_bytes(acc), partner)],
                               recvs=[(nb, partner)])
            acc = _np_reduce_typed(op, acc, _typed_view(bufs[0], dt))
            mask <<= 1
    # post: hand results back to the folded-out even ranks
    if r < 2 * rem:
        if r % 2 == 1:
            yield Round(sends=[(_bytes(acc), r - 1)])
        else:
            bufs = yield Round(recvs=[(nb, r + 1)])
            acc = _typed_view(bufs[0], dt)
    _unpack_into(acc, recvbuf)


def allreduce_ring(comm, sendbuf, recvbuf, op: _op.Op, nseg: int = 1):
    """Ring allreduce: reduce-scatter ring + allgather ring
    (coll_base_allreduce.c:345); with ``nseg > 1`` the element space is
    split into segments whose rings run pipelined — segment s executes its
    step t in global round s + t, so communication of one segment overlaps
    reduction of the next (the segmented ring of :622).

    Datapath: the accumulator lives directly in the user's receive
    buffer when its layout allows (in-place reduction — no private copy,
    no final unpack), segments ALIAS it instead of staging into padded
    scratch (scratch only for a non-divisible tail, counted), allgather-
    phase blocks land in their final slot via dest-view recvs, and the
    reduce-scatter staging blocks recycle through ``Round.free`` each
    step — the pool's steady state."""
    n, r = comm.size, comm.rank
    packed, _, dt = _packed(recvbuf if sendbuf is None else sendbuf)
    legacy = _sched.copy_mode()
    rdest = None if legacy else _direct_view(recvbuf)
    if rdest is not None and rdest.nbytes == packed.nbytes \
            and dt.np_dtype is not None:
        # accumulate in the receive buffer itself: seed it with the send
        # payload (free for IN_PLACE — packed already aliases recvbuf)
        if sendbuf is not None:
            rdest[:] = _bytes(packed)
        typed = rdest.view(dt.np_dtype)
        in_dest = True
    else:
        typed = _typed_view(packed.copy(), dt)
        in_dest = False
    if n == 1:
        if not in_dest:
            _unpack_into(typed, recvbuf)
        return
    total = typed.size
    nseg = max(1, min(int(nseg), max(1, total // n)))
    bounds = [total * s // nseg for s in range(nseg + 1)]
    segs = []  # [arr of n*k elements, k, orig_len, offset, staged]
    for s in range(nseg):
        a, b = bounds[s], bounds[s + 1]
        ln = b - a
        k = max(1, -(-ln // n))
        if not legacy and ln == n * k:
            segs.append([typed[a:b], k, ln, a, False])  # alias, no copy
        else:
            # legacy engine verbatim — and the padded-tail fallback: a
            # non-divisible segment stages into padded scratch, counted
            arr = np.zeros(n * k, dtype=typed.dtype)
            arr[:ln] = typed[a:b]
            _sched.note_copied(ln * typed.itemsize)
            segs.append([arr, k, ln, a, True])
    steps = 2 * n - 2
    left, right = (r - 1) % n, (r + 1) % n
    done_blocks: List[np.ndarray] = []
    for g in range(steps + nseg - 1):
        sends, recvs, meta = [], [], []
        for s, (arr, k, ln, off, staged) in enumerate(segs):
            t = g - s
            if not (0 <= t < steps):
                continue
            isz = arr.itemsize
            if t < n - 1:  # reduce-scatter phase
                sb, rb = (r - t) % n, (r - t - 1) % n
                kind = "rs"
            else:          # allgather phase
                ag = t - (n - 1)
                sb, rb = (r + 1 - ag) % n, (r - ag) % n
                kind = "ag"
            sends.append((_bytes(arr[sb * k:(sb + 1) * k]), right))
            if kind == "ag" and not legacy:
                # the forwarded block IS final data: land it in place
                recvs.append((k * isz, left,
                              _bytes(arr[rb * k:(rb + 1) * k])))
            else:
                recvs.append((k * isz, left))
            meta.append((s, kind, rb))
        bufs = yield Round(sends=sends, recvs=recvs, free=done_blocks)
        done_blocks = []
        for (s, kind, rb), b in zip(meta, bufs):
            arr, k, ln, off, staged = segs[s]
            if kind == "rs":
                got = b.view(arr.dtype)
                blk = arr[rb * k:(rb + 1) * k]
                arr[rb * k:(rb + 1) * k] = _np_reduce_typed(op, blk, got)
                done_blocks.append(b)  # operand consumed: recycle next yield
            elif legacy:
                arr[rb * k:(rb + 1) * k] = b.view(arr.dtype)
                _sched.note_copied(k * arr.itemsize)
            # (new engine: ag blocks landed in their final slot already)
    if legacy:
        out = np.empty(total, dtype=typed.dtype)
        for arr, k, ln, off, _staged in segs:
            out[off:off + ln] = arr[:ln]
        _sched.note_copied(total * typed.itemsize)
        _unpack_staging(out, recvbuf)
        return
    for arr, k, ln, off, staged in segs:
        if staged:  # padded-tail scratch folds back, counted
            typed[off:off + ln] = arr[:ln]
            _sched.note_copied(ln * typed.itemsize)
    if not in_dest:
        # the non-contiguous/pair-dtype fallback stages: its final
        # unpack is a counted copy the in-recvbuf path avoids
        _unpack_staging(_bytes(typed), recvbuf)


# --------------------------------------------------------------- allgather
def allgather_ring(comm, sendbuf, recvbuf):
    """n-1 rounds, each forwarding the block received last round
    (coll_base_allgather.c ring). Blocks land straight in the receive
    buffer and are forwarded as borrowed views of it."""
    n, r = comm.size, comm.rank
    block, _, _ = _packed(sendbuf)
    nb = block.nbytes
    dest = _direct_view(recvbuf)
    out = dest if dest is not None else np.empty(n * nb, dtype=np.uint8)
    out[r * nb:(r + 1) * nb] = block
    _sched.note_copied(nb)  # own-block placement (both engines)
    cur = out[r * nb:(r + 1) * nb]
    for d in range(1, n):
        src = (r - d) % n
        slot = out[src * nb:(src + 1) * nb]
        if dest is not None:
            yield Round(sends=[(cur, (r + 1) % n)],
                        recvs=[(nb, (r - 1) % n, slot)])
            cur = slot
        else:
            bufs = yield Round(sends=[(cur, (r + 1) % n)],
                               recvs=[(nb, (r - 1) % n)])
            cur = bufs[0]
            out[src * nb:(src + 1) * nb] = cur
            _sched.note_copied(nb)
    if dest is None:
        _unpack_staging(out, recvbuf)


def allgather_bruck(comm, sendbuf, recvbuf):
    """Bruck: ceil(log2 n) rounds of doubling block trains
    (coll_base_allgather.c bruck) — latency-optimal for small messages.
    The train lives in ONE flat accumulator: each send is a contiguous
    view of its head, each recv lands at its tail — the per-round
    concatenate of the legacy engine is gone; only the final bruck
    rotation copies (counted)."""
    n, r = comm.size, comm.rank
    block, _, _ = _packed(sendbuf)
    nb = block.nbytes
    if _sched.copy_mode():
        # legacy engine verbatim: list-of-blocks train, concatenated
        # into a fresh send buffer every round — the measured baseline
        acc: List[np.ndarray] = [block]
        dist = 1
        while dist < n:
            cnt = min(dist, n - dist)
            if cnt > 1:
                send_data = _bytes(np.concatenate(  # mpilint: disable=hot-copy — legacy copy_mode=1 A/B baseline, counted
                    [np.frombuffer(b, np.uint8) for b in acc[:cnt]]))
                _sched.note_copied(send_data.nbytes)
            else:
                send_data = _bytes(acc[0])
            bufs = yield Round(sends=[(send_data, (r - dist) % n)],
                               recvs=[(cnt * nb, (r + dist) % n)])
            got = bufs[0]
            acc.extend(got[i * nb:(i + 1) * nb] for i in range(cnt))
            dist <<= 1
        out = np.empty(n * nb, dtype=np.uint8)
        for i in range(n):
            src = (r + i) % n
            out[src * nb:(src + 1) * nb] = acc[i]
        _sched.note_copied(n * nb)
        _unpack_staging(out, recvbuf)
        return
    accbuf = np.empty(n * nb, dtype=np.uint8)
    accbuf[:nb] = block
    _sched.note_copied(nb)
    dist = 1
    while dist < n:
        cnt = min(dist, n - dist)
        yield Round(
            sends=[(accbuf[:cnt * nb], (r - dist) % n)],
            recvs=[(cnt * nb, (r + dist) % n,
                    accbuf[dist * nb:(dist + cnt) * nb])])
        dist <<= 1
    dest = _direct_view(recvbuf)
    out = dest if dest is not None else np.empty(n * nb, dtype=np.uint8)
    for i in range(n):  # the bruck rotation: a genuine reorder, counted
        src = (r + i) % n
        out[src * nb:(src + 1) * nb] = accbuf[i * nb:(i + 1) * nb]
    _sched.note_copied(n * nb)
    if dest is None:
        _unpack_staging(out, recvbuf)


def allgatherv_ring(comm, sendbuf, recvbuf, counts, displs):
    n, r = comm.size, comm.rank
    block, _, _ = _packed(sendbuf)
    robj, rcount, rdt = parse_buffer(recvbuf)
    counts = list(counts)
    if displs is None:
        displs = np.cumsum([0] + counts[:-1]).tolist()
    esz = rdt.size
    dest = _direct_view(recvbuf)
    out = dest if dest is not None \
        else np.zeros(rcount * esz, dtype=np.uint8)
    out[displs[r] * esz:displs[r] * esz + block.nbytes] = block
    _sched.note_copied(block.nbytes)
    cur = out[displs[r] * esz:displs[r] * esz + block.nbytes]
    for d in range(1, n):
        src = (r - d) % n
        nb_src = counts[src] * esz
        slot = out[displs[src] * esz:displs[src] * esz + nb_src]
        if dest is not None:
            yield Round(sends=[(cur, (r + 1) % n)],
                        recvs=[(nb_src, (r - 1) % n, slot)])
            cur = slot
        else:
            bufs = yield Round(sends=[(cur, (r + 1) % n)],
                               recvs=[(nb_src, (r - 1) % n)])
            cur = bufs[0]
            out[displs[src] * esz:displs[src] * esz + nb_src] = cur
            _sched.note_copied(nb_src)
    if dest is None:
        cv_unpack(out, robj, rcount, rdt)
        _sched.note_copied(out.nbytes)


# ---------------------------------------------------------------- alltoall
def alltoall_pairwise(comm, sendbuf, recvbuf):
    """n-1 pairwise exchange rounds (coll_base_alltoall.c pairwise).
    Every round is INDEPENDENT — disjoint send slices of the packed
    buffer, disjoint landing slots in the receive buffer — so rounds
    are yielded ``ordered=False`` and up to ``coll_round_window`` stay
    in flight instead of a barrier per peer."""
    n, r = comm.size, comm.rank
    packed, _, _ = _packed(sendbuf)
    nb = packed.nbytes // n
    robj, rcount, rdt = parse_buffer(recvbuf)
    dest = _direct_view(recvbuf)
    out = dest if dest is not None \
        else np.empty(rcount * rdt.size, dtype=np.uint8)
    out[r * nb:(r + 1) * nb] = packed[r * nb:(r + 1) * nb]
    _sched.note_copied(nb)
    for d in range(1, n):
        dst, src = (r + d) % n, (r - d) % n
        chunk = _bytes(packed[dst * nb:(dst + 1) * nb])
        if dest is not None:
            yield Round(sends=[(chunk, dst)],
                        recvs=[(nb, src, out[src * nb:(src + 1) * nb])],
                        ordered=False)
        else:
            bufs = yield Round(sends=[(chunk, dst)], recvs=[(nb, src)])
            out[src * nb:(src + 1) * nb] = bufs[0]
            _sched.note_copied(nb)
    if dest is None:
        cv_unpack(out, robj, rcount, rdt)
        _sched.note_copied(out.nbytes)


def alltoallv_pairwise(comm, sendbuf, recvbuf, sendcounts, sdispls,
                       recvcounts, rdispls):
    """Pairwise exchange with per-peer counts/displacements (element
    units, matching the blocking basic.alltoallv semantics). Rounds are
    independent — disjoint send slices, disjoint landing slots — so
    they window ``ordered=False`` like the fixed-count pairwise."""
    n, r = comm.size, comm.rank
    packed, _, sdt = _packed(sendbuf)
    robj, rcount, rdt = parse_buffer(recvbuf)
    se, re_ = sdt.size, rdt.size
    dest = _direct_view(recvbuf)
    out = dest if dest is not None \
        else np.zeros(rcount * re_, dtype=np.uint8)
    own = packed[sdispls[r] * se:(sdispls[r] + sendcounts[r]) * se]
    out[rdispls[r] * re_:rdispls[r] * re_ + own.nbytes] = own
    _sched.note_copied(own.nbytes)
    for d in range(1, n):
        dst, src = (r + d) % n, (r - d) % n
        chunk = _bytes(packed[sdispls[dst] * se:
                              (sdispls[dst] + sendcounts[dst]) * se])
        nb_src = recvcounts[src] * re_
        off = rdispls[src] * re_
        if dest is not None:
            yield Round(sends=[(chunk, dst)],
                        recvs=[(nb_src, src, out[off:off + nb_src])],
                        ordered=False)
        else:
            bufs = yield Round(sends=[(chunk, dst)],
                               recvs=[(nb_src, src)])
            out[off:off + nb_src] = bufs[0]
            _sched.note_copied(nb_src)
    if dest is None:
        cv_unpack(out, robj, rcount, rdt)
        _sched.note_copied(out.nbytes)


# ----------------------------------------------------------- gather/scatter
def gather_linear(comm, sendbuf, recvbuf, root: int):
    n, r = comm.size, comm.rank
    block, _, _ = _packed(sendbuf)
    if r != root:
        yield Round(sends=[(block, root)])
        return
    nb = block.nbytes
    dest = _direct_view(recvbuf)
    out = dest if dest is not None else np.empty(n * nb, dtype=np.uint8)
    others = [i for i in range(n) if i != root]
    if dest is not None:
        yield Round(recvs=[(nb, i, out[i * nb:(i + 1) * nb])
                           for i in others])
    else:
        bufs = yield Round(recvs=[(nb, i) for i in others])
        for i, b in zip(others, bufs):
            out[i * nb:(i + 1) * nb] = b
            _sched.note_copied(nb)
    out[root * nb:(root + 1) * nb] = block
    _sched.note_copied(nb)
    if dest is None:
        _unpack_staging(out, recvbuf)


def gatherv_linear(comm, sendbuf, recvbuf, counts, displs, root: int):
    """Linear fan-in with per-rank counts/displacements (element units,
    the blocking basic.gatherv semantics): the root lands each block
    straight in its displacement slot."""
    n, r = comm.size, comm.rank
    block, _, _ = _packed(sendbuf)
    if r != root:
        yield Round(sends=[(block, root)])
        return
    robj, rcount, rdt = parse_buffer(recvbuf)
    counts = list(counts)
    if displs is None:
        displs = np.cumsum([0] + counts[:-1]).tolist()
    esz = rdt.size
    dest = _direct_view(recvbuf)
    out = dest if dest is not None \
        else np.zeros(rcount * esz, dtype=np.uint8)
    others = [i for i in range(n) if i != root]
    if dest is not None:
        yield Round(recvs=[(counts[i] * esz, i,
                            out[displs[i] * esz:
                                displs[i] * esz + counts[i] * esz])
                           for i in others])
    else:
        bufs = yield Round(recvs=[(counts[i] * esz, i) for i in others])
        for i, bb in zip(others, bufs):
            out[displs[i] * esz:displs[i] * esz + bb.nbytes] = bb
            _sched.note_copied(bb.nbytes)
    out[displs[root] * esz:displs[root] * esz + block.nbytes] = block
    _sched.note_copied(block.nbytes)
    if dest is None:
        _unpack_staging(out, recvbuf)


def scatterv_linear(comm, sendbuf, recvbuf, counts, displs, root: int):
    """Linear fan-out with per-rank counts/displacements (element
    units, the blocking basic.scatterv semantics)."""
    n, r = comm.size, comm.rank
    robj, rcount, rdt = parse_buffer(recvbuf)
    if r == root:
        packed, _, sdt = _packed(sendbuf)
        counts = list(counts)
        if displs is None:
            displs = np.cumsum([0] + counts[:-1]).tolist()
        esz = sdt.size
        sends = []
        for i in range(n):
            chunk = _bytes(packed[displs[i] * esz:
                                  (displs[i] + counts[i]) * esz])
            if i == root:
                cv_unpack(chunk, robj, rcount, rdt)
            else:
                sends.append((chunk, i))
        if sends:
            yield Round(sends=sends)
    else:
        nb = rcount * rdt.size
        dest = _direct_view(recvbuf)
        if dest is not None:
            yield Round(recvs=[(nb, root, dest)])
        else:
            bufs = yield Round(recvs=[(nb, root)])
            cv_unpack(bufs[0], robj, rcount, rdt)
            _sched.note_copied(nb)


def scatter_linear(comm, sendbuf, recvbuf, root: int):
    n, r = comm.size, comm.rank
    robj, rcount, rdt = parse_buffer(recvbuf)
    nb = rcount * rdt.size
    if r == root:
        packed, _, _ = _packed(sendbuf)
        sends = []
        for i in range(n):
            chunk = _bytes(packed[i * nb:(i + 1) * nb])
            if i == root:
                cv_unpack(chunk, robj, rcount, rdt)
            else:
                sends.append((chunk, i))
        if sends:
            yield Round(sends=sends)
    else:
        dest = _direct_view(recvbuf)
        if dest is not None:
            yield Round(recvs=[(nb, root, dest)])
        else:
            bufs = yield Round(recvs=[(nb, root)])
            cv_unpack(bufs[0], robj, rcount, rdt)
            _sched.note_copied(nb)


# -------------------------------------------------------------- scan family
def scan_linear(comm, sendbuf, recvbuf, op: _op.Op):
    n, r = comm.size, comm.rank
    packed, _, dt = _packed(recvbuf if sendbuf is None else sendbuf)
    if r > 0:
        bufs = yield Round(recvs=[(packed.nbytes, r - 1)])
        acc = _np_reduce_typed(op, _typed_view(bufs[0], dt),
                               _typed_view(packed.copy(), dt))
    else:
        acc = _typed_view(packed.copy(), dt)
    if r < n - 1:
        yield Round(sends=[(_bytes(acc), r + 1)])
    _unpack_into(acc, recvbuf)


def exscan_linear(comm, sendbuf, recvbuf, op: _op.Op):
    n, r = comm.size, comm.rank
    packed, _, dt = _packed(recvbuf if sendbuf is None else sendbuf)
    prefix: Optional[np.ndarray] = None
    if r > 0:
        bufs = yield Round(recvs=[(packed.nbytes, r - 1)])
        prefix = bufs[0]
    if r < n - 1:
        if prefix is None:
            nxt = packed
        else:
            nxt = _bytes(_np_reduce_typed(op, _typed_view(prefix.copy(), dt),
                                          _typed_view(packed, dt)))
        yield Round(sends=[(nxt, r + 1)])
    if prefix is not None:
        _unpack_into(np.frombuffer(prefix, np.uint8), recvbuf)


# --------------------------------------------------------- compound schedules
def reduce_scatter_block_sched(comm, sendbuf, recvbuf, op: _op.Op):
    """reduce + scatter composition, as one schedule."""
    robj, rcount, rdt = parse_buffer(recvbuf)
    n = comm.size
    tmp_obj = np.empty(rcount * n * max(rdt.extent, 1), dtype=np.uint8)
    tmp = [tmp_obj, rcount * n, rdt]
    alg = reduce_binomial if op.commutative else reduce_linear
    yield from alg(comm, sendbuf, tmp, op, 0)
    yield from scatter_linear(comm, tmp, recvbuf, 0)
