"""coll/xla — MPI collectives lowered to XLA collective HLO over the ICI mesh.

This is the flagship component (BASELINE.json north star): for mesh-mode
communicators every collective is a traced/jitted ``shard_map`` program.
No Python runs on the data path after trace time; compiles are cached per
(verb, op, dtype, shape) in the communicator (the compile-cache discipline
SURVEY.md §7 lists as hard part 6).

Communicator→mesh projection (SURVEY.md §7 hard part 2):

- **World comm** (every mesh position): collectives lower 1:1 to native XLA
  HLO — ``psum``/``pmax``/``pmin`` (AllReduce), ``all_gather``,
  ``psum_scatter`` (ReduceScatter), ``all_to_all`` — the compiler owns the
  ICI schedule.
- **Sub-communicators** (arbitrary partitions from Split/Create_group):
  jax's shard_map does not support ``axis_index_groups``, so grouped
  collectives lower to **ppermute schedules**: recursive doubling for
  power-of-two groups, ring rotation otherwise — the reference's own
  algorithm library (coll_base_allreduce.c:134 recursive doubling, :345
  ring; bcast/scan trees in coll_base_bcast.c) re-expressed as ICI
  collective-permute chains instead of PML round-trips, exactly the
  SURVEY.md §5 mapping. All rounds trace into one XLA program, so XLA
  fuses the elementwise combine into each permute step.

Singleton groups (the padding for non-members of Create_group and
MPI_UNDEFINED colors) are masked out of every schedule and keep their own
data — which is also the correct MPI semantics for 1-member comms.

MPI_Op → device computation: SUM/MAX/MIN lower natively; PROD,
logical/bitwise and jax-traceable user fns use their elementwise combine
inside the schedule (reference analog: op/avx SIMD kernels become VPU
vector code emitted by XLA). MINLOC/MAXLOC reduce (value, index) PAIR
arrays on device — trailing dim of 2, values in [..., 0], indices in
[..., 1] — since XLA has no structured record dtype; the host path keeps
the record-array layout (reference analog: op/avx's 2-wide pair kernels
over MPI_FLOAT_INT and friends).
"""

from __future__ import annotations

import math
from typing import Optional, Tuple

import numpy as np

from ompi_tpu.coll.base import CollModule, coll_framework
from ompi_tpu.core import op as _op
from ompi_tpu.core.errors import MPIError, ERR_ARG, ERR_UNSUPPORTED_OPERATION
from ompi_tpu.mca.component import Component
from ompi_tpu.mca.var import register_pvar
from ompi_tpu.runtime import trace as _trace


from ompi_tpu.parallel.axes import shard_map_compat as _shard_map


class _CacheStats:
    """Compile-cache telemetry (the discipline SURVEY.md §7 lists as
    hard part 6, made visible): hits count resolved-executable reuse —
    both the slow path's _jit_cache probe and the communicator's _fast
    table (parallel/mesh.py bumps hits there); misses and build time
    come from _cached. Surfaced as coll_xla_* MPI_T pvars."""

    __slots__ = ("hits", "misses", "compile_ns")

    def __init__(self):
        self.hits = 0
        self.misses = 0
        self.compile_ns = 0


stats = _CacheStats()

register_pvar("coll_xla", "cache_hits", lambda: stats.hits,
              help="Collective dispatches served by a cached executable")
register_pvar("coll_xla", "cache_misses", lambda: stats.misses,
              help="Collective dispatches that had to trace+compile")
register_pvar("coll_xla", "compile_time_us",
              lambda: stats.compile_ns // 1000,
              help="Cumulative trace+compile time across cache misses")


def _is_bool(dtype) -> bool:
    return np.dtype(dtype) == np.bool_


def _check_device_op(op: _op.Op, x=None) -> None:
    """Validate the op's device lowering before trace time. MINLOC/MAXLOC
    reduce (value, index) pairs: the host path carries them as structured
    record arrays (no XLA dtype), so the device layout is a trailing dim
    of 2 — ``x[..., 0]`` values, ``x[..., 1]`` indices (reference analog:
    the 2-wide pair kernels of op/avx)."""
    if op.name in _op.PAIR_OPS:
        if x is None or x.ndim < 1 or x.shape[-1] != 2:
            raise MPIError(
                ERR_UNSUPPORTED_OPERATION,
                f"device {op.name} reduces pair arrays: shape [..., 2] "
                "with (value, index) in the last dim (structured record "
                "dtypes have no XLA representation)")


# --------------------------------------------------------------- schedules
def _shift_perm(groups, d: int) -> Tuple[Tuple[int, int], ...]:
    """Ring shift by +d within each (non-singleton) group."""
    out = []
    for g in groups:
        n = len(g)
        if n < 2:
            continue
        out.extend((g[i], g[(i + d) % n]) for i in range(n))
    return tuple(out)


def _xor_perm(groups, bit: int) -> Tuple[Tuple[int, int], ...]:
    """Recursive-doubling partner exchange within each group."""
    out = []
    for g in groups:
        if len(g) < 2:
            continue
        out.extend((g[i], g[i ^ bit]) for i in range(len(g)))
    return tuple(out)


def cache_key(verb: str, op: Optional[_op.Op] = None, extra: Tuple = ()):
    """Public compile-cache key layout (shared with XlaComm's fast path —
    the per-call dispatch must be one dict hit, reference analog: the
    pre-resolved per-comm fn table pointers of comm->c_coll)."""
    key = (verb,)
    if op is not None:
        key += (op.uid,)
    return key + tuple(extra)


class XlaColl(CollModule):
    """Collectives for XlaComm; one compiled executable per
    (verb, op, dtype, shape), cached on the communicator."""

    # ------------------------------------------------------------ plumbing
    def _cached(self, comm, key, builder):
        fn = comm._jit_cache.get(key)
        if fn is None:
            stats.misses += 1
            raw = builder()

            # jax.jit is lazy: the real XLA compile happens on the first
            # invocation with concrete shapes, not in builder(). Cache a
            # one-shot wrapper that times (and spans) that first call,
            # then rebinds the cache entry to the raw executable so
            # steady state pays nothing.
            def first_call(*args, _raw=raw, _key=key, _comm=comm):
                import time as _t

                t0 = _t.perf_counter_ns()
                if _trace.enabled():
                    with _trace.span("coll.xla.compile", cat="coll",
                                     verb=str(_key[0])):
                        out = _raw(*args)
                else:
                    out = _raw(*args)
                stats.compile_ns += _t.perf_counter_ns() - t0
                _comm._jit_cache[_key] = _raw
                return out

            first_call._compile_pending = True
            comm._jit_cache[key] = first_call
            return first_call
        if not getattr(fn, "_compile_pending", False):
            # a still-pending wrapper (its first run raised before the
            # rebind) is a retry of the compile, not a cache hit
            stats.hits += 1
        return fn

    def _dispatch(self, comm, key, builder, *args):
        """Resolve (or build) the executable and run it under the
        coll.xla.dispatch span — the component-dispatch hook the
        BENCH_r05 'where does the layer time go' question needs."""
        fn = self._cached(comm, key, builder)
        if _trace.enabled():
            with _trace.span("coll.xla.dispatch", cat="coll",
                             verb=str(key[0])):
                return fn(*args)
        return fn(*args)

    def _wrap(self, comm, body, n_in: int = 1, rooted: bool = False):
        import jax
        from jax.sharding import PartitionSpec as P

        specs = tuple([P(comm.axis)] * n_in + ([P()] if rooted else []))
        f = _shard_map(body, comm.mesh, specs, P(comm.axis))
        return jax.jit(f)

    @staticmethod
    def _masks(comm):
        """(pos_map, singleton_mask) as jnp constants for traced lookups."""
        import jax.numpy as jnp

        return jnp.asarray(comm.pos_map), jnp.asarray(comm.singleton_mask)

    @staticmethod
    def _group_sizes(comm):
        """Per-mesh-position group size as a jnp constant."""
        import jax.numpy as jnp
        import numpy as np

        gs = np.ones(comm.world_size, dtype=np.int32)
        if comm.groups is not None:
            for g in comm.groups:
                for r in g:
                    gs[r] = len(g)
        else:
            gs[:] = comm.world_size
        return jnp.asarray(gs)

    # ------------------------------------------- grouped allreduce schedule
    def _grouped_allreduce_body(self, comm, op: _op.Op):
        """Build body(block)->block implementing in-group allreduce via
        ppermute rounds. Uniform power-of-two colors take recursive
        doubling; everything else (including NON-UNIFORM color sizes —
        the reference supports arbitrary Splits, comm.c) takes a masked
        ring: rounds = max group size - 1, and each rank stops
        accumulating after its own group's size-1 rounds while values
        keep rotating harmlessly around the smaller rings."""
        import jax.numpy as jnp
        from jax import lax

        groups = comm.groups
        axis = comm.axis
        pos_map, single = self._masks(comm)
        sizes = {len(g) for g in groups if len(g) > 1}
        max_g = max(sizes) if sizes else 1
        uniform = len(sizes) <= 1

        pow2 = uniform and max_g >= 2 and (max_g & (max_g - 1)) == 0
        if pow2:
            perms = [_xor_perm(groups, 1 << k)
                     for k in range(int(math.log2(max_g)))]
        else:
            perms = [_shift_perm(groups, 1)] * max(max_g - 1, 0)
        gsize = self._group_sizes(comm)

        def body(b_in):
            idx = lax.axis_index(axis)
            b = (b_in != 0).astype(jnp.int32) if op.logical else b_in
            acc = b
            if pow2:
                # reference: coll_base_allreduce.c:134 recursive doubling
                for perm in perms:
                    other = lax.ppermute(acc, axis, perm)
                    acc = op.jax_reduce(acc, other)
            else:
                # reference: coll_base_allreduce.c:345 ring, with a
                # per-rank round mask for non-uniform group sizes
                cur = b
                for d, perm in enumerate(perms):
                    cur = lax.ppermute(cur, axis, perm)
                    nxt = op.jax_reduce(acc, cur)
                    acc = jnp.where(d < gsize[idx] - 1, nxt, acc)
            out = jnp.where(single[idx], b, acc.astype(b.dtype))
            return out.astype(b_in.dtype)

        return body

    # ---------------------------------------------------------- collectives
    def _allreduce_body(self, comm, op: _op.Op):
        """Build the plain body(block)->block for allreduce — shared by
        the standard path below and the quantized wrapper
        (quant_allreduce_body), which falls back to it at trace time for
        ineligible dtypes/sizes."""
        import jax.numpy as jnp
        from jax import lax

        axis = comm.axis
        if comm.groups is not None:
            return self._grouped_allreduce_body(comm, op)
        kind = op.jax_kind

        def body(b):
            # logical ops reduce truthiness, not values; bools ride
            # the int path because XLA AllReduce wants arithmetic
            if op.logical:
                v = (b != 0).astype(jnp.int32)
            elif _is_bool(b.dtype):
                v = b.astype(jnp.int32)
            else:
                v = b
            if kind == "psum":
                r = lax.psum(v, axis)
            elif kind == "pmax":
                r = lax.pmax(v, axis)
            elif kind == "pmin":
                r = lax.pmin(v, axis)
            else:
                g = lax.all_gather(v[0], axis)  # [W, ...]
                acc = g[0]
                for i in range(1, g.shape[0]):
                    acc = op.jax_reduce(acc, g[i])
                return acc[None].astype(b.dtype)
            return r.astype(b.dtype)

        return body

    def allreduce(self, comm, x, op: _op.Op = _op.SUM):
        _check_device_op(op, x)
        key = cache_key("allreduce", op)

        def build():
            return self._wrap(comm, self._allreduce_body(comm, op))

        return self._dispatch(comm, key, build, x)

    def reduce(self, comm, x, op: _op.Op = _op.SUM, root: int = 0):
        """MPI only defines the root row; we return the reduction on every
        group row (a legal strengthening — free on a mesh, where Reduce and
        Allreduce cost the same under XLA's schedules)."""
        return self.allreduce(comm, x, op)

    def bcast(self, comm, x, root: int = 0):
        import jax.numpy as jnp
        from jax import lax

        key = cache_key("bcast")

        def build():
            axis = comm.axis
            pos_map, single = self._masks(comm)

            def body(b, r):
                # mask non-root contributions, then sum — one AllReduce
                # (or grouped schedule); works for every castable dtype.
                idx = lax.axis_index(axis)
                pos = pos_map[idx]
                v = b.astype(jnp.int32) if _is_bool(b.dtype) else b
                contrib = jnp.where(pos == r, v, jnp.zeros_like(v))
                if comm.groups is None:
                    out = lax.psum(contrib, axis)
                else:
                    out = self._grouped_allreduce_body(comm, _op.SUM)(contrib)
                out = jnp.where(single[idx], v, out)
                return out.astype(b.dtype)

            return self._wrap(comm, body, rooted=True)

        return self._dispatch(comm, key, build, x, jnp.int32(root))

    def allgather(self, comm, x):
        """[W, ...] -> [W, G, ...]: each rank-row becomes its group's
        stacked contributions (MPI_Allgather, stacked layout)."""
        import jax.numpy as jnp
        from jax import lax

        key = cache_key("allgather")

        def build():
            axis = comm.axis
            G = comm.size
            pos_map, single = self._masks(comm)

            if comm.groups is None:

                def body(b):
                    return lax.all_gather(b[0], axis)[None]

            else:
                perms = [_shift_perm(comm.groups, 1)] * max(G - 1, 0)

                def body(b):
                    # ring allgather (reference: coll_base_allgather.c ring)
                    idx = lax.axis_index(axis)
                    pos = pos_map[idx]
                    out = jnp.zeros((1, G) + b.shape[1:], b.dtype)
                    out = lax.dynamic_update_index_in_dim(
                        out, b, pos, axis=1)
                    cur = b
                    for d, perm in enumerate(perms, start=1):
                        cur = lax.ppermute(cur, axis, perm)
                        out = lax.dynamic_update_index_in_dim(
                            out, cur, (pos - d) % G, axis=1)
                    return out

            return self._wrap(comm, body)

        return self._dispatch(comm, key, build, x)

    def alltoall(self, comm, x):
        """[W, G, ...] -> [W, G, ...]: chunk j of group-rank i goes to
        chunk i of group-rank j (MPI_Alltoall)."""
        import jax.numpy as jnp
        from jax import lax

        G = comm.size
        if x.ndim < 2 or x.shape[1] != G:
            raise MPIError(
                ERR_ARG,
                f"alltoall expects [world, group_size={G}, ...], got "
                f"{tuple(x.shape)}",
            )
        key = cache_key("alltoall")

        def build():
            axis = comm.axis
            pos_map, single = self._masks(comm)

            if comm.groups is None:

                def body(b):
                    r = lax.all_to_all(b[0], axis, split_axis=0,
                                       concat_axis=0, tiled=False)
                    return r[None]

            else:

                def body(b):
                    # one ppermute per ring offset (reference:
                    # coll_base_alltoall.c pairwise exchange)
                    idx = lax.axis_index(axis)
                    pos = pos_map[idx]
                    chunks = b[0]  # [G, ...]
                    out = jnp.zeros_like(chunks)
                    out = lax.dynamic_update_index_in_dim(
                        out, chunks[pos], pos, axis=0)
                    for d in range(1, G):
                        perm = _shift_perm(comm.groups, d)
                        send = lax.dynamic_index_in_dim(
                            chunks, (pos + d) % G, axis=0, keepdims=False)
                        recv = lax.ppermute(send, axis, perm)
                        out = lax.dynamic_update_index_in_dim(
                            out, recv, (pos - d) % G, axis=0)
                    return out[None]

            return self._wrap(comm, body)

        return self._dispatch(comm, key, build, x)

    def reduce_scatter_block(self, comm, x, op: _op.Op = _op.SUM):
        """[W, G, ...] -> [W, ...]: reduce across the group elementwise,
        rank p keeps chunk p (MPI_Reduce_scatter_block)."""
        import jax.numpy as jnp
        from jax import lax

        G = comm.size
        if x.ndim < 2 or x.shape[1] != G:
            raise MPIError(
                ERR_ARG,
                f"reduce_scatter expects [world, group_size={G}, ...], got "
                f"{tuple(x.shape)}",
            )
        _check_device_op(op, x)
        key = cache_key("reduce_scatter_block", op)

        def build():
            axis = comm.axis
            pos_map, single = self._masks(comm)

            if comm.groups is None and op.jax_kind == "psum":

                def body(b):
                    r = lax.psum_scatter(b[0], axis, scatter_dimension=0,
                                         tiled=False)
                    return r[None]

            elif comm.groups is None:

                def body(b):
                    g = lax.all_gather(b[0], axis)  # [W, G, ...]
                    acc = g[0]
                    for i in range(1, g.shape[0]):
                        acc = op.jax_reduce(acc, g[i])
                    idx = lax.axis_index(axis)
                    return acc[pos_map[idx]][None]

            else:
                red_body = self._grouped_allreduce_body(comm, op)

                def body(b):
                    red = red_body(b)  # [1, G, ...] group-reduced
                    idx = lax.axis_index(axis)
                    return lax.dynamic_index_in_dim(
                        red[0], pos_map[idx], axis=0, keepdims=False)[None]

            return self._wrap(comm, body)

        return self._dispatch(comm, key, build, x)

    def scan(self, comm, x, op: _op.Op = _op.SUM, exclusive: bool = False):
        """Prefix reduction across group ranks via Hillis–Steele doubling
        (log G masked ppermute rounds — reference analog: the linear
        MPI_Scan over PML sends, coll_base_scan.c, upgraded to a parallel
        scan schedule)."""
        import jax.numpy as jnp
        from jax import lax

        _check_device_op(op, x)
        key = cache_key("scan", op, (exclusive,))

        def build():
            axis = comm.axis
            pos_map, single = self._masks(comm)
            groups = comm.groups
            if groups is None:
                groups = (tuple(range(comm.world_size)),)
            # rounds sized by the LARGEST group; the pos >= d mask is
            # group-local, so non-uniform colors just idle early
            max_g = max((len(g) for g in groups), default=1)
            rounds = max(int(math.ceil(math.log2(max(max_g, 1)))), 0)

            def body(b):
                idx = lax.axis_index(axis)
                pos = pos_map[idx]
                acc = b
                for k in range(rounds):
                    d = 1 << k
                    perm = _shift_perm(groups, d)
                    sh = lax.ppermute(acc, axis, perm)
                    # ring shift wraps; mask wrapped contributions
                    acc = jnp.where(pos >= d, op.jax_reduce(sh, acc), acc)
                if exclusive:
                    perm1 = _shift_perm(groups, 1)
                    sh = lax.ppermute(acc, axis, perm1)
                    acc = jnp.where(pos == 0, jnp.zeros_like(b), sh)
                return jnp.where(single[idx], b, acc).astype(b.dtype)

            return self._wrap(comm, body)

        return self._dispatch(comm, key, build, x)

    def exscan(self, comm, x, op: _op.Op = _op.SUM):
        return self.scan(comm, x, op, exclusive=True)

    def barrier(self, comm) -> None:
        """Whole-mesh sync: tiny psum, block until ready."""
        import jax.numpy as jnp
        from jax import lax

        key = cache_key("barrier")

        def build():
            def body(b):
                return lax.psum(b, comm.axis)

            return self._wrap(comm, body)

        x = comm.shard(jnp.ones((comm.world_size, 1), dtype=jnp.int32))
        self._dispatch(comm, key, build, x).block_until_ready()

    # --------------------------------------------- layout ("root") movers
    def gather(self, comm, x, root: int = 0):
        """[W, ...] -> [W, G, ...]: the root's row holds its group's
        stacked contributions. MPI defines only the root row; returning
        the gather on every row is the same legal strengthening as
        reduce->allreduce (free on a mesh under XLA's schedules)."""
        return self.allgather(comm, x)

    def scatter(self, comm, x, root: int = 0):
        """[W, G, ...] -> [W, ...]: group rank p receives ROOT's chunk p
        (real MPI_Scatter semantics — the r1 reshard stub ignored the
        root's data)."""
        import jax.numpy as jnp
        from jax import lax

        G = comm.size
        if x.ndim < 2 or x.shape[1] != G:
            raise MPIError(
                ERR_ARG,
                f"scatter expects [world, group_size={G}, ...], got "
                f"{tuple(x.shape)}")
        key = cache_key("scatter")

        def build():
            axis = comm.axis
            pos_map, single = self._masks(comm)

            def body(b, r):
                idx = lax.axis_index(axis)
                pos = pos_map[idx]
                chunks = b[0]  # [G, ...]
                v = chunks.astype(jnp.int32) if _is_bool(chunks.dtype) \
                    else chunks
                contrib = jnp.where(pos == r, v, jnp.zeros_like(v))
                if comm.groups is None:
                    full = lax.psum(contrib, axis)
                else:
                    full = self._grouped_allreduce_body(comm, _op.SUM)(
                        contrib[None])[0]
                out = lax.dynamic_index_in_dim(full, pos, axis=0,
                                               keepdims=False)
                own = lax.dynamic_index_in_dim(v, pos, axis=0,
                                               keepdims=False)
                return jnp.where(single[idx], own,
                                 out).astype(chunks.dtype)[None]

            return self._wrap(comm, body, rooted=True)

        return self._dispatch(comm, key, build, x, jnp.int32(root))

    # ---------------------------------------------- neighborhood collectives
    # Reference: the coll.h neighbor_* slots. On a mesh, a cart topology's
    # neighbor exchange is exactly what the ICI torus is wired for: one
    # collective-permute per direction, wraparound links for periodic dims,
    # zero-fill standing in for MPI_PROC_NULL's undefined blocks.
    def _cart_in_perms(self, comm):
        """Per neighbor slot k: ppermute pairs (src -> me) for every rank
        whose k-th in-neighbor exists."""
        from ompi_tpu.topo import CartTopo

        t = comm.topo
        if not isinstance(t, CartTopo) or comm.groups is not None:
            raise MPIError(
                ERR_UNSUPPORTED_OPERATION,
                "mesh neighbor collectives need a cartesian topology over "
                "the whole mesh axis (graph topologies ride the host path)")
        nbrs = [t.neighbors(me) for me in range(comm.world_size)]
        perms = []
        for k in range(2 * t.ndims):
            pairs = [(nbrs[me][k], me) for me in range(comm.world_size)
                     if nbrs[me][k] >= 0]
            perms.append(tuple(pairs))
        return perms

    def neighbor_allgather(self, comm, x):
        """[W, ...] -> [W, K, ...]: slot k carries the k-th neighbor's row
        (cart order: per dim, negative then positive peer)."""
        import jax.numpy as jnp
        from jax import lax

        perms = self._cart_in_perms(comm)
        key = cache_key("neighbor_allgather")

        def build():
            axis = comm.axis

            def body(b):
                outs = [lax.ppermute(b[0], axis, p) for p in perms]
                return jnp.stack(outs, axis=0)[None]

            return self._wrap(comm, body)

        return self._dispatch(comm, key, build, x)

    def neighbor_alltoall(self, comm, x):
        """[W, K, ...] -> [W, K, ...]: block k goes to neighbor k; recv
        block k arrives from neighbor k (who sent its opposite-direction
        block along the same edge)."""
        import jax.numpy as jnp
        from jax import lax

        perms = self._cart_in_perms(comm)
        K = len(perms)
        if x.ndim < 2 or x.shape[1] != K:
            raise MPIError(
                ERR_ARG,
                f"neighbor_alltoall expects [world, {K}, ...], got "
                f"{tuple(x.shape)}")
        key = cache_key("neighbor_alltoall")

        def build():
            axis = comm.axis

            def body(b):
                blocks = b[0]  # [K, ...]
                outs = []
                for k in range(K):
                    d, parity = divmod(k, 2)
                    opp = 2 * d + (1 - parity)
                    outs.append(lax.ppermute(blocks[opp], axis, perms[k]))
                return jnp.stack(outs, axis=0)[None]

            return self._wrap(comm, body)

        return self._dispatch(comm, key, build, x)

    # ------------------------------------------------------------- pt2pt
    def permute(self, comm, x, perm: Tuple[Tuple[int, int], ...]):
        """Collective permute along GLOBAL mesh ranks — the mesh-native
        tag-free pt2pt (SURVEY.md §5: ppermute chains replace PML
        round-trips)."""
        from jax import lax

        key = cache_key("permute", extra=(tuple(perm),))

        def build():
            axis = comm.axis

            def body(b):
                return lax.ppermute(b, axis, perm)

            return self._wrap(comm, body)

        return self._dispatch(comm, key, build, x)


# ------------------------------------------------- quantized allreduce
def quant_allreduce_body(comm, plain_body, op: _op.Op, mode: str,
                         block: int, min_bytes: int):
    """Block-scaled quantized allreduce as ONE traced XLA program
    (EQuARX direction, arxiv 2506.17615): quantize per-destination
    chunks -> all_to_all int8/fp8 values + f32 block scales ->
    dequantize + reduce -> requantize -> all_gather -> dequantize.
    Wire bytes (ICI traffic) drop ~4x at int8 with block=64 while the
    compiled path stays a single executable.

    Eligibility is decided at TRACE time (shape/dtype are concrete), so
    one cache entry per (comm, op) serves every dtype: non-float
    payloads, non-psum ops, grouped comms, and messages under
    ``min_bytes`` fall through to ``plain_body`` with zero runtime
    branching. The chunk layout matches quant/codec.py's
    ``chunk_layout`` exactly, so the closed-form ``error_bound``
    contract holds for the mesh path too."""
    import jax.numpy as jnp
    from jax import lax

    from ompi_tpu.quant.codec import chunk_layout

    axis = comm.axis
    W = comm.world_size

    if mode == "fp8":
        qdtype = jnp.float8_e4m3fn
        target = 224.0  # amax -> 224 keeps rounded values < 448 (normal)
    else:
        qdtype = jnp.int8
        target = 127.0

    # numpy, NOT jnp: build() may run inside an outer jit trace (first
    # call under jax.jit/scan), where every jnp op stages into that
    # trace — a jnp constant here would be a tracer closed over by the
    # cached body, poisoning the cache for every later call
    inf = np.float32(np.inf)

    def _quantize(blocks):  # [..., nb, block] f32
        # non-finite blocks ride the codec.py sentinel scheme: the
        # block's scale is +inf and the lanes carry {+inf,-inf,nan}
        # code points (finite neighbors decode to 0, legal because the
        # error bound there is infinite) — without this, scale=inf
        # would NaN the whole block instead of propagating ±inf/nan in
        # place the way the plain psum path and the procmode codec do
        amax = jnp.max(jnp.abs(blocks), axis=-1)
        finite = jnp.isfinite(amax)
        scale = jnp.where(finite & (amax > 0), amax / target, 1.0)
        t = blocks / scale[..., None]
        t = jnp.where(jnp.isfinite(t), t, 0.0)  # int-cast of inf is UB
        if mode == "fp8":
            q = t.astype(qdtype)  # IEEE round-to-nearest-even cast
            code = jnp.where(
                blocks == inf, 448.0,
                jnp.where(blocks == -inf, -448.0,
                          jnp.where(jnp.isnan(blocks), jnp.nan,
                                    0.0))).astype(qdtype)
        else:
            q = jnp.clip(jnp.round(t), -127, 127).astype(qdtype)
            code = jnp.where(
                blocks == inf, 127,
                jnp.where(blocks == -inf, -127,
                          jnp.where(jnp.isnan(blocks), -128,
                                    0))).astype(qdtype)
        q = jnp.where(finite[..., None], q, code)
        return q, jnp.where(finite, scale, inf)

    def _dequantize(q, scale):
        fin = jnp.isfinite(scale)
        qf = q.astype(jnp.float32)
        v = qf * jnp.where(fin, scale, 1.0)[..., None]
        if mode == "fp8":
            sent = jnp.where(qf >= 448.0, inf,
                             jnp.where(qf <= -448.0, -inf,
                                       jnp.where(jnp.isnan(qf), jnp.nan,
                                                 0.0)))
        else:
            sent = jnp.where(q == 127, inf,
                             jnp.where(q == -127, -inf,
                                       jnp.where(q == -128, jnp.nan,
                                                 0.0)))
        return jnp.where(fin[..., None], v, sent)

    def body(b):
        x = b[0]
        if (W < 2 or comm.groups is not None or op.jax_kind != "psum"
                or not jnp.issubdtype(b.dtype, jnp.floating)
                or x.size * b.dtype.itemsize < min_bytes):
            return plain_body(b)
        flat = x.reshape(-1).astype(jnp.float32)
        n = flat.size
        per, padded = chunk_layout(n, W, block)
        nb = per // block
        f = jnp.zeros((padded,), jnp.float32).at[:n].set(flat)
        q, s = _quantize(f.reshape(W, nb, block))
        # reduce-scatter phase: chunk j (quantized) to rank j
        q2 = lax.all_to_all(q.reshape(W, per), axis, split_axis=0,
                            concat_axis=0, tiled=False)
        s2 = lax.all_to_all(s, axis, split_axis=0, concat_axis=0,
                            tiled=False)
        red = jnp.sum(_dequantize(q2.reshape(W, nb, block), s2), axis=0)
        # requantize the reduced chunk, allgather, dequantize
        qr, sr = _quantize(red)
        qg = lax.all_gather(qr.reshape(per), axis)       # [W, per]
        sg = lax.all_gather(sr, axis)                    # [W, nb]
        out = _dequantize(qg.reshape(padded // block, block),
                          sg.reshape(-1))
        return out.reshape(-1)[:n].reshape(x.shape).astype(b.dtype)[None]

    return body


class XlaCollComponent(Component):
    NAME = "xla"
    PRIORITY = 100  # beats every host algorithm on mesh comms

    _module: Optional[XlaColl] = None

    def query(self, comm=None, **ctx):
        from ompi_tpu.parallel.mesh import XlaComm

        if isinstance(comm, XlaComm):
            if XlaCollComponent._module is None:
                XlaCollComponent._module = XlaColl()
            return XlaCollComponent._module
        return None


coll_framework.register(XlaCollComponent())
