"""coll/tuned — decision layer choosing host algorithms by message size,
communicator size, and op properties.

Reference: ompi/mca/coll/tuned (6,890 LoC) — fixed heuristics per
op/size/commsize (coll_tuned_decision_fixed.c:55 for allreduce) plus
per-op forced-algorithm MCA vars. Same shape here: thresholds and forced
choices are MCA vars; the algorithms live in coll/algorithms.py and run
through the schedule engine. Slots not decided here fall through to
coll/basic (priority ordering in the per-comm table does that).

Decision rules (mirroring the reference's fixed rules, simplified):
- allreduce: non-commutative -> linear reduce+bcast (basic); small
  messages -> recursive doubling; large -> ring; very large -> segmented
  ring (pipelined).
- allgather: small -> bruck (latency-optimal); large -> ring (bw-optimal).
- reduce: commutative -> binomial; else linear.
- bcast: binomial (already the basic algorithm; kept for the forced var).
"""

from __future__ import annotations

from typing import Optional

from ompi_tpu.coll.base import CollModule, coll_framework
from ompi_tpu.coll.basic import BasicColl, COLL_CID_BIT
from ompi_tpu.coll import algorithms as alg
from ompi_tpu.coll.sched import run_blocking
from ompi_tpu.comm.communicator import parse_buffer
from ompi_tpu.core import op as _op
from ompi_tpu.mca.component import Component
from ompi_tpu.mca.var import register_var, get_var

register_var("coll_tuned", "allreduce_algorithm", "auto",
             help="Forced allreduce algorithm: auto|linear|"
                  "recursive_doubling|ring|ring_segmented", level=5,
             enum_values=("auto", "linear", "recursive_doubling", "ring",
                          "ring_segmented"))
register_var("coll_tuned", "allgather_algorithm", "auto",
             help="Forced allgather algorithm: auto|ring|bruck", level=5,
             enum_values=("auto", "ring", "bruck"))
register_var("coll_tuned", "allreduce_small_msg", 8192,
             help="Bytes below which allreduce uses recursive doubling",
             level=6)
register_var("coll_tuned", "allreduce_segsize", 1 << 20,
             help="Segment size for the pipelined segmented-ring allreduce",
             level=6)
register_var("coll_tuned", "allgather_small_msg", 65536,
             help="Total bytes below which allgather uses bruck", level=6)
register_var("coll_tuned", "alltoall_algorithm", "auto",
             help="Forced alltoall algorithm: auto|pairwise|basic — "
                  "pairwise runs the round engine's windowed pairwise "
                  "exchange (coll_round_window rounds in flight); basic "
                  "keeps the linear sendrecv fallback", level=5,
             enum_values=("auto", "pairwise", "basic"))
register_var("coll_tuned", "use_dynamic_rules", False,
             help="Consult the dynamic rules file before the fixed "
                  "heuristics (reference: coll_tuned_use_dynamic_rules)",
             level=6)
register_var("coll_tuned", "dynamic_rules_filename", "",
             help="Rules file: lines of '<coll> <comm_size_min> "
                  "<msg_bytes_min> <algorithm> [key=value ...]'; the "
                  "most specific matching rule wins, and params like "
                  "segsize=N tune the chosen algorithm (reference: "
                  "coll_tuned_dynamic_rules_filename's per-rule "
                  "fanout/segsize columns)", level=6)

TAG_TUNED = -30  # dedicated tag inside the collective CID plane


def _run(comm, gen) -> None:
    run_blocking(comm, gen, TAG_TUNED, comm.cid | COLL_CID_BIT)


def _msg_bytes(buf) -> int:
    obj, count, dt = parse_buffer(buf)
    return count * dt.size


# --------------------------------------------------- dynamic rule files
_KNOWN_ALGOS = {
    "allreduce": ("linear", "recursive_doubling", "ring",
                  "ring_segmented"),
    "allgather": ("ring", "bruck"),
    "alltoall": ("pairwise", "basic"),
    "reduce": ("linear", "binomial"),
}
_rules_cache = {"path": None, "mtime": None, "rules": []}


# per-rule tunables, scoped to the algorithms that consume them
# (reference: the fanout/segsize columns of the tuned dynamic-file
# format) — a param on an algorithm that ignores it is a silent
# misconfiguration, so the parser rejects it loudly
_ALGO_PARAMS = {
    ("allreduce", "ring_segmented"): ("segsize",),
}


def _load_rules(path: str):
    """[(coll, comm_size_min, msg_bytes_min, algo, params)] from the
    rules file (parsed once per mtime; bad lines are skipped with a
    warning — reference: ompi_coll_tuned_read_rules_config_file)."""
    import os

    from ompi_tpu.utils.output import get_logger

    try:
        mtime = os.path.getmtime(path)
    except OSError:
        return []
    if _rules_cache["path"] == path and _rules_cache["mtime"] == mtime:
        return _rules_cache["rules"]
    rules = []
    log = get_logger("coll.tuned")
    try:
        with open(path) as f:
            for ln, line in enumerate(f, 1):
                line = line.split("#", 1)[0].strip()
                if not line:
                    continue
                parts = line.split()
                if len(parts) < 4:
                    log.warning("rules %s:%d: want >=4 fields, got %r",
                                path, ln, line)
                    continue
                coll, cs, ms, algo = parts[:4]
                if algo not in _KNOWN_ALGOS.get(coll, ()):
                    log.warning("rules %s:%d: unknown %s algorithm %r",
                                path, ln, coll, algo)
                    continue
                params = {}
                ok = True
                allowed = _ALGO_PARAMS.get((coll, algo), ())
                for tok in parts[4:]:
                    k, _, v = tok.partition("=")
                    if k not in allowed:
                        log.warning("rules %s:%d: param %r does not "
                                    "apply to %s/%s (allowed: %s)",
                                    path, ln, tok, coll, algo,
                                    ", ".join(allowed) or "none")
                        ok = False
                        break
                    try:
                        params[k] = int(v)
                    except ValueError:
                        log.warning("rules %s:%d: non-integer param %r",
                                    path, ln, tok)
                        ok = False
                        break
                if not ok:
                    continue
                try:
                    rules.append((coll, int(cs), int(ms), algo, params))
                except ValueError:
                    log.warning("rules %s:%d: non-integer bounds in %r",
                                path, ln, line)
    except OSError as e:
        log.warning("cannot read rules file %s: %s", path, e)
        return []
    _rules_cache.update(path=path, mtime=mtime, rules=rules)
    return rules


def dynamic_choice(coll: str, comm_size: int, nbytes: int):
    """(algorithm, params) the dynamic rules select, or None (fall
    through to the fixed heuristics). Most specific match wins: largest
    (comm_size_min, msg_bytes_min) pair that is <= the actual values."""
    if not get_var("coll_tuned", "use_dynamic_rules"):
        return None
    path = get_var("coll_tuned", "dynamic_rules_filename")
    if not path:
        return None
    best = None
    best_key = (-1, -1)
    for c, cs, ms, algo, params in _load_rules(path):
        if c == coll and cs <= comm_size and ms <= nbytes and \
                (cs, ms) > best_key:
            best, best_key = (algo, params), (cs, ms)
    return best


class TunedColl(CollModule):
    """Decision slots; inherits nothing — undecided ops fall through to the
    lower-priority basic module via per-slot table selection."""

    # ------------------------------------------------------------ allreduce
    def allreduce(self, comm, sendbuf, recvbuf, op: _op.Op) -> None:
        choice = get_var("coll_tuned", "allreduce_algorithm")
        nbytes = _msg_bytes(recvbuf)
        params = {}
        if choice == "auto":
            dyn = dynamic_choice("allreduce", comm.size, nbytes)
            if dyn is not None and (op.commutative or dyn[0] == "linear"):
                choice, params = dyn
        if choice == "auto":
            if not op.commutative or comm.size == 1:
                choice = "linear"
            elif nbytes <= get_var("coll_tuned", "allreduce_small_msg"):
                choice = "recursive_doubling"
            elif nbytes <= 4 * get_var("coll_tuned", "allreduce_segsize"):
                choice = "ring"
            else:
                choice = "ring_segmented"
        if choice == "linear" or (comm.size == 1):
            self._basic().allreduce(comm, sendbuf, recvbuf, op)
        elif choice == "recursive_doubling":
            _run(comm, alg.allreduce_recursive_doubling(
                comm, sendbuf, recvbuf, op))
        elif choice == "ring":
            _run(comm, alg.allreduce_ring(comm, sendbuf, recvbuf, op))
        else:
            # per-rule segsize overrides the global var (reference: the
            # dynamic file's per-entry segsize column)
            seg = max(1, params.get(
                "segsize", get_var("coll_tuned", "allreduce_segsize")))
            nseg = max(1, -(-nbytes // seg))
            _run(comm, alg.allreduce_ring(comm, sendbuf, recvbuf, op,
                                          nseg=nseg))

    # ------------------------------------------------------------ allgather
    def allgather(self, comm, sendbuf, recvbuf) -> None:
        choice = get_var("coll_tuned", "allgather_algorithm")
        if choice == "auto":
            total = _msg_bytes(recvbuf)
            dyn = dynamic_choice("allgather", comm.size, total)
            if dyn is not None:
                choice = dyn[0]
        if choice == "auto":
            total = _msg_bytes(recvbuf)
            choice = ("bruck"
                      if total <= get_var("coll_tuned", "allgather_small_msg")
                      else "ring")
        if comm.size == 1 or choice == "ring":
            _run(comm, alg.allgather_ring(comm, sendbuf, recvbuf))
        else:
            _run(comm, alg.allgather_bruck(comm, sendbuf, recvbuf))

    # ------------------------------------------------------------- alltoall
    def alltoall(self, comm, sendbuf, recvbuf) -> None:
        """Pairwise exchange on the round engine: with contiguous
        buffers the rounds are independent (ordered=False), so up to
        coll_round_window exchanges overlap instead of the basic
        module's lockstep sendrecv chain. Note the window is the only
        pipelining knob here — the segmented-ring nseg/segsize pair
        does not apply to alltoall (rings are data-dependent chains and
        stay ordered regardless of the window)."""
        choice = get_var("coll_tuned", "alltoall_algorithm")
        if choice == "auto" and get_var("coll_tuned", "use_dynamic_rules"):
            # gate BEFORE sizing (the reduce-slot lesson): _msg_bytes
            # stages device buffers to host, a cost the default
            # (rules-off) path must not pay
            dyn = dynamic_choice("alltoall", comm.size,
                                 _msg_bytes(recvbuf))
            if dyn is not None:
                choice = dyn[0]
        if comm.size == 1 or choice == "basic":
            self._basic().alltoall(comm, sendbuf, recvbuf)
        else:
            _run(comm, alg.alltoall_pairwise(comm, sendbuf, recvbuf))

    # --------------------------------------------------------------- reduce
    def reduce(self, comm, sendbuf, recvbuf, op: _op.Op, root: int) -> None:
        choice = None
        if get_var("coll_tuned", "use_dynamic_rules"):
            # gate BEFORE sizing: _msg_bytes stages device buffers to
            # host, a cost the default (rules-off) path must not pay
            dyn = dynamic_choice("reduce", comm.size,
                                 _msg_bytes(sendbuf if sendbuf is not None
                                            else recvbuf))
            if dyn is not None and (op.commutative or dyn[0] == "linear"):
                choice = dyn[0]
        if choice is None:
            choice = ("binomial" if op.commutative and comm.size > 2
                      else "linear")
        if choice == "binomial":
            _run(comm, alg.reduce_binomial(comm, sendbuf, recvbuf, op, root))
        else:
            _run(comm, alg.reduce_linear(comm, sendbuf, recvbuf, op, root))

    # ------------------------------------------------------------- internals
    _basic_mod: Optional[BasicColl] = None

    @classmethod
    def _basic(cls) -> BasicColl:
        if cls._basic_mod is None:
            cls._basic_mod = BasicColl()
        return cls._basic_mod


class TunedCollComponent(Component):
    NAME = "tuned"
    PRIORITY = 30  # above basic(10), below self(~) — reference: tuned=30

    _module: Optional[TunedColl] = None

    def query(self, comm=None, **ctx):
        from ompi_tpu.comm.communicator import ProcComm

        if isinstance(comm, ProcComm) and comm.size > 1:
            if TunedCollComponent._module is None:
                TunedCollComponent._module = TunedColl()
            return TunedCollComponent._module
        return None


coll_framework.register(TunedCollComponent())
