"""Decision engine: static tables + self-tuning plan selection.

Starts from tuned-style static tables (topology must be nontrivial, the
payload at/above ``coll_hier_min_bytes``, the op commutative) and then
**self-tunes** from observed latency: every composed call's per-stage
wall times ship to the communicator root over a dedicated system-tag
plane (tag -4700; the metrics -4500 idiom), where they fold into the
metrics registry's EWMAs (``hier_plan_us`` per active plan,
``hier_stage_us`` per stage). When the active plan's EWMA degrades past
``coll_hier_retune_factor`` x its own post-warmup baseline, the root
latches a pending switch — ONCE per episode, with hysteresis exactly
like the straggler tracker, so selection can't flap per call.

The switch is applied on an AGREED collective index: every
``coll_hier_rescore_interval``-th call on a (cid, verb), all members
run a tiny suppressed bcast of the root's verdict (flat path — it must
not recurse into the composition being re-scored) and apply it before
executing. Call indices are per-(cid, verb) and collectives are
matched, so every member switches plans on the SAME call — never a
torn composition where half the comm composes and half runs flat. Each
applied switch pops the verb's frozen plan (coll/hier/plan.py) on every
member, bumps the ``hier_retunes`` pvar, and fires a show_help + trace
instant on the root.

A deterministic stage-delay injection hook (``coll_hier_inject_*``)
lets the chaos tests degrade exactly one stage after exactly N calls —
the procmode proof that the re-score trips once and lands everywhere on
the same index.
"""

from __future__ import annotations

# plane member (hier/__init__ owns the note_* hooks): mpilint
# module-scan marker for the derived INSTR_IMPL set
MPILINT_INSTR_IMPL = True

import json
import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

from ompi_tpu.coll import hier as _hier
from ompi_tpu.mca.var import register_var, get_var
from ompi_tpu.runtime import metrics as _metrics
from ompi_tpu.runtime import trace as _trace
from ompi_tpu.utils.show_help import register_topic, show_help

register_var("coll_hier", "enable", True,
             help="Hierarchical collective composition on multi-node "
                  "communicators (intra-host / intra-slice / cross-host "
                  "stages; HiCCL direction)", level=4)
register_var("coll_hier", "fake_nodes", 0,
             help="Pretend the comm spans N nodes (round-robin by rank) "
                  "— the single-host test hook, like coll_han_fake_nodes "
                  "but scoped to the composer", level=7)
register_var("coll_hier", "fake_slices", 0,
             help="Group the (fake or real) nodes round-robin into N "
                  "slices: exercises the three-level host/slice/cross "
                  "composition on one machine", level=7)
register_var("coll_hier", "min_bytes", 0,
             help="Static table: payloads below this run the flat chain "
                  "(the composed pipeline's extra stage latency only "
                  "pays off once bandwidth dominates)", level=5)
_selftune_var = register_var(
    "coll_hier", "selftune", True,
    help="Self-tune plan selection from observed per-stage latency "
         "EWMAs (root-folded; switches land on an agreed collective "
         "index)", level=4)
register_var("coll_hier", "rescore_interval", 32,
             help="Collective calls per (comm, verb) between plan-sync "
                  "points — the agreed indices where a pending re-score "
                  "is applied by every member together", level=6)
register_var("coll_hier", "retune_factor", 3.0, float,
             help="Re-score trip point: the active plan's latency EWMA "
                  "exceeding factor x its own post-warmup baseline "
                  "latches a switch to the alternative (re-arms below "
                  "half the trip ratio — straggler-style hysteresis)",
             level=6)
register_var("coll_hier", "min_samples", 8,
             help="Root-folded samples per (comm, verb, plan) before "
                  "the baseline latches and re-scoring may trip "
                  "(warmup guard against wireup/compile noise)", level=7)
register_var("coll_hier", "retune_min_us", 500.0, float,
             help="Absolute floor on the EWMA-over-baseline excess "
                  "before a re-score may trip: on microsecond-scale "
                  "baselines a bare ratio test would fire on scheduler "
                  "jitter", level=7)
register_var("coll_hier", "inject_stage", "", typ=str,
             help="TEST HOOK: stage-name prefix (e.g. 'cross') whose "
                  "execution is delayed on every rank running it",
             level=9)
register_var("coll_hier", "inject_delay_ms", 0.0, float,
             help="TEST HOOK: injected per-call delay for "
                  "coll_hier_inject_stage", level=9)
register_var("coll_hier", "inject_after", 0,
             help="TEST HOOK: injection starts after this many calls "
                  "on the (comm, verb)", level=9)


# verdict/report plane: clear of metrics (-4500) and diskless (-4600)
HIER_TAG = -4700

register_topic(
    "hier", "retune",
    "The hierarchical-collective decision engine re-scored a plan:\n"
    "{detail}\nThe switch is applied by every member on the same\n"
    "collective index (coll_hier_rescore_interval boundaries); tune\n"
    "coll_hier_retune_factor / coll_hier_min_samples if this trips on\n"
    "benign load transients.")

_PLAN_CODES = {"hier": 0, "flat": 1}
_PLAN_NAMES = {v: k for k, v in _PLAN_CODES.items()}


class VerbState:
    """Per-(cid, verb) selection state. Every member holds one (idx,
    active plan, switch log, pre-bound stage plans); the root-only
    folding fields drive the re-score."""

    __slots__ = ("cid", "verb", "idx", "active", "switch_log", "bound",
                 # root-only folding state
                 "root_active", "pending", "latched", "nsamp",
                 "baseline", "trips")

    def __init__(self, cid: int, verb: str, active: str):
        self.cid = cid
        self.verb = verb
        self.idx = 0
        self.active = active
        self.switch_log: List[int] = []
        self.bound: Dict[Tuple, object] = {}  # (dtype, count-class) -> StagePlan
        self.root_active = active
        self.pending: Optional[str] = None
        self.latched = False
        self.nsamp: Dict[str, int] = {}
        self.baseline: Dict[str, float] = {}
        self.trips = 0


_states: Dict[Tuple[int, str], VerbState] = {}
# guards the root-side fold/latch state: _fold runs on the transport
# thread for shipped reports AND on the app thread for the root's own
# samples, and sync() consumes st.pending on the app thread — unlocked
# interleavings could lose samples, double-latch, or drop a verdict
# (the metrics-plane tracker keeps the same discipline)
_fold_lock = threading.Lock()


def _clear_bound(_var=None) -> None:
    """cvar-write hook: the pre-bound stage plans froze the decision
    knobs (min_bytes), so a runtime write flushes them alongside the
    frozen dispatch plans."""
    for st in _states.values():
        st.bound.clear()


from ompi_tpu.mca.var import watch_var as _watch_var  # noqa: E402

_watch_var("coll_hier", "min_bytes", _clear_bound)


def state_for(comm, verb: str) -> VerbState:
    key = (comm.cid, verb)
    st = _states.get(key)
    if st is None:
        st = _states[key] = VerbState(comm.cid, verb, "hier")
    return st


def _forget_cid(cid: int) -> None:
    """Reclaim one communicator's selection state (metrics registers
    this as a forget hook, so comm-churny jobs don't leak a VerbState
    per cid ever created; the labeled EWMAs are reclaimed by the
    metrics plane's own cid sweep)."""
    for key in [k for k in _states if k[0] == cid]:
        del _states[key]


_metrics.register_forget_hook(_forget_cid)


def domain_map_for(comm):
    """The comm's locality hierarchy, identical on every member:
    fake-topology cvars first (the single-host test hook), then the
    modex node identity han already derives. None = decline."""
    from ompi_tpu.coll.han import HanCollComponent
    from ompi_tpu.runtime.topology import domain_map

    fake = int(get_var("coll_hier", "fake_nodes"))
    slices = int(get_var("coll_hier", "fake_slices"))
    if fake > 1:
        if fake >= comm.size:
            return None  # no node would hold 2+ ranks
        return domain_map([r % fake for r in range(comm.size)], slices)
    node_of = HanCollComponent._modex_node_map(comm)
    if node_of is None:
        return None
    return domain_map(node_of, slices)


def tuning() -> bool:
    """One live-Var attribute load: is self-tuning observation on?"""
    return _selftune_var._value


def sync_due(idx: int) -> bool:
    if not _selftune_var._value or idx == 0:
        return False
    return idx % max(int(get_var("coll_hier", "rescore_interval")), 1) == 0


def inject_delay_ms(stage: str, call_idx: int) -> float:
    """TEST HOOK — deterministic stage degradation for the chaos
    proof. Zero-cost when unset (one cvar read on the composed path)."""
    pref = get_var("coll_hier", "inject_stage")
    if not pref or not stage.startswith(pref):
        return 0.0
    if call_idx <= int(get_var("coll_hier", "inject_after")):
        return 0.0
    return float(get_var("coll_hier", "inject_delay_ms"))


# ----------------------------------------------------------- report/fold
def report(comm, st: VerbState, plan: str, tot_us: float,
           stages: Dict[str, float]) -> None:
    """Ship one composed call's timings to the comm root (the root
    folds its own synchronously — its sample alone can latch a trip, so
    a delayed stage is caught even if peer reports lag in transit)."""
    pml = getattr(comm, "pml", None)
    if pml is None or comm.size <= 1:
        return
    # the ROOT must bind the -4700 handler too: system frames have no
    # unexpected queue, so an unbound tag silently drops every peer's
    # report and re-scoring would see only the root's own samples
    _plane.ensure(pml)
    root_world = comm.group.world_rank(0)
    if root_world == pml.my_rank:
        _fold(st, plan, tot_us, stages)
        return
    _plane.send(pml, root_world,
                {"k": "hier", "cid": st.cid, "verb": st.verb,
                 "plan": plan, "tot": tot_us, "stages": stages})


def _fold(st: VerbState, plan: str, tot_us: float,
          stages: Dict[str, float]) -> None:
    """Root-side fold of one sample into the metrics-plane EWMAs +
    the latched re-score check."""
    v = _metrics.ewma_update("hier_plan_us", tot_us,
                             cid=st.cid, verb=st.verb, plan=plan)
    for name, us in (stages or {}).items():
        _metrics.ewma_update("hier_stage_us", us,
                             cid=st.cid, verb=st.verb, stage=name)
    tripped = None
    with _fold_lock:
        if plan != st.root_active:
            return  # stale report from before an applied switch
        n = st.nsamp.get(plan, 0) + 1
        st.nsamp[plan] = n
        if n < int(get_var("coll_hier", "min_samples")):
            return
        base = st.baseline.get(plan)
        if base is None:
            # post-warmup baseline: the EWMA has absorbed the worst of
            # the wireup/subcomm-construction noise by now
            st.baseline[plan] = max(v, 1e-3)
            return
        factor = float(get_var("coll_hier", "retune_factor"))
        if v < base:
            # the baseline tracks the plan's observed FLOOR: the first
            # composed call pays subcomm construction, so the EWMA
            # enters warmup high and decays — comparing against a
            # snapshot of that transient would hide real degradations
            # behind it
            st.baseline[plan] = base = max(v, 1e-3)
        if not st.latched and v > factor * base \
                and v - base > float(get_var("coll_hier",
                                             "retune_min_us")):
            st.latched = True
            st.trips += 1
            st.pending = "flat" if plan == "hier" else "hier"
            tripped = (st.pending, base, factor)
        elif st.latched and st.pending is None \
                and v < factor * base / 2.0:
            st.latched = False  # hysteresis re-arm for a later episode
    if tripped is not None:
        to, base, factor = tripped
        worst = max(stages.items(), key=lambda kv: kv[1])[0] \
            if stages else "?"
        detail = (f"  {st.verb} on cid={st.cid}: '{plan}' latency EWMA "
                  f"{v:.0f}us > {factor:g} x baseline {base:.0f}us "
                  f"(slowest stage: {worst}) -> switching to "
                  f"'{to}' at the next sync index")
        show_help("hier", "retune", once=False, detail=detail)
        if _trace.enabled():
            _trace.instant("hier.retune", cat="coll", cid=st.cid,
                           verb=st.verb, ewma_us=v, baseline_us=base)


def _on_system(hdr, payload) -> None:
    """Report dispatch (transport thread: record, never raise)."""
    try:
        msg = json.loads(bytes(payload))
    except ValueError:
        return
    if msg.get("k") != "hier":
        return
    st = _states.get((int(msg["cid"]), str(msg["verb"])))
    if st is None:
        return  # comm already freed: drop the straggling report
    _fold(st, str(msg["plan"]), float(msg["tot"]),
          {str(k): float(v) for k, v in (msg.get("stages") or {}).items()})


from ompi_tpu.pml.base import SystemPlane as _SystemPlane  # noqa: E402

_plane = _SystemPlane(HIER_TAG, _on_system)


def bind_plane(pml) -> None:
    """Wireup hook: bind the -4700 handler before the pre-activation
    fence. The lazy ensure in report() runs when THIS rank finishes a
    composed call — a peer that finished the same collective earlier
    has already shipped its stage report, and an unbound tag drops it
    (re-scoring would then see only a subset of samples). Unconditional:
    an unused handler is one dict slot, and hier selection is a
    per-communicator decision this plane must not depend on."""
    _plane.ensure(pml)


def link_floor_bytes() -> int:
    """Measured composition floor from the fabric telemetry
    (runtime/linkmodel): the worst bandwidth-delay product across this
    rank's links. The stage tables fold it into min_bytes when plans
    bind — the observability half of the egress-bandwidth model
    (ROADMAP item 4): below one wire-RTT of payload the composed
    pipeline's extra cross-link stage latency cannot pay for itself.
    Returns 0 (no floor) when the linkmodel plane is off or has no
    samples yet."""
    from ompi_tpu.runtime import linkmodel as _linkmodel

    if not _linkmodel._enable_var._value:
        return 0
    try:
        return _linkmodel.cross_floor_bytes()
    except Exception:
        return 0  # telemetry must never fail a collective


# ------------------------------------------------------------- plan sync
def sync(comm, st: VerbState, idx: int) -> None:
    """The agreed-index plan agreement: the root publishes its active
    plan; every member applies it BEFORE executing this call. Rides
    the flat bcast with spc suppressed — it must not recurse into the
    composition being re-scored, and it is library-internal traffic."""
    from ompi_tpu.coll.basic import flat_module
    from ompi_tpu.coll.hier import plan as _plan
    from ompi_tpu.runtime import spc

    if comm.rank == 0:
        with _fold_lock:  # a racing transport-thread fold must not
            if st.pending is not None:  # latch between read and clear
                st.root_active = st.pending
                st.pending = None
    payload = np.array([_PLAN_CODES.get(st.root_active, 0)],
                       dtype=np.int64)
    with spc.suppressed():
        flat_module().bcast(comm, payload, 0)
    new = _PLAN_NAMES[int(payload[0])]
    if new != st.active:
        st.active = new
        st.switch_log.append(idx)  # the call index everyone shares
        st.bound.clear()              # stage plans re-bind to the choice
        _hier._retunes[0] += 1
        _plan.invalidate_comm(comm, st.verb)  # frozen-plan re-score seam
