"""coll/hier — hierarchical collective composer with frozen cached plans.

Three modules (HiCCL's layering, arxiv 2408.05962, composed with the
multi-process-per-accelerator split patterns of arxiv 2508.13397):

- :mod:`compose` — decomposes allreduce/bcast/allgather/
  reduce_scatter_block into per-domain stages (intra-host via the
  sm-backed low comm, intra-slice leaders, cross-host leaders over tcp)
  on han's lazily-built leader sub-communicators.
- :mod:`decide` — tuned-style static tables that **self-tune** from the
  metrics plane's observed per-stage latency EWMAs; re-scores are
  latched with hysteresis and applied on an agreed collective index so
  every member switches plans together (never a torn composition).
- :mod:`plan` — the frozen :class:`~ompi_tpu.coll.hier.plan.CollPlan`
  cache behind ``ProcComm._coll``: the steady state of EVERY proc-mode
  collective dispatch (hier-owned or not) is one dict hit + an epoch
  compare + execute.

This package owns the observability hooks (the mpilint-covered
``note_*`` surface) and the ``hier_plan_hits/misses/retunes`` pvars;
keep it import-light — ``comm/communicator.py`` loads it on the verb
dispatch path.
"""

from __future__ import annotations

from ompi_tpu.mca.var import register_pvar

# dispatch-plan counters (bumped inline on the ProcComm._coll fast path
# — a list-slot add, no function call, so the cache hit stays one dict
# hit + execute)
_plan_hits = [0]
_plan_misses = [0]
_retunes = [0]

register_pvar("hier", "plan_hits", lambda: _plan_hits[0],
              help="Frozen-plan cache hits in ProcComm._coll on THIS "
                   "rank (steady-state dispatches: one dict hit + "
                   "execute)")
register_pvar("hier", "plan_misses", lambda: _plan_misses[0],
              help="Frozen-plan cache misses (first dispatch per slot "
                   "plus every epoch invalidation: comm change, "
                   "relevant cvar write, decide.py re-score)")
register_pvar("hier", "retunes", lambda: _retunes[0],
              help="Plan switches applied on THIS rank by the "
                   "self-tuning decision engine (hier <-> flat), "
                   "always on an agreed collective index")


def note_plan_hit() -> None:
    """One frozen-plan cache hit (hot call sites bump the counter
    inline; this hook exists for tools and the lint contract)."""
    _plan_hits[0] += 1


def note_plan_miss() -> None:
    """One frozen-plan rebuild (plan.py calls this on the slow path)."""
    _plan_misses[0] += 1


def note_retune() -> None:
    """One applied plan switch (decide.py sync, on the agreed index)."""
    _retunes[0] += 1


def note_stage(verb: str, stage: str, us: float) -> None:
    """Per-stage latency observation -> the metrics registry histogram
    (``hier_stage_us``). Call sites outside the hier impl modules must
    guard on ``metrics.enabled()`` (the mpilint hot-guard contract)."""
    from ompi_tpu.runtime import metrics as _metrics

    _metrics.observe("hier_stage_us", us, verb=verb, stage=stage)
