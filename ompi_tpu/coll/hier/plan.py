"""Frozen collective dispatch plans — the verb-layer dispatch-tax killer.

BENCH_r05's ``dispatch_tax.verb_sweep`` put the per-verb layer overhead
at 20-50us on top of a ~1.8us stub prologue: every ``ProcComm._coll``
re-did the slot lookup and re-tested the metrics/sanitizer/trace live
Vars, and every enabled instrumentation layer re-built its wrapper per
call. A :class:`CollPlan` freezes all of that at FIRST dispatch: the
resolved module fn, the sanitizer/trace interposition wrappers, and the
metrics entry-stamp binding are composed once into ``plan.fn``, so the
steady state in ``ProcComm._coll`` is one dict hit + an epoch compare +
execute (reference analog: comm->c_coll is resolved once at selection;
this extends the idea through the instrumentation stack).

Correctness of the freeze rests on invalidation — a stale plan would
silently drop instrumentation a user just enabled (or keep paying for
one they disabled):

- **relevant cvar write** — :func:`mca.var.watch_var` callbacks on the
  metrics/sanitizer/trace enables and the ``coll_hier_*`` knobs bump
  the global plan epoch; every live plan misses on its next dispatch
  and rebuilds against the new config.
- **comm epoch bump** — plans live on the communicator
  (``comm._plans``) and die with it (``Free`` clears); revocation is
  checked inside the frozen prologue (one attribute load), so a ULFM
  revoke needs no invalidation round.
- **decide.py re-score** — an applied plan switch pops the affected
  verb's plan on every member at the agreed collective index
  (decide.sync), so the rebuilt plan binds the newly-chosen chain.

The dtype/count-class keying of hier compositions lives one level down:
plan.fn for a hier-owned slot is the composer's dispatcher, which keys
its pre-bound stage chains on (verb, dtype, count-class) in the decide
state (compose._stage_plan) — the comm epoch and verb are this cache's
key components.
"""

from __future__ import annotations

# plane member (hier/__init__ owns the note_* hooks): mpilint
# module-scan marker for the derived INSTR_IMPL set
MPILINT_INSTR_IMPL = True

from typing import Optional

from ompi_tpu.coll import hier as _hier
from ompi_tpu.core.errors import MPIError, ERR_REVOKED
from ompi_tpu.mca.var import watch_var
from ompi_tpu.runtime import spc as _spc

# Global plan epoch: a plan is live iff plan.epoch == _EPOCH[0]. A list
# slot (not an int module global) so the communicator fast path can
# compare against the live value through one stable attribute load.
_EPOCH = [1]


def epoch() -> int:
    return _EPOCH[0]


def invalidate(_var=None) -> None:
    """Bump the global epoch: every frozen plan in the process misses on
    its next dispatch and rebuilds (watch_var callback signature)."""
    _EPOCH[0] += 1


# Config whose value is frozen into plan.fn. File/env sources resolve
# before any plan can exist; programmatic set_var must invalidate.
for _fw, _name in (("metrics", "enable"), ("sanitizer", "enable"),
                   ("trace", "enable"),
                   ("coll_hier", "enable"), ("coll_hier", "selftune"),
                   ("coll_hier", "min_bytes"),
                   ("coll_hier", "rescore_interval"),
                   ("coll_hier", "retune_factor"),
                   ("coll_hier", "retune_min_us"),
                   ("coll_hier", "min_samples"),
                   ("coll_hier", "fake_nodes"),
                   ("coll_hier", "fake_slices")):
    watch_var(_fw, _name, invalidate)


class CollPlan:
    """One frozen dispatch chain for (comm, verb): epoch-validated in
    ``ProcComm._coll``, rebuilt by :func:`build` on any miss."""

    __slots__ = ("verb", "epoch", "fn", "provider")

    def __init__(self, verb: str, epoch_: int, fn, provider: str):
        self.verb = verb
        self.epoch = epoch_
        self.fn = fn
        self.provider = provider

    def __repr__(self) -> str:  # tools/info + debugging
        return (f"<CollPlan {self.verb} via {self.provider} "
                f"epoch={self.epoch}>")


def build(comm, verb: str) -> CollPlan:
    """Resolve + freeze the dispatch chain for one slot (the slow path
    of ``ProcComm._coll``). Mirrors the pre-plan per-call order exactly:
    usable check -> SPC record -> metrics entry stamp -> sanitizer
    signature capture -> trace span -> module fn."""
    from ompi_tpu.runtime import metrics as _metrics
    from ompi_tpu.runtime import sanitizer as _san
    from ompi_tpu.runtime import trace as _trace

    _hier._plan_misses[0] += 1
    # capture the epoch BEFORE reading any config: a concurrent set_var
    # then at worst forces one extra rebuild, never a stale plan
    ep = _EPOCH[0]
    inner = comm.coll.get(verb)  # raises for unprovided slots, as before
    provider = comm.coll.providers.get(verb, "?")
    if _san._enable_var._value:
        # per-call signature capture happens inside the wrapper;
        # wrap_coll itself is per-(comm, verb) stateless, so binding it
        # once here is the whole point of the freeze
        inner = _san.wrap_coll(comm, verb, inner)
    if _trace.enabled():
        inner = _trace.wrap_span(f"comm.{verb}", "comm", inner)

    if _metrics._enable_var._value:
        def fn(comm2, *args, _inner=inner, _verb=verb):
            if comm2.revoked:
                raise MPIError(ERR_REVOKED, comm2.name)
            _spc.record(_verb)
            # entry stamp for the straggler plane (suppressed-internal
            # calls are skipped inside, same as the pre-plan dispatch)
            _metrics.on_coll_entry(comm2, _verb)
            return _inner(comm2, *args)
    else:
        def fn(comm2, *args, _inner=inner, _verb=verb):
            if comm2.revoked:
                raise MPIError(ERR_REVOKED, comm2.name)
            _spc.record(_verb)
            return _inner(comm2, *args)

    return CollPlan(verb, ep, fn, provider)


def invalidate_comm(comm, verb: Optional[str] = None) -> None:
    """Drop one comm's plan(s): the decide.py re-score seam (one verb,
    on the agreed index) and the Free path (all)."""
    # persistent plans (coll/persist.py) freeze the same decisions one
    # level further out: any per-comm invalidation (decide.py re-score
    # switch, Free) must miss them too, on the same agreed index
    comm._persist_cepoch = getattr(comm, "_persist_cepoch", 0) + 1
    plans = getattr(comm, "_plans", None)
    if plans is None:
        return
    if verb is None:
        plans.clear()
    else:
        plans.pop(verb, None)
