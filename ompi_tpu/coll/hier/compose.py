"""Per-domain stage composition for proc-mode collectives.

Reference directions: HiCCL (arxiv 2408.05962) — collectives decomposed
into per-domain stages beat flat algorithms once a comm spans locality
boundaries — and the multi-process-per-GPU allreduce split (arxiv
2508.13397): reduce toward the fast-domain leader, exchange between
leaders over the slow domain, fan back out. Mapped onto this runtime:

- **host** stage — the all-local sub-communicator (han's ``low``), whose
  own coll table picks coll/sm segment collectives / CMA;
- **slice** stage — leaders of the same slice (ICI domain analog),
  present only when the topology carries slice identity
  (``coll_hier_fake_slices`` on one machine; real slice cards later);
- **cross** stage — slice/node leaders over tcp (the DCN analog).

Sub-communicators are han's lazily-built (low, up) pairs resolved
through :func:`coll.han.shared_han` — one shared module (and one Split)
per comm even when han and hier are both selected, and the slice level
nests the SAME machinery on the up comm instead of growing a third
subcomm cache.

Composed verbs: allreduce, bcast, allgather, reduce_scatter_block.
Ineligible calls (non-commutative ops, IN_PLACE where the staging needs
a real send descriptor, payloads under ``coll_hier_min_bytes``) walk
the table's fallback chain (``CollTable.next_after``) to whatever would
own the slot had hier not been selected. Every composed call runs under
the decide engine: per-stage wall times feed the self-tuning re-score,
and the active plan ("hier"/"flat") is applied on agreed call indices.
"""

from __future__ import annotations

# plane member (hier/__init__ owns the note_* hooks): mpilint
# module-scan marker for the derived INSTR_IMPL set
MPILINT_INSTR_IMPL = True

import time
from typing import Any, Dict, Optional

import numpy as np

from ompi_tpu.coll import han as _han
from ompi_tpu.coll import hier as _hier
from ompi_tpu.coll.base import CollModule, coll_framework
from ompi_tpu.coll.hier import decide as _decide
from ompi_tpu.comm.communicator import parse_buffer
from ompi_tpu.core import op as _op
from ompi_tpu.core.datatype import BYTE
from ompi_tpu.mca.component import Component
from ompi_tpu.mca.var import get_var
from ompi_tpu.runtime import metrics as _metrics
from ompi_tpu.runtime import spc
from ompi_tpu.runtime import trace as _trace

_EMPTY = np.empty(0, np.uint8)

_COMPOSED = ("allreduce", "bcast", "allgather", "reduce_scatter_block")


def _flat_mod():
    """Re-entrancy fallback: the Splits inside subcomm construction run
    parent-comm collectives that dispatch back into hier's own slots
    (the han _building discipline)."""
    from ompi_tpu.coll.basic import flat_module

    return flat_module()


class _Stager:
    """Per-call stage runner: wall-times each stage when the decide
    engine observes (selftune) or the metrics plane is on, applies the
    deterministic chaos-delay injection, and wraps stages in trace
    spans when tracing. The fully-disabled path is two attribute loads
    per call and a plain thunk call per stage."""

    __slots__ = ("verb", "idx", "observe", "mx", "timings", "t0")

    def __init__(self, verb: str, idx: int):
        self.verb = verb
        self.idx = idx
        self.observe = _decide.tuning()
        self.mx = _metrics._enable_var._value
        self.timings: Dict[str, float] = {}
        self.t0 = time.perf_counter() if (self.observe or self.mx) else 0.0

    def run(self, name: str, thunk):
        d = _decide.inject_delay_ms(name, self.idx)
        if d:
            time.sleep(d / 1000.0)
        timed = self.observe or self.mx
        s0 = time.perf_counter() if timed else 0.0
        if _trace.enabled():
            with _trace.span(f"coll.hier.{self.verb}.{name}", cat="coll"):
                thunk()
        else:
            thunk()
        if timed:
            us = (time.perf_counter() - s0) * 1e6
            self.timings[name] = round(us, 1)
            if self.mx:
                _hier.note_stage(self.verb, name, us)

    def total_us(self) -> float:
        return (time.perf_counter() - self.t0) * 1e6


class _StagePlan:
    """Pre-bound per-(verb, dtype, count-class) composition state — the
    CollPlan's inner keying. Frozen at first dispatch: the dtype-level
    eligibility verdict and the node-grouped<->rank-ordered permutation
    template (allgather) live here so the steady state does no
    re-derivation."""

    __slots__ = ("eligible", "order", "min_bytes")

    def __init__(self, eligible: bool, order=None, min_bytes: int = 0):
        self.eligible = eligible
        self.order = order
        self.min_bytes = min_bytes


class HierColl(CollModule):
    """Stage-composed allreduce/bcast/allgather/reduce_scatter_block on
    the (host, slice, cross) domain hierarchy."""

    def __init__(self, comm, dm):
        self._dm = dm
        self._han = _han.shared_han(comm, list(dm.node_of))
        self._up_mod = None      # nested han over the leaders (3-level)
        self._up_checked = False

    # ----------------------------------------------------------- helpers
    def _subcomms(self, comm):
        return self._han._subcomms(comm)

    def _up_module(self, up):
        """The slice-level module over the leaders comm, built once: a
        nested shared_han over the up comm whose 'node' identity is the
        slice id — the same lazily-built subcomm machinery, no third
        cache."""
        if not self._up_checked:
            self._up_checked = True
            dm = self._dm
            if dm.n_slices >= 2 and up is not None:
                leaders = sorted(min(dm.members_of_node(n))
                                 for n in range(dm.n_nodes))
                up_map = [dm.slice_of_rank(ld) for ld in leaders]
                counts: Dict[int, int] = {}
                for s in up_map:
                    counts[s] = counts.get(s, 0) + 1
                if len(counts) >= 2 and max(counts.values()) >= 2:
                    self._up_mod = _han.shared_han(up, up_map)
        return self._up_mod

    def _delegate(self, comm, verb: str):
        """The module that would own this slot had hier not been
        selected (full-chain delegation: a conditional runner-up like
        quant never bounces back into hier)."""
        return comm.coll.next_after(verb, "hier")

    def _enter(self, comm, verb: str):
        """Per-call preamble shared by every composed slot: bump the
        (cid, verb) call index and run the agreed-index plan sync."""
        st = _decide.state_for(comm, verb)
        i = st.idx
        st.idx = i + 1
        if _decide.sync_due(i):
            _decide.sync(comm, st, i)
        return st, i

    def _run_flat(self, comm, st, verb: str, timed: bool, call):
        """Execute the fallback chain; when the flat PLAN is the active
        selection (not an eligibility bailout) its latency feeds the
        decide engine so a degraded flat path can re-score back."""
        fn = self._delegate(comm, verb)
        if timed and _decide.tuning():
            t0 = time.perf_counter()
            out = call(fn)
            _decide.report(comm, st, "flat",
                           (time.perf_counter() - t0) * 1e6, {})
            return out
        return call(fn)

    def _finish(self, comm, st, sg: _Stager) -> None:
        if sg.observe:
            _decide.report(comm, st, "hier", sg.total_us(), sg.timings)

    def _stage_plan(self, st, verb: str, dt, commutative: bool,
                    in_place: bool) -> _StagePlan:
        key = (verb, getattr(dt, "np_dtype", None), commutative, in_place)
        sp = st.bound.get(key)
        if sp is None:
            eligible = commutative and not in_place
            order = None
            if eligible and verb == "allgather":
                dm = self._dm
                order = [m for n in range(dm.n_nodes)
                         for m in dm.members_of_node(n)]
            # the static min_bytes table is floored by the MEASURED
            # bandwidth-delay product of this rank's cross links
            # (linkmodel, when armed): a composed pipeline pays ~one
            # extra cross-link RTT per stage, so composition pays off
            # only once the payload dwarfs what the wire holds in one
            # RTT. Frozen per (verb, dtype, flags) like min_bytes — the
            # plan, not the hot path, reads the telemetry.
            sp = _StagePlan(eligible, order,
                            max(int(get_var("coll_hier", "min_bytes")),
                                _decide.link_floor_bytes()))
            st.bound[key] = sp
        return sp

    # --------------------------------------------------------- allreduce
    def allreduce(self, comm, sendbuf, recvbuf, op: _op.Op = _op.SUM) -> None:
        if getattr(_han._building, "active", False):
            return _flat_mod().allreduce(comm, sendbuf, recvbuf, op)
        st, i = self._enter(comm, "allreduce")
        robj, rcount, rdt = parse_buffer(recvbuf)
        sp = self._stage_plan(st, "allreduce", rdt, op.commutative, False)
        nbytes = rcount * rdt.size
        if not sp.eligible or nbytes < sp.min_bytes:
            fn = self._delegate(comm, "allreduce")
            return fn(comm, sendbuf, recvbuf, op)
        if st.active != "hier":
            return self._run_flat(
                comm, st, "allreduce", True,
                lambda fn: fn(comm, sendbuf, recvbuf, op))
        low, up = self._subcomms(comm)
        sg = _Stager("allreduce", i)
        with spc.suppressed():
            sg.run("host.reduce",
                   lambda: low.Reduce(sendbuf, recvbuf, op=op, root=0))
            if up is not None:
                self._up_allreduce(sg, up, robj, recvbuf, rcount, rdt, op)
            sg.run("host.bcast", lambda: low.Bcast(recvbuf, root=0))
        self._finish(comm, st, sg)

    def _up_allreduce(self, sg, up, robj, recvbuf, rcount, rdt, op) -> None:
        """The leader phase: flat over the up comm in the two-level
        shape, or reduce-to-slice-leader / cross-slice-allreduce /
        slice-bcast when the topology carries slices."""
        uh = self._up_module(up)
        tmp = np.array(np.asarray(robj), copy=True)
        spec = [tmp, rcount, rdt]
        if uh is None:
            sg.run("cross.allreduce",
                   lambda: up.Allreduce(spec, recvbuf, op=op))
            return
        mid, top = uh._subcomms(up)
        sg.run("slice.reduce",
               lambda: mid.Reduce(spec, recvbuf, op=op, root=0))
        if top is not None:
            def cross():
                t2 = np.array(np.asarray(robj), copy=True)
                top.Allreduce([t2, rcount, rdt], recvbuf, op=op)

            sg.run("cross.allreduce", cross)
        sg.run("slice.bcast", lambda: mid.Bcast(recvbuf, root=0))

    # ------------------------------------------------------------- bcast
    def bcast(self, comm, buf, root: int = 0) -> None:
        if getattr(_han._building, "active", False):
            return _flat_mod().bcast(comm, buf, root)
        st, i = self._enter(comm, "bcast")
        if st.active != "hier":
            return self._run_flat(comm, st, "bcast", True,
                                  lambda fn: fn(comm, buf, root))
        low, up = self._subcomms(comm)
        dm = self._dm
        root_node = dm.node_of[root]
        my_node = dm.node_of[comm.rank]
        sg = _Stager("bcast", i)
        with spc.suppressed():
            if my_node == root_node:
                # distribute within the root's node first so its leader
                # holds the data for the leader phase
                sg.run("host.bcast_in",
                       lambda: low.Bcast(buf,
                                         root=self._han._low_rank[root]))
            if up is not None:
                uh = self._up_module(up)
                ur = self._han._up_rank_of_node[root_node]
                if uh is None:
                    sg.run("cross.bcast",
                           lambda: up.Bcast(buf, root=ur))
                else:
                    # the nested module runs slice-in / cross / slice-out
                    sg.run("cross.bcast",
                           lambda: uh.bcast(up, buf, ur))
            if my_node != root_node:
                sg.run("host.bcast", lambda: low.Bcast(buf, root=0))
        self._finish(comm, st, sg)

    # --------------------------------------------------------- allgather
    def allgather(self, comm, sendbuf, recvbuf) -> None:
        if getattr(_han._building, "active", False):
            return _flat_mod().allgather(comm, sendbuf, recvbuf)
        st, i = self._enter(comm, "allgather")
        robj, rcount, rdt = parse_buffer(recvbuf)
        sp = self._stage_plan(st, "allgather", rdt, True, sendbuf is None)
        nbytes = rcount * rdt.size
        if not sp.eligible or nbytes < sp.min_bytes:
            fn = self._delegate(comm, "allgather")
            return fn(comm, sendbuf, recvbuf)
        if st.active != "hier":
            return self._run_flat(comm, st, "allgather", True,
                                  lambda fn: fn(comm, sendbuf, recvbuf))
        from ompi_tpu.core.convertor import pack as cv_pack, \
            unpack as cv_unpack

        dm = self._dm
        n = comm.size
        sobj, scount, sdt = parse_buffer(sendbuf)
        blk = np.ascontiguousarray(cv_pack(sobj, scount, sdt))
        nb = blk.nbytes
        low, up = self._subcomms(comm)
        nlocal = low.Get_size()
        nodebuf = np.empty(nlocal * nb, np.uint8) if up is not None \
            else _EMPTY
        allbuf = np.empty(n * nb, np.uint8)
        sg = _Stager("allgather", i)
        with spc.suppressed():
            # host: gather the node's blocks at its leader (low-rank
            # order == ascending comm rank within the node)
            sg.run("host.gather",
                   lambda: low.Gather([blk, nb, BYTE],
                                      [nodebuf, nlocal * nb, BYTE],
                                      root=0))
            if up is not None:
                counts = [len(dm.members_of_node(node)) * nb
                          for node in range(dm.n_nodes)]
                sg.run("cross.allgatherv",
                       lambda: up.Allgatherv(
                           [nodebuf, nlocal * nb, BYTE],
                           [allbuf, n * nb, BYTE], counts))
            # host: every member receives the node-grouped surface
            sg.run("host.bcast",
                   lambda: low.Bcast([allbuf, n * nb, BYTE], root=0))
        # node-grouped -> comm-rank order via the pre-bound permutation
        out = np.empty(n * nb, np.uint8)
        for pos, m in enumerate(sp.order):
            out[m * nb:(m + 1) * nb] = allbuf[pos * nb:(pos + 1) * nb]
        cv_unpack(out, robj, rcount, rdt)
        self._finish(comm, st, sg)

    # ----------------------------------------------- reduce_scatter_block
    def reduce_scatter_block(self, comm, sendbuf, recvbuf,
                             op: _op.Op = _op.SUM) -> None:
        if getattr(_han._building, "active", False):
            return _flat_mod().reduce_scatter_block(comm, sendbuf,
                                                    recvbuf, op)
        st, i = self._enter(comm, "reduce_scatter_block")
        robj, rcount, rdt = parse_buffer(recvbuf)
        # contiguity gate: the block slicing below addresses the reduced
        # vector as packed bytes, which is only the unpacked layout for
        # contiguous datatypes (the han.reduce staging rule)
        sp = self._stage_plan(st, "reduce_scatter_block", rdt,
                              op.commutative and rdt.is_contiguous,
                              sendbuf is None)
        n = comm.size
        if not sp.eligible or n * rcount * rdt.size < sp.min_bytes:
            fn = self._delegate(comm, "reduce_scatter_block")
            return fn(comm, sendbuf, recvbuf, op)
        if st.active != "hier":
            return self._run_flat(
                comm, st, "reduce_scatter_block", True,
                lambda fn: fn(comm, sendbuf, recvbuf, op))
        from ompi_tpu.core.convertor import unpack as cv_unpack

        dm = self._dm
        total = n * rcount
        low, up = self._subcomms(comm)
        tmp = np.empty(total * rdt.size, np.uint8)
        sg = _Stager("reduce_scatter_block", i)
        with spc.suppressed():
            # host: reduce the full vector onto the node leader
            sg.run("host.reduce",
                   lambda: low.Reduce(sendbuf, [tmp, total, rdt],
                                      op=op, root=0))
            if up is not None:
                def cross():
                    t2 = tmp.copy()
                    up.Allreduce([t2, total, rdt], [tmp, total, rdt],
                                 op=op)

                sg.run("cross.allreduce", cross)
            # host: leader scatters each member its own block (node
            # members in low-rank order == ascending comm rank)
            if up is not None:
                members = dm.members_of_node(dm.node_of[comm.rank])
                nb = rcount * rdt.size
                sendv = np.empty(len(members) * nb, np.uint8)
                for j, m in enumerate(members):
                    sendv[j * nb:(j + 1) * nb] = tmp[m * nb:(m + 1) * nb]
                sg.run("host.scatter",
                       lambda: low.Scatter(
                           [sendv, len(members) * rcount, rdt],
                           recvbuf, root=0))
            else:
                sg.run("host.scatter",
                       lambda: low.Scatter([_EMPTY, 0, rdt], recvbuf,
                                           root=0))
        self._finish(comm, st, sg)


class HierCollComponent(Component):
    NAME = "hier"
    PRIORITY = 55  # above smcoll(50)/adaptive(48)/han(45): owns the
    # composed slots on multi-domain comms; below self(75)/xla/quant

    def query(self, comm=None, **ctx: Any) -> Optional[HierColl]:
        from ompi_tpu.comm.communicator import ProcComm

        if getattr(_han._building, "active", False):
            return None  # never stack hier inside its own subcomms
        if not isinstance(comm, ProcComm) or comm.size < 3:
            return None
        if not get_var("coll_hier", "enable"):
            return None
        dm = _decide.domain_map_for(comm)
        if dm is None or not dm.nontrivial:
            return None
        return HierColl(comm, dm)


coll_framework.register(HierCollComponent())
