"""coll/nbc — nonblocking collectives for process-mode communicators.

Reference: ompi/mca/coll/libnbc (12,429 LoC) — every MPI_I* collective is a
round-based schedule progressed by opal_progress. Here each I* slot builds
the same generator algorithm the blocking tuned path uses (coll/
algorithms.py) and hands it to ``sched.NbcRequest``, which advances rounds
from request completion callbacks — i.e. from the progress engine/thread,
exactly the libnbc model. Overlapping schedules on one communicator are
isolated by the NBC CID plane + per-comm sequence tags (sched.py).

Algorithm choice mirrors coll/tuned's decision rules where a choice
exists (commutativity gates the reduction trees).

Datapath: schedules ride the PR 10 round engine — pooled/direct-landing
recvs, borrowed-view sends, and ``ordered=False`` windowing (ialltoall
keeps up to ``coll_round_window`` pairwise rounds in flight, advanced
from completion callbacks without a per-round barrier).
"""

from __future__ import annotations

from typing import Optional

from ompi_tpu.coll.base import CollModule, coll_framework
from ompi_tpu.coll import algorithms as alg
import ompi_tpu.coll.tuned  # noqa: F401  (registers the threshold vars)
from ompi_tpu.coll.sched import NbcRequest
from ompi_tpu.core import op as _op
from ompi_tpu.core.request import Request
from ompi_tpu.mca.component import Component
from ompi_tpu.mca.var import get_var


class NbcColl(CollModule):
    # ------------------------------------------------------------ no-data ops
    def ibarrier(self, comm) -> Request:
        return NbcRequest(comm, alg.barrier_dissemination(comm))

    # ------------------------------------------------------------- rooted ops
    def ibcast(self, comm, buf, root: int) -> Request:
        return NbcRequest(comm, alg.bcast_binomial(comm, buf, root))

    def ireduce(self, comm, sendbuf, recvbuf, op: _op.Op,
                root: int) -> Request:
        a = (alg.reduce_binomial if op.commutative and comm.size > 2
             else alg.reduce_linear)
        return NbcRequest(comm, a(comm, sendbuf, recvbuf, op, root))

    def igather(self, comm, sendbuf, recvbuf, root: int) -> Request:
        return NbcRequest(comm, alg.gather_linear(comm, sendbuf, recvbuf,
                                                  root))

    def igatherv(self, comm, sendbuf, recvbuf, counts, displs,
                 root: int) -> Request:
        return NbcRequest(comm, alg.gatherv_linear(comm, sendbuf, recvbuf,
                                                   counts, displs, root))

    def iscatter(self, comm, sendbuf, recvbuf, root: int) -> Request:
        return NbcRequest(comm, alg.scatter_linear(comm, sendbuf, recvbuf,
                                                   root))

    def iscatterv(self, comm, sendbuf, recvbuf, counts, displs,
                  root: int) -> Request:
        return NbcRequest(comm, alg.scatterv_linear(comm, sendbuf, recvbuf,
                                                    counts, displs, root))

    # --------------------------------------------------------------- all-ops
    def iallreduce(self, comm, sendbuf, recvbuf, op: _op.Op) -> Request:
        if not op.commutative:
            gen = self._allreduce_linear(comm, sendbuf, recvbuf, op)
        elif (comm.size > 1 and self._nbytes(recvbuf)
                > get_var("coll_tuned", "allreduce_small_msg")):
            gen = alg.allreduce_ring(comm, sendbuf, recvbuf, op)
        else:
            gen = alg.allreduce_recursive_doubling(comm, sendbuf, recvbuf, op)
        return NbcRequest(comm, gen)

    @staticmethod
    def _allreduce_linear(comm, sendbuf, recvbuf, op):
        yield from alg.reduce_linear(comm, sendbuf, recvbuf, op, 0)
        yield from alg.bcast_binomial(comm, recvbuf, 0)

    def iallgather(self, comm, sendbuf, recvbuf) -> Request:
        total = self._nbytes(recvbuf)
        a = (alg.allgather_bruck
             if total <= get_var("coll_tuned", "allgather_small_msg")
             and comm.size > 1 else alg.allgather_ring)
        return NbcRequest(comm, a(comm, sendbuf, recvbuf))

    def iallgatherv(self, comm, sendbuf, recvbuf, counts, displs) -> Request:
        return NbcRequest(comm, alg.allgatherv_ring(comm, sendbuf, recvbuf,
                                                    counts, displs))

    def ialltoall(self, comm, sendbuf, recvbuf) -> Request:
        return NbcRequest(comm, alg.alltoall_pairwise(comm, sendbuf, recvbuf))

    def ialltoallv(self, comm, sendbuf, recvbuf, sendcounts, sdispls,
                   recvcounts, rdispls) -> Request:
        return NbcRequest(comm, alg.alltoallv_pairwise(
            comm, sendbuf, recvbuf, sendcounts, sdispls, recvcounts,
            rdispls))

    def ireduce_scatter_block(self, comm, sendbuf, recvbuf,
                              op: _op.Op) -> Request:
        return NbcRequest(comm, alg.reduce_scatter_block_sched(
            comm, sendbuf, recvbuf, op))

    def iscan(self, comm, sendbuf, recvbuf, op: _op.Op) -> Request:
        return NbcRequest(comm, alg.scan_linear(comm, sendbuf, recvbuf, op))

    def iexscan(self, comm, sendbuf, recvbuf, op: _op.Op) -> Request:
        return NbcRequest(comm, alg.exscan_linear(comm, sendbuf, recvbuf, op))

    @staticmethod
    def _nbytes(buf) -> int:
        from ompi_tpu.comm.communicator import parse_buffer

        obj, count, dt = parse_buffer(buf)
        return count * dt.size


class NbcCollComponent(Component):
    NAME = "nbc"
    PRIORITY = 20  # only provider of i* slots; between basic and tuned

    _module: Optional[NbcColl] = None

    def query(self, comm=None, **ctx):
        from ompi_tpu.comm.communicator import ProcComm

        if isinstance(comm, ProcComm):
            if NbcCollComponent._module is None:
                NbcCollComponent._module = NbcColl()
            return NbcCollComponent._module
        return None


coll_framework.register(NbcCollComponent())
