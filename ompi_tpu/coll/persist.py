"""coll/persist — the persistent-plan compiler (MPI-4 ``X_init`` → ``Start``).

Reference: Open MPI ships MPI-4 persistent collectives
(``MPI_Allreduce_init`` → ``MPI_Start``, coll.h:545-620) because a
serving/training hot loop calls the SAME collective millions of times
and must not pay a decision tree per call. PR 8 froze the dispatch
prologue (coll/hier/plan.py); this module extends that discipline to
the ENTIRE lowering: at init time the (buffer identity, count, dtype,
op, comm) tuple resolves ONCE into a frozen :class:`PersistPlan` —

- the slot/provider and the tuned-style algorithm choice (ring vs
  recursive doubling, bruck vs ring) are decided once, mirroring the
  nonblocking path's decision rules exactly;
- the full round schedule is PRE-BUILT: every :class:`~.sched.Round`
  object exists before the first Start, its sends borrowing views
  pre-pinned over the caller's buffers and its recvs landing either
  straight in pre-pinned destination slices or in size-classed
  ``mpool`` blocks acquired once and HELD for the request's lifetime;
- the local compute between rounds (reductions, block placement) is
  pre-bound into thunks that reproduce the ad-hoc algorithms'
  arithmetic order exactly — so a frozen replay is BITWISE equal to the
  ``coll_persist_enable=0`` re-issue path.

Steady-state ``Start`` is therefore a schedule replay with zero
per-call decisions: a fresh generator walks the frozen step list and
yields the pre-built rounds.

**Cross-phase chunk pipelining** (the software edition of the
multi-stream overlap of arxiv 2508.13397, composed over the stage split
of HiCCL 2408.05962): when ``coll_persist_chunk_bytes`` > 0 the frozen
allreduce splits each ring block into sub-chunks and issues chunk k+1's
reduce-scatter rounds as ``Round(ordered=False, wait=True)`` — the
engine resumes on the round's OWN completion — while chunk k's
allgather round (one ``ordered=False`` linear exchange) is still in
flight, instead of barriering between the phases. Sub-chunking WITHIN
ring blocks keeps every element's reduction chain identical to the
un-chunked ring, so the pipelined schedule stays bitwise-equal too.
With traffic shaping on (``btl_tcp_shape_enable``), the allgather
phase additionally rides QoS class BULK on tag sub-plane 1: the
overlapped phases then INTERLEAVE at the wire (the shaped btl serves
the next chunk's reduce-scatter — the critical path — ahead of queued
completion traffic) instead of self-contending FIFO on the shared
connection, which was the seam PR 11 left open.

**Wire compatibility**: every un-chunked frozen schedule emits the same
rounds (sizes, peers, order) as the ad-hoc generator it mirrors, so a
rank whose local buffer kind forces the re-issue fallback still
interoperates with frozen peers. Eligibility gates are functions of the
SYMMETRIC tuple (verb, count, dtype, op, comm size) only; rank-local
layout quirks (non-contiguous buffers, derived datatypes) are absorbed
by per-Start pack/unpack bounce thunks over a held scratch, never by a
schedule change. The chunked allreduce changes the allgather wire
pattern, so — like every ``coll_tuned`` algorithm knob —
``coll_persist_enable`` / ``coll_persist_chunk_bytes`` must be set
identically on every member, and buffers must be host buffers
(ndarray/bytearray) on all ranks or none.

**Invalidation** reuses the PR 8 machinery: relevant cvar writes bump
this module's epoch via :func:`~ompi_tpu.mca.var.watch_var` AND the
plan also pins the PR 8 global dispatch epoch plus a per-comm epoch
bumped by ``coll/hier/plan.invalidate_comm`` (the decide.py re-score /
Free seam), so a stale plan can never replay against a changed config —
the next Start recompiles exactly once. A mid-Start peer death fails
the activation through the PR 3 watchdog path; the completion hook then
DISCARDS (never recycles) the plan's held pool blocks, the PR 9
dying-conn lesson.
"""

from __future__ import annotations

import threading
import weakref
from typing import Callable, List, Optional, Sequence, Tuple

import numpy as np

from ompi_tpu import qos as _qos_mod
from ompi_tpu.coll import sched as _sched
from ompi_tpu.coll.sched import NbcRequest, Round
from ompi_tpu.coll.basic import _np_reduce_typed, _typed_view
from ompi_tpu.coll.hier import plan as _cplan
from ompi_tpu.comm.communicator import parse_buffer
from ompi_tpu.core.convertor import (
    _as_byte_view as _as_bytes,
    pack as cv_pack,
    unpack as cv_unpack,
)
from ompi_tpu.mca.var import (
    get_var,
    register_pvar,
    register_var,
    watch_var,
)
from ompi_tpu.runtime import mpool
from ompi_tpu.runtime import trace as _trace

_enable_var = register_var(
    "coll_persist", "enable", 1,
    help="1 = compile persistent collectives (X_init) into frozen "
         "replayable plans: provider/algorithm choice, round schedule, "
         "pinned buffer views, and pool blocks are resolved once at "
         "init so steady-state Start is a schedule replay. 0 = the "
         "pre-PR-11 re-issue path (rebuild the nonblocking schedule "
         "per Start), kept verbatim as the measured A/B baseline. "
         "Must match on every member of a communicator.", level=6)
_chunk_var = register_var(
    "coll_persist", "chunk_bytes", 262144,
    help="Sub-chunk size for the pipelined persistent allreduce: each "
         "ring block is split into ceil(block/chunk) chunks whose "
         "reduce-scatter rounds overlap the previous chunk's allgather "
         "(Round wait/unordered windowing across the phase boundary). "
         "0 disables chunking (plain frozen ring, wire-identical to "
         "the ad-hoc path). Must match on every member.", level=6)
_donate_var = register_var(
    "coll_persist", "donate", 0,
    help="Mesh mode: 1 = X_init also compiles a donated-operand "
         "executable, so Start(x) with a fresh operand lets XLA reuse "
         "x's buffer for the output (x is CONSUMED — the MPI-4 "
         "started-buffer ownership reading). The init-time operand "
         "stays un-donated for operand-less restarts.", level=7)

# replay counters (persist_* pvars). List slots so hot call sites
# (PersistentCollRequest.Start, mesh _pcoll_init) bump them with one
# attribute load + item add, no function call on the steady path.
_plans = [0]       # frozen plans compiled (proc + mesh)
_starts = [0]      # persistent Starts issued (both replay and re-issue)
_replay_us = [0.0]  # accumulated Start-call latency, microseconds
_overlap = [0]     # rounds issued across a chunk-phase boundary

register_pvar("persist", "plans", lambda: _plans[0],
              help="Persistent plans compiled (X_init freezes + "
                   "invalidation rebuilds; mesh executable freezes "
                   "count too)")
register_pvar("persist", "starts", lambda: _starts[0],
              help="Persistent Start activations issued (frozen replay "
                   "AND coll_persist_enable=0 re-issue — the A/B "
                   "denominator)")
register_pvar("persist", "replay_us", lambda: _replay_us[0],
              help="Accumulated Start-call latency in microseconds "
                   "(schedule issue, first-round launch); divide by "
                   "persist_starts deltas per mode for the A/B")
register_pvar("persist", "overlap_rounds", lambda: _overlap[0],
              help="Rounds the chunk-pipelined persistent allreduce "
                   "issued across a chunk-phase boundary with no "
                   "intervening barrier (> 0 proves cross-phase "
                   "overlap; stays flat when coll_round_window<=1 "
                   "forces lockstep)")


def note_plan() -> None:
    """One frozen plan compiled (hot call sites bump ``_plans[0]``
    inline; this hook exists for tools and the mpilint contract)."""
    _plans[0] += 1


def note_start(us: float) -> None:
    """One persistent Start, charging its issue latency (hot call sites
    bump the slots inline; tools/lint hook)."""
    _starts[0] += 1
    _replay_us[0] += us


def note_overlap(rounds: int) -> None:
    """Cross-phase rounds issued by one pipelined replay."""
    _overlap[0] += int(rounds)


# ------------------------------------------------------- stall forensics
# Live-plan registry for the forensics provider (runtime/forensics):
# WeakSet so GC'd plans drop out; populated on the cold compile path.
_fx_lock = threading.Lock()
_live_plans: "weakref.WeakSet" = weakref.WeakSet()


def _fx_debug_state() -> dict:
    """Forensics provider: frozen-plan census — how many plans are
    live, how many pool blocks they pin, and which persistent Starts
    are active right now (an active Start mid-stall is an in-flight
    round batch in coll.sched's section; this names the plan)."""
    with _fx_lock:
        plans = list(_live_plans)
    active = _forensics.clip(
        [{"verb": p.verb, "provider": p.provider,
          "held_blocks": len(p.held),
          "overlap_rounds": p.overlap_rounds}
         for p in plans if p.active])
    return {"plans_compiled": _plans[0],
            "starts": _starts[0],
            "live_plans": len(plans),
            "held_blocks": sum(len(p.held) for p in plans),
            "active_starts": active,
            "orphaned_blocks": len(_orphans)}


from ompi_tpu.runtime import forensics as _forensics  # noqa: E402

_forensics.register_provider("coll.persist", _fx_debug_state)


# ------------------------------------------------------------ invalidation
# Module epoch: a plan is live only while every epoch it pinned at
# compile time still matches. Config whose value is frozen into the
# schedule invalidates through watch_var; everything the PR 8 dispatch
# epoch already covers (metrics/sanitizer/trace enables, coll_hier
# knobs) rides along because the plan pins that epoch too.
_EPOCH = [1]


def epoch() -> int:
    return _EPOCH[0]


def invalidate(_var=None) -> None:
    """Bump the persist epoch: every frozen plan misses on its next
    Start and recompiles exactly once (watch_var callback shape)."""
    _EPOCH[0] += 1


for _fw, _name in (("coll_persist", "enable"),
                   ("coll_persist", "chunk_bytes"),
                   ("coll_tuned", "allreduce_small_msg"),
                   ("coll_tuned", "allgather_small_msg")):
    watch_var(_fw, _name, invalidate)


def enabled() -> bool:
    return bool(_enable_var._value)


# ---------------------------------------------------------------- pinning
class _Pin:
    """A pre-resolved flat uint8 view the schedule reads/writes, plus
    per-Start bounce thunks when the caller's layout can't be aliased:
    ``pre`` packs fresh send bytes into the held scratch, ``post``
    unpacks received bytes back into the caller's buffer. Rank-local by
    design — the wire schedule never depends on which side we took."""

    __slots__ = ("view", "pre", "post")

    def __init__(self, view, pre=None, post=None):
        self.view = view
        self.pre = pre
        self.post = post


def _pin(buf, writable: bool) -> Optional[Tuple[_Pin, int, object]]:
    """(pin, count, datatype) for a host buffer, or None when the
    buffer kind can't back a frozen plan (device arrays re-stage per
    call — the re-issue path owns those)."""
    obj = buf
    if isinstance(buf, (list, tuple)):
        if len(buf) not in (2, 3):
            return None
        obj = buf[0]
    if not isinstance(obj, (np.ndarray, bytearray, memoryview)):
        return None
    if isinstance(obj, memoryview) and obj.readonly and writable:
        return None
    obj2, count, dt = parse_buffer(buf)
    nbytes = count * dt.size
    if isinstance(obj2, np.ndarray):
        if not obj2.flags.c_contiguous:
            # a strided ndarray can't be byte-viewed (the convertor has
            # the same limit) — the re-issue path owns it, so frozen
            # and fallback ranks fail or succeed identically
            return None
        if obj2.flags.writeable or not writable:
            if dt.is_contiguous:
                return _Pin(_as_bytes(obj2)[:nbytes]), count, dt
        else:
            return None
    else:  # bytearray / memoryview: 1-D bytes, alias directly
        view = np.frombuffer(obj2, np.uint8, nbytes)
        if not (view.flags.writeable or not writable):
            return None
        if dt.is_contiguous:
            return _Pin(view), count, dt
        obj2 = view  # derived datatype over raw bytes: bounce below
    # bounce: a non-contiguous DATATYPE over a contiguous buffer — a
    # held scratch carries the wire bytes; pack/unpack run per Start
    # (counted staging — the layout genuinely can't alias)
    scratch = np.empty(nbytes, dtype=np.uint8)

    def pre(_o=obj2, _c=count, _d=dt, _s=scratch, _n=nbytes):
        data = cv_pack(_o, _c, _d)
        if not data.flags.c_contiguous:
            data = np.ascontiguousarray(data)
        _s[:] = _as_bytes(data)[:_n]
        _sched.note_copied(_n)

    def post(_o=obj2, _c=count, _d=dt, _s=scratch, _n=nbytes):
        cv_unpack(_s, _o, _c, _d)
        _sched.note_copied(_n)

    return _Pin(scratch, pre, post), count, dt


# ------------------------------------------------------------------- plans
class PersistPlan:
    """One frozen lowering for (comm, verb, args). ``steps`` is the
    replay program — ("r", Round) communication steps interleaved with
    ("c", thunk) pre-bound compute — or None for the epoch-tagged
    "re-issue" sentinel (ineligible shape: the Start path falls back
    without re-testing eligibility every call)."""

    __slots__ = ("verb", "steps", "held", "overlap_rounds", "epochs",
                 "provider", "dead", "discarded", "active", "__weakref__")

    def __init__(self, verb: str, steps, held, overlap_rounds: int,
                 epochs, provider: str):
        self.verb = verb
        self.steps = steps
        self.held = held                  # [(pool, block), ...]
        self.overlap_rounds = overlap_rounds
        self.epochs = epochs
        self.provider = provider
        self.dead = False
        self.discarded = False
        self.active = False

    def __repr__(self) -> str:  # tools/info + debugging
        kind = "replay" if self.steps is not None else "reissue"
        return (f"<PersistPlan {self.verb} {kind} "
                f"rounds={sum(1 for k, _ in (self.steps or ()) if k == 'r')} "
                f"held={len(self.held)} dead={self.dead}>")

    def retire(self) -> None:
        """Release held pool blocks back to their free lists — only on
        clean teardown (rebuild of an INACTIVE plan, comm Free). An
        active plan discards instead: its views may still be a landing
        zone for an in-flight drain."""
        if self.dead:
            return
        self.dead = True
        if self.active:
            self._drop(recycle=False)
        else:
            self._drop(recycle=True)

    def fail(self) -> None:
        """A replay died mid-Start (peer death through the PR 3
        watchdog, schedule error): DISCARD the held blocks — never
        recycle — and kill the plan so the next Start recompiles."""
        self.dead = True
        self.discarded = True
        self._drop(recycle=False)

    def _drop(self, recycle: bool) -> None:
        # drain IN PLACE: the GC finalizer holds this same list object,
        # so a settled plan must leave it empty, never rebound
        held = self.held
        while held:
            pool, block = held.pop()
            if recycle:
                pool.release(block)  # mpiown: disable=recycle-on-failure — fail() always passes recycle=False; this arm is retire()'s clean-teardown path only
            else:
                pool.discard(block)


class _Builder:
    """Accumulates the frozen step list + held-block ownership while a
    verb builder lays out its schedule."""

    __slots__ = ("steps", "held", "overlap")

    def __init__(self):
        self.steps: List[tuple] = []
        self.held: List[tuple] = []
        self.overlap = 0

    def block(self, nbytes: int) -> np.ndarray:
        """A staging view held for the plan's lifetime: size-classed
        pool block when poolable, plain allocation otherwise."""
        pool = mpool.class_pool(nbytes)
        if pool is None:
            return np.empty(max(nbytes, 1), dtype=np.uint8)[:nbytes]
        blk, _ = pool.acquire_pair()
        self.held.append((pool, blk))  # owns: held
        return np.frombuffer(blk, np.uint8, nbytes)

    def abort(self) -> None:
        """Settle every held block when a builder bails AFTER acquiring
        staging (the non-commutative allreduce's bcast-leg fallback):
        recycle is safe — the blocks were never exposed to a Start, so
        no drain can be in flight into them."""
        while self.held:
            pool, blk = self.held.pop()
            pool.release(blk)

    def rnd(self, sends: Sequence = (), recvs: Sequence = (),
            ordered: bool = True, wait: bool = False,
            qos=None, plane: int = 0, chunk=None) -> None:
        self.steps.append(("r", Round(sends=sends, recvs=recvs,
                                      ordered=ordered, wait=wait,
                                      qos=qos, plane=plane,
                                      chunk=chunk)))

    def do(self, fn: Callable[[], None]) -> None:
        self.steps.append(("c", fn))


def _replay(plan: PersistPlan):
    """A fresh generator over the frozen steps — the whole per-Start
    lowering. Rounds are the SAME pre-built objects every activation;
    compute thunks read/write the pre-pinned views."""
    for kind, item in plan.steps:
        if kind == "c":
            item()
        else:
            yield item


# --------------------------------------------------------------- lifecycle
# Pool blocks of plans the user dropped without Request_free/comm Free:
# the GC finalizer must not take pool locks (it can fire inside ANY
# allocation, including one a pool holds its lock around), so it parks
# the blocks here and the next compile/release settles the accounting.
_orphans: List[tuple] = []


def _orphan_held(held: List[tuple]) -> None:
    while held:
        _orphans.append(held.pop())  # mpiracer: disable=cross-thread-race — deliberately lock-free: this runs inside a GC finalizer that may fire while a pool holds its own lock; append is GIL-atomic and settle pops until empty


def _settle_orphans() -> None:
    while _orphans:
        pool, block = _orphans.pop()  # mpiracer: disable=cross-thread-race — GIL-atomic pop; a finalizer appending concurrently is settled by the next compile/release pass
        # discard, never recycle: nothing proves the dropped plan had
        # no activation still draining into its views
        pool.discard(block)


def compile_plan(comm, slot: str, args: tuple) -> PersistPlan:
    """Resolve + freeze the entire lowering for one persistent request
    (the X_init slow path). Returns a re-issue sentinel plan when the
    shape is ineligible — cached under the same epochs so Start never
    re-tests eligibility."""
    _settle_orphans()
    epochs = (_EPOCH[0], _cplan._EPOCH[0],
              getattr(comm, "_persist_cepoch", 0))
    builder = _BUILDERS.get(slot)
    built = builder(comm, *args) if builder is not None else None
    if built is None:
        return PersistPlan(slot, None, [], 0, epochs, "reissue")
    b, provider = built
    plan = PersistPlan(slot, b.steps, b.held, b.overlap, epochs, provider)
    if b.held:
        # a plan GC'd while still holding blocks (request dropped with
        # no Free) must not inflate pool accounting for process life
        weakref.finalize(plan, _orphan_held, b.held)
    _plans[0] += 1
    live = getattr(comm, "_persist_live", None)
    if live is None:
        live = comm._persist_live = weakref.WeakSet()
    live.add(plan)
    with _fx_lock:  # forensics plan census (cold compile path)
        _live_plans.add(plan)
    return plan


def valid(comm, plan: PersistPlan) -> bool:
    """Live iff every pinned epoch still matches: the persist epoch
    (coll_persist/tuned cvar writes), the PR 8 dispatch epoch
    (metrics/sanitizer/trace/hier config), and the per-comm epoch
    (decide.py re-scores, Free)."""
    return (not plan.dead
            and plan.epochs == (_EPOCH[0], _cplan._EPOCH[0],
                                getattr(comm, "_persist_cepoch", 0)))


def start(comm, plan: PersistPlan) -> NbcRequest:
    """Replay the frozen schedule as one NbcRequest activation. A
    failed activation (watchdog peer death, schedule error) discards
    the plan's blocks through :meth:`PersistPlan.fail`."""
    plan.active = True
    if _trace.enabled():
        # replay boundary in the trace: coll.round spans that follow
        # (until the request completes) belong to this frozen replay
        _trace.instant("coll.persist.start", cat="coll", verb=plan.verb,
                       provider=plan.provider,
                       overlap_rounds=plan.overlap_rounds)
    if plan.overlap_rounds and _sched._window_var._value > 1:
        # window<=1 forces every wait round into an ordered barrier —
        # the schedule still replays bitwise-identically, but no round
        # crosses a phase boundary, so the overlap claim must not grow
        _overlap[0] += plan.overlap_rounds
    req = NbcRequest(comm, _replay(plan))

    def settle(r, _plan=plan):
        _plan.active = False
        if r._error:
            _plan.fail()

    req.add_completion_callback(settle)
    return req


def release_comm(comm) -> None:
    """Comm Free: every live plan dies with its communicator — recycle
    the blocks of inactive plans, discard those of active ones."""
    _settle_orphans()
    live = getattr(comm, "_persist_live", None)
    if not live:
        return
    for plan in list(live):
        plan.retire()


# ================================================================ builders
# Each builder returns (``_Builder``, provider-tag) for an eligible
# SYMMETRIC shape, or None to fall back to the re-issue path. The wire
# schedule (rounds, sizes, peers, order) of every un-chunked builder is
# identical to the ad-hoc generator it mirrors, and reduction thunks
# reproduce the ad-hoc accumulation order exactly — bitwise equality
# across enable=0 / enable=1 / pipelined is by construction, and a
# locally-fallen-back rank still interoperates.
_Z = np.zeros(0, dtype=np.uint8)  # shared zero-byte token/landing view


def _b_barrier(comm):
    """Mirror alg.barrier_dissemination: ceil(log2 n) zero-byte rounds."""
    n, r = comm.size, comm.rank
    b = _Builder()
    d = 1
    while d < n:
        b.rnd(sends=[(_Z, (r + d) % n)], recvs=[(0, (r - d) % n, _Z)])
        d <<= 1
    return b, "persist/dissemination"


def _b_bcast(comm, buf, root):
    """Mirror alg.bcast_binomial: one recv from the parent straight
    into the (pinned) buffer, then one round fanning borrowed views of
    it to the children."""
    n, r = comm.size, comm.rank
    p = _pin(buf, writable=True)
    if p is None:
        return None
    pin, count, dt = p
    nbytes = count * dt.size
    b = _Builder()
    vrank = (r - root) % n
    if vrank == 0:
        if pin.pre:
            b.do(pin.pre)
        mask = 1
        while mask < n:
            mask <<= 1
        mask >>= 1
    else:
        mask = 1
        while not (vrank & mask):
            mask <<= 1
        src = (vrank - mask + root) % n
        b.rnd(recvs=[(nbytes, src, pin.view)])
        mask >>= 1
    sends = []
    while mask > 0:
        if vrank + mask < n and not (vrank & mask):
            sends.append((pin.view, (vrank + mask + root) % n))
        mask >>= 1
    if sends:
        b.rnd(sends=sends)
    if vrank != 0 and pin.post:
        b.do(pin.post)
    return b, "persist/binomial"


def _reduce_into(b, comm, spin, rview, op, root, count, dt):
    """Append frozen reduce-to-root steps writing the packed result
    into ``rview`` at the root (mirrors NbcColl.ireduce's choice:
    binomial for commutative ops past 2 ranks, else rank-ordered
    linear; accumulation order matches alg.reduce_* exactly)."""
    n, r = comm.size, comm.rank
    nbytes = count * dt.size
    if spin.pre:
        b.do(spin.pre)
    if op.commutative and n > 2:
        vrank = (r - root) % n
        children = []
        mask = 1
        while mask < n:
            if vrank & mask:
                break
            if vrank + mask < n:
                children.append((vrank + mask + root) % n)
            mask <<= 1
        acc = b.block(nbytes)
        b.do(lambda _a=acc, _s=spin.view: _a.__setitem__(slice(None), _s))
        if children:
            stages = [b.block(nbytes) for _ in children]
            b.rnd(recvs=[(nbytes, c, st)
                         for c, st in zip(children, stages)])

            def fold(_a=acc, _st=stages, _op=op, _dt=dt):
                t = _typed_view(_a, _dt)
                for s in _st:
                    t = _np_reduce_typed(_op, t, _typed_view(s, _dt))
                _a[:] = _as_bytes(np.ascontiguousarray(t))

            b.do(fold)
        if vrank != 0:
            parent = (vrank - mask + root) % n
            b.rnd(sends=[(acc, parent)])
        else:
            b.do(lambda _r=rview, _a=acc: _r.__setitem__(slice(None), _a))
        return
    # rank-ordered linear fan-in (non-commutative ops / 2 ranks)
    if r != root:
        b.rnd(sends=[(spin.view, root)])
        return
    others = [i for i in range(n) if i != root]
    stages = [b.block(nbytes) for _ in others]
    b.rnd(recvs=[(nbytes, i, st) for i, st in zip(others, stages)])

    def fold_linear(_o=others, _st=stages, _s=spin.view, _r=rview,
                    _op=op, _dt=dt, _root=root, _n=n):
        parts: List[np.ndarray] = [None] * _n  # type: ignore[list-item]
        parts[_root] = _s
        for i, st in zip(_o, _st):
            parts[i] = st
        acc = _typed_view(parts[0].copy(), _dt)
        for i in range(1, _n):
            acc = _np_reduce_typed(_op, acc, _typed_view(parts[i], _dt))
        _r[:] = _as_bytes(np.ascontiguousarray(acc))

    b.do(fold_linear)


def _b_reduce(comm, sendbuf, recvbuf, op, root):
    ps = _pin(recvbuf if sendbuf is None else sendbuf, writable=False)
    if ps is None:
        return None
    spin, count, dt = ps
    if dt.np_dtype is None:
        return None
    rview = None
    post = None
    if comm.rank == root:
        pr = _pin(recvbuf, writable=True)
        if pr is None:
            return None
        rpin, rcount, rdt = pr
        rview, post = rpin.view, rpin.post
    b = _Builder()
    _reduce_into(b, comm, spin, rview, op, root, count, dt)
    if post:
        b.do(post)
    return b, "persist/reduce"


def _b_allreduce(comm, sendbuf, recvbuf, op):
    """Mirror NbcColl.iallreduce: non-commutative → linear reduce +
    binomial bcast; large commutative → ring (chunk-pipelined when
    ``coll_persist_chunk_bytes`` is set); small commutative →
    recursive doubling (power-of-two worlds; the fold-in pre/post
    phase of non-pow2 worlds stays on the re-issue path)."""
    n, r = comm.size, comm.rank
    pr = _pin(recvbuf, writable=True)
    if pr is None:
        return None
    rpin, count, dt = pr
    if dt.np_dtype is None:
        return None
    if sendbuf is None:
        spin = rpin
    else:
        ps = _pin(sendbuf, writable=False)
        if ps is None:
            return None
        spin = ps[0]
    nbytes = count * dt.size
    if not op.commutative:
        b = _Builder()
        _reduce_into(b, comm, spin, rpin.view, op, 0, count, dt)
        if r == 0 and rpin.post:
            b.do(rpin.post)
        # bcast of the reduced recvbuf, mirroring _allreduce_linear's
        # second leg (by the time the bcast steps run, the fold — and
        # on a bounce layout its unpack — has landed the result in the
        # recvbuf the bcast re-reads)
        sub = _b_bcast(comm, recvbuf, 0)
        if sub is None:
            # the reduce leg already acquired fan-in staging into
            # b.held; falling back without settling it leaked those
            # blocks for process life (outstanding never decremented)
            b.abort()
            return None
        bb, _ = sub
        b.steps.extend(bb.steps)
        b.held.extend(bb.held)
        return b, "persist/linear+bcast"
    if n == 1:
        return _ar_trivial(spin, rpin, nbytes)
    if nbytes > get_var("coll_tuned", "allreduce_small_msg"):
        if count % n != 0:
            return None  # ad-hoc pads through scratch; re-issue owns it
        return _ring_allreduce(comm, spin, rpin, op, count, dt)
    if n & (n - 1):
        return None  # non-pow2 small: the rd fold-in stays re-issue
    return _rd_allreduce(comm, spin, rpin, op, count, dt)


def _ar_trivial(spin, rpin, nbytes):
    b = _Builder()
    if spin.pre:
        b.do(spin.pre)
    if spin.view is not rpin.view:
        b.do(lambda _r=rpin.view, _s=spin.view:
             _r.__setitem__(slice(None), _s))
    if rpin.post:
        b.do(rpin.post)
    return b, "persist/trivial"


def _rd_allreduce(comm, spin, rpin, op, count, dt):
    """Recursive doubling, power-of-two worlds: sendrecv with partner
    2^t away, accumulating ``op(acc, got)`` in a held scratch — the
    exact alg.allreduce_recursive_doubling order with rem == 0."""
    n, r = comm.size, comm.rank
    nbytes = count * dt.size
    b = _Builder()
    if spin.pre:
        b.do(spin.pre)
    acc = b.block(nbytes)
    b.do(lambda _a=acc, _s=spin.view: _a.__setitem__(slice(None), _s))
    stage = b.block(nbytes)
    mask = 1
    while mask < n:
        partner = r ^ mask
        b.rnd(sends=[(acc, partner)], recvs=[(nbytes, partner, stage)])

        def fold(_a=acc, _g=stage, _op=op, _dt=dt):
            out = _np_reduce_typed(_op, _typed_view(_a, _dt),
                                   _typed_view(_g, _dt))
            _a[:] = _as_bytes(np.ascontiguousarray(out))

        b.do(fold)
        mask <<= 1
    b.do(lambda _r=rpin.view, _a=acc: _r.__setitem__(slice(None), _a))
    if rpin.post:
        b.do(rpin.post)
    return b, "persist/recursive_doubling"


def _ring_allreduce(comm, spin, rpin, op, count, dt):
    """Ring reduce-scatter + allgather with pre-pinned block views.

    Un-chunked: wire-identical to alg.allreduce_ring (nseg=1, alias
    path) — same 2n-2 rounds, but the per-Start seed copy is gone: the
    reduce-scatter thunks read the local contribution STRAIGHT from the
    pinned send view (``recv[rb] = op(send[rb], got)`` — bitwise the
    seeded ``arr[rb] = op(arr[rb], got)``).

    Chunked (``coll_persist_chunk_bytes`` > 0): each ring block splits
    into m sub-chunks; chunk c's reduce-scatter rounds are
    ``Round(wait=True)`` so they resume on their own completion while
    chunk c-1's one-round linear allgather (``ordered=False``) is still
    in flight — the cross-phase overlap. Sub-chunking within blocks
    keeps every element's reduction chain identical."""
    n, r = comm.size, comm.rank
    npdt = dt.np_dtype
    isz = npdt.itemsize
    k = count // n  # elements per ring block (count % n == 0 gated)
    styped = spin.view.view(npdt)
    rtyped = rpin.view.view(npdt)
    left, right = (r - 1) % n, (r + 1) % n
    cb = int(_chunk_var._value)
    m = 1
    if cb > 0 and k * isz > cb:
        m = min(-(-(k * isz) // cb), k)
    bounds = [k * c // m for c in range(m + 1)]
    b = _Builder()
    if spin.pre:
        b.do(spin.pre)

    def bslice(typed, blk, c0, c1):
        return typed[blk * k + c0:blk * k + c1]

    def fold(dst, src, got, _op=op):
        dst[:] = _np_reduce_typed(_op, src, got)

    for c in range(m):
        c0, c1 = bounds[c], bounds[c + 1]
        ke = c1 - c0
        if ke == 0:
            continue
        stage = b.block(ke * isz)
        gtyped = stage.view(npdt)
        for t in range(n - 1):  # reduce-scatter phase
            sb, rb = (r - t) % n, (r - t - 1) % n
            # step 0 sends the local contribution straight from the
            # pinned send view; later steps send the partial the
            # previous fold wrote into the receive view — bitwise the
            # seeded ad-hoc accumulator, without the per-Start seed copy
            src = styped if t == 0 else rtyped
            send = bslice(src, sb, c0, c1).view(np.uint8)
            if m == 1:
                b.rnd(sends=[(send, right)],
                      recvs=[(ke * isz, left, stage)])
            else:
                if c > 0:
                    b.overlap += 1
                b.rnd(sends=[(send, right)],
                      recvs=[(ke * isz, left, stage)],
                      ordered=False, wait=True, chunk=c)
            b.do(lambda _d=bslice(rtyped, rb, c0, c1),
                 _s=bslice(styped, rb, c0, c1), _g=gtyped, _f=fold:
                 _f(_d, _s, _g))
        if m == 1:
            # ring allgather, wire-identical to the ad-hoc schedule:
            # forward the block received last round, land direct
            for t in range(n - 1, 2 * n - 2):
                ag = t - (n - 1)
                sb, rb = (r + 1 - ag) % n, (r - ag) % n
                b.rnd(sends=[(bslice(rtyped, sb, c0, c1).view(np.uint8),
                              right)],
                      recvs=[(ke * isz, left,
                              bslice(rtyped, rb, c0, c1).view(np.uint8))])
        else:
            # linear allgather: my fully-reduced block to every peer,
            # every other block straight into its final slice — all
            # independent, one unordered round left in flight while the
            # next chunk's reduce-scatter proceeds. The phase rides
            # QoS class BULK on tag sub-plane 1: the shaped tcp btl
            # may then serve the NEXT chunk's reduce-scatter frames
            # (the critical path) ahead of this completion traffic
            # instead of serializing the phases FIFO on the wire — and
            # the distinct tag plane keeps the cross-class reorder
            # away from the reduce-scatter matching (same peer, same
            # schedule, equal sizes). Unshaped jobs ignore the class;
            # the plane split is symmetric either way, so results stay
            # bitwise-equal across btl_tcp_shape_enable=0/1.
            own = (r + 1) % n
            if c > 0:
                b.overlap += 1
            b.rnd(sends=[(bslice(rtyped, own, c0, c1).view(np.uint8), p)
                         for p in range(n) if p != r],
                  recvs=[(ke * isz, (blk - 1) % n,
                          bslice(rtyped, blk, c0, c1).view(np.uint8))
                         for blk in range(n) if blk != own],
                  ordered=False, qos=_qos_mod.BULK, plane=1, chunk=c)
    if m > 1:
        b.rnd()  # request-less ordered round: drain the window
    if rpin.post:
        b.do(rpin.post)
    tag = "persist/ring" if m == 1 else f"persist/ring_pipelined[{m}]"
    return b, tag


def _b_allgather(comm, sendbuf, recvbuf):
    """Mirror NbcColl.iallgather: bruck under allgather_small_msg,
    ring above — both with frozen rounds."""
    n, r = comm.size, comm.rank
    ps = _pin(sendbuf, writable=False)
    pr = _pin(recvbuf, writable=True)
    if ps is None or pr is None:
        return None
    spin, scount, sdt = ps
    rpin, rcount, rdt = pr
    nb = scount * sdt.size
    total = rcount * rdt.size
    if total != n * nb:
        return None
    b = _Builder()
    if spin.pre:
        b.do(spin.pre)
    if total <= get_var("coll_tuned", "allgather_small_msg") and n > 1:
        acc = b.block(n * nb)
        b.do(lambda _a=acc, _s=spin.view, _nb=nb:
             (_a.__setitem__(slice(0, _nb), _s),
              _sched.note_copied(_nb))[0])
        dist = 1
        while dist < n:
            cnt = min(dist, n - dist)
            b.rnd(sends=[(acc[:cnt * nb], (r - dist) % n)],
                  recvs=[(cnt * nb, (r + dist) % n,
                          acc[dist * nb:(dist + cnt) * nb])])
            dist <<= 1

        def rotate(_a=acc, _o=rpin.view, _nb=nb, _n=n, _r=r):
            for i in range(_n):
                src = (_r + i) % _n
                _o[src * _nb:(src + 1) * _nb] = _a[i * _nb:(i + 1) * _nb]
            _sched.note_copied(_n * _nb)

        b.do(rotate)
        prov = "persist/bruck"
    else:
        out = rpin.view
        b.do(lambda _o=out, _s=spin.view, _r=r, _nb=nb:
             (_o.__setitem__(slice(_r * _nb, (_r + 1) * _nb), _s),
              _sched.note_copied(_nb))[0])
        cur = out[r * nb:(r + 1) * nb]
        for d in range(1, n):
            src = (r - d) % n
            slot = out[src * nb:(src + 1) * nb]
            b.rnd(sends=[(cur, (r + 1) % n)], recvs=[(nb, (r - 1) % n,
                                                      slot)])
            cur = slot
        prov = "persist/ring"
    if rpin.post:
        b.do(rpin.post)
    return b, prov


def _b_allgatherv(comm, sendbuf, recvbuf, counts, displs):
    """Mirror alg.allgatherv_ring with frozen per-source slices."""
    n, r = comm.size, comm.rank
    ps = _pin(sendbuf, writable=False)
    pr = _pin(recvbuf, writable=True)
    if ps is None or pr is None:
        return None
    spin, scount, sdt = ps
    rpin, rcount, rdt = pr
    counts = [int(c) for c in counts]
    if displs is None:
        displs = np.cumsum([0] + counts[:-1]).tolist()
    displs = [int(d) for d in displs]
    esz = rdt.size
    if scount * sdt.size != counts[r] * esz:
        return None
    out = rpin.view
    if any(displs[i] * esz + counts[i] * esz > out.nbytes
           for i in range(n)):
        return None
    b = _Builder()
    if spin.pre:
        b.do(spin.pre)
    nb_own = counts[r] * esz
    b.do(lambda _o=out, _s=spin.view, _d=displs[r] * esz, _nb=nb_own:
         (_o.__setitem__(slice(_d, _d + _nb), _s),
          _sched.note_copied(_nb))[0])
    cur = out[displs[r] * esz:displs[r] * esz + nb_own]
    for d in range(1, n):
        src = (r - d) % n
        nb_src = counts[src] * esz
        slot = out[displs[src] * esz:displs[src] * esz + nb_src]
        b.rnd(sends=[(cur, (r + 1) % n)],
              recvs=[(nb_src, (r - 1) % n, slot)])
        cur = slot
    if rpin.post:
        b.do(rpin.post)
    return b, "persist/ring"


def _b_alltoall(comm, sendbuf, recvbuf):
    """Mirror alg.alltoall_pairwise: n-1 independent unordered rounds
    over frozen slices."""
    n, r = comm.size, comm.rank
    ps = _pin(sendbuf, writable=False)
    pr = _pin(recvbuf, writable=True)
    if ps is None or pr is None:
        return None
    spin, scount, sdt = ps
    rpin, rcount, rdt = pr
    if scount * sdt.size != rcount * rdt.size or \
            (scount * sdt.size) % n != 0:
        return None
    nb = scount * sdt.size // n
    b = _Builder()
    if spin.pre:
        b.do(spin.pre)
    b.do(lambda _o=rpin.view, _s=spin.view, _r=r, _nb=nb:
         (_o.__setitem__(slice(_r * _nb, (_r + 1) * _nb),
                         _s[_r * _nb:(_r + 1) * _nb]),
          _sched.note_copied(_nb))[0])
    for d in range(1, n):
        dst, src = (r + d) % n, (r - d) % n
        b.rnd(sends=[(spin.view[dst * nb:(dst + 1) * nb], dst)],
              recvs=[(nb, src, rpin.view[src * nb:(src + 1) * nb])],
              ordered=False)
    if rpin.post:
        b.do(rpin.post)
    return b, "persist/pairwise"


def _b_alltoallv(comm, sendbuf, recvbuf, sendcounts, sdispls,
                 recvcounts, rdispls):
    """Mirror alg.alltoallv_pairwise with frozen per-peer slices."""
    n, r = comm.size, comm.rank
    ps = _pin(sendbuf, writable=False)
    pr = _pin(recvbuf, writable=True)
    if ps is None or pr is None:
        return None
    spin, scount, sdt = ps
    rpin, rcount, rdt = pr
    sc = [int(c) for c in sendcounts]
    sd = [int(d) for d in sdispls]
    rc = [int(c) for c in recvcounts]
    rd = [int(d) for d in rdispls]
    se, re_ = sdt.size, rdt.size
    if any((sd[i] + sc[i]) * se > spin.view.nbytes for i in range(n)) or \
            any((rd[i] + rc[i]) * re_ > rpin.view.nbytes
                for i in range(n)):
        return None
    b = _Builder()
    if spin.pre:
        b.do(spin.pre)
    nb_own = sc[r] * se
    if nb_own != rc[r] * re_:
        return None
    b.do(lambda _o=rpin.view, _s=spin.view, _so=sd[r] * se,
         _do=rd[r] * re_, _nb=nb_own:
         (_o.__setitem__(slice(_do, _do + _nb), _s[_so:_so + _nb]),
          _sched.note_copied(_nb))[0])
    for d in range(1, n):
        dst, src = (r + d) % n, (r - d) % n
        chunk = spin.view[sd[dst] * se:(sd[dst] + sc[dst]) * se]
        nb_src = rc[src] * re_
        b.rnd(sends=[(chunk, dst)],
              recvs=[(nb_src, src,
                      rpin.view[rd[src] * re_:rd[src] * re_ + nb_src])],
              ordered=False)
    if rpin.post:
        b.do(rpin.post)
    return b, "persist/pairwise"


def _b_gather(comm, sendbuf, recvbuf, root):
    return _gatherv_impl(comm, sendbuf, recvbuf, None, None, root)


def _b_gatherv(comm, sendbuf, recvbuf, counts, displs, root):
    return _gatherv_impl(comm, sendbuf, recvbuf, counts, displs, root)


def _gatherv_impl(comm, sendbuf, recvbuf, counts, displs, root):
    """Mirror alg.gather_linear / basic gatherv: non-roots send their
    pinned block; the root fans n-1 direct recvs into frozen slices."""
    n, r = comm.size, comm.rank
    ps = _pin(sendbuf, writable=False)
    if ps is None:
        return None
    spin, scount, sdt = ps
    nb = scount * sdt.size
    b = _Builder()
    if r != root:
        if spin.pre:
            b.do(spin.pre)
        b.rnd(sends=[(spin.view, root)])
        return b, "persist/linear"
    pr = _pin(recvbuf, writable=True)
    if pr is None:
        return None
    rpin, rcount, rdt = pr
    esz = rdt.size
    if counts is None:
        sizes = [nb] * n
        offs = [i * nb for i in range(n)]
    else:
        counts = [int(c) for c in counts]
        if displs is None:
            displs = np.cumsum([0] + counts[:-1]).tolist()
        sizes = [int(c) * esz for c in counts]
        offs = [int(d) * esz for d in displs]
    if any(offs[i] + sizes[i] > rpin.view.nbytes for i in range(n)) or \
            sizes[root] != nb:
        return None
    if spin.pre:
        b.do(spin.pre)
    others = [i for i in range(n) if i != root]
    b.rnd(recvs=[(sizes[i], i,
                  rpin.view[offs[i]:offs[i] + sizes[i]])
                 for i in others])
    b.do(lambda _o=rpin.view, _s=spin.view, _off=offs[root], _nb=nb:
         (_o.__setitem__(slice(_off, _off + _nb), _s),
          _sched.note_copied(_nb))[0])
    if rpin.post:
        b.do(rpin.post)
    return b, "persist/linear"


def _b_scatter(comm, sendbuf, recvbuf, root):
    return _scatterv_impl(comm, sendbuf, recvbuf, None, None, root)


def _b_scatterv(comm, sendbuf, recvbuf, counts, displs, root):
    return _scatterv_impl(comm, sendbuf, recvbuf, counts, displs, root)


def _scatterv_impl(comm, sendbuf, recvbuf, counts, displs, root):
    """Mirror alg.scatter_linear / basic scatterv: the root's one send
    round of frozen slices; non-roots land direct."""
    n, r = comm.size, comm.rank
    pr = _pin(recvbuf, writable=True)
    if pr is None:
        return None
    rpin, rcount, rdt = pr
    nb = rcount * rdt.size
    b = _Builder()
    if r != root:
        b.rnd(recvs=[(nb, root, rpin.view)])
        if rpin.post:
            b.do(rpin.post)
        return b, "persist/linear"
    ps = _pin(sendbuf, writable=False)
    if ps is None:
        return None
    spin, scount, sdt = ps
    esz = sdt.size
    if counts is None:
        sizes = [nb] * n
        offs = [i * nb for i in range(n)]
    else:
        counts = [int(c) for c in counts]
        if displs is None:
            displs = np.cumsum([0] + counts[:-1]).tolist()
        sizes = [int(c) * esz for c in counts]
        offs = [int(d) * esz for d in displs]
    if any(offs[i] + sizes[i] > spin.view.nbytes for i in range(n)) or \
            sizes[root] != nb:
        return None
    if spin.pre:
        b.do(spin.pre)
    b.do(lambda _o=rpin.view, _s=spin.view, _off=offs[root], _nb=nb:
         (_o.__setitem__(slice(None), _s[_off:_off + _nb]),
          _sched.note_copied(_nb))[0])
    sends = [(spin.view[offs[i]:offs[i] + sizes[i]], i)
             for i in range(n) if i != root]
    if sends:
        b.rnd(sends=sends)
    if rpin.post:
        b.do(rpin.post)
    return b, "persist/linear"


def _b_reduce_scatter_block(comm, sendbuf, recvbuf, op):
    """Mirror alg.reduce_scatter_block_sched: frozen reduce into a held
    tmp at root 0 composed with a frozen scatter out of it."""
    n, r = comm.size, comm.rank
    pr = _pin(recvbuf, writable=True)
    if pr is None:
        return None
    rpin, rcount, rdt = pr
    if rdt.np_dtype is None:
        return None
    ps = _pin(recvbuf if sendbuf is None else sendbuf, writable=False)
    if ps is None:
        return None
    spin, scount, sdt = ps
    if scount != rcount * n:
        return None
    nb = rcount * rdt.size
    b = _Builder()
    # only the root folds into (and scatters out of) the staging
    # buffer; a non-root holding an n*nb block would pin pool memory
    # for the request's lifetime without ever touching it
    tmp = b.block(n * nb) if r == 0 else None
    _reduce_into(b, comm, spin, tmp, op, 0, scount, sdt)
    if r == 0:
        b.do(lambda _o=rpin.view, _t=tmp, _nb=nb:
             (_o.__setitem__(slice(None), _t[:_nb]),
              _sched.note_copied(_nb))[0])
        sends = [(tmp[i * nb:(i + 1) * nb], i) for i in range(1, n)]
        if sends:
            b.rnd(sends=sends)
    else:
        b.rnd(recvs=[(nb, 0, rpin.view)])
    if rpin.post:
        b.do(rpin.post)
    return b, "persist/reduce+scatter"


def _b_scan(comm, sendbuf, recvbuf, op):
    """Mirror alg.scan_linear: rank-ordered prefix chain."""
    n, r = comm.size, comm.rank
    pr = _pin(recvbuf, writable=True)
    if pr is None:
        return None
    rpin, count, dt = pr
    if dt.np_dtype is None:
        return None
    spin = rpin if sendbuf is None else (_pin(sendbuf, False) or
                                         (None,))[0]
    if spin is None:
        return None
    nbytes = count * dt.size
    b = _Builder()
    if spin.pre:
        b.do(spin.pre)
    acc = b.block(nbytes)
    if r > 0:
        stage = b.block(nbytes)
        b.rnd(recvs=[(nbytes, r - 1, stage)])
        b.do(lambda _a=acc, _g=stage, _s=spin.view, _op=op, _dt=dt:
             _a.__setitem__(slice(None), _as_bytes(np.ascontiguousarray(
                 _np_reduce_typed(_op, _typed_view(_g, _dt),
                                  _typed_view(_s, _dt))))))
    else:
        b.do(lambda _a=acc, _s=spin.view:
             _a.__setitem__(slice(None), _s))
    if r < n - 1:
        b.rnd(sends=[(acc, r + 1)])
    b.do(lambda _r=rpin.view, _a=acc: _r.__setitem__(slice(None), _a))
    if rpin.post:
        b.do(rpin.post)
    return b, "persist/linear"


def _b_exscan(comm, sendbuf, recvbuf, op):
    """Mirror alg.exscan_linear (recvbuf undefined at rank 0)."""
    n, r = comm.size, comm.rank
    pr = _pin(recvbuf, writable=True)
    if pr is None:
        return None
    rpin, count, dt = pr
    if dt.np_dtype is None:
        return None
    spin = rpin if sendbuf is None else (_pin(sendbuf, False) or
                                         (None,))[0]
    if spin is None:
        return None
    nbytes = count * dt.size
    b = _Builder()
    if spin.pre:
        b.do(spin.pre)
    stage = None
    if r > 0:
        stage = b.block(nbytes)
        b.rnd(recvs=[(nbytes, r - 1, stage)])
    if r < n - 1:
        if r == 0:
            b.rnd(sends=[(spin.view, r + 1)])
        else:
            nxt = b.block(nbytes)
            b.do(lambda _x=nxt, _g=stage, _s=spin.view, _op=op, _dt=dt:
                 _x.__setitem__(slice(None), _as_bytes(
                     np.ascontiguousarray(_np_reduce_typed(
                         _op, _typed_view(_g.copy(), _dt),
                         _typed_view(_s, _dt))))))
            b.rnd(sends=[(nxt, r + 1)])
    if r > 0:
        b.do(lambda _r=rpin.view, _g=stage:
             _r.__setitem__(slice(None), _g))
        if rpin.post:
            b.do(rpin.post)
    return b, "persist/linear"


_BUILDERS = {
    "ibarrier": _b_barrier,
    "ibcast": _b_bcast,
    "ireduce": _b_reduce,
    "iallreduce": _b_allreduce,
    "iallgather": _b_allgather,
    "iallgatherv": _b_allgatherv,
    "ialltoall": _b_alltoall,
    "ialltoallv": _b_alltoallv,
    "igather": _b_gather,
    "igatherv": _b_gatherv,
    "iscatter": _b_scatter,
    "iscatterv": _b_scatterv,
    "ireduce_scatter_block": _b_reduce_scatter_block,
    "iscan": _b_scan,
    "iexscan": _b_exscan,
}
