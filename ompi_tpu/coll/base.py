"""Collectives framework: per-communicator function table + selection.

Reference: ompi/mca/coll (13,883 LoC base) — every component queries per
communicator and the highest-priority module wins *per function slot*
(coll_base_comm_select.c:216, priority sort :358). Identical model here:
``select_coll(comm)`` queries every registered component and fills a
``CollTable`` one slot at a time from the priority-ordered module list.
"""

from __future__ import annotations

from typing import Any, List, Optional

from ompi_tpu.mca.component import framework

coll_framework = framework("coll", "Collective operations")

# The 17-op surface (reference: coll.h:545-620, blocking slots; nonblocking
# variants share the table via the I-prefix dispatch in the communicator).
COLL_OPS = (
    "allgather",
    "allgatherv",
    "allreduce",
    "alltoall",
    "alltoallv",
    "alltoallw",
    "barrier",
    "bcast",
    "exscan",
    "gather",
    "gatherv",
    "reduce",
    "reduce_scatter",
    "reduce_scatter_block",
    "scan",
    "scatter",
    "scatterv",
    # neighborhood collectives (reference: coll.h neighbor_* slots)
    "neighbor_allgather",
    "neighbor_alltoall",
    # nonblocking variants (reference: coll.h pairs every blocking slot
    # with an i-slot in the same table; coll/libnbc provides them)
    "ibarrier",
    "ibcast",
    "ireduce",
    "iallreduce",
    "iallgather",
    "iallgatherv",
    "ialltoall",
    "ialltoallv",
    "igather",
    "igatherv",
    "iscatter",
    "iscatterv",
    "ireduce_scatter_block",
    "iscan",
    "iexscan",
)


class CollModule:
    """Base collectives module: components subclass and implement the slots
    they can serve for the queried communicator."""

    def enable(self, comm) -> None:
        pass


class CollTable:
    """Per-communicator function table (reference: comm->c_coll)."""

    def __init__(self):
        self.slots = {}
        self.providers = {}  # op -> component name, for introspection
        # op -> the FULL priority-ordered list of losing modules' fns for
        # slots a higher-priority module won (reference keeps the whole
        # priority-ordered module list on the comm). Conditional
        # components (coll/quant, coll/hier) route ineligible calls down
        # this chain so winning a slot can't silently downgrade the rest
        # of the traffic to tuned/basic — and with more than one
        # conditional component contesting a slot (quant over hier over
        # han), a single runner-up entry would make the second delegation
        # re-enter the module that just declined.
        self.fallbacks = {}           # op -> [fn, ...] after the winner
        self.fallback_providers = {}  # op -> [component name, ...], ditto

    def get(self, op: str):
        fn = self.slots.get(op)
        if fn is None:
            raise NotImplementedError(
                f"no collective module provides '{op}' for this communicator"
            )
        return fn

    def next_after(self, op: str, name: str):
        """The fn of the module ranked immediately below component
        ``name`` in this slot's priority chain — the delegation target
        for a conditional component routing an ineligible call to
        whatever would own the slot had it not been selected. A caller
        that is not in the chain (or is the winner) gets the first
        fallback. Raises KeyError when nothing ranks below the caller
        (coll/basic provides every op, so that is an invariant
        violation worth surfacing loudly)."""
        names = self.fallback_providers.get(op, [])
        fns = self.fallbacks.get(op, [])
        if name in names:
            # each component appears at most once per slot (one module
            # per component in _select_coll), so the next entry is it
            i = names.index(name) + 1
            if i < len(fns):
                return fns[i]
            raise KeyError(
                f"no module ranks below '{name}' for slot '{op}'")
        if not fns:
            raise KeyError(f"no fallback chain recorded for slot '{op}'")
        return fns[0]


def select_coll(comm) -> CollTable:
    """Build the per-comm table: highest priority module wins each slot."""
    from ompi_tpu.runtime import trace as _trace

    if _trace.enabled():
        with _trace.span("coll.select", cat="coll",
                         comm=getattr(comm, "name", "")):
            return _select_coll(comm)
    return _select_coll(comm)


def _select_coll(comm) -> CollTable:
    table = CollTable()
    modules = coll_framework.select_all(comm=comm)  # priority-descending
    for prio, name, module in modules:
        module.enable(comm)
        for op in COLL_OPS:
            fn = getattr(module, op, None)
            if fn is None:
                continue
            if op in table.slots:
                table.fallbacks.setdefault(op, []).append(fn)
                table.fallback_providers.setdefault(op, []).append(name)
            else:
                table.slots[op] = fn
                table.providers[op] = name
    return table
