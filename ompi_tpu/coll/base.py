"""Collectives framework: per-communicator function table + selection.

Reference: ompi/mca/coll (13,883 LoC base) — every component queries per
communicator and the highest-priority module wins *per function slot*
(coll_base_comm_select.c:216, priority sort :358). Identical model here:
``select_coll(comm)`` queries every registered component and fills a
``CollTable`` one slot at a time from the priority-ordered module list.
"""

from __future__ import annotations

from typing import Any, List, Optional

from ompi_tpu.mca.component import framework

coll_framework = framework("coll", "Collective operations")

# The 17-op surface (reference: coll.h:545-620, blocking slots; nonblocking
# variants share the table via the I-prefix dispatch in the communicator).
COLL_OPS = (
    "allgather",
    "allgatherv",
    "allreduce",
    "alltoall",
    "alltoallv",
    "alltoallw",
    "barrier",
    "bcast",
    "exscan",
    "gather",
    "gatherv",
    "reduce",
    "reduce_scatter",
    "reduce_scatter_block",
    "scan",
    "scatter",
    "scatterv",
    # neighborhood collectives (reference: coll.h neighbor_* slots)
    "neighbor_allgather",
    "neighbor_alltoall",
    # nonblocking variants (reference: coll.h pairs every blocking slot
    # with an i-slot in the same table; coll/libnbc provides them)
    "ibarrier",
    "ibcast",
    "ireduce",
    "iallreduce",
    "iallgather",
    "iallgatherv",
    "ialltoall",
    "igather",
    "iscatter",
    "ireduce_scatter_block",
    "iscan",
    "iexscan",
)


class CollModule:
    """Base collectives module: components subclass and implement the slots
    they can serve for the queried communicator."""

    def enable(self, comm) -> None:
        pass


class CollTable:
    """Per-communicator function table (reference: comm->c_coll)."""

    def __init__(self):
        self.slots = {}
        self.providers = {}  # op -> component name, for introspection
        # op -> the next-best module's fn for slots a higher-priority
        # module won (reference keeps the whole priority-ordered module
        # list on the comm; conditional components — coll/quant — route
        # ineligible calls here so winning a slot can't silently
        # downgrade the rest of the traffic to tuned/basic)
        self.fallbacks = {}
        self.fallback_providers = {}  # op -> component name, ditto

    def get(self, op: str):
        fn = self.slots.get(op)
        if fn is None:
            raise NotImplementedError(
                f"no collective module provides '{op}' for this communicator"
            )
        return fn


def select_coll(comm) -> CollTable:
    """Build the per-comm table: highest priority module wins each slot."""
    from ompi_tpu.runtime import trace as _trace

    if _trace.enabled():
        with _trace.span("coll.select", cat="coll",
                         comm=getattr(comm, "name", "")):
            return _select_coll(comm)
    return _select_coll(comm)


def _select_coll(comm) -> CollTable:
    table = CollTable()
    modules = coll_framework.select_all(comm=comm)  # priority-descending
    for prio, name, module in modules:
        module.enable(comm)
        for op in COLL_OPS:
            fn = getattr(module, op, None)
            if fn is None:
                continue
            if op in table.slots:
                if op not in table.fallbacks:
                    table.fallbacks[op] = fn
                    table.fallback_providers[op] = name
            else:
                table.slots[op] = fn
                table.providers[op] = name
    return table
