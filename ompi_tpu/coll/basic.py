"""coll/basic — host collectives over the PML for process-mode comms.

Reference: ompi/mca/coll/basic (fallback linear algorithms, 4,885 LoC) plus
selected schedules from coll/base (binomial bcast coll_base_bcast.c,
dissemination barrier, ring allgather coll_base_allgather.c). These carry
MPI completeness on the host/DCN path; device bulk data rides coll/xla.

All payloads move as packed wire bytes (the convertor handles arbitrary
datatypes), so every algorithm is datatype-agnostic. Reductions view the
packed stream with the datatype's numpy dtype (homogeneous typemaps) or a
structured pair dtype (MINLOC/MAXLOC).

Tag/context discipline: collective traffic runs in a separate context-id
plane (cid | COLL_CID_BIT) with per-op negative tags — the reference
separates collective from pt2pt traffic the same way (hidden coll context
ids; MCA_COLL_BASE_TAG_* constants).
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

from ompi_tpu.coll.base import CollModule, coll_framework
from ompi_tpu.comm.communicator import parse_buffer
from ompi_tpu.core import op as _op
from ompi_tpu.core.convertor import (
    _as_byte_view as _as_bytes,
    pack as cv_pack,
    unpack as cv_unpack,
)
from ompi_tpu.core.datatype import BYTE, Datatype
from ompi_tpu.core.errors import MPIError, ERR_UNSUPPORTED_OPERATION
from ompi_tpu.mca.component import Component

COLL_CID_BIT = 1 << 30

TAG_BARRIER = -10
TAG_BCAST = -11
TAG_REDUCE = -12
TAG_ALLGATHER = -13
TAG_ALLTOALL = -14
TAG_SCATTER = -15
TAG_GATHER = -16
TAG_SCAN = -17


def _ccid(comm) -> int:
    return comm.cid | COLL_CID_BIT


def _isend(comm, data: np.ndarray, dst: int, tag: int):
    return comm.pml.isend(data, data.nbytes, BYTE,
                          comm.group.world_rank(dst), tag, _ccid(comm))


def _irecv(comm, nbytes: int, src: int, tag: int):
    buf = np.empty(nbytes, dtype=np.uint8)
    req = comm.pml.irecv(buf, nbytes, BYTE,
                         comm.group.world_rank(src), tag, _ccid(comm))
    return buf, req


def _sendrecv(comm, data: np.ndarray, dst: int, nbytes: int, src: int,
              tag: int) -> np.ndarray:
    rbuf, rreq = _irecv(comm, nbytes, src, tag)
    sreq = _isend(comm, data, dst, tag)
    sreq.Wait()
    rreq.Wait()
    return rbuf


def _typed_view(raw: np.ndarray, dt: Datatype) -> np.ndarray:
    """View packed bytes with the datatype's element dtype for reductions."""
    if dt.np_dtype is not None:
        return raw.view(dt.np_dtype)
    kinds = {d for d, _ in dt.typemap}
    if len(kinds) == 1:
        return raw.view(next(iter(kinds)))
    if len(dt.typemap) == 2:  # value/index pair types (MINLOC/MAXLOC)
        f0, f1 = dt.typemap[0][0], dt.typemap[1][0]
        pair = np.dtype([("f0", f0), ("f1", f1)])
        return raw.view(pair)
    raise MPIError(ERR_UNSUPPORTED_OPERATION,
                   "reduction on heterogeneous derived datatype")


def _np_reduce_typed(op: _op.Op, a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """op.np_reduce with the operand dtype restored: logical ufuncs
    (np.logical_and/or/xor) return bool arrays, but MPI keeps the integer
    type (reference: op kernels are typed per dtype) — without the cast the
    byte-view downstream shrinks to 1 byte/element and unpack truncates."""
    out = op.np_reduce(a, b)
    return out.astype(a.dtype) if out.dtype != a.dtype else out


class BasicColl(CollModule):
    # -------------------------------------------------------------- barrier
    def barrier(self, comm) -> None:
        """Dissemination barrier: ceil(log2 n) zero-byte rounds
        (reference: the recursive-doubling barrier of coll/base)."""
        n, r = comm.size, comm.rank
        d = 1
        token = np.zeros(0, dtype=np.uint8)
        while d < n:
            dst = (r + d) % n
            src = (r - d) % n
            _sendrecv(comm, token, dst, 0, src, TAG_BARRIER)
            d <<= 1

    # ---------------------------------------------------------------- bcast
    def bcast(self, comm, buf, root: int) -> None:
        """Binomial tree (reference: coll_base_bcast.c binomial)."""
        n, r = comm.size, comm.rank
        obj, count, dt = parse_buffer(buf)
        nbytes = count * dt.size
        vrank = (r - root) % n
        data: Optional[np.ndarray] = None
        if vrank == 0:
            data = np.ascontiguousarray(cv_pack(obj, count, dt))
        mask = 1
        while mask < n:
            if vrank & mask:
                src = (vrank - mask + root) % n
                rbuf, rreq = _irecv(comm, nbytes, src, TAG_BCAST)
                rreq.Wait()
                data = rbuf
                break
            mask <<= 1
        mask >>= 1
        reqs = []
        while mask > 0:
            if vrank + mask < n and not (vrank & mask):
                dst = (vrank + mask + root) % n
                reqs.append(_isend(comm, data, dst, TAG_BCAST))
            mask >>= 1
        for q in reqs:
            q.Wait()
        if vrank != 0:
            cv_unpack(data, obj, count, dt)

    # --------------------------------------------------------------- reduce
    def reduce(self, comm, sendbuf, recvbuf, op: _op.Op, root: int) -> None:
        """Linear fan-in applying op in ascending rank order (correct for
        non-commutative ops — reference: coll/basic linear reduce)."""
        n, r = comm.size, comm.rank
        src_buf = recvbuf if sendbuf is None else sendbuf  # IN_PLACE
        obj, count, dt = parse_buffer(src_buf)
        packed = np.ascontiguousarray(cv_pack(obj, count, dt))
        if r != root:
            _isend(comm, packed, root, TAG_REDUCE).Wait()
            return
        contributions: List[Optional[np.ndarray]] = [None] * n
        contributions[r] = packed
        pend = []
        for i in range(n):
            if i != root:
                rbuf, rreq = _irecv(comm, packed.nbytes, i, TAG_REDUCE)
                pend.append(rreq)
                contributions[i] = rbuf
        for q in pend:
            q.Wait()
        acc = _typed_view(contributions[0].copy(), dt)
        for i in range(1, n):
            acc = _np_reduce_typed(op, acc, _typed_view(contributions[i], dt))
        robj, rcount, rdt = parse_buffer(recvbuf)
        cv_unpack(np.ascontiguousarray(acc).view(np.uint8), robj, rcount, rdt)

    def allreduce(self, comm, sendbuf, recvbuf, op: _op.Op) -> None:
        """reduce + bcast (reference: coll/basic; tuned schedules replace
        this for large sizes)."""
        self.reduce(comm, sendbuf, recvbuf, op, 0)
        self.bcast(comm, recvbuf, 0)

    # ------------------------------------------------------------ allgather
    def allgather(self, comm, sendbuf, recvbuf) -> None:
        """Ring (reference: coll_base_allgather.c ring): n-1 rounds, each
        forwarding the block received last round."""
        n, r = comm.size, comm.rank
        sobj, scount, sdt = parse_buffer(sendbuf)
        robj, rcount, rdt = parse_buffer(recvbuf)
        block = np.ascontiguousarray(cv_pack(sobj, scount, sdt))
        nb = block.nbytes
        out = np.empty(n * nb, dtype=np.uint8)
        out[r * nb : (r + 1) * nb] = block
        cur = block
        for d in range(1, n):
            cur = _sendrecv(comm, cur, (r + 1) % n, nb, (r - 1) % n,
                            TAG_ALLGATHER)
            out[((r - d) % n) * nb : ((r - d) % n + 1) * nb] = cur
        cv_unpack(out, robj, rcount, rdt)

    def allgatherv(self, comm, sendbuf, recvbuf, counts, displs) -> None:
        n, r = comm.size, comm.rank
        sobj, scount, sdt = parse_buffer(sendbuf)
        robj, rcount, rdt = parse_buffer(recvbuf)
        counts = list(counts)
        if displs is None:
            displs = np.cumsum([0] + counts[:-1]).tolist()
        block = np.ascontiguousarray(cv_pack(sobj, scount, sdt))
        esz = rdt.size
        out = np.zeros(rcount * esz, dtype=np.uint8)
        out[displs[r] * esz : displs[r] * esz + block.nbytes] = block
        cur = block
        for d in range(1, n):
            src_rank = (r - d) % n
            cur = _sendrecv(comm, cur, (r + 1) % n, counts[src_rank] * esz,
                            (r - 1) % n, TAG_ALLGATHER)
            off = displs[src_rank] * esz
            out[off : off + cur.nbytes] = cur
        cv_unpack(out, robj, rcount, rdt)

    # --------------------------------------------------------- gather/scatter
    def gather(self, comm, sendbuf, recvbuf, root: int) -> None:
        n, r = comm.size, comm.rank
        sobj, scount, sdt = parse_buffer(sendbuf)
        block = np.ascontiguousarray(cv_pack(sobj, scount, sdt))
        if r != root:
            _isend(comm, block, root, TAG_GATHER).Wait()
            return
        robj, rcount, rdt = parse_buffer(recvbuf)
        nb = block.nbytes
        out = np.empty(n * nb, dtype=np.uint8)
        out[r * nb : (r + 1) * nb] = block
        pend = []
        for i in range(n):
            if i != root:
                rb, rq = _irecv(comm, nb, i, TAG_GATHER)
                pend.append((i, rb, rq))
        for i, rb, rq in pend:
            rq.Wait()
            out[i * nb : (i + 1) * nb] = rb
        cv_unpack(out, robj, rcount, rdt)

    def gatherv(self, comm, sendbuf, recvbuf, counts, displs,
                root: int) -> None:
        n, r = comm.size, comm.rank
        sobj, scount, sdt = parse_buffer(sendbuf)
        block = np.ascontiguousarray(cv_pack(sobj, scount, sdt))
        if r != root:
            _isend(comm, block, root, TAG_GATHER).Wait()
            return
        robj, rcount, rdt = parse_buffer(recvbuf)
        counts = list(counts)
        if displs is None:
            displs = np.cumsum([0] + counts[:-1]).tolist()
        esz = rdt.size
        out = np.zeros(rcount * esz, dtype=np.uint8)
        out[displs[r] * esz : displs[r] * esz + block.nbytes] = block
        pend = []
        for i in range(n):
            if i != root:
                rb, rq = _irecv(comm, counts[i] * esz, i, TAG_GATHER)
                pend.append((i, rb, rq))
        for i, rb, rq in pend:
            rq.Wait()
            out[displs[i] * esz : displs[i] * esz + rb.nbytes] = rb
        cv_unpack(out, robj, rcount, rdt)

    def scatter(self, comm, sendbuf, recvbuf, root: int) -> None:
        n, r = comm.size, comm.rank
        robj, rcount, rdt = parse_buffer(recvbuf)
        nb = rcount * rdt.size
        if r == root:
            sobj, scount, sdt = parse_buffer(sendbuf)
            packed = np.ascontiguousarray(cv_pack(sobj, scount, sdt))
            reqs = []
            for i in range(n):
                chunk = packed[i * nb : (i + 1) * nb]
                if i == root:
                    cv_unpack(chunk, robj, rcount, rdt)
                else:
                    reqs.append(_isend(comm, np.ascontiguousarray(chunk),
                                       i, TAG_SCATTER))
            for q in reqs:
                q.Wait()
        else:
            rb, rq = _irecv(comm, nb, root, TAG_SCATTER)
            rq.Wait()
            cv_unpack(rb, robj, rcount, rdt)

    def scatterv(self, comm, sendbuf, recvbuf, counts, displs,
                 root: int) -> None:
        n, r = comm.size, comm.rank
        robj, rcount, rdt = parse_buffer(recvbuf)
        if r == root:
            sobj, scount, sdt = parse_buffer(sendbuf)
            counts = list(counts)
            if displs is None:
                displs = np.cumsum([0] + counts[:-1]).tolist()
            packed = np.ascontiguousarray(cv_pack(sobj, scount, sdt))
            esz = sdt.size
            reqs = []
            for i in range(n):
                chunk = packed[displs[i] * esz : (displs[i] + counts[i]) * esz]
                if i == root:
                    cv_unpack(chunk, robj, rcount, rdt)
                else:
                    reqs.append(_isend(comm, np.ascontiguousarray(chunk),
                                       i, TAG_SCATTER))
            for q in reqs:
                q.Wait()
        else:
            rb, rq = _irecv(comm, rcount * rdt.size, root, TAG_SCATTER)
            rq.Wait()
            cv_unpack(rb, robj, rcount, rdt)

    # ------------------------------------------------------------- alltoall
    def alltoall(self, comm, sendbuf, recvbuf) -> None:
        """Pairwise ring exchange (reference: coll_base_alltoall.c)."""
        n, r = comm.size, comm.rank
        sobj, scount, sdt = parse_buffer(sendbuf)
        robj, rcount, rdt = parse_buffer(recvbuf)
        packed = np.ascontiguousarray(cv_pack(sobj, scount, sdt))
        nb = packed.nbytes // n
        out = np.empty(packed.nbytes, dtype=np.uint8)
        out[r * nb : (r + 1) * nb] = packed[r * nb : (r + 1) * nb]
        for d in range(1, n):
            dst = (r + d) % n
            src = (r - d) % n
            chunk = np.ascontiguousarray(packed[dst * nb : (dst + 1) * nb])
            got = _sendrecv(comm, chunk, dst, nb, src, TAG_ALLTOALL)
            out[src * nb : (src + 1) * nb] = got
        cv_unpack(out, robj, rcount, rdt)

    def alltoallv(self, comm, sendbuf, recvbuf, sendcounts, sdispls,
                  recvcounts, rdispls) -> None:
        n, r = comm.size, comm.rank
        sobj, scount, sdt = parse_buffer(sendbuf)
        robj, rcount, rdt = parse_buffer(recvbuf)
        packed = np.ascontiguousarray(cv_pack(sobj, scount, sdt))
        se, re_ = sdt.size, rdt.size
        out = np.zeros(rcount * re_, dtype=np.uint8)
        own_s = packed[sdispls[r] * se : (sdispls[r] + sendcounts[r]) * se]
        out[rdispls[r] * re_ : rdispls[r] * re_ + own_s.nbytes] = own_s
        for d in range(1, n):
            dst = (r + d) % n
            src = (r - d) % n
            chunk = np.ascontiguousarray(
                packed[sdispls[dst] * se : (sdispls[dst] + sendcounts[dst]) * se])
            got = _sendrecv(comm, chunk, dst, recvcounts[src] * re_, src,
                            TAG_ALLTOALL)
            out[rdispls[src] * re_ : rdispls[src] * re_ + got.nbytes] = got
        cv_unpack(out, robj, rcount, rdt)

    def alltoallw(self, comm, sendbuf, recvbuf, sendcounts, sdispls,
                  sendtypes, recvcounts, rdispls, recvtypes) -> None:
        """MPI_Alltoallw: per-peer counts, BYTE displacements, and
        datatypes (the fully general exchange — reference:
        coll_basic_alltoallw.c; displacements are in bytes per the MPI
        spec, unlike alltoallv's element units)."""
        n, r = comm.size, comm.rank
        sobj, _, _ = parse_buffer(sendbuf)
        robj, _, _ = parse_buffer(recvbuf)
        sview = _as_bytes(sobj)
        rview = _as_bytes(robj)

        def _seg_len(dt, cnt: int) -> int:
            # full footprint incl. a leading true_lb gap (the convertor
            # gathers up to true_lb + true_extent - 1 on element 0)
            return max((cnt - 1) * dt.extent + dt.true_lb
                       + dt.true_extent, 0)

        def pack_block(dst: int) -> np.ndarray:
            dt = sendtypes[dst]
            cnt = sendcounts[dst]
            seg = sview[sdispls[dst] : sdispls[dst] + _seg_len(dt, cnt)]
            return np.ascontiguousarray(cv_pack(seg, cnt, dt))

        def unpack_block(src: int, data: np.ndarray) -> None:
            dt = recvtypes[src]
            cnt = recvcounts[src]
            seg = rview[rdispls[src] : rdispls[src] + _seg_len(dt, cnt)]
            cv_unpack(data, seg, cnt, dt)

        unpack_block(r, pack_block(r))
        for d in range(1, n):
            dst = (r + d) % n
            src = (r - d) % n
            got = _sendrecv(comm, pack_block(dst), dst,
                            recvcounts[src] * recvtypes[src].size, src,
                            TAG_ALLTOALL)
            unpack_block(src, got)

    # -------------------------------------------------------- reduce_scatter
    def reduce_scatter_block(self, comm, sendbuf, recvbuf,
                             op: _op.Op) -> None:
        n = comm.size
        robj, rcount, rdt = parse_buffer(recvbuf)
        tmp_obj = np.empty(rcount * n * max(rdt.extent, 1), dtype=np.uint8)
        tmp = [tmp_obj, rcount * n, rdt]
        self.reduce(comm, sendbuf, tmp, op, 0)
        self.scatter(comm, tmp, recvbuf, 0)

    def reduce_scatter(self, comm, sendbuf, recvbuf, recvcounts,
                       op: _op.Op) -> None:
        n, r = comm.size, comm.rank
        robj, rcount, rdt = parse_buffer(recvbuf)
        total = int(sum(recvcounts))
        tmp_obj = np.empty(total * max(rdt.extent, 1), dtype=np.uint8)
        tmp = [tmp_obj, total, rdt]
        self.reduce(comm, sendbuf, tmp, op, 0)
        self.scatterv(comm, tmp, recvbuf, recvcounts, None, 0)

    # ------------------------------------------------------------ scan/exscan
    def scan(self, comm, sendbuf, recvbuf, op: _op.Op) -> None:
        """Linear pipeline (reference: coll/basic scan — rank order is
        required for non-commutative correctness)."""
        n, r = comm.size, comm.rank
        src_buf = recvbuf if sendbuf is None else sendbuf
        obj, count, dt = parse_buffer(src_buf)
        packed = np.ascontiguousarray(cv_pack(obj, count, dt))
        if r > 0:
            rb, rq = _irecv(comm, packed.nbytes, r - 1, TAG_SCAN)
            rq.Wait()
            acc = _np_reduce_typed(op, _typed_view(rb, dt),
                                   _typed_view(packed.copy(), dt))
        else:
            acc = _typed_view(packed.copy(), dt)
        acc_bytes = np.ascontiguousarray(acc).view(np.uint8)
        if r < n - 1:
            _isend(comm, acc_bytes, r + 1, TAG_SCAN).Wait()
        robj, rcount, rdt = parse_buffer(recvbuf)
        cv_unpack(acc_bytes, robj, rcount, rdt)

    def exscan(self, comm, sendbuf, recvbuf, op: _op.Op) -> None:
        n, r = comm.size, comm.rank
        src_buf = recvbuf if sendbuf is None else sendbuf
        obj, count, dt = parse_buffer(src_buf)
        packed = np.ascontiguousarray(cv_pack(obj, count, dt))
        prefix: Optional[np.ndarray] = None
        if r > 0:
            rb, rq = _irecv(comm, packed.nbytes, r - 1, TAG_SCAN)
            rq.Wait()
            prefix = rb
        if r < n - 1:
            if prefix is None:
                nxt = packed
            else:
                nxt = np.ascontiguousarray(
                    _np_reduce_typed(op, _typed_view(prefix.copy(), dt),
                                     _typed_view(packed, dt))).view(np.uint8)
            _isend(comm, nxt, r + 1, TAG_SCAN).Wait()
        if prefix is not None:
            robj, rcount, rdt = parse_buffer(recvbuf)
            cv_unpack(prefix, robj, rcount, rdt)


_flat_singleton: Optional["BasicColl"] = None


def flat_module() -> "BasicColl":
    """The shared stateless BasicColl instance — the one flat-fallback
    module han/hier/decide delegate re-entrant or agreement traffic to
    (each caching its own copy just duplicated an allocation)."""
    global _flat_singleton
    if _flat_singleton is None:
        _flat_singleton = BasicColl()
    return _flat_singleton


class BasicCollComponent(Component):
    NAME = "basic"
    PRIORITY = 10  # fallback (reference: coll/basic priority 10)

    _module: Optional[BasicColl] = None

    def query(self, comm=None, **ctx):
        from ompi_tpu.comm.communicator import ProcComm

        if isinstance(comm, ProcComm):
            if BasicCollComponent._module is None:
                BasicCollComponent._module = BasicColl()
            return BasicCollComponent._module
        return None


coll_framework.register(BasicCollComponent())
