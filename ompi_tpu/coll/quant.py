"""coll/quant — block-scaled quantized collectives component.

Reference direction: EQuARX (arxiv 2506.17615) — near-2x XLA allreduce
speedups from block-scaled quantization with negligible quality loss —
packaged as a composable coll component (HiCCL's layering argument,
arxiv 2408.05962) so the existing per-communicator selection stack
picks it per message class instead of hard-wiring one verb.

Selection (the no-torn-collective invariant): the component queries the
*negotiated* per-communicator verdict (quant/negotiate.py — a pure
function over modex cards every member shares), never the local cvar.
A rank launched without ``quant_enable`` therefore de-selects the
module on EVERY rank; the disabled path costs nothing because the slot
stays with tuned/basic. With ``quant_strict``, a config mismatch keeps
the module selected in an error-armed state that raises the SAME
MPIError on every rank's quant-eligible call — mismatch surfaces as a
clean error, not a hang.

Two modules:

- :class:`QuantProcColl` (process mode) — quantize -> flat
  reduce-scatter exchange -> requantize -> allgather over the existing
  sched round machinery (coll/sched.py) in the collective CID plane.
  Accumulation is in ascending rank order and rounding is
  round-to-nearest-even, so results are bitwise-deterministic for a
  fixed (world, block, bits, mode) config and bitwise-identical to
  ``codec.simulate_allreduce``.
- :class:`QuantXlaColl` (mesh mode) — lowers to the jnp-native
  block-scaled body in coll/xla.py (``quant_allreduce_body``) so the
  compiled path stays ONE XLA program; the executable lands in the
  communicator's ``_jit_cache`` under the standard allreduce key, so
  XlaComm's resolved fast table serves it with the unchanged
  one-dict-hit prologue.

Ineligible calls (integer/pair dtypes, non-SUM ops, payloads under the
negotiated ``quant_min_bytes``) delegate to the module that would own
the slot had quant not been selected (``CollTable.fallbacks`` — e.g.
coll/sm on a single node, han across nodes, tuned otherwise) — which
also keeps every library-internal collective (CID agreement, Split's
allgather) exact.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from ompi_tpu.coll.base import CollModule, coll_framework
from ompi_tpu.coll.basic import COLL_CID_BIT
from ompi_tpu.coll.sched import Round, run_blocking
from ompi_tpu.comm.communicator import parse_buffer
from ompi_tpu.core import op as _op
from ompi_tpu.core.convertor import pack as cv_pack, unpack as cv_unpack
from ompi_tpu.core.errors import MPIError, ERR_UNSUPPORTED_OPERATION
from ompi_tpu.mca.component import Component
from ompi_tpu.quant import negotiate as _negotiate
from ompi_tpu.quant import note_coll as _note_coll
from ompi_tpu.quant.codec import _work_dtype, chunk_layout
from ompi_tpu.runtime import trace as _trace

TAG_QUANT = -35  # dedicated tag inside the collective CID plane

_QUANT_DTYPES = (np.dtype(np.float16), np.dtype(np.float32),
                 np.dtype(np.float64))


class QuantProcColl(CollModule):
    """Quantized allreduce / reduce_scatter_block / allgather for
    process-mode communicators; everything ineligible delegates."""

    def _delegate(self, comm, op_name: str):
        """Ineligible calls run on the module that would own this slot
        had quant not been selected (the CollTable fallback CHAIN —
        smcoll/han/hier/adaptive outrank tuned, so hard-wiring tuned
        here would silently downgrade every non-quantized collective on
        a quant-negotiated communicator). next_after walks the full
        priority-ordered chain: with hier also contesting the slot the
        runner-up is itself conditional, and it must delegate onward
        from ITS position instead of bouncing back here. coll/basic
        provides every op, so the chain is never empty for a slot quant
        won."""
        return comm.coll.next_after(op_name, "quant")

    # ------------------------------------------------------- eligibility
    @staticmethod
    def _eligible(st, dt, nbytes: int, op: Optional[_op.Op]) -> bool:
        if dt.np_dtype is None or dt.np_dtype not in _QUANT_DTYPES:
            return False
        if nbytes < st.min_bytes:
            return False
        if op is not None and not (op.name == "MPI_SUM" and op.commutative):
            return False
        return True

    @staticmethod
    def _check_armed(comm, st) -> None:
        if not st.active:
            # strict-armed negotiation failure: the SAME verdict (and
            # the same call-site eligibility) on every rank makes this
            # raise symmetric — a clean error instead of a torn hang
            raise MPIError(
                ERR_UNSUPPORTED_OPERATION,
                f"quantized collectives requested on '{comm.name}' but "
                f"negotiation failed under quant_strict: {st.reason}")

    def _run(self, comm, gen, span: str) -> None:
        if _trace.enabled():
            with _trace.span(span, cat="coll", comm=comm.name):
                run_blocking(comm, gen, TAG_QUANT,
                             comm.cid | COLL_CID_BIT)
        else:
            run_blocking(comm, gen, TAG_QUANT, comm.cid | COLL_CID_BIT)

    # --------------------------------------------------------- allreduce
    def allreduce(self, comm, sendbuf, recvbuf, op: _op.Op) -> None:
        st = comm._quant_state
        robj, rcount, rdt = parse_buffer(recvbuf)
        if not self._eligible(st, rdt, rcount * rdt.size, op):
            return self._delegate(comm, "allreduce")(
                comm, sendbuf, recvbuf, op)
        self._check_armed(comm, st)
        src = recvbuf if sendbuf is None else sendbuf  # IN_PLACE
        sobj, scount, sdt = parse_buffer(src)
        x = np.ascontiguousarray(
            cv_pack(sobj, scount, sdt)).view(sdt.np_dtype)
        n, r = comm.size, comm.rank
        codec = st.codec
        wdt = _work_dtype(rdt.np_dtype)
        per, padded = chunk_layout(rcount, n, codec.block)
        buf = np.zeros(padded, dtype=wdt)
        buf[:rcount] = x
        chunks = buf.reshape(n, per)
        wire = codec.wire_nbytes(per)
        enc_own = [codec.encode(chunks[j]) for j in range(n)]
        peers = [j for j in range(n) if j != r]
        pidx = {p: k for k, p in enumerate(peers)}  # O(1) recv lookup
        out: List[Optional[np.ndarray]] = [None]

        def sched():
            got = yield Round(
                sends=[(enc_own[j], j) for j in peers],
                recvs=[(wire, j) for j in peers])
            # reduce chunk r: every contribution quantized (own included,
            # so all ranks dequantize identical values), ascending rank
            # order — the codec.simulate_allreduce contract, bitwise
            enc = [enc_own[r] if i == r else got[pidx[i]]
                   for i in range(n)]
            red = codec.reduce_encoded(enc, per, wdt)
            enc_red = codec.encode(red)
            got2 = yield Round(
                sends=[(enc_red, j) for j in peers],
                recvs=[(wire, j) for j in peers])
            res = np.empty(padded, dtype=wdt)
            for i in range(n):
                payload = enc_red if i == r else got2[pidx[i]]
                res[i * per:(i + 1) * per] = codec.decode(payload, per,
                                                          wdt)
            out[0] = res

        self._run(comm, sched(), "coll.quant.allreduce")
        # raw baseline = what a full-precision schedule would move:
        # UNPADDED ceil(rcount/n) per chunk (counting the block padding
        # would inflate quant_bytes_saved)
        raw = 2 * len(peers) * (-(-rcount // n)) * rdt.size
        _note_coll("allreduce", raw, 2 * len(peers) * wire)
        res = out[0][:rcount].astype(rdt.np_dtype)
        cv_unpack(np.ascontiguousarray(res).view(np.uint8),
                  robj, rcount, rdt)

    # ------------------------------------------------ reduce_scatter_block
    def reduce_scatter_block(self, comm, sendbuf, recvbuf,
                             op: _op.Op) -> None:
        st = comm._quant_state
        robj, rcount, rdt = parse_buffer(recvbuf)
        n, r = comm.size, comm.rank
        if sendbuf is None or not self._eligible(
                st, rdt, n * rcount * rdt.size, op):
            return self._delegate(comm, "reduce_scatter_block")(
                comm, sendbuf, recvbuf, op)
        self._check_armed(comm, st)
        sobj, scount, sdt = parse_buffer(sendbuf)
        x = np.ascontiguousarray(
            cv_pack(sobj, scount, sdt)).view(sdt.np_dtype)
        codec = st.codec
        wdt = _work_dtype(rdt.np_dtype)
        wire = codec.wire_nbytes(rcount)
        enc_own = [codec.encode(
            x[j * rcount:(j + 1) * rcount].astype(wdt, copy=False))
            for j in range(n)]
        peers = [j for j in range(n) if j != r]
        pidx = {p: k for k, p in enumerate(peers)}
        out: List[Optional[np.ndarray]] = [None]

        def sched():
            got = yield Round(
                sends=[(enc_own[j], j) for j in peers],
                recvs=[(wire, j) for j in peers])
            enc = [enc_own[r] if i == r else got[pidx[i]]
                   for i in range(n)]
            out[0] = codec.reduce_encoded(enc, rcount, wdt)

        self._run(comm, sched(), "coll.quant.reduce_scatter")
        _note_coll("reduce_scatter_block", len(peers) * rcount * rdt.size,
                   len(peers) * wire)
        res = out[0][:rcount].astype(rdt.np_dtype)
        cv_unpack(np.ascontiguousarray(res).view(np.uint8),
                  robj, rcount, rdt)

    # --------------------------------------------------------- allgather
    def allgather(self, comm, sendbuf, recvbuf) -> None:
        st = comm._quant_state
        robj, rcount, rdt = parse_buffer(recvbuf)
        # gate on THIS rank's contribution (rcount is the total recv
        # surface, world x that) — the min_bytes cvar reasons about the
        # per-message wire cost, same as allreduce's per-rank payload
        if sendbuf is None or not self._eligible(
                st, rdt, rcount * rdt.size // comm.size, None):
            return self._delegate(comm, "allgather")(
                comm, sendbuf, recvbuf)
        self._check_armed(comm, st)
        sobj, scount, sdt = parse_buffer(sendbuf)
        x = np.ascontiguousarray(
            cv_pack(sobj, scount, sdt)).view(sdt.np_dtype)
        n, r = comm.size, comm.rank
        codec = st.codec
        wdt = _work_dtype(rdt.np_dtype)
        wire = codec.wire_nbytes(scount)
        enc = codec.encode(x.astype(wdt, copy=False))
        peers = [j for j in range(n) if j != r]
        pidx = {p: k for k, p in enumerate(peers)}
        out: List[Optional[np.ndarray]] = [None]

        def sched():
            got = yield Round(sends=[(enc, j) for j in peers],
                              recvs=[(wire, j) for j in peers])
            res = np.empty(n * scount, dtype=wdt)
            for i in range(n):
                payload = enc if i == r else got[pidx[i]]
                res[i * scount:(i + 1) * scount] = codec.decode(
                    payload, scount, wdt)
            out[0] = res

        self._run(comm, sched(), "coll.quant.allgather")
        _note_coll("allgather", len(peers) * scount * rdt.size,
                   len(peers) * wire)
        res = out[0][:rcount].astype(rdt.np_dtype)
        cv_unpack(np.ascontiguousarray(res).view(np.uint8),
                  robj, rcount, rdt)


class QuantXlaColl(CollModule):
    """Mesh-mode quantized allreduce: one compiled XLA program via the
    block-scaled body in coll/xla.py. Only the allreduce slot is
    provided — every other verb falls through to the xla component."""

    def __init__(self):
        from ompi_tpu.coll.xla import XlaColl

        self._xla = XlaColl()

    def allreduce(self, comm, x, op: _op.Op = _op.SUM):
        from ompi_tpu.coll.xla import (
            _check_device_op,
            cache_key,
            quant_allreduce_body,
        )

        st = comm._quant_state
        _check_device_op(op, x)
        # the key carries a "quant" discriminator: XlaColl.reduce shares
        # the PLAIN allreduce executable under cache_key("allreduce", op)
        # on this same comm, so reusing that key would make which body
        # runs (quantized vs exact) depend on reduce/allreduce call
        # order. XlaComm._allreduce_slow promotes this key into the fast
        # table when present.
        key = cache_key("allreduce", op, extra=("quant",))

        def build():
            plain = self._xla._allreduce_body(comm, op)
            body = quant_allreduce_body(comm, plain, op, st.mode,
                                        st.block, st.min_bytes)
            import jax
            import jax.numpy as jnp

            fn = self._xla._wrap(comm, body)
            _Tracer = jax.core.Tracer
            W = comm.world_size
            is_psum = op.jax_kind == "psum" and comm.groups is None
            codec = st.codec
            min_bytes = st.min_bytes

            def counted(b, _fn=fn):
                # rides the fast table too (_promote installs this
                # wrapper), so quant_colls/bytes pvars track the mesh
                # path live; only quant-negotiated comms pay it and the
                # mirror of the trace-time eligibility test keeps the
                # counters honest about which calls actually quantized
                out = _fn(b)
                try:
                    if isinstance(b, _Tracer):
                        # under an outer jit/scan this wrapper runs once
                        # at trace time while the collective executes per
                        # call — counting here would be wrong in both
                        # directions, so traced calls go unaccounted
                        return out
                    n = b.size // W
                    item = b.dtype.itemsize
                    # jnp.issubdtype, NOT np: the traced body gates on
                    # jnp's lattice, where bfloat16 IS floating —
                    # np.issubdtype says it isn't, so bf16 calls would
                    # quantize on the wire yet never be counted
                    if (is_psum and W >= 2
                            and jnp.issubdtype(b.dtype, jnp.floating)
                            and n * item >= min_bytes):
                        per, _ = chunk_layout(n, W, codec.block)
                        wire = codec.wire_nbytes(per)
                        # whole-mesh accounting (single controller =
                        # every rank): each of W ranks exchanges
                        # 2*(W-1) chunks (reduce-scatter + allgather);
                        # the raw baseline counts UNPADDED chunks
                        _note_coll("allreduce", 2 * W * (W - 1)
                                   * (-(-n // W)) * item,
                                   2 * W * (W - 1) * wire)
                except (AttributeError, TypeError):
                    pass  # tracers/unsized inputs: skip accounting
                return out

            return counted

        return self._xla._dispatch(comm, key, build, x)


class QuantCollComponent(Component):
    NAME = "quant"
    PRIORITY = 110  # above xla (100) and tuned (30): owns its slots
    # only where the NEGOTIATED verdict selected it

    _proc: Optional[QuantProcColl] = None
    _mesh: Optional[QuantXlaColl] = None

    def query(self, comm=None, **ctx):
        from ompi_tpu.comm.communicator import ProcComm

        if isinstance(comm, ProcComm) and comm.size > 1:
            st = _negotiate.for_proc_comm(comm)
            if st.active or st.strict:
                comm._quant_state = st
                if QuantCollComponent._proc is None:
                    QuantCollComponent._proc = QuantProcColl()
                return QuantCollComponent._proc
            return None
        from ompi_tpu.parallel.mesh import XlaComm

        if isinstance(comm, XlaComm):
            st = _negotiate.for_mesh_comm(comm)
            if st.active:
                comm._quant_state = st
                if QuantCollComponent._mesh is None:
                    QuantCollComponent._mesh = QuantXlaColl()
                return QuantCollComponent._mesh
        return None


coll_framework.register(QuantCollComponent())
