"""Shared filesystem helpers for the observability exporters."""

from __future__ import annotations

import json
import os
import threading
from typing import Any


def atomic_write_json(path: str, doc: Any, **dump_kwargs) -> str:
    """Write ``doc`` as JSON via a uniquely-named tmp + atomic rename,
    so a concurrent reader (mpitop, mpidiag, trace_merge) never sees a
    torn file and two writers (periodic vs finalize, fatal vs clean)
    never interleave. The ONE writer discipline for the metrics
    snapshot, the trace export, and the forensics dumps — a failed
    write (disk full: exactly the condition the abort-path exporters
    run under) unlinks its partial tmp instead of stranding one per
    attempt. Returns ``path``."""
    tmp = f"{path}.{os.getpid()}.{threading.get_ident()}.tmp"
    try:
        with open(tmp, "w") as f:
            json.dump(doc, f, **dump_kwargs)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    return path
