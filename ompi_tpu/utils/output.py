"""Multi-stream logging with per-subsystem verbosity.

Reference: opal/util/output.c (1,051 LoC) — every framework gets its own
output stream whose verbosity is an MCA variable. We build on Python logging
but keep the reference's contract: per-framework verbosity sourced from
``OMPI_TPU_MCA_<name>_verbose`` and rank-prefixed lines so interleaved
multi-rank output stays attributable (reference: opal_output_set_verbosity).
"""

from __future__ import annotations

import logging
import os
import sys
from typing import Dict

_loggers: Dict[str, logging.Logger] = {}
_configured = False


def _rank_prefix() -> str:
    rank = os.environ.get("OMPI_TPU_RANK")  # mpilint: disable=raw-environ — rank identity for log prefixes
    return f"[rank {rank}] " if rank is not None else ""


class _RankFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        return f"{_rank_prefix()}[{record.name}] {record.getMessage()}"


def _configure_root() -> None:
    global _configured
    if _configured:
        return
    handler = logging.StreamHandler(sys.stderr)
    handler.setFormatter(_RankFormatter())
    root = logging.getLogger("ompi_tpu")
    root.addHandler(handler)
    root.propagate = False
    root.setLevel(logging.WARNING)
    _configured = True


def get_logger(name: str) -> logging.Logger:
    """Get the named output stream, honoring OMPI_TPU_MCA_<name>_verbose
    (0=warn, 1=info, 2+=debug) — the reference's verbosity-level contract."""
    _configure_root()
    full = f"ompi_tpu.{name}"
    log = _loggers.get(full)
    if log is None:
        log = logging.getLogger(full)
        env = os.environ.get(  # mpilint: disable=raw-environ — see below
            f"OMPI_TPU_MCA_{name.replace('.', '_')}_verbose",  # mpilint: disable=cvar-once — logger names are dynamic; their verbose knobs cannot be pre-registered
            os.environ.get("OMPI_TPU_VERBOSE"),  # mpilint: disable=raw-environ — dynamic per-logger verbosity
        )
        if env is not None:
            try:
                lvl = int(env)
            except ValueError:
                lvl = 0
            log.setLevel(
                logging.DEBUG if lvl >= 2 else logging.INFO if lvl == 1 else logging.WARNING
            )
        _loggers[full] = log
    return log
