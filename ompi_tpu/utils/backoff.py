"""One bounded exponential-backoff+jitter schedule for every retry
loop in the tree.

Reference: opal's mca_btl_tcp endpoint complete-connect retry and the
orte/prte restart throttles — every reference retry loop carries the
same four knobs (base delay, doubling cap, attempt budget, total
deadline) and the same ±jitter so herds desynchronize. This tree grew
three hand-rolled copies of that loop (tcp connect establishment, the
serving admission gate, the link redial) before they were hoisted
here; the policy is now written once:

- delay for attempt *n* is ``min(base * 2**n, cap)`` multiplied by a
  uniform jitter factor in ``[1-jitter, 1+jitter)`` — a restarted peer
  is not reconnect-stormed by every rank at once;
- BOTH budgets bind: an attempt count AND a wall-clock deadline. A
  SYN-blackholed peer burning full per-attempt timeouts must not
  stretch total failure latency to ``attempts * timeout``;
- sleeps are clamped to the remaining deadline budget — backing off
  past the deadline would stretch failure latency beyond the bound the
  deadline exists to keep.

Callers iterate imperatively (the loops do real work between sleeps)::

    sched = Schedule(base_s=0.025, cap_s=2.0, retries=18, deadline_s=30)
    while True:
        try:
            return dial()
        except OSError:
            if not sched.sleep():
                raise          # budget exhausted — escalate

``rng`` is injectable for deterministic tests; the module-level default
uses the process RNG (jitter is the one place nondeterminism is the
feature).
"""

from __future__ import annotations

import random
import time
from typing import Optional


class Schedule:
    """One retry schedule instance: owns the attempt counter and the
    deadline clock for a single retry loop (construct per loop, not
    per module — the deadline starts at construction)."""

    __slots__ = ("base_s", "cap_s", "retries", "deadline", "jitter",
                 "rng", "attempt")

    def __init__(self, base_s: float, cap_s: float = 2.0,
                 retries: Optional[int] = None,
                 deadline_s: Optional[float] = None,
                 jitter: float = 0.5,
                 rng: Optional[random.Random] = None):
        self.base_s = max(float(base_s), 0.0)
        self.cap_s = max(float(cap_s), self.base_s)
        self.retries = None if retries is None else int(retries)
        self.deadline = None if deadline_s is None \
            else time.monotonic() + float(deadline_s)
        self.jitter = min(max(float(jitter), 0.0), 1.0)
        self.rng = rng  # None = module random (shared process RNG)
        self.attempt = 0

    # ------------------------------------------------------------ budget
    def remaining(self) -> float:
        """Seconds left on the deadline budget (inf when unbounded)."""
        if self.deadline is None:
            return float("inf")
        return self.deadline - time.monotonic()

    def expired(self) -> bool:
        return self.deadline is not None and self.remaining() <= 0.0

    def exhausted(self) -> bool:
        """True once EITHER budget is spent — the caller's cue to stop
        retrying and escalate."""
        if self.retries is not None and self.attempt >= self.retries:
            return True
        return self.expired()

    # ------------------------------------------------------------- delay
    def next_delay(self) -> Optional[float]:
        """The jittered, capped, deadline-clamped delay for the next
        retry, advancing the attempt counter — or ``None`` when either
        budget is exhausted (nothing consumed; the caller escalates).
        Split from :meth:`sleep` for callers with their own wait
        primitive (test seams, condition variables)."""
        if self.exhausted():
            return None
        # 1 << n overflows no sooner than float exp would; clamp the
        # exponent so a long-lived unbounded-retry schedule (the
        # admission gate under a stuck recovery) cannot build a bignum
        raw = self.base_s * (1 << min(self.attempt, 62))
        delay = min(raw, self.cap_s)
        if self.jitter:
            r = (self.rng or random).random()
            delay *= (1.0 - self.jitter) + 2.0 * self.jitter * r
        self.attempt += 1  # mpiracer: disable=cross-thread-race — a Schedule is constructed per retry loop and driven by that one thread; nothing shares an instance
        left = self.remaining()
        if left != float("inf"):
            delay = min(delay, max(left, 0.0))
        return delay

    def sleep(self) -> bool:
        """Sleep out the next delay; ``False`` (without sleeping) when
        the budget is exhausted."""
        d = self.next_delay()
        if d is None:
            return False
        if d > 0:
            time.sleep(d)
        return True
