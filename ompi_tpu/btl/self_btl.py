"""Loopback transport (reference: opal/mca/btl/self, ~600 LoC).

Frames to our own rank short-circuit straight into the matching engine —
no serialization beyond the header, no copies beyond what matching itself
requires.
"""

from __future__ import annotations

from ompi_tpu.btl.base import Btl, btl_framework
from ompi_tpu.mca.component import Component


class SelfBtl(Btl):
    NAME = "self"
    eager_limit = None  # any size moves in one "frame"
    # delivery is inline in send(): progress() never discovers work, so
    # this transport neither needs polling nor caps the idle park
    NEEDS_POLL = False

    def send(self, peer: int, header: bytes, payload) -> None:
        self.deliver(header, payload)


class SelfBtlComponent(Component):
    NAME = "self"
    PRIORITY = 100  # always best for loopback (reference: btl/self exclusivity)

    def query(self, deliver=None, **ctx):
        if deliver is None:
            return None
        return SelfBtl(deliver)


btl_framework.register(SelfBtlComponent())
