"""TCP transport.

Reference: opal/mca/btl/tcp (5,240 LoC — libevent-driven endpoints with
multi-link striping). Redesign: one non-blocking listener + lazy outgoing
connections, drained by the central progress engine (selectors-based; the
GIL releases in select so the progress thread is cheap). This is the DCN
path of the framework — ICI bulk data rides coll/xla instead, so the TCP
btl optimizes for control/pt2pt traffic, not peak bandwidth.

Frame format: [u32 total_len][header HDR_SIZE bytes][payload]. One frame
per pml message/fragment; TCP ordering per connection preserves MPI
ordering per peer (the reference's per-peer seq numbers guard reordering
across *multiple* btls; with one link per peer ordering is structural).

Zero-copy datapath (the opal convertor / btl writev discipline): a send
is a vector [length word, header, payload view] pushed with
``socket.sendmsg`` — no frame materialization, no eager-payload copy.
Only bytes the kernel would not take are copied, into an owned
write-queue entry (a deque of buffers drained by vectored I/O — the
reference's pending-frag list, minus the O(n^2) bytes-concat the old
``wbuf += frame`` paid under backlog). The receive side ``recv_into``s
a pooled block per connection and hands the pml *slices* of it; a copy
happens only at the pml delivery boundary when a payload must outlive
the block (unexpected-queue stash, system-plane blobs). The remaining
copies are measured, not estimated: ``btl_tcp_bytes_copied`` /
``btl_tcp_writev_calls`` / ``btl_tcp_wire_bytes`` pvars, and
``btl_tcp_copy_mode=1`` re-materializes the legacy copies so bench can
A/B the tax in one process.

Priority-aware traffic shaping (``btl_tcp_shape_enable``): each
connection's send backlog becomes three QoS-class sub-queues
(LATENCY / NORMAL / BULK, read from bits 6-7 of the pml kind byte —
see ompi_tpu/qos.py) drained by a weighted-deficit scheduler with a
starvation bound (``btl_tcp_shape_max_defer_bytes``), so a background
checkpoint blob can no longer head-of-line-block a 4KB allreduce for
its full serialization time. FIFO still holds WITHIN a class (the
pml's per-(peer, class) sequence planes depend on it); preemption
happens between frames — the pml segments oversized blobs into
sub-frames so the yield granularity is ``btl_tcp_shape_segment_bytes``.
The legacy single-FIFO drain stays verbatim behind shape_enable=0 (the
A/B baseline), and the win is measured from the ``btl_tcp_shape_*``
pvars (queued-bytes-by-class gauges, preemption counts) plus the
metrics-plane per-class deferral histogram.

On-wire compression (``btl_tcp_compress`` = zlib level 1-9, 0 = off):
large rendezvous payloads (>= ``btl_tcp_compress_min_bytes``) go out
zlib-deflated with the top bit of the length word flagging the frame;
the header stays plaintext so frame parsing is unchanged. The framing
is negotiated per connection during the rank handshake — a capability
bit meaning "I can DECODE flagged frames" rides the connector's rank
word (advertised unconditionally by this build, so engagement never
depends on which side dialed first) and the acceptor answers with an
ack word. A peer launched with ``btl_tcp_compress`` unset still
decodes. Forward-compat scope: a build WITHOUT this framing is safe as
the CONNECTOR (its bare rank word parses unchanged here, it never
advertises, and no flagged frame or ack is ever emitted toward it);
dialing such a build is NOT supported — its acceptor would parse the
capability bit as part of the rank. All ranks of one job run one
build, so the one-directional guarantee covers the real topology.

Link reliability (``btl_tcp_reliable``, default ON): a negotiated
per-connection reliability envelope turns wire faults from instant
link death into bounded self-healing. Every data frame on an engaged
link carries a link sequence number, a piggybacked cumulative ack and
a CRC32 trailer; sent frames are RETAINED (bounded by
``btl_tcp_retx_window_bytes``) until the peer's cumulative ack covers
them, a CRC mismatch NACKs a retransmission instead of desyncing or
killing the stream, and the receiver dedups by sequence so pml
delivery stays exactly-once under retransmit overlap. A failed
ESTABLISHED connection degrades instead of dying: outbound frames
keep accumulating in the retransmit window while the lower rank
redials on the utils/backoff schedule (``btl_tcp_link_retries`` /
``btl_tcp_link_backoff_ms`` / ``btl_tcp_link_deadline_s``); the
resync handshake on the fresh socket exchanges cumulative acks and
replays the unacked tail, invisible to the pml. Escalation — redial
budget blown, detector-confirmed death, or resync disagreement —
falls through to the pre-reliability failure path (mark_failed, dead
conn, pml failover/dead-letter) unchanged. The legacy wire format
stays bit-identical behind ``btl_tcp_reliable=0`` (the A/B baseline);
an engaged build caps frames at 512 MiB so the per-frame envelope and
control flag bits can never alias length bits (see the framing guard
in send()).
"""

from __future__ import annotations

# instrumentation-bearing framework code on the wire path (per-class
# deferral observations, preemption counters) with no note_* hooks of
# its own — the mpilint module-scan marker keeps it in the derived
# INSTR_IMPL set (span-ctx exemption) without hand-list extension
MPILINT_INSTR_IMPL = True

import errno
import itertools
import os
import selectors
import socket
import struct
import threading
import time
import weakref
import zlib
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

from ompi_tpu import qos as _qos
from ompi_tpu.btl.base import Btl, btl_framework
from ompi_tpu.ft import inject as _inject
from ompi_tpu.runtime import forensics as _forensics
from ompi_tpu.runtime import linkmodel as _linkmodel
from ompi_tpu.mca.component import Component
from ompi_tpu.mca.var import (register_var, register_pvar, get_var,
                              watch_var)
from ompi_tpu.pml.base import HDR_SIZE, QOS_SHIFT
from ompi_tpu.runtime import metrics as _metrics
from ompi_tpu.runtime import mpool as _mpool
from ompi_tpu.runtime import trace as _trace
from ompi_tpu.utils import backoff as _backoff
from ompi_tpu.utils.output import get_logger

register_var("btl_tcp", "eager_limit", 1 << 20,
             help="TCP eager/rendezvous threshold in bytes", level=4)
register_var("btl_tcp", "retries", 18,
             help="Bounded connection-establishment retries before the "
                  "connect fails up to the pml failover path "
                  "(reference: btl_tcp_retries_on_connect... the "
                  "endpoint complete-connect retry loop). The default "
                  "schedule (with btl_tcp_backoff_ms doubling to its "
                  "2s cap) spans the 30s total deadline, so a peer "
                  "that takes the whole pre-retry 30s window to come "
                  "up still connects", level=5)
register_var("btl_tcp", "backoff_ms", 25.0,
             help="Base delay between connect retries; doubles per "
                  "attempt (capped at 2s) with +-50% jitter so a "
                  "restarted peer isn't reconnect-stormed by every "
                  "rank at once", level=5)
# empty = auto: loopback for single-host jobs, all-interfaces bound +
# best non-loopback address advertised when the launcher flags a
# multi-host job (OMPI_TPU_MULTIHOST) — reference: btl_tcp_if_include
register_var("btl_tcp", "bind_host", "",
             help="Interface to bind/advertise (empty=auto; "
                  "reference: btl_tcp_if_*)",
             level=4)
_compress_var = register_var(
    "btl_tcp", "compress", 0,
    help="zlib level (1-9) for on-wire payload compression of frames "
         "at or above btl_tcp_compress_min_bytes; 0 (default) = off. "
         "Negotiated per connection during the rank handshake, so a "
         "non-compressing peer interops (it simply never receives a "
         "compressed frame)", level=4)
_compress_min_var = register_var(
    "btl_tcp", "compress_min_bytes", 1 << 16,
    help="Payload bytes below which frames are never compressed (the "
         "deflate cost beats the wire saving on small/eager traffic; "
         "the default targets rendezvous DATA fragments)", level=5)
_vecs_var = register_var(
    "btl_tcp", "writev_max_vecs", 64,
    help="Max iovecs handed to one sendmsg() when draining the "
         "vectored write queue (IOV_MAX guard; reference: the btl "
         "writev scatter-gather of opal's tcp frag lists)", level=5)
_copy_mode_var = register_var(
    "btl_tcp", "copy_mode", 0,
    help="1 = legacy copying datapath: materialize the eager-payload "
         "copy, the frame concat, the per-recv 1 MiB allocation + "
         "rbuf concat, and the receive parse copies the zero-copy "
         "vectored path eliminates. A/B baseline for bench.py's p2p "
         "section — the copies feed btl_tcp_bytes_copied either way, "
         "so copies-per-wire-byte is measured, not estimated", level=9)

# ------------------------------------------------- priority traffic shaping
# btl_tcp_shape_enable / shape_segment_bytes live in ompi_tpu/qos.py
# (the pml shares them: it stamps the class and segments system blobs);
# the scheduler knobs below are this transport's own.
_quantum_var = register_var(
    "btl_tcp", "shape_quantum_bytes", 1 << 16,
    help="Base quantum of the weighted-deficit drain: each scheduling "
         "round grants every backlogged class quantum * weight bytes "
         "of deficit; a class sends while its deficit covers its head "
         "frame. Smaller = tighter interleave, more scheduling work "
         "per byte", level=6)
_weights_var = register_var(
    "btl_tcp", "shape_weights", "8,4,1", typ=str,
    help="Deficit weights 'latency,normal,bulk' for the shaped drain "
         "(floor 1 each): the steady-state wire-byte ratio between "
         "backlogged classes", level=6)
_max_defer_var = register_var(
    "btl_tcp", "shape_max_defer_bytes", 4 << 20,
    help="Starvation bound: once other classes have sent this many "
         "bytes past a backlogged class's head frame, that class is "
         "served next regardless of deficit — BULK always progresses. "
         "0 disables the bound (pure weighted-deficit)", level=6)
_sndbuf_var = register_var(
    "btl_tcp", "sndbuf", 0,
    help="SO_SNDBUF for every tcp connection (reference: "
         "btl_tcp_sndbuf); 0 (default) = kernel default/autotuning. "
         "Bytes the kernel has accepted are beyond any send "
         "scheduler's reach, so with traffic shaping a bounded send "
         "buffer keeps scheduling authority at the btl's per-class "
         "queues instead of a deep autotuned kernel backlog", level=5)
_rcvbuf_var = register_var(
    "btl_tcp", "rcvbuf", 0,
    help="SO_RCVBUF for every tcp connection, applied before "
         "connect/listen so the TCP window scale reflects it "
         "(reference: btl_tcp_rcvbuf); 0 (default) = kernel default. "
         "Together with btl_tcp_sndbuf this bounds per-connection "
         "in-flight bytes — the A/B harness uses it to pin a "
         "deterministic wire bandwidth on loopback", level=5)

# ------------------------------------------------------ link reliability
_reliable_var = register_var(
    "btl_tcp", "reliable", 1,
    help="Self-healing links: CRC32-verified, ack'd-retransmit framing "
         "with transparent reconnect-and-replay when an ESTABLISHED "
         "connection fails. Negotiated per connection at the rank "
         "handshake — both sides must advertise; a reliable=0 peer "
         "interops at plain framing. 0 = legacy wire format, "
         "bit-identical to the pre-reliability build (the A/B "
         "baseline; btl_tcp_copy_mode=1 bench runs should also set 0 — "
         "legacy-datapath frames bypass the envelope and are not "
         "retained). With reliability on, one frame tops out at "
         "512 MiB instead of 2 GiB: length-word bits 29/30 become the "
         "envelope/control flags (see the framing guard in send())",
    level=4)
_retx_window_var = register_var(
    "btl_tcp", "retx_window_bytes", 8 << 20,
    help="Retained-frame budget per reliable connection: sent frames "
         "are kept for retransmission until cumulatively acked. On a "
         "HEALTHY link overflow evicts the oldest retained frame "
         "(tracked — a later resync that needs it escalates as "
         "disagreement); while DEGRADED the window is the replay "
         "guarantee, so overflow escalates to the failure path",
    level=5)
_retx_timeout_var = register_var(
    "btl_tcp", "retx_timeout_ms", 200.0, float,
    help="Oldest-unacked age past which the link timer retransmits the "
         "retained tail (the per-strike timeout grows; 3 strikes with "
         "no ack progress degrade the link — a half-open connection "
         "heals through redial, not blind retransmission). Also paces "
         "the receiver's periodic cumulative ack (at half this)",
    level=5)
_link_retries_var = register_var(
    "btl_tcp", "link_retries", 18,
    help="Redial attempts for a DEGRADED link before the redialer "
         "gives up (btl_tcp_link_deadline_s still bounds the total "
         "outage — both budgets bind, the utils/backoff contract)",
    level=5)
_link_backoff_var = register_var(
    "btl_tcp", "link_backoff_ms", 25.0, float,
    help="Base redial backoff for a DEGRADED link; doubles per attempt "
         "(2s cap) with +-50% jitter — the btl_tcp_backoff_ms schedule "
         "reused from utils/backoff", level=5)
_link_deadline_var = register_var(
    "btl_tcp", "link_deadline_s", 10.0, float,
    help="Total outage budget for a DEGRADED link: past it the link "
         "escalates to the pre-reliability failure path (mark_failed, "
         "dead conn, pml failover/dead-letter). Also bounds how long "
         "the outage refreshes the ft detector's heartbeat staleness "
         "on the peer's behalf", level=5)
_retx_adaptive_var = register_var(
    "btl_tcp", "retx_adaptive", 1,
    help="RTT-adaptive retransmit timer: once a conn holds >= "
         "btl_tcp_rtt_min_samples Karn-accepted RTT samples its "
         "effective timeout is min(btl_tcp_retx_timeout_ms, "
         "max(25ms floor, srtt + 4*rttvar)) — the fixed cvar becomes "
         "the CEILING, so a fast link retransmits in a few RTTs "
         "instead of waiting out a wan-sized constant while a slow "
         "link inflates past the constant and stops striking "
         "spuriously. 0 = fixed timer everywhere (the A/B baseline)",
    level=5)
_rtt_min_samples_var = register_var(
    "btl_tcp", "rtt_min_samples", 8,
    help="Karn-accepted RTT samples a conn must fold before the "
         "adaptive retransmit timer trusts its srtt/rttvar (below "
         "this the fixed btl_tcp_retx_timeout_ms applies)", level=6)

# adaptive-timer floor: below this the strike loop would outpace ack
# coalescing (receivers ack at timeout/2 or 8-frames/1MB, whichever
# first) and read its own batching as loss
_RETX_FLOOR_S = 0.025

# shaped-path counters + live queued-bytes-by-class gauges (plain int
# bumps like _ctr; the by-class gauges take _qlock because different
# conns bump them under different wlocks)
_shape_ctr = {"preempt": 0, "enqueued": 0}  # mpiracer: relaxed-counter — datapath bump discipline: single-op GIL adds, loss tolerated (the by-class gauges that need consistency take _qlock)
_qbytes = [0, 0, 0]   # queued bytes by class (qos.NORMAL/LATENCY/BULK)
_qpeak = [0, 0, 0]
_qlock = threading.Lock()

register_pvar("btl_tcp", "shape_queued_normal",
              lambda: _qbytes[_qos.NORMAL],
              help="Bytes currently queued in NORMAL-class send "
                   "sub-queues across all shaped connections")
register_pvar("btl_tcp", "shape_queued_latency",
              lambda: _qbytes[_qos.LATENCY],
              help="Bytes currently queued in LATENCY-class send "
                   "sub-queues across all shaped connections")
register_pvar("btl_tcp", "shape_queued_bulk",
              lambda: _qbytes[_qos.BULK],
              help="Bytes currently queued in BULK-class send "
                   "sub-queues across all shaped connections")
register_pvar("btl_tcp", "shape_peak_queued_normal",
              lambda: _qpeak[_qos.NORMAL],
              help="High-water mark of NORMAL-class queued bytes")
register_pvar("btl_tcp", "shape_peak_queued_latency",
              lambda: _qpeak[_qos.LATENCY],
              help="High-water mark of LATENCY-class queued bytes")
register_pvar("btl_tcp", "shape_peak_queued_bulk",
              lambda: _qpeak[_qos.BULK],
              help="High-water mark of BULK-class queued bytes")
register_pvar("btl_tcp", "shape_preemptions",
              lambda: _shape_ctr["preempt"],
              help="Frames the shaped drain served ahead of an "
                   "earlier-enqueued frame of another class (the "
                   "out-of-FIFO services the per-class scheduler "
                   "exists to make)")
register_pvar("btl_tcp", "shape_enqueued",
              lambda: _shape_ctr["enqueued"],
              help="Frames that took the shaped (backlogged) queue "
                   "path instead of the zero-copy direct send")

# mpitop/promexport read the by-class queue gauges as one sampler row
def register_shape_sampler() -> None:
    """(Re)bind the by-class queue sampler into the metrics registry —
    called at import; tests that reset the registry re-call it."""
    _metrics.register_sampler(
        "btl_tcp_shape_queued_bytes_by_class",
        lambda: {"latency": _qbytes[_qos.LATENCY],
                 "normal": _qbytes[_qos.NORMAL],
                 "bulk": _qbytes[_qos.BULK],
                 "peak_latency": _qpeak[_qos.LATENCY],
                 "peak_normal": _qpeak[_qos.NORMAL],
                 "peak_bulk": _qpeak[_qos.BULK]})


register_shape_sampler()

# strict-priority service preference inside one deficit round
_SERVICE_ORDER = (_qos.LATENCY, _qos.NORMAL, _qos.BULK)

_weights_memo: Optional[List[int]] = None


def _parse_weights(_var=None) -> None:
    global _weights_memo
    _weights_memo = None


watch_var("btl_tcp", "shape_weights", _parse_weights)


def _weights() -> List[int]:
    """[w_by_class_int]: cvar order is latency,normal,bulk; class ints
    are NORMAL=0/LATENCY=1/BULK=2. Floor 1 so every class drains."""
    global _weights_memo
    w = _weights_memo
    if w is None:
        parts = str(_weights_var._value).split(",")
        try:
            lat, norm, bulk = (max(int(p), 1) for p in parts[:3])
        except (ValueError, TypeError):
            lat, norm, bulk = 8, 4, 1
        w = [1, 1, 1]
        w[_qos.LATENCY], w[_qos.NORMAL], w[_qos.BULK] = lat, norm, bulk
        _weights_memo = w
    return w

# datapath counters (plain int bumps — no instrumentation framework on
# the per-frame path), exported as pvars below
_ctr = {"copied": 0, "writev": 0, "wire": 0}  # mpiracer: relaxed-counter — per-frame datapath counters; a lock per sendmsg would tax the wire path the zero-copy work just paid down

register_pvar("btl_tcp", "bytes_copied",
              lambda: _ctr["copied"],
              help="Payload/frame bytes the tcp datapath had to copy "
                   "(write-queue ownership under backpressure, rx "
                   "compaction/grow, legacy copy_mode re-adds)")
register_pvar("btl_tcp", "writev_calls",
              lambda: _ctr["writev"],
              help="Vectored sendmsg() syscalls issued by the write "
                   "path")
register_pvar("btl_tcp", "wire_bytes",
              lambda: _ctr["wire"],
              help="Frame bytes moved through the sockets (tx + rx), "
                   "the denominator of copies-per-wire-byte")

# link-reliability counters (same relaxed bump discipline as _ctr)
_lctr = {"recoveries": 0, "retransmits": 0, "crc_errors": 0,
         "dedup": 0, "released": 0}  # mpiracer: relaxed-counter — datapath/timer bumps from app + progress threads; pvar readers tolerate a stale view

register_pvar("btl_tcp", "link_recoveries",
              lambda: _lctr["recoveries"],
              help="Degraded links healed by reconnect-and-replay "
                   "(resync completed — the pml never saw the outage)")
register_pvar("btl_tcp", "retransmits",
              lambda: _lctr["retransmits"],
              help="Retained frames retransmitted (NACK, retransmit "
                   "timeout, or resync replay)")
register_pvar("btl_tcp", "crc_errors",
              lambda: _lctr["crc_errors"],
              help="Inbound reliable frames whose CRC32 check failed — "
                   "each NACKed a retransmission instead of desyncing "
                   "or killing the link")
register_pvar("btl_tcp", "link_dedup_frames",
              lambda: _lctr["dedup"],
              help="Inbound reliable frames discarded as duplicates by "
                   "link sequence (retransmit overlap — the receiver's "
                   "exactly-once guarantee to the pml)")
register_pvar("btl_tcp", "retx_released",
              lambda: _lctr["released"],
              help="Retained frames evicted UNACKED by window overflow "
                   "on a healthy link (a later resync that needs one "
                   "escalates as disagreement)")

# live transports for the link rollup (weak: test-built instances must
# not be pinned by the observability plane)
_live_btls: "weakref.WeakSet" = weakref.WeakSet()


def _link_rollup() -> dict:
    """Degraded-link / retained-frame rollup across live transports:
    mpitop's LNK column and the stall sentinel's pending probe. Reads
    are lock-free diagnostic snapshots — one torn sample skews one
    reading, never the link state itself."""
    degraded = frames = nbytes = 0
    for btl in list(_live_btls):
        if btl._closed:
            continue
        with btl._conn_lock:
            conns = list(btl.conns.values())
        for c in conns:
            if not c.rel or c.dead is not None:
                continue
            if c.state != "est":
                degraded += 1
            frames += len(c.retx)  # mpiracer: disable=cross-thread-race — lock-free diagnostic snapshot, see docstring
            nbytes += c.retx_bytes  # mpiracer: disable=cross-thread-race — lock-free diagnostic snapshot, see docstring
    return {"degraded_links": degraded, "retx_frames": frames,
            "retx_bytes": nbytes}


def register_link_sampler() -> None:
    """(Re)bind the link-health sampler (mpitop's LNK column) — called
    at import; tests that reset the metrics registry re-call it."""
    _metrics.register_sampler(
        "btl_tcp_link",
        lambda: dict(_link_rollup(),
                     recoveries=_lctr["recoveries"],
                     retransmits=_lctr["retransmits"],
                     crc_errors=_lctr["crc_errors"]))


register_link_sampler()


def _linkmodel_rows() -> list:
    """Per-conn estimator rows for the fabric-telemetry registry
    (runtime/linkmodel.py pulls these on its fold cadence). Lock-free
    diagnostic snapshot like _link_rollup: a torn read skews one fold,
    never the conn."""
    rows = []
    for btl in list(_live_btls):
        if btl._closed:
            continue
        with btl._conn_lock:
            conns = list(btl.conns.values())
        for c in conns:
            if not c.rel or c.dead is not None:
                continue
            oldest = 0.0
            try:  # mpiracer: disable=cross-thread-race — lock-free diagnostic snapshot, see docstring
                if c.retx:
                    oldest = max(
                        0.0, time.monotonic() - min(
                            ts for _, _, ts, _ in c.retx.values()))
            except (RuntimeError, ValueError):
                pass  # dict mutated mid-walk: skip the age this fold
            rows.append({
                "peer": c.peer,
                "state": c.state,
                "srtt": c.srtt,
                "rttvar": c.rttvar,
                "rtt_n": c.rtt_n,
                "acked_b": list(c.acked_b),
                "tx_frames": c.tx_seq,
                "rx_frames": c.rx_frames,
                "retx_n": c.retx_n,
                "nack_retx_n": c.nack_retx_n,
                "crc_errs": c.crc_errs,
                "dedup_n": c.dedup_n,
                "queue_age_s": oldest,
            })
    return rows


_linkmodel.register_source(_linkmodel_rows)

# a DEGRADED link is pending work (its retained frames complete only
# through heal-or-escalate): the stall sentinel must read a wedged heal
# as a stall — whose dump then carries the per-conn link evidence the
# btl.tcp provider exports — not as an idle process
_forensics.register_pending_probe(
    "btl.tcp.link", lambda: _link_rollup()["degraded_links"])

_LEN = struct.Struct("<I")

# receive staging block: sized for a full default rendezvous DATA frame
# (pml_frag_size 1 MiB + framing) so the common bulk frame fits without
# growing, shared by every TcpBtl through one mpool.BufferPool
_RX_BLOCK = (1 << 20) + (1 << 12)
_rx_pool = _mpool.BufferPool(_RX_BLOCK)

# rank-handshake capability bits + frame compression flag: compression
# rides the top bit of its u32 word (ranks and frame lengths stay
# < 2^30); the QoS bit advertises "my pml masks class bits from the
# kind byte and keys its sequence planes per (peer, class)" — every
# build with this code does, so like the compress bit it is advertised
# unconditionally and acked unconditionally. Shaping toward a peer
# that never acks (an older build) is documented-unsupported: its pml
# would reject class-stamped kind bytes, exactly like dialing a
# pre-compress acceptor.
_CAP_COMPRESS = 1 << 31
_CAP_QOS = 1 << 30
# link reliability: "my frames toward you will carry the reliability
# envelope, and I parse flagged frames from you" (gated on
# btl_tcp_reliable, unlike the unconditional decode-capability bits
# above — reliability changes MY wire format, not just my parser)
_CAP_RELIABLE = 1 << 29
# redial marker: this connection RESUMES an existing reliable link
# (the acceptor adopts the socket into the surviving conn and answers
# with a RESYNC exchange instead of building a fresh endpoint)
_CAP_RESYNC = 1 << 28
_ZFLAG = 1 << 31
_LEN_MASK = _ZFLAG - 1
# per-frame flags on a reliable link, interpreted only on connections
# whose handshake engaged reliability (rel_rx): bit 30 marks a
# link-control frame, bit 29 a reliability-enveloped data frame. A
# legacy (unflagged) frame stays parseable mid-stream — the
# copy_mode=1 datapath and the connector's pre-ack traffic ride it.
_LFLAG = 1 << 30
_RFLAG = 1 << 29
# reliable builds cap EVERY outbound frame here (512 MiB) so a legacy
# frame's length bits can never alias _LFLAG/_RFLAG on a reliable
# receiver — see the framing guard in send()
_RLEN_MASK = _RFLAG - 1
# acceptor's handshake ack: magic in the high byte + capability bits
_ZACK_MAGIC = 0x5A << 24
_ZACK_ACCEPT = 1
_ZACK_QOS = 2
_ZACK_RELIABLE = 4
_ZACK_WORDS = frozenset(
    _ZACK_MAGIC | a | q | r
    for a in (0, _ZACK_ACCEPT)
    for q in (0, _ZACK_QOS)
    for r in (0, _ZACK_RELIABLE))

# reliable data envelope, after the length word:
#   [u32 link seq][u32 cum ack][u32 crc32][hdr HDR_SIZE][payload]
# crc32 covers seq+ack+hdr+payload (the whole envelope: a corrupted
# piggyback ack must fail the check too). The frame is IMMUTABLE once
# built — retransmits resend it verbatim; the stale piggyback ack is
# harmless because acks are monotonic and the receiver takes the max.
_RELHDR = struct.Struct("<IIII")  # len|flags, seq, cum_ack, crc32
_RELSA = struct.Struct("<II")     # the crc'd seq+ack prefix
# link-control frame: [u32 _LFLAG|len][u32 crc32][u8 type][u32 a][u32 b]
#   ACK(cum_ack, 0)  NACK(rx_floor, 0)  RESYNC(rx_floor, tx_next)
# a control frame failing ITS crc is silently dropped (acks/nacks are
# re-generated by the timers; a lost RESYNC re-triggers redial)
_LCTL = struct.Struct("<BII")
_CTL_ACK, _CTL_NACK, _CTL_RESYNC = 1, 2, 3
_CTL_LEN = 4 + _LCTL.size  # crc word + body


def _compress_counters():
    """Wire-compression counters live in the quant plane (one
    observable subsystem for both reduced-precision paths)."""
    from ompi_tpu import quant

    return quant.counters()


register_pvar("btl_tcp", "compress_ratio",
              lambda: (lambda c: round(c["wire_raw"] / c["wire_comp"], 4)
                       if c["wire_comp"] else 0.0)(_compress_counters()),
              help="Cumulative raw/compressed payload-byte ratio over "
                   "frames that went out zlib-compressed")
register_pvar("btl_tcp", "compress_saved_bytes",
              lambda: (lambda c: c["wire_raw"] - c["wire_comp"])(
                  _compress_counters()),
              help="Payload bytes kept off the wire by tcp compression")


def _apply_bufs(sock: socket.socket) -> None:
    """SO_SNDBUF/SO_RCVBUF bounds (btl_tcp_sndbuf/rcvbuf, 0 = kernel
    default) — called before connect/listen so TCP window scaling
    honors them."""
    snd = int(_sndbuf_var._value)
    rcv = int(_rcvbuf_var._value)
    try:
        if snd > 0:
            sock.setsockopt(socket.SOL_SOCKET, socket.SO_SNDBUF, snd)
        if rcv > 0:
            sock.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF, rcv)
    except OSError:
        pass


def _corrupt_wire_copy(vecs: List) -> List:
    """Chaos harness (ft_inject ``corrupt``): flip one bit in a COPY of
    the frame's last vector (payload when present, else header) — the
    retained envelope stays clean, so retransmissions converge instead
    of resending the corruption forever. The length word (vecs[0]) is
    never touched: framing desync is outside this fault model — the
    injection corrupts CONTENT, not structure (a corrupted length word
    cannot be survived by any per-frame check; see the module doc)."""
    out = [bytes(v) for v in vecs]
    tail = bytearray(out[-1])  # mpilint: disable=hot-copy — fault-injection only (cold path); the copy is the point: the RETAINED envelope must stay clean so retransmits heal
    if tail:
        tail[len(tail) // 2] ^= 0x01
    out[-1] = bytes(tail)
    return out


class _Conn:
    __slots__ = ("sock", "rxb", "rstart", "rend", "wq", "wbuf", "rbuf",
                 "wlock", "peer", "dead", "peer_z", "await_ack",
                 "wqs", "cur", "cur_cls", "deficit", "defer", "peer_q",
                 "eseq", "last_rx", "last_tx",
                 # link reliability (btl_tcp_reliable)
                 "rel", "rel_rx", "state", "tx_seq", "tx_acked",
                 "tx_released", "retx", "retx_bytes", "rx_floor",
                 "rx_seen", "unacked_n", "unacked_b", "last_ack_tx",
                 "retx_strikes", "last_retx_t", "degraded_at",
                 "redial_deadline", "redial_n", "reconnects",
                 "crc_errs", "last_crc", "esc_eof",
                 # link telemetry (runtime/linkmodel.py + adaptive retx)
                 "srtt", "rttvar", "rtt_n", "karn", "acked_b",
                 "retx_n", "nack_retx_n", "dedup_n", "rx_frames")

    def __init__(self, sock: socket.socket, peer: Optional[int] = None):
        self.sock = sock
        # legacy concat queues, used ONLY under btl_tcp_copy_mode=1
        # (the bench A/B baseline) — empty otherwise
        self.wbuf = bytearray()
        self.rbuf = bytearray()
        # receive staging: a pooled block filled by recv_into, with the
        # unparsed span at [rstart, rend). Acquired lazily on first
        # drain, returned to the pool when the conn unregisters.
        self.rxb: Optional[bytearray] = None
        self.rstart = 0
        self.rend = 0
        # pending outbound buffers, drained by vectored sendmsg
        # (reference: btl/tcp's per-endpoint pending frag list flushed
        # on write-ready events). Entries are OWNED bytes-likes — a
        # borrowed payload view is copied exactly once, at the moment
        # the kernel declines it (buffered-send semantics: the caller
        # may reuse its buffer the instant send() returns).
        self.wq: deque = deque()
        # RLock: _conn_failed runs both under wlock (from _flush_locked)
        # and without it (from _drain's read-error path)
        self.wlock = threading.RLock()
        self.peer = peer
        self.dead: Optional[OSError] = None
        # negotiated at handshake: True once the peer advertised it
        # understands (and accepts) zlib-flagged frames on this link
        self.peer_z = False
        # connector side: an ack word is due before frame traffic; it is
        # consumed ASYNCHRONOUSLY by _drain (a blocking wait here could
        # deadlock two polling-only ranks dialing each other — each
        # stuck in its own handshake, neither accepting)
        self.await_ack = False
        # traffic shaping (btl_tcp_shape_enable): per-class send
        # sub-queues of (enqueue seq, nbytes, owned vec list, enq ts),
        # allocated lazily so unshaped conns pay one None slot; `cur`
        # is the partially-written frame that must finish before the
        # scheduler may switch class (TCP frames are contiguous on the
        # wire — preemption happens BETWEEN frames, which is why
        # oversized blobs are segmented upstream)
        self.wqs: Optional[tuple] = None
        self.cur: Optional[list] = None
        self.cur_cls = 0
        self.deficit = [0, 0, 0]
        self.defer = [0, 0, 0]
        # negotiated at handshake: peer masks QoS class bits and keys
        # its seq planes per class (every build with this code)
        self.peer_q = False
        self.eseq = 0
        # last wire activity (monotonic), stamped only while the
        # forensics plane is armed — dump evidence for "is this link
        # moving at all", not a live gauge
        self.last_rx: Optional[float] = None
        self.last_tx: Optional[float] = None
        # ---- link reliability (btl_tcp_reliable, handshake-engaged)
        # rel: WE envelope outbound frames; rel_rx: we interpret the
        # per-frame _RFLAG/_LFLAG bits on rx. The acceptor sets both at
        # accept; the connector on ack arrival — the split covers the
        # connector's pre-ack legacy frames interleaving on an engaged
        # acceptor (per-frame flags keep both parseable mid-stream).
        self.rel = False
        self.rel_rx = False
        # "est" | "degraded"; death stays in `dead` (the legacy field
        # every existing check keys off)
        self.state = "est"
        self.tx_seq = 0        # last link seq assigned to a sent frame
        self.tx_acked = 0      # highest cumulative ack from the peer
        self.tx_released = 0   # highest seq evicted from the window UNACKED
        # retained sent frames: seq -> (wire bytes, vec list, sent ts,
        # qos class); insertion-ordered = seq-ordered (seqs ascend)
        self.retx: Dict[int, tuple] = {}
        self.retx_bytes = 0
        self.rx_floor = 0      # contiguous inbound seqs delivered
        self.rx_seen: set = set()  # out-of-order seqs above the floor
        self.unacked_n = 0     # rx frames since our last cumulative ack
        self.unacked_b = 0
        self.last_ack_tx = 0.0
        self.retx_strikes = 0  # consecutive retx timeouts w/o ack progress
        self.last_retx_t = 0.0  # NACK-retransmit rate limit clock
        self.degraded_at = 0.0
        self.redial_deadline = 0.0
        self.redial_n = 0      # attempts in the CURRENT outage
        self.reconnects = 0    # lifetime successful resyncs
        self.crc_errs = 0
        self.last_crc: Optional[float] = None
        # was the interrupt that degraded this link an EOF? Escalation
        # preserves the pre-reliability semantics: EOF marked the peer
        # failed only under ft_enable; write errors unconditionally
        self.esc_eof = False
        # ---- link telemetry: Jacobson/Karn RTT off the ack clock
        # (always-on when reliable — the adaptive retransmit timer
        # needs it even with the linkmodel plane off), per-class acked
        # wire bytes (goodput = DELIVERED, not enqueued), and per-conn
        # loss attribution counters (the _lctr globals can't pin a
        # storm on an edge)
        self.srtt = 0.0
        self.rttvar = 0.0
        self.rtt_n = 0
        self.karn: set = set()  # seqs retransmitted: never RTT-sampled
        self.acked_b = [0, 0, 0]   # cumulative acked wire bytes by class
        self.retx_n = 0        # frames this conn retransmitted
        self.nack_retx_n = 0   # ...of which the peer NACKed (CRC reject
        # at the receiver: EVIDENCED wire corruption, unlike a timeout
        # retransmit, which may just be a slow ack)
        self.dedup_n = 0       # inbound duplicates this conn discarded
        self.rx_frames = 0     # reliable frames this conn delivered


class TcpBtl(Btl):
    bandwidth = 1  # stripe weight (reference: opal btl_bandwidth)

    NAME = "tcp"
    # fd-driven: the progress engine may park in select over idle_fds()
    # instead of polling this transport
    NEEDS_POLL = False

    def __init__(self, deliver: Callable[[bytes, bytes], None], my_rank: int):
        super().__init__(deliver)
        self.eager_limit = get_var("btl_tcp", "eager_limit")
        self.my_rank = my_rank
        self.log = get_logger("btl.tcp")
        host = get_var("btl_tcp", "bind_host")
        if not host:
            if os.environ.get("OMPI_TPU_MULTIHOST"):  # mpilint: disable=raw-environ — launcher topology hint, not MCA config
                host = "0.0.0.0"
            else:
                host = "127.0.0.1"
        bind = host
        if host == "0.0.0.0":
            # listen everywhere, advertise the best-scored non-loopback
            # address in the modex card (reference: opal/mca/reachable —
            # the endpoint blob carries routable addresses, see
            # ifaces.best_local_addr)
            from ompi_tpu.runtime.ifaces import best_local_addr

            host = best_local_addr() or "127.0.0.1"
        self.listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self.listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        # buffer bounds inherit to accepted sockets; RCVBUF must be
        # set before listen so the window scale factor reflects it
        _apply_bufs(self.listener)
        self.listener.bind((bind, 0))
        self.listener.listen(64)
        self.listener.setblocking(False)
        self.host = host
        self.port = self.listener.getsockname()[1]
        self.peers: Dict[int, str] = {}
        self.conns: Dict[int, _Conn] = {}  # peer rank -> connection
        self._conn_lock = threading.Lock()
        self.sel = selectors.DefaultSelector()
        self.sel.register(self.listener, selectors.EVENT_READ,
                          ("accept", None))
        self._sel_lock = threading.Lock()
        # single-drainer: exactly one thread runs the event loop at a time
        # (the app thread's wait-loop and the progress thread both call
        # progress(); concurrent drains would interleave frame parsing)
        self._progress_lock = threading.Lock()
        self._closed = False
        # link-reliability timer pass (acks, retransmit timeouts,
        # degraded-link deadlines) runs from progress() on this cadence
        self._rel_next = 0.0
        _live_btls.add(self)  # link sampler / pending-probe rollup
        # stall-forensics provider (rebind-by-name: the live transport
        # wins; weakly bound so test-built instances don't pin)
        _forensics.register_weak_provider(
            "btl.tcp", self, alive=lambda btl: not btl._closed)

    # -------------------------------------------------- stall forensics
    def debug_state(self) -> dict:
        """Forensics provider: per-connection dial/established/dead
        state, per-class shaped queue depths with the oldest frame's
        age, the partially-written frame, partial-frame reassembly
        residue, and the last wire rx/tx stamps (populated while the
        forensics plane is armed). Each conn is snapshotted under its
        own wlock — the same lock every WRITE-queue mutation holds; the
        rx parser's span fields belong to the progress thread and are
        read lock-free and clamped."""
        now = time.monotonic()
        with self._conn_lock:
            conns = dict(self.conns)
        out = []
        for peer, conn in sorted(conns.items())[:_forensics.CAP]:
            # single reads + clamp: the rx parser advances these on the
            # progress thread outside wlock, and a torn pair (rend read
            # before a compaction, rstart after) must not record a
            # negative partial-frame size as evidence
            r0, r1 = conn.rstart, conn.rend  # mpiracer: disable=cross-thread-race — lock-free diagnostic snapshot, clamped below; taking the progress side's lock here could block a dump behind the wedged loop it is diagnosing
            with conn.wlock:
                ent: dict = {
                    "peer": peer,
                    "state": ("dead" if conn.dead is not None else
                              "degraded" if conn.state == "degraded"
                              else
                              "dialing" if conn.await_ack else
                              "established"),
                    "dead_reason": str(conn.dead) if conn.dead else None,
                    "wq_frames": len(conn.wq),
                    "wq_bytes": sum(len(b) for b in conn.wq),
                    "legacy_wbuf_bytes": len(conn.wbuf),
                    "rx_partial_bytes": max(0, r1 - r0),
                    "last_rx_age_s": None if conn.last_rx is None
                    else round(now - conn.last_rx, 3),
                    "last_tx_age_s": None if conn.last_tx is None
                    else round(now - conn.last_tx, 3),
                }
                if conn.rel or conn.rel_rx:
                    # per-link reliability evidence (mpidiag's LINK
                    # blame verdict reads this)
                    link: dict = {
                        "tx_seq": conn.tx_seq,
                        "tx_acked": conn.tx_acked,
                        "tx_released": conn.tx_released,
                        "retx_frames": len(conn.retx),
                        "retx_bytes": conn.retx_bytes,
                        "rx_floor": conn.rx_floor,
                        "rx_ooo": len(conn.rx_seen),
                        "reconnects": conn.reconnects,
                        "crc_errors": conn.crc_errs,
                        "last_crc_age_s": None if conn.last_crc is None
                        else round(now - conn.last_crc, 3),
                        # fabric telemetry (runtime/linkmodel.py):
                        # mpidiag's wire-bound verdict splits on these
                        "srtt_us": round(conn.srtt * 1e6, 1)
                        if conn.rtt_n else None,
                        "rttvar_us": round(conn.rttvar * 1e6, 1)
                        if conn.rtt_n else None,
                        "rtt_samples": conn.rtt_n,
                        "acked_bytes_by_class": {
                            _qos.NAMES[c]: conn.acked_b[c]
                            for c in range(3)},
                        # directional (linkmodel discipline): loss_ppm
                        # charges the outbound edge, and only counts
                        # NACK-evidenced retransmits (a CRC reject at
                        # the peer) — a timeout retransmit may just be
                        # a slow ack; the conn's own crc/dedup counts
                        # describe inbound frames
                        "loss_ppm": round(
                            1e6 * conn.nack_retx_n
                            / max(conn.tx_seq, 1), 1),
                        "rx_loss_ppm": round(
                            1e6 * (conn.crc_errs + conn.dedup_n)
                            / max(conn.rx_frames, 1), 1),
                    }
                    if conn.retx:
                        oldest = next(iter(conn.retx.values()))
                        link["retx_oldest_age_s"] = round(
                            now - oldest[2], 3)
                    if conn.state == "degraded":
                        link["degraded_s"] = round(
                            now - conn.degraded_at, 3)
                        link["redial_attempts"] = conn.redial_n
                        link["redial_budget"] = int(
                            _link_retries_var._value)
                        link["deadline_in_s"] = round(
                            conn.redial_deadline - now, 3)
                    ent["link"] = link
                if conn.cur is not None:
                    ent["in_progress_frame"] = {
                        "cls": _qos.NAMES.get(conn.cur_cls,
                                              conn.cur_cls),
                        "bytes_left": sum(len(v) for v in conn.cur)}
                if conn.wqs is not None:
                    shaped = {}
                    for c in _SERVICE_ORDER:
                        dq = conn.wqs[c]
                        if not dq:
                            continue
                        shaped[_qos.NAMES[c]] = {
                            "frames": len(dq),
                            "bytes": sum(e[1] for e in dq),
                            "oldest_age_s": round(now - dq[0][3], 3),
                            "deferred_bytes": conn.defer[c]}
                    if shaped:
                        ent["shaped_queues"] = shaped
            out.append(ent)
        return {
            "rank": self.my_rank,
            "listen": f"{self.host}:{self.port}",
            "closed": self._closed,
            "conns": out,
            "conns_omitted": max(0, len(conns) - len(out)),
            "queued_by_class": {"latency": _qbytes[_qos.LATENCY],
                                "normal": _qbytes[_qos.NORMAL],
                                "bulk": _qbytes[_qos.BULK]},
        }

    # ------------------------------------------------------------- wiring
    def set_peers(self, peers: Dict[int, str]) -> None:
        self.peers = dict(peers)

    def _connect(self, peer: int) -> _Conn:
        addr = self.peers[peer]
        host, port = addr.rsplit(":", 1)
        # multi-homed hosts: dial from the best-weighted local interface
        # for this peer (reference: opal/mca/reachable weighted scoring)
        from ompi_tpu.runtime.ifaces import pick_source

        try:
            src = pick_source(socket.gethostbyname(host))
        except OSError:
            src = None
        # Bounded establishment retry with exponential backoff + jitter
        # (reference: the endpoint connect retry of btl/tcp): a peer
        # mid-restart or briefly overloaded must not fail the link on
        # the first ECONNREFUSED, and a herd of ranks redialing must
        # not synchronize. BOTH bounds apply — attempt count AND a 30s
        # total deadline (the pre-retry behavior): a SYN-blackholed
        # peer burning full per-attempt timeouts must not stretch the
        # failure to attempts * timeout. Exhaustion raises to the pml
        # failover path. The schedule itself (doubling, 2s cap, ±50%
        # jitter, deadline clamp) lives in utils/backoff — the link
        # redial reuses it verbatim.
        sched = _backoff.Schedule(
            base_s=float(get_var("btl_tcp", "backoff_ms")) / 1000.0,
            cap_s=2.0,
            retries=int(get_var("btl_tcp", "retries")),
            deadline_s=30.0)
        while True:
            left = sched.remaining()
            try:
                # manual socket (vs create_connection) so the
                # btl_tcp_sndbuf/rcvbuf bounds are applied BEFORE the
                # handshake — the window scale is negotiated at SYN
                s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
                try:
                    _apply_bufs(s)
                    s.settimeout(max(min(10.0, left), 1.0))
                    if src:
                        s.bind((src, 0))
                    s.connect((host, int(port)))
                except BaseException:
                    s.close()  # a failed attempt must not leak the fd
                    raise
                s.settimeout(None)
                break
            except OSError as e:
                delay = sched.next_delay()
                if delay is None:
                    self.log.error(
                        "connect to rank %s (%s) failed after %d "
                        "attempts: %s", peer, addr, sched.attempt + 1, e)
                    raise
                from ompi_tpu.runtime import spc

                spc.record("btl_tcp_connect_retries")
                time.sleep(delay)
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        conn = _Conn(s, peer)
        # identify ourselves so the acceptor can map conn -> rank. The
        # capability bit means "I can DECODE zlib-flagged frames" (every
        # build with this code can), NOT "I will compress" — advertising
        # it unconditionally keeps engagement symmetric: whether a
        # compress-enabled peer may flag frames to us must not depend on
        # which side happened to dial first (gating the bit on our own
        # compress level silently disabled the feature whenever the
        # compress=0 side connected first). The acceptor answers with an
        # ack word, consumed asynchronously by _drain — sends stay
        # uncompressed on this link until it lands, so a peer that never
        # acks (a build without this framing) simply keeps the link at
        # plain framing. The QoS capability bit rides along identically
        # (shaped per-class scheduling engages only after the peer acks
        # it — frames sent before the ack drain FIFO). The RELIABLE bit
        # is the one capability gated on its cvar rather than advertised
        # unconditionally: engaging it changes OUR wire format, so
        # btl_tcp_reliable=0 must keep the link bit-identical legacy.
        caps = _CAP_COMPRESS | _CAP_QOS
        if _reliable_var._value:
            caps |= _CAP_RELIABLE
        s.sendall(_LEN.pack(self.my_rank | caps))
        conn.await_ack = True
        s.setblocking(False)
        with self._sel_lock:
            self.sel.register(s, selectors.EVENT_READ, ("peer", conn))
        return conn

    def _get_conn(self, peer: int) -> _Conn:
        with self._conn_lock:
            conn = self.conns.get(peer)
            if conn is None:
                conn = self._connect(peer)
                self.conns[peer] = conn
            return conn

    # --------------------------------------------------------------- send
    def send(self, peer: int, header: bytes, payload) -> None:
        """Vectored zero-copy enqueue: the frame is pushed as
        [length word, header, payload view] via sendmsg with NO
        intermediate materialization; only bytes the kernel declines
        are copied into the owned write queue (buffered-send semantics
        — the caller may reuse its buffer the moment we return). Never
        blocks the caller on a full socket — the head-to-head
        large-send deadlock the reference's pending-frag design exists
        to avoid."""
        if isinstance(payload, bytes):
            mv = payload  # immutable: safe to queue without owning
        else:
            mv = memoryview(payload)
            if mv.ndim != 1 or mv.format != "B" or not mv.c_contiguous:
                try:
                    mv = mv.cast("B")
                except TypeError:
                    # non-contiguous source: ownership copy is forced
                    _ctr["copied"] += mv.nbytes
                    mv = bytes(mv)  # mpilint: disable=hot-copy — non-contiguous buffers cannot be viewed flat
        nbytes = len(mv)
        if HDR_SIZE + nbytes > _LEN_MASK:
            # absolute cap, checked BEFORE the conn lookup: an
            # oversized frame must raise loudly even toward a peer
            # this btl has no address for yet
            from ompi_tpu.core.errors import MPIError, ERR_OTHER

            raise MPIError(
                ERR_OTHER,
                f"tcp frame of {HDR_SIZE + nbytes} bytes exceeds "
                f"the {_LEN_MASK}-byte framing limit")
        conn = self._get_conn(peer)
        limit = _RLEN_MASK if (conn.rel or _reliable_var._value) \
            else _LEN_MASK
        if HDR_SIZE + nbytes > limit:
            # bit 31 of the length word carries the compression flag,
            # so one legacy frame tops out at 2 GiB; with link
            # reliability on (negotiated on this conn, or merely
            # enabled — a peer may engage rel_rx before our handshake
            # ack lands) bits 30/29 become the control/envelope flags
            # too, halving twice to 512 MiB. Beyond the cap the
            # receiver would mask a wrong length AND misparse the flag
            # bits — fail loudly here instead (callers shipping blobs
            # that large must split them)
            from ompi_tpu.core.errors import MPIError, ERR_OTHER

            raise MPIError(
                ERR_OTHER,
                f"tcp frame of {HDR_SIZE + nbytes} bytes exceeds "
                f"the {limit}-byte framing limit")
        drop = dup = corrupt = False
        sent_at = None
        if _inject._enable_var._value:  # chaos wire hook (ft/inject.py)
            # an injected delay() sleeps INLINE right here, before the
            # envelope stamps its retention instant — stamp the send
            # instant first so the chaos latency lands inside the RTT
            # sample, exactly as a slow wire would
            sent_at = time.monotonic()
            verdict = _inject.wire_send(self.my_rank, peer)
            if verdict:
                if verdict & _inject.SEVER:
                    err = ConnectionResetError(
                        "link severed by ft_inject_plan")
                    if conn.rel and verdict & _inject.TRANSIENT:
                        # recoverable outage: the link DEGRADES — this
                        # frame is retained below and replayed at
                        # resync (the self-healing under test)
                        self._conn_failed(conn, err)
                    elif conn.rel:
                        # permanent sever on a reliable link: skip the
                        # degrade window, straight to the legacy death
                        self._link_escalate(conn, err)
                    else:
                        self._conn_failed(conn, err)
                    # legacy/escalated: the dead-check below raises
                if verdict & _inject.DROP:
                    if not conn.rel:
                        return  # legacy drop: the frame just vanishes
                    # reliable drop: retain but skip the transmit — the
                    # retransmit timer heals the hole
                    drop = True
                dup = bool(verdict & _inject.DUP)
                corrupt = bool(verdict & _inject.CORRUPT)
        zflag = 0
        level = int(_compress_var._value)  # one live-Var load when off
        if level > 0 and conn.peer_z and \
                nbytes >= int(_compress_min_var._value):
            z = zlib.compress(mv, level)
            if len(z) < nbytes:  # incompressible data stays raw
                from ompi_tpu import quant as _quant

                _quant.note_wire(nbytes, len(z))
                mv = z
                nbytes = len(z)
                zflag = _ZFLAG
        lenw = _LEN.pack((HDR_SIZE + nbytes) | zflag)
        if nbytes:
            vecs: List = [lenw, header, mv]
        else:
            vecs = [lenw, header]
        if corrupt and not conn.rel:
            # historical hazard, preserved for the A/B contrast: a
            # corrupted legacy frame is delivered as garbage (or kills
            # the link, if compressed) — there is no CRC to catch it.
            # Only a wire COPY is corrupted; the caller's buffer stays
            # clean either way.
            vecs = _corrupt_wire_copy(vecs)
            if len(vecs) > 2:
                mv = vecs[2]
            else:
                header = vecs[1]
        if dup and not conn.rel:
            vecs = vecs + vecs
        with conn.wlock:
            # dead-check under wlock: _conn_failed flips dead/clears the
            # write queue under the same lock, so a frame can't slip
            # past the check into a cleared queue
            if conn.dead is not None:
                self._raise_dead(conn)
            if _copy_mode_var._value:
                # legacy A/B datapath: bypasses the reliability
                # envelope by design — per-frame flags keep an engaged
                # peer's parser happy, but these frames are NOT
                # retained (the reliable cvar help tells copy_mode
                # bench runs to set reliable=0)
                self._send_legacy(conn, lenw, header, mv, dup)
                return
            if conn.rel:
                cls = header[0] >> QOS_SHIFT
                txv = self._rel_envelope(conn, header, mv, nbytes,
                                         zflag, cls, sent_at)
                self._evict_window(conn)
                if conn.dead is not None:
                    # window overflow while degraded escalated inline
                    self._raise_dead(conn)
                if drop or conn.state != "est":
                    # retained, not transmitted: a degraded link
                    # replays at resync; an injected drop heals via
                    # the retransmit timer
                    return
                wire = _corrupt_wire_copy(txv) if corrupt else list(txv)
                if dup:
                    wire += list(txv)
                self._rel_transmit(conn, wire, cls)
            elif _qos._enable_var._value and conn.peer_q:
                # shaped path: per-class sub-queues drained by the
                # weighted-deficit scheduler (poke below still runs —
                # a backlog may have been queued)
                self._send_shaped(conn, vecs, header[0] >> QOS_SHIFT)
            else:
                if conn.cur is not None or \
                        (conn.wqs is not None and any(conn.wqs)):
                    # shaped residue after a shape_enable flip: older
                    # frames must hit the wire first
                    self._fold_shaped_residue(conn)
                if conn.wbuf:
                    # legacy residue after a copy_mode flip: older
                    # frames must hit the wire first
                    conn.wq.append(bytes(conn.wbuf))
                    conn.wbuf.clear()
                backlog = bool(conn.wq)
                if not backlog:
                    # fast path: push straight from the caller's buffer
                    vecs = self._try_send(conn, vecs)
                    if not vecs:
                        return  # fully on the wire (or conn failed): 0 copies
                # backpressure: own the unsent remainder — the ONE copy
                # the zero-copy path ever pays, and only for bytes the
                # kernel would not take now
                for v in vecs:
                    if isinstance(v, memoryview):
                        _ctr["copied"] += len(v)
                        v = bytes(v)
                    conn.wq.append(v)
                if backlog:
                    self._flush_locked(conn)
                else:
                    self._want_write(conn, True)
        # a backlog was (or may still be) queued: wake a progress loop
        # parked in the idle select so the flush doesn't wait out the
        # park interval — the park's write-fd list was computed before
        # this conn wanted write
        from ompi_tpu.runtime import progress as _progress

        _progress.poke()

    def _fold_wq_legacy(self, conn: _Conn) -> None:
        """Vectored residue after a copy_mode flip: fold the deque into
        the legacy concat queue, oldest first. Caller holds wlock."""
        while conn.wq:
            conn.wbuf += conn.wq.popleft()  # mpilint: disable=hot-copy — mode-flip bridge into the legacy A/B queue

    def _send_legacy(self, conn: _Conn, lenw: bytes, header: bytes,
                     mv, dup: bool) -> None:
        """The pre-vectored datapath, verbatim (btl_tcp_copy_mode=1,
        the bench A/B baseline): unconditional eager-payload copy,
        frame concat, bytes-concat queue append, byte-wise flush. The
        copies feed btl_tcp_bytes_copied so copies-per-wire-byte is
        MEASURED on the real legacy code, not modeled. Caller holds
        conn.wlock and has done the dead-check."""
        if conn.cur is not None or \
                (conn.wqs is not None and any(conn.wqs)):
            # shaped residue after a copy_mode flip: a partially-written
            # shaped frame MUST finish (and older shaped frames must
            # drain) before legacy bytes hit the wire, or the stream
            # desyncs / same-class frames overtake their seqs
            self._fold_shaped_residue(conn)
        payload = bytes(mv)  # the old eager copy (pre-PR tcp.py:277)  # mpilint: disable=hot-copy — legacy A/B path reproduces the old copies on purpose
        frame = lenw + header + payload
        _ctr["copied"] += len(payload) + len(frame)
        self._fold_wq_legacy(conn)
        conn.wbuf += frame  # mpilint: disable=hot-copy — legacy A/B path reproduces the old concat queue on purpose
        _ctr["copied"] += len(frame)
        if dup:
            conn.wbuf += frame  # mpilint: disable=hot-copy — legacy A/B path
            _ctr["copied"] += len(frame)
        self._flush_legacy(conn)

    def _flush_legacy(self, conn: _Conn) -> None:
        """The pre-vectored flush: byte-wise send + O(n) front-trim of
        the concat queue (O(n^2) across a backlog — the measured tax).
        Caller holds conn.wlock."""
        if conn.cur is not None or \
                (conn.wqs is not None and any(conn.wqs)):
            # shaped residue after a copy_mode flip: ordered first
            self._fold_shaped_residue(conn)
        self._fold_wq_legacy(conn)
        while conn.wbuf:
            try:
                sent = conn.sock.send(conn.wbuf)
            except socket.error as e:
                if e.errno in (errno.EAGAIN, errno.EWOULDBLOCK):
                    self._want_write(conn, True)
                    return
                self._conn_failed(conn, e)
                return
            if sent <= 0:
                self._want_write(conn, True)
                return
            _ctr["wire"] += sent
            if _forensics._enable_var._value:  # last-tx dump evidence
                conn.last_tx = time.monotonic()
            del conn.wbuf[:sent]
        self._want_write(conn, False)

    def _try_send(self, conn: _Conn, vecs: List) -> List:
        """Vectored push of ``vecs`` until the socket blocks; returns
        the unsent remainder as views (the caller owns copying them).
        Caller holds conn.wlock. On a fatal error the conn is failed
        and [] returned — the bytes are lost and the NEXT send to this
        peer raises (same contract as the old flush path)."""
        max_vecs = int(_vecs_var._value)
        while vecs:
            try:
                sent = conn.sock.sendmsg(vecs[:max_vecs])
            except socket.error as e:
                if e.errno in (errno.EAGAIN, errno.EWOULDBLOCK):
                    return vecs
                # Fatal send error: queued (and eagerly-completed) bytes
                # are lost. Surface it — mark the conn dead, tell the
                # failure detector, fail future sends (ADVICE r1).
                self._conn_failed(conn, e)
                return []
            if sent <= 0:
                return vecs
            _ctr["writev"] += 1
            _ctr["wire"] += sent
            if _forensics._enable_var._value:  # last-tx dump evidence
                conn.last_tx = time.monotonic()
            while sent:
                l0 = len(vecs[0])
                if sent >= l0:
                    sent -= l0
                    vecs.pop(0)
                else:
                    # O(1) partial-consume: slice the view, no copy
                    vecs[0] = memoryview(vecs[0])[sent:]
                    sent = 0
        return vecs

    def _raise_dead(self, conn: _Conn) -> None:
        """Raise the dead-conn error for a send. ULFM class when the
        failure detector confirmed the peer's death — user recovery
        code keys off this code."""
        from ompi_tpu.core.errors import (MPIError, ERR_OTHER,
                                          ERR_PROC_FAILED)
        from ompi_tpu.ft.detector import known_failed

        code = ERR_PROC_FAILED if conn.peer in known_failed() \
            else ERR_OTHER
        raise MPIError(
            code,
            f"connection to rank {conn.peer} is dead: {conn.dead}")

    # --------------------------------------------------- link reliability
    # btl_tcp_reliable=1 (handshake-engaged): every data frame out of
    # send() is wrapped in the _RELHDR envelope and RETAINED until the
    # peer's cumulative ack covers it; the receive side verifies CRC,
    # dedups by link seq and NACKs holes; a failed ESTABLISHED conn
    # degrades (redial + resync + replay) instead of dying. The methods
    # below are that whole state machine.
    def _rel_envelope(self, conn: _Conn, header, mv, nbytes: int,
                      zflag: int, cls: int,
                      sent_at: Optional[float] = None) -> List:
        """Build + RETAIN one immutable reliable envelope; returns its
        vec list. Caller holds conn.wlock (seq assignment must be
        atomic with transmit order). Ownership copies happen here: the
        retained frame must outlive the caller's buffer no matter what
        the kernel takes now, so this path trades the zero-copy fast
        path's deferred copy for an up-front one (charged to
        btl_tcp_bytes_copied — the A/B delta vs reliable=0 measures
        the reliability tax honestly)."""
        if not isinstance(header, bytes):
            header = bytes(header)
        if isinstance(mv, memoryview):
            _ctr["copied"] += nbytes
            mv = bytes(mv)  # mpilint: disable=hot-copy — retention ownership: the retransmit window outlives the caller's buffer
        conn.tx_seq += 1
        seq = conn.tx_seq
        ack = conn.rx_floor
        # CRC over the WHOLE envelope after the length word (seq, ack,
        # header, payload): a corrupted piggyback ack must fail the
        # check too, not silently release retained frames
        crc = zlib.crc32(header, zlib.crc32(_RELSA.pack(seq, ack)))
        if nbytes:
            crc = zlib.crc32(mv, crc)
        head = _RELHDR.pack((12 + HDR_SIZE + nbytes) | zflag | _RFLAG,
                            seq, ack, crc & 0xFFFFFFFF)
        vecs: List = [head, header, mv] if nbytes else [head, header]
        wire = 4 + 12 + HDR_SIZE + nbytes
        # sent_at: send() pre-stamps before the chaos inject hook (an
        # injected delay() sleeps inline there, and that latency must
        # land inside the RTT sample like a slow wire's would)
        conn.retx[seq] = (wire, vecs,
                          time.monotonic() if sent_at is None
                          else sent_at, cls)
        conn.retx_bytes += wire
        return vecs

    def _evict_window(self, conn: _Conn) -> None:
        """Bound the retained-frame window (btl_tcp_retx_window_bytes).
        Healthy link: evict oldest unacked, remembering the high-water
        released seq — a later resync that needs it escalates as
        disagreement. Degraded link: the window IS the replay
        guarantee, so overflow escalates now. Caller holds wlock."""
        window = int(_retx_window_var._value)
        if conn.retx_bytes <= window:
            return
        if conn.state != "est":
            self._link_escalate(conn, OSError(
                f"retransmit window overflow ({conn.retx_bytes} bytes "
                f"retained) while link degraded"))
            return
        while conn.retx_bytes > window and len(conn.retx) > 1:
            seq = next(iter(conn.retx))
            nb = conn.retx.pop(seq)[0]
            conn.karn.discard(seq)
            conn.retx_bytes -= nb
            if seq > conn.tx_released:
                conn.tx_released = seq
            _lctr["released"] += 1  # mpiracer: disable=cross-thread-race — relaxed counter, same discipline as _ctr; pvar readers tolerate a stale view

    def _rel_transmit(self, conn: _Conn, vecs: List, cls: int) -> None:
        """Route one already-OWNED frame (envelope, control, or
        retransmit) to the wire through the same scheduling the data
        path uses — shaped per-class when QoS is engaged (control
        frames ride LATENCY), plain FIFO otherwise. Folding into the
        plain queue while a shaped backlog exists would destroy the
        scheduler's ordering, hence the mirror of send()'s routing.
        Caller holds conn.wlock and has done the dead-check."""
        if _qos._enable_var._value and conn.peer_q:
            self._send_shaped(conn, vecs, cls)
            return
        if conn.cur is not None or \
                (conn.wqs is not None and any(conn.wqs)):
            # shaped residue after a shape_enable flip: ordered first
            self._fold_shaped_residue(conn)
        if conn.wbuf:
            conn.wq.append(bytes(conn.wbuf))
            conn.wbuf.clear()
        backlog = bool(conn.wq)
        if not backlog:
            vecs = self._try_send(conn, vecs)
            if not vecs:
                return
        for v in vecs:
            if isinstance(v, memoryview):
                v = bytes(v)
            conn.wq.append(v)
        if backlog:
            self._flush_locked(conn)
        else:
            self._want_write(conn, True)

    def _send_ctrl(self, conn: _Conn, typ: int, a: int, b: int) -> None:
        """Emit one link-control frame (ACK/NACK/RESYNC). Dropped
        silently on a dead or degraded link — control state is
        re-derived after resync, and control frames are never
        retained."""
        with conn.wlock:
            if conn.dead is not None or conn.state != "est":
                return
            body = _LCTL.pack(typ, a & 0xFFFFFFFF, b & 0xFFFFFFFF)
            frame = _RELSA.pack(_LFLAG | _CTL_LEN,
                                zlib.crc32(body) & 0xFFFFFFFF) + body
            self._rel_transmit(conn, [frame], _qos.LATENCY)

    def _rel_send_ack(self, conn: _Conn) -> None:
        """Cumulative ack (cadence or timer). Runs only under the
        progress engine's single-drainer exclusivity — the unacked
        counters are touched by no other thread."""
        conn.unacked_n = 0
        conn.unacked_b = 0
        conn.last_ack_tx = time.monotonic()
        self._send_ctrl(conn, _CTL_ACK, conn.rx_floor, 0)

    def _rel_ack_rx(self, conn: _Conn, ackv: int) -> None:
        """Cumulative-ack bookkeeping (piggyback, ACK, NACK and RESYNC
        floors all funnel here): release retained frames at or below
        ``ackv``. The lock-free pre-check keeps the per-frame rx cost
        at one compare when the ack is stale."""
        if ackv <= conn.tx_acked:  # mpiracer: disable=cross-thread-race — monotonic-int pre-check; the locked re-check below decides
            return
        sample = None
        with conn.wlock:
            if ackv <= conn.tx_acked:
                return
            conn.tx_acked = ackv
            retx = conn.retx
            now = time.monotonic()
            for seq in [s for s in retx if s <= ackv]:
                wire, _vecs, ts, cls = retx.pop(seq)
                conn.retx_bytes -= wire
                conn.acked_b[cls] += wire  # DELIVERED bytes: goodput
                if seq in conn.karn:
                    # Karn: an ack after a retransmission is ambiguous
                    # about which copy it acknowledges — never sample
                    conn.karn.discard(seq)
                else:
                    # one cumulative ack releases a batch; the
                    # youngest released frame carries the least
                    # ack-coalescing delay, so it is the sample
                    sample = now - ts
            conn.retx_strikes = 0  # ack progress resets the timer
            if sample is not None and sample >= 0.0:
                # Jacobson/Karn fold (RFC 6298 constants), kept on the
                # conn: the adaptive retransmit timer reads it even
                # with the linkmodel plane off
                if conn.rtt_n == 0:
                    conn.srtt = sample
                    conn.rttvar = sample / 2.0
                else:
                    d = sample - conn.srtt
                    conn.srtt += 0.125 * d
                    conn.rttvar += 0.25 * (abs(d) - conn.rttvar)
                conn.rtt_n += 1
        if sample is not None and sample >= 0.0 \
                and _linkmodel._enable_var._value:
            _linkmodel.note_rtt_sample(conn.peer, sample)

    def _rel_retransmit(self, conn: _Conn) -> None:
        """NACK service: retransmit every retained frame in seq order
        (sender-side go-back-N — the receiver's dedup makes overlap
        free and the window bound keeps the tail small). Rate-limited:
        a burst of NACKs from one corruption storm must not multiply
        the resend."""
        now = time.monotonic()
        with conn.wlock:
            if conn.dead is not None or conn.state != "est" \
                    or not conn.retx:
                return
            if now - conn.last_retx_t < 0.02:
                return  # this storm already triggered a resend
            conn.last_retx_t = now
            for seq in list(conn.retx):
                if conn.dead is not None or conn.state != "est":
                    break  # a transmit failure degraded us mid-loop
                nb, vecs, _ts, cls = conn.retx[seq]
                conn.retx[seq] = (nb, vecs, now, cls)  # re-age
                conn.karn.add(seq)  # Karn: never RTT-sample this seq
                conn.retx_n += 1
                conn.nack_retx_n += 1
                _lctr["retransmits"] += 1
                self._rel_transmit(conn, list(vecs), cls)

    def _rel_ctrl_rx(self, conn: _Conn, body) -> None:
        """Parse one link-control frame body:
        [u32 crc32][u8 type][u32 a][u32 b]. A control frame failing
        its own CRC is silently dropped (counted): acks and nacks
        regenerate on the timers, and a lost RESYNC re-triggers the
        redial."""
        if len(body) != _CTL_LEN:
            conn.crc_errs += 1
            conn.last_crc = time.monotonic()
            _lctr["crc_errors"] += 1  # mpiracer: disable=cross-thread-race — relaxed counter, same discipline as _ctr; pvar readers tolerate a stale view
            return
        crc = _LEN.unpack_from(body, 0)[0]
        if zlib.crc32(body[4:]) & 0xFFFFFFFF != crc:
            conn.crc_errs += 1
            conn.last_crc = time.monotonic()
            _lctr["crc_errors"] += 1  # mpiracer: disable=cross-thread-race — relaxed counter, same discipline as _ctr; pvar readers tolerate a stale view
            return
        typ, a, b = _LCTL.unpack_from(body, 4)
        if typ == _CTL_ACK:
            self._rel_ack_rx(conn, a)
        elif typ == _CTL_NACK:
            self._rel_ack_rx(conn, a)  # the floor is a cumulative ack
            self._rel_retransmit(conn)
        elif typ == _CTL_RESYNC:
            self._rel_resync_rx(conn, a, b)

    def _resync_frame(self, conn: _Conn) -> bytes:
        """RESYNC control frame: my cumulative rx floor (an ack for
        everything I hold) + the next seq I will send. The reads are
        lock-free on purpose — a slightly stale floor only makes the
        peer replay more, which the dedup absorbs."""
        body = _LCTL.pack(
            _CTL_RESYNC,
            conn.rx_floor & 0xFFFFFFFF,  # mpiracer: disable=cross-thread-race — stale floor over-replays, dedup absorbs (see docstring)
            (conn.tx_seq + 1) & 0xFFFFFFFF)  # mpiracer: disable=cross-thread-race — see docstring
        return _RELSA.pack(_LFLAG | _CTL_LEN,
                           zlib.crc32(body) & 0xFFFFFFFF) + body

    def _rel_resync_rx(self, conn: _Conn, peer_floor: int,
                       peer_tx_next: int) -> None:
        """Resync exchange on a (re)connected reliable link: the peer
        reports its cumulative rx floor (acking everything it has) and
        the next seq it will send. Agreement → release the acked tail,
        replay everything still retained, back to ESTABLISHED — the
        pml never saw the outage. Disagreement — the peer needs a
        frame the healthy-link window already evicted, or it resumes
        below our delivered floor (a restarted peer) — is
        unrecoverable stream damage: escalate to the legacy failure
        path."""
        esc: Optional[OSError] = None
        restored = False
        with conn.wlock:
            if conn.dead is not None or not conn.rel:
                return
            self._rel_ack_rx(conn, peer_floor)
            if peer_floor < conn.tx_released:
                esc = OSError(
                    f"resync disagreement: peer acked {peer_floor} "
                    f"but unacked frames through {conn.tx_released} "
                    f"were already evicted from the window")
            elif peer_tx_next and peer_tx_next - 1 < conn.rx_floor:
                esc = OSError(
                    f"resync disagreement: peer resumes at seq "
                    f"{peer_tx_next} below our delivered floor "
                    f"{conn.rx_floor} (restarted peer?)")
            else:
                was_degraded = conn.state == "degraded"
                redials = conn.redial_n
                conn.state = "est"
                conn.esc_eof = False
                conn.retx_strikes = 0
                conn.last_retx_t = 0.0
                conn.redial_n = 0
                # queued wire copies raced the old socket and are
                # stale; every frame that matters is in retx
                conn.wq.clear()
                conn.wbuf.clear()
                self._drop_shaped(conn)
                now = time.monotonic()
                replayed = len(conn.retx)
                for seq in list(conn.retx):
                    if conn.dead is not None or conn.state != "est":
                        break  # transmit failure re-degraded us
                    nb, vecs, _ts, cls = conn.retx[seq]
                    conn.retx[seq] = (nb, vecs, now, cls)
                    conn.karn.add(seq)  # replay = retransmit: no sample
                    conn.retx_n += 1
                    _lctr["retransmits"] += 1
                    self._rel_transmit(conn, list(vecs), cls)
                self._rel_send_ack(conn)
                if was_degraded and conn.state == "est":
                    restored = True
                    _lctr["recoveries"] += 1
                    outage = now - conn.degraded_at
                    if _metrics._enable_var._value:
                        _metrics.observe("btl_tcp_link_outage_us",
                                         outage * 1e6)
                    if _trace.enabled():
                        _trace.instant("btl_tcp.link_restored",
                                       cat="btl", peer=conn.peer,
                                       outage_s=round(outage, 4))
                    self.log.warning(
                        "link to rank %s restored after %.3fs "
                        "(%d redial(s), %d frame(s) replayed)",
                        conn.peer, outage, redials, replayed)
        if esc is not None:
            self._link_escalate(conn, esc)
            return
        if restored:
            from ompi_tpu.ft.detector import note_link_restored

            note_link_restored(conn.peer,
                               link=self._conn_link_stats(conn))
            cb = self.link_restored_cb
            if cb is not None:
                # pml dead-letter replay seam (wireup binds it): frames
                # the pml stashed while this link looked dead go back
                # on the wire now
                try:
                    cb(conn.peer)
                except Exception:
                    self.log.exception("link_restored callback failed")

    def _conn_failed(self, conn: _Conn, err: OSError,
                     eof: bool = False) -> None:
        """A connection died under traffic. On a reliability-engaged
        ESTABLISHED link this is an INTERRUPT — degrade and redial;
        the pml never hears about it unless healing fails
        (_link_escalate). Everything else takes the legacy path: drop
        the conn, surface the loss (reference: btl/tcp endpoint error
        → pml error callback; here the ULFM detector is the
        propagation plane)."""
        if conn.rel and conn.dead is None and not self._closed:
            if conn.state == "degraded":
                return  # already healing; the redialer/timer owns it
            self._link_interrupt(conn, err, eof)
            return
        with conn.wlock:
            conn.dead = err
            conn.wq.clear()
            conn.wbuf.clear()
            self._drop_shaped(conn)
        self.log.error("i/o with rank %s failed: %s", conn.peer, err)
        self._unregister(conn)
        # The dead conn stays in self.conns: bytes already queued (and
        # eagerly completed) were lost, so silently reconnecting would hide
        # a hole in the message stream — subsequent sends raise instead.
        # mark_failed stays UNCONDITIONAL here (unlike the EOF path): the
        # exit-fence abandon predicate and the failure flood both key off
        # known_failed() even in non-FT jobs. The pml's request-failing
        # sweep is what gates on ft_enable — without the detector armed a
        # single-rail write error must not fail requests a healthy
        # fallback rail can still re-drive.
        if conn.peer is not None:
            from ompi_tpu.ft.detector import mark_failed

            mark_failed(conn.peer)

    def _conn_link_stats(self, conn: _Conn) -> dict:
        """How the link was performing at a degrade/restore edge — the
        ft detector carries this into its forensics debug_state and
        the mpidiag LINK line (lock-free diagnostic snapshot)."""
        st = {  # mpiracer: disable=cross-thread-race — lock-free diagnostic snapshot, see docstring
            "srtt_us": round(conn.srtt * 1e6, 1) if conn.rtt_n else None,
            "rtt_samples": conn.rtt_n,
            "loss_ppm": round(1e6 * conn.nack_retx_n
                              / max(conn.tx_seq, 1), 1),
            "goodput_bps": None,
        }
        if _linkmodel._enable_var._value:
            row = _linkmodel.edge(conn.peer)
            if row is not None:
                st["goodput_bps"] = round(
                    sum(row["goodput_bps"].values()), 1)
        return st

    def _link_interrupt(self, conn: _Conn, err: OSError,
                        eof: bool) -> None:
        """Enter LINK_DEGRADED: close the broken socket but KEEP the
        conn (dead stays None — sends keep landing in the retransmit
        window), then start the bounded redial. The LOWER rank
        redials — one dialer per edge, or both sides race fresh
        sockets at each other and half-adopt two; the higher rank runs
        a liveness PROBE loop instead (so a dead peer is noticed in
        ~3 refused connects, not at the deadline) and waits for the
        acceptor-side adoption. Escalation is the progress timer's
        job, never the redial thread's."""
        with conn.wlock:
            if conn.dead is not None or conn.state == "degraded":
                return
            conn.state = "degraded"
            conn.esc_eof = bool(eof)
            now = time.monotonic()
            conn.degraded_at = now
            conn.redial_deadline = now + float(_link_deadline_var._value)
            conn.redial_n = 0
            # queued wire copies fold away: every enveloped frame is
            # already retained, replay happens from the window
            conn.wq.clear()
            conn.wbuf.clear()
            self._drop_shaped(conn)
        self._unregister(conn)  # closes the socket; conn STAYS in conns
        self.log.warning(
            "link to rank %s degraded (%s): redialing, budget %d "
            "attempts / %.1fs", conn.peer, err,
            int(_link_retries_var._value),
            float(_link_deadline_var._value))
        if _trace.enabled():
            _trace.instant("btl_tcp.link_degraded", cat="btl",
                           peer=conn.peer, err=str(err))
        from ompi_tpu.ft.detector import note_link_degraded

        note_link_degraded(conn.peer, link=self._conn_link_stats(conn))
        if conn.peer is not None:
            t = threading.Thread(
                target=self._redial_loop,
                args=(conn, conn.degraded_at), daemon=True,
                name=f"ompi-tpu-tcp-redial-{conn.peer}")
            t.start()

    def _redial_loop(self, conn: _Conn, epoch: float) -> None:
        """Redial/probe daemon for one outage of one degraded link
        (``epoch`` is the outage's degraded_at stamp — a later outage
        starts its own thread and this one stands down). The
        utils/backoff schedule bounds it; ESCALATION is not this
        thread's job — the progress timer owns the deadline (a wedged
        progress engine must not leave escalation racing finalize).
        Consecutive connection-refused attempts collapse the deadline:
        a transiently severed WIRE times out or resets, but a DEAD
        PROCESS refuses — waiting out the full outage budget for a
        closed listener would stretch real failure detection by the
        whole grace window."""
        dialer = self.my_rank < conn.peer
        sched = _backoff.Schedule(
            base_s=float(_link_backoff_var._value) / 1000.0,
            cap_s=2.0,
            retries=int(_link_retries_var._value),
            deadline_s=float(_link_deadline_var._value))
        refused = 0
        while not (self._closed or conn.dead is not None
                   or conn.state != "degraded"
                   or conn.degraded_at != epoch):
            try:
                if dialer:
                    if self._redial_once(conn, epoch):
                        return
                else:
                    self._probe_once(conn)
            except ConnectionRefusedError:
                refused += 1
                if refused >= 3:
                    # mpiracer: disable=cross-thread-race — monotonic clamp read by the timer tick
                    conn.redial_deadline = min(conn.redial_deadline,
                                               time.monotonic())
                    return  # the timer escalates on its next pass
            except OSError:
                refused = 0
            conn.redial_n += 1  # mpiracer: disable=cross-thread-race — diagnostic counter, single-writer (this thread)
            if not sched.sleep():
                return  # budget spent; the timer escalates at deadline

    def _redial_once(self, conn: _Conn, epoch: float) -> bool:
        """One redial attempt (lower rank): blocking dial + resync
        handshake, then adopt the fresh socket under wlock. True =
        adopted, or the outage resolved some other way; False/raise =
        retry."""
        peer = conn.peer
        if _inject._enable_var._value and \
                _inject.link_down(self.my_rank, peer):
            raise OSError("link down (ft_inject_plan outage window)")
        addr = self.peers.get(peer)
        if addr is None:
            return False  # no address card; the deadline escalates
        host, port = addr.rsplit(":", 1)
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        try:
            _apply_bufs(s)
            s.settimeout(2.0)
            s.connect((host, int(port)))
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            caps = (_CAP_COMPRESS | _CAP_QOS | _CAP_RELIABLE
                    | _CAP_RESYNC)
            s.sendall(_LEN.pack(self.my_rank | caps))
            s.sendall(self._resync_frame(conn))
            s.settimeout(None)
        except BaseException:
            s.close()  # a failed attempt must not leak the fd
            raise
        with conn.wlock:
            if self._closed or conn.dead is not None \
                    or conn.state != "degraded" \
                    or conn.degraded_at != epoch:
                s.close()
                return True  # outage resolved some other way
            s.setblocking(False)
            conn.sock = s
            conn.await_ack = True  # fresh socket, fresh ack word
            conn.rstart = conn.rend = 0
            conn.rbuf.clear()
            conn.reconnects += 1
        with self._sel_lock:
            try:
                self.sel.register(s, selectors.EVENT_READ,
                                  ("peer", conn))
            except (KeyError, ValueError, RuntimeError):
                return True  # selector closed: finalize race
        from ompi_tpu.runtime import progress as _progress

        _progress.poke()
        return True

    def _probe_once(self, conn: _Conn) -> None:
        """One liveness probe (higher rank — the acceptor side of the
        redial): connect to the peer's listener and close. Success
        proves the PROCESS is alive (the real resync arrives through
        our acceptor when the peer's dialer gets through); refusal
        propagates to the loop's fast-escalate counter. The accepting
        side sees a 0-byte handshake and drops the socket."""
        if _inject._enable_var._value and \
                _inject.link_down(self.my_rank, conn.peer):
            raise OSError("link down (ft_inject_plan outage window)")
        addr = self.peers.get(conn.peer)
        if addr is None:
            return
        host, port = addr.rsplit(":", 1)
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        try:
            s.settimeout(2.0)
            s.connect((host, int(port)))
        finally:
            s.close()

    def _link_escalate(self, conn: _Conn, err: OSError) -> None:
        """Healing failed (redial budget blown, detector-confirmed
        death, resync disagreement, permanent injected sever): fall
        through to the pre-reliability failure contract — dead conn,
        failure detector, pml failover/dead-letter. One deliberate
        nuance: mark_failed honors the EOF gate the original interrupt
        carried. An EOF in a non-FT job never marked the peer failed
        before reliability existed, and escalating a degraded-EOF link
        must not change that; write errors stay unconditional."""
        with conn.wlock:
            if conn.dead is not None:
                return
            conn.dead = err
            eof = conn.esc_eof
            conn.wq.clear()
            conn.wbuf.clear()
            self._drop_shaped(conn)
            conn.retx.clear()
            conn.retx_bytes = 0
            conn.rx_seen.clear()
        self.log.error(
            "link to rank %s failed permanently (%.3fs degraded): %s",
            conn.peer,
            (time.monotonic() - conn.degraded_at)
            if conn.degraded_at else 0.0, err)
        self._unregister(conn)
        if _trace.enabled():
            _trace.instant("btl_tcp.link_escalated", cat="btl",
                           peer=conn.peer, err=str(err))
        if _forensics._enable_var._value:
            # cross-rank dump at the verdict moment, while the
            # evidence (retx depths, redial counts, peer vantage
            # points) is still warm
            _forensics.trigger(
                f"btl_tcp link to rank {conn.peer} escalated: {err}")
        if conn.peer is not None:
            from ompi_tpu.ft.detector import mark_failed

            if not eof or get_var("ft", "enable"):
                mark_failed(conn.peer)

    def _conn_timeout(self, conn: _Conn, ceiling_s: float) -> float:
        """Effective retransmit timeout for one conn. With the
        RTT-adaptive timer on (btl_tcp_retx_adaptive, default) and
        enough Karn-accepted samples folded, the classic
        srtt + 4*rttvar RTO applies — floored so ack coalescing never
        reads as loss, and CEILINGED by btl_tcp_retx_timeout_ms: a
        fast link retransmits in a few RTTs instead of waiting out a
        wan-sized constant, a slow link inflates toward the cvar and
        stops striking spuriously."""
        if _retx_adaptive_var._value \
                and conn.rtt_n >= int(_rtt_min_samples_var._value):
            return min(ceiling_s,
                       max(_RETX_FLOOR_S,
                           conn.srtt + 4.0 * conn.rttvar))
        return ceiling_s

    def _rel_tick(self, now: float) -> int:
        """Link-reliability timer pass (~25ms cadence from progress):
        periodic cumulative acks, retransmit timeouts with strike
        escalation to DEGRADED, and the degraded-link deadline /
        detector checks. Escalation runs HERE, on the progress thread,
        never on a redial thread."""
        with self._conn_lock:
            conns = [c for c in self.conns.values()
                     if c.rel and c.dead is None]
        if not conns:
            return 0
        from ompi_tpu.ft.detector import (known_failed,
                                          note_link_degraded)

        work = 0
        ceiling = max(float(_retx_timeout_var._value), 1.0) / 1000.0
        failed = None
        for conn in conns:
            timeout = self._conn_timeout(conn, ceiling)
            if conn.state != "est":
                # degraded: keep the detector's grace fresh while the
                # window is open, enforce the outage budget
                note_link_degraded(conn.peer)
                if failed is None:
                    failed = known_failed()
                if conn.peer in failed:
                    self._link_escalate(conn, OSError(
                        "peer declared failed during link outage"))
                elif now > conn.redial_deadline:
                    self._link_escalate(conn, OSError(
                        f"link redial budget exhausted "
                        f"({conn.redial_n} attempts, "
                        f"{float(_link_deadline_var._value):.1f}s "
                        f"deadline)"))
                work += 1
                continue
            if (conn.unacked_n or conn.unacked_b) \
                    and now - conn.last_ack_tx > timeout / 2.0:
                self._rel_send_ack(conn)
                work += 1
            if not conn.retx:
                continue
            with conn.wlock:
                if conn.dead is not None or conn.state != "est" \
                        or not conn.retx:
                    continue
                oldest = next(iter(conn.retx.values()))[2]
                if now - oldest <= timeout * (1 + conn.retx_strikes):
                    continue
                if conn.wbuf or conn.wq or (conn.wqs is not None
                                            and any(conn.wqs)):
                    # Local backpressure, not peer silence: the oldest
                    # retained frame may still be queued behind this
                    # conn's own backlog (a bulk storm over small
                    # socket buffers holds megabytes locally), and a
                    # frame that never reached the wire cannot have
                    # been acked yet. Striking here would degrade a
                    # healthy-but-busy link, and the go-back-N resend
                    # would dump the retained tail on top of the very
                    # backlog that stalled it. A dead peer behind a
                    # full queue still fails fast — the drain's write
                    # raises — and the detector heartbeat covers the
                    # half-open case.
                    continue
                conn.retx_strikes += 1
                silent = (conn.last_rx is None
                          or now - conn.last_rx > timeout * 2.0)
                if conn.retx_strikes > 3 and silent:
                    # acks stopped AND the wire went quiet: a
                    # half-open link heals through redial, not blind
                    # retransmission. Inbound bytes veto the verdict —
                    # a peer mid-HOL-stall (its acks serialized behind
                    # a jumbo frame in its own legacy FIFO) is slow,
                    # not dead, and tearing the link down would lose
                    # the very frames the stall was about to deliver.
                    self._conn_failed(conn, OSError(
                        f"no ack progress after {conn.retx_strikes} "
                        f"retransmit timeouts"))
                    work += 1
                    continue
                rnow = time.monotonic()
                conn.last_retx_t = rnow
                for seq in list(conn.retx):
                    if conn.dead is not None or conn.state != "est":
                        break  # transmit failure degraded us mid-loop
                    nb, vecs, _ts, cls = conn.retx[seq]
                    conn.retx[seq] = (nb, vecs, rnow, cls)
                    conn.karn.add(seq)  # Karn: never RTT-sample this seq
                    conn.retx_n += 1
                    _lctr["retransmits"] += 1
                    self._rel_transmit(conn, list(vecs), cls)
                work += 1
        return work

    # ------------------------------------------------- shaped send path
    # btl_tcp_shape_enable=1: every connection drains three class
    # sub-queues (LATENCY/NORMAL/BULK, read from bits 6-7 of the pml
    # kind byte) with a weighted-deficit scheduler instead of one FIFO.
    # FIFO holds WITHIN a class (the pml's per-(peer, class) seq planes
    # depend on it); across classes the scheduler reorders on purpose —
    # that is the whole point. A partially-written frame always
    # finishes first (TCP frames are contiguous on the wire), so the
    # preemption granularity is one frame — which is why the pml
    # segments oversized blobs before they get here.
    def _send_shaped(self, conn: _Conn, vecs: List, cls: int) -> None:
        """Shaped enqueue/send of one frame. Caller holds conn.wlock
        and has done the dead-check."""
        if conn.wqs is None:
            conn.wqs = (deque(), deque(), deque())
        if conn.wbuf:
            # legacy residue after a copy_mode flip: ordered first
            conn.wq.append(bytes(conn.wbuf))
            conn.wbuf.clear()
        if conn.wq:
            # pre-shaping FIFO residue (mode flip, or frames queued
            # before the peer's QoS ack landed): it must hit the wire
            # before any shaped frame. If a partial shaped frame is
            # already mid-write it is older still — append after it.
            if conn.cur is None:
                conn.cur = list(conn.wq)
                conn.cur_cls = _qos.NORMAL
            else:
                conn.cur.extend(conn.wq)
            conn.wq.clear()
        if conn.cur is None and not any(conn.wqs):
            # fast path: push straight from the caller's buffer
            total = sum(len(v) for v in vecs)
            vecs = self._try_send(conn, vecs)
            if not vecs:
                return  # fully on the wire (or conn failed): 0 copies
            # backpressure: own the unsent remainder. A frame with
            # bytes already on the wire is the unpreemptible
            # in-progress frame; one the kernel took NOTHING of is
            # still schedulable — queue it so a LATENCY arrival can
            # jump ahead of an untouched bulk frame.
            cur = []
            left = 0
            for v in vecs:
                left += len(v)
                if isinstance(v, memoryview):
                    _ctr["copied"] += len(v)
                    v = bytes(v)
                cur.append(v)
            if left < total:
                conn.cur = cur
                conn.cur_cls = cls
            else:
                conn.eseq += 1
                conn.wqs[cls].append(
                    (conn.eseq, left, cur, time.monotonic()))
                _shape_ctr["enqueued"] += 1
                with _qlock:
                    _qbytes[cls] += left
                    if _qbytes[cls] > _qpeak[cls]:
                        _qpeak[cls] = _qbytes[cls]
            self._want_write(conn, True)
            return
        # backlog: own the frame into its class sub-queue, then give
        # the scheduler a drain pass (a LATENCY arrival may preempt
        # the queued bulk right now instead of at the next progress)
        nb = 0
        owned = []
        for v in vecs:
            if isinstance(v, memoryview):
                _ctr["copied"] += len(v)
                v = bytes(v)
            owned.append(v)
            nb += len(v)
        conn.eseq += 1
        conn.wqs[cls].append((conn.eseq, nb, owned, time.monotonic()))
        _shape_ctr["enqueued"] += 1
        with _qlock:
            _qbytes[cls] += nb
            if _qbytes[cls] > _qpeak[cls]:
                _qpeak[cls] = _qbytes[cls]
        if cls == _qos.BULK:
            # background enqueue: do NOT drain synchronously — a bulk
            # producer in a tight ship loop would otherwise spend its
            # own timeslice pushing the whole backlog through sendmsg,
            # starving the latency-critical threads the shaper exists
            # to protect. The progress engine drains it (the trailing
            # poke in send() wakes a parked loop).
            self._want_write(conn, True)
        else:
            self._flush_shaped(conn)

    def _flush_shaped(self, conn: _Conn) -> None:
        """Drain the shaped sub-queues: finish the in-progress frame,
        then repeatedly let the deficit scheduler pick the next class.
        Caller holds conn.wlock.

        The drain is BUDGETED per call: a fast kernel (loopback) would
        otherwise accept an entire multi-blob backlog in one loop while
        this thread holds conn.wlock — and a LATENCY frame born on the
        app thread mid-drain would block on the lock for the whole
        serialization, re-creating exactly the head-of-line blocking
        the scheduler exists to remove. Stopping every ~16 quanta
        releases the lock (the yield point between sendmsg calls); the
        selector's write interest re-enters the drain immediately."""
        budget = 16 * max(int(_quantum_var._value), 1)
        sent = 0
        while True:
            if conn.cur is not None:
                before = sum(len(v) for v in conn.cur)
                rem = self._try_send(conn, conn.cur)
                if conn.dead is not None or conn.state != "est":
                    # a fatal send inside _try_send killed OR degraded
                    # the conn (the interrupt cleared cur/wqs inline —
                    # same thread, RLock): nothing left to drain
                    return
                if rem:
                    conn.cur = rem  # socket full mid-frame: resume later
                    self._want_write(conn, True)
                    return
                sent += before
                conn.cur = None
            if sent >= budget:
                # yield point: backlog remains, the lock must breathe
                self._want_write(conn, True)
                return
            cls = self._pick_class(conn)
            if cls is None:
                self._want_write(conn, False)
                return
            wqs = conn.wqs
            # peek-try-commit: a frame the kernel takes NOTHING of
            # stays at its queue head, still schedulable — committing
            # it to `cur` would let an untouched frame block a later
            # preemption for no wire progress
            eseq, nb, owned, ts = wqs[cls][0]
            rem = self._try_send(conn, list(owned))
            if conn.dead is not None or conn.state != "est":
                # killed or degraded mid-send: the queues were cleared
                # under this same RLock — touching wqs[cls] again
                # would IndexError on the emptied deque
                return
            if rem and sum(len(v) for v in rem) == nb:
                self._want_write(conn, True)
                return
            wqs[cls].popleft()
            # preemption = serving ahead of an earlier-enqueued frame
            # of another class (the out-of-FIFO service the per-class
            # scheduler exists to make)
            older = [wqs[c][0][0] for c in _SERVICE_ORDER
                     if c != cls and wqs[c]]
            if older and min(older) < eseq:
                _shape_ctr["preempt"] += 1
            with _qlock:
                _qbytes[cls] -= nb
            if conn.deficit[cls] >= nb:
                # only deficit-granted serves spend credit: a grant
                # that bypassed the deficit check (sole backlogged
                # class, starvation bound) must not drive the counter
                # negative, or a class that ran alone for a while
                # starts a later contention epoch in deep debt and
                # starves against its own weight (classic DRR never
                # goes negative)
                conn.deficit[cls] -= nb
            if not wqs[cls]:
                conn.deficit[cls] = 0  # classic DRR: empty resets
            conn.defer[cls] = 0
            for c in _SERVICE_ORDER:
                if c != cls and wqs[c]:
                    conn.defer[c] += nb
            if _metrics._enable_var._value:
                # per-frame deferral histogram (time queued by class)
                _metrics.observe("btl_tcp_shape_defer_us",
                                 (time.monotonic() - ts) * 1e6,
                                 cls=_qos.NAMES[cls])
            if rem:
                conn.cur = rem  # frame started: must finish first
                conn.cur_cls = cls
                self._want_write(conn, True)
                return
            sent += nb

    def _pick_class(self, conn: _Conn) -> Optional[int]:
        """Next class to serve: the starvation bound first (a class
        past btl_tcp_shape_max_defer_bytes of deferral wins outright —
        BULK always progresses), then weighted-deficit round-robin in
        LATENCY > NORMAL > BULK preference order. Caller holds wlock."""
        wqs = conn.wqs
        nonempty = [c for c in _SERVICE_ORDER if wqs[c]]
        if not nonempty:
            return None
        if len(nonempty) == 1:
            return nonempty[0]
        md = int(_max_defer_var._value)
        if md > 0:
            starved = [c for c in nonempty if conn.defer[c] >= md]
            if starved:
                return max(starved, key=lambda c: conn.defer[c])
        q = max(int(_quantum_var._value), 1)
        w = _weights()
        while True:
            for c in nonempty:
                if conn.deficit[c] >= wqs[c][0][1]:
                    return c
            for c in nonempty:
                conn.deficit[c] += q * w[c]

    def _fold_shaped_residue(self, conn: _Conn) -> None:
        """Shaped residue after a shape_enable flip: fold the partial
        frame and every class sub-queue into the legacy FIFO, oldest
        class-order (cross-class order is arbitrary by construction —
        the shaper had already unordered them). Caller holds wlock."""
        frames: List = []
        if conn.cur is not None:
            frames.extend(conn.cur)
            conn.cur = None
        if conn.wqs is not None:
            for c in _SERVICE_ORDER:
                dq = conn.wqs[c]
                while dq:
                    _eseq, nb, owned, _ts = dq.popleft()
                    frames.extend(owned)
                    with _qlock:
                        _qbytes[c] -= nb
        conn.wq.extendleft(reversed(frames))

    def _drop_shaped(self, conn: _Conn) -> None:
        """Dead conn: release the shaped queues and settle the by-class
        gauges. Caller holds conn.wlock."""
        conn.cur = None
        if conn.wqs is not None:
            for c in _SERVICE_ORDER:
                dq = conn.wqs[c]
                while dq:
                    _eseq, nb, _owned, _ts = dq.popleft()
                    with _qlock:
                        _qbytes[c] -= nb

    def _flush_locked(self, conn: _Conn) -> None:
        """Drain the owned write queue with vectored sends; caller
        holds conn.wlock."""
        if conn.cur is not None or \
                (conn.wqs is not None and any(conn.wqs)):
            # shaped residue after a shape_enable flip: ordered first
            self._fold_shaped_residue(conn)
        if conn.wbuf:
            # legacy residue after a copy_mode flip: ordered first
            conn.wq.appendleft(bytes(conn.wbuf))
            conn.wbuf.clear()
        wq = conn.wq
        max_vecs = int(_vecs_var._value)
        while wq:
            try:
                sent = conn.sock.sendmsg(
                    list(itertools.islice(wq, max_vecs)))
            except socket.error as e:
                if e.errno in (errno.EAGAIN, errno.EWOULDBLOCK):
                    self._want_write(conn, True)
                    return
                self._conn_failed(conn, e)
                return
            if sent <= 0:
                self._want_write(conn, True)
                return
            _ctr["writev"] += 1
            _ctr["wire"] += sent
            if _forensics._enable_var._value:  # last-tx dump evidence
                conn.last_tx = time.monotonic()
            while sent:
                l0 = len(wq[0])
                if sent >= l0:
                    sent -= l0
                    wq.popleft()
                else:
                    # partial first buffer: O(1) reslice over the OWNED
                    # bytes (the deque keeps them alive) — the old
                    # bytearray queue paid an O(n) del wbuf[:sent] here,
                    # O(n^2) across a backlog
                    wq[0] = memoryview(wq[0])[sent:]
                    sent = 0
        self._want_write(conn, False)

    def _want_write(self, conn: _Conn, on: bool) -> None:
        ev = selectors.EVENT_READ | (selectors.EVENT_WRITE if on else 0)
        with self._sel_lock:
            try:
                self.sel.modify(conn.sock, ev, ("peer", conn))
            except (KeyError, ValueError):
                pass

    # ----------------------------------------------------------- progress
    def idle_fds(self) -> Tuple[list, list]:
        """Export (read-fds, write-interest-fds) for the progress
        engine's idle-blocking select: the listener plus every live
        conn, and — so a parked loop resumes flushing — every conn
        with queued writes. A socket closing between export and the
        select is handled by the caller (select raises, treated as a
        wake)."""
        rfds: list = []
        wfds: list = []
        if self._closed:
            return rfds, wfds
        with self._sel_lock:
            try:
                keys = list(self.sel.get_map().values())
            except RuntimeError:  # selector closed by a finalize race
                return rfds, wfds
        for key in keys:
            rfds.append(key.fd)
            if key.events & selectors.EVENT_WRITE:
                wfds.append(key.fd)
        return rfds, wfds

    def progress(self) -> int:
        """Drain ready sockets; called from the progress engine
        (reference: btl progress fns registered at opal_progress.c:416)."""
        if self._closed:
            return 0
        if not self._progress_lock.acquire(blocking=False):
            return 0
        try:
            try:
                with self._sel_lock:
                    events = self.sel.select(timeout=0)
            except OSError:
                return 0
            n = 0
            for key, mask in events:
                kind, conn = key.data
                if kind == "accept":
                    n += self._accept()
                    continue
                if mask & selectors.EVENT_WRITE:
                    with conn.wlock:
                        if _copy_mode_var._value:
                            self._flush_legacy(conn)
                        elif conn.cur is not None or \
                                (conn.wqs is not None and any(conn.wqs)):
                            # shaped backlog pending (regardless of the
                            # cvar's CURRENT value: a flip mid-backlog
                            # must still drain what the shaper queued)
                            self._flush_shaped(conn)
                        else:
                            self._flush_locked(conn)
                if mask & selectors.EVENT_READ:
                    n += self._drain(conn)
            # link-reliability timers (acks, retransmit timeouts,
            # degraded-link deadlines) ride the progress cadence; the
            # _rel_next gate keeps the idle cost at one clock read
            now = time.monotonic()
            if now >= self._rel_next:
                self._rel_next = now + 0.025
                n += self._rel_tick(now)
            return n
        finally:
            self._progress_lock.release()

    def _accept(self) -> int:
        try:
            s, _ = self.listener.accept()
        except OSError:
            return 0
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        # first 4 bytes: peer rank
        s.setblocking(True)
        raw = b""
        while len(raw) < 4:
            chunk = s.recv(4 - len(raw))
            if not chunk:
                return 0
            raw += chunk
        word = _LEN.unpack(raw)[0]
        _ALLCAPS = (_CAP_COMPRESS | _CAP_QOS | _CAP_RELIABLE
                    | _CAP_RESYNC)
        peer = word & ~_ALLCAPS
        if word & _CAP_RESYNC:
            # not a fresh endpoint: a redial resuming an existing
            # reliable link — adopt the socket into the surviving conn
            return self._adopt_redial(s, peer)
        conn = _Conn(s, peer)
        if word & _ALLCAPS:
            # the connector understands zlib-flagged frames / QoS class
            # bits; answer with our ack so it knows we do too (decoding
            # is always available in this build — acceptance is
            # unconditional, per advertised capability). The RELIABLE
            # bit is the exception: engaging it changes OUR wire
            # format, so it follows this side's cvar.
            ack = _ZACK_MAGIC
            if word & _CAP_COMPRESS:
                conn.peer_z = True
                ack |= _ZACK_ACCEPT
            if word & _CAP_QOS:
                conn.peer_q = True
                ack |= _ZACK_QOS
            if word & _CAP_RELIABLE and _reliable_var._value:
                # engage both directions now: every frame we send from
                # here on is enveloped, and TCP ordering puts our ack
                # word ahead of all of them on the peer's side
                conn.rel = conn.rel_rx = True
                ack |= _ZACK_RELIABLE
            try:
                s.sendall(_LEN.pack(ack))
            except OSError:
                # the dialer died mid-handshake; under PR 3's connect
                # retry it will redial — close or each attempt leaks a fd
                try:
                    s.close()
                except OSError:
                    pass
                return 0
        s.setblocking(False)
        with self._conn_lock:
            # keep one canonical conn per peer for sending; both sides may
            # connect simultaneously — every conn gets drained regardless
            self.conns.setdefault(peer, conn)
        with self._sel_lock:
            self.sel.register(s, selectors.EVENT_READ, ("peer", conn))
        return 1

    def _adopt_redial(self, s: socket.socket, peer: int) -> int:
        """Acceptor side of reconnect-and-replay: a _CAP_RESYNC dial
        RESUMES an existing reliable conn. Answer the handshake ack +
        our own RESYNC frame, retire whatever socket the conn held and
        swap the fresh one in under wlock; the normal drain then
        parses the dialer's RESYNC (the replay trigger) off the new
        socket. Refused — socket closed — when there is nothing to
        resume; the dialer's next attempt or its deadline handles
        that."""
        with self._conn_lock:
            conn = self.conns.get(peer)
        if conn is None or not conn.rel or conn.dead is not None \
                or self._closed:
            try:
                s.close()
            except OSError:
                pass
            return 0
        ack = _ZACK_MAGIC | _ZACK_RELIABLE
        if conn.peer_z:
            ack |= _ZACK_ACCEPT
        if conn.peer_q:
            ack |= _ZACK_QOS
        with conn.wlock:
            old = conn.sock
            try:
                s.sendall(_LEN.pack(ack))
                s.sendall(self._resync_frame(conn))
            except OSError:
                try:
                    s.close()
                except OSError:
                    pass
                return 0
            # retire the old socket (already closed if this side had
            # degraded too; a half-open survivor otherwise)
            with self._sel_lock:
                try:
                    self.sel.unregister(old)
                except (KeyError, ValueError):
                    pass
            try:
                old.close()
            except OSError:
                pass
            s.setblocking(False)
            conn.sock = s
            conn.await_ack = False  # acceptor: we SENT the ack word
            # the old socket's partial rx frame is gone with it — the
            # peer's replay covers whatever the tail cut off
            conn.rstart = conn.rend = 0
            conn.rbuf.clear()
            conn.reconnects += 1
        with self._sel_lock:
            try:
                self.sel.register(s, selectors.EVENT_READ,
                                  ("peer", conn))
            except (KeyError, ValueError, RuntimeError):
                return 0  # selector closed: finalize race
        return 1

    def _drain(self, conn: _Conn) -> int:
        if _copy_mode_var._value and not conn.rel_rx:
            # reliability-engaged conns stay on the pooled parser even
            # under copy_mode: the legacy parser cannot interpret the
            # per-frame envelope/control flags
            return self._drain_legacy(conn)
        # pooled receive staging: recv_into this conn's reusable block
        # (one pool hit) instead of a fresh 1 MiB allocation per recv —
        # a 4-byte ack used to cost a megabyte of garbage plus an rbuf
        # concat. Frames are then SLICED out of the block; anything
        # that must outlive it is copied at the pml delivery boundary.
        if conn.rbuf:
            # legacy residue after a copy_mode flip: replay it through
            # the block so frame parsing stays continuous
            self._adopt_legacy_rbuf(conn)
        buf = conn.rxb
        if buf is None:
            buf = conn.rxb = _rx_pool.acquire()  # owns: rxb
            conn.rstart = conn.rend = 0
        if conn.rend == len(buf):
            # no room left: slide the parked partial frame to the
            # front, or grow into a private (unpooled) buffer when one
            # frame is bigger than the block — bounded boundary copies,
            # both charged to btl_tcp_bytes_copied
            pending = conn.rend - conn.rstart
            if conn.rstart > 0:
                buf[:pending] = buf[conn.rstart:conn.rend]
            else:
                total = 0
                if pending >= 4:
                    total = _LEN.unpack_from(buf, 0)[0] & _LEN_MASK
                nbuf = bytearray(max(4 + total, 2 * len(buf)))
                nbuf[:pending] = buf
                # only a pool-sized block goes back: regrowing an
                # ALREADY-grown buffer (a second jumbo outgrowing the
                # first, or legacy-residue adoption that exactly filled
                # its grown buffer) used to release the private
                # bytearray here, spuriously decrementing the pool's
                # outstanding count for a block it never handed out
                if len(buf) == _RX_BLOCK:
                    _rx_pool.release(buf)
                conn.rxb = buf = nbuf
            _ctr["copied"] += pending
            conn.rstart, conn.rend = 0, pending
        try:
            n_in = conn.sock.recv_into(memoryview(buf)[conn.rend:])
        except socket.error as e:
            if e.errno in (errno.EAGAIN, errno.EWOULDBLOCK):
                return 0
            self._conn_failed(conn, e)
            return 0
        if not n_in:
            if conn.rel and conn.dead is None and not self._closed:
                # reliable link: EOF on an established conn INTERRUPTS
                # (degrade + redial) — a severed wire manifests as EOF
                # on the passive side, and this is its heal path. A
                # real peer death redials into a refused listener and
                # fast-escalates; escalation's EOF gate preserves the
                # pre-reliability semantics below (mark_failed only
                # under ft_enable).
                self._conn_failed(
                    conn, ConnectionResetError("closed by peer"),
                    eof=True)
                return 0
            # EOF: could be a peer crash OR a clean peer Finalize — mark
            # the conn dead so later sends raise instead of vanishing.
            # With the ULFM detector armed (ft_enable) the EOF is also
            # reported as a failure vantage point — in an FT job a peer
            # that stops talking IS failed (its heartbeats stop too, so
            # the flood only arrives sooner); without ft_enable a clean
            # shutdown must not raise failure events, so detection stays
            # local.
            if conn.dead is None:
                conn.dead = ConnectionResetError("closed by peer")
            if conn.peer is not None:
                from ompi_tpu.ft.detector import mark_failed

                if get_var("ft", "enable"):
                    mark_failed(conn.peer)
            self._unregister(conn)
            return 0
        _ctr["wire"] += n_in
        if _forensics._enable_var._value or conn.rel:
            # forensics: last-rx dump evidence. Reliable link: inbound
            # liveness — _rel_tick refuses to escalate ack-progress
            # strikes into DEGRADED while bytes are still arriving
            conn.last_rx = time.monotonic()
        conn.rend += n_in
        n = 0
        mv = memoryview(buf)  # borrows: rxb
        off = conn.rstart
        end = conn.rend
        if conn.await_ack and end - off >= 4:
            # the compress-handshake ack leads every frame on a dialed
            # link. Match the FULL word (magic byte + reserved-zero
            # bits + accept bit), not just the high byte: a non-acking
            # peer's first frame could legally be ~1.41 GiB long under
            # the 2 GiB cap, and a high-byte-only match would eat its
            # length word and desync the whole stream
            word = _LEN.unpack_from(buf, off)[0]
            conn.await_ack = False
            if word in _ZACK_WORDS:
                conn.peer_z = bool(word & _ZACK_ACCEPT)
                conn.peer_q = bool(word & _ZACK_QOS)
                if word & _ZACK_RELIABLE:
                    # both sides advertised: envelope from here on (the
                    # frames we sent pre-ack went out legacy-framed —
                    # per-frame flags keep both parseable)
                    conn.rel = conn.rel_rx = True
                off += 4
        while end - off >= 4:
            word = _LEN.unpack_from(buf, off)[0]
            if conn.rel_rx and word & _LFLAG:
                # link-control frame (ACK/NACK/RESYNC)
                total = word & _RLEN_MASK
                if end - off - 4 < total:
                    break
                self._rel_ctrl_rx(conn, mv[off + 4:off + 4 + total])
                off = off + 4 + total
                if conn.dead is not None:
                    # a resync disagreement escalated mid-parse; the
                    # block was discarded with the conn
                    return n
                continue
            if conn.rel_rx and word & _RFLAG:
                # reliability-enveloped data frame:
                # [len|flags][seq][cum_ack][crc32][hdr][payload]
                total = word & _RLEN_MASK
                if end - off - 4 < total:
                    break
                start = off + 4
                off = start + total
                if total < 12 + HDR_SIZE:
                    # structurally impossible envelope: treat like a
                    # CRC failure — drop and NACK
                    conn.crc_errs += 1
                    conn.last_crc = time.monotonic()
                    _lctr["crc_errors"] += 1  # mpiracer: disable=cross-thread-race — relaxed counter, same discipline as _ctr; pvar readers tolerate a stale view
                    self._send_ctrl(conn, _CTL_NACK, conn.rx_floor, 0)
                    continue
                seq, ackv, crc = struct.unpack_from("<III", buf, start)
                hdr = mv[start + 12:start + 12 + HDR_SIZE]
                payload = mv[start + 12 + HDR_SIZE:start + total]
                c = zlib.crc32(mv[start:start + 8])
                c = zlib.crc32(hdr, c)
                c = zlib.crc32(payload, c)
                if c & 0xFFFFFFFF != crc:
                    # CRC mismatch: drop THIS frame only (framing is
                    # intact — the length word is outside the fault
                    # model) and NACK a retransmission. Before the
                    # envelope this was a desynced stream or a
                    # poisoned pml delivery.
                    conn.crc_errs += 1
                    conn.last_crc = time.monotonic()
                    _lctr["crc_errors"] += 1  # mpiracer: disable=cross-thread-race — relaxed counter, same discipline as _ctr; pvar readers tolerate a stale view
                    self._send_ctrl(conn, _CTL_NACK, conn.rx_floor, 0)
                    continue
                self._rel_ack_rx(conn, ackv)
                if seq <= conn.rx_floor or seq in conn.rx_seen:
                    # duplicate (retransmit overlap): drop, but count
                    # toward the ack cadence — the sender needs the
                    # ack to stop resending
                    _lctr["dedup"] += 1  # mpiracer: disable=cross-thread-race — relaxed counter, same discipline as _ctr; pvar readers tolerate a stale view
                    conn.dedup_n += 1
                    conn.unacked_n += 1
                    if conn.unacked_n >= 8 or \
                            conn.unacked_b >= 1 << 20:
                        self._rel_send_ack(conn)
                    continue
                if seq == conn.rx_floor + 1:
                    conn.rx_floor = seq
                    while conn.rx_floor + 1 in conn.rx_seen:
                        conn.rx_seen.discard(conn.rx_floor + 1)
                        conn.rx_floor += 1
                else:
                    # a gap (CRC-dropped or reordered-by-replay frame
                    # in flight): deliver NOW anyway — the pml's
                    # per-(peer, class) seq planes own ordering; the
                    # link layer owns only exactly-once
                    conn.rx_seen.add(seq)
                conn.rx_frames += 1
                conn.unacked_n += 1
                conn.unacked_b += total
                if _copy_mode_var._value:
                    # legacy A/B discipline on an enveloped link: the
                    # legacy parser cannot read envelope flags, so the
                    # pooled parser reproduces its per-frame parse copy
                    # here — copy_mode=1 keeps measuring the copying
                    # baseline on reliable conns too
                    hdr = bytes(hdr)  # mpilint: disable=hot-copy — legacy A/B path reproduces the old parse copy on purpose
                    payload = bytes(payload)  # mpilint: disable=hot-copy — legacy A/B path reproduces the old parse copy on purpose
                    _ctr["copied"] += len(hdr) + len(payload)
                if word & _ZFLAG:
                    try:
                        payload = zlib.decompress(payload)
                    except zlib.error as e:
                        # the CRC passed, so this is not wire noise —
                        # it is a torn negotiation or our bug; the
                        # legacy contract (fail the link) applies
                        self.log.exception("corrupt compressed frame")
                        conn.rstart = off
                        self._conn_failed(conn, OSError(
                            f"corrupt compressed frame from rank "
                            f"{conn.peer}: {e}"))
                        return n
                try:
                    self.deliver(hdr, payload)  # mpiown: disable=escaping-view — synchronous over this block; ob1's _owned gate copies any payload that must survive it
                except Exception:
                    self.log.exception(
                        "frame handler failed (frame dropped)")
                n += 1
                if conn.unacked_n >= 8 or conn.unacked_b >= 1 << 20:
                    self._rel_send_ack(conn)
                continue
            total = word & _LEN_MASK
            if end - off - 4 < total:
                break
            start = off + 4
            # zero-copy parse: header and payload are views over the
            # pool block, valid for the synchronous deliver below; the
            # pml copies at its boundary when a payload must survive it
            hdr = mv[start:start + HDR_SIZE]
            payload = mv[start + HDR_SIZE:start + total]
            off = start + total
            if _copy_mode_var._value:
                # same legacy A/B parse-copy discipline for the
                # plain-framed frames a reliable conn carries (the
                # pre-negotiation tail)
                hdr = bytes(hdr)  # mpilint: disable=hot-copy — legacy A/B path reproduces the old parse copy on purpose
                payload = bytes(payload)  # mpilint: disable=hot-copy — legacy A/B path reproduces the old parse copy on purpose
                _ctr["copied"] += total
            if word & _ZFLAG:
                # negotiated framing: only a handshake-capable peer ever
                # sets the flag, so this build always knows how to undo
                # it. A decompress failure means stream integrity is
                # gone — silently dropping the frame would leave the
                # pml's per-peer sequence waiting forever on a hole, so
                # fail the LINK and let the PR 3 failover/dead-letter
                # machinery take over (same contract as a read error)
                try:
                    payload = zlib.decompress(payload)
                except zlib.error as e:
                    self.log.exception("corrupt compressed frame")
                    conn.rstart = off
                    self._conn_failed(conn, OSError(
                        f"corrupt compressed frame from rank "
                        f"{conn.peer}: {e}"))
                    return n
            # A frame handler may itself send (ob1 replies with CTS/DATA
            # from inside deliver); if that send hits a dead peer the
            # MPIError must not escape — it would skip the cursor
            # advance below (re-delivering frames) and kill the
            # progress thread.
            try:
                self.deliver(hdr, payload)  # mpiown: disable=escaping-view — the deliver is synchronous over this block; ob1's _owned gate copies any payload that must survive it
            except Exception:
                self.log.exception("frame handler failed (frame dropped)")
            n += 1
        if off >= end:
            # block fully parsed: reset the cursors — no memmove, and a
            # buffer grown for a jumbo frame is dropped so the conn
            # reacquires a pooled block on the next drain
            conn.rstart = conn.rend = 0
            if len(buf) != _RX_BLOCK:
                conn.rxb = None
        else:
            conn.rstart = off
        return n

    def _adopt_legacy_rbuf(self, conn: _Conn) -> None:
        """Move legacy rbuf residue (a copy_mode flip mid-stream) into
        the pooled block, growing it if needed. Runs under the drain's
        single-drainer exclusivity."""
        pending = len(conn.rbuf)
        if conn.rxb is None:
            conn.rxb = _rx_pool.acquire()  # owns: rxb
            conn.rstart = conn.rend = 0
        live = conn.rend - conn.rstart
        if live + pending > len(conn.rxb):
            nbuf = bytearray(max(live + pending, 2 * len(conn.rxb)))
            nbuf[:live] = conn.rxb[conn.rstart:conn.rend]
            if len(conn.rxb) == _RX_BLOCK:
                _rx_pool.release(conn.rxb)
            conn.rxb = nbuf
            conn.rstart, conn.rend = 0, live
        elif conn.rend + pending > len(conn.rxb):
            conn.rxb[:live] = conn.rxb[conn.rstart:conn.rend]
            conn.rstart, conn.rend = 0, live
        conn.rxb[conn.rend:conn.rend + pending] = conn.rbuf
        conn.rend += pending
        _ctr["copied"] += pending
        conn.rbuf.clear()

    def _drain_legacy(self, conn: _Conn) -> int:
        """The pre-vectored read path, verbatim (btl_tcp_copy_mode=1,
        the bench A/B baseline): a fresh 1 MiB allocation per recv, an
        rbuf concat, and per-frame header/payload parse copies — all
        charged to btl_tcp_bytes_copied so the legacy copy tax is
        measured on the real legacy code."""
        if conn.rxb is not None and conn.rend > conn.rstart:
            # vectored residue after a copy_mode flip
            conn.rbuf += memoryview(conn.rxb)[conn.rstart:conn.rend]  # mpilint: disable=hot-copy — legacy A/B path adopts the pooled residue
            _ctr["copied"] += conn.rend - conn.rstart
        if conn.rxb is not None:
            if len(conn.rxb) == _RX_BLOCK:
                _rx_pool.discard(conn.rxb)  # mpiracer: disable=cross-thread-race — BufferPool serializes internally (_plock); discard never recycles, so the racing drain keeps sole ownership
            conn.rxb = None
            conn.rstart = conn.rend = 0
        try:
            data = conn.sock.recv(1 << 20)
        except socket.error as e:
            if e.errno in (errno.EAGAIN, errno.EWOULDBLOCK):
                return 0
            self._conn_failed(conn, e)
            return 0
        if not data:
            if conn.dead is None:
                conn.dead = ConnectionResetError("closed by peer")
            if conn.peer is not None:
                from ompi_tpu.ft.detector import mark_failed

                if get_var("ft", "enable"):
                    mark_failed(conn.peer)
            self._unregister(conn)
            return 0
        _ctr["wire"] += len(data)
        if _forensics._enable_var._value:  # last-rx dump evidence
            conn.last_rx = time.monotonic()
        conn.rbuf += data  # mpilint: disable=hot-copy — legacy A/B path reproduces the old rbuf concat on purpose
        _ctr["copied"] += len(data)
        n = 0
        buf = conn.rbuf
        off = 0
        if conn.await_ack and len(buf) >= 4:
            word = _LEN.unpack_from(buf, 0)[0]
            conn.await_ack = False
            if word in _ZACK_WORDS:
                conn.peer_z = bool(word & _ZACK_ACCEPT)
                conn.peer_q = bool(word & _ZACK_QOS)
                if word & _ZACK_RELIABLE:
                    # engaged mid-copy_mode: the NEXT drain dispatches
                    # to the pooled parser (it alone reads the
                    # per-frame envelope flags)
                    conn.rel = conn.rel_rx = True
                off = 4
        while len(buf) - off >= 4:
            word = _LEN.unpack_from(buf, off)[0]
            total = word & _LEN_MASK
            if len(buf) - off - 4 < total:
                break
            start = off + 4
            hdr = bytes(buf[start:start + HDR_SIZE])  # mpilint: disable=hot-copy — legacy A/B path reproduces the old parse copy on purpose
            payload = bytes(buf[start + HDR_SIZE:start + total])  # mpilint: disable=hot-copy — legacy A/B path reproduces the old parse copy on purpose
            _ctr["copied"] += total
            off += 4 + total
            if word & _ZFLAG:
                try:
                    payload = zlib.decompress(payload)
                except zlib.error as e:
                    self.log.exception("corrupt compressed frame")
                    self._conn_failed(conn, OSError(
                        f"corrupt compressed frame from rank "
                        f"{conn.peer}: {e}"))
                    return n
            try:
                self.deliver(hdr, payload)
            except Exception:
                self.log.exception("frame handler failed (frame dropped)")
            n += 1
        if off:
            del buf[:off]
        return n

    def _unregister(self, conn: _Conn) -> None:
        with self._sel_lock:
            try:
                self.sel.unregister(conn.sock)
            except (KeyError, ValueError):
                pass
        try:
            conn.sock.close()
        except OSError:
            pass
        # drop the receive block. discard, NOT release: _unregister can
        # run from the app thread's _conn_failed while the progress
        # thread is mid-_drain on this very block — recycling it would
        # hand live memory to the next acquire. (A buffer grown past
        # the pool size was never pooled; its accounting was settled at
        # grow time.)
        if conn.rxb is not None:
            if len(conn.rxb) == _RX_BLOCK:
                _rx_pool.discard(conn.rxb)  # mpiracer: disable=cross-thread-race — BufferPool serializes internally (_plock); discard never recycles, so the mid-drain reader keeps sole ownership
            conn.rxb = None
            conn.rstart = conn.rend = 0

    def finalize(self) -> None:
        # Graceful link close: exiting while this side's last frames
        # sit unacked in retx turns a recoverable wire fault (a CRC
        # reject awaiting retransmit, a dropped frame riding the retx
        # timer) into permanent loss — the peer's Finalize fence then
        # waits on a frame nobody will ever resend. The progress
        # thread is already stopped when the btl finalizes, so pump
        # the datapath directly until every established link drains
        # or the bound expires. Degraded/dead links are excluded: an
        # outage budget must not stall a clean exit.
        deadline = time.monotonic() + 2.0
        while time.monotonic() < deadline:
            with self._conn_lock:
                pending = [c for c in self.conns.values()
                           if c.rel and c.dead is None
                           and c.state == "est" and c.retx]
            if not pending:
                break
            self.progress()
            time.sleep(0.001)
        self._closed = True
        with self._sel_lock:
            try:
                self.sel.unregister(self.listener)
            except (KeyError, ValueError):
                pass
        try:
            self.listener.close()
        except OSError:
            pass
        with self._conn_lock:
            conns = list(self.conns.values())
            self.conns.clear()
        for conn in conns:
            if conn.rel:
                # stand the link state machine down: a degraded conn's
                # redial thread exits on dead, and a post-finalize send
                # raises instead of interrupting into a fresh redial
                with conn.wlock:
                    if conn.dead is None:
                        conn.dead = OSError("btl finalized")
            self._unregister(conn)
        with self._sel_lock:
            try:
                self.sel.close()
            except OSError:
                pass


class TcpBtlComponent(Component):
    NAME = "tcp"
    PRIORITY = 20

    def query(self, deliver=None, my_rank=None, **ctx):
        if deliver is None or my_rank is None:
            return None
        return TcpBtl(deliver, my_rank)


btl_framework.register(TcpBtlComponent())
