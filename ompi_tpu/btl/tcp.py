"""TCP transport.

Reference: opal/mca/btl/tcp (5,240 LoC — libevent-driven endpoints with
multi-link striping). Redesign: one non-blocking listener + lazy outgoing
connections, drained by the central progress engine (selectors-based; the
GIL releases in select so the progress thread is cheap). This is the DCN
path of the framework — ICI bulk data rides coll/xla instead, so the TCP
btl optimizes for control/pt2pt traffic, not peak bandwidth.

Frame format: [u32 total_len][header HDR_SIZE bytes][payload]. One frame
per pml message/fragment; TCP ordering per connection preserves MPI
ordering per peer (the reference's per-peer seq numbers guard reordering
across *multiple* btls; with one link per peer ordering is structural).

Zero-copy datapath (the opal convertor / btl writev discipline): a send
is a vector [length word, header, payload view] pushed with
``socket.sendmsg`` — no frame materialization, no eager-payload copy.
Only bytes the kernel would not take are copied, into an owned
write-queue entry (a deque of buffers drained by vectored I/O — the
reference's pending-frag list, minus the O(n^2) bytes-concat the old
``wbuf += frame`` paid under backlog). The receive side ``recv_into``s
a pooled block per connection and hands the pml *slices* of it; a copy
happens only at the pml delivery boundary when a payload must outlive
the block (unexpected-queue stash, system-plane blobs). The remaining
copies are measured, not estimated: ``btl_tcp_bytes_copied`` /
``btl_tcp_writev_calls`` / ``btl_tcp_wire_bytes`` pvars, and
``btl_tcp_copy_mode=1`` re-materializes the legacy copies so bench can
A/B the tax in one process.

Priority-aware traffic shaping (``btl_tcp_shape_enable``): each
connection's send backlog becomes three QoS-class sub-queues
(LATENCY / NORMAL / BULK, read from bits 6-7 of the pml kind byte —
see ompi_tpu/qos.py) drained by a weighted-deficit scheduler with a
starvation bound (``btl_tcp_shape_max_defer_bytes``), so a background
checkpoint blob can no longer head-of-line-block a 4KB allreduce for
its full serialization time. FIFO still holds WITHIN a class (the
pml's per-(peer, class) sequence planes depend on it); preemption
happens between frames — the pml segments oversized blobs into
sub-frames so the yield granularity is ``btl_tcp_shape_segment_bytes``.
The legacy single-FIFO drain stays verbatim behind shape_enable=0 (the
A/B baseline), and the win is measured from the ``btl_tcp_shape_*``
pvars (queued-bytes-by-class gauges, preemption counts) plus the
metrics-plane per-class deferral histogram.

On-wire compression (``btl_tcp_compress`` = zlib level 1-9, 0 = off):
large rendezvous payloads (>= ``btl_tcp_compress_min_bytes``) go out
zlib-deflated with the top bit of the length word flagging the frame;
the header stays plaintext so frame parsing is unchanged. The framing
is negotiated per connection during the rank handshake — a capability
bit meaning "I can DECODE flagged frames" rides the connector's rank
word (advertised unconditionally by this build, so engagement never
depends on which side dialed first) and the acceptor answers with an
ack word. A peer launched with ``btl_tcp_compress`` unset still
decodes. Forward-compat scope: a build WITHOUT this framing is safe as
the CONNECTOR (its bare rank word parses unchanged here, it never
advertises, and no flagged frame or ack is ever emitted toward it);
dialing such a build is NOT supported — its acceptor would parse the
capability bit as part of the rank. All ranks of one job run one
build, so the one-directional guarantee covers the real topology.
"""

from __future__ import annotations

# instrumentation-bearing framework code on the wire path (per-class
# deferral observations, preemption counters) with no note_* hooks of
# its own — the mpilint module-scan marker keeps it in the derived
# INSTR_IMPL set (span-ctx exemption) without hand-list extension
MPILINT_INSTR_IMPL = True

import errno
import itertools
import os
import random
import selectors
import socket
import struct
import threading
import time
import zlib
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

from ompi_tpu import qos as _qos
from ompi_tpu.btl.base import Btl, btl_framework
from ompi_tpu.ft import inject as _inject
from ompi_tpu.runtime import forensics as _forensics
from ompi_tpu.mca.component import Component
from ompi_tpu.mca.var import (register_var, register_pvar, get_var,
                              watch_var)
from ompi_tpu.pml.base import HDR_SIZE, QOS_SHIFT
from ompi_tpu.runtime import metrics as _metrics
from ompi_tpu.runtime import mpool as _mpool
from ompi_tpu.utils.output import get_logger

register_var("btl_tcp", "eager_limit", 1 << 20,
             help="TCP eager/rendezvous threshold in bytes", level=4)
register_var("btl_tcp", "retries", 18,
             help="Bounded connection-establishment retries before the "
                  "connect fails up to the pml failover path "
                  "(reference: btl_tcp_retries_on_connect... the "
                  "endpoint complete-connect retry loop). The default "
                  "schedule (with btl_tcp_backoff_ms doubling to its "
                  "2s cap) spans the 30s total deadline, so a peer "
                  "that takes the whole pre-retry 30s window to come "
                  "up still connects", level=5)
register_var("btl_tcp", "backoff_ms", 25.0,
             help="Base delay between connect retries; doubles per "
                  "attempt (capped at 2s) with +-50% jitter so a "
                  "restarted peer isn't reconnect-stormed by every "
                  "rank at once", level=5)
# empty = auto: loopback for single-host jobs, all-interfaces bound +
# best non-loopback address advertised when the launcher flags a
# multi-host job (OMPI_TPU_MULTIHOST) — reference: btl_tcp_if_include
register_var("btl_tcp", "bind_host", "",
             help="Interface to bind/advertise (empty=auto; "
                  "reference: btl_tcp_if_*)",
             level=4)
_compress_var = register_var(
    "btl_tcp", "compress", 0,
    help="zlib level (1-9) for on-wire payload compression of frames "
         "at or above btl_tcp_compress_min_bytes; 0 (default) = off. "
         "Negotiated per connection during the rank handshake, so a "
         "non-compressing peer interops (it simply never receives a "
         "compressed frame)", level=4)
_compress_min_var = register_var(
    "btl_tcp", "compress_min_bytes", 1 << 16,
    help="Payload bytes below which frames are never compressed (the "
         "deflate cost beats the wire saving on small/eager traffic; "
         "the default targets rendezvous DATA fragments)", level=5)
_vecs_var = register_var(
    "btl_tcp", "writev_max_vecs", 64,
    help="Max iovecs handed to one sendmsg() when draining the "
         "vectored write queue (IOV_MAX guard; reference: the btl "
         "writev scatter-gather of opal's tcp frag lists)", level=5)
_copy_mode_var = register_var(
    "btl_tcp", "copy_mode", 0,
    help="1 = legacy copying datapath: materialize the eager-payload "
         "copy, the frame concat, the per-recv 1 MiB allocation + "
         "rbuf concat, and the receive parse copies the zero-copy "
         "vectored path eliminates. A/B baseline for bench.py's p2p "
         "section — the copies feed btl_tcp_bytes_copied either way, "
         "so copies-per-wire-byte is measured, not estimated", level=9)

# ------------------------------------------------- priority traffic shaping
# btl_tcp_shape_enable / shape_segment_bytes live in ompi_tpu/qos.py
# (the pml shares them: it stamps the class and segments system blobs);
# the scheduler knobs below are this transport's own.
_quantum_var = register_var(
    "btl_tcp", "shape_quantum_bytes", 1 << 16,
    help="Base quantum of the weighted-deficit drain: each scheduling "
         "round grants every backlogged class quantum * weight bytes "
         "of deficit; a class sends while its deficit covers its head "
         "frame. Smaller = tighter interleave, more scheduling work "
         "per byte", level=6)
_weights_var = register_var(
    "btl_tcp", "shape_weights", "8,4,1", typ=str,
    help="Deficit weights 'latency,normal,bulk' for the shaped drain "
         "(floor 1 each): the steady-state wire-byte ratio between "
         "backlogged classes", level=6)
_max_defer_var = register_var(
    "btl_tcp", "shape_max_defer_bytes", 4 << 20,
    help="Starvation bound: once other classes have sent this many "
         "bytes past a backlogged class's head frame, that class is "
         "served next regardless of deficit — BULK always progresses. "
         "0 disables the bound (pure weighted-deficit)", level=6)
_sndbuf_var = register_var(
    "btl_tcp", "sndbuf", 0,
    help="SO_SNDBUF for every tcp connection (reference: "
         "btl_tcp_sndbuf); 0 (default) = kernel default/autotuning. "
         "Bytes the kernel has accepted are beyond any send "
         "scheduler's reach, so with traffic shaping a bounded send "
         "buffer keeps scheduling authority at the btl's per-class "
         "queues instead of a deep autotuned kernel backlog", level=5)
_rcvbuf_var = register_var(
    "btl_tcp", "rcvbuf", 0,
    help="SO_RCVBUF for every tcp connection, applied before "
         "connect/listen so the TCP window scale reflects it "
         "(reference: btl_tcp_rcvbuf); 0 (default) = kernel default. "
         "Together with btl_tcp_sndbuf this bounds per-connection "
         "in-flight bytes — the A/B harness uses it to pin a "
         "deterministic wire bandwidth on loopback", level=5)

# shaped-path counters + live queued-bytes-by-class gauges (plain int
# bumps like _ctr; the by-class gauges take _qlock because different
# conns bump them under different wlocks)
_shape_ctr = {"preempt": 0, "enqueued": 0}  # mpiracer: relaxed-counter — datapath bump discipline: single-op GIL adds, loss tolerated (the by-class gauges that need consistency take _qlock)
_qbytes = [0, 0, 0]   # queued bytes by class (qos.NORMAL/LATENCY/BULK)
_qpeak = [0, 0, 0]
_qlock = threading.Lock()

register_pvar("btl_tcp", "shape_queued_normal",
              lambda: _qbytes[_qos.NORMAL],
              help="Bytes currently queued in NORMAL-class send "
                   "sub-queues across all shaped connections")
register_pvar("btl_tcp", "shape_queued_latency",
              lambda: _qbytes[_qos.LATENCY],
              help="Bytes currently queued in LATENCY-class send "
                   "sub-queues across all shaped connections")
register_pvar("btl_tcp", "shape_queued_bulk",
              lambda: _qbytes[_qos.BULK],
              help="Bytes currently queued in BULK-class send "
                   "sub-queues across all shaped connections")
register_pvar("btl_tcp", "shape_peak_queued_normal",
              lambda: _qpeak[_qos.NORMAL],
              help="High-water mark of NORMAL-class queued bytes")
register_pvar("btl_tcp", "shape_peak_queued_latency",
              lambda: _qpeak[_qos.LATENCY],
              help="High-water mark of LATENCY-class queued bytes")
register_pvar("btl_tcp", "shape_peak_queued_bulk",
              lambda: _qpeak[_qos.BULK],
              help="High-water mark of BULK-class queued bytes")
register_pvar("btl_tcp", "shape_preemptions",
              lambda: _shape_ctr["preempt"],
              help="Frames the shaped drain served ahead of an "
                   "earlier-enqueued frame of another class (the "
                   "out-of-FIFO services the per-class scheduler "
                   "exists to make)")
register_pvar("btl_tcp", "shape_enqueued",
              lambda: _shape_ctr["enqueued"],
              help="Frames that took the shaped (backlogged) queue "
                   "path instead of the zero-copy direct send")

# mpitop/promexport read the by-class queue gauges as one sampler row
def register_shape_sampler() -> None:
    """(Re)bind the by-class queue sampler into the metrics registry —
    called at import; tests that reset the registry re-call it."""
    _metrics.register_sampler(
        "btl_tcp_shape_queued_bytes_by_class",
        lambda: {"latency": _qbytes[_qos.LATENCY],
                 "normal": _qbytes[_qos.NORMAL],
                 "bulk": _qbytes[_qos.BULK],
                 "peak_latency": _qpeak[_qos.LATENCY],
                 "peak_normal": _qpeak[_qos.NORMAL],
                 "peak_bulk": _qpeak[_qos.BULK]})


register_shape_sampler()

# strict-priority service preference inside one deficit round
_SERVICE_ORDER = (_qos.LATENCY, _qos.NORMAL, _qos.BULK)

_weights_memo: Optional[List[int]] = None


def _parse_weights(_var=None) -> None:
    global _weights_memo
    _weights_memo = None


watch_var("btl_tcp", "shape_weights", _parse_weights)


def _weights() -> List[int]:
    """[w_by_class_int]: cvar order is latency,normal,bulk; class ints
    are NORMAL=0/LATENCY=1/BULK=2. Floor 1 so every class drains."""
    global _weights_memo
    w = _weights_memo
    if w is None:
        parts = str(_weights_var._value).split(",")
        try:
            lat, norm, bulk = (max(int(p), 1) for p in parts[:3])
        except (ValueError, TypeError):
            lat, norm, bulk = 8, 4, 1
        w = [1, 1, 1]
        w[_qos.LATENCY], w[_qos.NORMAL], w[_qos.BULK] = lat, norm, bulk
        _weights_memo = w
    return w

# datapath counters (plain int bumps — no instrumentation framework on
# the per-frame path), exported as pvars below
_ctr = {"copied": 0, "writev": 0, "wire": 0}  # mpiracer: relaxed-counter — per-frame datapath counters; a lock per sendmsg would tax the wire path the zero-copy work just paid down

register_pvar("btl_tcp", "bytes_copied",
              lambda: _ctr["copied"],
              help="Payload/frame bytes the tcp datapath had to copy "
                   "(write-queue ownership under backpressure, rx "
                   "compaction/grow, legacy copy_mode re-adds)")
register_pvar("btl_tcp", "writev_calls",
              lambda: _ctr["writev"],
              help="Vectored sendmsg() syscalls issued by the write "
                   "path")
register_pvar("btl_tcp", "wire_bytes",
              lambda: _ctr["wire"],
              help="Frame bytes moved through the sockets (tx + rx), "
                   "the denominator of copies-per-wire-byte")

_LEN = struct.Struct("<I")

# receive staging block: sized for a full default rendezvous DATA frame
# (pml_frag_size 1 MiB + framing) so the common bulk frame fits without
# growing, shared by every TcpBtl through one mpool.BufferPool
_RX_BLOCK = (1 << 20) + (1 << 12)
_rx_pool = _mpool.BufferPool(_RX_BLOCK)

# rank-handshake capability bits + frame compression flag: compression
# rides the top bit of its u32 word (ranks and frame lengths stay
# < 2^30); the QoS bit advertises "my pml masks class bits from the
# kind byte and keys its sequence planes per (peer, class)" — every
# build with this code does, so like the compress bit it is advertised
# unconditionally and acked unconditionally. Shaping toward a peer
# that never acks (an older build) is documented-unsupported: its pml
# would reject class-stamped kind bytes, exactly like dialing a
# pre-compress acceptor.
_CAP_COMPRESS = 1 << 31
_CAP_QOS = 1 << 30
_ZFLAG = 1 << 31
_LEN_MASK = _ZFLAG - 1
# acceptor's handshake ack: magic in the high byte + capability bits
_ZACK_MAGIC = 0x5A << 24
_ZACK_ACCEPT = 1
_ZACK_QOS = 2
_ZACK_WORDS = frozenset(
    _ZACK_MAGIC | a | q for a in (0, _ZACK_ACCEPT) for q in (0, _ZACK_QOS))


def _compress_counters():
    """Wire-compression counters live in the quant plane (one
    observable subsystem for both reduced-precision paths)."""
    from ompi_tpu import quant

    return quant.counters()


register_pvar("btl_tcp", "compress_ratio",
              lambda: (lambda c: round(c["wire_raw"] / c["wire_comp"], 4)
                       if c["wire_comp"] else 0.0)(_compress_counters()),
              help="Cumulative raw/compressed payload-byte ratio over "
                   "frames that went out zlib-compressed")
register_pvar("btl_tcp", "compress_saved_bytes",
              lambda: (lambda c: c["wire_raw"] - c["wire_comp"])(
                  _compress_counters()),
              help="Payload bytes kept off the wire by tcp compression")


def _apply_bufs(sock: socket.socket) -> None:
    """SO_SNDBUF/SO_RCVBUF bounds (btl_tcp_sndbuf/rcvbuf, 0 = kernel
    default) — called before connect/listen so TCP window scaling
    honors them."""
    snd = int(_sndbuf_var._value)
    rcv = int(_rcvbuf_var._value)
    try:
        if snd > 0:
            sock.setsockopt(socket.SOL_SOCKET, socket.SO_SNDBUF, snd)
        if rcv > 0:
            sock.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF, rcv)
    except OSError:
        pass


class _Conn:
    __slots__ = ("sock", "rxb", "rstart", "rend", "wq", "wbuf", "rbuf",
                 "wlock", "peer", "dead", "peer_z", "await_ack",
                 "wqs", "cur", "cur_cls", "deficit", "defer", "peer_q",
                 "eseq", "last_rx", "last_tx")

    def __init__(self, sock: socket.socket, peer: Optional[int] = None):
        self.sock = sock
        # legacy concat queues, used ONLY under btl_tcp_copy_mode=1
        # (the bench A/B baseline) — empty otherwise
        self.wbuf = bytearray()
        self.rbuf = bytearray()
        # receive staging: a pooled block filled by recv_into, with the
        # unparsed span at [rstart, rend). Acquired lazily on first
        # drain, returned to the pool when the conn unregisters.
        self.rxb: Optional[bytearray] = None
        self.rstart = 0
        self.rend = 0
        # pending outbound buffers, drained by vectored sendmsg
        # (reference: btl/tcp's per-endpoint pending frag list flushed
        # on write-ready events). Entries are OWNED bytes-likes — a
        # borrowed payload view is copied exactly once, at the moment
        # the kernel declines it (buffered-send semantics: the caller
        # may reuse its buffer the instant send() returns).
        self.wq: deque = deque()
        # RLock: _conn_failed runs both under wlock (from _flush_locked)
        # and without it (from _drain's read-error path)
        self.wlock = threading.RLock()
        self.peer = peer
        self.dead: Optional[OSError] = None
        # negotiated at handshake: True once the peer advertised it
        # understands (and accepts) zlib-flagged frames on this link
        self.peer_z = False
        # connector side: an ack word is due before frame traffic; it is
        # consumed ASYNCHRONOUSLY by _drain (a blocking wait here could
        # deadlock two polling-only ranks dialing each other — each
        # stuck in its own handshake, neither accepting)
        self.await_ack = False
        # traffic shaping (btl_tcp_shape_enable): per-class send
        # sub-queues of (enqueue seq, nbytes, owned vec list, enq ts),
        # allocated lazily so unshaped conns pay one None slot; `cur`
        # is the partially-written frame that must finish before the
        # scheduler may switch class (TCP frames are contiguous on the
        # wire — preemption happens BETWEEN frames, which is why
        # oversized blobs are segmented upstream)
        self.wqs: Optional[tuple] = None
        self.cur: Optional[list] = None
        self.cur_cls = 0
        self.deficit = [0, 0, 0]
        self.defer = [0, 0, 0]
        # negotiated at handshake: peer masks QoS class bits and keys
        # its seq planes per class (every build with this code)
        self.peer_q = False
        self.eseq = 0
        # last wire activity (monotonic), stamped only while the
        # forensics plane is armed — dump evidence for "is this link
        # moving at all", not a live gauge
        self.last_rx: Optional[float] = None
        self.last_tx: Optional[float] = None


class TcpBtl(Btl):
    bandwidth = 1  # stripe weight (reference: opal btl_bandwidth)

    NAME = "tcp"
    # fd-driven: the progress engine may park in select over idle_fds()
    # instead of polling this transport
    NEEDS_POLL = False

    def __init__(self, deliver: Callable[[bytes, bytes], None], my_rank: int):
        super().__init__(deliver)
        self.eager_limit = get_var("btl_tcp", "eager_limit")
        self.my_rank = my_rank
        self.log = get_logger("btl.tcp")
        host = get_var("btl_tcp", "bind_host")
        if not host:
            if os.environ.get("OMPI_TPU_MULTIHOST"):  # mpilint: disable=raw-environ — launcher topology hint, not MCA config
                host = "0.0.0.0"
            else:
                host = "127.0.0.1"
        bind = host
        if host == "0.0.0.0":
            # listen everywhere, advertise the best-scored non-loopback
            # address in the modex card (reference: opal/mca/reachable —
            # the endpoint blob carries routable addresses, see
            # ifaces.best_local_addr)
            from ompi_tpu.runtime.ifaces import best_local_addr

            host = best_local_addr() or "127.0.0.1"
        self.listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self.listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        # buffer bounds inherit to accepted sockets; RCVBUF must be
        # set before listen so the window scale factor reflects it
        _apply_bufs(self.listener)
        self.listener.bind((bind, 0))
        self.listener.listen(64)
        self.listener.setblocking(False)
        self.host = host
        self.port = self.listener.getsockname()[1]
        self.peers: Dict[int, str] = {}
        self.conns: Dict[int, _Conn] = {}  # peer rank -> connection
        self._conn_lock = threading.Lock()
        self.sel = selectors.DefaultSelector()
        self.sel.register(self.listener, selectors.EVENT_READ,
                          ("accept", None))
        self._sel_lock = threading.Lock()
        # single-drainer: exactly one thread runs the event loop at a time
        # (the app thread's wait-loop and the progress thread both call
        # progress(); concurrent drains would interleave frame parsing)
        self._progress_lock = threading.Lock()
        self._closed = False
        # stall-forensics provider (rebind-by-name: the live transport
        # wins; weakly bound so test-built instances don't pin)
        _forensics.register_weak_provider(
            "btl.tcp", self, alive=lambda btl: not btl._closed)

    # -------------------------------------------------- stall forensics
    def debug_state(self) -> dict:
        """Forensics provider: per-connection dial/established/dead
        state, per-class shaped queue depths with the oldest frame's
        age, the partially-written frame, partial-frame reassembly
        residue, and the last wire rx/tx stamps (populated while the
        forensics plane is armed). Each conn is snapshotted under its
        own wlock — the same lock every WRITE-queue mutation holds; the
        rx parser's span fields belong to the progress thread and are
        read lock-free and clamped."""
        now = time.monotonic()
        with self._conn_lock:
            conns = dict(self.conns)
        out = []
        for peer, conn in sorted(conns.items())[:_forensics.CAP]:
            # single reads + clamp: the rx parser advances these on the
            # progress thread outside wlock, and a torn pair (rend read
            # before a compaction, rstart after) must not record a
            # negative partial-frame size as evidence
            r0, r1 = conn.rstart, conn.rend  # mpiracer: disable=cross-thread-race — lock-free diagnostic snapshot, clamped below; taking the progress side's lock here could block a dump behind the wedged loop it is diagnosing
            with conn.wlock:
                ent: dict = {
                    "peer": peer,
                    "state": ("dead" if conn.dead is not None else
                              "dialing" if conn.await_ack else
                              "established"),
                    "dead_reason": str(conn.dead) if conn.dead else None,
                    "wq_frames": len(conn.wq),
                    "wq_bytes": sum(len(b) for b in conn.wq),
                    "legacy_wbuf_bytes": len(conn.wbuf),
                    "rx_partial_bytes": max(0, r1 - r0),
                    "last_rx_age_s": None if conn.last_rx is None
                    else round(now - conn.last_rx, 3),
                    "last_tx_age_s": None if conn.last_tx is None
                    else round(now - conn.last_tx, 3),
                }
                if conn.cur is not None:
                    ent["in_progress_frame"] = {
                        "cls": _qos.NAMES.get(conn.cur_cls,
                                              conn.cur_cls),
                        "bytes_left": sum(len(v) for v in conn.cur)}
                if conn.wqs is not None:
                    shaped = {}
                    for c in _SERVICE_ORDER:
                        dq = conn.wqs[c]
                        if not dq:
                            continue
                        shaped[_qos.NAMES[c]] = {
                            "frames": len(dq),
                            "bytes": sum(e[1] for e in dq),
                            "oldest_age_s": round(now - dq[0][3], 3),
                            "deferred_bytes": conn.defer[c]}
                    if shaped:
                        ent["shaped_queues"] = shaped
            out.append(ent)
        return {
            "rank": self.my_rank,
            "listen": f"{self.host}:{self.port}",
            "closed": self._closed,
            "conns": out,
            "conns_omitted": max(0, len(conns) - len(out)),
            "queued_by_class": {"latency": _qbytes[_qos.LATENCY],
                                "normal": _qbytes[_qos.NORMAL],
                                "bulk": _qbytes[_qos.BULK]},
        }

    # ------------------------------------------------------------- wiring
    def set_peers(self, peers: Dict[int, str]) -> None:
        self.peers = dict(peers)

    def _connect(self, peer: int) -> _Conn:
        addr = self.peers[peer]
        host, port = addr.rsplit(":", 1)
        # multi-homed hosts: dial from the best-weighted local interface
        # for this peer (reference: opal/mca/reachable weighted scoring)
        from ompi_tpu.runtime.ifaces import pick_source

        try:
            src = pick_source(socket.gethostbyname(host))
        except OSError:
            src = None
        # Bounded establishment retry with exponential backoff + jitter
        # (reference: the endpoint connect retry of btl/tcp): a peer
        # mid-restart or briefly overloaded must not fail the link on
        # the first ECONNREFUSED, and a herd of ranks redialing must
        # not synchronize. BOTH bounds apply — attempt count AND a 30s
        # total deadline (the pre-retry behavior): a SYN-blackholed
        # peer burning full per-attempt timeouts must not stretch the
        # failure to attempts * timeout. Exhaustion raises to the pml
        # failover path.
        retries = int(get_var("btl_tcp", "retries"))
        backoff = float(get_var("btl_tcp", "backoff_ms")) / 1000.0
        deadline = time.monotonic() + 30.0
        attempt = 0
        while True:
            left = deadline - time.monotonic()
            try:
                # manual socket (vs create_connection) so the
                # btl_tcp_sndbuf/rcvbuf bounds are applied BEFORE the
                # handshake — the window scale is negotiated at SYN
                s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
                try:
                    _apply_bufs(s)
                    s.settimeout(max(min(10.0, left), 1.0))
                    if src:
                        s.bind((src, 0))
                    s.connect((host, int(port)))
                except BaseException:
                    s.close()  # a failed attempt must not leak the fd
                    raise
                s.settimeout(None)
                break
            except OSError as e:
                left = deadline - time.monotonic()
                if attempt >= retries or left <= 0:
                    self.log.error(
                        "connect to rank %s (%s) failed after %d "
                        "attempts: %s", peer, addr, attempt + 1, e)
                    raise
                from ompi_tpu.runtime import spc

                spc.record("btl_tcp_connect_retries")
                delay = min(backoff * (1 << attempt), 2.0) \
                    * (0.5 + random.random())
                attempt += 1
                # clamp the sleep to the remaining budget: backing off
                # past the deadline would stretch total failure latency
                # beyond the 30s bound the deadline exists to keep
                time.sleep(min(delay, left))
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        conn = _Conn(s, peer)
        # identify ourselves so the acceptor can map conn -> rank. The
        # capability bit means "I can DECODE zlib-flagged frames" (every
        # build with this code can), NOT "I will compress" — advertising
        # it unconditionally keeps engagement symmetric: whether a
        # compress-enabled peer may flag frames to us must not depend on
        # which side happened to dial first (gating the bit on our own
        # compress level silently disabled the feature whenever the
        # compress=0 side connected first). The acceptor answers with an
        # ack word, consumed asynchronously by _drain — sends stay
        # uncompressed on this link until it lands, so a peer that never
        # acks (a build without this framing) simply keeps the link at
        # plain framing. The QoS capability bit rides along identically
        # (shaped per-class scheduling engages only after the peer acks
        # it — frames sent before the ack drain FIFO).
        s.sendall(_LEN.pack(self.my_rank | _CAP_COMPRESS | _CAP_QOS))
        conn.await_ack = True
        s.setblocking(False)
        with self._sel_lock:
            self.sel.register(s, selectors.EVENT_READ, ("peer", conn))
        return conn

    def _get_conn(self, peer: int) -> _Conn:
        with self._conn_lock:
            conn = self.conns.get(peer)
            if conn is None:
                conn = self._connect(peer)
                self.conns[peer] = conn
            return conn

    # --------------------------------------------------------------- send
    def send(self, peer: int, header: bytes, payload) -> None:
        """Vectored zero-copy enqueue: the frame is pushed as
        [length word, header, payload view] via sendmsg with NO
        intermediate materialization; only bytes the kernel declines
        are copied into the owned write queue (buffered-send semantics
        — the caller may reuse its buffer the moment we return). Never
        blocks the caller on a full socket — the head-to-head
        large-send deadlock the reference's pending-frag design exists
        to avoid."""
        if isinstance(payload, bytes):
            mv = payload  # immutable: safe to queue without owning
        else:
            mv = memoryview(payload)
            if mv.ndim != 1 or mv.format != "B" or not mv.c_contiguous:
                try:
                    mv = mv.cast("B")
                except TypeError:
                    # non-contiguous source: ownership copy is forced
                    _ctr["copied"] += mv.nbytes
                    mv = bytes(mv)  # mpilint: disable=hot-copy — non-contiguous buffers cannot be viewed flat
        nbytes = len(mv)
        if HDR_SIZE + nbytes > _LEN_MASK:
            # bit 31 of the length word carries the compression flag,
            # so one frame tops out at 2 GiB; beyond it the receiver
            # would mask a wrong length AND misparse the frame as
            # compressed — fail loudly here instead (callers shipping
            # blobs that large must split them)
            from ompi_tpu.core.errors import MPIError, ERR_OTHER

            raise MPIError(
                ERR_OTHER,
                f"tcp frame of {HDR_SIZE + nbytes} bytes exceeds "
                f"the {_LEN_MASK}-byte framing limit")
        dup = False
        if _inject._enable_var._value:  # chaos wire hook (ft/inject.py)
            verdict = _inject.wire_send(self.my_rank, peer)
            if verdict:
                if verdict & _inject.SEVER:
                    conn = self._get_conn(peer)
                    self._conn_failed(conn, ConnectionResetError(
                        "link severed by ft_inject_plan"))
                    # fall through: the dead-check below raises
                elif verdict & _inject.DROP:
                    return
                dup = bool(verdict & _inject.DUP)
        conn = self._get_conn(peer)
        zflag = 0
        level = int(_compress_var._value)  # one live-Var load when off
        if level > 0 and conn.peer_z and \
                nbytes >= int(_compress_min_var._value):
            z = zlib.compress(mv, level)
            if len(z) < nbytes:  # incompressible data stays raw
                from ompi_tpu import quant as _quant

                _quant.note_wire(nbytes, len(z))
                mv = z
                nbytes = len(z)
                zflag = _ZFLAG
        lenw = _LEN.pack((HDR_SIZE + nbytes) | zflag)
        if nbytes:
            vecs: List = [lenw, header, mv]
        else:
            vecs = [lenw, header]
        if dup:
            vecs = vecs + vecs
        with conn.wlock:
            # dead-check under wlock: _conn_failed flips dead/clears the
            # write queue under the same lock, so a frame can't slip
            # past the check into a cleared queue
            if conn.dead is not None:
                from ompi_tpu.core.errors import (
                    MPIError,
                    ERR_OTHER,
                    ERR_PROC_FAILED,
                )
                from ompi_tpu.ft.detector import known_failed

                # ULFM class when the failure detector confirmed the
                # peer's death — user recovery code keys off this code
                code = ERR_PROC_FAILED if peer in known_failed() \
                    else ERR_OTHER
                raise MPIError(
                    code,
                    f"connection to rank {peer} is dead: {conn.dead}")
            if _copy_mode_var._value:
                self._send_legacy(conn, lenw, header, mv, dup)
                return
            if _qos._enable_var._value and conn.peer_q:
                # shaped path: per-class sub-queues drained by the
                # weighted-deficit scheduler (poke below still runs —
                # a backlog may have been queued)
                self._send_shaped(conn, vecs, header[0] >> QOS_SHIFT)
            else:
                if conn.cur is not None or \
                        (conn.wqs is not None and any(conn.wqs)):
                    # shaped residue after a shape_enable flip: older
                    # frames must hit the wire first
                    self._fold_shaped_residue(conn)
                if conn.wbuf:
                    # legacy residue after a copy_mode flip: older
                    # frames must hit the wire first
                    conn.wq.append(bytes(conn.wbuf))
                    conn.wbuf.clear()
                backlog = bool(conn.wq)
                if not backlog:
                    # fast path: push straight from the caller's buffer
                    vecs = self._try_send(conn, vecs)
                    if not vecs:
                        return  # fully on the wire (or conn failed): 0 copies
                # backpressure: own the unsent remainder — the ONE copy
                # the zero-copy path ever pays, and only for bytes the
                # kernel would not take now
                for v in vecs:
                    if isinstance(v, memoryview):
                        _ctr["copied"] += len(v)
                        v = bytes(v)
                    conn.wq.append(v)
                if backlog:
                    self._flush_locked(conn)
                else:
                    self._want_write(conn, True)
        # a backlog was (or may still be) queued: wake a progress loop
        # parked in the idle select so the flush doesn't wait out the
        # park interval — the park's write-fd list was computed before
        # this conn wanted write
        from ompi_tpu.runtime import progress as _progress

        _progress.poke()

    def _fold_wq_legacy(self, conn: _Conn) -> None:
        """Vectored residue after a copy_mode flip: fold the deque into
        the legacy concat queue, oldest first. Caller holds wlock."""
        while conn.wq:
            conn.wbuf += conn.wq.popleft()  # mpilint: disable=hot-copy — mode-flip bridge into the legacy A/B queue

    def _send_legacy(self, conn: _Conn, lenw: bytes, header: bytes,
                     mv, dup: bool) -> None:
        """The pre-vectored datapath, verbatim (btl_tcp_copy_mode=1,
        the bench A/B baseline): unconditional eager-payload copy,
        frame concat, bytes-concat queue append, byte-wise flush. The
        copies feed btl_tcp_bytes_copied so copies-per-wire-byte is
        MEASURED on the real legacy code, not modeled. Caller holds
        conn.wlock and has done the dead-check."""
        if conn.cur is not None or \
                (conn.wqs is not None and any(conn.wqs)):
            # shaped residue after a copy_mode flip: a partially-written
            # shaped frame MUST finish (and older shaped frames must
            # drain) before legacy bytes hit the wire, or the stream
            # desyncs / same-class frames overtake their seqs
            self._fold_shaped_residue(conn)
        payload = bytes(mv)  # the old eager copy (pre-PR tcp.py:277)  # mpilint: disable=hot-copy — legacy A/B path reproduces the old copies on purpose
        frame = lenw + header + payload
        _ctr["copied"] += len(payload) + len(frame)
        self._fold_wq_legacy(conn)
        conn.wbuf += frame  # mpilint: disable=hot-copy — legacy A/B path reproduces the old concat queue on purpose
        _ctr["copied"] += len(frame)
        if dup:
            conn.wbuf += frame  # mpilint: disable=hot-copy — legacy A/B path
            _ctr["copied"] += len(frame)
        self._flush_legacy(conn)

    def _flush_legacy(self, conn: _Conn) -> None:
        """The pre-vectored flush: byte-wise send + O(n) front-trim of
        the concat queue (O(n^2) across a backlog — the measured tax).
        Caller holds conn.wlock."""
        if conn.cur is not None or \
                (conn.wqs is not None and any(conn.wqs)):
            # shaped residue after a copy_mode flip: ordered first
            self._fold_shaped_residue(conn)
        self._fold_wq_legacy(conn)
        while conn.wbuf:
            try:
                sent = conn.sock.send(conn.wbuf)
            except socket.error as e:
                if e.errno in (errno.EAGAIN, errno.EWOULDBLOCK):
                    self._want_write(conn, True)
                    return
                self._conn_failed(conn, e)
                return
            if sent <= 0:
                self._want_write(conn, True)
                return
            _ctr["wire"] += sent
            if _forensics._enable_var._value:  # last-tx dump evidence
                conn.last_tx = time.monotonic()
            del conn.wbuf[:sent]
        self._want_write(conn, False)

    def _try_send(self, conn: _Conn, vecs: List) -> List:
        """Vectored push of ``vecs`` until the socket blocks; returns
        the unsent remainder as views (the caller owns copying them).
        Caller holds conn.wlock. On a fatal error the conn is failed
        and [] returned — the bytes are lost and the NEXT send to this
        peer raises (same contract as the old flush path)."""
        max_vecs = int(_vecs_var._value)
        while vecs:
            try:
                sent = conn.sock.sendmsg(vecs[:max_vecs])
            except socket.error as e:
                if e.errno in (errno.EAGAIN, errno.EWOULDBLOCK):
                    return vecs
                # Fatal send error: queued (and eagerly-completed) bytes
                # are lost. Surface it — mark the conn dead, tell the
                # failure detector, fail future sends (ADVICE r1).
                self._conn_failed(conn, e)
                return []
            if sent <= 0:
                return vecs
            _ctr["writev"] += 1
            _ctr["wire"] += sent
            if _forensics._enable_var._value:  # last-tx dump evidence
                conn.last_tx = time.monotonic()
            while sent:
                l0 = len(vecs[0])
                if sent >= l0:
                    sent -= l0
                    vecs.pop(0)
                else:
                    # O(1) partial-consume: slice the view, no copy
                    vecs[0] = memoryview(vecs[0])[sent:]
                    sent = 0
        return vecs

    # ------------------------------------------------- shaped send path
    # btl_tcp_shape_enable=1: every connection drains three class
    # sub-queues (LATENCY/NORMAL/BULK, read from bits 6-7 of the pml
    # kind byte) with a weighted-deficit scheduler instead of one FIFO.
    # FIFO holds WITHIN a class (the pml's per-(peer, class) seq planes
    # depend on it); across classes the scheduler reorders on purpose —
    # that is the whole point. A partially-written frame always
    # finishes first (TCP frames are contiguous on the wire), so the
    # preemption granularity is one frame — which is why the pml
    # segments oversized blobs before they get here.
    def _send_shaped(self, conn: _Conn, vecs: List, cls: int) -> None:
        """Shaped enqueue/send of one frame. Caller holds conn.wlock
        and has done the dead-check."""
        if conn.wqs is None:
            conn.wqs = (deque(), deque(), deque())
        if conn.wbuf:
            # legacy residue after a copy_mode flip: ordered first
            conn.wq.append(bytes(conn.wbuf))
            conn.wbuf.clear()
        if conn.wq:
            # pre-shaping FIFO residue (mode flip, or frames queued
            # before the peer's QoS ack landed): it must hit the wire
            # before any shaped frame. If a partial shaped frame is
            # already mid-write it is older still — append after it.
            if conn.cur is None:
                conn.cur = list(conn.wq)
                conn.cur_cls = _qos.NORMAL
            else:
                conn.cur.extend(conn.wq)
            conn.wq.clear()
        if conn.cur is None and not any(conn.wqs):
            # fast path: push straight from the caller's buffer
            total = sum(len(v) for v in vecs)
            vecs = self._try_send(conn, vecs)
            if not vecs:
                return  # fully on the wire (or conn failed): 0 copies
            # backpressure: own the unsent remainder. A frame with
            # bytes already on the wire is the unpreemptible
            # in-progress frame; one the kernel took NOTHING of is
            # still schedulable — queue it so a LATENCY arrival can
            # jump ahead of an untouched bulk frame.
            cur = []
            left = 0
            for v in vecs:
                left += len(v)
                if isinstance(v, memoryview):
                    _ctr["copied"] += len(v)
                    v = bytes(v)
                cur.append(v)
            if left < total:
                conn.cur = cur
                conn.cur_cls = cls
            else:
                conn.eseq += 1
                conn.wqs[cls].append(
                    (conn.eseq, left, cur, time.monotonic()))
                _shape_ctr["enqueued"] += 1
                with _qlock:
                    _qbytes[cls] += left
                    if _qbytes[cls] > _qpeak[cls]:
                        _qpeak[cls] = _qbytes[cls]
            self._want_write(conn, True)
            return
        # backlog: own the frame into its class sub-queue, then give
        # the scheduler a drain pass (a LATENCY arrival may preempt
        # the queued bulk right now instead of at the next progress)
        nb = 0
        owned = []
        for v in vecs:
            if isinstance(v, memoryview):
                _ctr["copied"] += len(v)
                v = bytes(v)
            owned.append(v)
            nb += len(v)
        conn.eseq += 1
        conn.wqs[cls].append((conn.eseq, nb, owned, time.monotonic()))
        _shape_ctr["enqueued"] += 1
        with _qlock:
            _qbytes[cls] += nb
            if _qbytes[cls] > _qpeak[cls]:
                _qpeak[cls] = _qbytes[cls]
        if cls == _qos.BULK:
            # background enqueue: do NOT drain synchronously — a bulk
            # producer in a tight ship loop would otherwise spend its
            # own timeslice pushing the whole backlog through sendmsg,
            # starving the latency-critical threads the shaper exists
            # to protect. The progress engine drains it (the trailing
            # poke in send() wakes a parked loop).
            self._want_write(conn, True)
        else:
            self._flush_shaped(conn)

    def _flush_shaped(self, conn: _Conn) -> None:
        """Drain the shaped sub-queues: finish the in-progress frame,
        then repeatedly let the deficit scheduler pick the next class.
        Caller holds conn.wlock.

        The drain is BUDGETED per call: a fast kernel (loopback) would
        otherwise accept an entire multi-blob backlog in one loop while
        this thread holds conn.wlock — and a LATENCY frame born on the
        app thread mid-drain would block on the lock for the whole
        serialization, re-creating exactly the head-of-line blocking
        the scheduler exists to remove. Stopping every ~16 quanta
        releases the lock (the yield point between sendmsg calls); the
        selector's write interest re-enters the drain immediately."""
        budget = 16 * max(int(_quantum_var._value), 1)
        sent = 0
        while True:
            if conn.cur is not None:
                before = sum(len(v) for v in conn.cur)
                rem = self._try_send(conn, conn.cur)
                if conn.dead is not None:
                    return
                if rem:
                    conn.cur = rem  # socket full mid-frame: resume later
                    self._want_write(conn, True)
                    return
                sent += before
                conn.cur = None
            if sent >= budget:
                # yield point: backlog remains, the lock must breathe
                self._want_write(conn, True)
                return
            cls = self._pick_class(conn)
            if cls is None:
                self._want_write(conn, False)
                return
            wqs = conn.wqs
            # peek-try-commit: a frame the kernel takes NOTHING of
            # stays at its queue head, still schedulable — committing
            # it to `cur` would let an untouched frame block a later
            # preemption for no wire progress
            eseq, nb, owned, ts = wqs[cls][0]
            rem = self._try_send(conn, list(owned))
            if conn.dead is not None:
                return
            if rem and sum(len(v) for v in rem) == nb:
                self._want_write(conn, True)
                return
            wqs[cls].popleft()
            # preemption = serving ahead of an earlier-enqueued frame
            # of another class (the out-of-FIFO service the per-class
            # scheduler exists to make)
            older = [wqs[c][0][0] for c in _SERVICE_ORDER
                     if c != cls and wqs[c]]
            if older and min(older) < eseq:
                _shape_ctr["preempt"] += 1
            with _qlock:
                _qbytes[cls] -= nb
            if conn.deficit[cls] >= nb:
                # only deficit-granted serves spend credit: a grant
                # that bypassed the deficit check (sole backlogged
                # class, starvation bound) must not drive the counter
                # negative, or a class that ran alone for a while
                # starts a later contention epoch in deep debt and
                # starves against its own weight (classic DRR never
                # goes negative)
                conn.deficit[cls] -= nb
            if not wqs[cls]:
                conn.deficit[cls] = 0  # classic DRR: empty resets
            conn.defer[cls] = 0
            for c in _SERVICE_ORDER:
                if c != cls and wqs[c]:
                    conn.defer[c] += nb
            if _metrics._enable_var._value:
                # per-frame deferral histogram (time queued by class)
                _metrics.observe("btl_tcp_shape_defer_us",
                                 (time.monotonic() - ts) * 1e6,
                                 cls=_qos.NAMES[cls])
            if rem:
                conn.cur = rem  # frame started: must finish first
                conn.cur_cls = cls
                self._want_write(conn, True)
                return
            sent += nb

    def _pick_class(self, conn: _Conn) -> Optional[int]:
        """Next class to serve: the starvation bound first (a class
        past btl_tcp_shape_max_defer_bytes of deferral wins outright —
        BULK always progresses), then weighted-deficit round-robin in
        LATENCY > NORMAL > BULK preference order. Caller holds wlock."""
        wqs = conn.wqs
        nonempty = [c for c in _SERVICE_ORDER if wqs[c]]
        if not nonempty:
            return None
        if len(nonempty) == 1:
            return nonempty[0]
        md = int(_max_defer_var._value)
        if md > 0:
            starved = [c for c in nonempty if conn.defer[c] >= md]
            if starved:
                return max(starved, key=lambda c: conn.defer[c])
        q = max(int(_quantum_var._value), 1)
        w = _weights()
        while True:
            for c in nonempty:
                if conn.deficit[c] >= wqs[c][0][1]:
                    return c
            for c in nonempty:
                conn.deficit[c] += q * w[c]

    def _fold_shaped_residue(self, conn: _Conn) -> None:
        """Shaped residue after a shape_enable flip: fold the partial
        frame and every class sub-queue into the legacy FIFO, oldest
        class-order (cross-class order is arbitrary by construction —
        the shaper had already unordered them). Caller holds wlock."""
        frames: List = []
        if conn.cur is not None:
            frames.extend(conn.cur)
            conn.cur = None
        if conn.wqs is not None:
            for c in _SERVICE_ORDER:
                dq = conn.wqs[c]
                while dq:
                    _eseq, nb, owned, _ts = dq.popleft()
                    frames.extend(owned)
                    with _qlock:
                        _qbytes[c] -= nb
        conn.wq.extendleft(reversed(frames))

    def _drop_shaped(self, conn: _Conn) -> None:
        """Dead conn: release the shaped queues and settle the by-class
        gauges. Caller holds conn.wlock."""
        conn.cur = None
        if conn.wqs is not None:
            for c in _SERVICE_ORDER:
                dq = conn.wqs[c]
                while dq:
                    _eseq, nb, _owned, _ts = dq.popleft()
                    with _qlock:
                        _qbytes[c] -= nb

    def _flush_locked(self, conn: _Conn) -> None:
        """Drain the owned write queue with vectored sends; caller
        holds conn.wlock."""
        if conn.cur is not None or \
                (conn.wqs is not None and any(conn.wqs)):
            # shaped residue after a shape_enable flip: ordered first
            self._fold_shaped_residue(conn)
        if conn.wbuf:
            # legacy residue after a copy_mode flip: ordered first
            conn.wq.appendleft(bytes(conn.wbuf))
            conn.wbuf.clear()
        wq = conn.wq
        max_vecs = int(_vecs_var._value)
        while wq:
            try:
                sent = conn.sock.sendmsg(
                    list(itertools.islice(wq, max_vecs)))
            except socket.error as e:
                if e.errno in (errno.EAGAIN, errno.EWOULDBLOCK):
                    self._want_write(conn, True)
                    return
                self._conn_failed(conn, e)
                return
            if sent <= 0:
                self._want_write(conn, True)
                return
            _ctr["writev"] += 1
            _ctr["wire"] += sent
            if _forensics._enable_var._value:  # last-tx dump evidence
                conn.last_tx = time.monotonic()
            while sent:
                l0 = len(wq[0])
                if sent >= l0:
                    sent -= l0
                    wq.popleft()
                else:
                    # partial first buffer: O(1) reslice over the OWNED
                    # bytes (the deque keeps them alive) — the old
                    # bytearray queue paid an O(n) del wbuf[:sent] here,
                    # O(n^2) across a backlog
                    wq[0] = memoryview(wq[0])[sent:]
                    sent = 0
        self._want_write(conn, False)

    def _conn_failed(self, conn: _Conn, err: OSError) -> None:
        """A connection died under queued traffic: drop it, surface the
        loss (reference: btl/tcp endpoint error → pml error callback; here
        the ULFM detector is the propagation plane)."""
        with conn.wlock:
            conn.dead = err
            conn.wq.clear()
            conn.wbuf.clear()
            self._drop_shaped(conn)
        self.log.error("i/o with rank %s failed: %s", conn.peer, err)
        self._unregister(conn)
        # The dead conn stays in self.conns: bytes already queued (and
        # eagerly completed) were lost, so silently reconnecting would hide
        # a hole in the message stream — subsequent sends raise instead.
        # mark_failed stays UNCONDITIONAL here (unlike the EOF path): the
        # exit-fence abandon predicate and the failure flood both key off
        # known_failed() even in non-FT jobs. The pml's request-failing
        # sweep is what gates on ft_enable — without the detector armed a
        # single-rail write error must not fail requests a healthy
        # fallback rail can still re-drive.
        if conn.peer is not None:
            from ompi_tpu.ft.detector import mark_failed

            mark_failed(conn.peer)

    def _want_write(self, conn: _Conn, on: bool) -> None:
        ev = selectors.EVENT_READ | (selectors.EVENT_WRITE if on else 0)
        with self._sel_lock:
            try:
                self.sel.modify(conn.sock, ev, ("peer", conn))
            except (KeyError, ValueError):
                pass

    # ----------------------------------------------------------- progress
    def idle_fds(self) -> Tuple[list, list]:
        """Export (read-fds, write-interest-fds) for the progress
        engine's idle-blocking select: the listener plus every live
        conn, and — so a parked loop resumes flushing — every conn
        with queued writes. A socket closing between export and the
        select is handled by the caller (select raises, treated as a
        wake)."""
        rfds: list = []
        wfds: list = []
        if self._closed:
            return rfds, wfds
        with self._sel_lock:
            try:
                keys = list(self.sel.get_map().values())
            except RuntimeError:  # selector closed by a finalize race
                return rfds, wfds
        for key in keys:
            rfds.append(key.fd)
            if key.events & selectors.EVENT_WRITE:
                wfds.append(key.fd)
        return rfds, wfds

    def progress(self) -> int:
        """Drain ready sockets; called from the progress engine
        (reference: btl progress fns registered at opal_progress.c:416)."""
        if self._closed:
            return 0
        if not self._progress_lock.acquire(blocking=False):
            return 0
        try:
            try:
                with self._sel_lock:
                    events = self.sel.select(timeout=0)
            except OSError:
                return 0
            n = 0
            for key, mask in events:
                kind, conn = key.data
                if kind == "accept":
                    n += self._accept()
                    continue
                if mask & selectors.EVENT_WRITE:
                    with conn.wlock:
                        if _copy_mode_var._value:
                            self._flush_legacy(conn)
                        elif conn.cur is not None or \
                                (conn.wqs is not None and any(conn.wqs)):
                            # shaped backlog pending (regardless of the
                            # cvar's CURRENT value: a flip mid-backlog
                            # must still drain what the shaper queued)
                            self._flush_shaped(conn)
                        else:
                            self._flush_locked(conn)
                if mask & selectors.EVENT_READ:
                    n += self._drain(conn)
            return n
        finally:
            self._progress_lock.release()

    def _accept(self) -> int:
        try:
            s, _ = self.listener.accept()
        except OSError:
            return 0
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        # first 4 bytes: peer rank
        s.setblocking(True)
        raw = b""
        while len(raw) < 4:
            chunk = s.recv(4 - len(raw))
            if not chunk:
                return 0
            raw += chunk
        word = _LEN.unpack(raw)[0]
        peer = word & ~(_CAP_COMPRESS | _CAP_QOS)
        conn = _Conn(s, peer)
        if word & (_CAP_COMPRESS | _CAP_QOS):
            # the connector understands zlib-flagged frames / QoS class
            # bits; answer with our ack so it knows we do too (decoding
            # is always available in this build — acceptance is
            # unconditional, per advertised capability)
            ack = _ZACK_MAGIC
            if word & _CAP_COMPRESS:
                conn.peer_z = True
                ack |= _ZACK_ACCEPT
            if word & _CAP_QOS:
                conn.peer_q = True
                ack |= _ZACK_QOS
            try:
                s.sendall(_LEN.pack(ack))
            except OSError:
                # the dialer died mid-handshake; under PR 3's connect
                # retry it will redial — close or each attempt leaks a fd
                try:
                    s.close()
                except OSError:
                    pass
                return 0
        s.setblocking(False)
        with self._conn_lock:
            # keep one canonical conn per peer for sending; both sides may
            # connect simultaneously — every conn gets drained regardless
            self.conns.setdefault(peer, conn)
        with self._sel_lock:
            self.sel.register(s, selectors.EVENT_READ, ("peer", conn))
        return 1

    def _drain(self, conn: _Conn) -> int:
        if _copy_mode_var._value:
            return self._drain_legacy(conn)
        # pooled receive staging: recv_into this conn's reusable block
        # (one pool hit) instead of a fresh 1 MiB allocation per recv —
        # a 4-byte ack used to cost a megabyte of garbage plus an rbuf
        # concat. Frames are then SLICED out of the block; anything
        # that must outlive it is copied at the pml delivery boundary.
        if conn.rbuf:
            # legacy residue after a copy_mode flip: replay it through
            # the block so frame parsing stays continuous
            self._adopt_legacy_rbuf(conn)
        buf = conn.rxb
        if buf is None:
            buf = conn.rxb = _rx_pool.acquire()  # owns: rxb
            conn.rstart = conn.rend = 0
        if conn.rend == len(buf):
            # no room left: slide the parked partial frame to the
            # front, or grow into a private (unpooled) buffer when one
            # frame is bigger than the block — bounded boundary copies,
            # both charged to btl_tcp_bytes_copied
            pending = conn.rend - conn.rstart
            if conn.rstart > 0:
                buf[:pending] = buf[conn.rstart:conn.rend]
            else:
                total = 0
                if pending >= 4:
                    total = _LEN.unpack_from(buf, 0)[0] & _LEN_MASK
                nbuf = bytearray(max(4 + total, 2 * len(buf)))
                nbuf[:pending] = buf
                # only a pool-sized block goes back: regrowing an
                # ALREADY-grown buffer (a second jumbo outgrowing the
                # first, or legacy-residue adoption that exactly filled
                # its grown buffer) used to release the private
                # bytearray here, spuriously decrementing the pool's
                # outstanding count for a block it never handed out
                if len(buf) == _RX_BLOCK:
                    _rx_pool.release(buf)
                conn.rxb = buf = nbuf
            _ctr["copied"] += pending
            conn.rstart, conn.rend = 0, pending
        try:
            n_in = conn.sock.recv_into(memoryview(buf)[conn.rend:])
        except socket.error as e:
            if e.errno in (errno.EAGAIN, errno.EWOULDBLOCK):
                return 0
            self._conn_failed(conn, e)
            return 0
        if not n_in:
            # EOF: could be a peer crash OR a clean peer Finalize — mark
            # the conn dead so later sends raise instead of vanishing.
            # With the ULFM detector armed (ft_enable) the EOF is also
            # reported as a failure vantage point — in an FT job a peer
            # that stops talking IS failed (its heartbeats stop too, so
            # the flood only arrives sooner); without ft_enable a clean
            # shutdown must not raise failure events, so detection stays
            # local.
            if conn.dead is None:
                conn.dead = ConnectionResetError("closed by peer")
            if conn.peer is not None:
                from ompi_tpu.ft.detector import mark_failed

                if get_var("ft", "enable"):
                    mark_failed(conn.peer)
            self._unregister(conn)
            return 0
        _ctr["wire"] += n_in
        if _forensics._enable_var._value:  # last-rx dump evidence
            conn.last_rx = time.monotonic()
        conn.rend += n_in
        n = 0
        mv = memoryview(buf)  # borrows: rxb
        off = conn.rstart
        end = conn.rend
        if conn.await_ack and end - off >= 4:
            # the compress-handshake ack leads every frame on a dialed
            # link. Match the FULL word (magic byte + reserved-zero
            # bits + accept bit), not just the high byte: a non-acking
            # peer's first frame could legally be ~1.41 GiB long under
            # the 2 GiB cap, and a high-byte-only match would eat its
            # length word and desync the whole stream
            word = _LEN.unpack_from(buf, off)[0]
            conn.await_ack = False
            if word in _ZACK_WORDS:
                conn.peer_z = bool(word & _ZACK_ACCEPT)
                conn.peer_q = bool(word & _ZACK_QOS)
                off += 4
        while end - off >= 4:
            word = _LEN.unpack_from(buf, off)[0]
            total = word & _LEN_MASK
            if end - off - 4 < total:
                break
            start = off + 4
            # zero-copy parse: header and payload are views over the
            # pool block, valid for the synchronous deliver below; the
            # pml copies at its boundary when a payload must survive it
            hdr = mv[start:start + HDR_SIZE]
            payload = mv[start + HDR_SIZE:start + total]
            off = start + total
            if word & _ZFLAG:
                # negotiated framing: only a handshake-capable peer ever
                # sets the flag, so this build always knows how to undo
                # it. A decompress failure means stream integrity is
                # gone — silently dropping the frame would leave the
                # pml's per-peer sequence waiting forever on a hole, so
                # fail the LINK and let the PR 3 failover/dead-letter
                # machinery take over (same contract as a read error)
                try:
                    payload = zlib.decompress(payload)
                except zlib.error as e:
                    self.log.exception("corrupt compressed frame")
                    conn.rstart = off
                    self._conn_failed(conn, OSError(
                        f"corrupt compressed frame from rank "
                        f"{conn.peer}: {e}"))
                    return n
            # A frame handler may itself send (ob1 replies with CTS/DATA
            # from inside deliver); if that send hits a dead peer the
            # MPIError must not escape — it would skip the cursor
            # advance below (re-delivering frames) and kill the
            # progress thread.
            try:
                self.deliver(hdr, payload)  # mpiown: disable=escaping-view — the deliver is synchronous over this block; ob1's _owned gate copies any payload that must survive it
            except Exception:
                self.log.exception("frame handler failed (frame dropped)")
            n += 1
        if off >= end:
            # block fully parsed: reset the cursors — no memmove, and a
            # buffer grown for a jumbo frame is dropped so the conn
            # reacquires a pooled block on the next drain
            conn.rstart = conn.rend = 0
            if len(buf) != _RX_BLOCK:
                conn.rxb = None
        else:
            conn.rstart = off
        return n

    def _adopt_legacy_rbuf(self, conn: _Conn) -> None:
        """Move legacy rbuf residue (a copy_mode flip mid-stream) into
        the pooled block, growing it if needed. Runs under the drain's
        single-drainer exclusivity."""
        pending = len(conn.rbuf)
        if conn.rxb is None:
            conn.rxb = _rx_pool.acquire()  # owns: rxb
            conn.rstart = conn.rend = 0
        live = conn.rend - conn.rstart
        if live + pending > len(conn.rxb):
            nbuf = bytearray(max(live + pending, 2 * len(conn.rxb)))
            nbuf[:live] = conn.rxb[conn.rstart:conn.rend]
            if len(conn.rxb) == _RX_BLOCK:
                _rx_pool.release(conn.rxb)
            conn.rxb = nbuf
            conn.rstart, conn.rend = 0, live
        elif conn.rend + pending > len(conn.rxb):
            conn.rxb[:live] = conn.rxb[conn.rstart:conn.rend]
            conn.rstart, conn.rend = 0, live
        conn.rxb[conn.rend:conn.rend + pending] = conn.rbuf
        conn.rend += pending
        _ctr["copied"] += pending
        conn.rbuf.clear()

    def _drain_legacy(self, conn: _Conn) -> int:
        """The pre-vectored read path, verbatim (btl_tcp_copy_mode=1,
        the bench A/B baseline): a fresh 1 MiB allocation per recv, an
        rbuf concat, and per-frame header/payload parse copies — all
        charged to btl_tcp_bytes_copied so the legacy copy tax is
        measured on the real legacy code."""
        if conn.rxb is not None and conn.rend > conn.rstart:
            # vectored residue after a copy_mode flip
            conn.rbuf += memoryview(conn.rxb)[conn.rstart:conn.rend]  # mpilint: disable=hot-copy — legacy A/B path adopts the pooled residue
            _ctr["copied"] += conn.rend - conn.rstart
        if conn.rxb is not None:
            if len(conn.rxb) == _RX_BLOCK:
                _rx_pool.discard(conn.rxb)  # mpiracer: disable=cross-thread-race — BufferPool serializes internally (_plock); discard never recycles, so the racing drain keeps sole ownership
            conn.rxb = None
            conn.rstart = conn.rend = 0
        try:
            data = conn.sock.recv(1 << 20)
        except socket.error as e:
            if e.errno in (errno.EAGAIN, errno.EWOULDBLOCK):
                return 0
            self._conn_failed(conn, e)
            return 0
        if not data:
            if conn.dead is None:
                conn.dead = ConnectionResetError("closed by peer")
            if conn.peer is not None:
                from ompi_tpu.ft.detector import mark_failed

                if get_var("ft", "enable"):
                    mark_failed(conn.peer)
            self._unregister(conn)
            return 0
        _ctr["wire"] += len(data)
        if _forensics._enable_var._value:  # last-rx dump evidence
            conn.last_rx = time.monotonic()
        conn.rbuf += data  # mpilint: disable=hot-copy — legacy A/B path reproduces the old rbuf concat on purpose
        _ctr["copied"] += len(data)
        n = 0
        buf = conn.rbuf
        off = 0
        if conn.await_ack and len(buf) >= 4:
            word = _LEN.unpack_from(buf, 0)[0]
            conn.await_ack = False
            if word in _ZACK_WORDS:
                conn.peer_z = bool(word & _ZACK_ACCEPT)
                conn.peer_q = bool(word & _ZACK_QOS)
                off = 4
        while len(buf) - off >= 4:
            word = _LEN.unpack_from(buf, off)[0]
            total = word & _LEN_MASK
            if len(buf) - off - 4 < total:
                break
            start = off + 4
            hdr = bytes(buf[start:start + HDR_SIZE])  # mpilint: disable=hot-copy — legacy A/B path reproduces the old parse copy on purpose
            payload = bytes(buf[start + HDR_SIZE:start + total])  # mpilint: disable=hot-copy — legacy A/B path reproduces the old parse copy on purpose
            _ctr["copied"] += total
            off += 4 + total
            if word & _ZFLAG:
                try:
                    payload = zlib.decompress(payload)
                except zlib.error as e:
                    self.log.exception("corrupt compressed frame")
                    self._conn_failed(conn, OSError(
                        f"corrupt compressed frame from rank "
                        f"{conn.peer}: {e}"))
                    return n
            try:
                self.deliver(hdr, payload)
            except Exception:
                self.log.exception("frame handler failed (frame dropped)")
            n += 1
        if off:
            del buf[:off]
        return n

    def _unregister(self, conn: _Conn) -> None:
        with self._sel_lock:
            try:
                self.sel.unregister(conn.sock)
            except (KeyError, ValueError):
                pass
        try:
            conn.sock.close()
        except OSError:
            pass
        # drop the receive block. discard, NOT release: _unregister can
        # run from the app thread's _conn_failed while the progress
        # thread is mid-_drain on this very block — recycling it would
        # hand live memory to the next acquire. (A buffer grown past
        # the pool size was never pooled; its accounting was settled at
        # grow time.)
        if conn.rxb is not None:
            if len(conn.rxb) == _RX_BLOCK:
                _rx_pool.discard(conn.rxb)  # mpiracer: disable=cross-thread-race — BufferPool serializes internally (_plock); discard never recycles, so the mid-drain reader keeps sole ownership
            conn.rxb = None
            conn.rstart = conn.rend = 0

    def finalize(self) -> None:
        self._closed = True
        with self._sel_lock:
            try:
                self.sel.unregister(self.listener)
            except (KeyError, ValueError):
                pass
        try:
            self.listener.close()
        except OSError:
            pass
        with self._conn_lock:
            conns = list(self.conns.values())
            self.conns.clear()
        for conn in conns:
            self._unregister(conn)
        with self._sel_lock:
            try:
                self.sel.close()
            except OSError:
                pass


class TcpBtlComponent(Component):
    NAME = "tcp"
    PRIORITY = 20

    def query(self, deliver=None, my_rank=None, **ctx):
        if deliver is None or my_rank is None:
            return None
        return TcpBtl(deliver, my_rank)


btl_framework.register(TcpBtlComponent())
