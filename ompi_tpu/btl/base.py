"""BTL — Byte Transfer Layer contract.

Reference: opal/mca/btl/btl.h:1170+ (mca_btl_base_module_t) — the raw
transport function table with eager/rendezvous limits. Our contract is a
slim frame interface: a BTL moves (header, payload) frames to a peer and
hands received frames to the PML's ``handle_incoming``. RDMA verbs
(put/get/atomics) are intentionally absent on the host path: device bulk
data rides the ICI/XLA path (coll/xla, osc over mesh) — the TPU-native
answer to the reference's RDMA pipeline.
"""

from __future__ import annotations

from typing import Callable, Optional

from ompi_tpu.mca.component import Component, framework

btl_framework = framework(
    "btl", "Byte transfer layer (host/DCN transports)"
)


class Btl:
    """Transport module. eager_limit=None means the transport has no
    rendezvous threshold (loopback/shm can move any size in one frame).

    Idle-blocking contract: a transport whose traffic is visible to
    select() exports ``idle_fds() -> (rfds, wfds)`` and sets
    ``NEEDS_POLL = False`` so the progress engine may PARK while idle
    (runtime/progress.py idle_block). The conservative default —
    NEEDS_POLL True, no exporter — marks a transport that discovers
    work only by polling (the sm rings): its presence keeps idle
    loops on the legacy sleep backoff instead of select-parking."""

    NAME = "base"
    eager_limit: Optional[int] = 65536
    NEEDS_POLL = True
    #: link-reliability upcall (btl/tcp reconnect-and-replay): wireup
    #: binds this to the pml's ``link_restored(rank)`` so a healed link
    #: replays the pml's dead-letter stash for that peer. Transports
    #: without link state never call it; None = no listener.
    link_restored_cb: Optional[Callable[[int], None]] = None

    def __init__(self, deliver: Callable[[bytes, bytes], None]):
        # deliver(header_bytes, payload) — the PML's handle_incoming.
        # Chaos harness receive-side choke point: with a plan armed,
        # every transport's inbound funnel is filtered (side=recv rules:
        # drop/delay/dup by frame source). The wrapper is installed at
        # CONSTRUCTION whenever ANY plan is armed — so the disabled path
        # never pays a wrapper frame, while the rule list itself stays
        # live (install()/uninstall() after btls exist re-point it).
        # Limitation: arming injection from scratch AFTER transports are
        # built only reaches the send-side and op-counter hooks.
        from ompi_tpu.ft import inject as _inject

        if _inject._enable_var._value:
            deliver = _inject.wrap_deliver(deliver)
        self.deliver = deliver

    def send(self, peer: int, header: bytes, payload) -> None:
        raise NotImplementedError

    def progress(self) -> int:
        return 0

    def finalize(self) -> None:
        pass
