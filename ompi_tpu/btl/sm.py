"""Shared-memory transport — the default same-host data path.

Reference: opal/mca/btl/sm (2,690 LoC): each process owns a shared
segment; senders write into per-peer FIFOs inside the *receiver's*
segment (btl_sm_sendi.c), so delivery is a single copy and the receiver
polls only its own memory. Redesign notes:

- The FIFO is the lock-free SPSC byte ring of ompi_tpu/native/sm_ring.cpp
  (C++ data plane via ctypes, Python fallback with identical layout) —
  the fastbox small-message path and the FIFO collapse into one ring,
  since the ring already moves small frames with one memcpy + one
  atomic store each way.
- Single-copy "smsc" analog: there is no second copy to elide — the
  sender gathers header+payload straight into the ring, and the receiver
  hands the popped frame to the PML, which unpacks straight into the
  posted buffer.
- Full-ring backpressure mirrors btl/tcp's pending-frag queue: send()
  never blocks; unflushed frames drain from progress().

Business card (modex): ``btl.sm.seg`` = segment path, ``btl.sm.node`` =
boot id (same-kernel check — the reference uses PMIx locality flags).
"""

from __future__ import annotations

import mmap
import os
import struct
import tempfile
import threading
from collections import deque
from typing import Callable, Dict, Optional, Tuple

from ompi_tpu.btl.base import Btl, btl_framework
from ompi_tpu.core.errors import MPIError, ERR_OTHER
from ompi_tpu.mca.component import Component
from ompi_tpu.mca.var import register_var, get_var
from ompi_tpu.native.ring import SmRing, HDR_BYTES
from ompi_tpu.pml.base import HDR_SIZE
from ompi_tpu.utils.output import get_logger

register_var("btl_sm", "ring_bytes", 1 << 22,
             help="Per-sender ring size in the receiver's segment", level=4)
register_var("btl_sm", "eager_limit", 1 << 16,
             help="SM eager/rendezvous threshold in bytes", level=4)
register_var("btl_sm", "fail_after", -1,
             help="Fault injection for the bml failover tests: sends "
                  "start raising after N successful ones (-1 = off)",
             level=9)
register_var("btl_sm", "use_native", 1,
             help="Use the C++ ring data plane (0 = Python fallback)",
             level=7)

_SEG_MAGIC = 0x534D5345474D4E54
_SEG_HDR = struct.Struct("<QQQ")  # magic, nranks, ring_bytes


def node_id() -> str:
    """Identity of this kernel instance (reference: PMIx locality)."""
    try:
        with open("/proc/sys/kernel/random/boot_id") as f:
            return f.read().strip()
    except OSError:
        import socket

        return socket.gethostname()


class SmBtl(Btl):
    # relative stripe weight for multi-btl rendezvous scheduling
    # (reference: opal btl_bandwidth; shared memory >> loopback tcp)
    bandwidth = 8

    NAME = "sm"

    def __init__(self, deliver: Callable[[bytes, bytes], None],
                 my_rank: int, n_ranks: int,
                 local_rank: Optional[int] = None):
        super().__init__(deliver)
        self.my_rank = my_rank            # universe rank (identity)
        # ring index inside same-job peers' segments (job-local; dynamic
        # processes from other jobs ride tcp instead — see wireup)
        self.local_rank = my_rank if local_rank is None else local_rank
        self.n_ranks = n_ranks
        self.eager_limit = get_var("btl_sm", "eager_limit")
        self.ring_bytes = int(get_var("btl_sm", "ring_bytes"))
        self.use_native = bool(get_var("btl_sm", "use_native"))
        self.fail_after = int(get_var("btl_sm", "fail_after"))
        self._sends_done = 0  # mpiracer: relaxed-counter — fault-injection trigger only (fail_after >= 0 in chaos runs); a lost bump shifts the injected failure by one op
        self.log = get_logger("btl.sm")

        # My segment: one inbound ring slot per potential sender, indexed
        # by world rank. The file is SPARSE (ftruncate, no write-out):
        # tmpfs only materializes pages that are touched, so the physical
        # footprint is one header page per ring plus whatever same-node
        # peers actually fill — proportional to ranks-per-node even though
        # the virtual size is proportional to world size (the reference
        # instead indexes by node-local rank from PMIx locality; world-rank
        # indexing keeps senders offset-computable without a handshake).
        shm_dir = "/dev/shm" if os.path.isdir("/dev/shm") else None
        fd, self.seg_path = tempfile.mkstemp(
            prefix=f"ompi_tpu_sm_r{my_rank}_", suffix=".seg", dir=shm_dir)
        seg_bytes = 64 + n_ranks * self.ring_bytes
        os.ftruncate(fd, seg_bytes)
        self.seg_mm = mmap.mmap(fd, seg_bytes)
        os.close(fd)
        _SEG_HDR.pack_into(self.seg_mm, 0, _SEG_MAGIC, n_ranks,
                           self.ring_bytes)
        self.inbound = []
        for r in range(n_ranks):
            ring = SmRing(self.seg_mm, 64 + r * self.ring_bytes,
                          self.ring_bytes, use_native=self.use_native)
            ring.init()
            self.inbound.append(ring)

        # peer state: world rank -> (mmap, ring-into-peer)
        self.peers: Dict[int, str] = {}
        self._out: Dict[int, Tuple[mmap.mmap, SmRing]] = {}
        self._pending: Dict[int, deque] = {}
        self._out_lock = threading.Lock()
        self._progress_lock = threading.Lock()
        self._closed = False

    # ------------------------------------------------------------- wiring
    def set_peers(self, peers: Dict[int, str]) -> None:
        """peer world-rank -> segment path (from the modex)."""
        self.peers = dict(peers)

    def _attach(self, peer: int) -> SmRing:  # locked-by: self._out_lock
        path = self.peers[peer]
        fd = os.open(path, os.O_RDWR)
        try:
            mm = mmap.mmap(fd, os.fstat(fd).st_size)
        finally:
            os.close(fd)
        magic, nranks, ring_bytes = _SEG_HDR.unpack_from(mm, 0)
        if magic != _SEG_MAGIC or self.local_rank >= nranks:
            raise RuntimeError(f"bad sm segment {path}")
        ring = SmRing(mm, 64 + self.local_rank * ring_bytes, ring_bytes,
                      use_native=self.use_native)
        self._out[peer] = (mm, ring)
        return ring

    def _out_ring(self, peer: int) -> SmRing:
        with self._out_lock:
            ent = self._out.get(peer)
            if ent is None:
                return self._attach(peer)
            return ent[1]

    # --------------------------------------------------------------- send
    # Frame layout inside the ring: [u64 flags][pml header][payload].
    # flags=0: payload inline. flags=1: overflow — the payload lives in a
    # side file (path in the frame body); the system-tag plane (osc active
    # messages) ships unbounded single frames, which must never fail just
    # because they exceed the ring (reference: btl/sm falls back to
    # single-copy smsc for what the fifo can't hold).
    _INLINE = struct.pack("<Q", 0)
    _OVERFLOW = struct.pack("<Q", 1)

    def send(self, peer: int, header: bytes, payload) -> None:
        if self.fail_after >= 0:  # fault injection (off = -1, no cost)
            self._sends_done += 1
            if self._sends_done > self.fail_after:
                raise MPIError(ERR_OTHER,
                               "btl/sm fault injection: channel down")
        ring = self._out_ring(peer)
        plen = (payload.nbytes if hasattr(payload, "nbytes")
                else len(payload) if isinstance(payload, (bytes, bytearray))
                else memoryview(payload).nbytes)
        with self._out_lock:
            pend = self._pending.setdefault(peer, deque())
            # A frame that can NEVER fit inline must spill regardless of
            # queue state: queued inline it would make _flush() spin on
            # push()==-1 forever and wedge this peer's channel.
            if not ring.can_fit(8 + len(header) + plen):
                self._send_overflow(ring, pend, peer, header, payload)
                return
            if not pend:
                rc = ring.push(self._INLINE + header, payload)
                if rc == 1:
                    return
                if rc < 0:  # unreachable after the pre-screen; keep safe
                    self._send_overflow(ring, pend, peer, header, payload)
                    return
            # ring full: queue, preserve per-peer order (the tcp write-
            # queue pattern). Ownership boundary: the caller may reuse
            # its buffer once send() returns, so queued payloads must
            # be owned — same one-copy-under-backpressure contract as
            # tcp's write queue.
            if not isinstance(payload, (bytes, bytearray)):
                if hasattr(payload, "tobytes"):
                    payload = payload.tobytes()
                else:
                    payload = bytes(memoryview(payload).cast("B"))  # mpilint: disable=hot-copy — ownership copy at the queue boundary (buffered-send semantics)
            pend.append((self._INLINE + header, payload))

    def drain_pending(self, peer: int):
        """Hand undelivered queued frames for ``peer`` to the bml
        failover re-drive (pml._send_frame). Overflow markers are
        reconstituted into real payloads — the replacement transport
        knows nothing of the spill-file convention."""
        with self._out_lock:
            pend = self._pending.pop(peer, None)
        out = []
        if not pend:
            return out
        for flagged, payload in pend:
            flag, hdr = flagged[:8], flagged[8:]
            if flag == self._OVERFLOW:
                path = bytes(payload).decode()
                try:
                    with open(path, "rb") as f:
                        data = f.read()
                    os.unlink(path)
                except OSError:
                    continue
                out.append((hdr, data))
            else:
                out.append((hdr, payload))
        return out

    def _spill(self, payload) -> bytes:
        """Write payload to a side file; return the path (marker body)."""
        fd, path = tempfile.mkstemp(
            prefix=f"ompi_tpu_ovf_r{self.my_rank}_",
            dir=os.path.dirname(self.seg_path) or None)
        with os.fdopen(fd, "wb") as f:
            f.write(payload if isinstance(payload, (bytes, bytearray))
                    else memoryview(payload).cast("B"))
        return path.encode()

    def _send_overflow(self, ring, pend, peer: int, header: bytes,
                       payload) -> None:
        """Caller holds _out_lock. Spill an over-ring-size payload to a
        side file; the tiny marker frame keeps per-peer ordering."""
        marker = self._spill(payload)
        if pend or ring.push(self._OVERFLOW + header, marker) != 1:
            pend.append((self._OVERFLOW + header, marker))

    def _flush(self) -> int:
        n = 0
        with self._out_lock:
            for peer, pend in self._pending.items():
                ring = self._out.get(peer)
                if ring is None:
                    continue
                ring = ring[1]
                while pend:
                    hdr, payload = pend[0]
                    rc = ring.push(hdr, payload)
                    if rc == 1:
                        pend.popleft()
                        n += 1
                        continue
                    if rc < 0 and hdr[:8] == self._INLINE:
                        # belt-and-braces: convert in place so the channel
                        # stays live instead of wedging (ordering kept).
                        # Only INLINE frames convert, and only once — a
                        # still-failing push (e.g. corrupt ring magic)
                        # must stall here, not spin spawning spill files.
                        pend[0] = (self._OVERFLOW + hdr[8:],
                                   self._spill(payload))
                        continue
                    break  # rc == 0 (full) or unconvertible: retry later
        return n

    # ----------------------------------------------------------- progress
    def progress(self) -> int:
        if self._closed:
            return 0
        if not self._progress_lock.acquire(blocking=False):
            return 0
        try:
            n = self._flush()
            for ring in self.inbound:
                while True:
                    frame = ring.peek()  # zero-copy view into the ring
                    if frame is None:
                        break
                    try:
                        flags = struct.unpack_from("<Q", frame, 0)[0]
                        hdr = bytes(frame[8 : 8 + HDR_SIZE])  # mpilint: disable=hot-copy — 49-byte header outlives ring.advance(); the slot is recycled under it
                        if flags == 1:  # overflow: body is the spill path
                            path = bytes(frame[8 + HDR_SIZE :]).decode()  # mpilint: disable=hot-copy — tiny spill-file path, consumed before the slot recycles
                            with open(path, "rb") as f:
                                payload = f.read()
                            os.unlink(path)
                            self.deliver(hdr, payload)
                        else:
                            # matched receives unpack straight from shared
                            # memory; the pml copies only on the unexpected
                            # path (single-copy delivery, btl_sm_sendi.c)
                            self.deliver(hdr, frame[8 + HDR_SIZE :])
                    except Exception:
                        self.log.exception(
                            "frame handler failed (frame dropped)")
                    finally:
                        ring.advance()
                    n += 1
            return n
        finally:
            self._progress_lock.release()

    def finalize(self) -> None:
        self._closed = True
        with self._out_lock:
            for mm, _ in self._out.values():
                try:
                    mm.close()
                except (BufferError, ValueError):
                    pass
            self._out.clear()
        try:
            self.seg_mm.close()
        except (BufferError, ValueError):
            pass  # ctypes from_buffer holds an export; the OS reclaims
        try:
            os.unlink(self.seg_path)
        except OSError:
            pass


class SmBtlComponent(Component):
    NAME = "sm"
    PRIORITY = 30  # above tcp (20): same-host peers prefer shared memory

    def query(self, deliver=None, my_rank=None, n_ranks=None,
              local_rank=None, **ctx):
        if deliver is None or my_rank is None or n_ranks is None:
            return None
        try:
            return SmBtl(deliver, my_rank, n_ranks, local_rank)
        except OSError:
            return None


btl_framework.register(SmBtlComponent())
