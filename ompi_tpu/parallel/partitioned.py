"""Mesh-mode partitioned communication (MPI-4 Psend/Precv on XlaComm).

Reference: ompi/mca/part/part.h:163,227 (Psend_init/Precv_init,
Pready/Parrived). SURVEY.md §5 maps partitioned comm on the mesh to
SEGMENTED ppermute schedules, and that is literally the implementation:

- the buffer is [W, P, ...] — rank rows over the mesh axis, P partitions;
- ``Pready(p)`` dispatches partition p's ppermute immediately (its own
  cached XLA executable; jax dispatch is asynchronous, so partitions
  overlap on ICI in ready order, not index order);
- ``Parrived(p)`` polls the partition's device readiness
  (jax.Array.is_ready — the transfer's completion flag);
- ``Wait`` assembles the permuted partitions back into [W, P, ...].

Single-controller collapse: the driver holds both endpoints, so one
request object serves the Psend/Precv pair — Precv_init returns the
same machinery (the host pml/partitioned.py keeps the two-process
protocol for process mode).
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

from ompi_tpu.core.errors import MPIError, ERR_ARG, ERR_PENDING


class MeshPartitionedRequest:
    """Persistent partitioned transfer over a mesh communicator."""

    def __init__(self, comm, x, perm: Sequence[Tuple[int, int]],
                 partitions: int):
        if partitions <= 0:
            raise MPIError(ERR_ARG, "partitions must be positive")
        if x.ndim < 2 or x.shape[1] % partitions:
            raise MPIError(
                ERR_ARG,
                f"buffer [W, K, ...] needs K divisible by partitions: "
                f"{tuple(x.shape)} vs {partitions}")
        self.comm = comm
        self.perm = tuple((int(s), int(d)) for s, d in perm)
        self.partitions = partitions
        self._seg = x.shape[1] // partitions
        self._x = x
        self._parts: List[Optional[object]] = [None] * partitions
        self.result = None

    # ------------------------------------------------------ MPI verbs
    def Start(self) -> "MeshPartitionedRequest":
        """Re-arm (persistent semantics); partition state clears."""
        self._parts = [None] * self.partitions
        self.result = None
        return self

    def Pready(self, partition: int) -> None:
        """Dispatch partition ``partition``'s segment of the ppermute
        schedule — any order, each its own async device dispatch."""
        p = int(partition)
        if not 0 <= p < self.partitions:
            raise MPIError(ERR_ARG, f"partition {p} out of range")
        if self._parts[p] is not None:
            raise MPIError(ERR_ARG, f"partition {p} already ready")
        lo = p * self._seg
        self._parts[p] = self.comm.permute(
            self._x[:, lo: lo + self._seg], self.perm)

    def Pready_range(self, lo: int, hi: int) -> None:
        for p in range(int(lo), int(hi) + 1):
            self.Pready(p)

    def Parrived(self, partition: int) -> bool:
        """Has partition ``partition`` completed on device?"""
        p = int(partition)
        if not 0 <= p < self.partitions:
            raise MPIError(ERR_ARG, f"partition {p} out of range")
        r = self._parts[p]
        if r is None:
            return False
        try:
            return bool(r.is_ready())
        except AttributeError:  # non-jax array (cpu fallback): done
            return True

    def Wait(self):
        """Complete the whole transfer: every partition must have been
        made ready; returns (and stores) the permuted [W, P*seg, ...]
        array."""
        missing = [i for i, r in enumerate(self._parts) if r is None]
        if missing:
            raise MPIError(
                ERR_PENDING,
                f"Wait before Pready of partitions {missing[:8]}")
        import jax
        import jax.numpy as jnp

        out = jnp.concatenate(self._parts, axis=1)
        jax.block_until_ready(out)
        self.result = out
        return out

    def Test(self) -> bool:
        return all(r is not None for r in self._parts) and \
            all(self.Parrived(i) for i in range(self.partitions))

    def Free(self) -> None:
        self._parts = [None] * self.partitions
        self._x = None
        self.result = None
