"""Multi-slice mesh collectives: two-level ICI + DCN hierarchy.

Reference: ompi/mca/coll/han (coll_han_subcomms.c builds node-local +
leader subcomms and runs two-level algorithms over them). The mesh-mode
analog for TPU pods that span ICI domains: each *slice* is a device
mesh wired by ICI under one controller; slices are bridged by the
host-side DCN transport (tcp btl in process mode). A two-level
allreduce is

    slice-local XLA collective (psum over ICI)
    -> leader exchange over the bridge comm (DCN)
    -> slice-wide broadcast of the combined result (ICI again, via a
       sharded device_put — the slice-local psum already left every
       device with the slice sum, so the final hop is placement only)

which is exactly han's node-reduce / leader-allreduce / node-bcast
split with "node" = slice. The DCN hop stages through the host — the
true data path between slices that XLA's single-slice compilation
cannot express (multi-slice XLA would fuse it; this layer is the
framework-level fallback the reference's han provides for hierarchical
interconnects).

Deployment shape: one process (MPI rank) per slice controller; the
bridge is any ProcComm over those ranks (COMM_WORLD in the tests, with
the tcp btl as the DCN). The dryrun check models a 2x4-device universe
as 2 ranks each holding a 4-device virtual CPU mesh.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ompi_tpu.core import op as _op
from ompi_tpu.core.errors import MPIError, ERR_ARG
from ompi_tpu.core.request import Request
from ompi_tpu.parallel.mesh import XlaComm


class _FutureRequest(Request):
    """Worker-thread-completed request for the DCN-staged nonblocking
    verbs (was defined per _ireq call, minting a throwaway class per
    invocation); ``result`` carries the verb's output."""

    result = None


class MultiSliceComm:
    """A communicator spanning ``bridge.size`` slices, each an XlaComm
    over this controller's local devices."""

    def __init__(self, slice_comm: XlaComm, bridge):
        if slice_comm.groups is not None:
            raise MPIError(ERR_ARG,
                           "multi-slice spans whole-mesh slice comms")
        self.slice = slice_comm
        self.bridge = bridge
        self._rep_cache = {}  # (shape, dtype) -> jitted device broadcast

    @property
    def n_slices(self) -> int:
        return self.bridge.size

    @property
    def slice_id(self) -> int:
        return self.bridge.rank

    @property
    def world_size(self) -> int:
        """Total devices across all slices (uniform slice size)."""
        return self.slice.world_size * self.n_slices

    # ------------------------------------------------------- collectives
    def _host_exchange(self, row: np.ndarray, op: _op.Op) -> np.ndarray:
        from ompi_tpu.runtime import spc

        out = np.zeros_like(row)
        with spc.suppressed():
            self.bridge.Allreduce(np.ascontiguousarray(row), out, op=op)
        return out

    def _replicate(self, row: np.ndarray):
        """One host row -> the sharded [D, ...] rank-dim array WITHOUT
        a D-times host materialization (the r4 path paid
        np.broadcast_to + ascontiguousarray = world_size x row bytes of
        host traffic per collective): the row crosses host->device ONCE
        and a jitted broadcast with sharded out_shardings expands it on
        device over ICI."""
        import jax
        import jax.numpy as jnp

        key = (row.shape, row.dtype.str)
        fn = self._rep_cache.get(key)
        if fn is None:
            D = self.slice.world_size

            def expand(r):
                return jnp.broadcast_to(r, (D,) + r.shape)

            fn = jax.jit(expand, out_shardings=self.slice.sharding())
            self._rep_cache[key] = fn
        return fn(row)

    def _do_allreduce(self, x, op: _op.Op = _op.SUM):
        """[D, ...] per slice -> every device of every slice holds the
        global reduction (han two-level: reduce/ICI, exchange/DCN,
        bcast/ICI)."""
        local = self.slice.allreduce(x, op)          # ICI: slice total
        row = np.asarray(local)[0]                   # leader host copy
        combined = self._host_exchange(row, op)      # DCN: cross-slice
        return self._replicate(combined)             # ICI place (1x row)

    def _do_bcast(self, x, root_slice: int = 0, root: int = 0):
        """Broadcast device-row ``root`` of slice ``root_slice`` to
        every device of every slice."""
        from ompi_tpu.runtime import spc

        if self.slice_id == root_slice:
            local = self.slice.bcast(x, root)
            row = np.array(np.asarray(local)[0])  # writable copy
        else:
            # shape/dtype template; Bcast fills it in place, and numpy
            # views of jax arrays are read-only
            row = np.array(np.asarray(x)[0])
        with spc.suppressed():
            self.bridge.Bcast(row, root=root_slice)
        return self._replicate(row)

    def _do_allgather(self, x):
        """[D, ...] per slice -> [D, S*D, ...]: every device row holds
        all S*D contributions, slice-major (slice id, device pos)."""
        from ompi_tpu.runtime import spc

        local = self.slice.allgather(x)  # [D, D, ...]
        block = np.asarray(local)[0]     # [D, ...] this slice's rows
        block = np.ascontiguousarray(block)
        gathered = np.zeros((self.n_slices,) + block.shape, block.dtype)
        with spc.suppressed():
            self.bridge.Allgather(block, gathered)
        flat = gathered.reshape((self.world_size,) + block.shape[1:])
        return self._replicate(flat)

    def _do_reduce_scatter(self, x, op: _op.Op = _op.SUM):
        """[D, ...] -> each device row d of slice s holds the global
        reduction of block index s*D + d (block layout over the row's
        leading dim, which must equal world_size)."""
        local = self.slice.allreduce(x, op)  # slice totals, all devices
        rows = np.asarray(local)[0]
        if rows.shape[0] != self.world_size:
            raise MPIError(
                ERR_ARG,
                f"reduce_scatter needs leading dim {self.world_size}")
        combined = self._host_exchange(rows, op)
        D = self.slice.world_size
        mine = combined[self.slice_id * D:(self.slice_id + 1) * D]
        return self.slice.shard(np.ascontiguousarray(mine))

    def _do_alltoall(self, x):
        """[D, W, ...] per slice (W = world_size chunks per device row)
        -> [D, W, ...]: chunk j of world position i lands as chunk i of
        world position j. Two-level: slice-to-slice blocks ride one
        bridge Alltoall over the DCN; the within-block transpose is
        driver-local (the single controller already holds the slice's
        rows)."""
        from ompi_tpu.runtime import spc

        D = self.slice.world_size
        S = self.n_slices
        arr = np.asarray(x)
        if arr.ndim < 2 or arr.shape[0] != D or \
                arr.shape[1] != self.world_size:
            raise MPIError(
                ERR_ARG,
                f"alltoall expects [slice_devices={D}, "
                f"world={self.world_size}, ...], got {tuple(arr.shape)}")
        # block for target slice t: my rows' chunks t*D..(t+1)*D
        sendblocks = np.ascontiguousarray(
            arr.reshape((D, S, D) + arr.shape[2:]).transpose(
                (1, 0, 2) + tuple(range(3, arr.ndim + 1))))
        recvblocks = np.zeros_like(sendblocks)  # [S, Dsrc, Dmine, ...]
        with spc.suppressed():
            self.bridge.Alltoall(sendblocks, recvblocks)
        # out[d_mine, s*D + d_src] = recvblocks[s, d_src, d_mine]
        out = recvblocks.transpose(
            (2, 0, 1) + tuple(range(3, arr.ndim + 1))).reshape(arr.shape)
        return self.slice.shard(np.ascontiguousarray(out))

    def _do_barrier(self) -> None:
        from ompi_tpu.runtime import spc

        self.slice.barrier()
        with spc.suppressed():
            self.bridge.Barrier()

    # ------------------------------------------ nonblocking (MPI_I*)
    # The DCN hop is host-blocking, so the I* variants run the whole
    # two-level schedule on a worker thread (the io/file.py nonblocking
    # pattern); the returned Request completes when the sharded result
    # is placed. Single worker: bridge verbs must stay ordered — every
    # rank dispatches its calls in the same program order, and a second
    # thread could reorder two in-flight bridge collectives. BLOCKING
    # verbs funnel through the SAME worker queue (submit + Wait), so a
    # blocking call issued while an I* is in flight cannot overtake it.
    def _ireq(self, fn, *args, **kw):
        from concurrent.futures import ThreadPoolExecutor

        if not hasattr(self, "_pool"):
            self._pool = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="multislice-nbc")
            # reap the worker at MPI_Finalize (ADVICE r4: the executor
            # thread outlived the job)
            from ompi_tpu.hook import register_hook

            register_hook("finalize_top",
                          lambda: self._pool.shutdown(wait=False))

        req = _FutureRequest()

        def run():
            from ompi_tpu.core.errors import ERR_INTERN

            try:
                req.result = fn(*args, **kw)
                req._set_complete(0)
            except MPIError as e:
                req._set_complete(e.code)
            except Exception:  # noqa: BLE001 — a swallowed worker
                # exception would leave Wait() spinning forever
                from ompi_tpu.utils.output import get_logger

                get_logger("parallel.multislice").exception(
                    "nonblocking multislice verb failed")
                req._set_complete(ERR_INTERN)

        self._pool.submit(run)
        return req

    def iallreduce(self, x, op: _op.Op = _op.SUM):
        return self._ireq(self._do_allreduce, x, op)

    def ibcast(self, x, root_slice: int = 0, root: int = 0):
        return self._ireq(self._do_bcast, x, root_slice, root)

    def iallgather(self, x):
        return self._ireq(self._do_allgather, x)

    def ialltoall(self, x):
        return self._ireq(self._do_alltoall, x)

    def ireduce_scatter(self, x, op: _op.Op = _op.SUM):
        return self._ireq(self._do_reduce_scatter, x, op)

    def ibarrier(self):
        return self._ireq(self._do_barrier)

    def _ordered(self, fn, *args, **kw):
        """Run a blocking verb through the worker queue so it cannot
        overtake an in-flight nonblocking one (cross-rank bridge
        collectives match by program order)."""
        req = self._ireq(fn, *args, **kw)
        req.Wait()
        return req.result

    # public blocking verbs: same worker queue as the I* variants
    def allreduce(self, x, op: _op.Op = _op.SUM):
        return self._ordered(self._do_allreduce, x, op)

    def bcast(self, x, root_slice: int = 0, root: int = 0):
        return self._ordered(self._do_bcast, x, root_slice, root)

    def allgather(self, x):
        return self._ordered(self._do_allgather, x)

    def reduce_scatter(self, x, op: _op.Op = _op.SUM):
        return self._ordered(self._do_reduce_scatter, x, op)

    def alltoall(self, x):
        return self._ordered(self._do_alltoall, x)

    def barrier(self) -> None:
        self._ordered(self._do_barrier)

    Allreduce = allreduce
    Bcast = bcast
    Allgather = allgather
    Alltoall = alltoall
    Barrier = barrier
    Iallreduce = iallreduce
    Ibcast = ibcast
    Iallgather = iallgather
    Ialltoall = ialltoall
    Ibarrier = ibarrier
