"""Multi-slice mesh collectives: two-level ICI + DCN hierarchy.

Reference: ompi/mca/coll/han (coll_han_subcomms.c builds node-local +
leader subcomms and runs two-level algorithms over them). The mesh-mode
analog for TPU pods that span ICI domains: each *slice* is a device
mesh wired by ICI under one controller; slices are bridged by the
host-side DCN transport (tcp btl in process mode). A two-level
allreduce is

    slice-local XLA collective (psum over ICI)
    -> leader exchange over the bridge comm (DCN)
    -> slice-wide broadcast of the combined result (ICI again, via a
       sharded device_put — the slice-local psum already left every
       device with the slice sum, so the final hop is placement only)

which is exactly han's node-reduce / leader-allreduce / node-bcast
split with "node" = slice. The DCN hop stages through the host — the
true data path between slices that XLA's single-slice compilation
cannot express (multi-slice XLA would fuse it; this layer is the
framework-level fallback the reference's han provides for hierarchical
interconnects).

Deployment shape: one process (MPI rank) per slice controller; the
bridge is any ProcComm over those ranks (COMM_WORLD in the tests, with
the tcp btl as the DCN). The dryrun check models a 2x4-device universe
as 2 ranks each holding a 4-device virtual CPU mesh.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ompi_tpu.core import op as _op
from ompi_tpu.core.errors import MPIError, ERR_ARG
from ompi_tpu.parallel.mesh import XlaComm


class MultiSliceComm:
    """A communicator spanning ``bridge.size`` slices, each an XlaComm
    over this controller's local devices."""

    def __init__(self, slice_comm: XlaComm, bridge):
        if slice_comm.groups is not None:
            raise MPIError(ERR_ARG,
                           "multi-slice spans whole-mesh slice comms")
        self.slice = slice_comm
        self.bridge = bridge

    @property
    def n_slices(self) -> int:
        return self.bridge.size

    @property
    def slice_id(self) -> int:
        return self.bridge.rank

    @property
    def world_size(self) -> int:
        """Total devices across all slices (uniform slice size)."""
        return self.slice.world_size * self.n_slices

    # ------------------------------------------------------- collectives
    def _host_exchange(self, row: np.ndarray, op: _op.Op) -> np.ndarray:
        from ompi_tpu.runtime import spc

        out = np.zeros_like(row)
        with spc.suppressed():
            self.bridge.Allreduce(np.ascontiguousarray(row), out, op=op)
        return out

    def allreduce(self, x, op: _op.Op = _op.SUM):
        """[D, ...] per slice -> every device of every slice holds the
        global reduction (han two-level: reduce/ICI, exchange/DCN,
        bcast/ICI)."""
        local = self.slice.allreduce(x, op)          # ICI: slice total
        row = np.asarray(local)[0]                   # leader host copy
        combined = self._host_exchange(row, op)      # DCN: cross-slice
        full = np.broadcast_to(
            combined, (self.slice.world_size,) + combined.shape)
        return self.slice.shard(np.ascontiguousarray(full))  # ICI place

    def bcast(self, x, root_slice: int = 0, root: int = 0):
        """Broadcast device-row ``root`` of slice ``root_slice`` to
        every device of every slice."""
        from ompi_tpu.runtime import spc

        if self.slice_id == root_slice:
            local = self.slice.bcast(x, root)
            row = np.array(np.asarray(local)[0])  # writable copy
        else:
            # shape/dtype template; Bcast fills it in place, and numpy
            # views of jax arrays are read-only
            row = np.array(np.asarray(x)[0])
        with spc.suppressed():
            self.bridge.Bcast(row, root=root_slice)
        full = np.broadcast_to(row,
                               (self.slice.world_size,) + row.shape)
        return self.slice.shard(np.ascontiguousarray(full))

    def allgather(self, x):
        """[D, ...] per slice -> [D, S*D, ...]: every device row holds
        all S*D contributions, slice-major (slice id, device pos)."""
        from ompi_tpu.runtime import spc

        local = self.slice.allgather(x)  # [D, D, ...]
        block = np.asarray(local)[0]     # [D, ...] this slice's rows
        block = np.ascontiguousarray(block)
        gathered = np.zeros((self.n_slices,) + block.shape, block.dtype)
        with spc.suppressed():
            self.bridge.Allgather(block, gathered)
        flat = gathered.reshape((self.world_size,) + block.shape[1:])
        full = np.broadcast_to(
            flat, (self.slice.world_size,) + flat.shape)
        return self.slice.shard(np.ascontiguousarray(full))

    def reduce_scatter(self, x, op: _op.Op = _op.SUM):
        """[D, ...] -> each device row d of slice s holds the global
        reduction of block index s*D + d (block layout over the row's
        leading dim, which must equal world_size)."""
        local = self.slice.allreduce(x, op)  # slice totals, all devices
        rows = np.asarray(local)[0]
        if rows.shape[0] != self.world_size:
            raise MPIError(
                ERR_ARG,
                f"reduce_scatter needs leading dim {self.world_size}")
        combined = self._host_exchange(rows, op)
        D = self.slice.world_size
        mine = combined[self.slice_id * D:(self.slice_id + 1) * D]
        return self.slice.shard(np.ascontiguousarray(mine))

    def barrier(self) -> None:
        from ompi_tpu.runtime import spc

        self.slice.barrier()
        with spc.suppressed():
            self.bridge.Barrier()

    Allreduce = allreduce
    Bcast = bcast
    Allgather = allgather
    Barrier = barrier
