"""Mesh-mode communicators: MPI_COMM_WORLD projected onto a jax.Mesh.

The TPU-native execution model (BASELINE.json north star): the single
controller owns a 1-D device mesh; MPI ranks are mesh positions; a
"distributed buffer" is a global jax.Array whose leading dim is the rank
dim, sharded over the mesh axis. Sub-communicators (Split / Create_group)
become ``axis_index_groups`` partitions, so *every* sub-communicator
collective is still one XLA collective over ICI — the communicator↔mesh
projection SURVEY.md §7 ranks as hard part 2.

Reference analogs: ompi/communicator/comm.c (split/dup/group math) with the
CID agreement replaced by driver-local allocation (single controller ⇒ no
distributed agreement needed — the reference needs comm_cid.c:61-109 only
because every rank allocates independently).
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np

from ompi_tpu.comm.communicator import Intracomm
from ompi_tpu.core import op as _op
from ompi_tpu.core.errors import (
    MPIError,
    ERR_ARG,
    ERR_RANK,
    ERR_UNSUPPORTED_OPERATION,
)
from ompi_tpu.core.group import Group
from ompi_tpu.runtime import spc
from ompi_tpu.runtime import trace as _tr

UNDEFINED = -32766

_next_mesh_cid = [100]


class XlaComm(Intracomm):
    """A communicator (or a color-family of communicators) on a device mesh.

    ``groups`` is None for the world comm, else a partition of all mesh
    positions; collectives act within each group independently — after a
    Split the one XlaComm object *is* every color's communicator, observed
    from the driver.
    """

    def __init__(self, mesh, axis: str, groups: Optional[Tuple[Tuple[int, ...], ...]] = None,
                 name: str = ""):
        self.mesh = mesh
        self.axis = axis
        self.world_size = int(mesh.shape[axis])
        if groups is not None:
            groups = tuple(tuple(int(r) for r in g) for g in groups)
            flat = sorted(r for g in groups for r in g)
            if flat != list(range(self.world_size)):
                raise MPIError(
                    ERR_ARG,
                    "groups must partition all mesh positions "
                    "(pad non-members as singleton groups)",
                )
        self.groups = groups
        # pos_map[global mesh position] = rank within its group;
        # singleton_mask marks padding groups excluded from schedules.
        pos = np.zeros(self.world_size, dtype=np.int32)
        single = np.zeros(self.world_size, dtype=bool)
        if groups is not None:
            for g in groups:
                for p, r in enumerate(g):
                    pos[r] = p
                    single[r] = len(g) == 1
        else:
            pos = np.arange(self.world_size, dtype=np.int32)
        self.pos_map = pos
        self.singleton_mask = single
        cid = _next_mesh_cid[0]
        _next_mesh_cid[0] += 1
        super().__init__(Group(range(self.world_size)), cid,
                         name or f"mesh-comm-{cid}")
        self._jit_cache = {}
        # (verb, args...) -> compiled-executable thunk: the per-comm
        # resolved fn table (reference: the comm->c_coll pointer chase of
        # ompi/mpi/c/allreduce.c.in:115, resolved once per verb+args).
        # Populated by each verb's first (slow) call; a hot call is ONE
        # dict hit + the dispatch. Fast paths skip argument validation —
        # the first call through the slow path did it.
        self._fast = {}
        from ompi_tpu.coll.base import select_coll
        from ompi_tpu.coll.xla import stats as _xla_stats

        # compile-cache telemetry: fast-table dispatches count as cache
        # hits (coll_xla_cache_hits pvar); misses/build time come from
        # XlaColl._cached
        self._cstats = _xla_stats
        self.coll = select_coll(self)

    # ------------------------------------------------------------- queries
    @property
    def size(self) -> int:
        """Group size: uniform across non-singleton colors (singletons are
        padding); raises if real colors differ in size."""
        if self.groups is None:
            return self.world_size
        sizes = {len(g) for g in self.groups if len(g) > 1}
        if not sizes:
            return 1
        if len(sizes) != 1:
            raise MPIError(
                ERR_UNSUPPORTED_OPERATION,
                "non-uniform color sizes: split into uniform colors or "
                "query per-color via .groups",
            )
        return next(iter(sizes))

    def Get_rank(self):
        raise MPIError(
            ERR_UNSUPPORTED_OPERATION,
            "mesh-mode driver holds all ranks; use jax.lax.axis_index "
            f"('{self.axis}') inside shard_map, or process mode for "
            "per-rank control flow",
        )

    def _require_uniform_groups(self, what: str) -> None:
        _ = self.size  # raises when non-uniform

    def _check_root(self, root: int) -> None:
        # root bounds must not force uniform sizes (rooted ops on
        # non-uniform splits are fine: the root is a group-local
        # position; groups smaller than root+1 have no such member and
        # their rows are unspecified, matching singleton-padding rules)
        if self.groups is None:
            limit = self.world_size
        else:
            limit = max((len(g) for g in self.groups), default=1)
        if not 0 <= root < limit:
            raise MPIError(ERR_RANK, f"root {root} out of range")

    # ------------------------------------------------------------ sharding
    def sharding(self, *rest_spec):
        from jax.sharding import NamedSharding, PartitionSpec as P

        return NamedSharding(self.mesh, P(self.axis, *rest_spec))

    def shard(self, x):
        """Place a [world, ...] array with the rank dim over the mesh."""
        import jax

        return jax.device_put(x, self.sharding())

    # ------------------------------------------- functional collectives
    def _slot(self, name: str):
        self._check_usable()
        spc.record(name)  # allreduce records in its own fast path instead
        return self._verb_fn(name)

    def _verb_fn(self, name: str):
        """Slot lookup, wrapped in the comm.<verb> span when tracing
        (the slow path; fast-table dispatches span through _hot)."""
        fn = self.coll.get(name)
        if _tr.enabled():
            return _tr.wrap_span("comm." + name, "comm", fn)
        return fn

    def _hot(self, verb: str, fn, *args):
        """Shared fast-path epilogue: SPC bump + compile-cache-hit
        count + the comm.<verb> span (one branch when tracing is off —
        the dispatch-tax budget of the resolved table)."""
        spc.record(verb)
        self._cstats.hits += 1
        if _tr.enabled():
            with _tr.span("comm." + verb, cat="comm"):
                return fn(*args)
        return fn(*args)

    def _promote(self, fast_key, exec_key, wrap=None):
        """After a slow call, resolve the compiled executable into the
        fast table (no-op when a non-xla coll module owns the verb and
        didn't populate the shared _jit_cache layout)."""
        fn = self._jit_cache.get(exec_key)
        if fn is not None:
            self._fast[fast_key] = wrap(fn) if wrap is not None else fn

    def allreduce(self, x, op: _op.Op = _op.SUM):
        # hot path: ONE dict hit to the compiled executable — the r2
        # bench showed the 32KB point paying ~9us of Python prologue per
        # call, so everything else (usability check, tuple key build,
        # module imports) lives on the miss path
        fn = self._fast.get(("allreduce", op.uid))
        if fn is not None and not self.revoked:
            if op.is_pair:
                from ompi_tpu.coll.xla import _check_device_op

                _check_device_op(op, x)
            return self._hot("allreduce", fn, x)
        return self._allreduce_slow(x, op)

    def _allreduce_slow(self, x, op: _op.Op):
        self._check_usable()
        from ompi_tpu.coll.xla import cache_key, _check_device_op

        spc.record("allreduce")
        if op.name in _op.PAIR_OPS:
            # the cached executable retraces per shape, so the pair-layout
            # contract must hold on every call, not just the first
            _check_device_op(op, x)
        out = self._verb_fn("allreduce")(self, x, op)
        # a quant-negotiated comm caches its executable under a
        # discriminated key (coll/quant.py) so it can't collide with the
        # plain body XlaColl.reduce shares; prefer it when present
        qkey = cache_key("allreduce", op, extra=("quant",))
        if qkey in self._jit_cache:
            self._promote(("allreduce", op.uid), qkey)
        else:
            self._promote(("allreduce", op.uid), cache_key("allreduce", op))
        return out

    def reduce(self, x, op: _op.Op = _op.SUM, root: int = 0):
        # the mesh schedule computes the reduction on every group row, so
        # XlaColl.reduce shares allreduce's executable — but the fast key
        # is reduce's own, populated only by reduce's slow path (another
        # coll module may implement reduce differently)
        fn = self._fast.get(("reduce", op.uid, root))
        if fn is not None and not self.revoked:
            if op.is_pair:
                from ompi_tpu.coll.xla import _check_device_op

                _check_device_op(op, x)
            return self._hot("reduce", fn, x)
        self._check_usable()
        self._check_root(root)
        from ompi_tpu.coll.xla import cache_key

        spc.record("reduce")
        out = self._verb_fn("reduce")(self, x, op, root)
        self._promote(("reduce", op.uid, root),
                      cache_key("allreduce", op))
        return out

    def bcast(self, x, root: int = 0):
        fn = self._fast.get(("bcast", root))
        if fn is not None and not self.revoked:
            return self._hot("bcast", fn, x)
        self._check_usable()
        self._check_root(root)
        from ompi_tpu.coll.xla import cache_key

        spc.record("bcast")
        out = self._verb_fn("bcast")(self, x, root)
        import jax.numpy as jnp

        r = jnp.int32(root)
        self._promote(("bcast", root), cache_key("bcast"),
                      wrap=lambda f: (lambda a, _f=f, _r=r: _f(a, _r)))
        return out

    def allgather(self, x):
        fn = self._fast.get(("allgather",))
        if fn is not None and not self.revoked:
            return self._hot("allgather", fn, x)
        self._check_usable()
        from ompi_tpu.coll.xla import cache_key

        spc.record("allgather")
        out = self._verb_fn("allgather")(self, x)
        self._promote(("allgather",), cache_key("allgather"))
        return out

    def alltoall(self, x):
        fn = self._fast.get(("alltoall",))
        if fn is not None and not self.revoked:
            return self._hot("alltoall", fn, x)
        self._check_usable()
        from ompi_tpu.coll.xla import cache_key

        spc.record("alltoall")
        out = self._verb_fn("alltoall")(self, x)
        self._promote(("alltoall",), cache_key("alltoall"))
        return out

    def reduce_scatter(self, x, op: _op.Op = _op.SUM):
        fn = self._fast.get(("reduce_scatter", op.uid))
        if fn is not None and not self.revoked:
            return self._hot("reduce_scatter_block", fn, x)
        self._check_usable()
        from ompi_tpu.coll.xla import cache_key

        spc.record("reduce_scatter_block")
        out = self._verb_fn("reduce_scatter_block")(self, x, op)
        self._promote(("reduce_scatter", op.uid),
                      cache_key("reduce_scatter_block", op))
        return out

    def scan(self, x, op: _op.Op = _op.SUM):
        fn = self._fast.get(("scan", op.uid))
        if fn is not None and not self.revoked:
            if op.is_pair:
                from ompi_tpu.coll.xla import _check_device_op

                _check_device_op(op, x)
            return self._hot("scan", fn, x)
        from ompi_tpu.coll.xla import cache_key

        out = self._slot("scan")(self, x, op)
        self._promote(("scan", op.uid), cache_key("scan", op, (False,)))
        return out

    def exscan(self, x, op: _op.Op = _op.SUM):
        fn = self._fast.get(("exscan", op.uid))
        if fn is not None and not self.revoked:
            if op.is_pair:
                from ompi_tpu.coll.xla import _check_device_op

                _check_device_op(op, x)
            return self._hot("exscan", fn, x)
        from ompi_tpu.coll.xla import cache_key

        out = self._slot("exscan")(self, x, op)
        self._promote(("exscan", op.uid), cache_key("scan", op, (True,)))
        return out

    def barrier(self) -> None:
        fn = self._fast.get(("barrier",))
        if fn is not None and not self.revoked:
            self._hot("barrier", fn)
            return
        self._slot("barrier")(self)
        from ompi_tpu.coll.xla import cache_key

        f = self._jit_cache.get(cache_key("barrier"))
        if f is not None:
            import jax.numpy as jnp

            # the tiny psum input is constant: device_put it once and
            # close over it — a fast barrier is one dict hit + dispatch
            x = self.shard(jnp.ones((self.world_size, 1), jnp.int32))
            self._fast[("barrier",)] = \
                lambda _f=f, _x=x: _f(_x).block_until_ready()

    def gather(self, x, root: int = 0):
        fn = self._fast.get(("gather", root))
        if fn is not None and not self.revoked:
            return self._hot("gather", fn, x)
        self._check_root(root)
        from ompi_tpu.coll.xla import cache_key, XlaColl

        out = self._slot("gather")(self, x, root)
        # the mesh gather is the allgather strengthening (xla.py gather)
        # — a CROSS-verb exec key, so the promote must verify the xla
        # module actually owns the gather slot (another module's gather
        # could have real root-only semantics while a prior allgather
        # call populated the allgather executable independently)
        owner = getattr(self.coll.get("gather"), "__self__", None)
        if isinstance(owner, XlaColl):
            self._promote(("gather", root), cache_key("allgather"))
        return out

    def scatter(self, x, root: int = 0):
        fn = self._fast.get(("scatter", root))
        if fn is not None and not self.revoked:
            return self._hot("scatter", fn, x)
        self._check_root(root)
        from ompi_tpu.coll.xla import cache_key

        out = self._slot("scatter")(self, x, root)
        import jax.numpy as jnp

        r = jnp.int32(root)
        G = self.size

        def wrap(f):
            def fast(a, _f=f, _r=r, _G=G):
                # the slow path's shape contract must hold on EVERY call
                # (the cached jit would retrace and silently clamp)
                if a.ndim < 2 or a.shape[1] != _G:
                    raise MPIError(
                        ERR_ARG,
                        f"scatter expects [world, group_size={_G}, ...], "
                        f"got {tuple(a.shape)}")
                return _f(a, _r)
            return fast

        self._promote(("scatter", root), cache_key("scatter"), wrap=wrap)
        return out

    # MPI-style aliases
    Allreduce = allreduce
    Bcast = bcast
    Allgather = allgather
    Alltoall = alltoall
    Barrier = barrier

    # ------------------------------------ nonblocking collectives (MPI_I*)
    # jax dispatch is already asynchronous: the jitted executable is
    # enqueued and control returns before the collective completes on
    # device. The I* variants surface that as a Request whose ``result``
    # holds the output array — Wait() blocks on device readiness
    # (reference: coll/libnbc round schedules; here the "schedule" is the
    # XLA program and ICI does the progression).
    def _ireq(self, result):
        from ompi_tpu.coll.sched import JaxRequest

        return JaxRequest(result)

    def iallreduce(self, x, op: _op.Op = _op.SUM):
        return self._ireq(self.allreduce(x, op))

    def ibcast(self, x, root: int = 0):
        return self._ireq(self.bcast(x, root))

    def ireduce(self, x, op: _op.Op = _op.SUM, root: int = 0):
        return self._ireq(self.reduce(x, op, root))

    def iallgather(self, x):
        return self._ireq(self.allgather(x))

    def ialltoall(self, x):
        return self._ireq(self.alltoall(x))

    def ireduce_scatter(self, x, op: _op.Op = _op.SUM):
        return self._ireq(self.reduce_scatter(x, op))

    def ibarrier(self):
        # the barrier collective itself is the dispatched executable; by
        # the time dispatch returns the round is enqueued on every shard
        from ompi_tpu.core.request import CompletedRequest

        self.barrier()
        return CompletedRequest()

    # ------------------------------------ persistent collectives (X_init)
    # MPI-4's third of the triple surface, TPU-native: the setup that
    # persistence amortizes is trace+compile. init runs one warm-up
    # dispatch (populating the per-comm jit cache) and PRE-FREEZES the
    # resolved fast-table executable into the request (coll/persist's
    # frozen-lowering discipline: Start skips even the fast-dict lookup
    # and the dispatch decision tree — revocation stays checked). With
    # coll_persist_donate=1, init also compiles a donated-operand
    # executable so Start(x) lets XLA reuse x's buffer for the output
    # (x is consumed). Reference: ompi/mca/coll/coll.h:545-620.
    def _pcoll_init(self, verb: str, x, *args, fast_key=None):
        from ompi_tpu.coll.sched import MeshPersistentRequest
        from ompi_tpu.coll import persist as _persist

        fn = getattr(self, verb)
        fn(x, *args)  # warm-up: trace+compile now, dispatch-only later
        frozen = None
        if fast_key is not None and _persist._enable_var._value:
            # coll_persist_enable=0 keeps the pre-PR-11 per-Start verb
            # dispatch verbatim — the same A/B contract as proc mode
            frozen = self._fast.get(fast_key)
        donate = None
        if frozen is not None:
            _persist._plans[0] += 1
            # the frozen dispatch keeps the fast-path epilogue (_hot:
            # SPC record + cache-hit count + comm.<verb> span) — a
            # persistent Start is still one collective invocation
            spc_name = ("reduce_scatter_block" if verb == "reduce_scatter"
                        else verb)
            dispatch = (lambda a, _f=frozen, _v=spc_name:
                        self._hot(_v, _f, a))
            if _persist._donate_var._value:
                import jax
                import jax.numpy as jnp

                dexec = jax.jit(frozen, donate_argnums=0)
                # warm the donated executable on a throwaway operand so
                # the first Start(x) is dispatch-only (init owns the
                # compile); the init-time x itself is never donated
                dexec(jnp.zeros_like(x))
                donate = (lambda a, _f=dexec, _v=spc_name:
                          self._hot(_v, _f, a))
        else:
            dispatch = lambda op_x: fn(op_x, *args)  # noqa: E731
        return MeshPersistentRequest(self, dispatch, x,
                                     frozen=frozen is not None,
                                     donate=donate)

    @staticmethod
    def _op_key(op: _op.Op):
        # pair ops re-validate their layout per call on the fast path;
        # a frozen executable would skip that check, so they keep the
        # legacy per-Start dispatch
        return None if op.is_pair else op.uid

    def allreduce_init(self, x, op: _op.Op = _op.SUM):
        k = self._op_key(op)
        return self._pcoll_init(
            "allreduce", x, op,
            fast_key=None if k is None else ("allreduce", k))

    def bcast_init(self, x, root: int = 0):
        return self._pcoll_init("bcast", x, root,
                                fast_key=("bcast", root))

    def reduce_init(self, x, op: _op.Op = _op.SUM, root: int = 0):
        k = self._op_key(op)
        return self._pcoll_init(
            "reduce", x, op, root,
            fast_key=None if k is None else ("reduce", k, root))

    def allgather_init(self, x):
        return self._pcoll_init("allgather", x, fast_key=("allgather",))

    def alltoall_init(self, x):
        return self._pcoll_init("alltoall", x, fast_key=("alltoall",))

    def reduce_scatter_init(self, x, op: _op.Op = _op.SUM):
        k = self._op_key(op)
        return self._pcoll_init(
            "reduce_scatter", x, op,
            fast_key=None if k is None else ("reduce_scatter", k))

    def scan_init(self, x, op: _op.Op = _op.SUM):
        k = self._op_key(op)
        return self._pcoll_init(
            "scan", x, op, fast_key=None if k is None else ("scan", k))

    def exscan_init(self, x, op: _op.Op = _op.SUM):
        k = self._op_key(op)
        return self._pcoll_init(
            "exscan", x, op,
            fast_key=None if k is None else ("exscan", k))

    Allreduce_init = allreduce_init
    Bcast_init = bcast_init
    Reduce_init = reduce_init
    Allgather_init = allgather_init
    Alltoall_init = alltoall_init
    Reduce_scatter_init = reduce_scatter_init
    Reduce_scatter_block_init = reduce_scatter_init  # ProcComm's spelling
    Scan_init = scan_init
    Exscan_init = exscan_init

    # ---------------------------------------- partitioned pt2pt (MPI-4)
    def Psend_init(self, x, perm: Sequence[Tuple[int, int]],
                   partitions: int):
        """Partitioned transfer: [W, K, ...] buffer, K split into
        ``partitions`` segments, each dispatched by Pready as its own
        segment of the ppermute schedule (reference: part.h:163; see
        parallel/partitioned.py)."""
        from ompi_tpu.parallel.partitioned import MeshPartitionedRequest

        return MeshPartitionedRequest(self, x, perm, partitions)

    # single-controller collapse: one request serves both endpoints
    Precv_init = Psend_init

    # ------------------------------------------------------------- pt2pt
    def permute(self, x, perm: Sequence[Tuple[int, int]]):
        """Tag-free pt2pt: move rank-rows along (src, dst) pairs in comm
        (group-local) ranks."""
        if self.groups is None:
            global_perm = tuple((int(s), int(d)) for s, d in perm)
        else:
            # singleton padding groups have no in-group peers to permute
            global_perm = tuple(
                (g[int(s)], g[int(d)])
                for g in self.groups
                if len(g) > 1
                for s, d in perm
            )
        fn = self._fast.get(("permute", global_perm))
        if fn is not None and not self.revoked:
            return self._hot("permute", fn, x)
        # slow path mirrors _hot's accounting (spc + span) so the FIRST
        # permute per schedule — the trace+compile one — isn't the only
        # call missing from counters and the trace
        spc.record("permute")
        slow = self._slot_permute()
        if _tr.enabled():
            slow = _tr.wrap_span("comm.permute", "comm", slow)
        out = slow(self, x, global_perm)
        from ompi_tpu.coll.xla import cache_key

        self._promote(("permute", global_perm),
                      cache_key("permute", extra=(global_perm,)))
        return out

    def _slot_permute(self):
        # permute is not one of the 17 standard slots; fetch the xla module
        # directly (host comms get pt2pt via pml instead).
        from ompi_tpu.coll.xla import XlaCollComponent

        mod = XlaCollComponent._module
        if mod is None:
            raise MPIError(ERR_UNSUPPORTED_OPERATION, "no xla coll module")
        return mod.permute

    def shift(self, x, steps: int = 1):
        """Ring shift by `steps` within each group (MPI_Sendrecv around a
        ring — the ring_c example's traffic pattern)."""
        n = self.size
        perm = tuple((i, (i + steps) % n) for i in range(n))
        return self.permute(x, perm)

    # ---------------------------------------------------------- resharding
    def reshard(self, x, src_spec, dst_spec):
        """Redistribute the canonical [W, *local] distributed buffer
        between layouts, lowered to ONE coll/xla verb (allgather /
        alltoall / local slicing) by the reshard engine — never
        allgather-then-slice (reshard/exec.py mesh_reshard; the plan
        layer is ompi_tpu/reshard/plan.py). Not a resolved-table verb:
        each call re-derives the lowering (cache the result, or use the
        underlying verbs directly, for per-step resharding loops)."""
        from ompi_tpu.reshard.exec import mesh_reshard

        return mesh_reshard(self, x, src_spec, dst_spec)

    # ------------------------------------------------------------ topology
    # Reference: ompi/mca/topo projected TPU-native — cart coordinates are
    # a row-major reshape of the mesh axis, shifts are collective-permute
    # rings riding the ICI torus (periodic dims = wraparound links).
    def Create_cart(self, dims, periods=None, reorder=False) -> "XlaComm":
        from ompi_tpu.topo import CartTopo

        topo = CartTopo(dims, periods if periods is not None
                        else [False] * len(dims))
        if self.groups is not None:
            raise MPIError(ERR_UNSUPPORTED_OPERATION,
                           "create the cart from the whole-axis comm")
        if topo.size != self.world_size:
            raise MPIError(
                ERR_ARG,
                f"mesh cart must cover the whole axis: prod(dims)="
                f"{topo.size} != {self.world_size} devices")
        new = XlaComm(self.mesh, self.axis, None,
                      name=f"{self.name}-cart")
        new.topo = topo
        from ompi_tpu.topo import _reselect_coll

        _reselect_coll(new)
        return new

    def Get_topo(self):
        """(dims, periods, None): the driver holds every rank, so there
        is no calling-process coords entry (same 3-tuple arity as the
        host path)."""
        t = self._cart()
        return t.dims, t.periods, None

    def Get_coords(self, rank: int):
        return self._cart().coords(rank)

    def cart_shift(self, x, direction: int, disp: int = 1):
        """Data-level MPI_Cart_shift: every rank-row moves `disp` steps
        along `direction`; rows shifted in from non-periodic edges are
        zero (the ppermute boundary semantics standing in for
        MPI_PROC_NULL's undefined buffer)."""
        if self.groups is not None:
            raise MPIError(ERR_UNSUPPORTED_OPERATION,
                           "cart topologies cover the whole mesh axis")
        t = self._cart()
        pairs = []
        for r in range(self.world_size):
            _, dst = t.shift(r, direction, disp)
            if dst >= 0:
                pairs.append((r, dst))
        return self.permute(x, tuple(pairs))

    def Sub(self, remain_dims) -> "XlaComm":
        """MPI_Cart_sub: one Split materializing every sub-cart color."""
        from ompi_tpu.topo import attach_sub_cart

        t = self._cart()
        colors, keys = t.sub_colors(remain_dims)
        sub = self.Split(colors, keys)
        attach_sub_cart(sub, t, remain_dims)
        return sub

    def neighbor_allgather(self, x):
        """[W, ...] -> [W, K, ...]: slot k holds the k-th cart neighbor's
        row (zeros off non-periodic edges)."""
        fn = self._fast.get(("neighbor_allgather",))
        if fn is not None and not self.revoked:
            return self._hot("neighbor_allgather", fn, x)
        from ompi_tpu.coll.xla import cache_key

        out = self._slot("neighbor_allgather")(self, x)
        self._promote(("neighbor_allgather",),
                      cache_key("neighbor_allgather"))
        return out

    def neighbor_alltoall(self, x):
        """[W, K, ...] -> [W, K, ...]: block k goes to neighbor k."""
        fn = self._fast.get(("neighbor_alltoall",))
        if fn is not None and not self.revoked:
            return self._hot("neighbor_alltoall", fn, x)
        from ompi_tpu.coll.xla import cache_key

        out = self._slot("neighbor_alltoall")(self, x)
        K = 2 * len(self._cart().dims)

        def wrap(f):
            def fast(a, _f=f, _K=K):
                # slow path's K-block contract, re-checked per call (a
                # wrong block count would retrace into garbage/IndexError)
                if a.ndim < 2 or a.shape[1] != _K:
                    raise MPIError(
                        ERR_ARG,
                        f"neighbor_alltoall expects [world, {_K}, ...], "
                        f"got {tuple(a.shape)}")
                return _f(a)
            return fast

        self._promote(("neighbor_alltoall",),
                      cache_key("neighbor_alltoall"), wrap=wrap)
        return out

    Neighbor_allgather = neighbor_allgather
    Neighbor_alltoall = neighbor_alltoall

    # ------------------------------------------------------ comm management
    def Dup(self) -> "XlaComm":
        new = XlaComm(self.mesh, self.axis, self.groups,
                      name=f"{self.name}-dup")
        self._copy_attrs_to(new)
        return new

    def Split(self, colors: Sequence[int],
              keys: Optional[Sequence[int]] = None) -> "XlaComm":
        """MPI_Comm_split, driver-level: `colors[i]` / `keys[i]` are rank
        i's arguments; all colors are materialized at once as the groups
        partition of the returned comm."""
        if len(colors) != self.world_size:
            raise MPIError(ERR_ARG, "need one color per mesh position")
        keys = list(keys) if keys is not None else [0] * self.world_size
        by_color = {}
        for r, (c, k) in enumerate(zip(colors, keys)):
            by_color.setdefault(c, []).append((k, r))
        groups: List[Tuple[int, ...]] = []
        for c, members in sorted(by_color.items(),
                                 key=lambda kv: (kv[0] == UNDEFINED, kv[0])):
            members.sort()
            if c == UNDEFINED:
                groups.extend((r,) for _, r in members)  # singleton padding
            else:
                groups.append(tuple(r for _, r in members))
        return XlaComm(self.mesh, self.axis, tuple(groups),
                       name=f"{self.name}-split")

    def Create_group(self, ranks: Sequence[int]) -> "XlaComm":
        """Sub-communicator of a rank subset; non-members are padded as
        singleton groups (their rows are unspecified after collectives)."""
        member = set(int(r) for r in ranks)
        groups = [tuple(int(r) for r in ranks)]
        groups.extend((r,) for r in range(self.world_size) if r not in member)
        return XlaComm(self.mesh, self.axis, tuple(groups),
                       name=f"{self.name}-sub")

    def Free(self) -> None:
        self._delete_all_attrs()
        self._freed = True
        self._jit_cache.clear()
        self._fast.clear()
        self.coll = None


def mesh_world(devices=None, axis_name: str = "mpi_world") -> XlaComm:
    """Build the mesh-mode MPI_COMM_WORLD over all (or given) devices."""
    import jax
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()
    mesh = Mesh(np.asarray(devices), (axis_name,))
    return XlaComm(mesh, axis_name, name="MESH_COMM_WORLD")
