// Native datatype pack/unpack: runs-based gather/scatter.
//
// Reference analog: opal/datatype/opal_pack_general.c — the tight C
// loops walking a datatype's contiguous runs. The Python engine
// materializes an int64 byte-index array (8x the payload) and fancy-
// indexes; this walks the (offset, length) runs per element with plain
// memcpy — no index materialization, sequential writes of the packed
// stream.
//
// Contract (ctypes, see core/convertor.py):
//   run_off/run_len: the datatype's coalesced per-element byte runs
//   count elements, each spanning `extent` source bytes
//   pack:   src (typed layout)  -> dst (dense stream)
//   unpack: src (dense stream)  -> dst (typed layout)

#include <cstdint>
#include <cstring>

extern "C" {

void ompi_tpu_pack_runs(const uint8_t* src, uint8_t* dst,
                        const int64_t* run_off, const int64_t* run_len,
                        int64_t n_runs, int64_t count, int64_t extent) {
    uint8_t* out = dst;
    for (int64_t e = 0; e < count; ++e) {
        const uint8_t* base = src + e * extent;
        for (int64_t r = 0; r < n_runs; ++r) {
            std::memcpy(out, base + run_off[r],
                        static_cast<size_t>(run_len[r]));
            out += run_len[r];
        }
    }
}

void ompi_tpu_unpack_runs(const uint8_t* src, uint8_t* dst,
                          const int64_t* run_off, const int64_t* run_len,
                          int64_t n_runs, int64_t count, int64_t extent) {
    const uint8_t* in = src;
    for (int64_t e = 0; e < count; ++e) {
        uint8_t* base = dst + e * extent;
        for (int64_t r = 0; r < n_runs; ++r) {
            std::memcpy(base + run_off[r], in,
                        static_cast<size_t>(run_len[r]));
            in += run_len[r];
        }
    }
}

}  // extern "C"
