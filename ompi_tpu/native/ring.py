"""SPSC shared-memory ring: ctypes binding + layout-compatible Python
fallback.

The memory layout is defined by sm_ring.cpp (RingHdr: head@0, tail@64,
capacity@128, magic@136, data@192; frames [u64 len][bytes] aligned to 8,
WRAP sentinel = 2^64-1). The Python fallback reads/writes the exact same
layout, so mixed deployments (one rank with the .so, one without) share
rings correctly — aligned 8-byte loads/stores are atomic on every
platform jax runs on, which stands in for the C++ acquire/release pairs
(reference analog: opal/include/opal/sys atomics vs the gcc_builtin
fallback).
"""

from __future__ import annotations

import ctypes
import struct
from typing import Optional

import numpy as np

HDR_BYTES = 192
MAGIC = 0x534D52494E470002
WRAP = (1 << 64) - 1

_U64 = struct.Struct("<Q")


def _align8(v: int) -> int:
    return (v + 7) & ~7


class SmRing:
    """One ring living at ``offset`` inside a writable buffer (mmap)."""

    def __init__(self, mm, offset: int, nbytes: int, use_native: bool = True):
        self.mm = mm
        self.offset = offset
        self.nbytes = nbytes
        self._view = memoryview(mm)[offset : offset + nbytes]
        self.lib = None
        if use_native:
            from ompi_tpu.native import get_lib

            self.lib = get_lib()
        if self.lib is not None:
            self._base = ctypes.addressof(
                ctypes.c_char.from_buffer(mm, offset))
        # scratch buffer for native pops (one per ring, reused)
        self._scratch = np.empty(nbytes, dtype=np.uint8)
        # capacity is a pure function of nbytes (both init paths compute
        # (nbytes - HDR_BYTES) & ~7) — cache it so senders can pre-screen
        # can-never-fit frames without a per-send ctypes call
        self._cap = (nbytes - HDR_BYTES) & ~7

    def can_fit(self, length: int) -> bool:
        """Whether a frame with len(hdr)+len(payload) == ``length`` can
        EVER fit (the exact complement of push()'s -1 condition)."""
        return _align8(8 + length) + 8 <= self._cap

    # ------------------------------------------------------------ lifecycle
    def init(self) -> None:
        if self.lib is not None:
            if self.lib.smr_init(self._base, self.nbytes) != 0:
                raise ValueError("ring too small")
            return
        if self.nbytes < HDR_BYTES + 1024:
            raise ValueError("ring too small")
        v = self._view
        _U64.pack_into(v, 0, 0)      # head
        _U64.pack_into(v, 64, 0)     # tail
        _U64.pack_into(v, 128, (self.nbytes - HDR_BYTES) & ~7)  # capacity
        _U64.pack_into(v, 136, MAGIC)

    @property
    def capacity(self) -> int:
        if self.lib is not None:
            return self.lib.smr_capacity(self._base)
        return _U64.unpack_from(self._view, 128)[0]

    def used(self) -> int:
        if self.lib is not None:
            return self.lib.smr_used(self._base)
        v = self._view
        return _U64.unpack_from(v, 0)[0] - _U64.unpack_from(v, 64)[0]

    # ----------------------------------------------------------------- push
    def push(self, hdr: bytes, payload) -> int:
        """1 = pushed, 0 = full (retry later), -1 = can never fit."""
        if not isinstance(payload, (bytes, bytearray)):
            payload = np.ascontiguousarray(
                np.frombuffer(memoryview(payload).cast("B"), np.uint8)
                if not isinstance(payload, np.ndarray)
                else payload.reshape(-1).view(np.uint8))
        if self.lib is not None:
            if isinstance(payload, np.ndarray):
                pl = payload.ctypes.data
                plen = payload.nbytes
            else:
                pl = payload
                plen = len(payload)
            return self.lib.smr_push2(self._base, hdr, len(hdr), pl, plen)
        return self._py_push(hdr, bytes(payload))

    def _py_push(self, hdr: bytes, payload: bytes) -> int:
        v = self._view
        cap = _U64.unpack_from(v, 128)[0]
        length = len(hdr) + len(payload)
        need = _align8(8 + length)
        if need + 8 > cap:
            return -1
        head = _U64.unpack_from(v, 0)[0]
        tail = _U64.unpack_from(v, 64)[0]
        pos = head % cap
        to_end = cap - pos
        skip = to_end if to_end < need else 0
        if (head + skip + need) - tail > cap:
            return 0
        if skip:
            _U64.pack_into(v, HDR_BYTES + pos, WRAP)
            pos = 0
        _U64.pack_into(v, HDR_BYTES + pos, length)
        v[HDR_BYTES + pos + 8 : HDR_BYTES + pos + 8 + len(hdr)] = hdr
        if payload:
            start = HDR_BYTES + pos + 8 + len(hdr)
            v[start : start + len(payload)] = payload
        _U64.pack_into(v, 0, head + skip + need)  # publish
        return 1

    # ------------------------------------------------------------------ pop
    def pop(self) -> Optional[bytes]:
        """One frame as bytes, or None when empty."""
        if self.lib is not None:
            n = self.lib.smr_pop(self._base, self._scratch.ctypes.data,
                                 self._scratch.nbytes)
            if n < 0:
                raise RuntimeError("sm ring corrupt or scratch too small")
            if n == 0:
                return None
            return self._scratch[:n].tobytes()
        return self._py_pop()

    # ------------------------------------------------- zero-copy consume
    # peek() hands out a view INTO the ring; the frame's bytes stay valid
    # until advance(). This is the single-copy receive path (reference:
    # btl/sm hands the pml a pointer into the fifo segment) — the consumer
    # unpacks straight from shared memory into the posted buffer. With the
    # native lib, the cursor loads/stores carry real acquire/release
    # semantics; the pure-Python fallback relies on x86-TSO ordering of
    # aligned stores (correct on x86_64 only — weakly-ordered hosts should
    # always have the .so, since g++ is a baked-in dependency there too).
    def peek(self) -> Optional[memoryview]:
        if self.lib is not None:
            pos = ctypes.c_uint64()
            n = self.lib.smr_peek(self._base, ctypes.byref(pos))
            if n < 0:
                raise RuntimeError("sm ring corrupt")
            if n == 0:
                return None
            self._peeked = n
            start = HDR_BYTES + pos.value + 8
            return self._view[start : start + n]
        v = self._view
        cap = _U64.unpack_from(v, 128)[0]
        tail = _U64.unpack_from(v, 64)[0]
        head = _U64.unpack_from(v, 0)[0]
        if head == tail:
            return None
        pos = tail % cap
        length = _U64.unpack_from(v, HDR_BYTES + pos)[0]
        if length == WRAP:
            tail += cap - pos
            _U64.pack_into(v, 64, tail)  # consume the sentinel now
            pos = 0
            if head == tail:
                return None
            length = _U64.unpack_from(v, HDR_BYTES)[0]
        if length > cap:
            raise RuntimeError(
                f"sm ring corrupt: len={length:#x} pos={pos} head={head} "
                f"tail={tail} cap={cap}")
        self._peeked = length
        self._peek_tail = tail
        return v[HDR_BYTES + pos + 8 : HDR_BYTES + pos + 8 + length]

    def advance(self) -> None:
        """Release the frame returned by the last peek()."""
        if self.lib is not None:
            self.lib.smr_advance(self._base, self._peeked)
            return
        _U64.pack_into(self._view, 64,
                       self._peek_tail + _align8(8 + self._peeked))

    def _py_pop(self) -> Optional[bytes]:
        v = self._view
        cap = _U64.unpack_from(v, 128)[0]
        tail = _U64.unpack_from(v, 64)[0]
        head = _U64.unpack_from(v, 0)[0]
        if head == tail:
            return None
        pos = tail % cap
        length = _U64.unpack_from(v, HDR_BYTES + pos)[0]
        if length == WRAP:
            tail += cap - pos
            pos = 0
            if head == tail:
                _U64.pack_into(v, 64, tail)
                return None
            length = _U64.unpack_from(v, HDR_BYTES)[0]
        if length > cap:
            raise RuntimeError(
                f"sm ring corrupt: len={length:#x} pos={pos} head={head} "
                f"tail={tail} cap={cap}")
        out = bytes(v[HDR_BYTES + pos + 8 : HDR_BYTES + pos + 8 + length])
        _U64.pack_into(v, 64, tail + _align8(8 + length))
        return out
