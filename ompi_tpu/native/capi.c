/* capi.c — the C binding's implementation: classic MPI C calls backed
 * by the TPU-native Python runtime through an embedded interpreter.
 *
 * Reference analog: ompi/mpi/c/ (the generated C binding layer over the
 * internal ompi_* API). Redesign for this framework: the "internal API"
 * IS the Python runtime, so the binding embeds CPython once at
 * MPI_Init, resolves COMM_WORLD, and forwards each call while viewing
 * the caller's C buffers zero-copy as numpy arrays (PyMemoryView over
 * the raw pointer — no staging copies on the C side; the launch
 * contract arrives via the OMPI_TPU_* environment like any rank).
 *
 * Threading: single GIL holder per call (PyGILState_Ensure), released
 * between calls so MPI_THREAD_FUNNELED-style C programs work.
 */
#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <stdio.h>
#include <time.h>

#include "mpi.h"

static PyObject *g_mod;     /* ompi_tpu */
static PyObject *g_world;   /* resolved ProcComm (proxy unwrapped) */
static PyObject *g_np;      /* numpy */
static PyThreadState *g_main;
static int g_initialized;
static int g_finalized;

/* ------------------------------------------------------------ helpers */
static const char *dt_np(MPI_Datatype dt) {
    switch (dt) {
    case MPI_CHAR:   return "int8";
    case MPI_BYTE:   return "uint8";
    case MPI_INT:    return "int32";
    case MPI_LONG:   return "int64";
    case MPI_FLOAT:  return "float32";
    case MPI_DOUBLE: return "float64";
    }
    return NULL;
}

static Py_ssize_t dt_size(MPI_Datatype dt) {
    switch (dt) {
    case MPI_CHAR: case MPI_BYTE: return 1;
    case MPI_INT: case MPI_FLOAT: return 4;
    case MPI_LONG: case MPI_DOUBLE: return 8;
    }
    return 0;
}

static const char *op_name(MPI_Op op) {
    switch (op) {
    case MPI_SUM:  return "SUM";
    case MPI_MAX:  return "MAX";
    case MPI_MIN:  return "MIN";
    case MPI_PROD: return "PROD";
    }
    return NULL;
}

static int err_out(const char *where) {
    if (PyErr_Occurred()) {
        fprintf(stderr, "[ompi_tpu capi] %s failed:\n", where);
        PyErr_Print();
    } else {
        fprintf(stderr, "[ompi_tpu capi] %s failed\n", where);
    }
    return MPI_ERR_OTHER;
}

/* zero-copy numpy view over a C buffer */
static PyObject *as_array(const void *buf, int count, MPI_Datatype dt,
                          int writable) {
    const char *npdt = dt_np(dt);
    Py_ssize_t nbytes = (Py_ssize_t)count * dt_size(dt);
    if (!npdt || count < 0) {
        PyErr_SetString(PyExc_ValueError, "bad datatype/count");
        return NULL;
    }
    PyObject *mv = PyMemoryView_FromMemory(
        (char *)buf, nbytes, writable ? PyBUF_WRITE : PyBUF_READ);
    if (!mv) return NULL;
    PyObject *arr = PyObject_CallMethod(g_np, "frombuffer", "Os", mv,
                                        npdt);
    Py_DECREF(mv);
    return arr;
}

static PyObject *comm_obj(MPI_Comm comm) {
    if (comm == MPI_COMM_WORLD) return g_world;
    PyErr_SetString(PyExc_ValueError,
                    "the C binding currently exposes MPI_COMM_WORLD "
                    "only (build sub-comms in Python)");
    return NULL;
}

static PyObject *op_obj(MPI_Op op) {
    const char *name = op_name(op);
    if (!name) {
        PyErr_SetString(PyExc_ValueError, "unknown MPI_Op");
        return NULL;
    }
    PyObject *m = PyImport_ImportModule("ompi_tpu.core.op");
    if (!m) return NULL;
    PyObject *o = PyObject_GetAttrString(m, name);
    Py_DECREF(m);
    return o;
}

#define ENTER PyGILState_STATE gst_ = PyGILState_Ensure()
#define LEAVE PyGILState_Release(gst_)

/* ---------------------------------------------------------- lifecycle */
int MPI_Init(int *argc, char ***argv) {
    (void)argc; (void)argv;
    if (g_initialized) return MPI_SUCCESS;
    if (g_finalized) {
        /* the standard forbids re-init, and the released-GIL state
         * after finalize would make it a CPython fatal error anyway */
        fprintf(stderr, "[ompi_tpu capi] MPI_Init after MPI_Finalize "
                        "is not allowed\n");
        return MPI_ERR_OTHER;
    }
    if (!Py_IsInitialized())
        Py_InitializeEx(0);          /* keep the C program's signals */
    g_mod = PyImport_ImportModule("ompi_tpu");
    if (!g_mod) return err_out("import ompi_tpu");
    g_np = PyImport_ImportModule("numpy");
    if (!g_np) return err_out("import numpy");
    /* unwrap the lazy COMM_WORLD proxy via its getter so every later
     * call skips the proxy __getattr__ */
    PyObject *proxy = PyObject_GetAttrString(g_mod, "COMM_WORLD");
    if (!proxy) return err_out("COMM_WORLD");
    PyObject *getter = PyObject_GetAttrString(proxy, "_getter");
    if (getter) {
        g_world = PyObject_CallNoArgs(getter);
        Py_DECREF(getter);
        Py_DECREF(proxy);
        if (!g_world) return err_out("world init");
    } else {
        PyErr_Clear();
        g_world = proxy;
    }
    g_initialized = 1;
    g_main = PyEval_SaveThread();    /* release the GIL between calls */
    return MPI_SUCCESS;
}

int MPI_Initialized(int *flag) {
    /* stays true after finalize, per the standard */
    if (flag) *flag = g_initialized || g_finalized;
    return MPI_SUCCESS;
}

int MPI_Finalize(void) {
    if (!g_initialized) return MPI_SUCCESS;
    PyEval_RestoreThread(g_main);
    PyObject *r = PyObject_CallMethod(g_mod, "Finalize", NULL);
    int rc = r ? MPI_SUCCESS : err_out("Finalize");
    Py_XDECREF(r);
    Py_XDECREF(g_world);
    Py_XDECREF(g_np);
    Py_XDECREF(g_mod);
    g_initialized = 0;
    g_finalized = 1;
    /* keep the interpreter alive: Py_Finalize with live daemon threads
     * (progress engine) is UB; the process is exiting anyway */
    g_main = PyEval_SaveThread();
    return rc;
}

int MPI_Abort(MPI_Comm comm, int errorcode) {
    (void)comm;
    fprintf(stderr, "[ompi_tpu capi] MPI_Abort(%d)\n", errorcode);
    _exit(errorcode ? errorcode : 1);
}

/* ------------------------------------------------------------ queries */
static int int_query(MPI_Comm comm, const char *method, int *out) {
    ENTER;
    int rc = MPI_SUCCESS;
    PyObject *c = comm_obj(comm);
    PyObject *r = c ? PyObject_CallMethod(c, method, NULL) : NULL;
    if (!r) rc = err_out(method);
    else { *out = (int)PyLong_AsLong(r); Py_DECREF(r); }
    LEAVE;
    return rc;
}

int MPI_Comm_rank(MPI_Comm comm, int *rank) {
    return int_query(comm, "Get_rank", rank);
}

int MPI_Comm_size(MPI_Comm comm, int *size) {
    return int_query(comm, "Get_size", size);
}

double MPI_Wtime(void) {
    struct timespec ts;
    clock_gettime(CLOCK_MONOTONIC, &ts);
    return (double)ts.tv_sec + 1e-9 * (double)ts.tv_nsec;
}

/* -------------------------------------------------------------- pt2pt */
int MPI_Send(const void *buf, int count, MPI_Datatype dt, int dest,
             int tag, MPI_Comm comm) {
    ENTER;
    int rc = MPI_SUCCESS;
    PyObject *c = comm_obj(comm);
    PyObject *arr = c ? as_array(buf, count, dt, 0) : NULL;
    PyObject *r = arr ? PyObject_CallMethod(c, "Send", "Oii", arr, dest,
                                            tag) : NULL;
    if (!r) rc = err_out("MPI_Send");
    Py_XDECREF(r);
    Py_XDECREF(arr);
    LEAVE;
    return rc;
}

int MPI_Recv(void *buf, int count, MPI_Datatype dt, int source, int tag,
             MPI_Comm comm, MPI_Status *status) {
    ENTER;
    int rc = MPI_SUCCESS;
    PyObject *c = comm_obj(comm);
    PyObject *arr = c ? as_array(buf, count, dt, 1) : NULL;
    PyObject *st = NULL, *r = NULL;
    if (arr) {
        /* MPI_STATUS_IGNORE: skip the Status allocation entirely */
        st = status ? PyObject_CallMethod(g_mod, "Status", NULL)
                    : Py_None;
        r = st ? PyObject_CallMethod(c, "Recv", "OiiO", arr, source,
                                     tag, st) : NULL;
        if (st == Py_None) st = NULL;
    }
    if (!r) rc = err_out("MPI_Recv");
    else if (status) {
        PyObject *src = PyObject_GetAttrString(st, "source");
        PyObject *tg = PyObject_GetAttrString(st, "tag");
        PyObject *nb = PyObject_GetAttrString(st, "_nbytes");
        status->MPI_SOURCE = src ? (int)PyLong_AsLong(src) : -1;
        status->MPI_TAG = tg ? (int)PyLong_AsLong(tg) : -1;
        status->_nbytes = nb ? (int)PyLong_AsLong(nb) : 0;
        status->MPI_ERROR = MPI_SUCCESS;
        Py_XDECREF(src); Py_XDECREF(tg); Py_XDECREF(nb);
        PyErr_Clear();
    }
    Py_XDECREF(r);
    Py_XDECREF(st);
    Py_XDECREF(arr);
    LEAVE;
    return rc;
}

int MPI_Get_count(const MPI_Status *status, MPI_Datatype dt,
                  int *count) {
    Py_ssize_t sz = dt_size(dt);
    if (!status || !sz) return MPI_ERR_ARG;
    /* a partial element means the count is undefined, per the
     * standard (matches the Python Status.Get_count) */
    *count = (status->_nbytes % sz) ? MPI_UNDEFINED
                                    : (int)(status->_nbytes / sz);
    return MPI_SUCCESS;
}

/* -------------------------------------------------------- collectives */
int MPI_Barrier(MPI_Comm comm) {
    ENTER;
    int rc = MPI_SUCCESS;
    PyObject *c = comm_obj(comm);
    PyObject *r = c ? PyObject_CallMethod(c, "Barrier", NULL) : NULL;
    if (!r) rc = err_out("MPI_Barrier");
    Py_XDECREF(r);
    LEAVE;
    return rc;
}

int MPI_Bcast(void *buf, int count, MPI_Datatype dt, int root,
              MPI_Comm comm) {
    ENTER;
    int rc = MPI_SUCCESS;
    PyObject *c = comm_obj(comm);
    PyObject *arr = c ? as_array(buf, count, dt, 1) : NULL;
    PyObject *r = arr ? PyObject_CallMethod(c, "Bcast", "Oi", arr, root)
                      : NULL;
    if (!r) rc = err_out("MPI_Bcast");
    Py_XDECREF(r);
    Py_XDECREF(arr);
    LEAVE;
    return rc;
}

int MPI_Allreduce(const void *sendbuf, void *recvbuf, int count,
                  MPI_Datatype dt, MPI_Op op, MPI_Comm comm) {
    ENTER;
    int rc = MPI_SUCCESS;
    PyObject *c = comm_obj(comm);
    PyObject *s = c ? as_array(sendbuf, count, dt, 0) : NULL;
    PyObject *d = s ? as_array(recvbuf, count, dt, 1) : NULL;
    PyObject *o = d ? op_obj(op) : NULL;
    PyObject *r = o ? PyObject_CallMethod(c, "Allreduce", "OOO", s, d, o)
                    : NULL;
    if (!r) rc = err_out("MPI_Allreduce");
    Py_XDECREF(r); Py_XDECREF(o); Py_XDECREF(d); Py_XDECREF(s);
    LEAVE;
    return rc;
}

int MPI_Reduce(const void *sendbuf, void *recvbuf, int count,
               MPI_Datatype dt, MPI_Op op, int root, MPI_Comm comm) {
    ENTER;
    int rc = MPI_SUCCESS;
    PyObject *c = comm_obj(comm);
    PyObject *s = c ? as_array(sendbuf, count, dt, 0) : NULL;
    /* non-roots may legally pass recvbuf=NULL; the runtime wants an
     * array object, so give it a scratch row there */
    PyObject *d = NULL;
    if (s) {
        if (recvbuf)
            d = as_array(recvbuf, count, dt, 1);
        else
            d = PyObject_CallMethod(g_np, "zeros", "is", count,
                                    dt_np(dt));
    }
    PyObject *o = d ? op_obj(op) : NULL;
    PyObject *r = o ? PyObject_CallMethod(c, "Reduce", "OOOi", s, d, o,
                                          root) : NULL;
    if (!r) rc = err_out("MPI_Reduce");
    Py_XDECREF(r); Py_XDECREF(o); Py_XDECREF(d); Py_XDECREF(s);
    LEAVE;
    return rc;
}

int MPI_Allgather(const void *sendbuf, int sendcount,
                  MPI_Datatype sendtype, void *recvbuf, int recvcount,
                  MPI_Datatype recvtype, MPI_Comm comm) {
    ENTER;
    int rc = MPI_SUCCESS;
    int size = 0;
    PyObject *c = comm_obj(comm);
    PyObject *s = c ? as_array(sendbuf, sendcount, sendtype, 0) : NULL;
    PyObject *d = NULL, *r = NULL;
    if (s && int_query(comm, "Get_size", &size) == MPI_SUCCESS)
        d = as_array(recvbuf, recvcount * size, recvtype, 1);
    if (d)
        r = PyObject_CallMethod(c, "Allgather", "OO", s, d);
    if (!r) rc = err_out("MPI_Allgather");
    Py_XDECREF(r); Py_XDECREF(d); Py_XDECREF(s);
    LEAVE;
    return rc;
}
