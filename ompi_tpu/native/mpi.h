/* mpi.h — C binding for the ompi_tpu framework.
 *
 * Reference analog: ompi/include/mpi.h.in — the reference's primary
 * user-facing surface is the C API; this header exposes the same core
 * subset over the TPU-native Python runtime via an embedded
 * interpreter (ompi_tpu/native/capi.c). Build programs with the
 * `python -m ompi_tpu.tools.mpicc` wrapper (the mpicc analog), run
 * them with the usual launcher:
 *
 *     python -m ompi_tpu.tools.mpicc ring.c -o ring
 *     python -m ompi_tpu.tools.mpirun -np 4 ./ring
 */
#ifndef OMPI_TPU_MPI_H
#define OMPI_TPU_MPI_H

#ifdef __cplusplus
extern "C" {
#endif

typedef int MPI_Comm;
#define MPI_COMM_NULL  (-1)
#define MPI_COMM_WORLD 0
#define MPI_COMM_SELF  1

typedef int MPI_Datatype;
#define MPI_DATATYPE_NULL 0
#define MPI_CHAR          1
#define MPI_BYTE          2
#define MPI_INT           3
#define MPI_LONG          4
#define MPI_FLOAT         5
#define MPI_DOUBLE        6
#define MPI_INT32_T       MPI_INT
#define MPI_INT64_T       MPI_LONG
#define MPI_UNSIGNED_CHAR MPI_BYTE

typedef int MPI_Op;
#define MPI_OP_NULL 0
#define MPI_SUM     1
#define MPI_MAX     2
#define MPI_MIN     3
#define MPI_PROD    4

#define MPI_ANY_SOURCE (-1)
#define MPI_ANY_TAG    (-1)
#define MPI_PROC_NULL  (-2)
#define MPI_UNDEFINED  (-32766)

#define MPI_SUCCESS     0
#define MPI_ERR_OTHER   16
#define MPI_ERR_ARG     13
#define MPI_MAX_ERROR_STRING 256

typedef struct {
    int MPI_SOURCE;
    int MPI_TAG;
    int MPI_ERROR;
    int _nbytes;   /* internal: received byte count for MPI_Get_count */
} MPI_Status;
#define MPI_STATUS_IGNORE ((MPI_Status *)0)

int    MPI_Init(int *argc, char ***argv);
int    MPI_Finalize(void);
int    MPI_Initialized(int *flag);
int    MPI_Comm_rank(MPI_Comm comm, int *rank);
int    MPI_Comm_size(MPI_Comm comm, int *size);
int    MPI_Send(const void *buf, int count, MPI_Datatype dt, int dest,
                int tag, MPI_Comm comm);
int    MPI_Recv(void *buf, int count, MPI_Datatype dt, int source,
                int tag, MPI_Comm comm, MPI_Status *status);
int    MPI_Get_count(const MPI_Status *status, MPI_Datatype dt,
                     int *count);
int    MPI_Barrier(MPI_Comm comm);
int    MPI_Bcast(void *buf, int count, MPI_Datatype dt, int root,
                 MPI_Comm comm);
int    MPI_Allreduce(const void *sendbuf, void *recvbuf, int count,
                     MPI_Datatype dt, MPI_Op op, MPI_Comm comm);
int    MPI_Reduce(const void *sendbuf, void *recvbuf, int count,
                  MPI_Datatype dt, MPI_Op op, int root, MPI_Comm comm);
int    MPI_Allgather(const void *sendbuf, int sendcount,
                     MPI_Datatype sendtype, void *recvbuf, int recvcount,
                     MPI_Datatype recvtype, MPI_Comm comm);
int    MPI_Abort(MPI_Comm comm, int errorcode);
double MPI_Wtime(void);

#ifdef __cplusplus
}
#endif
#endif /* OMPI_TPU_MPI_H */
