"""Native (C++) components and their ctypes bindings.

The reference implements its transports, rings, and atomics in C
(opal/class/opal_fifo.c, btl/sm); this package holds the TPU framework's
C++ equivalents, compiled on demand with the system toolchain and loaded
via ctypes (no pybind11 in the image). Every native component has a
pure-Python fallback so the framework still runs where no compiler
exists — the fallback implements the exact same memory layout, so a
Python rank and a C++ rank can share one ring.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import tempfile
import threading
from typing import Optional

from ompi_tpu.utils.output import get_logger

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRCS = [os.path.join(_HERE, "sm_ring.cpp"),
         os.path.join(_HERE, "convertor.cpp")]
_SO = os.path.join(_HERE, "_ompi_tpu_native.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_lib_tried = False


def compile_so(cmd_prefix, srcs, dest, timeout=180, on_error=None):
    """Race-safe on-demand compile shared by every native lib: build to
    a private temp file in dest's directory, atomically rename into
    place (last writer wins; identical content makes the race
    harmless). Returns dest or None; failures (including an unwritable
    destination directory) go through ``on_error(message)``."""
    report = on_error or (lambda m: get_logger("native").warning("%s", m))
    try:
        fd, tmp = tempfile.mkstemp(suffix=".so",
                                   dir=os.path.dirname(dest))
        os.close(fd)
    except OSError as e:
        report(f"cannot write {os.path.dirname(dest)}: {e}")
        return None
    try:
        subprocess.run(list(cmd_prefix) + list(srcs) + ["-o", tmp],
                       check=True, capture_output=True, text=True,
                       timeout=timeout)
        os.rename(tmp, dest)
        return dest
    except (subprocess.SubprocessError, OSError) as e:
        detail = getattr(e, "stderr", "") or str(e)
        report(f"native build failed: {detail.strip()[:500]}")
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return None


def _build() -> bool:
    log = get_logger("native")
    return compile_so(
        ["g++", "-O2", "-shared", "-fPIC", "-std=c++17"], _SRCS, _SO,
        timeout=120,
        on_error=lambda m: log.warning(
            "%s (falling back to Python)", m)) is not None


def get_lib() -> Optional[ctypes.CDLL]:
    """The native library, building it if needed; None if unavailable."""
    global _lib, _lib_tried
    with _lock:
        if _lib is not None or _lib_tried:
            return _lib
        _lib_tried = True
        src_mtime = max(os.path.getmtime(p) for p in _SRCS)
        if not os.path.exists(_SO) or os.path.getmtime(_SO) < src_mtime:
            if not _build():
                return None
        try:
            lib = ctypes.CDLL(_SO)
        except OSError as e:
            get_logger("native").warning("cannot load %s: %s", _SO, e)
            return None
        lib.smr_header_bytes.restype = ctypes.c_uint64
        lib.smr_init.restype = ctypes.c_int
        lib.smr_init.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        lib.smr_capacity.restype = ctypes.c_uint64
        lib.smr_capacity.argtypes = [ctypes.c_void_p]
        lib.smr_push2.restype = ctypes.c_int
        lib.smr_push2.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                  ctypes.c_uint64, ctypes.c_void_p,
                                  ctypes.c_uint64]
        lib.smr_pop.restype = ctypes.c_int64
        lib.smr_pop.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                ctypes.c_uint64]
        lib.smr_peek.restype = ctypes.c_int64
        lib.smr_peek.argtypes = [ctypes.c_void_p,
                                 ctypes.POINTER(ctypes.c_uint64)]
        lib.smr_advance.restype = None
        for fn in (lib.ompi_tpu_pack_runs, lib.ompi_tpu_unpack_runs):
            fn.restype = None
            fn.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                           ctypes.c_void_p, ctypes.c_void_p,
                           ctypes.c_int64, ctypes.c_int64,
                           ctypes.c_int64]
        lib.smr_advance.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        lib.smr_used.restype = ctypes.c_uint64
        lib.smr_used.argtypes = [ctypes.c_void_p]
        _lib = lib
        return _lib
