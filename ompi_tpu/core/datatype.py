"""MPI datatype engine, TPU-native.

Reference: opal/datatype (9,409 LoC — the type-description + convertor
engine, opal_convertor.c:245 pack) and ompi/datatype (4,387 LoC — MPI-level
constructors). Re-designed rather than ported:

- A datatype is a **typemap**: a list of (numpy dtype, byte displacement)
  pairs plus (lb, extent). Predefined types are single-entry typemaps.
- At ``Commit()`` the typemap is flattened into a **byte map** — a numpy
  int64 array of source-byte offsets for one element — plus a coalesced
  **run list** of contiguous (offset, length) extents. Packing N elements is
  then a single vectorized gather (numpy fancy indexing), not the
  reference's per-segment interpreter loop: the TPU-native stance is that
  pack/unpack should itself be an array program.
- Contiguous types skip all of that and pack with one memcpy-equivalent
  slice (reference: the OPAL_DATATYPE_FLAG_CONTIGUOUS fast path).
- Partial packing (the convertor's position/resume contract used by
  pipelined rendezvous — opal_convertor_set_position) falls out of the byte
  map: packed-stream byte p of element stream maps to source byte
  (p // size) * extent + byte_map[p % size].

Device-resident data never flows through this engine: jax.Arrays are dense
and XLA reshapes/gathers handle layout on-device (see coll/xla). This engine
serves the host/DCN path (pt2pt wire format, MPI-IO, heterogeneous users).
"""

from __future__ import annotations

import threading
from typing import List, Optional, Sequence, Tuple

import numpy as np

from ompi_tpu.core.errors import MPIError, ERR_TYPE, ERR_ARG

# One typemap entry: (numpy dtype, byte displacement from element origin)
TypemapEntry = Tuple[np.dtype, int]

_next_id_lock = threading.Lock()
_next_id = 0


def _alloc_id() -> int:
    global _next_id
    with _next_id_lock:
        _next_id += 1
        return _next_id


class Datatype:
    """An MPI datatype (reference: ompi/datatype/ompi_datatype.h)."""

    def __init__(
        self,
        typemap: Sequence[TypemapEntry],
        lb: int = 0,
        extent: Optional[int] = None,
        name: str = "",
        np_dtype: Optional[np.dtype] = None,
    ):
        self.id = _alloc_id()
        self.typemap: List[TypemapEntry] = [
            (np.dtype(d), int(disp)) for d, disp in typemap
        ]
        self.name = name
        # size = true data bytes per element (reference: opal_datatype size)
        self.size = sum(d.itemsize for d, _ in self.typemap)
        if self.typemap:
            true_lb = min(disp for _, disp in self.typemap)
            true_ub = max(disp + d.itemsize for d, disp in self.typemap)
        else:
            true_lb = true_ub = 0
        self.true_lb = true_lb
        self.true_extent = true_ub - true_lb
        self.lb = int(lb)
        self.extent = int(extent) if extent is not None else true_ub - self.lb
        # Predefined scalar types carry their numpy dtype for the zero-copy
        # fast paths (coll/xla device arrays, contiguous host buffers).
        self.np_dtype = np.dtype(np_dtype) if np_dtype is not None else None
        self.committed = False
        self._byte_map: Optional[np.ndarray] = None
        self._runs: Optional[List[Tuple[int, int]]] = None
        # construction metadata for Get_envelope/Get_contents
        # (reference: ompi_datatype_get_args.c); None = predefined/NAMED
        self._contents: Optional[tuple] = None

    # ------------------------------------------------------------------ info
    @property
    def is_contiguous(self) -> bool:
        """True if `count` elements pack as one memcpy (no holes and
        extent == size)."""
        if not self.typemap:
            return True
        if self.size != self.extent or self.lb != 0:
            return False
        runs = self._compute_runs()
        return len(runs) == 1 and runs[0] == (0, self.size)

    def Get_size(self) -> int:
        return self.size

    def Get_extent(self) -> Tuple[int, int]:
        return self.lb, self.extent

    def Get_true_extent(self) -> Tuple[int, int]:
        return self.true_lb, self.true_extent

    def __repr__(self) -> str:
        return f"Datatype({self.name or self.id}, size={self.size}, extent={self.extent})"

    # ------------------------------------------------------------ commit/map
    def _compute_runs(self) -> List[Tuple[int, int]]:
        """Coalesced contiguous (offset, length) byte runs of one element."""
        if self._runs is not None:
            return self._runs
        spans = sorted(
            (disp, d.itemsize) for d, disp in self.typemap
        )
        runs: List[Tuple[int, int]] = []
        for off, ln in spans:
            if runs and runs[-1][0] + runs[-1][1] == off:
                runs[-1] = (runs[-1][0], runs[-1][1] + ln)
            else:
                runs.append((off, ln))
        self._runs = runs
        return runs

    def _compute_byte_map(self) -> np.ndarray:
        """int64[size] array: packed byte i of one element comes from source
        byte byte_map[i] (relative to element origin)."""
        if self._byte_map is None:
            parts = [
                np.arange(off, off + ln, dtype=np.int64)
                for off, ln in self._compute_runs()
            ]
            self._byte_map = (
                np.concatenate(parts) if parts else np.zeros(0, np.int64)
            )
        return self._byte_map

    def Get_envelope(self):
        """(num_integers, num_addresses, num_datatypes, combiner) —
        MPI_Type_get_envelope (reference: ompi_datatype_get_args.c)."""
        if self._contents is None:
            return 0, 0, 0, "NAMED"
        comb, ints, addrs, dts = self._contents
        return len(ints), len(addrs), len(dts), comb

    def Get_contents(self):
        """(integers, addresses, datatypes) the constructor was called
        with — MPI_Type_get_contents; errors on NAMED types per MPI."""
        if self._contents is None:
            raise MPIError(ERR_ARG,
                           "Get_contents on a predefined (NAMED) type")
        comb, ints, addrs, dts = self._contents
        return list(ints), list(addrs), list(dts)

    def _with_contents(self, comb, ints=(), addrs=(), dts=()):
        self._contents = (comb, list(ints), list(addrs), list(dts))
        return self

    def Commit(self) -> "Datatype":
        self._compute_byte_map()
        self.committed = True
        return self

    def Free(self) -> None:
        self.committed = False
        self._byte_map = None
        self._runs = None

    # ---------------------------------------------------------- constructors
    # Reference: ompi/datatype/ompi_datatype_create_*.c
    def Create_contiguous(self, count: int) -> "Datatype":
        tm = [
            (d, disp + i * self.extent)
            for i in range(count)
            for d, disp in self.typemap
        ]
        return Datatype(
            tm,
            lb=self.lb,
            extent=self.extent * count,
            name=f"contig({count})x{self.name}",
            np_dtype=self.np_dtype if self.is_contiguous else None,
        )._with_contents("CONTIGUOUS", [count], [], [self])

    def Create_vector(self, count: int, blocklength: int, stride: int) -> "Datatype":
        """stride in units of this type's extent (MPI_Type_vector)."""
        t = self.Create_hvector(count, blocklength, stride * self.extent)
        return t._with_contents("VECTOR", [count, blocklength, stride],
                                [], [self])

    def Create_hvector(self, count: int, blocklength: int, stride_bytes: int) -> "Datatype":
        tm = []
        for i in range(count):
            base = i * stride_bytes
            for j in range(blocklength):
                for d, disp in self.typemap:
                    tm.append((d, base + j * self.extent + disp))
        ub = (count - 1) * stride_bytes + blocklength * self.extent
        return Datatype(tm, lb=0, extent=ub,
                        name=f"vector{count}x{blocklength}")._with_contents(
            "HVECTOR", [count, blocklength], [stride_bytes], [self])

    def Create_indexed(
        self, blocklengths: Sequence[int], displacements: Sequence[int]
    ) -> "Datatype":
        """displacements in units of this type's extent (MPI_Type_indexed)."""
        t = self.Create_hindexed(
            blocklengths, [d * self.extent for d in displacements]
        )
        return t._with_contents(
            "INDEXED",
            [len(blocklengths)] + list(blocklengths) + list(displacements),
            [], [self])

    def Create_hindexed(
        self, blocklengths: Sequence[int], displacements_bytes: Sequence[int]
    ) -> "Datatype":
        if len(blocklengths) != len(displacements_bytes):
            raise MPIError(ERR_ARG, "blocklengths/displacements length mismatch")
        tm = []
        ub = 0
        for bl, db in zip(blocklengths, displacements_bytes):
            for j in range(bl):
                for d, disp in self.typemap:
                    tm.append((d, db + j * self.extent + disp))
            ub = max(ub, db + bl * self.extent)
        return Datatype(tm, lb=0, extent=ub, name="hindexed")._with_contents(
            "HINDEXED", [len(blocklengths)] + list(blocklengths),
            list(displacements_bytes), [self])

    @staticmethod
    def Create_struct(
        blocklengths: Sequence[int],
        displacements_bytes: Sequence[int],
        types: Sequence["Datatype"],
    ) -> "Datatype":
        if not (len(blocklengths) == len(displacements_bytes) == len(types)):
            raise MPIError(ERR_ARG, "struct argument length mismatch")
        tm = []
        ub = 0
        lb = None
        for bl, db, t in zip(blocklengths, displacements_bytes, types):
            for j in range(bl):
                for d, disp in t.typemap:
                    tm.append((d, db + j * t.extent + disp))
            ub = max(ub, db + bl * t.extent)
            lb = db if lb is None else min(lb, db)
        return Datatype(tm, lb=lb or 0, extent=ub - (lb or 0),
                        name="struct")._with_contents(
            "STRUCT", [len(blocklengths)] + list(blocklengths),
            list(displacements_bytes), list(types))

    def Create_subarray(
        self,
        sizes: Sequence[int],
        subsizes: Sequence[int],
        starts: Sequence[int],
        order: str = "C",
    ) -> "Datatype":
        """n-dim subarray (MPI_Type_create_subarray), used heavily by MPI-IO."""
        if not (len(sizes) == len(subsizes) == len(starts)):
            raise MPIError(ERR_ARG, "subarray argument length mismatch")
        if order != "C":
            sizes, subsizes, starts = sizes[::-1], subsizes[::-1], starts[::-1]
        # Flattened element offsets of the subarray inside the full array.
        idx = np.zeros((), np.int64)
        for sz, ssz, st in zip(sizes, subsizes, starts):
            idx = idx[..., None] * sz + (st + np.arange(ssz, dtype=np.int64))
        offsets = idx.reshape(-1)
        tm = [
            (d, int(o) * self.extent + disp)
            for o in offsets
            for d, disp in self.typemap
        ]
        total = int(np.prod(np.asarray(sizes, dtype=np.int64)))
        return Datatype(tm, lb=0, extent=total * self.extent,
                        name="subarray")._with_contents(
            "SUBARRAY",
            [len(sizes)] + list(sizes) + list(subsizes) + list(starts),
            [], [self])

    def Create_resized(self, lb: int, extent: int) -> "Datatype":
        return Datatype(self.typemap, lb=lb, extent=extent,
                        name=f"resized:{self.name}",
                        np_dtype=self.np_dtype)._with_contents(
            "RESIZED", [], [lb, extent], [self])

    def Dup(self) -> "Datatype":
        t = Datatype(self.typemap, lb=self.lb, extent=self.extent,
                     name=self.name, np_dtype=self.np_dtype)
        # MPI_Type_dup always reports COMBINER_DUP — including dups of
        # predefined types (reference: ompi_datatype_get_args.c records
        # DUP args unconditionally)
        return t._with_contents("DUP", [], [], [self])


# --------------------------------------------------------------- predefined
def _predef(np_dtype, name: str) -> Datatype:
    d = np.dtype(np_dtype)
    t = Datatype([(d, 0)], lb=0, extent=d.itemsize, name=name, np_dtype=d)
    t.Commit()
    return t


BYTE = _predef(np.uint8, "MPI_BYTE")
CHAR = _predef(np.int8, "MPI_CHAR")
BOOL = _predef(np.bool_, "MPI_C_BOOL")
INT8 = _predef(np.int8, "MPI_INT8_T")
INT16 = _predef(np.int16, "MPI_INT16_T")
INT32 = _predef(np.int32, "MPI_INT32_T")
INT64 = _predef(np.int64, "MPI_INT64_T")
UINT8 = _predef(np.uint8, "MPI_UINT8_T")
UINT16 = _predef(np.uint16, "MPI_UINT16_T")
UINT32 = _predef(np.uint32, "MPI_UINT32_T")
UINT64 = _predef(np.uint64, "MPI_UINT64_T")
FLOAT16 = _predef(np.float16, "MPI_FLOAT16")
FLOAT32 = _predef(np.float32, "MPI_FLOAT")
FLOAT64 = _predef(np.float64, "MPI_DOUBLE")
COMPLEX64 = _predef(np.complex64, "MPI_C_FLOAT_COMPLEX")
COMPLEX128 = _predef(np.complex128, "MPI_C_DOUBLE_COMPLEX")

# bfloat16 is the TPU-native float; expose it as a first-class predefined
# type (the reference has no bf16 — shortfloat ext is the closest analog:
# ompi/mpiext/shortfloat).
try:
    import ml_dtypes

    BFLOAT16 = _predef(ml_dtypes.bfloat16, "MPI_BFLOAT16")
except ImportError:  # pragma: no cover
    BFLOAT16 = FLOAT16

# C-style aliases
INT = INT32
LONG = INT64
FLOAT = FLOAT32
DOUBLE = FLOAT64

# MINLOC/MAXLOC pair types (reference: ompi_datatype_create pair types)
FLOAT_INT = Datatype.Create_struct(
    [1, 1], [0, 4], [FLOAT32, INT32]
).Commit()
FLOAT_INT.name = "MPI_FLOAT_INT"
DOUBLE_INT = Datatype.Create_struct(
    [1, 1], [0, 8], [FLOAT64, INT32]
).Commit()
DOUBLE_INT.name = "MPI_DOUBLE_INT"
INT_INT = Datatype.Create_struct([1, 1], [0, 4], [INT32, INT32]).Commit()
INT_INT.name = "MPI_2INT"

_BY_NP: dict = {}
for _t in (BOOL, INT8, INT16, INT32, INT64, UINT8, UINT16, UINT32, UINT64,
           FLOAT16, BFLOAT16, FLOAT32, FLOAT64, COMPLEX64, COMPLEX128):
    _BY_NP.setdefault(np.dtype(_t.np_dtype), _t)


def from_numpy_dtype(dt) -> Datatype:
    """Map a numpy/jax dtype to the predefined MPI datatype."""
    d = np.dtype(dt)
    t = _BY_NP.get(d)
    if t is None:
        raise MPIError(ERR_TYPE, f"no MPI datatype for numpy dtype {d}")
    return t
