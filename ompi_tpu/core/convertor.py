"""Pack/unpack convertor.

Reference: opal/datatype/opal_convertor.c:245 (opal_convertor_pack),
opal_convertor.h:259,277 (prepare_for_send/recv) and the position/resume
contract (opal_convertor_set_position) that the pipelined rendezvous
protocol depends on.

Design (TPU-native): packing is a vectorized numpy gather over the
datatype's committed byte map, not an interpreter loop over a description
stack. The convertor is a small stateful cursor over the packed stream so
transports can drain a message in arbitrary fragment sizes.
"""

from __future__ import annotations

from typing import Optional, Union

import numpy as np

from ompi_tpu.core.datatype import Datatype
from ompi_tpu.core.errors import MPIError, ERR_BUFFER, ERR_TRUNCATE


def _as_byte_view(buf) -> np.ndarray:
    """View any buffer-protocol object / ndarray as a flat uint8 array
    WITHOUT copying."""
    if isinstance(buf, np.ndarray):
        return buf.reshape(-1).view(np.uint8)
    mv = memoryview(buf)
    if mv.ndim != 1 or mv.format not in ("B", "b", "c"):
        mv = mv.cast("B")
    return np.frombuffer(mv, dtype=np.uint8)


# native runs engine (opal_pack_general.c analog): plain memcpy over the
# datatype's coalesced runs — no 8x index-matrix materialization. Worth
# the two ctypes array handoffs above this payload size; numpy below it.
_NATIVE_MIN_BYTES = 4096


def _runs_arrays(datatype: Datatype):
    arrs = getattr(datatype, "_run_arrays", None)
    if arrs is None:
        runs = datatype._compute_runs()
        arrs = (np.array([o for o, _ in runs], np.int64),
                np.array([n for _, n in runs], np.int64))
        datatype._run_arrays = arrs
    return arrs


def _native_lib():
    from ompi_tpu.native import get_lib

    return get_lib()


def pack(buf, count: int, datatype: Datatype) -> np.ndarray:
    """Pack `count` elements of `datatype` from `buf` into a dense uint8
    array (the wire format). Contiguous fast path is a zero-copy view when
    possible; large derived types run the native runs engine."""
    src = _as_byte_view(buf)
    need = (count - 1) * datatype.extent + datatype.true_lb + datatype.true_extent
    if count and src.nbytes < need:
        raise MPIError(ERR_BUFFER,
                       f"buffer too small: {src.nbytes} < {need}")
    if datatype.is_contiguous:
        return src[: count * datatype.size]
    total = count * datatype.size
    if total >= _NATIVE_MIN_BYTES and src.flags.c_contiguous:
        lib = _native_lib()
        if lib is not None:
            import ctypes

            off, ln = _runs_arrays(datatype)
            out = np.empty(total, np.uint8)
            lib.ompi_tpu_pack_runs(
                src.ctypes.data, out.ctypes.data,
                off.ctypes.data, ln.ctypes.data,
                len(off), count, datatype.extent)
            return out
    bm = datatype._compute_byte_map()
    # element origins x per-element byte map → full gather index
    origins = np.arange(count, dtype=np.int64) * datatype.extent
    idx = (origins[:, None] + bm[None, :]).reshape(-1)
    return src[idx]


def unpack(packed, buf, count: int, datatype: Datatype) -> None:
    """Scatter the dense wire stream back into `buf` honoring the typemap."""
    dst = _as_byte_view(buf)
    src = _as_byte_view(packed)
    total = count * datatype.size
    if src.nbytes < total:
        raise MPIError(ERR_TRUNCATE,
                       f"packed stream {src.nbytes} < expected {total}")
    if datatype.is_contiguous:
        dst[:total] = src[:total]
        return
    if total >= _NATIVE_MIN_BYTES and src.flags.c_contiguous and \
            dst.flags.c_contiguous and dst.flags.writeable:
        lib = _native_lib()
        if lib is not None:
            off, ln = _runs_arrays(datatype)
            lib.ompi_tpu_unpack_runs(
                src.ctypes.data, dst.ctypes.data,
                off.ctypes.data, ln.ctypes.data,
                len(off), count, datatype.extent)
            return
    bm = datatype._compute_byte_map()
    origins = np.arange(count, dtype=np.int64) * datatype.extent
    idx = (origins[:, None] + bm[None, :]).reshape(-1)
    dst[idx] = src[:total]


class Convertor:
    """Stateful fragment-at-a-time cursor (reference prepare/pack/position
    contract). One convertor per in-flight message."""

    def __init__(self, buf, count: int, datatype: Datatype, for_send: bool):
        self.buf = buf
        self.count = count
        self.datatype = datatype
        self.for_send = for_send
        self.packed_size = count * datatype.size
        self.position = 0
        self._bytes = _as_byte_view(buf)

    @property
    def remaining(self) -> int:
        return self.packed_size - self.position

    def set_position(self, pos: int) -> None:
        """Reposition mid-stream (reference: opal_convertor_set_position —
        required by the RDMA/rendezvous pipeline's out-of-order fragments)."""
        if pos < 0 or pos > self.packed_size:
            raise MPIError(ERR_BUFFER, f"position {pos} out of range")
        self.position = pos

    def _stream_index(self, start: int, n: int) -> np.ndarray:
        """Map packed-stream bytes [start, start+n) to source-byte offsets."""
        dt = self.datatype
        p = np.arange(start, start + n, dtype=np.int64)
        bm = dt._compute_byte_map()
        return (p // dt.size) * dt.extent + bm[p % dt.size]

    def pack_frag(self, max_bytes: int) -> np.ndarray:
        """Next fragment of the packed stream. Contiguous fast path: a
        BORROWED view of the caller's buffer — no materialization
        anywhere between here and the socket (the tcp btl sends it as
        an iovec and copies only what the kernel declines). The view is
        only guaranteed stable until the transport's send() returns,
        which is exactly the buffered-send window ob1 completes in.
        Non-contiguous types gather into a fresh (owned) array."""
        n = min(max_bytes, self.remaining)
        dt = self.datatype
        if dt.is_contiguous:
            out = self._bytes[self.position : self.position + n]
        else:
            out = self._bytes[self._stream_index(self.position, n)]
        self.position += n  # mpiracer: disable=cross-thread-race — a convertor is owned by exactly one in-flight request; the pump lock / engine lock at the call sites serialize per-message use
        return out

    def unpack_frag(self, data) -> int:
        # `data` may be a borrowed view of a transport pool block (the
        # zero-copy tcp rx path): _as_byte_view wraps it without a
        # copy, and the scatter below is the message's ONE landing copy
        # into the posted buffer
        src = _as_byte_view(data)
        n = min(src.nbytes, self.remaining)
        dt = self.datatype
        if dt.is_contiguous:
            self._bytes[self.position : self.position + n] = src[:n]
        else:
            self._bytes[self._stream_index(self.position, n)] = src[:n]
        self.position += n  # mpiracer: disable=cross-thread-race — same single-owner contract as pack_frag
        return n
