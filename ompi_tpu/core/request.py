"""Request lifecycle + completion.

Reference: ompi/request (2,834 LoC) — requests complete via a sync-object
CAS (request.h:451-478 ompi_request_wait_completion) while the caller drives
``opal_progress()`` (req_wait.c:35,225 default_wait/wait_all). Same model
here: ``Wait`` spins the progress engine until the completion flag flips;
transports flip it from the progress callback (or a progress thread).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, List, Optional, Sequence, Tuple

import os

from ompi_tpu.core.errors import MPIError, ERR_REQUEST, ERR_PENDING
from ompi_tpu.core.status import Status

# Wait-loop policy: on a multicore host blocking waits spin hot (the
# reference busy-polls in ompi_request_wait_completion); on a single core
# spinning just burns the peer's timeslice, so yield immediately. Use the
# AFFINITY mask, not cpu_count: a rank pinned to one core of a big host
# is effectively single-core.
try:
    _MULTICORE = len(os.sched_getaffinity(0)) > 1
except AttributeError:  # non-Linux
    _MULTICORE = (os.cpu_count() or 1) > 1


class IdleBackoff:
    """The ONE wait-loop yield discipline every blocking wait shares
    (Request.Wait, Waitany, progress_until): busy-poll while events flow,
    yield the GIL once briefly idle, back off to millisecond waits under
    sustained idleness. A pure spin starves the peer rank on one-core
    hosts (reference: ompi_request_wait_completion's busy-poll, tempered
    by opal's yield_when_idle)."""

    __slots__ = ("_idle_since",)

    def __init__(self):
        self._idle_since = None

    def step(self, made_progress: bool, idle_wait=None) -> None:
        """Call once per loop iteration after no-completion was observed;
        ``idle_wait`` (seconds -> None) replaces the deep-idle sleep with
        a condition-variable wait where one is available."""
        if made_progress:
            self._idle_since = None
            return
        now = time.monotonic()
        if self._idle_since is None:
            self._idle_since = now
        idle = now - self._idle_since
        if idle >= 0.002:
            (idle_wait or time.sleep)(0.001)
        elif _MULTICORE and idle < 0.0003:
            pass  # pure spin: yields cost ~100us under load
        else:
            time.sleep(0)  # single core: hand the CPU to the peer


class Request:
    """A pending communication. Subclasses (pml send/recv, coll, grequest)
    arrange for ``_set_complete`` to be called."""

    def __init__(self):
        self.status = Status()
        self._complete = threading.Event()
        self._error: int = 0
        self._error_reported = False
        self._on_complete: List[Callable[["Request"], None]] = []
        self._cb_lock = threading.Lock()
        self.persistent = False
        if _san_new is not None:  # sanitizer request-leak tracking
            _san_new(self)

    # ------------------------------------------------------------ completion
    def _set_complete(self, error: int = 0) -> None:
        self._error = error
        # each completion is a fresh activation (persistent requests
        # cycle): the error, if any, is raisable exactly once again
        self._error_reported = False
        self.status.error = error
        if _san_done is not None:
            _san_done(self)
        if _fx_note is not None:  # forensics stall-sentinel tick
            _fx_note(self)
        # Flip the flag and snapshot callbacks under the registration lock:
        # a registration racing on another thread either lands in the
        # snapshot or observes the flag and self-fires — never lost
        # (reference: the sync-object CAS of request.h:451).
        with self._cb_lock:
            self._complete.set()
            cbs = list(self._on_complete)
            self._on_complete.clear()
        for cb in cbs:
            cb(self)
        _completion_cond_notify()

    def add_completion_callback(self, cb: Callable[["Request"], None]) -> None:
        with self._cb_lock:
            if not self._complete.is_set():
                self._on_complete.append(cb)
                return
        cb(self)

    @property
    def is_complete(self) -> bool:
        return self._complete.is_set()

    # ------------------------------------------------------------- MPI verbs
    def Test(self, status: Optional[Status] = None) -> bool:
        _progress_once()
        if self._complete.is_set():
            self._finish(status)
            return True
        return False

    def Wait(self, status: Optional[Status] = None, timeout: Optional[float] = None) -> None:
        """Block until complete, driving progress (reference: request.h:451
        hot loop over opal_progress)."""
        deadline = None if timeout is None else time.monotonic() + timeout
        backoff = IdleBackoff()
        # sanitizer wait-for-graph edge: register this blocked wait so
        # the deadlock detector can chase probes through it (one global
        # load + branch when the sanitizer is off)
        watch = _san_wait(self) if _san_wait is not None else None
        try:
            while not self._complete.is_set():
                made_progress = _progress_once()
                if self._complete.is_set():
                    break
                if deadline is not None and time.monotonic() > deadline:
                    raise MPIError(ERR_PENDING, "Wait timed out")
                if watch is not None:
                    watch.poll()
                backoff.step(made_progress, _completion_cond_wait)
        finally:
            if watch is not None:
                watch.close()
        self._finish(status)

    def _finish(self, status: Optional[Status]) -> None:
        """Deliver completion to the caller. Idempotent per completion:
        a stored error is raised exactly ONCE per activation — multi-wait
        verbs (Waitsome/Waitany then Waitall) legitimately finish the
        same request twice, and a double raise abandoned the remaining
        done requests mid-loop (the Waitsome bug)."""
        if status is not None:
            status.source = self.status.source
            status.tag = self.status.tag
            status.error = self.status.error
            status._nbytes = self.status._nbytes
            status.cancelled = self.status.cancelled
        if self._error and not self._error_reported:
            self._error_reported = True
            raise MPIError(self._error)

    def Cancel(self) -> None:
        """Best-effort cancel (reference: requests may decline)."""
        pass

    def Free(self) -> None:
        pass

    # ----------------------------------------------------------- multi-wait
    @staticmethod
    def Waitall(requests: Sequence["Request"],
                statuses: Optional[List[Status]] = None) -> None:
        for i, r in enumerate(requests):
            st = statuses[i] if statuses is not None else None
            r.Wait(st)

    @staticmethod
    def Waitany(requests: Sequence["Request"],
                status: Optional[Status] = None) -> int:
        if not requests:
            return -1
        backoff = IdleBackoff()
        while True:
            for i, r in enumerate(requests):
                if r.is_complete:
                    r._finish(status)
                    return i
            backoff.step(_progress_once(), _completion_cond_wait)

    @staticmethod
    def Waitsome(requests: Sequence["Request"]) -> List[int]:
        """Wait until at least one request completes; finish and return
        the indices of ALL completed entries. Errors are collected and
        the first one raised only after every done entry is finished
        (MPI_Waitsome's ERR_IN_STATUS shape: one failure must not
        abandon the other completions)."""
        if not requests:
            return []
        backoff = IdleBackoff()
        while not any(r.is_complete for r in requests):
            backoff.step(_progress_once(), _completion_cond_wait)
        done = [i for i, r in enumerate(requests) if r.is_complete]
        first_error: Optional[MPIError] = None
        for i in done:
            try:
                requests[i]._finish(None)
            except MPIError as e:
                if first_error is None:
                    first_error = e
        if first_error is not None:
            raise first_error
        return done

    @staticmethod
    def Startall(requests: Sequence["Request"]) -> None:
        """Start every persistent request (MPI_Startall); lives on the
        base class so mixed persistent-request kinds share one entry."""
        for r in requests:
            r.Start()

    @staticmethod
    def Testall(requests: Sequence["Request"]) -> bool:
        _progress_once()
        return all(r.is_complete for r in requests)

    @staticmethod
    def Testany(requests: Sequence["Request"]) -> Tuple[int, bool]:
        _progress_once()
        for i, r in enumerate(requests):
            if r.is_complete:
                r._finish(None)
                return i, True
        return -1, False


class CompletedRequest(Request):
    """Immediately-complete request (SPMD-mode collectives return these once
    dispatch has been enqueued to XLA; buffer-ownership rules are satisfied
    by jax's functional semantics)."""

    def __init__(self, nbytes: int = 0, source: int = -1, tag: int = -1):
        super().__init__()
        self.status.source = source
        self.status.tag = tag
        self.status._nbytes = nbytes
        self._set_complete(0)


class Grequest(Request):
    """Generalized request (reference: ompi/request/grequest.c)."""

    def __init__(self, query_fn=None, free_fn=None, cancel_fn=None):
        super().__init__()
        self._query_fn = query_fn
        self._free_fn = free_fn
        self._cancel_fn = cancel_fn

    def Complete(self) -> None:
        if self._query_fn is not None:
            self._query_fn(self.status)
        self._set_complete(0)

    def Cancel(self) -> None:
        if self._cancel_fn is not None:
            self._cancel_fn(self._complete.is_set())
            self.status.cancelled = True

    def Free(self) -> None:
        if self._free_fn is not None:
            self._free_fn()


class Prequest(Request):
    """Persistent request (MPI_Send_init / MPI_Recv_init; reference:
    part/persist builds partitioned comm on these)."""

    def __init__(self, start_fn: Callable[["Prequest"], None]):
        super().__init__()
        self.persistent = True
        self._start_fn = start_fn
        self._complete.set()  # inactive == complete per MPI semantics

    def Start(self) -> "Prequest":
        self._complete.clear()
        self.status = Status()
        self._start_fn(self)
        return self


# ---------------------------------------------------------------- progress
# Wired to the runtime progress engine lazily so core stays import-light.
_progress_fn: Optional[Callable[[], int]] = None
_completion_cond = threading.Condition()

# Sanitizer hooks, bound lazily by runtime/sanitizer.py install() (same
# pattern as _bind_progress — core must not import the runtime). All
# three default to None so the disabled path costs one global load and
# a branch; _san_new fires per Request construction, _san_done per
# completion, _san_wait wraps blocked Waits for the deadlock detector.
_san_new: Optional[Callable[["Request"], None]] = None
_san_done: Optional[Callable[["Request"], None]] = None
_san_wait = None  # Request -> watch object with poll()/close(), or None

# Stall-sentinel completion tick, bound by runtime/forensics.py only
# while forensics_enable is set (rebound live on cvar writes) — the
# disabled path is this one global load per completion.
_fx_note: Optional[Callable[["Request"], None]] = None


def _bind_sanitizer(new, done, wait) -> None:
    global _san_new, _san_done, _san_wait
    _san_new, _san_done, _san_wait = new, done, wait


def _bind_progress(fn: Callable[[], int]) -> None:
    global _progress_fn
    _progress_fn = fn


# Idle-block wakeup, bound lazily by runtime/progress.py (same
# core-must-not-import-runtime pattern as _bind_progress): a request
# completing must wake any wait parked in the progress engine's idle
# select, or a pred that flips off-transport could sleep out the full
# park interval. The bound fn is the _parked-gated poke — one list load
# and a branch when nobody is parked.
_wakeup_fn: Optional[Callable[[], None]] = None


def _bind_wakeup(fn: Callable[[], None]) -> None:
    global _wakeup_fn
    _wakeup_fn = fn


def _progress_once() -> int:
    if _progress_fn is None:
        return 0
    return _progress_fn()


def _completion_cond_notify() -> None:
    with _completion_cond:
        _completion_cond.notify_all()
    if _wakeup_fn is not None:
        _wakeup_fn()


def _completion_cond_wait(timeout: float) -> None:
    with _completion_cond:
        _completion_cond.wait(timeout)
