"""Request lifecycle + completion.

Reference: ompi/request (2,834 LoC) — requests complete via a sync-object
CAS (request.h:451-478 ompi_request_wait_completion) while the caller drives
``opal_progress()`` (req_wait.c:35,225 default_wait/wait_all). Same model
here: ``Wait`` spins the progress engine until the completion flag flips;
transports flip it from the progress callback (or a progress thread).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, List, Optional, Sequence, Tuple

import os

from ompi_tpu.core.errors import MPIError, ERR_REQUEST, ERR_PENDING
from ompi_tpu.core.status import Status

# Wait-loop policy: on a multicore host blocking waits spin hot (the
# reference busy-polls in ompi_request_wait_completion); on a single core
# spinning just burns the peer's timeslice, so yield immediately. Use the
# AFFINITY mask, not cpu_count: a rank pinned to one core of a big host
# is effectively single-core.
try:
    _MULTICORE = len(os.sched_getaffinity(0)) > 1
except AttributeError:  # non-Linux
    _MULTICORE = (os.cpu_count() or 1) > 1


class IdleBackoff:
    """The ONE wait-loop yield discipline every blocking wait shares
    (Request.Wait, Waitany, progress_until): busy-poll while events flow,
    yield the GIL once briefly idle, back off to millisecond waits under
    sustained idleness. A pure spin starves the peer rank on one-core
    hosts (reference: ompi_request_wait_completion's busy-poll, tempered
    by opal's yield_when_idle)."""

    __slots__ = ("_idle_since",)

    def __init__(self):
        self._idle_since = None

    def step(self, made_progress: bool, idle_wait=None) -> None:
        """Call once per loop iteration after no-completion was observed;
        ``idle_wait`` (seconds -> None) replaces the deep-idle sleep with
        a condition-variable wait where one is available."""
        if made_progress:
            self._idle_since = None
            return
        now = time.monotonic()
        if self._idle_since is None:
            self._idle_since = now
        idle = now - self._idle_since
        if idle >= 0.002:
            (idle_wait or time.sleep)(0.001)
        elif _MULTICORE and idle < 0.0003:
            pass  # pure spin: yields cost ~100us under load
        else:
            time.sleep(0)  # single core: hand the CPU to the peer


class Request:
    """A pending communication. Subclasses (pml send/recv, coll, grequest)
    arrange for ``_set_complete`` to be called."""

    def __init__(self):
        self.status = Status()
        self._complete = threading.Event()
        self._error: int = 0
        self._on_complete: List[Callable[["Request"], None]] = []
        self._cb_lock = threading.Lock()
        self.persistent = False

    # ------------------------------------------------------------ completion
    def _set_complete(self, error: int = 0) -> None:
        self._error = error
        self.status.error = error
        # Flip the flag and snapshot callbacks under the registration lock:
        # a registration racing on another thread either lands in the
        # snapshot or observes the flag and self-fires — never lost
        # (reference: the sync-object CAS of request.h:451).
        with self._cb_lock:
            self._complete.set()
            cbs = list(self._on_complete)
            self._on_complete.clear()
        for cb in cbs:
            cb(self)
        _completion_cond_notify()

    def add_completion_callback(self, cb: Callable[["Request"], None]) -> None:
        with self._cb_lock:
            if not self._complete.is_set():
                self._on_complete.append(cb)
                return
        cb(self)

    @property
    def is_complete(self) -> bool:
        return self._complete.is_set()

    # ------------------------------------------------------------- MPI verbs
    def Test(self, status: Optional[Status] = None) -> bool:
        _progress_once()
        if self._complete.is_set():
            self._finish(status)
            return True
        return False

    def Wait(self, status: Optional[Status] = None, timeout: Optional[float] = None) -> None:
        """Block until complete, driving progress (reference: request.h:451
        hot loop over opal_progress)."""
        deadline = None if timeout is None else time.monotonic() + timeout
        backoff = IdleBackoff()
        while not self._complete.is_set():
            made_progress = _progress_once()
            if self._complete.is_set():
                break
            if deadline is not None and time.monotonic() > deadline:
                raise MPIError(ERR_PENDING, "Wait timed out")
            backoff.step(made_progress, _completion_cond_wait)
        self._finish(status)

    def _finish(self, status: Optional[Status]) -> None:
        if status is not None:
            status.source = self.status.source
            status.tag = self.status.tag
            status.error = self.status.error
            status._nbytes = self.status._nbytes
            status.cancelled = self.status.cancelled
        if self._error:
            raise MPIError(self._error)

    def Cancel(self) -> None:
        """Best-effort cancel (reference: requests may decline)."""
        pass

    def Free(self) -> None:
        pass

    # ----------------------------------------------------------- multi-wait
    @staticmethod
    def Waitall(requests: Sequence["Request"],
                statuses: Optional[List[Status]] = None) -> None:
        for i, r in enumerate(requests):
            st = statuses[i] if statuses is not None else None
            r.Wait(st)

    @staticmethod
    def Waitany(requests: Sequence["Request"],
                status: Optional[Status] = None) -> int:
        if not requests:
            return -1
        backoff = IdleBackoff()
        while True:
            for i, r in enumerate(requests):
                if r.is_complete:
                    r._finish(status)
                    return i
            backoff.step(_progress_once(), _completion_cond_wait)

    @staticmethod
    def Waitsome(requests: Sequence["Request"]) -> List[int]:
        first = Request.Waitany(requests)
        if first < 0:
            return []
        done = [i for i, r in enumerate(requests) if r.is_complete]
        for i in done:
            requests[i]._finish(None)
        return done

    @staticmethod
    def Startall(requests: Sequence["Request"]) -> None:
        """Start every persistent request (MPI_Startall); lives on the
        base class so mixed persistent-request kinds share one entry."""
        for r in requests:
            r.Start()

    @staticmethod
    def Testall(requests: Sequence["Request"]) -> bool:
        _progress_once()
        return all(r.is_complete for r in requests)

    @staticmethod
    def Testany(requests: Sequence["Request"]) -> Tuple[int, bool]:
        _progress_once()
        for i, r in enumerate(requests):
            if r.is_complete:
                r._finish(None)
                return i, True
        return -1, False


class CompletedRequest(Request):
    """Immediately-complete request (SPMD-mode collectives return these once
    dispatch has been enqueued to XLA; buffer-ownership rules are satisfied
    by jax's functional semantics)."""

    def __init__(self, nbytes: int = 0, source: int = -1, tag: int = -1):
        super().__init__()
        self.status.source = source
        self.status.tag = tag
        self.status._nbytes = nbytes
        self._set_complete(0)


class Grequest(Request):
    """Generalized request (reference: ompi/request/grequest.c)."""

    def __init__(self, query_fn=None, free_fn=None, cancel_fn=None):
        super().__init__()
        self._query_fn = query_fn
        self._free_fn = free_fn
        self._cancel_fn = cancel_fn

    def Complete(self) -> None:
        if self._query_fn is not None:
            self._query_fn(self.status)
        self._set_complete(0)

    def Cancel(self) -> None:
        if self._cancel_fn is not None:
            self._cancel_fn(self._complete.is_set())
            self.status.cancelled = True

    def Free(self) -> None:
        if self._free_fn is not None:
            self._free_fn()


class Prequest(Request):
    """Persistent request (MPI_Send_init / MPI_Recv_init; reference:
    part/persist builds partitioned comm on these)."""

    def __init__(self, start_fn: Callable[["Prequest"], None]):
        super().__init__()
        self.persistent = True
        self._start_fn = start_fn
        self._complete.set()  # inactive == complete per MPI semantics

    def Start(self) -> "Prequest":
        self._complete.clear()
        self.status = Status()
        self._start_fn(self)
        return self


# ---------------------------------------------------------------- progress
# Wired to the runtime progress engine lazily so core stays import-light.
_progress_fn: Optional[Callable[[], int]] = None
_completion_cond = threading.Condition()


def _bind_progress(fn: Callable[[], int]) -> None:
    global _progress_fn
    _progress_fn = fn


def _progress_once() -> int:
    if _progress_fn is None:
        return 0
    return _progress_fn()


def _completion_cond_notify() -> None:
    with _completion_cond:
        _completion_cond.notify_all()


def _completion_cond_wait(timeout: float) -> None:
    with _completion_cond:
        _completion_cond.wait(timeout)
