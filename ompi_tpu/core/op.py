"""MPI reduction operations.

Reference: ompi/op (1,204 LoC dispatch) + the SIMD kernel components
ompi/mca/op/{avx,aarch64,riscv64} (op_avx_functions.c:31-39). The TPU-native
re-design: every op carries

- ``np_reduce(a, b)``  — elementwise numpy kernel for the host/DCN path
  (numpy ufuncs are the host-SIMD analog of op/avx), and
- ``jax_kind``         — how coll/xla lowers it on device:
  'psum' / 'pmax' / 'pmin' lower straight to XLA AllReduce computations;
  'gather' ops (prod, logical/bitwise, loc-pairs, user fns) lower to
  all_gather + an on-device tree reduction, which XLA fuses — still one
  collective on the wire.
"""

from __future__ import annotations

from typing import Callable, Optional

import numpy as np

from ompi_tpu.core.errors import MPIError, ERR_OP


_op_counter = [0]


class Op:
    def __init__(
        self,
        name: str,
        np_reduce: Callable,
        jax_kind: str = "gather",
        jax_reduce: Optional[Callable] = None,
        commutative: bool = True,
        logical: bool = False,
    ):
        self.name = name
        self.np_reduce = np_reduce
        self.jax_kind = jax_kind  # 'psum' | 'pmax' | 'pmin' | 'gather'
        self._jax_reduce = jax_reduce
        self.commutative = commutative
        # logical ops normalize operands to {0,1} before lowering (MPI_LAND
        # on ints is truthiness, not numeric min/max)
        self.logical = logical
        # unique id: compiled-executable caches key on this, so two distinct
        # user ops never share an executable even with the same name
        _op_counter[0] += 1
        self.uid = _op_counter[0]
        # precomputed for the mesh verbs' hot path: one attribute load
        # instead of a name-in-tuple scan per call
        self.is_pair = name in PAIR_OPS

    def jax_reduce(self, a, b):
        """Elementwise combine traceable by XLA (used by the gather path and
        by ring/segmented schedules)."""
        if self._jax_reduce is not None:
            return self._jax_reduce(a, b)
        if not _JNP_EQUIV:  # late import: core must not require jax
            _register_jnp_equivs()
        fn = _JNP_EQUIV.get(self.name)
        if fn is None:
            raise MPIError(ERR_OP, f"op {self.name} has no device kernel")
        return fn(a, b)

    @staticmethod
    def Create(fn: Callable, commute: bool = True, name: str = "user") -> "Op":
        """User-defined op (MPI_Op_create). `fn(a, b)` must be elementwise;
        if it is jax-traceable it also runs on device via the gather path."""
        return Op(name, fn, jax_kind="gather", jax_reduce=fn,
                  commutative=commute)

    def __repr__(self) -> str:
        return f"Op({self.name})"


def _minloc(a, b):
    """Elementwise on structured (value, index) pairs; ties take the lower
    index, per MPI_MINLOC."""
    take_b = (b["f0"] < a["f0"]) | ((b["f0"] == a["f0"]) & (b["f1"] < a["f1"]))
    out = np.array(a, copy=True)
    out[take_b] = b[take_b]
    return out


def _maxloc(a, b):
    take_b = (b["f0"] > a["f0"]) | ((b["f0"] == a["f0"]) & (b["f1"] < a["f1"]))
    out = np.array(a, copy=True)
    out[take_b] = b[take_b]
    return out


def _minloc_jax(a, b):
    """Device MINLOC: operands are pair arrays with a trailing dim of 2
    holding (value, index) — the XLA-representable layout replacing the
    host path's structured dtype (reference: the MPI pair types
    ompi_datatype FLOAT_INT etc., reduced by op/avx's 2-wide kernels)."""
    import jax.numpy as jnp

    av, ai = a[..., 0], a[..., 1]
    bv, bi = b[..., 0], b[..., 1]
    take_a = (av < bv) | ((av == bv) & (ai <= bi))
    return jnp.stack([jnp.where(take_a, av, bv),
                      jnp.where(take_a, ai, bi)], axis=-1)


def _maxloc_jax(a, b):
    import jax.numpy as jnp

    av, ai = a[..., 0], a[..., 1]
    bv, bi = b[..., 0], b[..., 1]
    take_a = (av > bv) | ((av == bv) & (ai <= bi))
    return jnp.stack([jnp.where(take_a, av, bv),
                      jnp.where(take_a, ai, bi)], axis=-1)


_JNP_EQUIV = {}

# ops whose device operands are (value, index) pair arrays ([..., 2])
PAIR_OPS = ("MPI_MINLOC", "MPI_MAXLOC")


def _register_jnp_equivs():
    import jax.numpy as jnp

    _JNP_EQUIV.update({
        "MPI_MINLOC": _minloc_jax,
        "MPI_MAXLOC": _maxloc_jax,
        "MPI_SUM": jnp.add,
        "MPI_PROD": jnp.multiply,
        "MPI_MAX": jnp.maximum,
        "MPI_MIN": jnp.minimum,
        "MPI_LAND": jnp.logical_and,
        "MPI_LOR": jnp.logical_or,
        "MPI_LXOR": jnp.logical_xor,
        "MPI_BAND": jnp.bitwise_and,
        "MPI_BOR": jnp.bitwise_or,
        "MPI_BXOR": jnp.bitwise_xor,
        "MPI_REPLACE": lambda a, b: b,
        "MPI_NO_OP": lambda a, b: a,
    })


SUM = Op("MPI_SUM", np.add, jax_kind="psum")
PROD = Op("MPI_PROD", np.multiply, jax_kind="gather")
MAX = Op("MPI_MAX", np.maximum, jax_kind="pmax")
MIN = Op("MPI_MIN", np.minimum, jax_kind="pmin")
LAND = Op("MPI_LAND", np.logical_and, jax_kind="pmin", logical=True)
LOR = Op("MPI_LOR", np.logical_or, jax_kind="pmax", logical=True)
LXOR = Op("MPI_LXOR", np.logical_xor, jax_kind="gather", logical=True)
BAND = Op("MPI_BAND", np.bitwise_and, jax_kind="gather")
BOR = Op("MPI_BOR", np.bitwise_or, jax_kind="gather")
BXOR = Op("MPI_BXOR", np.bitwise_xor, jax_kind="gather")
MINLOC = Op("MPI_MINLOC", _minloc, jax_kind="gather")
MAXLOC = Op("MPI_MAXLOC", _maxloc, jax_kind="gather")
REPLACE = Op("MPI_REPLACE", lambda a, b: b, jax_kind="gather",
             commutative=False)
NO_OP = Op("MPI_NO_OP", lambda a, b: a, jax_kind="gather", commutative=False)
