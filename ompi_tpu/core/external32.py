"""MPI_Pack/Unpack and the external32 canonical data representation.

Reference: ompi/datatype/ompi_datatype_external32.c (the canonical
big-endian representation every MPI must provide for file/message
portability) and opal/datatype/opal_copy_functions_heterogeneous.c (the
pack/unpack kernels that byteswap per predefined type, not per byte
run — a complex number swaps each component, not the whole 16 bytes).

Built on the byte-map engine of core/convertor.py: native Pack/Unpack
reuse it directly; the external32 variants walk the typemap ENTRIES in
DECLARATION order — the canonical stream follows the typemap as
declared (MPI external32 contract), which for a struct with
out-of-order displacements differs from the byte-map's
displacement-sorted internal wire format — so each field is gathered,
endian-converted as a unit, and placed at its canonical offset. Our predefined types all have external32 sizes equal
to their native sizes (IEEE floats, two's-complement ints), so
conversion is pure byte reordering — the fixed-size table of
ompi_datatype_external32.c collapses to the typemap itemsizes.
"""

from __future__ import annotations

import numpy as np

from ompi_tpu.core.convertor import (
    _as_byte_view,
    pack as _native_pack,
    unpack as _native_unpack,
)
from ompi_tpu.core.datatype import Datatype
from ompi_tpu.core.errors import (
    MPIError,
    ERR_ARG,
    ERR_BUFFER,
    ERR_TRUNCATE,
)

_LITTLE = np.little_endian


def _check_rep(datarep: str) -> None:
    if datarep != "external32":
        raise MPIError(ERR_ARG,
                       f"unsupported data representation {datarep!r} "
                       "(only 'external32')")


def _entries(dt: Datatype):
    """(packed_offset, disp, np.dtype) per typemap entry, in typemap
    DECLARATION order — external32 streams fields as declared, so other
    MPI implementations decode them identically."""
    out = []
    pos = 0
    for d, disp in dt.typemap:
        out.append((pos, disp, d))
        pos += d.itemsize
    return out


def _check_data_extent(view: np.ndarray, count: int, dt: Datatype,
                       what: str) -> None:
    """The data buffer must span count elements of the datatype's
    extent (same rule as convertor.pack) — undersized buffers raise
    MPIError, not a raw numpy IndexError."""
    need = (count - 1) * dt.extent + dt.true_lb + dt.true_extent
    if count and view.nbytes < need:
        raise MPIError(ERR_BUFFER,
                       f"{what} too small: {view.nbytes} < {need}")


def _swap_fields(block: np.ndarray, d: np.dtype) -> np.ndarray:
    """Reverse each field's bytes (little <-> big endian). block is
    [count, itemsize] uint8. Complex types swap each real/imag
    component separately (the heterogeneous-kernel rule)."""
    if d.itemsize == 1 or not _LITTLE:
        return block
    n = block.shape[0]
    if d.kind == "c":
        half = d.itemsize // 2
        return block.reshape(n, 2, half)[:, :, ::-1].reshape(
            n, d.itemsize)
    return block[:, ::-1]


def pack_external_size(datarep: str, count: int, datatype: Datatype) -> int:
    """MPI_Pack_external_size: bytes `count` elements occupy in the
    canonical representation."""
    _check_rep(datarep)
    return count * datatype.size


def pack_external(datarep: str, inbuf, count: int, datatype: Datatype,
                  outbuf, position: int = 0) -> int:
    """MPI_Pack_external: append `count` elements in canonical
    big-endian form to `outbuf` at `position`; returns the new
    position."""
    _check_rep(datarep)
    src = _as_byte_view(inbuf)
    dst = _as_byte_view(outbuf)
    _check_data_extent(src, count, datatype, "inbuf")
    total = count * datatype.size
    if position + total > dst.nbytes:
        raise MPIError(ERR_BUFFER,
                       f"outbuf too small: {dst.nbytes} < "
                       f"{position + total}")
    if count == 0:
        return position
    entries = _entries(datatype)
    if len(entries) == 1 and datatype.is_contiguous:
        # contiguous single-field fast path: one strided byte reversal,
        # no index matrices (they cost 8-16x the payload in temporaries)
        _, _, d = entries[0]
        block = _swap_fields(
            src[: total].reshape(count, d.itemsize), d)
        dst[position: position + total] = block.reshape(-1)
        return position + total
    elem = np.arange(count, dtype=np.int64)
    for pos, disp, d in entries:
        isz = d.itemsize
        gather = (elem[:, None] * datatype.extent + disp
                  + np.arange(isz, dtype=np.int64)[None, :])
        block = _swap_fields(src[gather.reshape(-1)].reshape(count, isz),
                             d)
        place = (position + elem[:, None] * datatype.size + pos
                 + np.arange(isz, dtype=np.int64)[None, :])
        dst[place.reshape(-1)] = block.reshape(-1)
    return position + total


def unpack_external(datarep: str, inbuf, position: int, outbuf,
                    count: int, datatype: Datatype) -> int:
    """MPI_Unpack_external: read `count` canonical elements from
    `inbuf` at `position` into `outbuf`; returns the new position."""
    _check_rep(datarep)
    src = _as_byte_view(inbuf)
    dst = _as_byte_view(outbuf)
    _check_data_extent(dst, count, datatype, "outbuf")
    total = count * datatype.size
    if position + total > src.nbytes:
        raise MPIError(ERR_TRUNCATE,
                       f"packed stream {src.nbytes} < expected "
                       f"{position + total}")
    if count == 0:
        return position
    entries = _entries(datatype)
    if len(entries) == 1 and datatype.is_contiguous:
        _, _, d = entries[0]
        block = _swap_fields(
            src[position: position + total].reshape(count, d.itemsize), d)
        dst[: total] = block.reshape(-1)
        return position + total
    elem = np.arange(count, dtype=np.int64)
    for pos, disp, d in entries:
        isz = d.itemsize
        take = (position + elem[:, None] * datatype.size + pos
                + np.arange(isz, dtype=np.int64)[None, :])
        block = _swap_fields(src[take.reshape(-1)].reshape(count, isz), d)
        place = (elem[:, None] * datatype.extent + disp
                 + np.arange(isz, dtype=np.int64)[None, :])
        dst[place.reshape(-1)] = block.reshape(-1)
    return position + total


# ------------------------------------------------- native Pack / Unpack
def pack_size(count: int, datatype: Datatype) -> int:
    """MPI_Pack_size (native representation: exact, no slack needed)."""
    return count * datatype.size


def mpi_pack(inbuf, count: int, datatype: Datatype, outbuf,
             position: int = 0) -> int:
    """MPI_Pack: append `count` native-representation elements."""
    dst = _as_byte_view(outbuf)
    data = _native_pack(inbuf, count, datatype)
    if position + data.nbytes > dst.nbytes:
        raise MPIError(ERR_BUFFER,
                       f"outbuf too small: {dst.nbytes} < "
                       f"{position + data.nbytes}")
    dst[position: position + data.nbytes] = data
    return position + data.nbytes


def mpi_unpack(inbuf, position: int, outbuf, count: int,
               datatype: Datatype) -> int:
    """MPI_Unpack: scatter `count` native elements from `inbuf`."""
    src = _as_byte_view(inbuf)
    total = count * datatype.size
    if position + total > src.nbytes:
        raise MPIError(ERR_TRUNCATE,
                       f"packed stream {src.nbytes} < expected "
                       f"{position + total}")
    _native_unpack(src[position: position + total], outbuf, count,
                   datatype)
    return position + total
