"""MPI error codes, exception type, and error handlers.

Reference: ompi/errhandler (3,163 LoC) + the MPI_ERR_* constants of
ompi/include/mpi.h.in. Error *classes* are stable integers; the Python-native
surface raises ``MPIError`` carrying the class, while the errhandler objects
reproduce MPI_ERRORS_ARE_FATAL / MPI_ERRORS_RETURN semantics for code that
wants C-style return handling.
"""

from __future__ import annotations

from typing import Callable, Optional

SUCCESS = 0
ERR_BUFFER = 1
ERR_COUNT = 2
ERR_TYPE = 3
ERR_TAG = 4
ERR_COMM = 5
ERR_RANK = 6
ERR_REQUEST = 7
ERR_ROOT = 8
ERR_GROUP = 9
ERR_OP = 10
ERR_TOPOLOGY = 11
ERR_DIMS = 12
ERR_ARG = 13
ERR_UNKNOWN = 14
ERR_TRUNCATE = 15
ERR_OTHER = 16
ERR_INTERN = 17
ERR_IN_STATUS = 18
ERR_PENDING = 19
ERR_ACCESS = 20
ERR_AMODE = 21
ERR_BAD_FILE = 23
ERR_FILE = 27
ERR_FILE_EXISTS = 25
ERR_FILE_IN_USE = 26
ERR_IO = 32
ERR_NO_SPACE = 36
ERR_NO_SUCH_FILE = 37
ERR_READ_ONLY = 40
ERR_WIN = 45
ERR_KEYVAL = 48
ERR_INFO = 50
ERR_NO_MEM = 51
ERR_BASE = 52
ERR_PORT = 55
ERR_SERVICE = 56
ERR_NAME = 57
ERR_SPAWN = 61
ERR_UNSUPPORTED_DATAREP = 62
ERR_UNSUPPORTED_OPERATION = 63
ERR_SESSION = 72
# ULFM fault-tolerance error classes (reference: ompi/mpiext/ftmpi — the
# MPIX_ERR_* codes guarded by OPAL_ENABLE_FT_MPI)
ERR_PROC_FAILED = 75
ERR_PROC_FAILED_PENDING = 76
ERR_REVOKED = 77
# implementation-specific class: the runtime sanitizer detected an MPI
# semantics violation (deadlock cycle, signature mismatch) at level >= 2
ERR_SANITIZER = 78

_ERROR_STRINGS = {
    SUCCESS: "MPI_SUCCESS: no error",
    ERR_BUFFER: "MPI_ERR_BUFFER: invalid buffer pointer",
    ERR_COUNT: "MPI_ERR_COUNT: invalid count argument",
    ERR_TYPE: "MPI_ERR_TYPE: invalid datatype argument",
    ERR_TAG: "MPI_ERR_TAG: invalid tag argument",
    ERR_COMM: "MPI_ERR_COMM: invalid communicator",
    ERR_RANK: "MPI_ERR_RANK: invalid rank",
    ERR_REQUEST: "MPI_ERR_REQUEST: invalid request",
    ERR_ROOT: "MPI_ERR_ROOT: invalid root",
    ERR_GROUP: "MPI_ERR_GROUP: invalid group",
    ERR_OP: "MPI_ERR_OP: invalid reduce operation",
    ERR_TOPOLOGY: "MPI_ERR_TOPOLOGY: invalid communicator topology",
    ERR_DIMS: "MPI_ERR_DIMS: invalid dimension argument",
    ERR_ARG: "MPI_ERR_ARG: invalid argument",
    ERR_UNKNOWN: "MPI_ERR_UNKNOWN: unknown error",
    ERR_TRUNCATE: "MPI_ERR_TRUNCATE: message truncated",
    ERR_OTHER: "MPI_ERR_OTHER: known error not in list",
    ERR_INTERN: "MPI_ERR_INTERN: internal error",
    ERR_IN_STATUS: "MPI_ERR_IN_STATUS: error code in status",
    ERR_PENDING: "MPI_ERR_PENDING: pending request",
    ERR_WIN: "MPI_ERR_WIN: invalid window",
    ERR_SESSION: "MPI_ERR_SESSION: invalid session",
    ERR_PROC_FAILED: "MPIX_ERR_PROC_FAILED: process failure",
    ERR_REVOKED: "MPIX_ERR_REVOKED: communicator revoked",
    ERR_UNSUPPORTED_OPERATION: "MPI_ERR_UNSUPPORTED_OPERATION",
    ERR_SANITIZER: "MPIX_ERR_SANITIZER: MPI semantics violation "
                   "detected by the runtime sanitizer",
}


def Error_string(code: int) -> str:
    return _ERROR_STRINGS.get(code, f"MPI error class {code}")


class MPIError(Exception):
    def __init__(self, code: int, detail: str = ""):
        self.code = code
        msg = Error_string(code)
        if detail:
            msg = f"{msg} ({detail})"
        super().__init__(msg)


class Errhandler:
    """MPI errhandler object (reference: ompi/errhandler/errhandler.h).

    ``fn(comm_like, code, detail)`` decides how an error surfaces.
    """

    def __init__(self, fn: Callable, name: str = "user"):
        self.fn = fn
        self.name = name

    def invoke(self, obj, code: int, detail: str = "") -> int:
        return self.fn(obj, code, detail)


def _fatal(obj, code: int, detail: str = "") -> int:
    raise MPIError(code, detail)


def _ret(obj, code: int, detail: str = "") -> int:
    return code


ERRORS_ARE_FATAL = Errhandler(_fatal, "MPI_ERRORS_ARE_FATAL")
ERRORS_RETURN = Errhandler(_ret, "MPI_ERRORS_RETURN")
