"""Deterministic, seedable fault injection (the chaos harness).

Reference: the reference validates ompi/communicator/ft with dedicated
failure-propagator tests and the ftagree mpiext fault hooks; MTT-style
soak rigs additionally use wire-level drop/delay shims. Here the same
discipline is a first-class framework: a *chaos plan* parsed from the
``ft_inject_plan`` cvar (which rides the normal MCA env channel, so
``mpirun --mca ft_inject_plan ...`` reaches every procmode child)
drives two choke points:

- a **btl wire hook** — ``wire_send`` consulted by ``btl/tcp.py`` before
  a frame is queued (drop / delay / dup / sever on the DCN path), and a
  ``wrap_deliver`` receive-side filter installed by ``btl/base.py`` on
  every transport for rules marked ``side=recv``;
- a **pml op-counter hook** — ``on_op`` in ``pml/ob1``'s isend/irecv,
  counting MATCH-plane operations so ``kill(rank, after=N)`` terminates
  the victim at a deterministic point mid-protocol.

Plan grammar (``;``-separated actions; ranks are universe ranks, ``*``
is a wildcard)::

    kill(rank, after=N)            die (exit 0) after N pml ops
    preempt(rank, after=N,         preemption notice: run the registered
            grace_ms=M)            preemption hooks (ft/diskless's final
                                   flush) with an M-ms grace window,
                                   then die — the TPU preemption model
    drop(src, dst, frac=F)         drop outbound frames with prob. F
    drop(src, dst, nth=N)          drop every Nth frame
    delay(src, dst, ms=M)          sleep M ms before queuing a frame
    sever(src, dst, after=N)       break the link on the Nth matching
                                   frame (default the first): conn
                                   marked dead, peer marked failed (the
                                   pml's request-failing sweep on that
                                   mark arms only with ft_enable)
    dup(src, dst, nth=N)           queue every Nth frame twice
    corrupt(src, dst, nth=N|frac=F)  flip bits in the wire payload of
                                   matching frames (reliable links CRC-
                                   NACK and retransmit; legacy links
                                   see the historical desync/_conn_failed)
    sever_transient(src, dst,      break the link on the Nth matching
            after=N, down_ms=M)    frame, then hold it DOWN for M ms —
                                   redials fail (link_down) until the
                                   window closes; drives the degraded->
                                   reconnect-and-replay path
    blackhole(src, dst, ms=M)      from the first matching frame, drop
                                   ALL matching frames for M ms (a
                                   silent wire stall: no reset, no EOF
                                   — heals via retransmit timeout)

Wire rules take an optional ``side=recv`` to apply at the receiver's
deliver funnel instead of the sender's tcp enqueue. ``frac`` draws from
a ``ft_inject_seed``-keyed PRNG (stable per rule across runs and ranks).

Hot-guard discipline: the disabled path is ONE live attribute load —
``_enable_var._value`` — the same slot shape as spc/trace/sanitizer
gates (mpilint enforces this for injection calls in hot modules).
Every injected fault counts into the ``ft_injected_faults`` pvar, an
``spc`` counter per action, and (when tracing) a trace instant.
"""

from __future__ import annotations

import random
import re
import time
import zlib
from typing import Dict, List, Optional

from ompi_tpu.mca.var import register_var, register_pvar
from ompi_tpu.runtime import trace as _trace
from ompi_tpu.utils.output import get_logger
from ompi_tpu.utils.show_help import register_topic, show_help

register_topic(
    "ft", "bad-inject-plan",
    "The ft_inject_plan cvar could not be parsed:\n  {error}\n"
    "Grammar: ';'-separated kill(rank,after=N) / preempt(rank,after=N,"
    "grace_ms=M) / drop(src,dst,frac=F|nth=N) / delay(src,dst,ms=M) / "
    "sever(src,dst,after=N) / dup(src,dst,nth=N) / "
    "corrupt(src,dst,nth=N|frac=F) / "
    "sever_transient(src,dst,after=N,down_ms=M) / "
    "blackhole(src,dst,ms=M), optional side=recv on drop/delay/dup "
    "wire rules ('*' = any rank).\n"
    "Fix the plan or unset the cvar; injection refuses to start with "
    "a plan it cannot honor.")

_plan_var = register_var(
    "ft", "inject_plan", "", typ=str,
    help="Chaos plan: ';'-separated kill(rank,after=N) / "
         "preempt(rank,after=N,grace_ms=M) / "
         "drop(src,dst,frac=F|nth=N) / delay(src,dst,ms=M) / "
         "sever(src,dst,after=N) / dup(src,dst,nth=N) / "
         "corrupt(src,dst,nth=N|frac=F) / "
         "sever_transient(src,dst,after=N,down_ms=M) / "
         "blackhole(src,dst,ms=M) actions applied at the "
         "btl wire and pml op-counter hooks (empty = injection off; "
         "drop/delay/dup take side=recv to apply at the receiver)",
    level=9)
_seed_var = register_var(
    "ft", "inject_seed", 0,
    help="Seed for probabilistic (frac=) injection decisions — the "
         "same plan+seed replays the same fault schedule", level=9)

log = get_logger("ft.inject")

# wire_send verdict bits
DROP = 1
DUP = 2
SEVER = 4
CORRUPT = 8
# rides SEVER for sever_transient: the outage is RECOVERABLE — a
# reliability-engaged btl degrades-and-redials instead of killing the
# link outright (plain sever keeps its permanent instant-death verdict
# on every datapath, reliable or not)
TRANSIENT = 16

_WIRE_ACTIONS = ("drop", "delay", "sever", "dup", "corrupt",
                 "sever_transient", "blackhole")
# send-only wire actions: they act on the sender's connection/wire
# bytes, which a receive-side deliver filter cannot reach
_SEND_ONLY = ("sever", "sever_transient", "corrupt", "blackhole")
_DIE_ACTIONS = ("kill", "preempt")  # victim-terminating op-counter rules


class _LiveFlag:
    """One-slot live gate: hot call sites load ``_enable_var._value``
    exactly like the spc/trace/sanitizer guards (a registered bool cvar
    would be wrong here — enablement is derived from the parsed plan,
    not a user knob of its own)."""

    __slots__ = ("_value",)

    def __init__(self):
        self._value = False


_enable_var = _LiveFlag()


class _Rule:
    __slots__ = ("action", "src", "dst", "frac", "nth", "ms", "after",
                 "side", "count", "rng", "fired_edges", "until")

    def __init__(self, action: str, src: Optional[int], dst: Optional[int],
                 frac: Optional[float], nth: Optional[int],
                 ms: float, after: int, side: str, seed: int):
        self.action = action
        self.src = src        # None = wildcard ('*'); kill: the victim
        self.dst = dst
        self.frac = frac
        self.nth = nth
        self.ms = ms
        self.after = after
        self.side = side
        self.count = 0
        self.fired_edges = set()  # sever one-shot latch, per (src,dst)
        self.until: Optional[float] = None  # blackhole window end (mono)
        # stable per-rule stream: identical across ranks and runs for a
        # given (plan position irrelevant) rule shape + seed
        key = zlib.crc32(f"{action}:{src}:{dst}:{frac}:{nth}".encode())
        self.rng = random.Random(seed ^ key)

    def __repr__(self) -> str:  # plan echo in logs/errors
        extra = []
        if self.frac is not None:
            extra.append(f"frac={self.frac}")
        if self.nth is not None:
            extra.append(f"nth={self.nth}")
        if self.action in ("delay", "blackhole"):
            extra.append(f"ms={self.ms:g}")
        if self.action == "sever_transient":
            extra.append(f"after={self.after}")
            extra.append(f"down_ms={self.ms:g}")
        if self.action == "kill":
            return f"kill({self.src},after={self.after})"
        if self.action == "preempt":
            return (f"preempt({self.src},after={self.after},"
                    f"grace_ms={self.ms:g})")
        if self.side == "recv":
            extra.append("side=recv")
        args = ",".join([str("*" if self.src is None else self.src),
                         str("*" if self.dst is None else self.dst)]
                        + extra)
        return f"{self.action}({args})"


_kill_rules: List[_Rule] = []
_send_rules: List[_Rule] = []
_recv_rules: List[_Rule] = []
_my_rank: Optional[int] = None
_faults: Dict[str, int] = {}
# sever_transient down-windows: unordered edge -> monotonic end time.
# Consulted by the tcp redial loop (link_down) so BOTH sides see the
# outage for the full window — the severed conn plus every reconnect
# attempt inside it — then heal together.
_down_until: Dict[tuple, float] = {}

register_pvar("ft", "injected_faults",
              lambda: sum(_faults.values()),
              help="Faults injected by the ft_inject_plan chaos harness "
                   "(all actions; per-action detail in the "
                   "spc_ft_inject_* counters)")


_ACTION_RE = re.compile(r"^\s*([a-z_]+)\s*\(([^)]*)\)\s*$")


def _parse_action(text: str, seed: int) -> _Rule:
    m = _ACTION_RE.match(text)
    if m is None:
        raise ValueError(f"ft_inject_plan: cannot parse action {text!r}")
    action, raw = m.group(1), m.group(2)
    if action not in _WIRE_ACTIONS and action not in _DIE_ACTIONS:
        raise ValueError(f"ft_inject_plan: unknown action {action!r}")
    pos: List[str] = []
    kv: Dict[str, str] = {}
    for part in raw.split(","):
        part = part.strip()
        if not part:
            continue
        if "=" in part:
            k, v = part.split("=", 1)
            kv[k.strip()] = v.strip()
        else:
            if kv:
                raise ValueError(
                    f"ft_inject_plan: positional arg after keyword "
                    f"in {text!r}")
            pos.append(part)

    def rank(s: str) -> Optional[int]:
        return None if s == "*" else int(s)

    if action in _DIE_ACTIONS:
        if len(pos) != 1 or pos[0] == "*":
            raise ValueError(
                f"ft_inject_plan: {action} needs {action}(rank, "
                f"after=N), got {text!r}")
        after = int(kv.pop("after", "0"))
        # preempt carries its grace window in the ms slot (the notice
        # hooks get grace_ms/1000 seconds to flush before death)
        grace = float(kv.pop("grace_ms", "500")) if action == "preempt" \
            else 0.0
        if kv:
            raise ValueError(
                f"ft_inject_plan: unknown {action}() args {sorted(kv)}")
        return _Rule(action, int(pos[0]), None, None, None, grace,
                     max(after, 1), "send", seed)

    if len(pos) != 2:
        raise ValueError(
            f"ft_inject_plan: {action} needs (src, dst), got {text!r}")
    src, dst = rank(pos[0]), rank(pos[1])
    side = kv.pop("side", "send")
    if side not in ("send", "recv"):
        raise ValueError(f"ft_inject_plan: side must be send|recv "
                         f"in {text!r}")
    frac = float(kv.pop("frac")) if "frac" in kv else None
    nth = int(kv.pop("nth")) if "nth" in kv else None
    ms = float(kv.pop("ms", "0"))
    after = 0
    if action == "sever":
        # optional Nth-frame gate (default: first matching frame), so a
        # permanent sever can be placed mid-stream instead of landing
        # on wireup traffic
        after = max(int(kv.pop("after", "1")), 1)
    if action == "sever_transient":
        # Nth matching frame triggers the sever; down_ms rides the ms
        # slot (the link stays DOWN — redials fail via link_down() —
        # until the window closes)
        after = max(int(kv.pop("after", "1")), 1)
        ms = float(kv.pop("down_ms", "500"))
        if ms <= 0:
            raise ValueError(
                f"ft_inject_plan: sever_transient needs down_ms>0 "
                f"in {text!r}")
    if kv:
        raise ValueError(
            f"ft_inject_plan: unknown {action}() args {sorted(kv)}")
    if action == "drop" and frac is None and nth is None:
        frac = 1.0  # drop(src,dst) = drop everything on the edge
    if action in ("dup", "corrupt") and frac is None and nth is None:
        nth = 1
    if action == "delay" and ms <= 0:
        raise ValueError(f"ft_inject_plan: delay needs ms=M in {text!r}")
    if action == "blackhole" and ms <= 0:
        raise ValueError(
            f"ft_inject_plan: blackhole needs ms=M in {text!r}")
    if action in _SEND_ONLY and side == "recv":
        raise ValueError(
            f"ft_inject_plan: {action} is send-side only (it acts on "
            f"the sender's connection/wire bytes)")
    return _Rule(action, src, dst, frac, nth, ms, after, side, seed)


def parse_plan(text: str, seed: int = 0) -> List[_Rule]:
    return [_parse_action(a, seed) for a in text.split(";") if a.strip()]


def install(plan: Optional[str] = None, seed: Optional[int] = None) -> None:
    """(Re)parse the plan and arm the hooks. Called at import with the
    cvar value; tests call it directly after set_var. Send-side and
    op-counter hooks are live-guarded at every call site; side=recv
    rules additionally need a deliver wrapper that btl/base installs at
    transport construction — they take effect immediately when SOME
    plan was already armed at that point (the rule list is live), but
    arming injection from scratch after transports exist reaches only
    the send/op hooks."""
    global _kill_rules, _send_rules, _recv_rules
    if plan is None:
        plan = str(_plan_var._value or "")
    if seed is None:
        seed = int(_seed_var._value or 0)
    rules = parse_plan(plan, seed)
    _down_until.clear()  # mpiracer: disable=cross-thread-race — stale outage windows must not survive a re-arm; install() runs before the hooks it arms, so no wire thread races the clear
    _kill_rules = [r for r in rules if r.action in _DIE_ACTIONS]
    _send_rules = [r for r in rules
                   if r.action not in _DIE_ACTIONS and r.side == "send"]
    _recv_rules = [r for r in rules if r.side == "recv"]
    _enable_var._value = bool(rules)
    if rules:
        log.warning("chaos plan armed: %s",
                    "; ".join(repr(r) for r in rules))


def uninstall() -> None:
    global _kill_rules, _send_rules, _recv_rules
    _kill_rules, _send_rules, _recv_rules = [], [], []
    _faults.clear()
    _down_until.clear()
    _enable_var._value = False


def link_down(a: int, b: int) -> bool:
    """True while a sever_transient down-window is open on the
    unordered edge (a, b) — the tcp redial loop consults this so
    reconnect attempts inside the outage fail like the real wire
    would, instead of instantly reconnecting over loopback."""
    if not _down_until:
        return False
    edge = (a, b) if a <= b else (b, a)
    t = _down_until.get(edge)
    if t is None:
        return False
    if time.monotonic() >= t:
        del _down_until[edge]
        return False
    return True


def note_rank(rank: int) -> None:
    """Identity for the receive-side filter (set by ob1 when a plan is
    armed — the pml knows the universe rank; btls are built after it)."""
    global _my_rank
    _my_rank = rank


def fault_counts() -> Dict[str, int]:
    return dict(_faults)


def has_recv_rules() -> bool:
    return bool(_recv_rules)


# Preemption-notice hooks: run on the doomed rank between the notice
# and death, with the rule's grace window (seconds) — the registration
# channel for ft/diskless.flush_final (the TPU preemption model where a
# doomed worker gets a short warning to flush state).
_preempt_hooks: List = []


def on_preempt(cb) -> None:
    """Register ``cb(grace_s: float)`` to run when a preempt() rule
    fires on this rank, before the process exits."""
    if cb not in _preempt_hooks:
        _preempt_hooks.append(cb)


def _fire(rule: _Rule, src, dst) -> None:
    from ompi_tpu.runtime import spc

    _faults[rule.action] = _faults.get(rule.action, 0) + 1
    spc.record(f"ft_inject_{rule.action}")
    if _trace.enabled():
        _trace.instant(f"ft.inject.{rule.action}", cat="ft",
                       src=src, dst=dst)


def _hits(rule: _Rule) -> bool:
    if rule.frac is not None:
        return rule.rng.random() < rule.frac
    if rule.nth is not None:
        return rule.count % rule.nth == 0
    return True


def _edge(rule: _Rule, src: int, dst: int) -> bool:
    return (rule.src is None or rule.src == src) and \
           (rule.dst is None or rule.dst == dst)


# ------------------------------------------------------------------ hooks
def on_op(rank: int, tag: int) -> None:
    """pml op counter (call sites guard on ``_enable_var._value``).
    System-plane traffic (heartbeats, era, revoke floods — tag <=
    SYSTEM_TAG_BASE) is excluded so op counts stay deterministic under
    background detector chatter."""
    from ompi_tpu.pml.base import SYSTEM_TAG_BASE

    if tag <= SYSTEM_TAG_BASE:
        return
    for rule in _kill_rules:
        if rule.src != rank:
            continue
        rule.count += 1
        if rule.count >= rule.after:
            import os

            _fire(rule, rank, None)
            if rule.action == "preempt":
                # latch BEFORE the hooks: a flush that sends user-tag
                # traffic would re-enter this counter and recurse
                fired = rule.count
                rule.after = 1 << 62
                log.warning("chaos preempt: rank %d notified after %d "
                            "pml ops (grace %.0fms)", rank, fired,
                            rule.ms)
                for cb in list(_preempt_hooks):
                    try:
                        cb(rule.ms / 1000.0)
                    except Exception:
                        log.warning("preempt hook failed",
                                    exc_info=True)
            else:
                log.warning("chaos kill: rank %d dying after %d pml "
                            "ops", rank, rule.count)
            # exit 0: the launcher treats nonzero as a job abort and
            # would tear down the survivors this plan exists to test
            os._exit(0)


def wire_send(my_rank: int, peer: int) -> int:
    """Send-side wire verdict for one frame: OR of DROP/DUP/SEVER bits;
    delay sleeps inline. Call sites guard on ``_enable_var._value``."""
    flags = 0
    for rule in _send_rules:
        if not _edge(rule, my_rank, peer):
            continue
        rule.count += 1
        if rule.action == "sever":
            # one-shot PER EDGE (a wildcard rule severs every matching
            # link once): the Nth matching frame (after=, default the
            # first) kills that connection; after that the dead-conn
            # check raises on its own, and re-firing would inflate
            # ft_injected_faults (one severed link = one fault) and
            # re-run the btl's failure path per frame
            if (my_rank, peer) not in rule.fired_edges and \
                    rule.count >= rule.after:
                rule.fired_edges.add((my_rank, peer))
                _fire(rule, my_rank, peer)
                flags |= SEVER
        elif rule.action == "delay":
            _fire(rule, my_rank, peer)
            time.sleep(rule.ms / 1000.0)
        elif rule.action == "sever_transient":
            # like sever's one-shot-per-edge latch, but gated on the
            # Nth matching frame, and the edge additionally enters a
            # down-window during which link_down() holds redials off
            if (my_rank, peer) not in rule.fired_edges and \
                    rule.count >= rule.after:
                rule.fired_edges.add((my_rank, peer))
                edge = (my_rank, peer) if my_rank <= peer \
                    else (peer, my_rank)
                _down_until[edge] = time.monotonic() + rule.ms / 1000.0
                _fire(rule, my_rank, peer)
                flags |= SEVER | TRANSIENT
        elif rule.action == "blackhole":
            # silent outage: from the first matching frame, every
            # matching frame vanishes for ms — no reset, no EOF, so
            # only a retransmit timeout can notice. One fault counted
            # per window (per-frame counts would make
            # ft_injected_faults depend on send timing)
            now = time.monotonic()
            if rule.until is None:
                rule.until = now + rule.ms / 1000.0
                _fire(rule, my_rank, peer)
            if now < rule.until:
                flags |= DROP
        elif rule.action == "corrupt":
            if _hits(rule):
                _fire(rule, my_rank, peer)
                flags |= CORRUPT
        elif rule.action == "drop":
            if _hits(rule):
                _fire(rule, my_rank, peer)
                flags |= DROP
        elif rule.action == "dup":
            if _hits(rule):
                _fire(rule, my_rank, peer)
                flags |= DUP
    return flags


def wrap_deliver(deliver):
    """Receive-side filter over a btl's deliver funnel — installed by
    btl/base.py at construction whenever a plan is armed (the rule list
    stays live across install()/uninstall()). With no recv-side rules
    the wrapper costs one truthiness check per frame — no Header parse
    — and the no-plan path never pays even the wrapper frame."""
    from ompi_tpu.pml.base import Header

    def injected_deliver(hdr_bytes, payload):
        if not _recv_rules:
            return deliver(hdr_bytes, payload)
        h = Header(hdr_bytes)
        me = _my_rank
        drop = dup = False
        for rule in _recv_rules:
            if me is None or not _edge(rule, h.src, me):
                continue
            rule.count += 1
            if rule.action == "delay":
                _fire(rule, h.src, me)
                time.sleep(rule.ms / 1000.0)
            elif rule.action == "drop" and _hits(rule):
                _fire(rule, h.src, me)
                drop = True
            elif rule.action == "dup" and _hits(rule):
                _fire(rule, h.src, me)
                dup = True
        if drop:
            return
        deliver(hdr_bytes, payload)
        if dup:
            deliver(hdr_bytes, payload)

    return injected_deliver


try:
    install()  # arm from the cvar (env-sourced in procmode children)
except ValueError as _e:
    # an operator typo must fail LOUDLY with an MCA-style banner before
    # the import error cascade — silently disabling injection would let
    # a chaos test run with no chaos and report false confidence
    show_help("ft", "bad-inject-plan", error=str(_e))
    raise
