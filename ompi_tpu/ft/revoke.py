"""Communicator revocation and shrink.

Reference: ompi/communicator/ft/comm_ft_revoke.c + the reliable
broadcast of comm_ft_reliable_bcast.c — revoke must reach every live
member even when the initiator dies mid-propagation. Redesign: a
FLOOD — every rank re-forwards the notice to all peers the first time
it learns of the revocation (the revoked flag is the dedup), so any
connected component of live ranks converges after one failure, which is
the rbcast property the reference's BMG topology provides.
"""

from __future__ import annotations

from ompi_tpu.utils.show_help import show_help


REVOKE_TAG = -4242  # internal tag space (negative tags are framework-only)


def revoke_comm(comm) -> None:
    """Flip local revoked state and flood the notice to every peer.
    Re-entry (a notice for an already-revoked comm) stops the flood."""
    import numpy as np

    if comm.revoked:
        return
    comm.revoked = True
    show_help("comm", "revoked", name=comm.name)
    from ompi_tpu.mpit import emit  # MPI_T event (mpit.py)

    emit("comm", "revoked", name=comm.name, cid=comm.cid)
    pml = getattr(comm, "pml", None)
    if pml is None:
        return  # mesh-mode comms revoke locally (single controller)
    token = np.array([comm.cid], dtype=np.int64)
    for r in comm.group.ranks:
        if r == pml.my_rank:
            continue
        try:
            pml.isend(token, 1, _int64(), r, REVOKE_TAG, comm.cid)
        except Exception:
            pass  # peer may already be dead; its detector will notice
    # fail every pending operation on the revoked comm NOW (ULFM: the
    # revocation completes pending operations with ERR_REVOKED). A rank
    # blocked mid-collective on a LIVE peer that left for recovery has
    # nothing the peer-death sweep can convert — without this drain it
    # waits out the era timeout while the recovering peers' agreement
    # stalls on it (the "agreement stalled on coordinator" soak class).
    # Runs on the initiator AND on every flood receipt (_on_revoke
    # re-enters here exactly once per rank — the revoked flag dedups).
    drain = getattr(pml, "revoke_requests", None)
    if drain is not None:
        drain(comm.cid)


def _int64():
    from ompi_tpu.core.datatype import INT64

    return INT64


# Shrink agreement plane: its own CID bit so agreement traffic on the
# (revoked) comm can't match user or collective traffic.
FT_CID_BIT = 1 << 25
_TAG_SHRINK = 90


def _agree_max_alive(pml, alive, cid: int, value: int,
                     timeout: float = 30.0) -> int:
    """MAX-agreement among the live members over direct pml exchange —
    the revoked comm's collectives are unusable, which is exactly why
    ftagree exists (reference: coll/ftagree ERA; this is the
    coordinator-based simplification over an already-shrunk live set).

    Failure handling (r2 advice: never silently return the local value —
    diverging members would adopt different CIDs and hang):
    - a contributor that dies mid-round is excluded once the detector
      confirms it;
    - a coordinator that dies mid-round triggers a retry with the next
      live coordinator on fresh tags;
    - an *undetected* stall raises MPIError after the timeout, with every
      outstanding irecv cancelled, instead of diverging.

    Known limit vs real ERA: a coordinator that dies after a PARTIAL
    result broadcast leaves the recipients returned while the rest retry
    a round the recipients no longer serve — those ranks raise after the
    timeout (fail-fast, not divergence). Full mid-call consensus is
    ft/era.py's job; this coordinator round remains only as the transport
    for already-shrunk live sets."""
    import time

    import numpy as np

    from ompi_tpu.core.datatype import INT64
    from ompi_tpu.core.errors import MPIError, ERR_PENDING
    from ompi_tpu.ft.detector import known_failed

    plane = cid | FT_CID_BIT
    coords = sorted(alive)
    for rnd, coord in enumerate(coords):
        if coord in known_failed():
            continue
        tag_in = _TAG_SHRINK + 2 * rnd
        tag_out = tag_in + 1
        deadline = time.monotonic() + timeout

        def recv_from(peer: int, tag: int, who: str):
            """(value, None) on success, (None, 'dead') when the peer died
            (detector-confirmed); raises on an undetected stall. A reply
            racing the peer's detected death still counts: cancel_recv
            returns False when the request already completed, in which
            case the buffer holds the value."""
            buf = np.zeros(1, np.int64)
            req = pml.irecv(buf, 1, INT64, peer, tag, plane)
            while True:
                try:
                    req.Wait(timeout=0.25)
                    return int(buf[0]), None
                except MPIError:
                    if peer in known_failed():
                        if not pml.cancel_recv(req) and not req._error:
                            return int(buf[0]), None  # reply won the race
                        return None, "dead"
                    if time.monotonic() > deadline:
                        pml.cancel_recv(req)
                        raise MPIError(
                            ERR_PENDING,
                            f"shrink agreement stalled on {who} {peer}")

        if pml.my_rank == coord:
            vals = [value]
            for r in alive:
                if r == coord or r in known_failed():
                    continue
                v, dead = recv_from(r, tag_in, "rank")
                if dead is None:
                    vals.append(v)  # dead contributors are excluded
            agreed = max(vals)
            out = np.array([agreed], np.int64)
            for r in alive:
                if r != coord and r not in known_failed():
                    try:
                        pml.isend(out, 1, INT64, r, tag_out, plane)
                    except MPIError:
                        pass  # recipient's transport died: detector's job
            return agreed
        try:
            pml.isend(np.array([value], np.int64), 1, INT64, coord,
                      tag_in, plane)
        except MPIError:
            # coordinator's transport already dead (tcp marks connections
            # dead before the detector confirms): roll to the next round
            continue
        v, dead = recv_from(coord, tag_out, "coordinator")
        if dead is None:
            return v
        # coordinator died: next round, next coordinator
    raise MPIError(ERR_PENDING, "shrink agreement: no live coordinator")


def shrink_comm(comm):
    """MPIX_Comm_shrink: new communicator over the live members, with a
    real CID agreement among them (r1 left this as 'future work')."""
    from ompi_tpu.comm.communicator import (
        ProcComm,
        _bump_local_cid,
        _next_local_cid,
    )
    from ompi_tpu.core.group import Group
    from ompi_tpu.ft.detector import known_failed

    failed = known_failed()
    alive = [r for r in comm.group.ranks if r not in failed]
    newgrp = Group(alive)
    cid = _agree_max_alive(comm.pml, alive, comm.cid,
                           _next_local_cid() + 1000)
    _bump_local_cid(cid)
    shrunk = ProcComm(newgrp, cid, comm.pml, name=f"{comm.name}-shrunk")
    comm._propagate_session(shrunk)  # session tracking survives shrink
    return shrunk
