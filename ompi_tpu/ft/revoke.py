"""Communicator revocation and shrink.

Reference: ompi/communicator/ft/comm_ft_revoke.c (revoke propagates via
reliable broadcast and flips the revoked flag checked by every operation —
communicator.h:360-363) and MPIX_Comm_shrink (new comm excluding failed
ranks). Our propagation rides a best-effort revoke notice to every peer
over the pml; local state flips immediately.
"""

from __future__ import annotations

from ompi_tpu.utils.show_help import show_help


REVOKE_TAG = -4242  # internal tag space (negative tags are framework-only)


def revoke_comm(comm) -> None:
    """Flip local revoked state and best-effort notify peers (reference:
    the revoke reliable-bcast; peers also learn via their own detector)."""
    import numpy as np

    if comm.revoked:
        return
    comm.revoked = True
    show_help("comm", "revoked", name=comm.name)
    pml = getattr(comm, "pml", None)
    if pml is None:
        return  # mesh-mode comms revoke locally (single controller)
    token = np.array([comm.cid], dtype=np.int64)
    for r in comm.group.ranks:
        if r == pml.my_rank:
            continue
        try:
            pml.isend(token, 1, _int64(), r, REVOKE_TAG, comm.cid)
        except Exception:
            pass  # peer may already be dead; its detector will notice


def _int64():
    from ompi_tpu.core.datatype import INT64

    return INT64


def shrink_comm(comm):
    """MPIX_Comm_shrink: new communicator over the live members."""
    from ompi_tpu.comm.communicator import ProcComm
    from ompi_tpu.core.group import Group
    from ompi_tpu.ft.detector import known_failed

    failed = known_failed()
    alive = [r for r in comm.group.ranks if r not in failed]
    newgrp = Group(alive)
    # CID agreement must run on a usable comm; shrink is defined on revoked
    # comms, so allocate from the local counter + max over alive via direct
    # pml exchange is future work — use local allocation (single-host jobs
    # share the counter ordering because every rank revokes then shrinks in
    # the same order).
    from ompi_tpu.comm.communicator import _next_local_cid, _bump_local_cid

    cid = _next_local_cid() + 1000  # shrink CID space, disjoint from normal
    _bump_local_cid(cid)
    return ProcComm(newgrp, cid, comm.pml, name=f"{comm.name}-shrunk")
