"""ULFM-style fault tolerance (reference: ompi/communicator/ft + coll/ftagree
+ ompi/mpiext/ftmpi — MPIX_Comm_revoke/shrink/agree and the heartbeat
failure detector). The detector lives in ompi_tpu.ft.detector; revoke/shrink
in ompi_tpu.ft.revoke; agreement in ompi_tpu.ft.agreement; diskless
in-memory checkpoint replication in ompi_tpu.ft.diskless; the
shrink/respawn recovery policies in ompi_tpu.ft.recovery; deterministic
fault injection (incl. the preemption-notice model) in
ompi_tpu.ft.inject."""
