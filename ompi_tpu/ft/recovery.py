"""Recovery policies: shrink-and-continue and respawn-and-rejoin.

Reference: the ULFM specification's fault-tolerant loop (and OMPI's
ompi/mpiext/ftmpi examples): on MPIX_ERR_PROC_FAILED the survivors
revoke the communicator, agree on the failure knowledge, shrink to a
new communicator over the live membership, restore state, and retry.
This module packages that sequence over the pieces this tree already
has — ``ft/revoke.py`` (revoke flood + shrink), ``ft/era.py``
(early-returning agreement), ``ft/detector.py`` (the failure oracle),
``runtime/checkpoint.py`` (ranked two-phase-commit disk checkpoints)
and ``ft/diskless.py`` (in-memory replicated epochs):

- :func:`recover` runs revoke -> era agreement on the survivor set ->
  shrink, then applies a recovery *policy*:

  * ``policy="shrink"`` — continue degraded at N-1 ranks, optionally
    restoring this rank's partition of the newest committed DISK
    checkpoint (the PR 3 behavior, unchanged).
  * ``policy="respawn"`` — restore the ORIGINAL world size: the
    survivors spawn replacements through ``runtime/dpm.spawn``, merge
    the child job in and re-rank everyone back to their original
    ranks, rebuild each dead rank's state from survivor memory (a
    buddy replica, an XOR parity group, or a preemption final-flush
    blob — ft/diskless.py), and deliver it to the newcomer. No
    filesystem is touched unless every in-memory source is gone, in
    which case the disk checkpoint (when configured) is the fallback;
    with nothing left the failure show_helps and escalates
    ERR_PROC_FAILED.

- :func:`rejoin` is the replacement process's side of the respawn
  choreography (detect with :func:`is_respawned`): merge with the
  survivors, take the dead rank's original rank, receive the rebuilt
  state.
- :func:`resilient` wraps user code in the retry loop so an
  application writes its step function once.
- :func:`grow` / :func:`join_grow` are the PLANNED capacity-expansion
  twins of respawn/rejoin — the same spawn + Merge/Split choreography
  with nobody dead: existing members keep their ranks, newcomers take
  the new top ranks, and live state is redistributed through the
  elastic N→M reshard engine. The serve-layer autoscaler
  (serve/autoscale.py) drives this for scale-up; scale-down rides the
  ordinary shrink path.

Counters: ``ft_failovers`` / ``ft_retries`` / ``ft_respawns`` pvars
(mirrored as spc counters) join the watchdog's ``pml_watchdog_trips``
and the chaos harness's ``ft_injected_faults`` in ``ompi_tpu_info``.
"""

from __future__ import annotations

import functools
import json
import os
import sys
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ompi_tpu.core.errors import (
    MPIError,
    ERR_ARG,
    ERR_PROC_FAILED,
    ERR_PROC_FAILED_PENDING,
    ERR_REVOKED,
)
from ompi_tpu.mca.var import register_pvar
from ompi_tpu.runtime import trace as _trace
from ompi_tpu.utils.output import get_logger
from ompi_tpu.utils.show_help import show_help

log = get_logger("ft.recovery")

#: error classes the recovery loop treats as a survivable peer failure
FAILURE_CODES = (ERR_PROC_FAILED, ERR_PROC_FAILED_PENDING, ERR_REVOKED)

#: user-plane tag reserved on the re-ranked comm for state delivery to
#: newcomers (the comm is fresh — no application traffic precedes it)
RESPAWN_STATE_TAG = 4242
#: shrunk-comm tag for parity-reconstruction blob exchange
_PARITY_XCHG_TAG = 4243

_counts: Dict[str, int] = {"failovers": 0, "retries": 0, "respawns": 0,
                           "grows": 0}

# recovery-window depth: recover() publishes "a recovery is in flight
# on this process" so step-boundary admission control (serve/policy's
# AdmissionGate) can hold traffic instead of issuing collectives into
# a membership that is mid-revoke/shrink/respawn. Single int bumped
# and read under the GIL; nested recover() calls (a failure during
# recovery's own collectives escalating into an outer retry) stack.
_recovering = [0]  # mpiracer: relaxed-counter — GIL-atomic depth bumps; admission readers tolerate a one-poll-stale view


def recovering() -> bool:
    """Is a :func:`recover` call in flight on this process? The serve
    admission gate polls this to queue steps for the recovery window
    instead of tearing collectives across the dying membership."""
    return _recovering[0] > 0

register_pvar("ft", "failovers", lambda: _counts["failovers"],
              help="Completed revoke->agree->shrink recoveries")
register_pvar("ft", "retries", lambda: _counts["retries"],
              help="User operations retried on a recovered communicator "
                   "by the ft.recovery.resilient wrapper")
register_pvar("ft", "respawns", lambda: _counts["respawns"],
              help="Respawn-and-rejoin recoveries completed (original "
                   "world size restored)")
register_pvar("ft", "grows", lambda: _counts["grows"],
              help="Planned grow resizes completed (capacity expansion "
                   "— the respawn machinery with nobody dead)")


def _agree_survivors(comm) -> None:
    """Align every survivor's failure knowledge BEFORE shrink: each
    contributes a bitmask of the members it believes alive; the era
    AND is exactly the intersection, uniform on all survivors (the
    agreement itself excludes members that die mid-call). Without this
    step two survivors whose detectors fired at different times could
    shrink to DIFFERENT groups and the new comm would be torn.

    Masks ride the era int64 payload, so comms beyond 62 ranks fall
    back to a plain Agree(1) sync (their detectors have the flood to
    converge on; documented limit)."""
    from ompi_tpu.ft.detector import known_failed, mark_failed

    members = comm.group.ranks
    if len(members) > 62:
        comm.Agree(1)
        return
    failed = known_failed()
    mask = 0
    for i, r in enumerate(members):
        if r not in failed:
            mask |= 1 << i
    agreed = comm.Agree(mask)
    for i, r in enumerate(members):
        if not (agreed >> i) & 1 and r not in known_failed():
            # a peer's detector saw a death mine hasn't yet: adopt it
            mark_failed(r)


def recover(comm, checkpoint_dir: Optional[str] = None,
            step: Optional[int] = None, policy: str = "shrink",
            command: Optional[str] = None,
            args: Optional[Tuple[str, ...]] = None,
            elastic: bool = False,
            replicated: Tuple[str, ...] = ()
            ) -> Tuple[Any, Optional[dict]]:
    """One full ULFM recovery: revoke ``comm``, agree on the survivor
    set, shrink, then apply ``policy`` (see the module docstring).

    Returns ``(new_comm, state_or_None)``; ``state=None`` means
    "continue with your live state" (the preemption final-flush path,
    or no checkpoint source configured).

    Final-flush consistency contract: when every dead rank left a
    grace-window flush, survivors are NOT rolled back — this assumes
    the application advances its state only after a collective
    completes, and that the torn collective completed on no survivor.
    A symmetric collective CAN complete on a strict subset of
    survivors (the dying rank's last frames may reach only some
    peers), leaving survivors one step apart; applications that cannot
    tolerate that skew should reconcile after recovery (e.g. agree on
    the minimum step) or rely on the epoch path, which rolls every
    member to the same committed epoch. For ``policy="respawn"``, ``command``/``args`` name
    the replacement's program (default: this process's own argv) and
    the returned comm has the ORIGINAL size with every survivor at its
    original rank. ``elastic=True`` (shrink policy) restores the disk
    checkpoint REPARTITIONED onto the shrunk world instead of handing
    each survivor its old same-size partition: the checkpoint taken by
    N ranks is redistributed over the M survivors through an N->M
    reshard plan (reshard/elastic.py; ``replicated`` names state keys
    broadcast verbatim instead of row-concatenated). Collective over
    the survivors."""
    if policy not in ("shrink", "respawn"):
        raise MPIError(ERR_ARG, f"unknown recovery policy {policy!r}")
    from ompi_tpu.runtime import spc

    _recovering[0] += 1
    try:
        if _trace.enabled():
            with _trace.span("ft.recover", cat="ft", cid=comm.cid,
                             policy=policy):
                return _recover(comm, checkpoint_dir, step, policy,
                                command, args, spc, elastic, replicated)
        return _recover(comm, checkpoint_dir, step, policy, command,
                        args, spc, elastic, replicated)
    finally:
        _recovering[0] -= 1


def _recover(comm, checkpoint_dir, step, policy, command, args, spc,
             elastic=False, replicated=()):
    old_rank = comm.Get_rank()
    comm.Revoke()
    _agree_survivors(comm)
    shrunk = comm.Shrink()
    # world membership is changing: stale cached quant cards (a dead
    # rank's, or a respawned replacement's predecessor's) would split
    # the per-communicator codec verdict across survivors
    from ompi_tpu.quant import negotiate as _qneg

    _qneg.invalidate_cards()
    _counts["failovers"] += 1
    spc.record("ft_failover")
    log.warning("recovered: %s (%d ranks) -> %s (%d ranks)",
                comm.name, comm.size, shrunk.name, shrunk.size)
    if policy == "respawn":
        return _respawn(comm, shrunk, old_rank, checkpoint_dir,
                        command, args)
    state = None
    if checkpoint_dir is not None:
        if elastic:
            state = _elastic_restore(shrunk, checkpoint_dir, step,
                                     replicated)
        else:
            state = _disk_restore(shrunk, checkpoint_dir, step, old_rank)
    return shrunk, state


def _elastic_restore(shrunk, checkpoint_dir, step, replicated):
    from ompi_tpu.reshard.elastic import restore_elastic
    from ompi_tpu.runtime.checkpoint import latest_ranked_step

    use = latest_ranked_step(checkpoint_dir) if step is None else step
    if use is None:
        return None
    return restore_elastic(shrunk, checkpoint_dir, use,
                           replicated=replicated)


def _disk_restore(comm, checkpoint_dir, step, old_rank):
    from ompi_tpu.runtime.checkpoint import (
        latest_ranked_step,
        restore_ranked,
    )

    use = latest_ranked_step(checkpoint_dir) if step is None else step
    if use is None:
        return None
    return restore_ranked(comm, checkpoint_dir, use, rank=old_rank)


# ------------------------------------------------------ respawn machinery
def _allgather_obj(comm, obj) -> List[dict]:
    """JSON allgather over ``comm`` (suppressed from user counters)."""
    from ompi_tpu.runtime import spc

    data = json.dumps(obj).encode()
    n = comm.Get_size()
    lens = np.zeros(n, np.int64)
    with spc.suppressed():
        comm.Allgather(np.array([len(data)], np.int64), lens)
        buf = np.zeros(max(int(lens.sum()), 1), np.uint8)
        comm.Allgatherv(np.frombuffer(data, np.uint8), buf,
                        counts=lens.tolist())
    out, pos = [], 0
    for ln in lens.tolist():
        out.append(json.loads(bytes(buf[pos:pos + ln]).decode()))
        pos += ln
    return out


def _survivor_caps(old_rank: int, dead: List[int], checkpoint_dir) -> dict:
    """What THIS survivor can serve for each dead original rank."""
    from ompi_tpu.ft import diskless
    from ompi_tpu.runtime.checkpoint import latest_ranked_step

    committed = diskless.committed_epoch()
    # capabilities cover the WHOLE keep window: min() over survivor
    # committed epochs can trail this rank's newest epoch by one when a
    # commit vote was torn by a concurrent revocation
    caps = {
        "rank": old_rank,
        "epoch": committed,
        "next": diskless.next_epoch(),
        "replicas": {str(d): diskless.replica_epochs(d) for d in dead},
        "final": [d for d in dead
                  if diskless.final_blob(d) is not None],
        "parity": diskless.parity_epochs(),
        "own": diskless.own_epochs(),
        "disk": (latest_ranked_step(checkpoint_dir)
                 if checkpoint_dir is not None else None),
    }
    return caps


def _plan_sources(dead: List[int], caps: List[dict], size: int,
                  mode: str, groups: Dict[int, List[int]]) -> dict:
    """Deterministic recovery plan, computed identically on every
    survivor from the allgathered capabilities. ``caps[i]`` belongs to
    the survivor at shrunk rank i; ``caps[i]['rank']`` is its original
    rank. Returns ``{"mode": "final"|"epoch", "epoch": E,
    "next": N, "sources": {dead: (kind, shrunk_rank)}}`` where kind is
    final|mem|parity|disk; raises ERR_PROC_FAILED (after a show_help)
    when some dead rank has no source at all."""
    old_of = [c["rank"] for c in caps]
    alive = set(old_of)
    epochs = [c["epoch"] for c in caps if c["epoch"] >= 0]
    E = min(epochs) if epochs else -1
    nxt = max(c["next"] for c in caps)
    # preemption fast path: every dead rank flushed a final blob —
    # survivors keep their live state, nobody rolls back
    finals = {}
    for d in dead:
        for i, c in enumerate(caps):
            if d in c["final"]:
                finals[d] = ("final", i)
                break
    if len(finals) == len(dead):
        return {"mode": "final", "epoch": E, "next": nxt,
                "sources": finals}
    sources: Dict[int, Tuple[str, int]] = {}
    unrecoverable = []
    for d in dead:
        src = None
        if E >= 0:
            for i, c in enumerate(caps):  # buddy replica at E
                if E in c["replicas"].get(str(d), ()):
                    src = ("mem", i)
                    break
            if src is None and mode == "parity":
                others = [m for m in groups[d] if m != d]
                if others and all(m in alive for m in others):
                    # single failure in the group: the lowest surviving
                    # member coordinates the XOR rebuild — which needs
                    # the coordinator's parity block AND every helper's
                    # own blob retained at E (a keep-window divergence
                    # can purge either; falling through to disk beats
                    # crashing mid-choreography)
                    coord = min(others)
                    if E in caps[old_of.index(coord)]["parity"] and \
                            all(E in caps[old_of.index(m)].get("own", ())
                                for m in others):
                        src = ("parity", old_of.index(coord))
        if src is None:
            for i, c in enumerate(caps):  # disk fallback
                if c["disk"] is not None:
                    src = ("disk", i)
                    break
        if src is None:
            unrecoverable.append(d)
        else:
            sources[d] = src
    if unrecoverable:
        show_help("ft", "ckpt-unrecoverable", once=False,
                  ranks=unrecoverable,
                  reason=f"mode={mode}, committed epoch {E}, "
                         f"survivors {sorted(alive)}")
        raise MPIError(
            ERR_PROC_FAILED,
            f"diskless recovery: no state source for dead ranks "
            f"{unrecoverable}")
    return {"mode": "epoch", "epoch": E, "next": nxt,
            "sources": sources}


def _rebuild_blob(shrunk, plan, d: int, caps: List[dict],
                  groups: Dict[int, List[int]], checkpoint_dir,
                  my_shrunk: int) -> Optional[Tuple[bytes, dict]]:
    """Produce dead rank ``d``'s state blob on its designated sender
    (returns None on every other rank). Parity reconstruction is
    collective among the group's survivors; everything else is local."""
    from ompi_tpu.ft import diskless
    from ompi_tpu.runtime import spc

    kind, sender = plan["sources"][d]
    E = plan["epoch"]
    meta = {"kind": kind, "epoch": E, "next": plan["next"],
            "mode": plan["mode"]}
    if kind == "final":
        if my_shrunk != sender:
            return None
        blob, fmeta = diskless.final_blob(d)
        meta["flush_epoch"] = fmeta.get("epoch")
        return blob, meta
    if kind == "mem":
        if my_shrunk != sender:
            return None
        diskless.note_replica_restore()
        return diskless.replica_blob(d, E), meta
    if kind == "parity":
        others = [m for m in groups[d] if m != d]
        old_of = [c["rank"] for c in caps]
        if old_of[my_shrunk] not in others:
            return None
        if my_shrunk == sender:
            pinfo = diskless.parity_info(E)
            parity, lengths = pinfo
            lengths = {int(k): int(v) for k, v in lengths.items()}
            blobs = [diskless.own_blob(E)]
            for m in others:
                if m == old_of[my_shrunk]:
                    continue
                buf = np.zeros(lengths[m], np.uint8)
                with spc.suppressed():
                    shrunk.Recv(buf, source=old_of.index(m),
                                tag=_PARITY_XCHG_TAG)
                blobs.append(bytes(buf))
            return diskless.xor_reconstruct(parity, lengths, d,
                                            blobs), meta
        # helper: ship my own epoch blob to the coordinator
        blob = diskless.own_blob(E)
        with spc.suppressed():
            shrunk.Send(np.frombuffer(blob, np.uint8), dest=sender,
                        tag=_PARITY_XCHG_TAG)
        return None
    # disk: the sender reads the dead rank's partition and re-encodes
    if my_shrunk != sender:
        return None
    state = _disk_restore(shrunk, checkpoint_dir, None, d)
    if state is None:
        raise MPIError(ERR_PROC_FAILED,
                       f"disk fallback vanished for rank {d}")
    meta["kind"] = "disk"
    return diskless.encode_state(state), meta


def _send_state(comm, dst: int, meta: dict, blob: bytes) -> None:
    from ompi_tpu.runtime import spc

    mb = json.dumps(meta).encode()
    hdr = np.array([len(mb), len(blob)], np.int64)
    with spc.suppressed():
        comm.Send(hdr, dest=dst, tag=RESPAWN_STATE_TAG)
        comm.Send(np.frombuffer(mb + bytes(blob), np.uint8), dest=dst,
                  tag=RESPAWN_STATE_TAG)


def _recv_state(comm) -> Tuple[dict, bytes]:
    from ompi_tpu.comm.communicator import ANY_SOURCE
    from ompi_tpu.core.status import Status
    from ompi_tpu.runtime import spc

    st = Status()
    hdr = np.zeros(2, np.int64)
    with spc.suppressed():
        comm.Recv(hdr, source=ANY_SOURCE, tag=RESPAWN_STATE_TAG,
                  status=st)
        buf = np.zeros(int(hdr[0] + hdr[1]), np.uint8)
        comm.Recv(buf, source=st.source, tag=RESPAWN_STATE_TAG)
    meta = json.loads(bytes(buf[:int(hdr[0])]).decode())
    return meta, bytes(buf[int(hdr[0]):])


def _respawn(comm, shrunk, old_rank: int, checkpoint_dir,
             command, args):
    """Survivor side of respawn-and-rejoin (see recover)."""
    from ompi_tpu.ft import diskless
    from ompi_tpu.ft.detector import known_failed
    from ompi_tpu.mca.var import get_var
    from ompi_tpu.runtime import spc
    from ompi_tpu.runtime.dpm import spawn

    members = comm.group.ranks
    n = len(members)
    failed = known_failed()
    dead = [i for i, r in enumerate(members) if r in failed]
    if not dead:
        raise MPIError(ERR_PROC_FAILED,
                       "respawn requested but no member of this "
                       "communicator is known failed")
    mode = str(get_var("ft", "ckpt_mode"))
    groups = {d: diskless.group_members(d, n) for d in dead}
    caps = _allgather_obj(
        shrunk, _survivor_caps(old_rank, dead, checkpoint_dir))
    plan = _plan_sources(dead, caps, n, mode, groups)
    log.warning("respawn plan: dead=%s mode=%s epoch=%d sources=%s",
                dead, plan["mode"], plan["epoch"],
                {d: k for d, (k, _s) in plan["sources"].items()})
    # rebuild the dead ranks' blobs BEFORE spawning (parity exchange
    # runs on the shrunk comm; the spawn handshake must not interleave)
    rebuilt: Dict[int, Tuple[bytes, dict]] = {}
    for d in dead:
        out = _rebuild_blob(shrunk, plan, d, caps, groups,
                            checkpoint_dir, shrunk.Get_rank())
        if out is not None:
            rebuilt[d] = out
    # launch the replacements and bridge them in; the argv defaults are
    # INDEPENDENT — command=X with args unset still inherits this
    # process's argv tail (a replacement launched with no arguments
    # would crash at startup and fail the whole recovery)
    if command is None:
        command = os.path.abspath(sys.argv[0])
    if args is None:
        args = tuple(sys.argv[1:])
    info = {"env_OMPI_TPU_RESPAWN": "1",
            "env_OMPI_TPU_RESPAWN_TARGETS":
                ",".join(str(d) for d in dead),
            "env_OMPI_TPU_RESPAWN_SIZE": str(n)}
    inter = spawn(shrunk, command, tuple(args or ()), maxprocs=len(dead),
                  root=0, info=info)
    merged = inter.Merge(high=False)
    newcomm = merged.Split(0, key=old_rank)
    newcomm.name = f"{comm.name}-respawned"
    # deliver each rebuilt state to its newcomer (now at rank d)
    for d, (blob, meta) in rebuilt.items():
        _send_state(newcomm, d, meta, blob)
    # epoch alignment + survivor-side restore
    if plan["mode"] == "final":
        diskless.rollback_to(plan["next"] - 1)
        state = None  # survivors keep their live state (no rollback)
    else:
        state = diskless.my_state(plan["epoch"]) \
            if plan["epoch"] >= 0 else None
        diskless.rollback_to(plan["epoch"]
                             if plan["epoch"] >= 0
                             else plan["next"] - 1)
        if state is None and checkpoint_dir is not None:
            state = _disk_restore(newcomm, checkpoint_dir, None,
                                  old_rank)
    _counts["respawns"] += 1
    spc.record("ft_respawn")
    log.warning("respawn complete: %s is back to %d ranks (me=%d)",
                newcomm.name, newcomm.Get_size(), newcomm.Get_rank())
    return newcomm, state


def is_respawned() -> bool:
    """Is this process a replacement launched by a respawn recovery?"""
    return os.environ.get("OMPI_TPU_RESPAWN") == "1"  # mpilint: disable=raw-environ — respawn identity rides the dpm launch channel, like rank identity


def rejoin() -> Tuple[Any, Optional[dict], dict]:
    """Replacement-process side of respawn-and-rejoin: merge with the
    survivors, take the dead rank's original rank, receive the rebuilt
    state. Returns ``(comm, state_or_None, meta)`` — the comm has the
    original world size, this process sits at the dead rank's rank,
    and ``meta['kind']`` says where the state came from
    (final|mem|parity|disk)."""
    from ompi_tpu.ft import diskless
    from ompi_tpu.runtime import state as _state
    from ompi_tpu.runtime.dpm import Comm_get_parent

    targets = [int(x) for x in
               os.environ["OMPI_TPU_RESPAWN_TARGETS"].split(",")]  # mpilint: disable=raw-environ — respawn identity rides the dpm launch channel, like rank identity
    world = _state.get_world()
    parent = Comm_get_parent()
    if parent is None:
        raise MPIError(ERR_ARG, "rejoin() outside a respawned process")
    target = targets[world.Get_rank()]
    merged = parent.Merge(high=True)
    want = int(os.environ["OMPI_TPU_RESPAWN_SIZE"])  # mpilint: disable=raw-environ — respawn identity rides the dpm launch channel, like rank identity
    if merged.Get_size() != want:
        raise MPIError(
            ERR_ARG,
            f"respawn merge produced {merged.Get_size()} ranks, the "
            f"original world had {want} — survivor set and spawn count "
            "disagree")
    newcomm = merged.Split(0, key=target)
    meta, blob = _recv_state(newcomm)
    state = diskless.decode_state(blob) if blob else None
    # align the epoch clock with the survivors (SAME rule they apply in
    # _respawn — a skewed clock would stamp future epochs differently
    # and no receipt would ever match its wait); seed our own committed
    # copy so we can serve the NEXT recovery as a survivor
    if meta.get("mode") == "epoch" and int(meta["epoch"]) >= 0:
        diskless.rollback_to(int(meta["epoch"]))
        if blob:
            diskless.seed_own(int(meta["epoch"]), blob)
    else:
        diskless.rollback_to(int(meta["next"]) - 1)
    log.warning("rejoined as rank %d of %s (state source: %s)",
                newcomm.Get_rank(), newcomm.name, meta.get("kind"))
    return newcomm, state, meta


# ------------------------------------------------------- planned grow
def grow(comm, nprocs: int, command: Optional[str] = None,
         args: Optional[Tuple[str, ...]] = None,
         state: Optional[dict] = None,
         replicated: Tuple[str, ...] = (),
         note: Optional[dict] = None) -> Tuple[Any, Optional[dict]]:
    """Planned capacity EXPANSION: the respawn machinery with nobody
    dead. Collective over ``comm`` (every member is a survivor); spawns
    ``nprocs`` newcomers, merges them in, and re-ranks so the existing
    members keep ranks ``0..n-1`` and the newcomers take
    ``n..n+nprocs-1``. When ``state`` is given (REQUIRED to be given on
    every member or on none — the redistribution is collective), it is
    redistributed onto the grown world through an N→M elastic reshard
    plan (``replicated`` names keys broadcast verbatim); newcomers
    receive their partition inside :func:`join_grow`.

    The grow publishes a recovery window (``recovering()``) for its
    whole duration, so serve-layer admission holds new steps — no
    collective ever tears across the membership change. Unlike
    :func:`recover` there is no revoke/agree/shrink: the membership is
    healthy, only growing.

    ``note`` is a small JSON-serializable dict delivered verbatim to
    the newcomers (``join_grow`` returns it) — the caller's channel for
    controller state that must arrive consistent with the survivors
    (cooldown clocks, policy mode), keeping deterministic controllers
    deterministic across the resize.

    Returns ``(new_comm, new_state_or_None)``."""
    from ompi_tpu.ft import diskless
    from ompi_tpu.quant import negotiate as _qneg
    from ompi_tpu.runtime import spc
    from ompi_tpu.runtime.dpm import spawn

    if nprocs < 1:
        raise MPIError(ERR_ARG, f"grow(nprocs={nprocs}): need >= 1")
    old_rank = comm.Get_rank()
    n = comm.Get_size()
    _recovering[0] += 1
    try:
        if command is None:
            command = os.path.abspath(sys.argv[0])
        if args is None:
            args = tuple(sys.argv[1:])
        info = {"env_OMPI_TPU_GROW": "1",
                "env_OMPI_TPU_GROW_BASE": str(n),
                "env_OMPI_TPU_GROW_SIZE": str(n + nprocs),
                "env_OMPI_TPU_GROW_RESHARD":
                    "1" if state is not None else "0"}
        if note is not None:
            info["env_OMPI_TPU_GROW_NOTE"] = json.dumps(note)
        inter = spawn(comm, command, tuple(args or ()),
                      maxprocs=nprocs, root=0, info=info)
        merged = inter.Merge(high=False)
        newcomm = merged.Split(0, key=old_rank)
        newcomm.name = f"{comm.name}-grown"
        # membership changed: stale cached quant cards would split the
        # per-communicator codec verdict between old and new members
        _qneg.invalidate_cards()
        # epoch-clock alignment over the NEW comm (newcomers included):
        # everyone adopts the fastest clock so the next collective
        # save() stamps the same epoch on every member
        clocks = _allgather_obj(newcomm,
                                {"next": diskless.next_epoch()})
        diskless.rollback_to(max(c["next"] for c in clocks) - 1)
        new_state = None
        if state is not None:
            from ompi_tpu.reshard.elastic import reshard_states

            new_state = reshard_states(
                newcomm, {old_rank: state}, n_old=n,
                my_old_rank=old_rank, replicated=tuple(replicated))
        _counts["grows"] += 1
        spc.record("ft_grow")
        if _trace.enabled():
            _trace.instant("ft.grow", cat="ft", n_old=n,
                           n_new=n + nprocs)
        log.warning("grow complete: %s %d -> %d ranks (me=%d)",
                    newcomm.name, n, newcomm.Get_size(),
                    newcomm.Get_rank())
        return newcomm, new_state
    finally:
        _recovering[0] -= 1


def is_grown() -> bool:
    """Is this process a newcomer launched by a planned grow?"""
    return os.environ.get("OMPI_TPU_GROW") == "1"  # mpilint: disable=raw-environ — grow identity rides the dpm launch channel, like rank identity


def join_grow(replicated: Tuple[str, ...] = ()
              ) -> Tuple[Any, Optional[dict], Optional[dict]]:
    """Newcomer side of the planned-grow choreography (detect with
    :func:`is_grown`): merge with the existing members, take rank
    ``base + child_rank`` on the grown comm, align the epoch clock and
    receive this rank's partition of the redistributed state.
    ``replicated`` must match the survivors' ``grow(...)`` call.
    Returns ``(comm, state_or_None, note_or_None)``."""
    from ompi_tpu.ft import diskless
    from ompi_tpu.runtime import state as _state
    from ompi_tpu.runtime.dpm import Comm_get_parent

    world = _state.get_world()
    parent = Comm_get_parent()
    if parent is None:
        raise MPIError(ERR_ARG, "join_grow() outside a grown process")
    base = int(os.environ["OMPI_TPU_GROW_BASE"])  # mpilint: disable=raw-environ — grow identity rides the dpm launch channel, like rank identity
    want = int(os.environ["OMPI_TPU_GROW_SIZE"])  # mpilint: disable=raw-environ — grow identity rides the dpm launch channel, like rank identity
    reshard = os.environ.get("OMPI_TPU_GROW_RESHARD") == "1"  # mpilint: disable=raw-environ — grow identity rides the dpm launch channel, like rank identity
    raw_note = os.environ.get("OMPI_TPU_GROW_NOTE")  # mpilint: disable=raw-environ — grow identity rides the dpm launch channel, like rank identity
    merged = parent.Merge(high=True)
    if merged.Get_size() != want:
        raise MPIError(
            ERR_ARG,
            f"grow merge produced {merged.Get_size()} ranks, expected "
            f"{want} — member set and spawn count disagree")
    newcomm = merged.Split(0, key=base + world.Get_rank())
    # SAME clock-alignment allgather the survivors run in grow()
    clocks = _allgather_obj(newcomm, {"next": diskless.next_epoch()})
    diskless.rollback_to(max(c["next"] for c in clocks) - 1)
    state = None
    if reshard:
        from ompi_tpu.reshard.elastic import reshard_states

        state = reshard_states(newcomm, {}, n_old=base,
                               my_old_rank=None,
                               replicated=tuple(replicated))
    log.warning("grew in as rank %d of %s (world %d -> %d)",
                newcomm.Get_rank(), newcomm.name, base, want)
    return newcomm, state, (json.loads(raw_note) if raw_note else None)


def resilient(checkpoint_dir: Optional[str] = None,
              max_failovers: int = 2,
              codes: Tuple[int, ...] = FAILURE_CODES,
              policy: str = "shrink", elastic: bool = False,
              replicated: Tuple[str, ...] = ()):
    """Decorator running ``fn(comm, state, *args, **kwargs)`` with the
    retry-on-the-recovered-comm loop::

        @resilient(checkpoint_dir="/ckpt")
        def train(comm, state):
            ...collectives on comm, save_ranked/diskless checkpoints...
            return state

        result = train(COMM_WORLD, initial_state)

    On an MPIError in ``codes`` the wrapper runs :func:`recover` with
    the configured ``policy`` and re-invokes ``fn`` with the recovered
    comm (and the restored state when a source exists), up to
    ``max_failovers`` failures; anything else — or one failure too
    many — re-raises."""

    def deco(fn):
        @functools.wraps(fn)
        def run(comm, state=None, *args, **kwargs):
            failures = 0
            while True:
                try:
                    return fn(comm, state, *args, **kwargs)
                except MPIError as e:
                    if e.code not in codes or failures >= max_failovers:
                        raise
                    failures += 1
                    log.warning("%s failed (%s); recovering "
                                "(failover %d/%d)", fn.__name__, e,
                                failures, max_failovers)
                    comm, restored = recover(comm, checkpoint_dir,
                                             policy=policy,
                                             elastic=elastic,
                                             replicated=replicated)
                    if restored is not None:
                        state = restored
                    from ompi_tpu.runtime import spc

                    _counts["retries"] += 1
                    spc.record("ft_retry")

        return run

    return deco
