"""Shrink-and-continue recovery — the canonical ULFM idiom, reusable.

Reference: the ULFM specification's fault-tolerant loop (and OMPI's
ompi/mpiext/ftmpi examples): on MPIX_ERR_PROC_FAILED the survivors
revoke the communicator, agree on the failure knowledge, shrink to a
new communicator over the live membership, restore state, and retry.
This module packages that sequence over the pieces this tree already
has — ``ft/revoke.py`` (revoke flood + shrink), ``ft/era.py``
(early-returning agreement), ``ft/detector.py`` (the failure oracle),
and ``runtime/checkpoint.py`` (ranked two-phase-commit checkpoints):

- :func:`recover` runs revoke -> era agreement on the survivor set ->
  shrink -> optional restore from the newest committed checkpoint.
- :func:`resilient` wraps user code in the retry-on-the-shrunk-comm
  loop so an application writes its step function once and the ULFM
  choreography stays here.

Counters: ``ft_failovers`` / ``ft_retries`` pvars (mirrored as
``spc_ft_failover`` / ``spc_ft_retry``) join the watchdog's
``pml_watchdog_trips`` and the chaos harness's ``ft_injected_faults``
in ``ompi_tpu_info --pvars`` output.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Optional, Tuple

from ompi_tpu.core.errors import (
    MPIError,
    ERR_PROC_FAILED,
    ERR_PROC_FAILED_PENDING,
    ERR_REVOKED,
)
from ompi_tpu.mca.var import register_pvar
from ompi_tpu.runtime import trace as _trace
from ompi_tpu.utils.output import get_logger

log = get_logger("ft.recovery")

#: error classes the recovery loop treats as a survivable peer failure
FAILURE_CODES = (ERR_PROC_FAILED, ERR_PROC_FAILED_PENDING, ERR_REVOKED)

_counts: Dict[str, int] = {"failovers": 0, "retries": 0}

register_pvar("ft", "failovers", lambda: _counts["failovers"],
              help="Completed revoke->agree->shrink recoveries")
register_pvar("ft", "retries", lambda: _counts["retries"],
              help="User operations retried on a shrunk communicator "
                   "by the ft.recovery.resilient wrapper")


def _agree_survivors(comm) -> None:
    """Align every survivor's failure knowledge BEFORE shrink: each
    contributes a bitmask of the members it believes alive; the era
    AND is exactly the intersection, uniform on all survivors (the
    agreement itself excludes members that die mid-call). Without this
    step two survivors whose detectors fired at different times could
    shrink to DIFFERENT groups and the new comm would be torn.

    Masks ride the era int64 payload, so comms beyond 62 ranks fall
    back to a plain Agree(1) sync (their detectors have the flood to
    converge on; documented limit)."""
    from ompi_tpu.ft.detector import known_failed, mark_failed

    members = comm.group.ranks
    if len(members) > 62:
        comm.Agree(1)
        return
    failed = known_failed()
    mask = 0
    for i, r in enumerate(members):
        if r not in failed:
            mask |= 1 << i
    agreed = comm.Agree(mask)
    for i, r in enumerate(members):
        if not (agreed >> i) & 1 and r not in known_failed():
            # a peer's detector saw a death mine hasn't yet: adopt it
            mark_failed(r)


def recover(comm, checkpoint_dir: Optional[str] = None,
            step: Optional[int] = None) -> Tuple[Any, Optional[dict]]:
    """One full ULFM recovery: revoke ``comm``, agree on the survivor
    set, shrink, and (with ``checkpoint_dir``) restore this rank's
    partition of the newest committed ranked checkpoint — by the rank
    it held in ``comm``, which is the rank that wrote the partition.

    Returns ``(shrunk_comm, state_or_None)``. Collective over the
    survivors; the caller retries its work on the returned comm."""
    from ompi_tpu.runtime import spc

    if _trace.enabled():
        with _trace.span("ft.recover", cat="ft", cid=comm.cid):
            return _recover(comm, checkpoint_dir, step, spc)
    return _recover(comm, checkpoint_dir, step, spc)


def _recover(comm, checkpoint_dir, step, spc):
    old_rank = comm.Get_rank()
    comm.Revoke()
    _agree_survivors(comm)
    shrunk = comm.Shrink()
    _counts["failovers"] += 1
    spc.record("ft_failover")
    log.warning("recovered: %s (%d ranks) -> %s (%d ranks)",
                comm.name, comm.size, shrunk.name, shrunk.size)
    state = None
    if checkpoint_dir is not None:
        from ompi_tpu.runtime.checkpoint import (
            latest_ranked_step,
            restore_ranked,
        )

        use = latest_ranked_step(checkpoint_dir) if step is None else step
        if use is not None:
            state = restore_ranked(shrunk, checkpoint_dir, use,
                                   rank=old_rank)
    return shrunk, state


def resilient(checkpoint_dir: Optional[str] = None,
              max_failovers: int = 2,
              codes: Tuple[int, ...] = FAILURE_CODES):
    """Decorator running ``fn(comm, state, *args, **kwargs)`` with the
    retry-the-work-on-the-shrunk-comm loop::

        @resilient(checkpoint_dir="/ckpt")
        def train(comm, state):
            ...collectives on comm, save_ranked checkpoints...
            return state

        result = train(COMM_WORLD, initial_state)

    On an MPIError in ``codes`` the wrapper runs :func:`recover` and
    re-invokes ``fn`` with the shrunk comm (and the restored checkpoint
    state when a directory is configured), up to ``max_failovers``
    failures; anything else — or one failure too many — re-raises."""

    def deco(fn):
        @functools.wraps(fn)
        def run(comm, state=None, *args, **kwargs):
            failures = 0
            while True:
                try:
                    return fn(comm, state, *args, **kwargs)
                except MPIError as e:
                    if e.code not in codes or failures >= max_failovers:
                        raise
                    failures += 1
                    log.warning("%s failed (%s); recovering "
                                "(failover %d/%d)", fn.__name__, e,
                                failures, max_failovers)
                    comm, restored = recover(comm, checkpoint_dir)
                    if restored is not None:
                        state = restored
                    from ompi_tpu.runtime import spc

                    _counts["retries"] += 1
                    spc.record("ft_retry")

        return run

    return deco
