"""Early-returning fault-tolerant agreement (ERA).

Reference: ompi/mca/coll/ftagree/coll_ftagree_earlyreturning.c (4,326 LoC)
— uniform consensus on a bitwise-AND flag that completes correctly even
when members die *during* the call. Redesign around this package's
system-message plane instead of the reference's tree topology:

- Every member entering ``agree`` records per-(cid, seq) state and pushes
  its contribution to every lower-ranked live member — any of which may
  become coordinator, so a later coordinator already holds the flags of
  every entered member (the reference rebalances its tree on failure;
  with the driver-scale rank counts here, eager replication to potential
  coordinators is simpler and needs no repair protocol).
- The lowest live rank coordinates: it collects a contribution-or-death
  for every member, then runs a *query phase* — every live member answers
  whether it already holds a decision for this sequence. Any surviving
  decision is adopted; only when no one holds one does the coordinator
  compute AND over the collected flags. This is the early-returning
  property: a member that returned early still serves its decision from
  the background handler (states are kept for ERA_GC_KEEP sequences), so
  a coordinator death after a partial broadcast can never split the
  survivors.
- Stale-decision fencing: answering a coordinator's query with "none"
  commits the member to ignore decisions from any lower-ranked (dead)
  coordinator still in flight (``min_decider``), closing the race where
  an old DECIDE crosses a new coordinator's fresh computation.

Failure model: fail-stop with the ft detector as the (assumed accurate)
failure oracle — the same assumption the reference's detector-driven
protocols make (comm_ft_detector.c). An undetected stall fails fast with
ERR_PENDING after ``era_timeout`` rather than hanging or diverging.

Message format: int64[4] = [kind, cid, seq, value] on the dedicated
system tag ERA_TAG (negative tags are framework-internal and bypass comm
usability — agreement must work on revoked comms; that is its job).
"""

from __future__ import annotations

import threading
import time as _time
from typing import Dict, List, Optional, Tuple

import numpy as np

from ompi_tpu.mca.var import register_var, get_var
from ompi_tpu.runtime import forensics as _forensics
from ompi_tpu.utils.show_help import register_topic, show_help

register_topic(
    "ft", "era-timeout",
    "An era agreement TIMED OUT (ERR_PENDING after ft_era_timeout):\n"
    "{detail}\n"
    "The round/participant/votes-outstanding snapshot above is the\n"
    "soak-triage evidence: a missing contribution names the stalled\n"
    "member, a missing query answer names the stalled survivor. With\n"
    "forensics_enable set, per-rank stall-rank<N>.json dumps were also\n"
    "requested — merge them with tools/mpidiag.py to name the blocking\n"
    "edge under the agreement.")

ERA_TAG = -4244  # system plane (REVOKE=-4242, HEARTBEAT=-4243)

K_CONTRIB = 1   # value = member's flag
K_QUERY = 2     # value unused; answer with HAVE or NONE (coordinator only)
K_HAVE = 3      # value = cached decision
K_NONE = 4      # no decision cached (and src will not accept stale ones)
K_DECIDE = 5    # value = decision
K_PULL = 6      # member asking a (possibly returned) peer for a cached
                # decision; answered with DECIDE iff one exists — no fence

ERA_GC_KEEP = 16  # sequences of per-comm agreement state kept for serving


def _participant_bitmask(members: Optional[List[int]],
                         have: List[int]) -> int:
    """Bit i set = the i-th member (ascending member-list order) has
    contributed to the round — positional over the member list so the
    mask stays compact for sparse world-rank sets; falls back to raw
    world-rank bits when the member list is unknown (a state created
    by the background handler before the local agree() entered)."""
    if members is None:
        return sum(1 << r for r in have if 0 <= r < 1024)
    pos = {m: i for i, m in enumerate(sorted(members))}
    return sum(1 << pos[r] for r in have if r in pos)

register_var("ft", "era_timeout", 60.0,
             help="Seconds before an undetected agreement stall fails "
                  "fast with ERR_PENDING", level=6)
register_var("ft", "era_inject", "",
             help="Fault injection for the agreement test harness: "
                  "'partial_decide' makes a coordinator die after "
                  "broadcasting its decision to only one member "
                  "(reference analog: the ftagree fault-injection hooks "
                  "in its mpiext test suite)", level=9)


class _AgreeState:
    __slots__ = ("flag", "contribs", "decision", "qans", "min_decider",
                 "lock", "members", "entered", "done")

    def __init__(self):
        self.flag: Optional[int] = None          # my contribution
        self.contribs: Dict[int, int] = {}       # world rank -> flag
        self.decision: Optional[int] = None
        self.qans: Dict[int, Tuple[bool, int]] = {}  # rank -> (have, val)
        self.min_decider = -1
        self.lock = threading.Lock()
        # introspection only (set by the local agree() entry; states
        # created by the background handler have None/0 until then):
        # the member list this rank agreed over, its entry stamp, and
        # whether the local call has exited (return OR raise) — an
        # in-progress agreement (members set, not done) is pending work
        # for the stall sentinel, which posts no pml requests of its own
        self.members: Optional[List[int]] = None
        self.entered = 0.0
        self.done = False


class EraEngine:
    """Per-pml agreement engine: background message service + the
    blocking ``agree`` driver. One instance per process (all comms share
    it; states are keyed by (cid, seq))."""

    def __init__(self, pml):
        self.pml = pml
        self._states: Dict[Tuple[int, int], _AgreeState] = {}
        self._seqs: Dict[int, int] = {}  # cid -> next sequence
        self._lock = threading.Lock()
        pml.register_system_handler(ERA_TAG, self._on_message)
        # stall-forensics provider (rebind-by-name: the newest engine —
        # one per pml — reports; weakly bound so test engines don't pin)
        import weakref

        ref = weakref.ref(self)
        _forensics.register_weak_provider("ft.era", self)

        def _fx_pending(_ref=ref) -> int:
            # agreements this rank is INSIDE (members recorded, call
            # not exited): they post no pml requests, so without this
            # probe an era stall reads as "idle" to the stall sentinel
            eng = _ref()
            if eng is None:
                return 0
            with eng._lock:
                states = list(eng._states.values())
            return sum(1 for st in states
                       if st.members is not None and not st.done)

        _forensics.register_pending_probe("ft.era", _fx_pending)

    # ------------------------------------------------------------ plumbing
    def _state(self, cid: int, seq: int) -> _AgreeState:
        with self._lock:
            st = self._states.get((cid, seq))
            if st is None:
                st = self._states[(cid, seq)] = _AgreeState()
            return st

    def _gc(self, cid: int, seq: int) -> None:
        with self._lock:
            drop = [k for k in self._states
                    if k[0] == cid and k[1] < seq - ERA_GC_KEEP]
            for k in drop:
                del self._states[k]

    def _send(self, dst: int, kind: int, cid: int, seq: int,
              value: int) -> None:
        from ompi_tpu.core.datatype import INT64

        msg = np.array([kind, cid, seq, value], dtype=np.int64)
        try:
            self.pml.isend(msg, 4, INT64, dst, ERA_TAG, 0)
        except Exception:
            pass  # dst dead or dying: the detector is the oracle

    # --------------------------------------------------- background service
    def _on_message(self, hdr, payload: bytes) -> None:
        kind, cid, seq, value = (int(v) for v in
                                 np.frombuffer(payload, dtype=np.int64)[:4])
        src = hdr.src
        st = self._state(cid, seq)
        if kind == K_CONTRIB:
            with st.lock:
                st.contribs[src] = value
        elif kind == K_QUERY:
            with st.lock:
                if st.decision is not None:
                    ans, val = K_HAVE, st.decision
                else:
                    # fence: once we tell src "none", a stale DECIDE from
                    # any lower-ranked (dead) coordinator must be ignored
                    st.min_decider = max(st.min_decider, src)
                    ans, val = K_NONE, 0
            self._send(src, ans, cid, seq, val)
        elif kind == K_HAVE:
            with st.lock:
                st.qans[src] = (True, value)
                if st.decision is None:
                    st.decision = value
        elif kind == K_NONE:
            with st.lock:
                st.qans[src] = (False, 0)
        elif kind == K_DECIDE:
            with st.lock:
                if st.decision is None and src >= st.min_decider:
                    st.decision = value
        elif kind == K_PULL:
            with st.lock:
                dec = st.decision
            if dec is not None:
                self._send(src, K_DECIDE, cid, seq, dec)

    # ------------------------------------------------- stall forensics
    def debug_state(self) -> dict:
        """Forensics provider: every kept agreement round's state —
        contributions held (the participant bitmask over the member
        list), cached decision, query answers, stale-decision fence —
        newest rounds first, clipped to forensics.CAP."""
        now = _time.monotonic()
        with self._lock:
            n_states = len(self._states)
            keys = sorted(self._states, reverse=True)[:_forensics.CAP]
            states = [(k, self._states[k]) for k in keys]
            seqs = dict(self._seqs)
        rounds = []
        for (cid, seq), st in states:
            with st.lock:
                members = st.members
                have = sorted(st.contribs)
                rounds.append({
                    "cid": cid, "round": seq,
                    "members": members,
                    "contribs": have,
                    "participant_bitmask": _participant_bitmask(
                        members, have),
                    "votes_outstanding": (
                        None if members is None
                        else [m for m in members if m not in st.contribs]),
                    "decision": st.decision is not None,
                    "in_progress": st.members is not None
                    and not st.done,
                    "query_answers": sorted(st.qans),
                    "min_decider": st.min_decider,
                    "age_s": round(now - st.entered, 3)
                    if st.entered else None,
                })
        return {"rounds": rounds,
                "rounds_omitted": max(0, n_states - len(rounds)),
                "next_seq_by_cid": {
                    str(c): s for c, s in seqs.items()}}

    def _timeout(self, st: _AgreeState, cid: int, seq: int,
                 phase: str, waiting: str):
        """Build (and show_help) the agreement-timeout verdict carrying
        the round, participant bitmask, and votes-outstanding — the
        evidence soak triage needs even with forensics disabled — and
        return the MPIError to raise."""
        from ompi_tpu.core.errors import MPIError, ERR_PENDING
        from ompi_tpu.ft.detector import known_failed

        with st.lock:
            members = st.members
            have = sorted(st.contribs)
            decision = st.decision
            qans = sorted(st.qans)
        failed = sorted(known_failed()
                        & set(members or have))
        outstanding = [] if members is None else \
            [m for m in members if m not in have and m not in failed]
        detail = (f"{phase}: agreement round {seq} on cid {cid} "
                  f"stalled waiting on {waiting}; members {members}, "
                  f"contributions held {have} (participant bitmask "
                  f"0x{_participant_bitmask(members, have):x}), votes "
                  f"outstanding {outstanding}, query answers {qans}, "
                  f"known failed {failed}, decision "
                  f"{'cached' if decision is not None else 'none'}")
        show_help("ft", "era-timeout", once=False, detail=detail)
        if _forensics._enable_var._value:
            _forensics.trigger(f"era-timeout: round {seq} cid {cid} "
                               f"waiting on {waiting}")
        return MPIError(ERR_PENDING, detail)

    # ----------------------------------------------------------- the driver
    def agree(self, comm, flag: int, abort_on_revoke: bool = False) -> int:
        """Uniform AND-consensus over ``comm``'s live members.

        ``abort_on_revoke=True`` is for agreements subordinate to the
        recovery choreography (the diskless epoch-commit vote): a
        revocation landing mid-call means a peer has already entered
        recovery on this comm, so waiting out the era timeout would
        stall the failover — raise ERR_REVOKED promptly instead. The
        DEFAULT stays False: MPIX_Comm_agree and the recovery's own
        survivor agreement must complete on revoked comms (that is the
        ULFM contract and the entire point of ERA)."""
        cid = comm.cid
        with self._lock:
            seq = self._seqs.get(cid, 0)
            self._seqs[cid] = seq + 1
        self._gc(cid, seq)
        st = self._state(cid, seq)
        me = self.pml.my_rank
        members = sorted(comm.group.ranks)
        flag = int(flag)
        with st.lock:
            st.flag = flag
            st.contribs[me] = flag
            st.members = list(members)   # introspection/timeout detail
            st.entered = _time.monotonic()
        try:
            return self._agree_drive(comm, st, cid, seq, me, members,
                                     flag, abort_on_revoke)
        finally:
            # every exit — decision, timeout, revoke-abort — retires
            # the round from the stall sentinel's pending-work view
            st.done = True

    def _agree_drive(self, comm, st: _AgreeState, cid: int, seq: int,
                     me: int, members, flag: int,
                     abort_on_revoke: bool) -> int:
        from ompi_tpu.core.errors import MPIError, ERR_PENDING, ERR_REVOKED
        from ompi_tpu.ft.detector import known_failed
        from ompi_tpu.runtime.progress import progress_until
        import time

        # eager replication: every potential coordinator gets my flag now
        for m in members:
            if m < me and m not in known_failed():
                self._send(m, K_CONTRIB, cid, seq, flag)

        deadline = time.monotonic() + get_var("ft", "era_timeout")
        recovering = False  # a coordinator died during this call
        while True:
            live = [m for m in members if m not in known_failed()]
            if not live:
                raise MPIError(ERR_PENDING, "agreement: no live members")
            coord = live[0]
            if abort_on_revoke and comm.revoked and st.decision is None:
                raise MPIError(ERR_REVOKED,
                               "agreement aborted: communicator revoked "
                               "(a peer is already in recovery)")
            if coord == me:
                return self._coordinate(comm, st, cid, seq, members,
                                        deadline, abort_on_revoke)
            # member: wait for a decision or the coordinator's death.
            # In recovery the new coordinator may have ALREADY returned
            # (it got the dead coordinator's decision) and will never
            # broadcast — pull its cached decision periodically; it
            # serves pulls from the background handler after returning
            # (the early-returning property).
            if recovering:
                self._send(coord, K_PULL, cid, seq, 0)
            # short wait slices whenever a prompt wake matters: a
            # recovery pull retry, or noticing a mid-call revocation
            slice_s = 0.25 if (recovering or abort_on_revoke) else None
            left = max(0.0, deadline - time.monotonic())
            done = progress_until(
                lambda: st.decision is not None
                or coord in known_failed(),
                timeout=left if slice_s is None else min(slice_s, left))
            if st.decision is not None:
                return st.decision
            if time.monotonic() >= deadline:
                raise self._timeout(
                    st, cid, seq, "member wait",
                    f"coordinator {coord} (no decision broadcast)")
            if done and coord in known_failed():
                recovering = True
            # the loop recomputes the coordinator; my entry-time CONTRIB
            # already reached every lower rank, and ranks above me pull
            # state through the query phase — nothing to resend.

    def _coordinate(self, comm, st: _AgreeState, cid: int, seq: int,
                    members, deadline, abort_on_revoke: bool = False) -> int:
        from ompi_tpu.core.errors import MPIError, ERR_PENDING, ERR_REVOKED
        from ompi_tpu.ft.detector import known_failed
        from ompi_tpu.runtime.progress import progress_until
        import time

        me = self.pml.my_rank

        def remaining() -> float:
            return max(0.0, deadline - time.monotonic())

        def aborted() -> bool:
            return abort_on_revoke and comm.revoked

        # phase 1: a contribution-or-death for every member
        def contribs_complete() -> bool:
            if aborted():
                return True
            failed = known_failed()
            return all(m in st.contribs or m in failed for m in members)

        if not progress_until(contribs_complete, timeout=remaining()):
            missing = [m for m in members if m not in st.contribs
                       and m not in known_failed()]
            raise self._timeout(
                st, cid, seq, "coordinator contribution collection",
                f"contribution from {missing}")
        if aborted():
            raise MPIError(ERR_REVOKED,
                           "agreement aborted: communicator revoked "
                           "(a peer is already in recovery)")

        # phase 2: query every live member for a surviving decision (the
        # early-returning recovery path). min_decider fences out any
        # DECIDE still in flight from a dead predecessor coordinator.
        with st.lock:
            st.min_decider = max(st.min_decider, me)
            st.qans.clear()
            prior = st.decision
        queried = [m for m in members
                   if m != me and m not in known_failed()]
        if prior is None:
            for m in queried:
                self._send(m, K_QUERY, cid, seq, 0)

            def queries_complete() -> bool:
                if aborted():
                    return True
                failed = known_failed()
                return all(m in st.qans or m in failed for m in queried)

            if not progress_until(queries_complete, timeout=remaining()):
                missing = [m for m in queried if m not in st.qans
                           and m not in known_failed()]
                raise self._timeout(
                    st, cid, seq, "coordinator query phase",
                    f"query answer from {missing}")
            if aborted():
                raise MPIError(ERR_REVOKED,
                               "agreement aborted: communicator revoked "
                               "(a peer is already in recovery)")

        # decide: adopt any surviving decision, else AND over every
        # collected contribution (contributions from members that died
        # after contributing are included — uniformity is guaranteed
        # because either this broadcast reaches the survivors or the next
        # coordinator recovers this very decision through its query phase)
        with st.lock:
            if st.decision is None:
                d = st.flag
                for v in st.contribs.values():
                    d &= v
                st.decision = d
            decision = st.decision
        recipients = [m for m in members
                      if m != me and m not in known_failed()]
        if get_var("ft", "era_inject") == "partial_decide" and recipients:
            # die after the decision escapes to exactly one member: the
            # survivors must converge through that member's early-return
            # service (the scenario ERA exists for)
            import os

            self._send(recipients[0], K_DECIDE, cid, seq, decision)
            progress_until(lambda: False, timeout=0.5)  # drain the send
            os._exit(0)
        for m in recipients:
            self._send(m, K_DECIDE, cid, seq, decision)
        return decision


_engines: Dict[int, EraEngine] = {}
_engines_lock = threading.Lock()


def engine_for(pml) -> EraEngine:
    with _engines_lock:
        eng = _engines.get(id(pml))
        if eng is None:
            eng = _engines[id(pml)] = EraEngine(pml)
        return eng
