"""Fault-tolerant agreement (MPIX_Comm_agree).

Reference: ompi/mca/coll/ftagree (4,326 LoC, early-returning consensus /
ERA). The MPI contract: every live process contributes a flag; the result
is the bitwise AND across live contributions, uniform on all survivors,
and the call succeeds even when members fail *during* it.

Process mode delegates to ft/era.py — the early-returning engine that
survives mid-call coordinator death (no Shrink, no leaked comms: the
agreement runs directly over the live membership on the system plane).
Mesh mode is a single controller, so agreement degenerates to a BAND
allreduce (there is no independent failure to survive)."""

from __future__ import annotations

import numpy as np


def agree(comm, flag: int) -> int:
    pml = getattr(comm, "pml", None)
    if pml is None:
        # mesh mode: one controller holds every rank; the agreement is a
        # BAND allreduce over the rank dim (mesh collectives are
        # functional: [W, ...] in, [W, ...] out)
        from ompi_tpu.core import op as _op

        flag = int(flag)
        if not -2**31 <= flag < 2**31:
            # jax demotes int64 to int32 without jax_enable_x64, which
            # would silently wrap wide bitmasks; every mesh position
            # contributes the same driver-held value, so AND == flag
            return flag
        x = comm.shard(np.full((comm.world_size, 1), flag, np.int32))
        out = comm.allreduce(x, _op.BAND)
        return int(np.asarray(out)[0, 0])
    from ompi_tpu.ft.era import engine_for

    return engine_for(pml).agree(comm, flag)
