"""Diskless in-memory checkpoint replication.

Reference: the classic diskless-checkpointing pair the vprotocol /
rollback-recovery literature assumes (SURVEY §5; Plank's diskless
checkpointing and the ftmpi examples keep survivor state in peer
memory): every rank serializes its application state each *epoch* and
ships it to peers, so recovery needs NO shared filesystem — exactly the
preemptible-TPU deployment the ROADMAP targets, where local disk
vanishes with the VM. Two redundancy schemes, both over a dedicated
system-plane tag (``FT_CKPT_TAG`` = -4600, the sanitizer/metrics idiom):

- **buddy** (default): each rank ships its blob to the next
  ``ft_ckpt_buddies`` ranks in comm order. Memory cost 1+k blobs per
  rank; any failure whose owner has one live buddy is recoverable.
- **parity**: ranks form groups of ``ft_ckpt_group``; each member XORs
  every group peer's blob into a running accumulator (transient — peer
  blobs are NOT retained) and keeps only the group parity ``P`` = XOR
  over all g members plus a per-owner length map. Memory cost 2 blobs
  per rank regardless of g; any SINGLE failure per group is rebuilt as
  ``P ⊕ (⊕ survivors' own blobs)``. A double failure inside one group
  falls back to the disk checkpoint (ft/recovery.py) when one exists.

Epoch semantics are prepare/commit: blobs stage under their epoch
number until EVERY rank reports its expected replicas arrived, ratified
by a :func:`ft.agreement.agree` (ERA) round — the uniform-consensus
property means a crash mid-epoch aborts the epoch on every survivor and
the previous complete epoch stays restorable (the two-phase-commit
discipline of ``runtime/checkpoint.save_ranked``, minus the
filesystem). The blob encoding IS ``save_ranked``'s: an in-memory npz
of the rank's ``{name: ndarray}`` state.

Preemption: ``ft/inject.py``'s ``preempt(rank, after=N, grace_ms=M)``
action (the TPU preemption-notice model from runtime/checkpoint.py's
design note) invokes :func:`flush_final` on the doomed rank, which
ships one FINAL single-owner blob (from the registered state provider)
to its buddies inside the grace window. When every dead rank left a
final blob, ``recover(policy="respawn")`` skips the rollback entirely:
survivors keep their live state and only the replacement restores.

Hot-path discipline: everything is gated on the ``ft_ckpt_enable``
live Var — the disabled path of every hook is one attribute load
(``_enable_var._value``; mpilint's hot-guard rule covers the
``diskless.save`` / ``diskless.flush_final`` hooks in hot modules).
Observability: ``ft_ckpt_epochs`` / ``ft_ckpt_bytes_replicated`` /
``ft_ckpt_restores_mem`` / ``ft_ckpt_restores_parity`` pvars,
``ft_ckpt_ship_us`` / ``ft_ckpt_restore_us`` latency histograms +
``ft_ckpt_epoch`` / ``ft_ckpt_store_bytes`` gauges in the metrics
plane, trace spans, and ``ft`` MPI_T events.
"""

from __future__ import annotations

import io
import json
import struct
import threading
import time
import weakref
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from ompi_tpu.core.errors import MPIError, ERR_OTHER
from ompi_tpu.mca.var import register_var, register_pvar
from ompi_tpu.mpit import emit as _emit, register_event_type
from ompi_tpu.runtime import metrics as _metrics
from ompi_tpu.runtime import trace as _trace
from ompi_tpu.utils.output import get_logger
from ompi_tpu.utils.show_help import register_topic

log = get_logger("ft.diskless")

#: diskless replication plane (sanitizer -4400, metrics -4500)
FT_CKPT_TAG = -4600

#: epoch-commit votes ride their own era cid plane (payload-only — era
#: frames carry the cid in their int64 body, not the wire header) so a
#: commit racing a recovery agreement on the same comm can never join
#: the wrong sequence
CKPT_CID_BIT = 1 << 31

_enable_var = register_var(
    "ft", "ckpt_enable", False,
    help="Replicate in-memory checkpoint epochs to peer ranks "
         "(diskless checkpointing) so ft/recovery can restore from "
         "survivor memory with no shared filesystem; disabled path is "
         "one attribute load per hook", level=3)
_mode_var = register_var(
    "ft", "ckpt_mode", "buddy", typ=str,
    help="Redundancy scheme: 'buddy' ships each rank's blob to the "
         "next ft_ckpt_buddies ranks; 'parity' keeps one XOR parity "
         "block per ft_ckpt_group ranks (2x memory at any group size, "
         "one recoverable failure per group)", level=4)
_buddies_var = register_var(
    "ft", "ckpt_buddies", 1,
    help="Replica count k in buddy mode: rank r ships to ranks "
         "r+1..r+k (mod size)", level=4)
_group_var = register_var(
    "ft", "ckpt_group", 3,
    help="XOR parity group size g in parity mode (consecutive comm "
         "ranks; a trailing remainder group smaller than 2 has no "
         "redundancy)", level=4)
_timeout_var = register_var(
    "ft", "ckpt_timeout", 30.0, float,
    help="Seconds a rank waits for its expected incoming replicas "
         "before voting to abort the epoch (the commit agreement turns "
         "any rank's timeout into a uniform abort)", level=6)
_keep_var = register_var(
    "ft", "ckpt_keep", 2,
    help="Committed epochs retained in memory (own blob + replicas "
         "+ parity); older epochs are garbage-collected at commit",
    level=7)

register_event_type("ft", "ckpt_commit",
                    "A diskless checkpoint epoch committed (ratified "
                    "by ERA agreement)")
register_event_type("ft", "ckpt_restore",
                    "Rank state restored from the in-memory epoch "
                    "store (own blob, buddy replica, or XOR parity)")
register_event_type("ft", "ckpt_preempt_flush",
                    "A preemption-doomed rank flushed one final blob "
                    "to its buddies inside the grace window")
register_topic(
    "ft", "ckpt-unrecoverable",
    "Diskless recovery cannot rebuild the state of dead rank(s) "
    "{ranks}:\n  {reason}\nNo buddy replica survived, the XOR parity "
    "group lost more than one member, and no committed disk "
    "checkpoint exists to fall back to. Increase ft_ckpt_buddies, "
    "shrink ft_ckpt_group, or configure a checkpoint_dir; escalating "
    "MPIX_ERR_PROC_FAILED to the application.")

_counts: Dict[str, int] = {"epochs": 0, "bytes": 0,
                           "restores_mem": 0, "restores_parity": 0}

register_pvar("ft", "ckpt_epochs", lambda: _counts["epochs"],
              help="Diskless checkpoint epochs committed (agreement-"
                   "ratified) on this rank")
register_pvar("ft", "ckpt_bytes_replicated", lambda: _counts["bytes"],
              help="Serialized state bytes shipped to buddy/parity "
                   "peers by the diskless checkpoint plane")
register_pvar("ft", "ckpt_restores_mem", lambda: _counts["restores_mem"],
              help="States restored from in-memory blobs (own epoch "
                   "copy or a buddy replica)")
register_pvar("ft", "ckpt_restores_parity",
              lambda: _counts["restores_parity"],
              help="States reconstructed from an XOR parity group")


def enabled() -> bool:
    """One attribute load off the live Var (spc/trace discipline)."""
    return _enable_var._value


# ----------------------------------------------------------- blob encoding
def encode_state(state: Dict[str, np.ndarray]) -> bytes:
    """The ``save_ranked`` npz encoding, in memory."""
    buf = io.BytesIO()
    np.savez(buf, **state)
    return buf.getvalue()


def decode_state(blob: bytes) -> Dict[str, np.ndarray]:
    with np.load(io.BytesIO(bytes(blob))) as z:
        return {k: z[k].copy() for k in z.files}


def _xor_into(acc: bytearray, blob: bytes) -> None:
    """acc ^= blob, growing acc to cover blob (zero padding is the XOR
    identity, so differing blob lengths compose correctly)."""
    if len(acc) < len(blob):
        acc.extend(b"\0" * (len(blob) - len(acc)))
    a = np.frombuffer(acc, np.uint8)
    a[: len(blob)] ^= np.frombuffer(blob, np.uint8)


def xor_reconstruct(parity: bytes, lengths: Dict[int, int], dead: int,
                    blobs: List[bytes]) -> bytes:
    """Rebuild the dead group member's blob: parity ⊕ every surviving
    member's blob, truncated to the dead member's recorded length.
    ``blobs`` must hold the g-1 surviving members' blobs (any order)."""
    acc = bytearray(parity)
    for b in blobs:
        _xor_into(acc, b)
    n = int(lengths[dead])
    if n > len(acc):
        raise MPIError(ERR_OTHER,
                       f"parity reconstruction underflow: need {n} "
                       f"bytes, accumulator holds {len(acc)}")
    _counts["restores_parity"] += 1
    return bytes(acc[:n])


# ------------------------------------------------------------- geometry
def buddies(rank: int, size: int, k: Optional[int] = None) -> List[int]:
    """The k successor ranks holding this rank's replica (comm order)."""
    if k is None:
        k = int(_buddies_var._value)
    k = max(0, min(int(k), size - 1))
    return [(rank + j) % size for j in range(1, k + 1)]


def group_members(rank: int, size: int,
                  g: Optional[int] = None) -> List[int]:
    """This rank's XOR parity group (consecutive comm ranks)."""
    if g is None:
        g = int(_group_var._value)
    g = max(2, int(g))
    lo = (rank // g) * g
    return list(range(lo, min(lo + g, size)))


def _expected_owners(rank: int, size: int, mode: str) -> List[int]:
    """Ranks whose epoch blob must land HERE for the epoch to commit.
    Buddy mode is the closed form — I replicate FOR my k predecessors
    (the inverse of buddies()) — not an O(size) membership scan."""
    if mode == "parity":
        return [m for m in group_members(rank, size) if m != rank]
    k = max(0, min(int(_buddies_var._value), size - 1))
    return sorted({(rank - j) % size for j in range(1, k + 1)})


# ----------------------------------------------------------------- store
class _Store:
    """Epoch-keyed blob store. ``staged_*`` holds the in-flight epoch;
    commit promotes it and garbage-collects beyond ft_ckpt_keep."""

    def __init__(self):
        self.own: Dict[int, bytes] = {}
        self.replicas: Dict[Tuple[int, int], bytes] = {}  # (epoch, owner)
        self.parity: Dict[int, Tuple[bytes, Dict[int, int]]] = {}
        self.staged_own: Dict[int, bytes] = {}
        self.staged_replicas: Dict[Tuple[int, int], bytes] = {}
        self.staged_parity: Dict[int, list] = {}  # epoch -> [acc, lens]
        self.final: Dict[int, Tuple[bytes, dict]] = {}  # owner -> blob
        self.committed = -1
        self.next_epoch = 0


_lock = threading.Lock()
_store = _Store()
_provider: Optional[Callable[[], Dict[str, np.ndarray]]] = None
_comm_ref = None  # weakref to the last attached communicator


# ----------------------------------------------------------- system plane
def _ship(pml, dst_urank: int, kind: str, epoch: int, owner: int,
          blob: bytes) -> None:
    """One framed blob on the replication plane: u32 meta length + JSON
    meta + raw npz bytes in a single logical system-plane message
    (system tags skip the eager limit). With traffic shaping on
    (``btl_tcp_shape_enable``), the pml classifies tag -4600 as BULK
    (``qos_tag_map``) and segments the blob into
    ``btl_tcp_shape_segment_bytes`` sub-frames reassembled at the
    receiver, so a 64MB epoch ship is preemptible by latency traffic
    instead of holding the wire for its full serialization time (and
    blobs past the 2 GiB tcp framing limit become shippable at all).
    Fire-and-forget: a dead destination surfaces as a missing receipt
    and the commit agreement aborts the epoch — a transfer severed
    mid-blob leaves a partial the pml purges on peer failure, and this
    rank's wait below times out into an abort vote."""
    from ompi_tpu.core.datatype import BYTE
    from ompi_tpu.runtime import spc

    meta = json.dumps({"kind": kind, "epoch": int(epoch),
                       "owner": int(owner), "len": len(blob)}).encode()
    # chunked frame build: one monolithic `header + bytes(blob)` concat
    # holds the GIL for the whole blob (~13ms per 64MB) and a burst of
    # epoch ships starves every other thread in the process — the
    # foreground collectives this plane must stay out of the way of.
    # Slice-assigning in 1MB steps keeps every GIL hold sub-millisecond.
    frame = bytearray(4 + len(meta) + len(blob))
    struct.pack_into("<I", frame, 0, len(meta))
    frame[4:4 + len(meta)] = meta
    dst = memoryview(frame)
    src = memoryview(blob).cast("B") if not isinstance(blob, bytes) \
        else memoryview(blob)
    base = 4 + len(meta)
    step = 1 << 20
    for off in range(0, len(blob), step):
        dst[base + off:base + off + min(step, len(blob) - off)] = \
            src[off:off + step]
    arr = np.frombuffer(frame, np.uint8)
    try:
        with spc.suppressed():
            pml.isend(arr, arr.size, BYTE, dst_urank, FT_CKPT_TAG, 0)
    except Exception:
        log.debug("ship to universe rank %d failed (dead peer?)",
                  dst_urank, exc_info=True)


def _on_system(hdr, payload) -> None:
    """Replication-plane dispatch (runs on the transport's delivery
    thread — store and return, never raise)."""
    try:
        # the pml's system-plane delivery hands OWNED bytes/bytearrays
        # (`_owned` copies borrowed transport views; segmented blobs
        # arrive as the reassembly accumulator itself), so the blob can
        # be kept as a zero-copy memoryview slice — materializing
        # `bytes(payload)` + a tail slice was two GIL-held full-blob
        # copies per epoch received
        (mlen,) = struct.unpack_from("<I", payload, 0)
        meta = json.loads(bytes(payload[4:4 + mlen]).decode())
        blob = memoryview(payload)[4 + mlen:]
        kind = meta["kind"]
        epoch = int(meta["epoch"])
        owner = int(meta["owner"])
    except Exception:
        log.warning("dropping malformed ft_ckpt frame from %d", hdr.src)
        return
    with _lock:
        if kind in ("replica", "parity") and \
                epoch < _store.next_epoch - 1:
            # straggler for an epoch whose save already finished
            # (committed or aborted): staging it would pin the blob
            # forever — nothing ever promotes or purges a past-epoch
            # staged entry
            return
        if kind == "replica":
            _store.staged_replicas[(epoch, owner)] = blob
        elif kind == "parity":
            acc = _store.staged_parity.get(epoch)
            if acc is None:
                acc = _store.staged_parity[epoch] = [bytearray(), {}]
            if owner in acc[1]:
                # XOR is NOT idempotent: a duplicated frame (transport
                # re-drive, chaos dup rule — the wire hooks don't
                # exempt system tags) would cancel the owner's
                # contribution out of the parity while still counting
                # it present, committing a silently corrupt block
                return
            _xor_into(acc[0], blob)
            acc[1][owner] = len(blob)
        elif kind == "final":
            _store.final[owner] = (blob, meta)
    if _trace.enabled():
        _trace.instant("ft.ckpt.recv", cat="ft", kind=kind,
                       epoch=epoch, owner=owner, nbytes=len(blob))


from ompi_tpu.pml.base import SystemPlane as _SystemPlane  # noqa: E402

_plane = _SystemPlane(FT_CKPT_TAG, _on_system)


def _bind_world_handler() -> None:
    """init_bottom hook: bind the replication handler before user code
    runs, so a fast peer's first epoch blob can't be dropped by lazy
    registration (the metrics-plane discipline)."""
    from ompi_tpu.pml.base import world_pml

    if not _enable_var._value:
        return
    pml = world_pml()
    if pml is not None:
        _plane.ensure(pml)


# ------------------------------------------------------------------ save
def attach(comm) -> None:
    """Remember the communicator the replication geometry runs over —
    save() does this implicitly; the preemption flush needs it when the
    notice arrives outside any save call."""
    global _comm_ref
    _comm_ref = weakref.ref(comm)
    pml = getattr(comm, "pml", None)
    if pml is not None:
        _plane.ensure(pml)


def set_state_provider(comm, fn: Callable[[], Dict[str, np.ndarray]]) -> None:
    """Register the zero-arg callable the preemption-notice flush
    serializes (return a self-consistent {name: ndarray} snapshot —
    update it only at step boundaries)."""
    global _provider
    _provider = fn
    attach(comm)


class _CommitChannel:
    """The comm facets era reads (cid, group, pml, revoked), with the
    cid shifted onto the commit plane."""

    __slots__ = ("_comm", "cid", "group", "pml")

    def __init__(self, comm):
        self._comm = comm
        self.cid = comm.cid | CKPT_CID_BIT
        self.group = comm.group
        self.pml = comm.pml

    @property
    def revoked(self) -> bool:
        return self._comm.revoked


def _have_all(epoch: int, owners: List[int], mode: str) -> bool:
    with _lock:
        if mode == "parity":
            acc = _store.staged_parity.get(epoch)
            got = set(acc[1]) if acc is not None else set()
            return all(o in got for o in owners)
        return all((epoch, o) in _store.staged_replicas for o in owners)


def save(comm, state: Dict[str, np.ndarray],
         timeout: Optional[float] = None) -> bool:
    """Replicate one epoch of ``state`` (collective over ``comm``).
    Returns True when the epoch committed on every rank, False when it
    aborted (a peer died or timed out mid-epoch — the previous
    committed epoch remains restorable either way). No-op returning
    False when ``ft_ckpt_enable`` is unset (one attribute load)."""
    if not _enable_var._value:
        return False
    if _trace.enabled():
        with _trace.span("ft.ckpt.save", cat="ft", cid=comm.cid):
            return _save(comm, state, timeout)
    return _save(comm, state, timeout)


def _save(comm, state, timeout) -> bool:
    from ompi_tpu.runtime import spc
    from ompi_tpu.runtime.progress import progress_until

    pml = getattr(comm, "pml", None)
    if pml is None:
        raise MPIError(ERR_OTHER,
                       "diskless checkpoints require process mode "
                       "(mesh mode has a single controller — use "
                       "MeshCheckpointer)")
    attach(comm)
    me, n = comm.Get_rank(), comm.Get_size()
    mode = str(_mode_var._value)
    with _lock:
        epoch = _store.next_epoch
        _store.next_epoch = epoch + 1
        # shed staging left behind by older epochs (a frame that raced
        # past the handler's past-epoch gate, or an abort whose
        # straggler landed later) — staging is only ever live for the
        # current epoch ± a one-epoch peer skew
        for key in [k for k in _store.staged_replicas if k[0] < epoch]:
            del _store.staged_replicas[key]
        for e in [e for e in _store.staged_parity if e < epoch]:
            del _store.staged_parity[e]
        for e in [e for e in _store.staged_own if e < epoch]:
            del _store.staged_own[e]
    t0 = time.monotonic()
    blob = encode_state(state)
    if mode == "parity" and n > 1:
        peers = [m for m in group_members(me, n) if m != me]
        kind = "parity"
    else:
        peers = buddies(me, n)
        kind = "replica"
    with _lock:
        _store.staged_own[epoch] = blob
        if kind == "parity":
            acc = _store.staged_parity.setdefault(epoch, [bytearray(), {}])
            _xor_into(acc[0], blob)
            acc[1][me] = len(blob)
    for p in peers:
        _ship(pml, comm.group.world_rank(p), kind, epoch, me, blob)
    if peers:
        _counts["bytes"] += len(blob) * len(peers)
        spc.record_bytes("ft_ckpt_ship_bytes", len(blob) * len(peers))
    owners = _expected_owners(me, n, mode)
    owner_uranks = {comm.group.world_rank(o) for o in owners}
    tmo = float(_timeout_var._value) if timeout is None else timeout

    def _settled() -> bool:
        # complete, or provably never completing: a dead owner can't
        # ship its blob (vote to abort now, don't burn the timeout),
        # and a revocation means a peer already failed into recovery
        from ompi_tpu.ft.detector import known_failed

        return (_have_all(epoch, owners, mode) or comm.revoked
                or bool(owner_uranks & known_failed()))

    progress_until(_settled, timeout=tmo)
    if comm.revoked:
        from ompi_tpu.core.errors import ERR_REVOKED

        raise MPIError(ERR_REVOKED,
                       "epoch save aborted: communicator revoked "
                       "(a peer is already in recovery)")
    ok = _have_all(epoch, owners, mode)
    if _metrics._enable_var._value:
        _metrics.observe("ft_ckpt_ship_us",
                         (time.monotonic() - t0) * 1e6, mode=mode)
    # The commit vote: AND over every member's "my replicas arrived" —
    # uniform even under mid-call death (the ERA property), so a torn
    # epoch aborts everywhere and the previous epoch stays whole. Runs
    # on a dedicated era cid channel with abort_on_revoke: a peer that
    # already failed into recovery revokes the comm, and this vote must
    # yield to that recovery (ERR_REVOKED reaches the caller's
    # failure-handling loop) instead of colliding with its agreement.
    from ompi_tpu.ft.era import engine_for

    decided = engine_for(pml).agree(_CommitChannel(comm), 1 if ok else 0,
                                    abort_on_revoke=True)
    if decided:
        _commit(epoch)
        return True
    with _lock:
        _store.staged_own.pop(epoch, None)
        _store.staged_parity.pop(epoch, None)
        for key in [k for k in _store.staged_replicas if k[0] == epoch]:
            del _store.staged_replicas[key]
    log.warning("diskless epoch %d aborted (ok=%d)", epoch, ok)
    return False


def _commit(epoch: int) -> None:
    with _lock:
        _store.own[epoch] = _store.staged_own.pop(epoch)
        for key in [k for k in _store.staged_replicas if k[0] == epoch]:
            _store.replicas[key] = _store.staged_replicas.pop(key)
        acc = _store.staged_parity.pop(epoch, None)
        if acc is not None:
            _store.parity[epoch] = (bytes(acc[0]), dict(acc[1]))
        _store.committed = epoch
        floor = epoch - max(int(_keep_var._value), 1) + 1
        for d in (_store.own, _store.parity):
            for e in [e for e in d if e < floor]:
                del d[e]
        for key in [k for k in _store.replicas if k[0] < floor]:
            del _store.replicas[key]
        resident = (sum(map(len, _store.own.values()))
                    + sum(map(len, _store.replicas.values()))
                    + sum(len(p) for p, _ in _store.parity.values()))
    _counts["epochs"] += 1
    _emit("ft", "ckpt_commit", epoch=epoch)
    if _metrics._enable_var._value:
        _metrics.gauge_set("ft_ckpt_epoch", epoch)
        _metrics.gauge_set("ft_ckpt_store_bytes", resident)
    if _trace.enabled():
        _trace.instant("ft.ckpt.commit", cat="ft", epoch=epoch,
                       resident=resident)


# --------------------------------------------------------------- restore
def committed_epoch() -> int:
    return _store.committed


def next_epoch() -> int:
    return _store.next_epoch


def my_state(epoch: Optional[int] = None) -> Optional[Dict[str, np.ndarray]]:
    """This rank's own committed blob, decoded (the survivor-side
    rollback in recover); None when nothing is committed."""
    with _lock:
        e = _store.committed if epoch is None else int(epoch)
        blob = _store.own.get(e)
    if blob is None:
        return None
    t0 = time.monotonic()
    state = decode_state(blob)
    _counts["restores_mem"] += 1
    _emit("ft", "ckpt_restore", epoch=e, source="own")
    if _metrics._enable_var._value:
        _metrics.observe("ft_ckpt_restore_us",
                         (time.monotonic() - t0) * 1e6, source="own")
    return state


def replica_blob(owner: int, epoch: int) -> Optional[bytes]:
    with _lock:
        return _store.replicas.get((int(epoch), int(owner)))


def replica_epochs(owner: int) -> List[int]:
    """Every committed epoch this rank holds ``owner``'s replica for —
    the recovery planner keys on min(survivor committed epochs), which
    can trail MY committed epoch by one when a commit vote was torn by
    a concurrent revocation, so capabilities must cover the whole keep
    window, not just the newest epoch."""
    with _lock:
        return sorted(e for (e, o) in _store.replicas
                      if o == int(owner))


def parity_epochs() -> List[int]:
    """Committed epochs with a retained parity block (same keep-window
    rationale as replica_epochs)."""
    with _lock:
        return sorted(_store.parity)


def own_epochs() -> List[int]:
    """Committed epochs whose OWN blob is still held — a parity rebuild
    needs every surviving group member's own blob at the plan epoch, so
    the planner must see each helper's retention, not just the
    coordinator's parity block."""
    with _lock:
        return sorted(_store.own)


def note_replica_restore() -> None:
    """Count a buddy-replica restore (the recovery driver decodes the
    blob itself after shipping it to the newcomer)."""
    _counts["restores_mem"] += 1
    _emit("ft", "ckpt_restore", source="replica")


def parity_info(epoch: int) -> Optional[Tuple[bytes, Dict[int, int]]]:
    with _lock:
        return _store.parity.get(int(epoch))


def own_blob(epoch: int) -> Optional[bytes]:
    with _lock:
        return _store.own.get(int(epoch))


def final_blob(owner: int) -> Optional[Tuple[bytes, dict]]:
    with _lock:
        return _store.final.get(int(owner))


def rollback_to(epoch: int) -> None:
    """Re-align the epoch clock after a recovery: the next save() on
    every member (survivor or respawned newcomer) must stamp the same
    epoch number or receipts would never match their waits."""
    with _lock:
        _store.next_epoch = int(epoch) + 1
        _store.committed = min(_store.committed, int(epoch))
        _store.staged_own.clear()
        _store.staged_replicas.clear()
        _store.staged_parity.clear()
        _store.final.clear()


def seed_own(epoch: int, blob: bytes) -> None:
    """Install a restored blob as the newcomer's own committed copy so
    it can serve a future recovery as a survivor."""
    with _lock:
        _store.own[int(epoch)] = bytes(blob)
        _store.committed = max(_store.committed, int(epoch))


# ------------------------------------------------------- preemption flush
def flush_final(grace_s: float) -> int:
    """Preemption-notice hook (registered with ft/inject.on_preempt):
    serialize the provider's state and ship ONE final single-owner blob
    to this rank's buddies, then drive progress for the remainder of
    the grace window so the frames reach the wire before death.
    Returns the number of blobs shipped (0 = disabled/no provider)."""
    if not _enable_var._value:
        return 0
    from ompi_tpu.runtime.progress import progress_until

    prov = _provider
    comm = _comm_ref() if _comm_ref is not None else None
    if prov is None or comm is None:
        return 0
    try:
        blob = encode_state(prov())
    except Exception:
        log.warning("preempt flush: state provider failed", exc_info=True)
        return 0
    me, n = comm.Get_rank(), comm.Get_size()
    targets = buddies(me, n)
    # parity mode can't recompute a group XOR inside the grace window —
    # the final flush always buddy-ships (documented asymmetry)
    if not targets and n > 1:
        targets = buddies(me, n, k=1)
    with _lock:
        epoch = _store.next_epoch
    for p in targets:
        _ship(comm.pml, comm.group.world_rank(p), "final", epoch, me, blob)
    if targets:
        _counts["bytes"] += len(blob) * len(targets)
    _emit("ft", "ckpt_preempt_flush", epoch=epoch, nbytes=len(blob),
          targets=len(targets))
    if _trace.enabled():
        _trace.instant("ft.ckpt.preempt_flush", cat="ft", epoch=epoch,
                       nbytes=len(blob))
    log.warning("preemption flush: %d bytes to buddies %s (grace %.0fms)",
                len(blob), targets, grace_s * 1000)
    # drain: the frames are queued on the transport; progress pushes
    # them out. The rank dies right after, so burning the window is fine.
    progress_until(lambda: False, timeout=max(float(grace_s), 0.05))
    return len(targets)


def reset_for_testing() -> None:
    global _store, _provider, _comm_ref
    with _lock:
        _store = _Store()
    _provider = None
    _comm_ref = None
    for k in _counts:
        _counts[k] = 0
    _plane.reset()


# preemption notice + early handler binding
from ompi_tpu.ft import inject as _inject  # noqa: E402
from ompi_tpu.hook import register_hook  # noqa: E402

_inject.on_preempt(flush_final)
register_hook("init_bottom", _bind_world_handler)
