"""Failure detector.

Reference: ompi/communicator/ft/comm_ft_detector.c (728 LoC) — a ring
heartbeat: each process observes its ring predecessor; a missed-heartbeat
timeout marks the peer failed and the propagator broadcasts the failure.
Process mode runs the heartbeat over the btl (started by wireup when
``ft_enable`` is set); mesh mode has a single controller, so failure
handling reduces to XLA/PJRT error propagation.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Set

from ompi_tpu.mca.var import register_var, get_var
from ompi_tpu.utils.output import get_logger

register_var("ft", "enable", False,
             help="Enable the ULFM heartbeat failure detector", level=3)
register_var("ft", "heartbeat_period", 0.2,
             help="Seconds between heartbeats (reference: the detector's "
                  "period MCA var)", level=6)
register_var("ft", "heartbeat_timeout", 2.0,
             help="Seconds without heartbeat before declaring failure",
             level=6)

HEARTBEAT_TAG = -4243
FAILURE_PROP_TAG = -4245

_failed: Set[int] = set()
_failed_lock = threading.Lock()
_callbacks: List[Callable[[int], None]] = []
_propagator: Optional[Callable[[int], None]] = None
_log = get_logger("ft.detector")
_live_hb = [None]  # weakref to the running HeartbeatDetector, if any

# degrade/restore edge journal: how the link was PERFORMING when it
# died/healed (btl/tcp passes its linkmodel snapshot at the edge) —
# forensics debug_state + the mpidiag LINK verdict read it. Bounded;
# one entry per state EDGE (the link timer re-notes every tick while
# an outage is open, which must not flood the journal).
_link_events: deque = deque(maxlen=32)
_link_state: Dict[int, str] = {}  # rank -> "degraded" | "restored"


def _fx_debug_state() -> dict:
    """Stall-forensics provider (runtime/forensics contract): the
    confirmed-failure set plus the ring observer's suspicion state —
    who this rank watches, how stale that edge is vs the timeout."""
    out: dict = {"known_failed": sorted(known_failed())}
    ref = _live_hb[0]
    det = ref() if ref is not None else None
    if det is not None:
        age = time.monotonic() - det.last_seen
        timeout = float(get_var("ft", "heartbeat_timeout"))
        out["heartbeat"] = {
            "rank": det.rank, "observed": det.observed,
            "target": det.target,
            "last_seen_age_s": round(age, 3),
            "timeout_s": timeout,
            "suspect": bool(det.observed != det.rank
                            and age > timeout / 2.0),
        }
    with _failed_lock:
        events = list(_link_events)
    if events:
        now = time.monotonic()
        out["link_events"] = [
            {"rank": ev["rank"], "event": ev["event"],
             "age_s": round(now - ev["t"], 3), "link": ev["link"]}
            for ev in events]
    return out


def known_failed() -> Set[int]:
    with _failed_lock:
        return set(_failed)


def set_propagator(fn: Callable[[int], None]) -> None:
    """Install the failure-notice flood (reference: the reliable
    broadcast of comm_ft_propagator.c). Detection is local — the ring
    observer and a tcp EOF each see a death from one vantage point; the
    flood re-forwards every *newly learned* failure to all peers, so any
    connected component of live ranks converges (dedup = the _failed
    set)."""
    global _propagator
    _propagator = fn


def mark_failed(rank: int) -> None:
    with _failed_lock:
        if rank in _failed:
            return
        _failed.add(rank)
    _log.warning("rank %d declared FAILED", rank)
    from ompi_tpu.mpit import emit  # MPI_T event (mpit.py)

    emit("ft", "proc_failed", rank=rank)
    if _propagator is not None:
        try:
            _propagator(rank)
        except Exception:
            _log.warning("failure propagation failed", exc_info=True)
    for cb in list(_callbacks):
        cb(rank)


def on_failure(cb: Callable[[int], None]) -> None:
    """Register a failure observer (reference: the PMIx event handlers
    registered at instance.c init)."""
    _callbacks.append(cb)  # mpiracer: disable=cross-thread-race — GIL-atomic append at registration time; mark_failed iterates a list() snapshot


def _note_link_event(rank: int, event: str, link: Optional[dict]) -> None:
    """Journal one degrade/restore state EDGE with the link's last
    performance snapshot (dedup: the tick-driven re-notes of an open
    outage don't re-journal)."""
    with _failed_lock:
        if _link_state.get(rank) == event:
            if link is not None:
                # a tick-driven re-note raced ahead of the entry call
                # that carries the snapshot: backfill it
                for ev in reversed(_link_events):
                    if ev["rank"] == rank and ev["event"] == event:
                        if ev["link"] is None:
                            ev["link"] = link
                        break
            return
        _link_state[rank] = event
        _link_events.append({"t": time.monotonic(), "rank": rank,
                             "event": event, "link": link})


def note_link_degraded(rank: int, link: Optional[dict] = None) -> None:
    """Link-reliability grace seam (btl/tcp LINK_DEGRADED): while the
    tcp link layer is inside its bounded redial window for ``rank``,
    the heartbeat silence the outage itself causes must not convert
    into a confirmed death — refresh the observed edge's clock so the
    ring observer charges staleness from NOW, not from before the
    blip. Called at degrade entry and on every link-timer tick while
    the window is open, so a long redial keeps its grace; the link
    layer's own escalation (budget blown -> mark_failed) keeps death
    detection bounded by btl_tcp_link_deadline_s. ``link`` (degrade
    entry only) is the edge's last linkmodel snapshot — srtt/goodput/
    loss at the moment the wire died — journaled for forensics and
    the mpidiag LINK verdict."""
    _note_link_event(rank, "degraded", link)
    ref = _live_hb[0]
    det = ref() if ref is not None else None
    if det is not None and det.observed == rank:
        det.last_seen = time.monotonic()


def note_link_restored(rank: int, link: Optional[dict] = None) -> None:
    """Link healed (resync complete): reset the observed edge's
    staleness so the outage tail is not charged against the next
    heartbeat-timeout window, and journal how the healed link is
    performing."""
    _note_link_event(rank, "restored", link)
    ref = _live_hb[0]
    det = ref() if ref is not None else None
    if det is not None and det.observed == rank:
        det.last_seen = time.monotonic()


class HeartbeatDetector:
    """Ring heartbeat: rank r observes (r-1) mod n and pings (r+1) mod n
    (reference topology: comm_ft_detector.c ring observation)."""

    def __init__(self, pml, my_rank: int, size: int):
        self.pml = pml
        self.rank = my_rank
        self.size = size
        self.observed = (my_rank - 1) % size
        self.target = (my_rank + 1) % size
        self.last_seen = time.monotonic()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        if self.size < 2:
            return
        import weakref

        _live_hb[0] = weakref.ref(self)  # forensics suspicion view
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="ompi-tpu-ft-detector")
        self._thread.start()

    def note_heartbeat(self, src: int) -> None:
        if src == self.observed:
            self.last_seen = time.monotonic()

    def _run(self) -> None:
        import numpy as np
        from ompi_tpu.core.datatype import INT64

        period = get_var("ft", "heartbeat_period")
        timeout = get_var("ft", "heartbeat_timeout")
        beat = np.array([self.rank], dtype=np.int64)
        while not self._stop.is_set():
            # heal the TARGET side too: when my successor dies, the next
            # living successor must start receiving my heartbeats, or it
            # will falsely declare ME dead once it heals its observer
            # edge toward me (reference: the detector rebuilds both ring
            # edges, comm_ft_detector.c)
            failed = known_failed()
            while self.target in failed and self.target != self.rank:
                self.target = (self.target + 1) % self.size
            try:
                self.pml.isend(beat, 1, INT64, self.target,
                               HEARTBEAT_TAG, 0)
            except Exception:
                pass
            if (self.observed != self.rank
                    and time.monotonic() - self.last_seen > timeout):
                mark_failed(self.observed)
                # re-route around the failure (ring heals: observe next
                # living predecessor — reference: detector ring repair)
                nxt = (self.observed - 1) % self.size
                while nxt in known_failed() and nxt != self.rank:
                    nxt = (nxt - 1) % self.size
                self.observed = nxt
                self.last_seen = time.monotonic()
            self._stop.wait(period)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=1.0)


def _reset_for_testing() -> None:
    with _failed_lock:
        _failed.clear()
        _link_events.clear()
        _link_state.clear()
    _callbacks.clear()


from ompi_tpu.runtime import forensics as _forensics  # noqa: E402

_forensics.register_provider("ft.detector", _fx_debug_state)
