"""Block-scaled quantization codecs with closed-form error bounds.

EQuARX-style block scaling (arxiv 2506.17615): a float vector is cut
into blocks of ``block`` elements; each block carries one f32 scale
derived from its amax, and the elements ride the wire as int8, packed
int4, or float8_e4m3fn. Rounding is deterministic round-to-nearest-even
(``np.rint`` / the IEEE cast), so a fixed (world, block, bits, mode)
config reproduces bitwise.

Wire layout of one encoded vector of ``n`` elements::

    [nblocks * f32 little-endian scales][quantized payload]

``nblocks = ceil(n / block)``; the payload is ``n`` bytes (int8/fp8) or
``ceil(n/2)`` bytes (int4 nibbles, low nibble first).

Non-finite blocks (amax inf or nan — the adversarial inputs the test
sweep feeds) are carried losslessly in *shape*: the block's scale is the
``+inf`` sentinel and the code points encode {+inf, -inf, nan, other}.
Finite values inside such a block decode to 0 — legal, because the
error bound for that block is infinite.

Closed-form worst-case error (the ``error_bound`` contract): one
quantize/dequantize round trip of a block with amax ``A`` errs at most
``A * eps`` per element, with ``eps`` = 1/254 (int8, half a step of
amax/127), 1/14 (int4), 2**-4 (fp8 e4m3: 3 mantissa bits after the
amax -> 224 scaling keeps everything in the normal range). The
quantized allreduce quantizes every rank's contribution once (error
<= S * eps, S = sum over ranks of the block amax) and requantizes the
reduced block once more (its amax <= S * (1 + eps)), so::

    |allreduce_quant - allreduce_exact|  <=  S * eps * (2 + eps) + slack

where ``slack = S * 4 * (W + 2) * finfo(out_dtype).eps`` covers f32
scale storage, the W-term dequant-sum rounding, and the final cast back
to the caller's dtype (dominant only for f16 outputs, where it is the
honest cast cost). Block scales are clamped to >= f32 tiny (a
tiny-denormal amax would underflow ``amax/divisor`` to 0), so ``A`` and
``S`` in the bound are really ``max(amax, tiny * divisor)`` — the
clamped scale's own rounding step. Symmetrically, a float64 block whose
amax exceeds ``f32max * divisor`` cannot ship its scale in f32: encode
clamps the scale to f32max (values saturate near ``qmax * f32max``
instead of the inf-scale sentinel silently zeroing the block) and
``error_bound`` is infinite there — no finite guarantee.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

__all__ = ["BlockCodec", "make_codec", "chunk_layout"]


def chunk_layout(count: int, world: int, block: int) -> Tuple[int, int]:
    """(per, padded): the canonical chunking shared by the procmode and
    mesh schedules AND by the error bound — ``count`` elements pad up to
    ``padded = per * world`` with ``per`` a multiple of ``block``; chunk
    ``j`` (destined for rank ``j``) is ``padded[j*per:(j+1)*per]``."""
    per = -(-max(count, 1) // world)
    per = -(-per // block) * block
    return per, per * world


def _work_dtype(dtype) -> np.dtype:
    return np.dtype(np.float64 if np.dtype(dtype) == np.float64
                    else np.float32)


class BlockCodec:
    """One (mode, bits, block) codec instance. ``mode`` is ``int8`` or
    ``fp8``; ``bits`` is 8, or 4 for packed-nibble int quantization."""

    def __init__(self, mode: str = "int8", bits: int = 8, block: int = 64):
        if mode not in ("int8", "fp8"):
            raise ValueError(f"unknown quant mode {mode!r}")
        if bits not in (8, 4):
            raise ValueError(f"unsupported quant bits {bits}")
        if mode == "fp8":
            if bits != 8:
                raise ValueError("fp8 requires bits=8")
            import ml_dtypes  # jax dependency; gate, never pip install

            self._f8 = np.dtype(ml_dtypes.float8_e4m3fn)
        if block < 1:
            raise ValueError(f"quant block must be >= 1, got {block}")
        self.mode = mode
        self.bits = bits
        self.block = int(block)
        if mode == "fp8":
            self.qmax = 448.0           # e4m3fn finite max (sentinel code)
            self.eps = 2.0 ** -4
        else:
            self.qmax = (1 << (bits - 1)) - 1   # 127 / 7
            self.eps = 0.5 / self.qmax
        # fp8 scaling target: amax -> 224 keeps every rounded value in
        # the normal range (< 448), so the relative-eps bound holds
        self._fp8_target = 224.0
        # encode clamps the block scale to >= f32 tiny (a tiny-denormal
        # amax underflows amax/divisor to 0); below this amax the error
        # is governed by the clamped scale, so the bound uses
        # max(amax, _amax_floor) — _amax_floor * eps == the clamped
        # scale's worst rounding error
        divisor = self._fp8_target if mode == "fp8" else self.qmax
        self._amax_floor = float(np.finfo(np.float32).tiny) * divisor
        # scales ship as f32 on the wire: a float64 block whose amax
        # exceeds f32max * divisor cannot be represented — encode clamps
        # the scale to f32max (values saturate at ~qmax * f32max instead
        # of the inf-scale SENTINEL misread silently zeroing the block)
        # and error_bound reports inf for such blocks (no guarantee)
        self._amax_ceiling = float(np.finfo(np.float32).max) * divisor

    # ------------------------------------------------------------ sizing
    def nblocks(self, n: int) -> int:
        return -(-n // self.block)

    def payload_nbytes(self, n: int) -> int:
        return -(-n // 2) if self.bits == 4 else n

    def wire_nbytes(self, n: int) -> int:
        """Encoded size of an n-element vector (scales + payload)."""
        return 4 * self.nblocks(n) + self.payload_nbytes(n)

    def ratio(self, n: int, itemsize: int = 4) -> float:
        """Full-precision bytes / quantized wire bytes."""
        return (n * itemsize) / self.wire_nbytes(n)

    # ---------------------------------------------------------- encoding
    def _blocks(self, x: np.ndarray) -> np.ndarray:
        n = x.size
        nb = self.nblocks(n)
        padded = np.zeros(nb * self.block, dtype=_work_dtype(x.dtype))
        padded[:n] = np.asarray(x, dtype=padded.dtype).reshape(-1)
        return padded.reshape(nb, self.block)

    def encode(self, x: np.ndarray) -> np.ndarray:
        """Quantize a 1-D float vector into one contiguous uint8 wire
        payload (deterministic round-to-nearest-even)."""
        blocks = self._blocks(x)
        nb = blocks.shape[0]
        amax = np.max(np.abs(blocks), axis=1)  # nan propagates
        finite = np.isfinite(amax)
        scale = np.ones(nb, dtype=np.float32)
        # over=: the f64-amax-past-f32-range divide overflows to inf BY
        # DESIGN (clamped to f32max right below) — the warning would
        # spam stderr per encode and raise under warnings-as-errors
        with np.errstate(divide="ignore", invalid="ignore",
                         over="ignore"):
            if self.mode == "fp8":
                np.divide(amax, self._fp8_target, out=scale,
                          where=finite & (amax > 0), casting="unsafe")
            else:
                np.divide(amax, self.qmax, out=scale,
                          where=finite & (amax > 0), casting="unsafe")
        # clamp to the smallest NORMAL f32: a tiny-denormal amax
        # underflows amax/qmax to exactly 0 (div-by-zero in the encode
        # below, block decodes to 0 with a 0 bound), and a subnormal
        # scale's rounding error alone can exceed amax*eps — the clamp
        # keeps the divide finite and error_bound carries the matching
        # additive tiny term
        np.maximum(scale, np.finfo(np.float32).tiny, out=scale,
                   where=finite & (amax > 0))
        # f64 blocks with amax > f32max * divisor overflow the f32
        # divide to inf — which decode would misread as the non-finite
        # sentinel and zero the block; clamp to f32max (saturating the
        # values, bound reports inf there)
        np.minimum(scale, np.finfo(np.float32).max, out=scale,
                   where=finite & (amax > 0))
        scale[~finite] = np.inf  # sentinel: block carries non-finite data

        if self.mode == "fp8":
            q = np.zeros(blocks.shape, dtype=self._f8)
            if finite.any():
                t = blocks[finite] / scale[finite, None]
                q[finite] = t.astype(self._f8)  # IEEE RTE cast
            if not finite.all():
                xb = blocks[~finite]
                qb = np.zeros(xb.shape, dtype=self._f8)
                qb[xb == np.inf] = self.qmax      # 448 = +inf code point
                qb[xb == -np.inf] = -self.qmax
                qb[np.isnan(xb)] = np.nan
                q[~finite] = qb
            payload = np.ascontiguousarray(q).view(np.uint8).reshape(-1)
        else:
            q = np.zeros(blocks.shape, dtype=np.int8)
            if finite.any():
                t = blocks[finite] / scale[finite, None]
                q[finite] = np.clip(np.rint(t), -self.qmax,
                                    self.qmax).astype(np.int8)
            if not finite.all():
                xb = blocks[~finite]
                qb = np.zeros(xb.shape, dtype=np.int8)
                qb[xb == np.inf] = int(self.qmax)
                qb[xb == -np.inf] = -int(self.qmax)
                qb[np.isnan(xb)] = -int(self.qmax) - 1  # nan code point
                q[~finite] = qb
            flat = q.reshape(-1)[: x.size] if self.bits == 4 else q
            if self.bits == 4:
                nibbles = (flat.astype(np.int16) + 8).astype(np.uint8)
                if nibbles.size % 2:
                    nibbles = np.concatenate(
                        [nibbles, np.full(1, 8, np.uint8)])
                pairs = nibbles.reshape(-1, 2)
                payload = (pairs[:, 0] | (pairs[:, 1] << 4)).astype(np.uint8)
            else:
                payload = np.ascontiguousarray(q).view(np.uint8).reshape(-1)
        payload = payload[: self.payload_nbytes(x.size)] \
            if self.bits == 4 else payload[: x.size]
        out = np.empty(self.wire_nbytes(x.size), dtype=np.uint8)
        out[: 4 * nb] = scale.astype("<f4").view(np.uint8)
        out[4 * nb:] = payload
        return out

    def decode(self, payload: np.ndarray, n: int,
               dtype=np.float32) -> np.ndarray:
        """Dequantize ``n`` elements from one wire payload into the work
        dtype for ``dtype`` (f64 in, f64 math; everything else f32)."""
        nb = self.nblocks(n)
        raw = np.frombuffer(bytes(payload), dtype=np.uint8)
        scale = raw[: 4 * nb].view("<f4").astype(np.float32)
        body = raw[4 * nb: 4 * nb + self.payload_nbytes(n)]
        wdt = _work_dtype(dtype)
        if self.mode == "fp8":
            q = body.view(self._f8).astype(wdt)
        elif self.bits == 4:
            lo = (body & 0x0F).astype(np.int16) - 8
            hi = (body >> 4).astype(np.int16) - 8
            q = np.empty(body.size * 2, dtype=np.int16)
            q[0::2] = lo
            q[1::2] = hi
            q = q[:n].astype(wdt)
        else:
            q = body.view(np.int8).astype(wdt)
        q = q[:n]
        pad = nb * self.block - n
        if pad:
            q = np.concatenate([q, np.zeros(pad, dtype=wdt)])
        blocks = q.reshape(nb, self.block)
        bad = np.isinf(scale)
        with np.errstate(invalid="ignore"):
            out = blocks * scale[:, None].astype(wdt)
        if bad.any():
            qb = blocks[bad]
            ob = np.zeros(qb.shape, dtype=wdt)
            if self.mode == "fp8":
                ob[qb == self.qmax] = np.inf
                ob[qb == -self.qmax] = -np.inf
                ob[np.isnan(qb)] = np.nan
            else:
                ob[qb == self.qmax] = np.inf
                ob[qb == -self.qmax] = -np.inf
                ob[qb == -self.qmax - 1] = np.nan
            out[bad] = ob
        return out.reshape(-1)[:n]

    # ------------------------------------------------------ error bounds
    def _slack(self, world: int, out_dtype) -> float:
        return 4.0 * (world + 2) * float(np.finfo(np.dtype(out_dtype)).eps)

    def error_bound(self, x: np.ndarray, out_dtype=None) -> np.ndarray:
        """Closed-form worst-case absolute error, per element.

        - 1-D ``x``: one encode/decode round trip of ``x`` —
          ``bound = A' * (eps + slack)`` with ``A'`` the element's block
          amax floored at ``_amax_floor`` (the encode-side scale clamp:
          tiny-denormal blocks err by the clamped scale's rounding step,
          not by ``A * eps``).
        - 2-D ``x`` of shape [world, n] (the stacked per-rank
          contributions): the full quantized allreduce —
          ``bound = S' * (eps * (2 + eps) + slack)`` with ``S'`` the sum
          over ranks of the floored block amax under the allreduce's
          ``chunk_layout`` chunking. Non-finite blocks get an infinite
          bound (they are carried as sentinels, not values). All bound
          math runs in f64 so the bound itself cannot underflow.
        """
        x = np.asarray(x)
        od = np.dtype(out_dtype) if out_dtype is not None else \
            (x.dtype if x.dtype.kind == "f" else np.dtype(np.float32))
        if x.ndim == 1:
            blocks = self._blocks(x)
            amax = np.max(np.abs(blocks), axis=1).astype(np.float64)
            eff = np.where(amax > 0,
                           np.maximum(amax, self._amax_floor), 0.0)
            bound = eff * (self.eps + self._slack(1, od))
            # beyond the f32-representable scale range the encode
            # saturates — no finite guarantee
            bound = np.where(np.isfinite(amax)
                             & (eff <= self._amax_ceiling), bound, np.inf)
            per_el = np.repeat(bound, self.block)[: x.size]
            return per_el.astype(np.float64)
        if x.ndim != 2:
            raise ValueError("error_bound wants a vector or a "
                             "[world, n] stack")
        world, n = x.shape
        per, padded = chunk_layout(n, world, self.block)
        a = np.zeros((world, padded), dtype=np.float64)
        a[:, :n] = np.abs(x.astype(np.float64, copy=False))
        # [world(src), world(chunk), blocks/chunk]
        amax = a.reshape(world, world, per // self.block,
                         self.block).max(axis=-1)
        eff = np.where(amax > 0, np.maximum(amax, self._amax_floor), 0.0)
        S = eff.sum(axis=0)  # per (chunk, block), floored amaxes
        bound = S * (self.eps * (2.0 + self.eps) + self._slack(world, od))
        # S bounds the reduced block's amax too (the requantize step):
        # past the f32 scale ceiling either encode saturates — inf bound
        bound = np.where(np.isfinite(amax.sum(axis=0))
                         & (S <= self._amax_ceiling), bound, np.inf)
        return np.repeat(bound.reshape(-1), self.block)[:n]

    # --------------------------------------------------------- reference
    def reduce_encoded(self, encoded, per: int, dtype=np.float32):
        """Sum decoded chunks in ascending-rank order (THE canonical
        accumulation order — procmode ranks and the offline simulator
        share it so results agree bitwise)."""
        acc = self.decode(encoded[0], per, dtype)
        # invalid=: sentinel blocks legitimately reduce inf + (-inf) ->
        # nan (the adversarial sweep's contract); the warning would
        # raise under warnings-as-errors embedders
        with np.errstate(invalid="ignore"):
            for e in encoded[1:]:
                acc = acc + self.decode(e, per, dtype)
        return acc

    def simulate_allreduce(self, xs: np.ndarray) -> np.ndarray:
        """Offline oracle of the quantized allreduce: quantize every
        rank's chunk, reduce in rank order, requantize, dequantize —
        exactly the wire schedule, bitwise (tests + tools/quantreport)."""
        xs = np.asarray(xs)
        world, n = xs.shape
        per, padded = chunk_layout(n, world, self.block)
        wdt = _work_dtype(xs.dtype)
        buf = np.zeros((world, padded), dtype=wdt)
        buf[:, :n] = xs
        out = np.empty(padded, dtype=wdt)
        for c in range(world):
            enc = [self.encode(buf[r, c * per:(c + 1) * per])
                   for r in range(world)]
            red = self.reduce_encoded(enc, per, wdt)
            out[c * per:(c + 1) * per] = self.decode(
                self.encode(red), per, wdt)
        return out[:n].astype(xs.dtype if xs.dtype.kind == "f" else wdt)


def make_codec(mode: str, bits: int, block: int) -> BlockCodec:
    return BlockCodec(mode, bits, block)
