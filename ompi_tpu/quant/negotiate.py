"""Per-communicator quant codec negotiation over the modex card plane.

The torn-collective hazard: if the quantized module were selected from
each rank's LOCAL cvars, a rank launched with ``quant_enable`` unset
would run the tuned schedule while its peers run the quantized one —
mismatched tags, permanent hang. The reference fix is the same one the
btl endpoints use: publish config as a modex business card during
wireup (before the first fence), so by the time any communicator is
built every rank holds every member's card and the verdict is a pure
local computation over SHARED data. All ranks reach the same decision:
quantize, fall back to full precision, or (``quant_strict``) raise the
same error on every rank's quant-eligible collectives.

Mesh mode is single-controller — there is nobody to disagree with — so
its verdict reads the local cvars directly.
"""

from __future__ import annotations

import dataclasses
import json
import threading
from typing import Dict, List, Optional

from ompi_tpu.quant import (
    _bits_var,
    _block_var,
    _enable_var,
    _min_bytes_var,
    _mode_var,
    _strict_var,
)
from ompi_tpu.utils.show_help import register_topic, show_help

register_topic(
    "quant", "negotiate-fallback",
    "Quantized collectives requested but not negotiated on "
    "communicator '%(comm)s': %(reason)s.\n"
    "All members fell back to full precision together (set "
    "quant_strict to turn this into an error). Every rank must "
    "launch with quant_enable set and matching quant_bits / "
    "quant_block / quant_mode for the quantized path to engage.")
register_topic(
    "quant", "codec-unavailable",
    "The negotiated quant codec (%(mode)s/%(bits)s) is unavailable "
    "on this build: %(err)s. Falling back to full precision.")


@dataclasses.dataclass(frozen=True)
class QuantState:
    """The per-communicator verdict (identical on every member)."""

    active: bool
    bits: int = 8
    block: int = 64
    mode: str = "int8"
    min_bytes: int = 65536
    strict: bool = False
    reason: str = ""

    _codec_cache: dict = dataclasses.field(default_factory=dict,
                                           compare=False, repr=False)

    @property
    def codec(self):
        c = self._codec_cache.get("c")
        if c is None:
            from ompi_tpu.quant.codec import make_codec

            c = make_codec(self.mode, self.bits, self.block)
            self._codec_cache["c"] = c
        return c


INACTIVE = QuantState(active=False, reason="quant_enable unset")


def _fp8_available() -> int:
    try:
        import ml_dtypes  # noqa: F401  (jax dependency; may be absent)
    except ImportError:
        return 0
    return 1


def local_card() -> Dict[str, int]:
    """This rank's negotiation card, straight off the cvars (read at
    wireup — later set_var calls do not re-publish; per-job config is
    launch-time config, like every other modex card). Codec
    AVAILABILITY rides the card too: probing ml_dtypes locally inside
    decide() would let heterogeneous builds reach opposite verdicts —
    the torn-collective hazard this plane exists to prevent."""
    return {
        "enable": int(bool(_enable_var._value)),
        "bits": int(_bits_var._value),
        "block": int(_block_var._value),
        "mode": str(_mode_var._value),
        "min_bytes": int(_min_bytes_var._value),
        "strict": int(bool(_strict_var._value)),
        "fp8_ok": _fp8_available(),
    }


def card_json() -> str:
    return json.dumps(local_card())


CARD_KEY = "quant.card"

_card_lock = threading.Lock()
_card_cache: Dict[int, Dict] = {}


def _member_card(modex, world_rank: int) -> Dict:
    with _card_lock:
        c = _card_cache.get(world_rank)
    if c is not None:
        return c
    try:
        # cards are published before the publisher's first fence, and a
        # comm can only contain ranks whose init (hence card put) has
        # completed — post-fence a missing card will never appear, so
        # don't wait (the wireup.py sm-card discipline); a 10s poll here
        # would stall coll selection per card-less cross-job member
        c = json.loads(modex.get(world_rank, CARD_KEY, timeout=0.0))
    except TimeoutError:
        # a peer without a card (pre-quant build) negotiates as
        # disabled — the conservative verdict every rank reaches
        # identically, because the key is symmetrically absent for all.
        # Anything OTHER than key-absent (a transport hiccup, a broken
        # card) must propagate: silently mapping it to disabled would
        # let ONE rank's hiccup split the verdict — the torn-collective
        # hazard this plane exists to prevent — so fail loudly instead
        c = {"enable": 0, "_missing": True}
    with _card_lock:
        _card_cache[world_rank] = c
    return c


def invalidate_cards() -> None:
    """Drop every cached member card. Recovery calls this whenever
    world membership changes (shrink/respawn): a respawned replacement
    re-publishes its card under the dead predecessor's world rank, and
    a survivor serving the stale cached card would negotiate a
    different verdict than the ranks reading fresh."""
    with _card_lock:
        _card_cache.clear()


def decide(cards: List[Dict]) -> QuantState:
    """Pure verdict over the member cards — every rank feeds the same
    cards in the same (comm-rank) order and lands on the same state."""
    if not cards:
        return INACTIVE
    # inactive verdicts still carry the ENABLED members' negotiated
    # floor: a strict-armed state gates _check_armed through _eligible,
    # and reverting to the dataclass default 65536 would silently no-op
    # quant_strict for every payload between the configured floor and
    # 64 KiB (symmetric — a pure function of the shared cards)
    def _floor() -> int:
        return max((int(c.get("min_bytes", 65536))
                    for c in cards if c.get("enable")), default=65536)

    if not all(c.get("enable") for c in cards):
        off = sum(1 for c in cards if not c.get("enable"))
        reason = f"{off}/{len(cards)} member rank(s) have " \
                 "quant_enable unset"
        strict = any(c.get("enable") and c.get("strict") for c in cards)
        wanted = any(c.get("enable") for c in cards)
        return QuantState(active=False, strict=strict and wanted,
                          min_bytes=_floor(), reason=reason)
    configs = {(int(c["bits"]), int(c["block"]), str(c["mode"]))
               for c in cards}
    strict = any(c.get("strict") for c in cards)
    if len(configs) != 1:
        return QuantState(
            active=False, strict=strict, min_bytes=_floor(),
            reason="mismatched quant config across members: "
                   + ", ".join(f"bits={b}/block={k}/mode={m}"
                               for b, k, m in sorted(configs)))
    bits, block, mode = next(iter(configs))
    if mode == "fp8" and bits != 8:
        return QuantState(active=False, strict=strict,
                          min_bytes=_floor(),
                          reason="fp8 requires quant_bits=8")
    if mode == "fp8" and not all(c.get("fp8_ok") for c in cards):
        # availability comes from the SHARED cards, never a local
        # probe: one build without ml_dtypes must flip every rank to
        # the same fallback, not just itself
        off = sum(1 for c in cards if not c.get("fp8_ok"))
        return QuantState(
            active=False, strict=strict, min_bytes=_floor(),
            reason=f"fp8 codec unavailable on {off}/{len(cards)} "
                   "member build(s) (ml_dtypes missing)")
    # symmetric threshold: the LARGEST requested floor wins, so no rank
    # quantizes a message a peer expected at full precision
    min_bytes = max(int(c["min_bytes"]) for c in cards)
    st = QuantState(active=True, bits=bits, block=block, mode=mode,
                    min_bytes=min_bytes, strict=strict)
    try:
        st.codec  # validate availability (fp8 needs ml_dtypes)
    except Exception as e:
        show_help("quant", "codec-unavailable", mode=mode, bits=bits,
                  err=str(e))
        return QuantState(active=False, strict=strict,
                          min_bytes=min_bytes,
                          reason=f"codec unavailable: {e}")
    return st


_warned = set()


def for_proc_comm(comm) -> QuantState:
    """Negotiate for a process-mode communicator (called once, at coll
    selection time). Reads members' modex cards; never communicates."""
    from ompi_tpu.runtime import wireup

    if comm.size < 2:
        return INACTIVE
    ctx = wireup._ctx
    if ctx is None:
        # no modex plane (unit-test comms): local card only, and only
        # ever size >= 2 via hand-built groups — treat as single-config
        cards = [local_card()] * comm.size
    else:
        modex = ctx["modex"]
        cards = [_member_card(modex, comm.group.world_rank(i))
                 for i in range(comm.size)]
    st = decide(cards)
    if not st.active and not st.strict and \
            any(c.get("enable") for c in cards):
        key = (st.reason,)
        if key not in _warned:
            _warned.add(key)  # mpiracer: disable=cross-thread-race — GIL-atomic dedup for show_help; a racing add at worst prints the fallback banner twice
            show_help("quant", "negotiate-fallback",
                      comm=getattr(comm, "name", "?"), reason=st.reason)
    return st


def for_mesh_comm(comm) -> QuantState:
    """Mesh-mode verdict: single controller, local cvars only. The
    compiled path supports whole-axis comms at 8-bit codecs; anything
    else rides the plain XLA schedule."""
    if not _enable_var._value:
        return INACTIVE
    card = local_card()
    st = decide([card] * max(comm.world_size, 1))
    if st.active and (st.bits != 8 or comm.groups is not None
                      or comm.world_size < 2):
        return QuantState(
            active=False, strict=False,
            reason="mesh quant path needs an 8-bit codec on a "
                   "whole-axis comm with >= 2 devices")
    return st


def _reset_for_testing() -> None:
    with _card_lock:
        _card_cache.clear()
    _warned.clear()
