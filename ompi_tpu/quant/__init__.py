"""Quantized & compressed collectives plane (EQuARX direction).

Reference points: EQuARX (arxiv 2506.17615) reports near-2x XLA
allreduce speedups from block-scaled quantization with negligible
quality loss; HiCCL (arxiv 2408.05962) motivates packaging the
reduced-precision codec as a composable layer the existing coll
selection stack picks per message class rather than a one-off hack.

Pieces:

- :mod:`ompi_tpu.quant.codec` — block-scaled int8/int4/fp8
  quantize/dequantize codecs (per-block amax scaling, deterministic
  round-to-nearest-even) with closed-form worst-case error bounds.
- :mod:`ompi_tpu.quant.negotiate` — per-communicator codec agreement
  over the modex card plane: every rank publishes its quant config
  during wireup, so the verdict is a pure local computation over data
  all ranks share — a rank with ``quant_enable`` unset (or mismatched
  bits/block/mode) makes ALL ranks fall back to full precision (or
  raise cleanly under ``quant_strict``) instead of hanging a torn
  collective.
- :mod:`ompi_tpu.coll.quant` — the coll component lowering quantized
  allreduce / reduce_scatter_block / allgather onto the existing
  sched/p2p machinery (procmode) and onto one compiled XLA program
  (mesh mode, via coll/xla.py's block-scaled body).
- on-wire zlib compression for large tcp rendezvous payloads lives in
  :mod:`ompi_tpu.btl.tcp` (``btl_tcp_compress*`` cvars) and reports
  through this module's wire counters.

This module owns the cvars, the pvar counters, and the two
instrumentation hooks (``note_coll``/``note_wire``) hot code is allowed
to call behind the one-live-Var-load guard discipline (mpilint's
hot-guard rule covers the quant aliases).
"""

from __future__ import annotations

import threading
from typing import Dict

from ompi_tpu.mca.var import register_var, register_pvar

_enable_var = register_var(
    "quant", "enable", False,
    help="Enable block-scaled quantized collectives (allreduce, "
         "reduce_scatter_block, allgather) for float payloads at or "
         "above quant_min_bytes. Negotiated per communicator: every "
         "member must enable with matching bits/block/mode, else all "
         "ranks fall back to full precision together", level=3)
_bits_var = register_var(
    "quant", "bits", 8,
    help="Quantized payload width in bits per element: 8 (int8/fp8) "
         "or 4 (packed int4; int mode only)", level=4,
    enum_values=(8, 4))
_block_var = register_var(
    "quant", "block", 64,
    help="Elements per scaling block (one f32 amax-derived scale is "
         "carried per block; larger blocks compress better, smaller "
         "blocks bound error tighter)", level=4)
_min_bytes_var = register_var(
    "quant", "min_bytes", 65536,
    help="Payload bytes below which quantization is skipped and the "
         "collective rides the full-precision path (quantization "
         "overhead beats the wire saving on small messages)", level=4)
_mode_var = register_var(
    "quant", "mode", "int8",
    help="Codec family: int8 (symmetric round-to-nearest-even "
         "integers) or fp8 (float8_e4m3fn via ml_dtypes)", level=4,
    enum_values=("int8", "fp8"))
_strict_var = register_var(
    "quant", "strict", False,
    help="On negotiation mismatch, raise MPIError on quant-eligible "
         "collectives (symmetrically, on every rank) instead of "
         "silently falling back to full precision", level=5)


def enabled() -> bool:
    """One attribute load off the live Var (spc/trace discipline)."""
    return _enable_var._value


# ------------------------------------------------------------- counters
_lock = threading.Lock()
_counts: Dict[str, int] = {
    "colls": 0,          # quantized collectives executed on this rank
    "bytes_wire": 0,     # quantized payload bytes this rank sent
    "bytes_saved": 0,    # full-precision bytes minus bytes_wire
    "wire_raw": 0,       # tcp-compressed frames: payload bytes pre-zlib
    "wire_comp": 0,      # tcp-compressed frames: payload bytes on wire
    "wire_frames": 0,    # tcp frames that went out compressed
}

register_pvar("quant", "colls", lambda: _counts["colls"],
              help="Collectives that took the quantized path on this "
                   "rank")
register_pvar("quant", "bytes_saved", lambda: _counts["bytes_saved"],
              help="Payload bytes NOT moved thanks to quantization "
                   "(full-precision wire bytes minus quantized wire "
                   "bytes, summed over this rank's sends)")
register_pvar("quant", "bytes_wire", lambda: _counts["bytes_wire"],
              help="Quantized payload bytes this rank actually sent")


def note_coll(verb: str, raw_bytes: int, wire_bytes: int) -> None:
    """One quantized collective finished: ``raw_bytes`` is what the
    full-precision schedule would have sent from this rank,
    ``wire_bytes`` what the quantized schedule sent. Call sites on hot
    paths guard on ``enabled()`` (one live-Var attribute load when the
    plane is off — the spc/trace discipline)."""
    from ompi_tpu.runtime import metrics as _metrics
    from ompi_tpu.runtime import spc

    with _lock:
        _counts["colls"] += 1
        _counts["bytes_wire"] += int(wire_bytes)
        _counts["bytes_saved"] += max(int(raw_bytes) - int(wire_bytes), 0)
    spc.record("quant_" + verb)
    if _metrics._enable_var._value and raw_bytes > 0:
        _metrics.observe("quant_wire_pct", 100.0 * wire_bytes / raw_bytes,
                         verb=verb)


def note_wire(raw_bytes: int, comp_bytes: int) -> None:
    """One tcp frame went out zlib-compressed (btl/tcp.py hook): the
    payload was ``raw_bytes`` and ``comp_bytes`` hit the wire."""
    from ompi_tpu.runtime import metrics as _metrics
    from ompi_tpu.runtime import spc

    with _lock:
        _counts["wire_raw"] += int(raw_bytes)
        _counts["wire_comp"] += int(comp_bytes)
        _counts["wire_frames"] += 1
    spc.record("btl_tcp_compressed_frames")
    if _metrics._enable_var._value and raw_bytes > 0:
        _metrics.observe("btl_tcp_compress_pct",
                         100.0 * comp_bytes / raw_bytes)


def counters() -> Dict[str, int]:
    with _lock:
        return dict(_counts)


def _reset_for_testing() -> None:
    with _lock:
        for k in _counts:
            _counts[k] = 0
