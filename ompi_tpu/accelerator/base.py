"""Accelerator framework — device-memory abstraction.

Reference: opal/mca/accelerator/accelerator.h:671-712 — the module
function table every accelerator component (cuda/rocm/ze/null) implements:
check_addr, mem_alloc/release, mem_copy (sync+async), get_address_range,
IPC handles, host_register, get_device, device_can_access_peer,
get_buffer_id, num_devices, get_mem_bw.

TPU-native redesign: TPUs expose no raw device pointers — device memory is
opaque ``jax.Array`` buffers owned by the runtime. So ``check_addr`` is a
type/registry membership test rather than an address-range lookup, copies
are ``device_put``/``np.asarray`` (which ride PJRT's async streams), and
"IPC" is serialization through host memory (single-controller mesh mode
makes true cross-process device IPC unnecessary: every device is already
addressable from the one controller).
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import numpy as np

from ompi_tpu.mca.component import framework

accelerator_framework = framework(
    "accelerator", "Device memory abstraction (TPU/HBM buffers)"
)


class AcceleratorModule:
    """The module contract (reference: mca_accelerator_base_module_t).

    Flags mirror the reference's transfer-type enum
    (MCA_ACCELERATOR_TRANSFER_{HTOD,DTOH,DTOD}).
    """

    NAME = "base"

    # --- identity / discovery ------------------------------------------
    def check_addr(self, obj: Any) -> bool:
        """Is ``obj`` device memory? (reference: accelerator.h:176 —
        flags out-param collapsed into the bool; TPU buffers are always
        "unified-addressing false, device true")."""
        raise NotImplementedError

    def num_devices(self) -> int:
        """reference: accelerator.h:647"""
        raise NotImplementedError

    def get_device(self, obj: Any) -> int:
        """Device ordinal owning the buffer (reference: get_device)."""
        raise NotImplementedError

    def get_buffer_id(self, obj: Any) -> int:
        """Stable id for a device buffer (reference: get_buffer_id, used
        by the rcache to detect buffer reuse)."""
        raise NotImplementedError

    def device_can_access_peer(self, dev_a: int, dev_b: int) -> bool:
        """reference: device_can_access_peer — on TPU, every chip in the
        slice is ICI-reachable."""
        raise NotImplementedError

    def get_mem_bw(self, device: int = 0) -> float:
        """HBM bandwidth estimate in GB/s (reference: accelerator.h:657,
        used by coll decision layers to weigh staging costs)."""
        raise NotImplementedError

    # --- alloc / copy ---------------------------------------------------
    def mem_alloc(self, nbytes: int, device: int = 0) -> Any:
        """Allocate an uninitialized device buffer of ``nbytes`` bytes
        (reference: mem_alloc, accelerator.h:364)."""
        raise NotImplementedError

    def mem_release(self, obj: Any) -> None:
        """reference: mem_release — jax buffers are GC-owned; explicit
        release is delete()."""
        raise NotImplementedError

    def mem_copy_to_host(self, obj: Any) -> np.ndarray:
        """DTOH copy; blocks until the device value is materialized
        (reference: mem_copy with MCA_ACCELERATOR_TRANSFER_DTOH)."""
        raise NotImplementedError

    def mem_copy_to_device(self, host: np.ndarray,
                           device: Optional[int] = None) -> Any:
        """HTOD copy; async under PJRT, completion on first use
        (reference: mem_copy_async HTOD)."""
        raise NotImplementedError

    def synchronize(self, obj: Any = None) -> None:
        """Fence outstanding async work on a buffer (or all work when
        obj is None). Reference analog: stream/event synchronize
        (accelerator.h:189-258); PJRT's equivalent is
        block_until_ready."""
        raise NotImplementedError

    # --- IPC ------------------------------------------------------------
    def get_ipc_handle(self, obj: Any) -> bytes:
        """Serialize a device buffer so another process can reconstruct
        it (reference: get_ipc_handle, accelerator.h:447). TPU has no
        cross-process device handles; the bytes carry dtype/shape/data
        through host memory."""
        raise NotImplementedError

    def open_ipc_handle(self, handle: bytes) -> Any:
        """Reconstruct a device buffer from a handle (reference:
        open_ipc_handle)."""
        raise NotImplementedError

    # --- host registration ---------------------------------------------
    def host_register(self, host: np.ndarray) -> None:
        """Pin host memory for faster DMA (reference: host_register).
        PJRT manages its own staging; no-op by default."""

    def host_unregister(self, host: np.ndarray) -> None:
        pass


class DeviceBuffer:
    """Receive-side holder for device data.

    jax.Arrays are immutable, so MPI's "recv into this buffer" contract
    cannot mutate one in place. A DeviceBuffer owns a mutable host staging
    array that the PML/collective writes into, and exposes the result as a
    fresh device array — the functional-update idiom XLA expects instead
    of the reference's in-place device writes (accelerator mem_copy DTOD).

    Usage::

        out = DeviceBuffer((4,), jnp.float32)
        comm.Allreduce(jax_send_array, out)
        result = out.array        # jax.Array on device
    """

    def __init__(self, shape_or_array, dtype=None, device: Optional[int] = None):
        if dtype is None and hasattr(shape_or_array, "dtype"):
            # wrap an existing array (device or host) as initial contents
            init = np.asarray(shape_or_array)
            self.host = np.array(init)  # mutable copy
        else:
            shape = (shape_or_array if isinstance(shape_or_array, tuple)
                     else (int(shape_or_array),))
            self.host = np.zeros(shape, dtype=np.dtype(dtype))
        self.device = device
        self._cache: Tuple[int, Any] = (-1, None)
        self._version = 0

    def _mark_dirty(self) -> None:
        self._version += 1  # mpiracer: disable=cross-thread-race — a DeviceBuffer is owned by the dispatching (accelerator) thread; the progress engine never mutates device state

    @property
    def array(self):
        """The current contents as a device array (cached per version)."""
        ver, arr = self._cache
        if ver != self._version or arr is None:
            mod = get_module()
            arr = mod.mem_copy_to_device(self.host, self.device)
            self._cache = (self._version, arr)
        return arr

    def __array__(self, dtype=None):
        return self.host if dtype is None else self.host.astype(dtype)


# ----------------------------------------------------------------- selection
_selected: Optional[AcceleratorModule] = None


def get_module() -> AcceleratorModule:
    """The process-wide accelerator module (reference:
    opal_accelerator_base_module singleton selected at init —
    accelerator_base_select.c)."""
    global _selected
    if _selected is None:
        _, _selected = accelerator_framework.select_one()
    return _selected


def _reset_selection() -> None:
    """Test hook: force re-selection (e.g. after changing the MCA var)."""
    global _selected
    _selected = None


def is_device_buffer(obj: Any) -> bool:
    """Cheap global check used by parse_buffer on every verb. Avoids
    selecting/initializing a backend for plain host buffers."""
    # Fast structural test first: all jax Arrays have these; plain
    # ndarrays/bytearrays do not.
    if isinstance(obj, (np.ndarray, bytes, bytearray, memoryview)):
        return False
    if not hasattr(obj, "addressable_shards") and not hasattr(obj, "device_buffer"):
        # covers jax.Array across versions without importing jax here
        if type(obj).__module__.split(".")[0] not in ("jax", "jaxlib"):
            return False
    return get_module().check_addr(obj)


def stage_to_host(obj: Any) -> np.ndarray:
    """DTOH-stage a device buffer for the host data path, returning a
    READ-ONLY ndarray: writes into the staging copy would be silently
    lost (the device array is immutable), so attempting one must fail
    loudly. Receive-side device data goes through DeviceBuffer instead."""
    host = get_module().mem_copy_to_host(obj)
    host = np.ascontiguousarray(host)
    host.flags.writeable = False
    return host
