"""OpenSHMEM-style PGAS layer.

Reference: oshmem/ (52,531 LoC) — a PGAS API initialized ON TOP of MPI
(oshmem_shmem_init.c:141 calls ompi_mpi_init), with frameworks: spml
(one-sided put/get engine), memheap (symmetric heap allocator), scoll
(collectives delegating to MPI coll — scoll/mpi), atomic.

Redesign: the symmetric heap is one RMA window over COMM_WORLD
(spml == the osc active-message engine); symmetry holds by construction
— every PE performs the same allocation sequence, so offsets agree
(the reference's memheap contract). Collectives delegate to the MPI
layer exactly like scoll/mpi. The TPU note: PGAS on the mesh path is
the MeshWin driver-array model; this module is the host/process-mode
surface.

Usage::

    from ompi_tpu import shmem
    shmem.init()
    a = shmem.zeros(8, np.float64)        # symmetric across PEs
    shmem.barrier_all()
    shmem.put(a, np.arange(8.), pe=1)     # write into PE 1's copy
    shmem.quiet()
    v = shmem.atomic_fetch_add(a, 5.0, pe=0)
    shmem.finalize()
"""

from __future__ import annotations

import threading
from typing import Optional

import numpy as np

from ompi_tpu.core import op as _op
from ompi_tpu.core.errors import MPIError, ERR_OTHER
from ompi_tpu.mca.var import register_var, get_var

register_var("shmem", "heap_bytes", 1 << 24,
             help="Symmetric heap size per PE (reference: memheap's "
                  "SHMEM_SYMMETRIC_HEAP_SIZE)", level=3)

_lock = threading.Lock()
_ctx: Optional[dict] = None

_ALIGN = 16


class SymArray:
    """A symmetric allocation: same offset in every PE's heap
    (reference: memheap block). ``local`` is THIS PE's data."""

    __slots__ = ("off", "count", "dtype", "local")

    def __init__(self, off: int, count: int, dtype, local: np.ndarray):
        self.off = off
        self.count = count
        self.dtype = np.dtype(dtype)
        self.local = local

    def _disp(self, index: int = 0) -> int:
        # element-unit displacement for Win verbs
        byte = self.off + index * self.dtype.itemsize
        assert byte % self.dtype.itemsize == 0
        return byte // self.dtype.itemsize


def init() -> None:
    """shmem_init (reference: oshmem_shmem_init -> ompi_mpi_init)."""
    global _ctx
    with _lock:
        if _ctx is not None:
            return
        import ompi_tpu
        from ompi_tpu.osc.window import Win

        ompi_tpu.Init()
        comm = ompi_tpu.runtime.state.get_world()
        # the symmetric heap is implementation-owned: Win.Allocate backs
        # it with the node-shared segment when all PEs are local, making
        # shmem_put/get single mapped memcpys (reference: memheap over
        # the sshmem segment + smsc, the same zero-copy layering)
        win = Win.Allocate(int(get_var("shmem", "heap_bytes")), comm)
        heap = win.buf.reshape(-1).view(np.uint8)
        _ctx = {
            "comm": comm,
            "heap": heap,
            "win": win,
            # first-fit free list of (offset, size) spans — the memheap
            # allocator analog (reference: oshmem/mca/memheap ptmalloc/
            # buddy); symmetric because every PE runs the same sequence
            "free": [(0, heap.nbytes)],
            "live": {},  # off -> nbytes of live allocations
            "nbi": [],  # outstanding nonblocking put/get requests
        }


def finalize() -> None:
    global _ctx
    with _lock:
        if _ctx is None:
            return
        _ctx["win"].Free()
        _ctx = None


def _need() -> dict:
    if _ctx is None:
        init()
    return _ctx


def my_pe() -> int:
    return _need()["comm"].Get_rank()


def n_pes() -> int:
    return _need()["comm"].Get_size()


# ----------------------------------------------------------- memheap
def zeros(count: int, dtype=np.float64) -> SymArray:
    """Symmetric allocation (shmem_malloc + zero). SYMMETRY CONTRACT:
    every PE must perform the same allocation/free sequence (the
    reference's memheap makes the same assumption — remote addresses
    are computed, not exchanged). First-fit over the free list with
    alignment padding kept reusable."""
    ctx = _need()
    dt = np.dtype(dtype)
    if count == 0:  # empty symmetric array: nothing to carve or address
        return SymArray(0, 0, dt, np.zeros(0, dt))
    nbytes = count * dt.itemsize
    for i, (foff, fsize) in enumerate(ctx["free"]):
        off = (foff + _ALIGN - 1) & ~(_ALIGN - 1)
        pad = off - foff
        if pad + nbytes > fsize:
            continue
        # carve: [foff, off) stays free (alignment pad), the tail after
        # the block stays free
        repl = []
        if pad:
            repl.append((foff, pad))
        tail = fsize - pad - nbytes
        if tail:
            repl.append((off + nbytes, tail))
        ctx["free"][i: i + 1] = repl
        ctx["live"][off] = nbytes
        local = ctx["heap"][off: off + nbytes].view(dt)
        local[:] = 0
        return SymArray(off, count, dt, local)
    raise MPIError(ERR_OTHER,
                   f"symmetric heap exhausted ({ctx['heap'].nbytes}B; "
                   "raise shmem_heap_bytes)")


def free(arr: SymArray) -> None:
    """shmem_free: return the block to the free list, coalescing with
    adjacent spans (reference: memheap's real allocator — long-running
    PGAS programs must be able to reclaim)."""
    ctx = _need()
    nbytes = arr.count * arr.dtype.itemsize
    if nbytes == 0:
        return
    # a free must name an exact live span: a double-free or a stale /
    # foreign SymArray would insert an overlapping span and, after
    # coalescing, the allocator would hand the same heap bytes to two
    # live allocations on every PE — corrupting symmetric data silently
    if ctx["live"].get(arr.off) != nbytes:
        raise MPIError(
            ERR_OTHER,
            f"shmem_free: [{arr.off}, {arr.off + nbytes}) is not a live "
            "allocation (double free, or a stale/foreign SymArray)")
    del ctx["live"][arr.off]
    spans = ctx["free"]
    spans.append((arr.off, nbytes))
    spans.sort()
    merged = [spans[0]]
    for off, size in spans[1:]:
        loff, lsize = merged[-1]
        if loff + lsize == off:
            merged[-1] = (loff, lsize + size)
        else:
            merged.append((off, size))
    ctx["free"] = merged


# ------------------------------------------------------------- put/get
def put(arr: SymArray, src, pe: int, offset: int = 0) -> None:
    """shmem_put: write ``src`` into PE ``pe``'s copy of ``arr``
    (nonblocking-ish: local completion immediate, remote at quiet())."""
    ctx = _need()
    src = np.ascontiguousarray(np.asarray(src, dtype=arr.dtype))
    ctx["win"].Put(src, pe, target_disp=arr._disp(offset))


def get(arr: SymArray, count: int, pe: int, offset: int = 0) -> np.ndarray:
    """shmem_get: fetch ``count`` elements of PE ``pe``'s copy."""
    ctx = _need()
    out = np.zeros(count, arr.dtype)
    ctx["win"].Get(out, pe, target_disp=arr._disp(offset))
    return out


def p(arr: SymArray, value, pe: int, offset: int = 0) -> None:
    """shmem_p (single element)."""
    put(arr, np.asarray([value], arr.dtype), pe, offset)


def g(arr: SymArray, pe: int, offset: int = 0):
    """shmem_g (single element)."""
    return get(arr, 1, pe, offset)[0]


# ------------------------------------------------- nonblocking put/get
def _rput_nbi(reqs: list, arr: SymArray, src, pe: int,
              offset: int) -> None:
    ctx = _need()
    src = np.ascontiguousarray(np.asarray(src, dtype=arr.dtype))
    reqs.append(ctx["win"].Rput(src, pe, target_disp=arr._disp(offset)))


def _rget_nbi(reqs: list, arr: SymArray, out: np.ndarray, pe: int,
              offset: int) -> None:
    ctx = _need()
    if out.dtype != arr.dtype:
        raise MPIError(ERR_OTHER,
                       f"get_nbi dtype mismatch: {out.dtype} vs "
                       f"{arr.dtype}")
    if not out.flags["C_CONTIGUOUS"]:
        raise MPIError(ERR_OTHER, "get_nbi needs a contiguous out array")
    reqs.append(ctx["win"].Rget(out, pe, target_disp=arr._disp(offset)))


def _drain(reqs: list) -> None:
    """Wait every request, dropping none even on failure."""
    err = None
    for r in reqs:
        try:
            r.Wait()
        except MPIError as e:
            err = err or e
    if err is not None:
        raise err


def put_nbi(arr: SymArray, src, pe: int, offset: int = 0) -> None:
    """shmem_put_nbi: neither local nor remote completion at return —
    both at quiet() (reference: oshmem/shmem/c/shmem_put_nb.c; the src
    buffer must stay unmodified until quiet)."""
    _rput_nbi(_need()["nbi"], arr, src, pe, offset)


def get_nbi(arr: SymArray, out: np.ndarray, pe: int,
            offset: int = 0) -> None:
    """shmem_get_nbi: ``out`` is valid only after quiet(). ``out`` must
    be a contiguous array of the symmetric dtype — the landing callback
    writes through a flat view, which would silently fill a temporary
    for a strided destination."""
    _rget_nbi(_need()["nbi"], arr, out, pe, offset)


# -------------------------------------------------------- strided iput
def iput(arr: SymArray, src, tst: int, sst: int, nelems: int,
         pe: int, offset: int = 0) -> None:
    """shmem_iput: element k of the strided source (stride sst) lands at
    target index offset + k*tst (reference: oshmem/shmem/c/shmem_iput.c
    — the spml likewise decomposes to element transfers)."""
    ctx = _need()
    src = np.asarray(src, dtype=arr.dtype)
    for k in range(nelems):
        ctx["win"].Put(np.ascontiguousarray(src[k * sst: k * sst + 1]),
                       pe, target_disp=arr._disp(offset + k * tst))


def iget(arr: SymArray, tst: int, sst: int, nelems: int, pe: int,
         offset: int = 0) -> np.ndarray:
    """shmem_iget: gather target indices offset + k*sst into a local
    strided array of stride tst (returned dense, spanning
    (nelems-1)*tst + 1 elements; empty for nelems == 0)."""
    ctx = _need()
    if nelems == 0:
        return np.zeros(0, arr.dtype)
    out = np.zeros(1 + (nelems - 1) * tst, arr.dtype)
    reqs = []
    for k in range(nelems):
        reqs.append(ctx["win"].Rget(out[k * tst: k * tst + 1], pe,
                                    target_disp=arr._disp(offset + k * sst)))
    for r in reqs:
        r.Wait()
    return out


# ------------------------------------------------------ wait_until/test
CMP_EQ, CMP_NE, CMP_GT, CMP_GE, CMP_LT, CMP_LE = range(6)

_CMPS = {
    CMP_EQ: lambda a, b: a == b,
    CMP_NE: lambda a, b: a != b,
    CMP_GT: lambda a, b: a > b,
    CMP_GE: lambda a, b: a >= b,
    CMP_LT: lambda a, b: a < b,
    CMP_LE: lambda a, b: a <= b,
}


def test(arr: SymArray, cmp: int, value, index: int = 0) -> bool:
    """shmem_test: one progress-driving poll of the LOCAL location."""
    from ompi_tpu.runtime.progress import progress

    _need()
    progress()
    return bool(_CMPS[cmp](arr.local[index], value))


def wait_until(arr: SymArray, cmp: int, value, index: int = 0,
               timeout: Optional[float] = None) -> None:
    """shmem_wait_until: block (driving progress) until a remote put or
    atomic makes the local location satisfy the comparison (reference:
    oshmem/shmem/c/shmem_wait.c over the spml's memory-update hooks —
    here the osc active-message engine applies updates from progress)."""
    from ompi_tpu.runtime.progress import progress_until

    _need()
    if not progress_until(
            lambda: bool(_CMPS[cmp](arr.local[index], value)),
            timeout=timeout):
        raise MPIError(ERR_OTHER, "shmem_wait_until timed out")


# -------------------------------------------------------- distributed lock
def set_lock(lock: SymArray) -> None:
    """shmem_set_lock: acquire via CAS(0 -> my_pe+1) on the lock's home
    PE, spinning through the progress engine (reference:
    oshmem/shmem/c/shmem_lock.c — theirs is an MCS queue over the
    symmetric variable; a CAS spin with backoff serves the same mutual-
    exclusion contract at driver scale)."""
    from ompi_tpu.core.request import IdleBackoff

    me = my_pe() + 1
    backoff = IdleBackoff()
    while True:
        old = atomic_compare_swap(lock, 0, me, pe=_lock_home(lock))
        if old == 0:
            return
        backoff.step(False)


def test_lock(lock: SymArray) -> bool:
    """shmem_test_lock: one acquisition attempt; True = got it."""
    me = my_pe() + 1
    return atomic_compare_swap(lock, 0, me, pe=_lock_home(lock)) == 0


def clear_lock(lock: SymArray) -> None:
    """shmem_clear_lock: release (must hold it)."""
    me = my_pe() + 1
    old = atomic_compare_swap(lock, me, 0, pe=_lock_home(lock))
    if old != me:
        raise MPIError(ERR_OTHER,
                       f"clear_lock by non-holder (lock held by {old})")


def _lock_home(lock: SymArray) -> int:
    # deterministic home PE spread by heap offset (same value on every
    # PE — the symmetry contract)
    return (lock.off // _ALIGN) % n_pes()


# ------------------------------------------------------------- atomics
def atomic_add(arr: SymArray, value, pe: int, offset: int = 0) -> None:
    ctx = _need()
    ctx["win"].Accumulate(np.asarray([value], arr.dtype), pe,
                          target_disp=arr._disp(offset), op=_op.SUM)


def atomic_fetch_add(arr: SymArray, value, pe: int, offset: int = 0):
    ctx = _need()
    out = np.zeros(1, arr.dtype)
    ctx["win"].Fetch_and_op(np.asarray([value], arr.dtype), out, pe,
                            target_disp=arr._disp(offset), op=_op.SUM)
    return out[0]


def atomic_compare_swap(arr: SymArray, cond, value, pe: int,
                        offset: int = 0):
    ctx = _need()
    out = np.zeros(1, arr.dtype)
    ctx["win"].Compare_and_swap(np.asarray([cond], arr.dtype),
                                np.asarray([value], arr.dtype), out, pe,
                                target_disp=arr._disp(offset))
    return out[0]


def atomic_fetch(arr: SymArray, pe: int, offset: int = 0):
    return g(arr, pe, offset)


# ------------------------------------------------------ ordering/sync
def fence() -> None:
    """shmem_fence: order puts per-PE — our transports deliver per-peer
    in order, and quiet() is stronger; provided for API parity."""
    quiet()


def quiet() -> None:
    """shmem_quiet: remote completion of all outstanding puts/atomics,
    including the _nbi ones (their requests complete here)."""
    ctx = _need()
    reqs, ctx["nbi"] = ctx["nbi"], []
    _drain(reqs)
    ctx["win"].Flush()


def barrier_all() -> None:
    """shmem_barrier_all: quiet + barrier (reference: shmem_barrier_all
    implies completion of all remote writes, including _nbi ones)."""
    ctx = _need()
    quiet()
    from ompi_tpu.runtime import spc

    with spc.suppressed():
        ctx["comm"].Barrier()


# --------------------------------------------------- collectives (scoll)
def _bcast_impl(comm, arr: SymArray, root: int) -> None:
    comm.Bcast([arr.local, arr.count, _dt_of(arr.dtype)], root=root)


def _reduce_impl(comm, target: SymArray, source: SymArray, op) -> None:
    comm.Allreduce(
        [source.local, source.count, _dt_of(source.dtype)],
        [target.local, target.count, _dt_of(target.dtype)], op=op)


def _collect_impl(comm, arr: SymArray) -> np.ndarray:
    n = comm.Get_size()
    out = np.zeros(arr.count * n, arr.dtype)
    comm.Allgather(
        [arr.local, arr.count, _dt_of(arr.dtype)],
        [out, arr.count * n, _dt_of(arr.dtype)])
    return out


def broadcast(arr: SymArray, root: int = 0) -> None:
    """shmem_broadcast over the symmetric block (scoll/mpi pattern:
    delegate to the MPI collective)."""
    _bcast_impl(_need()["comm"], arr, root)


def sum_to_all(target: SymArray, source: SymArray) -> None:
    _reduce_impl(_need()["comm"], target, source, _op.SUM)


def max_to_all(target: SymArray, source: SymArray) -> None:
    _reduce_impl(_need()["comm"], target, source, _op.MAX)


def collect(arr: SymArray) -> np.ndarray:
    """shmem_collect (fixed size): every PE's block, concatenated."""
    return _collect_impl(_need()["comm"], arr)


def _dt_of(np_dtype):
    from ompi_tpu.core.datatype import from_numpy_dtype

    return from_numpy_dtype(np_dtype)


# ----------------------------------------------------- teams (OpenSHMEM 1.5)
# Reference: oshmem/shmem/c/shmem_team_*.c + the scoll team collectives.
# A team is a PE subset with its own rank space; split_strided is
# collective over the parent team, and team collectives delegate to a
# sub-communicator of the world comm (the scoll/mpi pattern, same as the
# module-level collectives).
class Team:
    """A PE team. ``pes`` lists world PEs in team-rank order; ``comm``
    is the member-side sub-communicator (None on non-members)."""

    def __init__(self, pes, comm):
        self.pes = list(pes)
        self._comm = comm

    def my_pe(self) -> int:
        me = my_pe()
        return self.pes.index(me) if me in self.pes else -1

    def n_pes(self) -> int:
        return len(self.pes)

    def translate_pe(self, pe: int, dest: "Team") -> int:
        """shmem_team_translate_pe: my-team rank -> dest-team rank."""
        world = self.pes[pe]
        return dest.pes.index(world) if world in dest.pes else -1

    def split_strided(self, start: int, stride: int,
                      size: int) -> Optional["Team"]:
        """Collective over THIS team; returns the new team (None =
        SHMEM_TEAM_INVALID on non-members)."""
        from ompi_tpu.core.group import Group
        from ompi_tpu.runtime import spc

        ctx = _need()
        pes = [self.pes[start + i * stride] for i in range(size)]
        parent = self._comm if self._comm is not None else ctx["comm"]
        with spc.suppressed():
            sub = parent.Create_group(Group(pes))
        team = Team(pes, sub)
        return team if sub is not None else None

    # team-relative RMA: translate then delegate
    def put(self, arr: SymArray, src, pe: int, offset: int = 0) -> None:
        put(arr, src, self.pes[pe], offset)

    def get(self, arr: SymArray, count: int, pe: int,
            offset: int = 0) -> np.ndarray:
        return get(arr, count, self.pes[pe], offset)

    # --------------------------------------------- team collectives
    def sync(self) -> None:
        """shmem_team_sync: quiet + team barrier."""
        from ompi_tpu.runtime import spc

        quiet()
        with spc.suppressed():
            self._comm.Barrier()

    # user collectives are NOT spc-suppressed (they are user activity,
    # same as the module-level equivalents)
    def broadcast(self, arr: SymArray, root: int = 0) -> None:
        _bcast_impl(self._comm, arr, root)

    def sum_to_all(self, target: SymArray, source: SymArray) -> None:
        _reduce_impl(self._comm, target, source, _op.SUM)

    def max_to_all(self, target: SymArray, source: SymArray) -> None:
        _reduce_impl(self._comm, target, source, _op.MAX)

    def collect(self, arr: SymArray) -> np.ndarray:
        return _collect_impl(self._comm, arr)


def team_world() -> Team:
    """SHMEM_TEAM_WORLD."""
    ctx = _need()
    return Team(list(range(n_pes())), ctx["comm"])


# ------------------------------------------------ contexts (OpenSHMEM 1.5)
class Context:
    """shmem_ctx: an independent ordering/completion domain — quiet on
    one context completes ONLY that context's operations (reference:
    oshmem ctx_create over spml contexts). EVERY operation issued on a
    context — including plain put — goes through a tracked request, so
    ctx.quiet() waits exactly this context's acks and nothing else (no
    window-global flush; the isolation is real, not over-completion)."""

    def __init__(self):
        _need()
        self._nbi = []

    def put(self, arr: SymArray, src, pe: int, offset: int = 0) -> None:
        """Local completion at return (the payload is copied at post);
        remote completion at this context's quiet."""
        _rput_nbi(self._nbi, arr, src, pe, offset)

    def get(self, arr: SymArray, count: int, pe: int,
            offset: int = 0) -> np.ndarray:
        return get(arr, count, pe, offset)  # blocking: self-completing

    def put_nbi(self, arr: SymArray, src, pe: int,
                offset: int = 0) -> None:
        _rput_nbi(self._nbi, arr, src, pe, offset)

    def get_nbi(self, arr: SymArray, out: np.ndarray, pe: int,
                offset: int = 0) -> None:
        _rget_nbi(self._nbi, arr, out, pe, offset)

    def quiet(self) -> None:
        """Complete THIS context's operations only."""
        reqs, self._nbi = self._nbi, []
        _drain(reqs)

    def fence(self) -> None:
        self.quiet()

    def destroy(self) -> None:
        """shmem_ctx_destroy: implicit quiet."""
        self.quiet()


def ctx_create() -> Context:
    return Context()
