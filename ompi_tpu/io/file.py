"""MPI-IO.

Reference: ompi/mca/io/ompio + common/ompio (the engine,
common_ompio_file_write.c:49), fcoll two-phase collective IO (vulcan /
dynamic_gen2), fbtl/posix (pwritev), sharedfp (shared file pointers).

Redesign notes:
- **File views** reuse the datatype engine directly: a view is
  (disp, etype, filetype); logical byte L of the element stream maps to
  file offset disp + (L // S) * E + byte_map[L % S] where S/E are the
  filetype's size/extent — the same byte-map mapping the pt2pt convertor
  uses, so subarray/vector views cost one vectorized gather (reference:
  ompio's decoded-iovec machinery).
- **Independent IO** is positional pread/pwrite per contiguous run.
- **Collective IO** (`*_all`) is two-phase with rank 0 as aggregator
  (reference: fcoll with one aggregator — the dynamic/vulcan schedule
  specialization for single-host): gather segments, coalesce, write large.
- **Shared file pointers** are a Fetch_and_op window hosted on rank 0
  (reference: sharedfp/sm's shared counter, built here on our own RMA).
"""

from __future__ import annotations

import os
from concurrent.futures import ThreadPoolExecutor
from typing import List, Optional, Tuple

import numpy as np

from ompi_tpu.comm.communicator import parse_buffer
from ompi_tpu.core import op as _op
from ompi_tpu.core.datatype import BYTE, Datatype
from ompi_tpu.core.errors import MPIError, ERR_AMODE, ERR_FILE, ERR_IO
from ompi_tpu.core.request import Request
from ompi_tpu.mca.var import register_var, get_var

register_var("io", "num_aggregators", 2,
             help="Aggregator count for two-phase collective IO "
                  "(reference: fcoll/vulcan's aggregator selection)",
             level=4)
register_var("io", "stripe_size", 1 << 20,
             help="File-cycle stripe: stripe s belongs to aggregator "
                  "(s %% num_aggregators) — the vulcan round-robin cycle "
                  "assignment", level=6)

# Independent nonblocking IO rides a small worker pool (the fbtl/posix
# aio analog: the request completes asynchronously and Wait's condition
# variable wakes through the normal completion path).
_io_pool = ThreadPoolExecutor(max_workers=2,
                              thread_name_prefix="ompi-tpu-io")


def _suppressed_spc():
    from ompi_tpu.runtime import spc

    return spc.suppressed()

# CID plane for collective-IO exchange traffic (COLL=1<<30, PART=1<<29,
# NBC=1<<28, DPM=1<<27, FT=1<<25 — IO takes 1<<26)
IO_CID_BIT = 1 << 26

MODE_RDONLY = 2
MODE_RDWR = 8
MODE_WRONLY = 4
MODE_CREATE = 1
MODE_EXCL = 64
MODE_DELETE_ON_CLOSE = 16
MODE_APPEND = 128


def _os_flags(amode: int) -> int:
    if amode & MODE_RDWR:
        fl = os.O_RDWR
    elif amode & MODE_WRONLY:
        fl = os.O_WRONLY
    elif amode & MODE_RDONLY:
        fl = os.O_RDONLY
    else:
        raise MPIError(ERR_AMODE, "need RDONLY, WRONLY or RDWR")
    if amode & MODE_CREATE:
        fl |= os.O_CREAT
    if amode & MODE_EXCL:
        fl |= os.O_EXCL
    if amode & MODE_APPEND:
        fl |= os.O_APPEND
    return fl


class File:
    def __init__(self, comm, filename: str, amode: int):
        self.comm = comm
        self.filename = filename
        self.amode = amode
        try:
            if comm.rank == 0:
                self.fd = os.open(filename, _os_flags(amode), 0o644)
                comm.Barrier()
            else:
                comm.Barrier()  # rank 0 creates first (reference: ompio
                self.fd = os.open(filename, _os_flags(amode & ~MODE_EXCL),
                                  0o644)
        except OSError as e:
            raise MPIError(ERR_FILE, f"{filename}: {e}")
        # default view: contiguous bytes from offset 0
        self.disp = 0
        self.etype: Datatype = BYTE
        self.filetype: Datatype = BYTE
        self.offset = 0  # individual file pointer, in etypes
        self._shared_win = None
        # private comm for collective-IO traffic (reference: ompio dups
        # the communicator at file open, ompio_file_open.c) — collective
        # phases never cross-match user traffic, and nonblocking
        # collective IO can progress from a worker thread
        from ompi_tpu.runtime import spc

        with spc.suppressed():
            self._io_comm = comm.Dup() if comm.size > 1 else comm
        if self._io_comm is not comm:
            # move the io comm onto its own CID plane (IO_CID_BIT): the
            # two-phase exchange is library-internal traffic —
            # pml/monitoring must not count it as application pt2pt and
            # pml/v must not payload-log it (it regenerates on replay)
            from ompi_tpu.comm.communicator import _live_comms

            _live_comms.pop(self._io_comm.cid, None)
            self._io_comm.cid |= IO_CID_BIT
            _live_comms[self._io_comm.cid] = self._io_comm
        # collective ops per file run on ONE serial worker: MPI requires
        # collective calls in order per comm, so i*_all must not reorder
        self._coll_pool = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="ompi-tpu-io-coll")

    @staticmethod
    def Open(comm, filename: str, amode: int = MODE_RDWR | MODE_CREATE
             ) -> "File":
        return File(comm, filename, amode)

    def Close(self) -> None:
        self._coll_pool.shutdown(wait=True)  # drain i*_all in flight
        self.comm.Barrier()
        os.close(self.fd)
        if self._io_comm is not self.comm:
            self._io_comm.Free()
        if self.amode & MODE_DELETE_ON_CLOSE and self.comm.rank == 0:
            try:
                os.unlink(self.filename)
            except OSError:
                pass

    # ------------------------------------------------------------- views
    def Set_view(self, disp: int = 0, etype: Optional[Datatype] = None,
                 filetype: Optional[Datatype] = None) -> None:
        self.disp = disp
        self.etype = etype or BYTE
        self.filetype = filetype or self.etype
        self.offset = 0

    def Get_view(self):
        return self.disp, self.etype, self.filetype

    def _file_runs(self, offset_etypes: int, nbytes: int
                   ) -> List[Tuple[int, int, int]]:
        """Map nbytes of the logical element stream starting at
        offset_etypes into coalesced (file_off, stream_off, length) runs."""
        ft = self.filetype
        S, E = ft.size, ft.extent
        start = offset_etypes * self.etype.size
        if ft.is_contiguous:
            return [(self.disp + start, 0, nbytes)]
        bm = ft._compute_byte_map()
        stream = np.arange(start, start + nbytes, dtype=np.int64)
        file_off = self.disp + (stream // S) * E + bm[stream % S]
        runs: List[Tuple[int, int, int]] = []
        run_start = 0
        for i in range(1, len(file_off) + 1):
            if i == len(file_off) or file_off[i] != file_off[i - 1] + 1:
                runs.append((int(file_off[run_start]), run_start,
                             i - run_start))
                run_start = i
        return runs

    # ---------------------------------------------------- independent IO
    def Write_at(self, offset: int, buf) -> int:
        obj, count, dt = parse_buffer(buf)
        from ompi_tpu.core.convertor import pack

        data = pack(obj, count, dt).tobytes()
        total = 0
        for foff, soff, ln in self._file_runs(offset, len(data)):
            total += os.pwrite(self.fd, data[soff: soff + ln], foff)
        return total

    def Read_at(self, offset: int, buf) -> int:
        obj, count, dt = parse_buffer(buf)
        from ompi_tpu.core.convertor import unpack

        nbytes = count * dt.size
        chunks = bytearray(nbytes)
        total = 0
        for foff, soff, ln in self._file_runs(offset, nbytes):
            got = os.pread(self.fd, ln, foff)
            chunks[soff: soff + len(got)] = got
            total += len(got)
        unpack(np.frombuffer(bytes(chunks), np.uint8), obj, count, dt)
        return total

    def Write(self, buf) -> int:
        obj, count, dt = parse_buffer(buf)
        n = self.Write_at(self.offset, buf)
        self.offset += (count * dt.size) // max(self.etype.size, 1)
        return n

    def Read(self, buf) -> int:
        obj, count, dt = parse_buffer(buf)
        n = self.Read_at(self.offset, buf)
        self.offset += (count * dt.size) // max(self.etype.size, 1)
        return n

    def Seek(self, offset: int, whence: int = 0) -> None:
        if whence == 0:
            self.offset = offset
        elif whence == 1:
            self.offset += offset
        else:
            size = os.fstat(self.fd).st_size
            self.offset = size // max(self.etype.size, 1) + offset

    def Get_position(self) -> int:
        return self.offset

    def Get_size(self) -> int:
        return os.fstat(self.fd).st_size

    def Set_size(self, size: int) -> None:
        os.ftruncate(self.fd, size)
        self.comm.Barrier()

    def Sync(self) -> None:
        os.fsync(self.fd)

    # ----------------------------------------------------- collective IO
    # Two-phase with MULTIPLE aggregators (reference:
    # fcoll/vulcan/fcoll_vulcan_file_write_all.c — aggregators own file
    # cycles round-robin; every rank exchanges its stripe-split segments
    # with the owning aggregator, which issues large coalesced IO).
    def _aggregators(self) -> List[int]:
        n = self._io_comm.size
        a = max(1, min(int(get_var("io", "num_aggregators")), n))
        # spread aggregators across the rank space (vulcan picks evenly
        # spaced ranks for locality across nodes)
        return [(i * n) // a for i in range(a)]

    def _split_by_stripe(self, runs, naggs: int):
        """Split (file_off, stream_off, length) runs at stripe
        boundaries, bucketing by owning aggregator; stream offsets
        advance in step so every piece knows its place in the local
        element stream (no reassembly search needed)."""
        stripe = max(1, int(get_var("io", "stripe_size")))
        buckets: List[list] = [[] for _ in range(naggs)]
        for foff, soff, ln in runs:
            pos = 0
            while pos < ln:
                s = (foff + pos) // stripe
                take = min(ln - pos, (s + 1) * stripe - (foff + pos))
                buckets[int(s) % naggs].append(
                    (foff + pos, soff + pos, take))
                pos += take
        return buckets

    _TAG_WSEG = 11   # rank -> aggregator: pickled write segments
    _TAG_RREQ = 12   # rank -> aggregator: pickled read runs
    _TAG_RDAT = 13   # aggregator -> rank: pickled per-run read payloads

    def _recv_pickled(self, source: int, tag: int):
        """Probe-sized pickled receive on the io comm (the exchange
        phases all speak length-prefixed pickle)."""
        import pickle

        from ompi_tpu.core.status import Status

        comm = self._io_comm
        st = Status()
        comm.Probe(source=source, tag=tag, status=st)
        raw = np.zeros(st.Get_count(BYTE), np.uint8)
        comm.Recv(raw, source=source, tag=tag)
        return pickle.loads(raw.tobytes())

    def _send_pickled(self, obj, dest: int, tag: int):
        import pickle

        blob = np.frombuffer(pickle.dumps(obj), np.uint8)
        return self._io_comm.Isend(blob, dest=dest, tag=tag)

    def Write_at_all(self, offset: int, buf) -> int:
        """Collective write: serialized through the file's collective
        worker so blocking and nonblocking *_all calls on this file
        execute in MPI call order."""
        fut = self._coll_pool.submit(self._write_at_all_impl, offset,
                                     buf)
        return fut.result()

    def _write_at_all_impl(self, offset: int, buf) -> int:
        obj, count, dt = parse_buffer(buf)
        from ompi_tpu.core.convertor import pack

        data = pack(obj, count, dt).tobytes()
        runs = self._file_runs(offset, len(data))
        comm = self._io_comm
        written = len(data)
        if comm.size == 1:
            for foff, soff, ln in runs:
                os.pwrite(self.fd, data[soff: soff + ln], foff)
            return written
        aggs = self._aggregators()
        buckets = self._split_by_stripe(runs, len(aggs))
        reqs = []
        for k, agg in enumerate(aggs):
            segs = [(foff, data[soff: soff + ln])
                    for foff, soff, ln in buckets[k]]
            reqs.append(self._send_pickled(segs, agg, self._TAG_WSEG))
        if comm.rank in aggs:
            mine: List[Tuple[int, bytes]] = []
            for r in range(comm.size):
                mine.extend(self._recv_pickled(r, self._TAG_WSEG))
            mine.sort(key=lambda s: s[0])
            # coalesce adjacent pieces into large writes (phase 2)
            i = 0
            while i < len(mine):
                foff, d = mine[i]
                parts = [d]
                end = foff + len(d)
                j = i + 1
                while j < len(mine) and mine[j][0] == end:
                    parts.append(mine[j][1])
                    end += len(mine[j][1])
                    j += 1
                os.pwrite(self.fd, b"".join(parts), foff)
                i = j
        Request.Waitall(reqs)
        with _suppressed_spc():
            comm.Barrier()
        return written

    def Read_at_all(self, offset: int, buf) -> int:
        """Collective read, serialized like Write_at_all."""
        fut = self._coll_pool.submit(self._read_at_all_impl, offset, buf)
        return fut.result()

    def _read_at_all_impl(self, offset: int, buf) -> int:
        """Two-phase collective read: aggregators pread their stripes
        and serve each rank's runs back (vulcan's read_all mirror)."""
        obj, count, dt = parse_buffer(buf)
        from ompi_tpu.core.convertor import unpack

        nbytes = count * dt.size
        runs = self._file_runs(offset, nbytes)
        comm = self._io_comm
        if comm.size == 1:
            return self.Read_at(offset, buf)
        aggs = self._aggregators()
        # bucket my (file_off, stream_off, length) runs by aggregator;
        # each piece carries its own stream offset for reassembly
        want = self._split_by_stripe(runs, len(aggs))
        reqs = [self._send_pickled([(foff, ln) for foff, _, ln in want[k]],
                                   agg, self._TAG_RREQ)
                for k, agg in enumerate(aggs)]
        serve = []
        if comm.rank in aggs:
            for r in range(comm.size):
                asked = self._recv_pickled(r, self._TAG_RREQ)
                # per-run ACTUAL payloads: a pread at/past EOF is short,
                # and the requester must know each run's real length or
                # every later slice misaligns and zeros count as read
                pieces = [os.pread(self.fd, ln, foff)
                          for foff, ln in asked]
                serve.append(self._send_pickled(pieces, r,
                                                self._TAG_RDAT))
        # collect my data from each aggregator, in my request order
        chunks = bytearray(nbytes)
        got_total = 0
        for k, agg in enumerate(aggs):
            pieces = self._recv_pickled(agg, self._TAG_RDAT)
            for (_foff, soff, _ln), piece in zip(want[k], pieces):
                chunks[soff: soff + len(piece)] = piece
                got_total += len(piece)
        Request.Waitall(reqs + serve)
        unpack(np.frombuffer(bytes(chunks), np.uint8), obj, count, dt)
        with _suppressed_spc():
            comm.Barrier()
        return got_total

    def Write_all(self, buf) -> int:
        obj, count, dt = parse_buffer(buf)
        n = self.Write_at_all(self.offset, buf)
        self.offset += (count * dt.size) // max(self.etype.size, 1)
        return n

    def Read_all(self, buf) -> int:
        obj, count, dt = parse_buffer(buf)
        n = self.Read_at_all(self.offset, buf)
        self.offset += (count * dt.size) // max(self.etype.size, 1)
        return n

    # ---------------------------------------------------- nonblocking IO
    # Reference: common_ompio_file_iwrite{,_at,_all} (common_ompio.h:262
    # -267) over the fbtl aio machinery. The request completes from a
    # worker; independent ops share a small pool, collective ops run on
    # the file's single serial worker (collective order per comm must be
    # preserved) against the private io comm.
    def _submit(self, pool, fn) -> Request:
        req = Request()

        def run():
            try:
                n = fn()
                req.status._nbytes = int(n)
                req._set_complete(0)
            except MPIError as e:
                req._set_complete(e.code)
            except Exception:
                req._set_complete(ERR_IO)

        pool.submit(run)
        return req

    def Iwrite_at(self, offset: int, buf) -> Request:
        return self._submit(_io_pool, lambda: self.Write_at(offset, buf))

    def Iread_at(self, offset: int, buf) -> Request:
        return self._submit(_io_pool, lambda: self.Read_at(offset, buf))

    def Iwrite(self, buf) -> Request:
        obj, count, dt = parse_buffer(buf)
        off = self.offset
        self.offset += (count * dt.size) // max(self.etype.size, 1)
        return self._submit(_io_pool, lambda: self.Write_at(off, buf))

    def Iread(self, buf) -> Request:
        obj, count, dt = parse_buffer(buf)
        off = self.offset
        self.offset += (count * dt.size) // max(self.etype.size, 1)
        return self._submit(_io_pool, lambda: self.Read_at(off, buf))

    def Iwrite_at_all(self, offset: int, buf) -> Request:
        # submit the impl, not the public verb: the public verb itself
        # queues on the single-slot collective worker (deadlock)
        return self._submit(self._coll_pool,
                            lambda: self._write_at_all_impl(offset, buf))

    def Iread_at_all(self, offset: int, buf) -> Request:
        return self._submit(self._coll_pool,
                            lambda: self._read_at_all_impl(offset, buf))

    # ------------------------------------------------- shared file pointer
    def _shared(self):
        if self._shared_win is None:
            from ompi_tpu.osc.window import Win

            base = np.zeros(1, np.int64) if self.comm.rank == 0 else None
            self._shared_win = Win(
                base if base is not None else np.zeros(0, np.int64),
                self.comm)
        return self._shared_win

    def Write_shared(self, buf) -> int:
        obj, count, dt = parse_buffer(buf)
        n_et = (count * dt.size) // max(self.etype.size, 1)
        win = self._shared()
        old = np.zeros(1, np.int64)
        win.Fetch_and_op(np.array([n_et], np.int64), old, target=0,
                         op=_op.SUM)
        return self.Write_at(int(old[0]), buf)

    def Read_shared(self, buf) -> int:
        obj, count, dt = parse_buffer(buf)
        n_et = (count * dt.size) // max(self.etype.size, 1)
        win = self._shared()
        old = np.zeros(1, np.int64)
        win.Fetch_and_op(np.array([n_et], np.int64), old, target=0,
                         op=_op.SUM)
        return self.Read_at(int(old[0]), buf)

    def Get_amode(self) -> int:
        return self.amode

    def Delete(self) -> None:
        try:
            os.unlink(self.filename)
        except OSError as e:
            raise MPIError(ERR_IO, str(e))
