"""Flagship demo model: a causal-LM transformer parallelized with the
framework's collective vocabulary.

This is the framework's end-to-end proof (the analog of the reference's
examples/ + the OSU/Horovod ladder configs in BASELINE.md): a training step
whose every communication — tensor-parallel activation reductions,
sequence-parallel ring attention, data-parallel gradient allreduce — is an
ompi_tpu collective (ompi_tpu.parallel.axes in-mesh verbs + ops.ring_attention),
laid out Megatron-style over a (dp, sp, tp) mesh:

- tp: QKV/W1 column-parallel, WO/W2 row-parallel with psum of partial
  outputs (attention heads sharded over tp)
- sp: sequence dim sharded; attention runs as ring attention (ppermute
  K/V rotation with flash-style accumulation)
- dp: batch sharded; gradients allreduced (the "Horovod-style 1GB gradient
  allreduce" BASELINE config is exactly this traffic)

All matmuls run in bfloat16 on the MXU with float32 accumulation/params.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Dict, Tuple

import numpy as np


@dataclasses.dataclass(frozen=True)
class Config:
    vocab: int = 512
    d_model: int = 128
    n_heads: int = 8
    n_layers: int = 2
    d_ff: int = 512
    seq_len: int = 128
    lr: float = 1e-2
    # rematerialize each block's activations in backward (jax.checkpoint):
    # trades ~30% more FLOPs for O(layers) less HBM — the standard TPU
    # memory/compute exchange, letting batch sizes that keep the MXU busy
    remat: bool = False

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads


def init_params(key, cfg: Config) -> Dict[str, Any]:
    import jax
    import jax.numpy as jnp

    keys = jax.random.split(key, 2 + cfg.n_layers)
    scale = lambda d: 1.0 / np.sqrt(d)
    params: Dict[str, Any] = {
        "embed": jax.random.normal(keys[0], (cfg.vocab, cfg.d_model),
                                   jnp.float32) * scale(cfg.d_model),
        "pos": jax.random.normal(keys[1], (cfg.seq_len, cfg.d_model),
                                 jnp.float32) * scale(cfg.d_model),
        "ln_f": jnp.ones((cfg.d_model,), jnp.float32),
        "blocks": [],
    }
    for i in range(cfg.n_layers):
        k1, k2, k3, k4 = jax.random.split(keys[2 + i], 4)
        params["blocks"].append({
            "ln1": jnp.ones((cfg.d_model,), jnp.float32),
            # [D, H, 3*hd]: sharding the heads dim over tp keeps each
            # shard's q/k/v intact (a flat [D, 3D] column shard would mix
            # q columns with k columns)
            "qkv": jax.random.normal(
                k1, (cfg.d_model, cfg.n_heads, 3 * cfg.head_dim),
                jnp.float32
            ) * scale(cfg.d_model),
            "wo": jax.random.normal(
                k2, (cfg.d_model, cfg.d_model), jnp.float32
            ) * scale(cfg.d_model),
            "ln2": jnp.ones((cfg.d_model,), jnp.float32),
            "w1": jax.random.normal(
                k3, (cfg.d_model, cfg.d_ff), jnp.float32
            ) * scale(cfg.d_model),
            "w2": jax.random.normal(
                k4, (cfg.d_ff, cfg.d_model), jnp.float32
            ) * scale(cfg.d_ff),
        })
    return params


def param_specs(cfg: Config):
    """Megatron sharding plan as PartitionSpecs (tp axis only; every param
    is replicated over dp and sp)."""
    from jax.sharding import PartitionSpec as P

    block = {
        "ln1": P(), "ln2": P(),
        "qkv": P(None, "tp", None),  # heads sharded (column parallel)
        "wo": P("tp", None),         # row parallel -> psum
        "w1": P(None, "tp"),         # column parallel
        "w2": P("tp", None),         # row parallel -> psum
    }
    return {
        "embed": P(), "pos": P(), "ln_f": P(),
        "blocks": [dict(block) for _ in range(cfg.n_layers)],
    }


def _ln(x, g):
    import jax.numpy as jnp

    x = x - jnp.mean(x, axis=-1, keepdims=True)
    x = x / jnp.sqrt(jnp.mean(x * x, axis=-1, keepdims=True) + 1e-6)
    return x * g


def _mm(a, w):
    """bf16 MXU matmul with f32 accumulation."""
    import jax.numpy as jnp

    return jnp.einsum("...d,df->...f", a.astype(jnp.bfloat16),
                      w.astype(jnp.bfloat16),
                      preferred_element_type=jnp.float32)




def features_local(params, tokens, cfg: Config, tp: int = 1, sp: int = 1,
                   in_mesh: bool = False, causal_ring: bool = True):
    """Forward on local shards up to the final layernorm (pre-logits
    features [B, T, D]). Inside shard_map (``in_mesh=True``): tokens
    [B/dp, S/sp]; tp-sharded weights arrive as local slices; activations
    psum over 'tp' after every row-parallel matmul (emitted even when
    tp == 1 — a size-1 psum is free and lets shard_map prove the loss is
    tp-replicated); attention rotates K/V over 'sp'. With in_mesh=False
    this is the plain single-device forward.
    """
    import jax.numpy as jnp

    from ompi_tpu.ops.mxu import einsum_bf16
    from ompi_tpu.ops.ring_attention import ring_attention
    from ompi_tpu.parallel import axes

    B, T = tokens.shape
    h_local = cfg.n_heads // tp
    hd = cfg.head_dim

    if in_mesh:
        seq_off = axes.rank("sp") * T
        pos_idx = seq_off + jnp.arange(T)
    else:
        pos_idx = jnp.arange(T)
    x = params["embed"][tokens] + params["pos"][pos_idx][None]

    def block(x, blk):
        h = _ln(x, blk["ln1"])
        w_qkv = blk["qkv"]  # local [D, H/tp, 3*hd]
        # three projections emitted straight into the attention kernel's
        # native [B, H, T, hd] layout: a fused qkv einsum + split costs a
        # strided-slice relayout of 3x128MB per block (measured +8.7ms per
        # layer on v5e); separate slices of the weight are free
        hb = h.astype(jnp.bfloat16)
        wb = w_qkv.astype(jnp.bfloat16)
        # bf16 q/k/v via einsum_bf16: the attention kernel consumes bf16
        # tiles anyway, and keeping the projections (= the kernel's saved
        # residuals) in bf16 halves their HBM footprint — at the flagship
        # shape the f32 version sat on the 15.75GB ceiling and XLA
        # spilled (r4 ablation: attention cost 178ms in-model vs 87ms
        # isolated); the backward transpose dots still accumulate f32
        q = einsum_bf16("btd,dhf->bhtf", hb, wb[..., :hd])
        k = einsum_bf16("btd,dhf->bhtf", hb, wb[..., hd:2 * hd])
        v = einsum_bf16("btd,dhf->bhtf", hb, wb[..., 2 * hd:])
        if in_mesh:
            # full-tile chunk: the flash/recompute backward keeps the
            # dense tile memory-safe; long-seq configs shrink the tile
            # via the chunk arg (lax fallback only)
            att = ring_attention(q, k, v, "sp", sp, causal=causal_ring,
                                 mxu_dtype=jnp.bfloat16, chunk=T,
                                 layout="bhtd")
        else:
            from ompi_tpu.ops.ring_attention import reference_attention

            tr = lambda a: jnp.transpose(a, (0, 2, 1, 3))
            att = tr(reference_attention(tr(q), tr(k), tr(v), causal=True))
        # row-parallel output projection contracted directly over (h, d):
        # no [B,T,H*hd] relayout of the attention output
        wo = blk["wo"].reshape(h_local, hd, cfg.d_model)
        out = jnp.einsum("bhtf,hfd->btd", att.astype(jnp.bfloat16),
                         wo.astype(jnp.bfloat16),
                         preferred_element_type=jnp.float32)
        if in_mesh:
            out = axes.allreduce(out, "tp")  # MPI_Allreduce on ICI
        x = x + out

        h2 = _ln(x, blk["ln2"])
        # the saved relu residual ([B,T,d_ff], the layer's largest
        # activation) is stored bf16 (half-size) with f32-accumulated
        # backward via einsum_bf16
        ff1 = jnp.maximum(einsum_bf16("btd,df->btf",
                                      h2.astype(jnp.bfloat16),
                                      blk["w1"].astype(jnp.bfloat16)),
                          jnp.bfloat16(0))
        ff = _mm(ff1, blk["w2"])
        if in_mesh:
            ff = axes.allreduce(ff, "tp")
        return x + ff

    if cfg.remat:
        import jax

        block = jax.checkpoint(block)
    for blk in params["blocks"]:
        x = block(x, blk)

    return _ln(x, params["ln_f"])


def forward_local(params, tokens, cfg: Config, tp: int = 1, sp: int = 1,
                  in_mesh: bool = False, causal_ring: bool = True):
    """Forward to logits [B, T, vocab] (dense — for inference/tests; the
    training loss streams the vocab projection instead, see
    ops/softmax_xent.py)."""
    from ompi_tpu.ops.softmax_xent import logits_matmul

    x = features_local(params, tokens, cfg, tp=tp, sp=sp, in_mesh=in_mesh,
                       causal_ring=causal_ring)
    return logits_matmul(x, params["embed"])


def forward(params, tokens, cfg: Config):
    """Single-device forward (jittable as-is) — the graft entry fn."""
    return forward_local(params, tokens, cfg, tp=1, sp=1, in_mesh=False)


def _loss_local(params, tokens, targets, cfg: Config, tp: int, sp: int,
                denom: float):
    from ompi_tpu.ops.softmax_xent import softmax_xent_sum

    x = features_local(params, tokens, cfg, tp=tp, sp=sp, in_mesh=True)
    return softmax_xent_sum(x, params["embed"], targets, 128,
                            ("dp", "sp")) / denom


def make_train_step(mesh, cfg: Config):
    """Build the jitted full training step over a (dp, sp, tp) mesh:
    forward + backward + dp/sp gradient allreduce + SGD update."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import NamedSharding, PartitionSpec as P

    dp = int(mesh.shape["dp"])
    sp = int(mesh.shape["sp"])
    tp = int(mesh.shape["tp"])
    pspecs = param_specs(cfg)
    tok_spec = P("dp", "sp")

    def step_local(params, tokens, targets):
        B, T = tokens.shape
        denom = float(B * T * dp * sp)

        def lossfn(p):
            return _loss_local(p, tokens, targets, cfg, tp, sp, denom)

        loss, grads = jax.value_and_grad(lossfn)(params)
        # NOTE on the gradient allreduce (the Horovod-style traffic of
        # BASELINE config #5): params are replicated over (dp, sp), so
        # shard_map's replication-preserving AD *auto-inserts* the psum of
        # their cotangents across dp/sp — the collective is in the compiled
        # program without an explicit call here (an explicit psum would
        # double-count; verified by loss-trajectory tests).
        loss = lax.psum(loss, ("dp", "sp"))
        new_params = jax.tree.map(
            lambda p, g: (p - cfg.lr * g).astype(p.dtype), params, grads)
        return loss, new_params

    from ompi_tpu.parallel.axes import shard_map_compat

    step = shard_map_compat(step_local, mesh,
                            (pspecs, tok_spec, tok_spec),
                            (P(), pspecs))
    jitted = jax.jit(step)

    def place(params, tokens, targets):
        params = jax.tree.map(
            lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
            params, pspecs)
        sh = NamedSharding(mesh, tok_spec)
        return params, jax.device_put(tokens, sh), jax.device_put(targets, sh)

    return jitted, place
