"""mpicc — compiler wrapper for the C binding.

Reference: ompi/tools/wrappers (mpicc adds the include/lib flags so
`mpicc ring.c -o ring` just works). Here the wrapper additionally
builds the binding library itself on first use (the same on-demand
pattern as ompi_tpu/native/__init__.py) and bakes an rpath so the
produced binary runs without LD_LIBRARY_PATH:

    python -m ompi_tpu.tools.mpicc ring.c -o ring
    python -m ompi_tpu.tools.mpirun -np 4 ./ring

Pass ``--showme`` to print the flags instead of compiling (the
reference wrapper's introspection contract).
"""

from __future__ import annotations

import os
import subprocess
import sys
import sysconfig
import tempfile
from typing import List, Optional

_HERE = os.path.dirname(os.path.abspath(__file__))
_NATIVE = os.path.join(os.path.dirname(_HERE), "native")
_CAPI_SRC = os.path.join(_NATIVE, "capi.c")


def _python_embed_flags() -> List[str]:
    """Include + link flags for embedding this interpreter (what
    `python3-config --includes --embed --ldflags` reports, but read
    from sysconfig so it matches THIS python even in venvs)."""
    inc = sysconfig.get_path("include")
    libdir = sysconfig.get_config_var("LIBDIR") or ""
    ver = sysconfig.get_config_var("LDVERSION") or \
        sysconfig.get_config_var("VERSION")
    flags = [f"-I{inc}"]
    if libdir:
        flags += [f"-L{libdir}", f"-Wl,-rpath,{libdir}"]
    flags += [f"-lpython{ver}", "-ldl", "-lm"]
    return flags


_CAPI_HDR = os.path.join(_NATIVE, "mpi.h")


def _safe_dir(d: str) -> bool:
    """Only trust/build in a dir we own that nobody else can write —
    a world-writable fallback would let another local user plant a
    libompi_tpu_c.so that gets rpath'd into the victim's binary."""
    try:
        st = os.stat(d)
    except OSError:
        return False
    return st.st_uid == os.getuid() and not (st.st_mode & 0o022)


def _lib_dirs() -> List[str]:
    """Candidate homes for libompi_tpu_c.so: next to the sources, then
    a per-user 0700 cache dir for read-only installs."""
    cache = os.environ.get("XDG_CACHE_HOME") or \
        os.path.join(os.path.expanduser("~"), ".cache")
    return [_NATIVE, os.path.join(cache, "ompi_tpu_c")]


def build_capi(cc: str = "cc") -> Optional[str]:
    """Compile libompi_tpu_c.so if stale (vs BOTH sources — a header
    edit must rebuild or the lib's struct offsets go stale); returns
    the path or None. Falls back to a per-user cache dir when the
    package directory is read-only."""
    srcs = [_CAPI_SRC, _CAPI_HDR]
    missing = [s for s in srcs if not os.path.exists(s)]
    if missing:
        sys.stderr.write(
            "mpicc: binding sources missing (%s) — reinstall with the "
            "package data intact\n" % ", ".join(missing))
        return None
    src_mtime = max(os.path.getmtime(s) for s in srcs)
    for d in _lib_dirs():
        so = os.path.join(d, "libompi_tpu_c.so")
        if _safe_dir(d) and os.path.exists(so) and \
                os.path.getmtime(so) >= src_mtime:
            return so
    from ompi_tpu.native import compile_so

    cmd = [cc, "-O2", "-shared", "-fPIC", f"-I{_NATIVE}"] + \
        _python_embed_flags()
    for d in _lib_dirs():
        try:
            os.makedirs(d, mode=0o700, exist_ok=True)
        except OSError:
            continue
        # skip unwritable/untrusted dirs BEFORE compiling: a genuine
        # compiler error must fail once, not be retried per dir
        if not (_safe_dir(d) and os.access(d, os.W_OK)):
            continue
        return compile_so(cmd, [_CAPI_SRC],
                          os.path.join(d, "libompi_tpu_c.so"),
                          on_error=lambda m: sys.stderr.write(
                              f"mpicc: {m}\n"))
    sys.stderr.write("mpicc: no writable owner-only directory for "
                     "libompi_tpu_c.so\n")
    return None


def wrapper_flags(libdir: str = _NATIVE) -> List[str]:
    """The flags mpicc injects around the user's arguments."""
    return [f"-I{_NATIVE}", f"-L{libdir}", f"-Wl,-rpath,{libdir}",
            "-lompi_tpu_c"] + _python_embed_flags()


def main(argv: Optional[List[str]] = None) -> int:
    argv = sys.argv[1:] if argv is None else list(argv)
    cc = os.environ.get("OMPI_TPU_CC", "cc")
    if "--showme" in argv:
        # point -L/-rpath at wherever the lib actually lives (a
        # read-only install builds into the cache dir, not _NATIVE)
        libdir = _NATIVE
        for d in _lib_dirs():
            if os.path.exists(os.path.join(d, "libompi_tpu_c.so")):
                libdir = d
                break
        print(" ".join([cc] + wrapper_flags(libdir)))
        return 0
    so = build_capi(cc)
    if so is None:
        return 1
    # user args first so their -o/-c land naturally; link flags last
    # (the classic wrapper ordering: libraries after objects)
    cmd = [cc] + argv + wrapper_flags(os.path.dirname(so))
    return subprocess.run(cmd).returncode


if __name__ == "__main__":
    sys.exit(main())
