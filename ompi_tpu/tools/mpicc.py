"""mpicc — compiler wrapper for the C binding.

Reference: ompi/tools/wrappers (mpicc adds the include/lib flags so
`mpicc ring.c -o ring` just works). Here the wrapper additionally
builds the binding library itself on first use (the same on-demand
pattern as ompi_tpu/native/__init__.py) and bakes an rpath so the
produced binary runs without LD_LIBRARY_PATH:

    python -m ompi_tpu.tools.mpicc ring.c -o ring
    python -m ompi_tpu.tools.mpirun -np 4 ./ring

Pass ``--showme`` to print the flags instead of compiling (the
reference wrapper's introspection contract).
"""

from __future__ import annotations

import os
import subprocess
import sys
import sysconfig
import tempfile
from typing import List, Optional

_HERE = os.path.dirname(os.path.abspath(__file__))
_NATIVE = os.path.join(os.path.dirname(_HERE), "native")
_CAPI_SRC = os.path.join(_NATIVE, "capi.c")
_CAPI_SO = os.path.join(_NATIVE, "libompi_tpu_c.so")


def _python_embed_flags() -> List[str]:
    """Include + link flags for embedding this interpreter (what
    `python3-config --includes --embed --ldflags` reports, but read
    from sysconfig so it matches THIS python even in venvs)."""
    inc = sysconfig.get_path("include")
    libdir = sysconfig.get_config_var("LIBDIR") or ""
    ver = sysconfig.get_config_var("LDVERSION") or \
        sysconfig.get_config_var("VERSION")
    flags = [f"-I{inc}"]
    if libdir:
        flags += [f"-L{libdir}", f"-Wl,-rpath,{libdir}"]
    flags += [f"-lpython{ver}", "-ldl", "-lm"]
    return flags


_CAPI_HDR = os.path.join(_NATIVE, "mpi.h")


def build_capi(cc: str = "cc") -> Optional[str]:
    """Compile libompi_tpu_c.so if stale (vs BOTH sources — a header
    edit must rebuild or the lib's struct offsets go stale); returns
    the path or None."""
    srcs = [_CAPI_SRC, _CAPI_HDR]
    missing = [s for s in srcs if not os.path.exists(s)]
    if missing:
        sys.stderr.write(
            "mpicc: binding sources missing (%s) — reinstall with the "
            "package data intact\n" % ", ".join(missing))
        return None
    if os.path.exists(_CAPI_SO) and os.path.getmtime(_CAPI_SO) >= \
            max(os.path.getmtime(s) for s in srcs):
        return _CAPI_SO
    fd, tmp = tempfile.mkstemp(suffix=".so", dir=_NATIVE)
    os.close(fd)
    cmd = [cc, "-O2", "-shared", "-fPIC", f"-I{_NATIVE}", _CAPI_SRC,
           "-o", tmp] + _python_embed_flags()
    try:
        subprocess.run(cmd, check=True, capture_output=True, text=True,
                       timeout=180)
        os.rename(tmp, _CAPI_SO)
        return _CAPI_SO
    except (subprocess.SubprocessError, OSError) as e:
        sys.stderr.write("libompi_tpu_c build failed: %s\n%s\n"
                         % (" ".join(cmd),
                            getattr(e, "stderr", "") or str(e)))
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return None


def wrapper_flags() -> List[str]:
    """The flags mpicc injects around the user's arguments."""
    return [f"-I{_NATIVE}", f"-L{_NATIVE}", f"-Wl,-rpath,{_NATIVE}",
            "-lompi_tpu_c"] + _python_embed_flags()


def main(argv: Optional[List[str]] = None) -> int:
    argv = sys.argv[1:] if argv is None else list(argv)
    cc = os.environ.get("OMPI_TPU_CC", "cc")
    if "--showme" in argv:
        print(" ".join([cc] + wrapper_flags()))
        return 0
    if build_capi(cc) is None:
        return 1
    # user args first so their -o/-c land naturally; link flags last
    # (the classic wrapper ordering: libraries after objects)
    cmd = [cc] + argv + wrapper_flags()
    return subprocess.run(cmd).returncode


if __name__ == "__main__":
    sys.exit(main())
