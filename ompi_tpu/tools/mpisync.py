"""mpisync — cross-rank clock offset measurement.

Reference: ompi/tools/mpisync (Hunold/Traeff-style clock sync used to
align per-rank trace timestamps). The classic midpoint estimator: rank
0 ping-pongs a timestamp with every peer; for the minimum-RTT exchange
(least queueing noise), offset_r = t_peer - (t_send + t_recv)/2.
CLOCK_MONOTONIC is machine-wide on Linux, so same-host offsets measure
the method's own error bar; cross-host offsets measure real skew.

Run:  mpirun -np N ompi_tpu/tools/mpisync.py [iters] [--out offsets.json]

Output (rank 0): one line per rank — offset seconds + min RTT — the
same table the reference tool feeds to its trace-alignment scripts.
``--out`` additionally writes a ``{rank: offset_seconds}`` JSON map,
the input ``tools/trace_merge.py --offsets`` consumes to align
per-rank trace files onto rank 0's timeline.
"""

from __future__ import annotations

import sys
import time

import numpy as np

SYNC_TAG = 42


def measure_offsets(comm, iters: int = 25):
    """rank 0 -> {rank: (offset_s, min_rtt_s)}; peers serve echoes."""
    me = comm.Get_rank()
    n = comm.Get_size()
    if me == 0:
        table = {0: (0.0, 0.0)}
        buf = np.zeros(1, np.float64)
        for peer in range(1, n):
            best_rtt = float("inf")
            best_off = 0.0
            for _ in range(iters):
                t0 = time.monotonic()
                comm.Send(np.array([t0], np.float64), dest=peer,
                          tag=SYNC_TAG)
                comm.Recv(buf, source=peer, tag=SYNC_TAG)
                t1 = time.monotonic()
                rtt = t1 - t0
                if rtt < best_rtt:
                    best_rtt = rtt
                    best_off = float(buf[0]) - (t0 + t1) / 2.0
            table[peer] = (best_off, best_rtt)
        return table
    echo = np.zeros(1, np.float64)
    for _ in range(iters):
        comm.Recv(echo, source=0, tag=SYNC_TAG)
        comm.Send(np.array([time.monotonic()], np.float64), dest=0,
                  tag=SYNC_TAG)
    return None


def main() -> int:
    import ompi_tpu
    from ompi_tpu import COMM_WORLD

    args = sys.argv[1:]
    out_path = None
    if "--out" in args:
        i = args.index("--out")
        if i + 1 >= len(args):
            sys.stderr.write(
                "usage: mpisync [iters] [--out offsets.json]\n")
            return 2
        out_path = args[i + 1]
        del args[i:i + 2]
    iters = int(args[0]) if args else 25
    table = measure_offsets(COMM_WORLD, iters)
    if table is not None:
        for rank in sorted(table):
            off, rtt = table[rank]
            sys.stdout.write(
                f"mpisync rank {rank}: offset {off:+.6e} s  "
                f"rtt {rtt:.6e} s\n")
        sys.stdout.flush()
        if out_path:
            import json

            with open(out_path, "w") as f:
                json.dump({str(r): table[r][0] for r in table}, f)
    COMM_WORLD.Barrier()
    ompi_tpu.Finalize()
    return 0


if __name__ == "__main__":
    sys.exit(main())
