"""fake_rsh — in-tree remote-execution shim for the launch-agent path.

Reference analog: prte's plm tests stub the ssh agent the same way (the
agent contract is just argv = [agent..., host, command]). This shim obeys
that contract but runs the command on the local box with a SCRUBBED
environment — every OMPI_TPU_*/PYTHONPATH/JAX_* variable inherited from
the launcher is dropped, so the command line must carry the entire launch
contract exactly as it would have to over real ssh. CI on a single box
therefore proves the remote marshalling path end to end.

Usage (what mpirun execs): python -m ompi_tpu.tools.fake_rsh HOST COMMAND
"""

from __future__ import annotations

import os
import sys

from ompi_tpu.runtime.plm import _FORWARD_ENV


def main(argv=None) -> "int":
    argv = sys.argv[1:] if argv is None else argv
    if len(argv) < 2:
        print("usage: fake_rsh HOST COMMAND", file=sys.stderr)
        return 2
    _host, command = argv[0], argv[1]
    # scrub exactly the complement of what plm.remote_command marshals
    # (plus the device-pool grant mpirun deliberately withholds), so a
    # marshalling regression can't be masked by inherited state
    env = {k: v for k, v in os.environ.items()
           if not (k.startswith("OMPI_TPU_") or k.startswith("JAX_")
                   or k in _FORWARD_ENV or k == "PALLAS_AXON_POOL_IPS")}
    # exec, not fork: the job-teardown SIGTERM mpirun sends must land on
    # the rank itself (our command string exec-chains sh -> env ->
    # python), not die with a wrapper while the rank runs on orphaned
    os.execve("/bin/sh", ["/bin/sh", "-c", command], env)


if __name__ == "__main__":
    sys.exit(main())
