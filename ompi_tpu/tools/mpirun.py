"""mpirun — process-mode launcher.

Reference: ompi/tools/mpirun/main.c (a thin wrapper handing off to PRRTE's
prterun) + the prted PMIx server it relies on. Here the launcher hosts the
modex server itself (no external runtime dependency) and spawns one Python
process per rank with the launch-contract env:

    OMPI_TPU_RANK, OMPI_TPU_SIZE, OMPI_TPU_MODEX

Multi-host jobs (reference: prte's plm/ssh daemon launch): ``--hostfile``
or ``--host`` place ranks onto nodes; remote ranks are started through a
pluggable launch agent (``--launch-agent``, default ssh — the
plm_ssh_agent analog; ``fake`` is the in-tree CI shim) with the launch
contract marshalled into the remote command line, and the modex server
listens on all interfaces advertising its best non-loopback address.

Usage:
    python -m ompi_tpu.tools.mpirun -np 4 [--mca k v]... script.py [args...]
    python -m ompi_tpu.tools.mpirun -np 4 --host n1:2,n2:2 script.py
"""

from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
from typing import List, Optional

from ompi_tpu.runtime import plm
from ompi_tpu.runtime.modex import ModexServer


def main(argv: List[str] | None = None) -> int:
    parser = argparse.ArgumentParser(prog="mpirun (ompi_tpu)")
    parser.add_argument("-np", "-n", type=int, required=True, dest="np",
                        help="number of ranks")
    parser.add_argument("--mca", nargs=2, action="append", default=[],
                        metavar=("VAR", "VALUE"),
                        help="set an MCA variable (framework_name value)")
    parser.add_argument("--timeout", type=float, default=600.0,
                        help="job wall-clock limit in seconds")
    parser.add_argument("--hostfile", "--machinefile", default=None,
                        help="hostfile: one 'node [slots=N]' per line")
    parser.add_argument("--host", "-H", default=None,
                        help="inline host list: n1[:slots],n2[:slots]")
    parser.add_argument("--launch-agent", default="ssh",
                        help="remote-exec agent for non-local hosts "
                             "(argv contract: AGENT HOST COMMAND; 'fake' "
                             "= in-tree local shim for CI)")
    parser.add_argument("--with-tpu", action="store_true",
                        help="let ranks claim TPU devices (default: ranks "
                             "are host-only; the device path belongs to "
                             "mesh mode / the single controller)")
    parser.add_argument("program", help="python script to run")
    parser.add_argument("args", nargs=argparse.REMAINDER)
    opts = parser.parse_args(argv)

    placement: Optional[List[str]] = None
    if opts.hostfile:
        placement = plm.assign_ranks(plm.parse_hostfile(opts.hostfile),
                                     opts.np)
    elif opts.host:
        placement = plm.assign_ranks(plm.parse_host_list(opts.host),
                                     opts.np)

    multihost = placement is not None and any(
        not plm.is_local(h) for h in placement)
    if multihost:
        # remote ranks dial back over the network: listen everywhere,
        # advertise the best non-loopback address (if/reachable analog)
        from ompi_tpu.runtime.ifaces import best_local_addr

        adv = best_local_addr() or "127.0.0.1"
        server = ModexServer(opts.np, host="0.0.0.0", advertise=adv)
    else:
        server = ModexServer(opts.np)
    env_base = dict(os.environ)
    env_base["OMPI_TPU_SIZE"] = str(opts.np)
    env_base["OMPI_TPU_MODEX"] = server.address
    if multihost:
        # ranks bind/advertise their own non-loopback addresses too
        env_base["OMPI_TPU_MULTIHOST"] = "1"
    # ranks run `python script.py`, which puts the script's dir (not our
    # cwd) on sys.path — propagate the launcher's import environment so
    # `import ompi_tpu` resolves the same way it did for the launcher
    pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    extra = [os.getcwd(), pkg_root]
    prior = env_base.get("PYTHONPATH")
    if prior:
        extra.append(prior)
    env_base["PYTHONPATH"] = os.pathsep.join(extra)
    if not opts.with_tpu:
        # A TPU chip is an exclusive grant; N rank interpreters racing to
        # claim it deadlock at startup. Process-mode ranks are host-only
        # unless explicitly opted in (the device path is mesh mode's).
        env_base.pop("PALLAS_AXON_POOL_IPS", None)
        env_base["JAX_PLATFORMS"] = "cpu"
    for var, value in opts.mca:
        env_base[f"OMPI_TPU_MCA_{var}"] = value

    # a SIGTERM (shell timeout, operator ^C relayed by a wrapper) must
    # run the finally block below — a default-handler death leaks every
    # rank as an orphan spinning on a dead modex (observed: stale ranks
    # from killed jobs loading the CI host for hours)
    signal.signal(signal.SIGTERM, lambda *_: sys.exit(143))

    procs: List[subprocess.Popen] = []
    try:
        for rank in range(opts.np):
            env = dict(env_base)
            env["OMPI_TPU_RANK"] = str(rank)
            host = placement[rank] if placement else None
            procs.append(plm.spawn_rank(host, opts.launch_agent, env,
                                        opts.program, opts.args,
                                        os.getcwd()))
        # Poll ALL children: the first abnormal exit tears down the whole
        # job immediately (reference: prterun kills the job on abnormal
        # termination) — waiting rank-by-rank would let a peer blocked on
        # the dead rank hang until the full job timeout.
        import time

        rc = 0
        deadline = time.monotonic() + opts.timeout
        remaining = set(range(opts.np))
        while remaining:
            for i in list(remaining):
                code = procs[i].poll()
                if code is not None:
                    remaining.discard(i)
                    if code != 0 and rc == 0:
                        rc = code
            if rc != 0:
                break
            if time.monotonic() > deadline:
                rc = 124
                break
            if remaining:
                time.sleep(0.05)
        if rc != 0:
            for p in procs:
                if p.poll() is None:
                    p.send_signal(signal.SIGTERM)
            grace = time.monotonic() + 2.0
            while (any(p.poll() is None for p in procs)
                   and time.monotonic() < grace):
                time.sleep(0.05)
        return rc
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        server.close()


if __name__ == "__main__":
    sys.exit(main())
