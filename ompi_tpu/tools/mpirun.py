"""mpirun — process-mode launcher.

Reference: ompi/tools/mpirun/main.c (a thin wrapper handing off to PRRTE's
prterun) + the prted PMIx server it relies on. Here the launcher hosts the
modex server itself (no external runtime dependency) and spawns one Python
process per rank with the launch-contract env:

    OMPI_TPU_RANK, OMPI_TPU_SIZE, OMPI_TPU_MODEX

Usage:
    python -m ompi_tpu.tools.mpirun -np 4 [--mca k v]... script.py [args...]
"""

from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
from typing import List

from ompi_tpu.runtime.modex import ModexServer


def main(argv: List[str] | None = None) -> int:
    parser = argparse.ArgumentParser(prog="mpirun (ompi_tpu)")
    parser.add_argument("-np", "-n", type=int, required=True, dest="np",
                        help="number of ranks")
    parser.add_argument("--mca", nargs=2, action="append", default=[],
                        metavar=("VAR", "VALUE"),
                        help="set an MCA variable (framework_name value)")
    parser.add_argument("--timeout", type=float, default=600.0,
                        help="job wall-clock limit in seconds")
    parser.add_argument("--with-tpu", action="store_true",
                        help="let ranks claim TPU devices (default: ranks "
                             "are host-only; the device path belongs to "
                             "mesh mode / the single controller)")
    parser.add_argument("program", help="python script to run")
    parser.add_argument("args", nargs=argparse.REMAINDER)
    opts = parser.parse_args(argv)

    server = ModexServer(opts.np)
    env_base = dict(os.environ)
    env_base["OMPI_TPU_SIZE"] = str(opts.np)
    env_base["OMPI_TPU_MODEX"] = server.address
    # ranks run `python script.py`, which puts the script's dir (not our
    # cwd) on sys.path — propagate the launcher's import environment so
    # `import ompi_tpu` resolves the same way it did for the launcher
    pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    extra = [os.getcwd(), pkg_root]
    prior = env_base.get("PYTHONPATH")
    if prior:
        extra.append(prior)
    env_base["PYTHONPATH"] = os.pathsep.join(extra)
    if not opts.with_tpu:
        # A TPU chip is an exclusive grant; N rank interpreters racing to
        # claim it deadlock at startup. Process-mode ranks are host-only
        # unless explicitly opted in (the device path is mesh mode's).
        env_base.pop("PALLAS_AXON_POOL_IPS", None)
        env_base["JAX_PLATFORMS"] = "cpu"
    for var, value in opts.mca:
        env_base[f"OMPI_TPU_MCA_{var}"] = value

    procs: List[subprocess.Popen] = []
    try:
        for rank in range(opts.np):
            env = dict(env_base)
            env["OMPI_TPU_RANK"] = str(rank)
            procs.append(subprocess.Popen(
                [sys.executable, opts.program, *opts.args], env=env))
        # Poll ALL children: the first abnormal exit tears down the whole
        # job immediately (reference: prterun kills the job on abnormal
        # termination) — waiting rank-by-rank would let a peer blocked on
        # the dead rank hang until the full job timeout.
        import time

        rc = 0
        deadline = time.monotonic() + opts.timeout
        remaining = set(range(opts.np))
        while remaining:
            for i in list(remaining):
                code = procs[i].poll()
                if code is not None:
                    remaining.discard(i)
                    if code != 0 and rc == 0:
                        rc = code
            if rc != 0:
                break
            if time.monotonic() > deadline:
                rc = 124
                break
            if remaining:
                time.sleep(0.05)
        if rc != 0:
            for p in procs:
                if p.poll() is None:
                    p.send_signal(signal.SIGTERM)
            grace = time.monotonic() + 2.0
            while (any(p.poll() is None for p in procs)
                   and time.monotonic() < grace):
                time.sleep(0.05)
        return rc
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        server.close()


if __name__ == "__main__":
    sys.exit(main())
