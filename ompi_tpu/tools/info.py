"""ompi_tpu_info — introspection CLI.

Reference: ompi/tools/ompi_info — dumps every framework, component, and
MCA parameter so users can see exactly what the library will select and
which knobs exist. Usage:

    python -m ompi_tpu.tools.info                 # everything, level <= 6
    python -m ompi_tpu.tools.info --level 9       # developer params too
    python -m ompi_tpu.tools.info --param btl     # one framework's vars
    python -m ompi_tpu.tools.info --pvars         # performance variables
"""

from __future__ import annotations

import argparse
import sys


def _load_everything() -> None:
    """Import every component module so registries are populated (the
    CLI analog of the reference's component-repository scan —
    mca_base_component_repository.c:365)."""
    import ompi_tpu.runtime.state  # btl/coll component side effects
    import ompi_tpu.accelerator  # accelerator framework
    import ompi_tpu.coll.xla  # mesh collectives
    import ompi_tpu.coll.neighbor  # topology collectives
    import ompi_tpu.runtime.spc  # spc vars
    import ompi_tpu.runtime.trace  # trace cvars + pvars
    import ompi_tpu.runtime.metrics  # metrics cvars + straggler/critpath pvars (metrics_critpath_steps/bound_rank/bound_category)
    import ompi_tpu.runtime.sanitizer  # sanitizer cvars + pvar
    import ompi_tpu.pml.monitoring  # pml_monitoring enable cvar
    import ompi_tpu.runtime.topology  # topo binding vars
    import ompi_tpu.pml.ob1  # pml vars
    import ompi_tpu.pml.vprotocol  # pml_v message-logging vars
    import ompi_tpu.runtime.smsc  # single-copy (cma) vars
    import ompi_tpu.io.file  # collective-IO aggregator vars
    import ompi_tpu.ft.era  # agreement vars
    import ompi_tpu.ft.detector  # heartbeat detector vars
    import ompi_tpu.ft.inject  # chaos-plan vars + injected-faults pvar
    import ompi_tpu.ft.recovery  # failover/retry/respawn pvars
    import ompi_tpu.ft.diskless  # diskless ckpt cvars + ft_ckpt_* pvars
    import ompi_tpu.runtime.dpm  # dynamic-process spawn vars
    import ompi_tpu.reshard.plan  # reshard cvars + plans_compiled pvar
    import ompi_tpu.reshard.exec  # reshard exec/bytes/staging pvars
    import ompi_tpu.quant  # quant_* cvars + colls/bytes pvars
    import ompi_tpu.quant.negotiate  # negotiation topics
    import ompi_tpu.coll.quant  # quantized-collectives component
    import ompi_tpu.coll.hier.compose  # hier composer + coll_hier cvars
    import ompi_tpu.coll.hier  # hier_plan_hits/misses/retunes pvars
    import ompi_tpu.btl.tcp  # btl_tcp compress/writev/copy_mode + reliable/retx_*/link_* cvars, datapath + link pvars
    import ompi_tpu.runtime.progress  # idle-block cvar + progress_idle_blocks pvar
    import ompi_tpu.runtime.mpool  # BufferPool mpool_pool_* pvars
    import ompi_tpu.coll.sched  # coll_round_* window/copy_mode cvars + datapath pvars
    import ompi_tpu.coll.persist  # coll_persist_* cvars + persist_* replay pvars
    import ompi_tpu.qos  # QoS classes: btl_tcp_shape_enable/segment + qos_* cvars/pvars
    import ompi_tpu.runtime.forensics  # stall-forensics cvars + forensics_* pvars
    import ompi_tpu.runtime.linkmodel  # fabric telemetry: linkmodel_* cvars + rtt/goodput/probe pvars
    import ompi_tpu.serve  # elastic serving: serve_* SLO/RTO/admission cvars + pvars
    # (btl/tcp.py above also carries the btl_tcp_shape_* scheduler knobs)
    # mpilint/mpiracer/mpiown (ompi_tpu/analysis/) are build-time gates
    # by design: they register no cvars/pvars, so there is nothing to
    # load


def print_header(out) -> None:
    from ompi_tpu.version import __version__

    print(f"ompi_tpu: {__version__}", file=out)
    print(f"python:   {sys.version.split()[0]}", file=out)
    try:
        import jax

        print(f"jax:      {jax.__version__}", file=out)
    except Exception:
        print("jax:      unavailable", file=out)


def print_components(out) -> None:
    from ompi_tpu.mca.component import all_frameworks

    print("\nframeworks / components "
          "(reference: ompi_info component list):", file=out)
    for fname, fw in sorted(all_frameworks().items()):
        comps = sorted(fw.components.values(),
                       key=lambda c: -c.PRIORITY)
        names = ", ".join(f"{c.NAME} (priority {c.PRIORITY})"
                          for c in comps) or "-"
        print(f"  {fname:<14} {fw.description}", file=out)
        print(f"  {'':<14} components: {names}", file=out)


def print_vars(out, level: int, framework: str = "") -> None:
    from ompi_tpu.mca.var import all_vars

    print(f"\nmca parameters (level <= {level}"
          + (f", framework '{framework}'" if framework else "") + "):",
          file=out)
    for key, var in sorted(all_vars().items()):
        if var.level > level:
            continue
        if framework and var.framework != framework:
            continue
        src = var.source.name.lower()
        print(f"  {var.full_name:<36} = {var.value!r:<14} "
              f"[{var.typ.__name__}, level {var.level}, source {src}]",
              file=out)
        if var.help:
            print(f"  {'':<36}   {var.help}", file=out)


def print_pvars(out) -> None:
    from ompi_tpu.mca.var import all_pvars

    print("\nperformance variables (reference: MPI_T pvars / "
          "mca_base_pvar.c):", file=out)
    pvars = all_pvars()
    if not pvars:
        print("  (none recorded yet)", file=out)
    for key, pv in sorted(pvars.items()):
        print(f"  {pv.full_name:<36} = {pv.value!r}", file=out)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="ompi_tpu_info",
        description="Dump frameworks, components, and MCA parameters")
    ap.add_argument("--level", type=int, default=6,
                    help="max parameter level to show (1-9, default 6)")
    ap.add_argument("--param", default="",
                    help="restrict parameters to one framework")
    ap.add_argument("--pvars", action="store_true",
                    help="show performance variables")
    ap.add_argument("--all", action="store_true",
                    help="everything incl. level-9 params and pvars")
    opts = ap.parse_args(argv)
    if opts.all:
        opts.level, opts.pvars = 9, True

    _load_everything()
    out = sys.stdout
    print_header(out)
    print_components(out)
    print_vars(out, opts.level, opts.param)
    if opts.pvars:
        print_pvars(out)
    return 0


if __name__ == "__main__":
    sys.exit(main())
