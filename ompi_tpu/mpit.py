"""MPI_T — the MPI tool information interface.

Reference: ompi/mpi/tool (2,852 LoC: init_thread.c, cvar_*.c, pvar_*.c,
category_*.c, event_*.c over the opal/mca/base registries). The repo's
cvar/pvar *backends* live in mca/var.py; this module is the handle-based
programmatic surface a profiler binds to, plus the MPI-4 event system:

- **cvars**: stable indices over the registered control variables;
  handles read and (scope permitting) write them
  (cvar_handle_alloc.c / cvar_read.c / cvar_write.c).
- **pvars**: per-session handles with start/stop/read/reset semantics —
  reset baselines a counter, stop freezes the reading
  (pvar_session_create.c, pvar_start.c, pvar_read.c).
- **categories**: one per framework, grouping its cvars/pvars/events
  (category_get_info.c; the reference registers one category per
  project/framework/component).
- **events**: typed event sources fired at component selection, comm
  creation/revocation, and process-failure detection; callbacks receive
  an immutable instance carrying a timestamp and the event payload
  (event_handle_alloc.c, event_register_callback.c,
  event_get_timestamp.c; MPI-4 §14.3.8).

Index stability: indices are append-only for the lifetime of the
process (the MPI_T contract — get_num may grow, existing indices never
move), guaranteed by dict insertion order in the backing registries.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from ompi_tpu.core.errors import MPIError, ERR_ARG, ERR_OTHER
from ompi_tpu.mca import var as _var

# ------------------------------------------------------------------ init
_init_count = 0
_init_lock = threading.Lock()


def init_thread() -> None:
    """MPI_T_init_thread: refcounted, independent of MPI_Init
    (init_thread.c — MPI_T may be used before MPI_Init)."""
    global _init_count
    with _init_lock:
        _init_count += 1


def finalize() -> None:
    global _init_count
    with _init_lock:
        if _init_count == 0:
            raise MPIError(ERR_OTHER, "MPI_T finalize without init")
        _init_count -= 1


def _check_init() -> None:
    if _init_count == 0:
        raise MPIError(ERR_OTHER, "MPI_T not initialized")


# ----------------------------------------------------------------- cvars
@dataclasses.dataclass(frozen=True)
class CvarInfo:
    index: int
    name: str
    help: str
    level: int
    typ: type
    scope: str
    default: Any


def _cvar_list() -> List[_var.Var]:
    return list(_var.all_vars().values())


def cvar_get_num() -> int:
    _check_init()
    return len(_cvar_list())


def cvar_get_info(index: int) -> CvarInfo:
    _check_init()
    vs = _cvar_list()
    if not 0 <= index < len(vs):
        raise MPIError(ERR_ARG, f"cvar index {index} out of range")
    v = vs[index]
    return CvarInfo(index, v.full_name, v.help, v.level, v.typ,
                    v.scope.value, v.default)


def cvar_get_index(name: str) -> int:
    _check_init()
    for i, v in enumerate(_cvar_list()):
        if v.full_name == name:
            return i
    raise MPIError(ERR_ARG, f"no cvar named {name}")


class CvarHandle:
    """cvar_handle_alloc.c — a read/write handle onto one cvar."""

    def __init__(self, index: int):
        _check_init()
        vs = _cvar_list()
        if not 0 <= index < len(vs):
            raise MPIError(ERR_ARG, f"cvar index {index} out of range")
        self._var = vs[index]

    def read(self) -> Any:
        return self._var.value

    def write(self, value: Any) -> None:
        if self._var.scope == _var.VarScope.READONLY:
            raise MPIError(ERR_ARG,
                           f"{self._var.full_name} is read-only")
        self._var._apply(value, _var.VarSource.SET)


def cvar_handle_alloc(index: int) -> CvarHandle:
    return CvarHandle(index)


# ----------------------------------------------------------------- pvars
@dataclasses.dataclass(frozen=True)
class PvarInfo:
    index: int
    name: str
    help: str


def _pvar_list() -> List[_var.Pvar]:
    return list(_var.all_pvars().values())


def pvar_get_num() -> int:
    _check_init()
    return len(_pvar_list())


def pvar_get_info(index: int) -> PvarInfo:
    _check_init()
    ps = _pvar_list()
    if not 0 <= index < len(ps):
        raise MPIError(ERR_ARG, f"pvar index {index} out of range")
    p = ps[index]
    return PvarInfo(index, p.full_name, p.help)


def pvar_get_index(name: str) -> int:
    _check_init()
    for i, p in enumerate(_pvar_list()):
        if p.full_name == name:
            return i
    raise MPIError(ERR_ARG, f"no pvar named {name}")


class PvarSession:
    """pvar_session_create.c — handles are scoped to a session so
    concurrent tools keep independent baselines/start state; freeing
    the session invalidates its handles (pvar_session_free semantics)."""

    def __init__(self):
        _check_init()
        self._handles: List[PvarHandle] = []
        self._freed = False

    def handle_alloc(self, index: int) -> "PvarHandle":
        if self._freed:
            raise MPIError(ERR_ARG, "pvar session already freed")
        h = PvarHandle(self, index)
        self._handles.append(h)
        return h

    def free(self) -> None:
        self._freed = True
        self._handles.clear()  # mpiracer: disable=cross-thread-race — MPI_T sessions are tool-thread objects; the standard leaves concurrent session use undefined


class PvarHandle:
    """Start/stop/read/reset semantics over a read-only backend reader:
    reset re-baselines (numeric pvars read as deltas from the baseline),
    stop freezes the reading until start (pvar_start.c, pvar_read.c)."""

    def __init__(self, session: PvarSession, index: int):
        ps = _pvar_list()
        if not 0 <= index < len(ps):
            raise MPIError(ERR_ARG, f"pvar index {index} out of range")
        self._session = session
        self._pvar = ps[index]
        self._baseline: Any = 0
        self._started = True
        self._frozen: Any = None

    def _raw(self) -> Any:
        if self._session._freed:
            raise MPIError(ERR_ARG, "pvar handle's session was freed")
        return self._pvar.value

    def read(self) -> Any:
        if self._session._freed:
            raise MPIError(ERR_ARG, "pvar handle's session was freed")
        val = self._frozen if not self._started else self._raw()
        if isinstance(val, (int, float)) and isinstance(
                self._baseline, (int, float)):
            return val - self._baseline
        return val

    def reset(self) -> None:
        raw = self._raw()
        self._baseline = raw if isinstance(raw, (int, float)) else 0

    def start(self) -> None:
        self._started = True
        self._frozen = None

    def stop(self) -> None:
        self._frozen = self._raw()
        self._started = False


# ------------------------------------------------------------ categories
@dataclasses.dataclass(frozen=True)
class CategoryInfo:
    index: int
    name: str
    num_cvars: int
    num_pvars: int
    num_events: int


def _categories() -> List[str]:
    seen: Dict[str, None] = {}
    for v in _cvar_list():
        seen.setdefault(v.framework)
    for p in _pvar_list():
        seen.setdefault(p.framework)
    for e in _event_types:
        seen.setdefault(e.framework)
    return list(seen)


def category_get_num() -> int:
    _check_init()
    return len(_categories())


def category_get_info(index: int) -> CategoryInfo:
    _check_init()
    cats = _categories()
    if not 0 <= index < len(cats):
        raise MPIError(ERR_ARG, f"category index {index} out of range")
    name = cats[index]
    return CategoryInfo(
        index, name,
        len(category_get_cvars(index)),
        len(category_get_pvars(index)),
        len([e for e in _event_types if e.framework == name]))


def category_get_index(name: str) -> int:
    _check_init()
    cats = _categories()
    if name not in cats:
        raise MPIError(ERR_ARG, f"no category named {name}")
    return cats.index(name)


def _category_name(index: int) -> str:
    cats = _categories()
    if not 0 <= index < len(cats):
        raise MPIError(ERR_ARG, f"category index {index} out of range")
    return cats[index]


def category_get_cvars(index: int) -> List[int]:
    """Indices of the category's cvars (category_get_cvars.c)."""
    _check_init()
    name = _category_name(index)
    return [i for i, v in enumerate(_cvar_list()) if v.framework == name]


def category_get_pvars(index: int) -> List[int]:
    _check_init()
    name = _category_name(index)
    return [i for i, p in enumerate(_pvar_list()) if p.framework == name]


# ---------------------------------------------------------------- events
@dataclasses.dataclass(frozen=True)
class EventType:
    framework: str
    name: str
    help: str = ""

    @property
    def full_name(self) -> str:
        return f"{self.framework}_{self.name}"


@dataclasses.dataclass(frozen=True)
class EventInstance:
    """What a callback receives (event_read.c/event_get_timestamp.c:
    instances are immutable snapshots with a source timestamp)."""
    type: EventType
    timestamp: float
    data: Dict[str, Any]


_event_types: List[EventType] = []
_event_handles: Dict[str, List["EventHandle"]] = {}
_event_lock = threading.Lock()


def register_event_type(framework: str, name: str, help: str = "") -> None:
    """Called by instrumented subsystems at import; idempotent."""
    with _event_lock:
        for e in _event_types:
            if e.framework == framework and e.name == name:
                return
        _event_types.append(EventType(framework, name, help))


def event_get_num() -> int:
    _check_init()
    return len(_event_types)


def event_get_info(index: int) -> EventType:
    _check_init()
    if not 0 <= index < len(_event_types):
        raise MPIError(ERR_ARG, f"event index {index} out of range")
    return _event_types[index]


def event_get_index(name: str) -> int:
    _check_init()
    for i, e in enumerate(_event_types):
        if e.full_name == name:
            return i
    raise MPIError(ERR_ARG, f"no event named {name}")


class EventHandle:
    """event_handle_alloc.c + event_register_callback.c — a subscription
    to one event type; dropped-instance accounting included (the MPI-4
    dropped handler reports instances lost to a full buffer — here the
    only drop source is a callback raising)."""

    def __init__(self, index: int, cb: Callable[[EventInstance], None]):
        _check_init()
        if not 0 <= index < len(_event_types):
            raise MPIError(ERR_ARG, f"event index {index} out of range")
        self.type = _event_types[index]
        self._cb = cb
        self.dropped = 0
        with _event_lock:
            _event_handles.setdefault(self.type.full_name,
                                      []).append(self)

    def free(self) -> None:
        with _event_lock:
            hs = _event_handles.get(self.type.full_name, [])
            if self in hs:
                hs.remove(self)


def event_handle_alloc(index: int,
                       cb: Callable[[EventInstance], None]) -> EventHandle:
    return EventHandle(index, cb)


def emit(_fw: str, _name: str, **data: Any) -> None:
    """Fire an event to every subscribed handle. Near-zero cost when no
    tool is attached (one dict probe); instrumentation sites call this
    unconditionally. Positional params are underscored so payload kwargs
    may use any key (including 'framework'/'name')."""
    with _event_lock:
        handles = list(_event_handles.get(f"{_fw}_{_name}", ()))
    if not handles:
        return
    etype = None
    for e in _event_types:
        if e.framework == _fw and e.name == _name:
            etype = e
            break
    inst = EventInstance(etype or EventType(_fw, _name),
                         time.monotonic(), dict(data))
    for h in handles:
        try:
            h._cb(inst)
        except Exception:
            h.dropped += 1  # the dropped-handler accounting


# Built-in event types (instrumentation sites live in mca/component.py,
# comm/communicator.py, ft/detector.py, ft/revoke.py)
register_event_type("mca", "component_selected",
                    "A framework selected its component")
register_event_type("comm", "created", "A communicator was constructed")
register_event_type("comm", "revoked", "A communicator was revoked")
register_event_type("ft", "proc_failed",
                    "The detector declared a process failed")
# span-stream mirror (runtime/trace.py fires these from its span hooks,
# so an MPI_T-attached tool sees the same stream the Chrome-trace file
# export records; flip the trace_enable cvar via a CvarHandle to start it)
register_event_type("trace", "span_begin", "A trace span opened")
register_event_type("trace", "span_end", "A trace span closed")
