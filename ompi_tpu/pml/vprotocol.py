"""pml/v — pessimist message logging for rollback recovery.

Reference: ompi/mca/vprotocol/pessimist (vprotocol_pessimist.h: sender-
based payload repository + event log + replay mode, ~3k LoC). The
pessimist discipline: every nondeterministic event (which source a
receive matched, in what order) is forced to stable storage BEFORE the
message is delivered to the application, and every sent payload is kept
by the sender — so a crashed process can be restarted alone and re-driven
through the exact same receive sequence from its peers' payload logs.

Redesign as an interposition PML (the pml/monitoring.py pattern):

- live mode: ``isend`` appends (dst, tag, cid, payload) to this rank's
  sender-based log; ``irecv`` completion appends (seq, src, tag, cid,
  nbytes) to the event log, flushed per record (the pessimist property).
  ``seq`` is the receive's POSTING order — completion order differs with
  concurrent outstanding irecvs, and replay consumes in posting order.
- replay mode (``--mca pml_v_replay 1`` after a restart): receives are
  served from the peers' sender logs in the order dictated by this
  rank's own event log — per-source FIFO cursors resolve the payload,
  the event log resolves the cross-source interleaving (the only true
  nondeterminism; pt2pt is FIFO per (src, cid) pair). Sends are
  suppressed (their receivers already delivered them) and VERIFIED
  byte-identical against the sender log — a divergence means the
  application itself is nondeterministic and replay cannot be sound.

Logs live under ``pml_v_logdir`` as ``sender_<rank>.log`` /
``events_<rank>.log`` — the stable-storage assumption of pessimist
logging (the reference mmaps its repository to disk the same way).
Record framing: 4 little-endian int64 header words + raw payload.
Probe results are not event-logged (reference covers them; documented
gap), and replay ends when the event log is exhausted — further receives
raise rather than silently going live without their peers.
"""

from __future__ import annotations

import os
import struct
import threading
from typing import Dict, Optional, Tuple

import numpy as np

from ompi_tpu.core.request import Request as _BaseRequest
from ompi_tpu.mca.var import register_var, get_var, register_pvar

register_var("pml_v", "enable", False,
             help="Interpose the pml with pessimist message logging "
                  "(reference: ompi/mca/vprotocol/pessimist)", level=4)
register_var("pml_v", "logdir", "pml_v_logs",
             help="Stable-storage directory for sender-based payload "
                  "and event logs", level=6)
register_var("pml_v", "replay", False,
             help="Restart mode: serve receives from the logged event "
                  "sequence and suppress+verify resends", level=6)
register_var("pml_v", "replay_rank", -1,
             help="Original rank identity of a standalone restart (the "
                  "restarted process runs without the launcher; its "
                  "world is rebuilt from the logged metadata)", level=6)

_HDR = struct.Struct("<qqqq")  # four int64 words
# event records carry a 5th word: the receive's POSTING-sequence index.
# Events are appended at completion time, which can differ from posting
# order with concurrent outstanding irecvs — replay consumes in posting
# order, so pairing by seq (not log position) keeps them matched
# (r3 advisor finding).
_EVHDR = struct.Struct("<qqqqq")


def _append(f, a: int, b: int, c: int, d: int, payload: bytes = b"") -> None:
    f.write(_HDR.pack(a, b, c, d))
    if payload:
        f.write(payload)
    f.flush()
    os.fsync(f.fileno())  # pessimist: stable BEFORE delivery/completion


# magic first record identifying the 5-word event format: a log written
# by a different build must fail LOUDLY at open, not misparse record
# boundaries into wrong-source events
_EV_MAGIC = (-0x564C4F47, 2, 0, 0, 0)  # 'VLOG', version 2


def _append_event(f, seq: int, src: int, tag: int, cid: int,
                  nbytes: int) -> None:
    if f.tell() == 0:
        f.write(_EVHDR.pack(*_EV_MAGIC))
    f.write(_EVHDR.pack(seq, src, tag, cid, nbytes))
    f.flush()
    os.fsync(f.fileno())


def _read_events(path: str) -> Dict[int, Tuple[int, int, int, int]]:
    """seq -> (src, tag, cid, nbytes); torn tail records dropped."""
    from ompi_tpu.core.errors import MPIError, ERR_INTERN

    out: Dict[int, Tuple[int, int, int, int]] = {}
    if not os.path.exists(path):
        return out
    with open(path, "rb") as f:
        first = f.read(_EVHDR.size)
        if not first:
            return out
        if len(first) < _EVHDR.size or \
                _EVHDR.unpack(first) != _EV_MAGIC:
            raise MPIError(
                ERR_INTERN,
                f"pml_v: {path} is not a version-2 event log (written "
                "by an older build?) — replay would misparse it")
        while True:
            hdr = f.read(_EVHDR.size)
            if len(hdr) < _EVHDR.size:
                break
            seq, src, tag, cid, nbytes = _EVHDR.unpack(hdr)
            out[seq] = (src, tag, cid, nbytes)
    return out


def _read_records(path: str, with_payload: bool):
    out = []
    if not os.path.exists(path):
        return out
    with open(path, "rb") as f:
        while True:
            hdr = f.read(_HDR.size)
            if len(hdr) < _HDR.size:
                break  # torn tail record from a crash: drop it
            a, b, c, d = _HDR.unpack(hdr)
            payload = b""
            if with_payload:
                payload = f.read(d)
                if len(payload) < d:
                    break
            out.append((a, b, c, d, payload))
    return out


class VprotocolPml:
    """Pessimist-logging interposition wrapper around the selected pml."""

    _OWN = ("_inner", "_lock", "_sb", "_ev", "_replay", "_events",
            "_ev_pos", "_max_seq", "_peer_logs", "_send_log",
            "_send_pos", "_post_seq", "logged_send_bytes",
            "logged_events")

    def __init__(self, inner, logdir: str, replay: bool):
        self._inner = inner
        # RLock: a self-send completes synchronously through SelfBtl,
        # firing the receive's event-log callback on THIS thread while
        # isend still holds the lock for its append+send critical section
        self._lock = threading.RLock()
        self._replay = replay
        self._post_seq = 0  # posting-sequence of logged receives
        self.logged_send_bytes = 0
        self.logged_events = 0
        os.makedirs(logdir, exist_ok=True)
        me = inner.my_rank
        if replay:
            self._sb = self._ev = None
            # my event log dictates the receive sequence; peers' sender
            # logs hold the payloads, filtered to records addressed to me
            self._events = _read_events(
                os.path.join(logdir, f"events_{me}.log"))
            self._ev_pos = 0  # posting-sequence counter during replay
            self._max_seq = max(self._events, default=-1)
            self._peer_logs: Dict[int, list] = {}
            for fn in os.listdir(logdir):
                if fn.startswith("sender_") and fn.endswith(".log"):
                    src = int(fn[len("sender_"):-len(".log")])
                    if src == me:
                        continue
                    recs = _read_records(os.path.join(logdir, fn), True)
                    self._peer_logs[src] = [
                        r for r in recs if r[0] == me]
            # my own sender log verifies resends byte-for-byte
            self._send_log = _read_records(
                os.path.join(logdir, f"sender_{me}.log"), True)
            self._send_pos = 0
        else:
            # a FRESH live run must not append to a previous generation's
            # logs: seqs would collide (replay's _read_events silently
            # keeps the last) while the per-source payload FIFOs still
            # serve the OLD run's bytes first — wrong-data replay. Move
            # stale logs aside instead (kept for forensics).
            for fn in (f"sender_{me}.log", f"events_{me}.log",
                       f"meta_{me}.log"):
                p = os.path.join(logdir, fn)
                if os.path.exists(p) and os.path.getsize(p):
                    os.replace(p, p + ".stale")
            self._sb = open(os.path.join(logdir, f"sender_{me}.log"),
                            "ab")
            self._ev = open(os.path.join(logdir, f"events_{me}.log"),
                            "ab")
        register_pvar("pml_v", "logged_send_bytes",
                      lambda: self.logged_send_bytes,
                      help="Payload bytes in the sender-based log")
        register_pvar("pml_v", "logged_events",
                      lambda: self.logged_events,
                      help="Receive events forced to the event log")

    # ------------------------------------------------------------- verbs
    # Only user pt2pt is logged/replayed: library-internal traffic
    # (plane-bit cids, system tags) regenerates naturally on replay —
    # classification shared with pml/monitoring (pml/base.user_traffic).
    def isend(self, buf, count, datatype, dst, tag, cid, qos=None):
        from ompi_tpu.core.convertor import pack
        from ompi_tpu.pml.base import user_traffic

        if not user_traffic(tag, cid):
            return self._inner.isend(buf, count, datatype, dst, tag, cid,
                                     qos=qos)
        # one extra pack vs the inner pml's own convertor — accepted cost
        # of the payload log; the memoryview write avoids a bytes copy
        packed = pack(buf, count, datatype)
        if self._replay:
            return self._replay_send(packed.tobytes(), dst, tag, cid)
        with self._lock:
            # the append and the send stay under ONE lock: replay
            # resolves payloads by per-source FIFO over this log, so log
            # order must equal wire order even with concurrent senders
            _append(self._sb, dst, tag, cid, packed.nbytes,
                    memoryview(packed))
            self.logged_send_bytes += packed.nbytes
            return self._inner.isend(buf, count, datatype, dst, tag, cid,
                                     qos=qos)

    def irecv(self, buf, count, datatype, src, tag, cid):
        from ompi_tpu.pml.base import user_traffic

        if not user_traffic(tag, cid):
            return self._inner.irecv(buf, count, datatype, src, tag, cid)
        if self._replay:
            return self._replay_recv(buf, count, datatype, src, tag, cid)
        # the posting-sequence index is assigned NOW (deterministic in a
        # deterministic app); the event is written at completion, which
        # may be out of posting order with concurrent outstanding irecvs
        with self._lock:
            seq = self._post_seq
            self._post_seq += 1
        req = self._inner.irecv(buf, count, datatype, src, tag, cid)

        def done(r):
            if r.status.cancelled or r.status.source < 0:
                return
            with self._lock:
                _append_event(self._ev, seq, r.status.source,
                              r.status.tag, cid, r.status._nbytes)
                self.logged_events += 1

        req.add_completion_callback(done)
        return req

    # ------------------------------------------------------ replay engine
    def _replay_send(self, data: bytes, dst, tag, cid):
        from ompi_tpu.core.errors import MPIError, ERR_INTERN
        from ompi_tpu.core.request import CompletedRequest

        with self._lock:
            if self._send_pos >= len(self._send_log):
                raise MPIError(
                    ERR_INTERN,
                    "pml_v replay: send past the end of the sender log "
                    "(restart reached the crash point; reconnect to the "
                    "live job to continue)")
            ldst, ltag, lcid, _, lpayload = self._send_log[self._send_pos]
            self._send_pos += 1
        if (ldst, ltag, lcid) != (dst, tag, cid) or lpayload != data:
            raise MPIError(
                ERR_INTERN,
                f"pml_v replay diverged: send #{self._send_pos - 1} to "
                f"{dst} tag {tag} does not match the log — the "
                "application is nondeterministic beyond its receives")
        return CompletedRequest(nbytes=len(data))

    def _replay_recv(self, buf, count, datatype, src, tag, cid):
        from ompi_tpu.core.convertor import unpack
        from ompi_tpu.core.errors import MPIError, ERR_INTERN
        from ompi_tpu.core.request import CompletedRequest

        from ompi_tpu.pml.base import ANY_SOURCE as _ANY, ANY_TAG as _ANYT

        # ONE critical section: event pop + payload resolution must be
        # atomic or concurrent replayed receives pair events with the
        # wrong sender-log records
        with self._lock:
            seq = self._ev_pos
            ev = self._events.get(seq)
            if ev is None:
                if seq <= self._max_seq:
                    # seq GAP below logged events: this receive never
                    # completed in the original execution (cancelled, or
                    # still outstanding at the crash) while later posts
                    # did — hand back a never-completing (cancellable)
                    # request so the later logged events stay replayable
                    self._ev_pos += 1
                    return _NeverDeliveredRequest()
                # truly past the log's end: the crash point is reached
                raise MPIError(
                    ERR_INTERN,
                    "pml_v replay: receive past the end of the event log "
                    "(restart reached the crash point)")
            esrc, etag, ecid, enbytes = ev
            if src not in (_ANY, esrc):
                raise MPIError(
                    ERR_INTERN,
                    f"pml_v replay diverged: receive posted for source "
                    f"{src} but the event log matched {esrc}")
            if tag not in (_ANYT, etag):
                raise MPIError(
                    ERR_INTERN,
                    f"pml_v replay diverged: receive posted with tag "
                    f"{tag} but the event log matched {etag}")
            self._ev_pos += 1
            # the event log resolves the nondeterminism (which source);
            # per-source FIFO order resolves the payload — take the first
            # unconsumed record matching (tag, cid), skipping records a
            # differently-tagged receive will consume later
            recs = self._peer_logs.get(esrc, [])
            cur = 0
            while cur < len(recs) and not (
                    recs[cur][1] == etag and recs[cur][2] == ecid):
                cur += 1
            if cur >= len(recs):
                raise MPIError(
                    ERR_INTERN,
                    f"pml_v replay: no payload in rank {esrc}'s sender "
                    f"log for event (tag {etag}, cid {ecid})")
            payload = recs.pop(cur)[4]
        unpack(np.frombuffer(payload, dtype=np.uint8), buf, count,
               datatype)
        req = CompletedRequest(nbytes=enbytes, source=esrc, tag=etag)
        return req

    # -------------------------------------------------- plain delegation
    def __getattr__(self, name):
        return getattr(self._inner, name)

    def __setattr__(self, name, value):
        if name in self._OWN:
            object.__setattr__(self, name, value)
        else:
            setattr(self._inner, name, value)

    def note_world(self, size: int, base: int = 0) -> None:
        """Record the job geometry (live mode) so a standalone restart
        can rebuild its world view; spawned jobs have universe ranks
        base..base+size-1. Reference analog: the nspace info a restarted
        process re-reads from the event logger."""
        if self._replay:
            return
        logdir = get_var("pml_v", "logdir")
        with open(os.path.join(logdir,
                               f"meta_{self._inner.my_rank}.log"),
                  "w") as f:
            f.write(f"{size} {base}")

    @staticmethod
    def logged_world(logdir: str, rank: int) -> Tuple[int, int]:
        """(size, base) of the crashed rank's job."""
        with open(os.path.join(logdir, f"meta_{rank}.log")) as f:
            parts = f.read().split()
        return int(parts[0]), int(parts[1]) if len(parts) > 1 else 0

    def close_logs(self) -> None:
        for f in (self._sb, self._ev):
            if f is not None:
                try:
                    f.close()
                except Exception:
                    pass


def maybe_wrap(pml):
    """Interpose if enabled (called at PML selection alongside
    pml/monitoring; v wraps closest to the wire so monitoring counts
    replayed traffic too)."""
    if not get_var("pml_v", "enable"):
        return pml
    wrapped = VprotocolPml(pml, get_var("pml_v", "logdir"),
                           bool(get_var("pml_v", "replay")))
    from ompi_tpu.hook import register_hook

    register_hook("finalize_bottom", wrapped.close_logs)
    return wrapped


class _NeverDeliveredRequest(_BaseRequest):
    """Replay stand-in for a receive with no logged event below later
    logged seqs: the original execution never delivered it (cancelled or
    outstanding at the crash), so it must not complete here either —
    but it stays cancellable, matching an app that cancels and moves
    on."""

    def Cancel(self) -> None:
        self.status.cancelled = True
        self._set_complete()
