"""pml/monitoring — interposition layer counting point-to-point traffic.

Reference: ompi/mca/pml/monitoring + ompi/mca/common/monitoring (the
interposition PML that counts messages/bytes per peer then forwards to
the real PML; matrix output via profile2mat.pl). Redesign: a delegating
wrapper around the selected PML, enabled with
``--mca pml_monitoring_enable 1`` (or implicitly by
``--mca metrics_enable 1`` — the live metrics plane rides the same
interposition); per-peer counters surface as pvars, the finalize hook
prints the communication matrix (one row per rank: ``peer:msgs/bytes``,
the profile2mat analog) when monitoring proper is enabled, and with the
metrics plane on every user send/recv also lands in per-peer latency
histograms (``pml_send_latency_us`` / ``pml_recv_latency_us``) plus a
src→dst bytes/messages matrix sampler merged into the metrics snapshot
(runtime/metrics.py, tools/promexport.py).
"""

from __future__ import annotations

import threading
import time
from collections import defaultdict
from typing import Dict, List, Tuple

from ompi_tpu.mca.var import register_var, get_var, register_pvar
from ompi_tpu.pml.base import user_traffic
from ompi_tpu.runtime import metrics as _metrics

register_var("pml_monitoring", "enable", False,
             help="Interpose the pml and count per-peer messages/bytes "
                  "(reference: pml/monitoring)", level=4)


class MonitoringPml:
    """Forwarding wrapper (reference: every pml/monitoring verb bumps
    counters then calls the underlying module)."""

    def __init__(self, inner):
        self._inner = inner
        self._lock = threading.Lock()
        # (peer, direction) -> [messages, bytes]
        self.counts: Dict[Tuple[int, str], list] = defaultdict(
            lambda: [0, 0])
        # register_pvar is idempotent-by-name: a SECOND MonitoringPml
        # (restart in-process, tests) would get back the first instance's
        # Pvar and its stale reader closures. Rebind the reader so the
        # pvar always reports the LIVE wrapper.
        for name, direction, help_ in (
                ("total_sent_bytes", "tx",
                 "Bytes sent through the monitored pml"),
                ("total_recv_bytes", "rx",
                 "Bytes received through the monitored pml")):
            reader = (lambda d=direction, me=self: me._total_bytes(d))
            register_pvar("pml_monitoring", name, reader,
                          help=help_).reader = reader
        # metrics sampler rides the same rebind discipline: the snapshot
        # always reflects the live wrapper's matrix
        _metrics.register_sampler(
            "pml_comm_matrix", lambda me=self: me.matrix())

    def _total_bytes(self, direction: str) -> int:
        with self._lock:
            return sum(v[1] for (p, d), v in self.counts.items()
                       if d == direction)

    def _bump(self, peer: int, direction: str, nbytes: int) -> None:
        with self._lock:
            c = self.counts[(peer, direction)]
            c[0] += 1
            c[1] += nbytes

    # ------------------------------------------------- monitored verbs
    def isend(self, buf, count, datatype, dst, tag, cid, qos=None):
        if user_traffic(tag, cid):
            self._bump(dst, "tx", count * datatype.size)
            if _metrics._enable_var._value:
                # post→completion latency into the per-peer histogram
                # (one attribute load when the metrics plane is off)
                t0 = time.monotonic_ns()
                req = self._inner.isend(buf, count, datatype, dst, tag,
                                        cid, qos=qos)
                req.add_completion_callback(
                    lambda r, t0=t0, dst=dst: _metrics.observe(
                        "pml_send_latency_us",
                        (time.monotonic_ns() - t0) / 1000.0, peer=dst))
                return req
        return self._inner.isend(buf, count, datatype, dst, tag, cid,
                                 qos=qos)

    def irecv(self, buf, count, datatype, src, tag, cid):
        req = self._inner.irecv(buf, count, datatype, src, tag, cid)
        if user_traffic(tag, cid):
            t0 = time.monotonic_ns()

            def done(r):
                if r.status.source >= 0:
                    self._bump(r.status.source, "rx", r.status._nbytes)
                    if _metrics._enable_var._value:
                        _metrics.observe(
                            "pml_recv_latency_us",
                            (time.monotonic_ns() - t0) / 1000.0,
                            peer=r.status.source)

            req.add_completion_callback(done)
        return req

    # ------------------------------------------------- plain delegation
    _OWN = ("_inner", "_lock", "counts")

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def __setattr__(self, name, value):
        # writes fall through to the real pml (wireup assigns
        # endpoint_resolver post-construction; landing it on the wrapper
        # would silently break cross-job endpoint resolution)
        if name in self._OWN:
            object.__setattr__(self, name, value)
        else:
            setattr(self._inner, name, value)

    # ------------------------------------------------------ matrix dump
    def matrix(self) -> List[Dict[str, int]]:
        """src→dst messages/bytes rows from THIS rank's vantage (tx rows
        originate here, rx rows terminate here) — the metrics-snapshot /
        Prometheus shape of the communication matrix."""
        me = self._inner.my_rank
        with self._lock:
            # materialize the [msgs, bytes] pairs under the lock — the
            # lists are the live objects _bump mutates, and reading
            # them after release could tear a row mid-bump
            items = sorted((k, tuple(v)) for k, v in self.counts.items())
        merged: Dict[Tuple[int, int], List[int]] = {}
        for (p, d), v in items:
            key = (me, p) if d == "tx" else (p, me)
            cur = merged.get(key)
            if cur is None:
                merged[key] = [v[0], v[1]]
            else:
                # self-traffic: the tx and rx counters are two views of
                # the SAME (me, me) edge — emitting both would render
                # duplicate Prometheus samples; max (not sum: that
                # double-counts) tolerates an in-flight delta
                cur[0] = max(cur[0], v[0])
                cur[1] = max(cur[1], v[1])
        return [{"src": s, "dst": t, "msgs": m, "bytes": b}
                for (s, t), (m, b) in sorted(merged.items())]

    def dump_matrix(self, file=None) -> None:
        """The comm-matrix report (reference: common/monitoring's
        output consumed by profile2mat.pl)."""
        import sys

        out = file or sys.stderr
        tx = {p: v for (p, d), v in sorted(self.counts.items())
              if d == "tx"}
        rx = {p: v for (p, d), v in sorted(self.counts.items())
              if d == "rx"}
        me = self._inner.my_rank
        cells = " ".join(f"{p}:{v[0]}/{v[1]}B" for p, v in tx.items())
        print(f"pml_monitoring rank {me} sent: {cells or '-'}", file=out)
        cells = " ".join(f"{p}:{v[0]}/{v[1]}B" for p, v in rx.items())
        print(f"pml_monitoring rank {me} recv: {cells or '-'}", file=out)


def maybe_wrap(pml):
    """Interpose if enabled (called by wireup at PML selection — the
    reference's monitoring component wins selection then forwards).
    The live metrics plane implies interposition too (latency
    histograms + matrix sampler need the wrapper in place at init);
    the finalize stderr matrix stays exclusive to pml_monitoring_enable
    so metrics-only jobs don't get the text dump."""
    monitoring = get_var("pml_monitoring", "enable")
    if not (monitoring or _metrics._enable_var._value):
        return pml
    wrapped = MonitoringPml(pml)
    if monitoring:
        from ompi_tpu.hook import register_hook

        register_hook("finalize_top", wrapped.dump_matrix)
    return wrapped
