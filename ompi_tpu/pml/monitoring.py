"""pml/monitoring — interposition layer counting point-to-point traffic.

Reference: ompi/mca/pml/monitoring + ompi/mca/common/monitoring (the
interposition PML that counts messages/bytes per peer then forwards to
the real PML; matrix output via profile2mat.pl). Redesign: a delegating
wrapper around the selected PML, enabled with
``--mca pml_monitoring_enable 1``; per-peer counters surface as pvars
and the finalize hook prints the communication matrix (one row per
rank: ``peer:msgs/bytes``), the profile2mat analog.
"""

from __future__ import annotations

import threading
from collections import defaultdict
from typing import Dict, Tuple

from ompi_tpu.mca.var import register_var, get_var, register_pvar
from ompi_tpu.pml.base import user_traffic

register_var("pml_monitoring", "enable", False,
             help="Interpose the pml and count per-peer messages/bytes "
                  "(reference: pml/monitoring)", level=4)


class MonitoringPml:
    """Forwarding wrapper (reference: every pml/monitoring verb bumps
    counters then calls the underlying module)."""

    def __init__(self, inner):
        self._inner = inner
        self._lock = threading.Lock()
        # (peer, direction) -> [messages, bytes]
        self.counts: Dict[Tuple[int, str], list] = defaultdict(
            lambda: [0, 0])
        # register_pvar is idempotent-by-name: a SECOND MonitoringPml
        # (restart in-process, tests) would get back the first instance's
        # Pvar and its stale reader closures. Rebind the reader so the
        # pvar always reports the LIVE wrapper.
        for name, direction, help_ in (
                ("total_sent_bytes", "tx",
                 "Bytes sent through the monitored pml"),
                ("total_recv_bytes", "rx",
                 "Bytes received through the monitored pml")):
            reader = (lambda d=direction, me=self: me._total_bytes(d))
            register_pvar("pml_monitoring", name, reader,
                          help=help_).reader = reader

    def _total_bytes(self, direction: str) -> int:
        with self._lock:
            return sum(v[1] for (p, d), v in self.counts.items()
                       if d == direction)

    def _bump(self, peer: int, direction: str, nbytes: int) -> None:
        with self._lock:
            c = self.counts[(peer, direction)]
            c[0] += 1
            c[1] += nbytes

    # ------------------------------------------------- monitored verbs
    def isend(self, buf, count, datatype, dst, tag, cid):
        if user_traffic(tag, cid):
            self._bump(dst, "tx", count * datatype.size)
        return self._inner.isend(buf, count, datatype, dst, tag, cid)

    def irecv(self, buf, count, datatype, src, tag, cid):
        req = self._inner.irecv(buf, count, datatype, src, tag, cid)
        if user_traffic(tag, cid):
            def done(r):
                if r.status.source >= 0:
                    self._bump(r.status.source, "rx", r.status._nbytes)

            req.add_completion_callback(done)
        return req

    # ------------------------------------------------- plain delegation
    _OWN = ("_inner", "_lock", "counts")

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def __setattr__(self, name, value):
        # writes fall through to the real pml (wireup assigns
        # endpoint_resolver post-construction; landing it on the wrapper
        # would silently break cross-job endpoint resolution)
        if name in self._OWN:
            object.__setattr__(self, name, value)
        else:
            setattr(self._inner, name, value)

    # ------------------------------------------------------ matrix dump
    def dump_matrix(self, file=None) -> None:
        """The comm-matrix report (reference: common/monitoring's
        output consumed by profile2mat.pl)."""
        import sys

        out = file or sys.stderr
        tx = {p: v for (p, d), v in sorted(self.counts.items())
              if d == "tx"}
        rx = {p: v for (p, d), v in sorted(self.counts.items())
              if d == "rx"}
        me = self._inner.my_rank
        cells = " ".join(f"{p}:{v[0]}/{v[1]}B" for p, v in tx.items())
        print(f"pml_monitoring rank {me} sent: {cells or '-'}", file=out)
        cells = " ".join(f"{p}:{v[0]}/{v[1]}B" for p, v in rx.items())
        print(f"pml_monitoring rank {me} recv: {cells or '-'}", file=out)


def maybe_wrap(pml):
    """Interpose if enabled (called by wireup at PML selection — the
    reference's monitoring component wins selection then forwards)."""
    if not get_var("pml_monitoring", "enable"):
        return pml
    wrapped = MonitoringPml(pml)
    from ompi_tpu.hook import register_hook

    register_hook("finalize_top", wrapped.dump_matrix)
    return wrapped
